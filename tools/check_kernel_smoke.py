#!/usr/bin/env python
"""Kernel-plane CI smoke (ISSUE 12, TIER1_KERNEL_SMOKE): runs the
ops/autotune.py harness end to end on CPU in measure-only mode and gates
the plane's safety contract:

1. the harness MEASURES every candidate variant per bucket on a trained
   model — real step times, max |dScore| vs the f32 baseline, and the AUC
   gate evaluated against a labeled held-out block;
2. the decision table is WELL-FORMED (every bucket present, gates
   recorded, persisted JSON parseable and keyed by model:version);
3. measure-only ENABLES NOTHING — every per-bucket decision is the
   baseline and live submits never route to a variant;
4. with the plane off entirely ([kernels] enabled=false -> batcher.kernels
   None), served scores are BIT-IDENTICAL to a batcher that never heard
   of the plane.

Exits nonzero with a reason on any violation; prints one JSON line
(`kernel_smoke` block) for the CI log either way.
"""

import json
import os
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def fail(msg: str, block: dict) -> None:
    print(json.dumps({"kernel_smoke": block, "ok": False, "error": msg}))
    print(f"kernel smoke FAILED: {msg}", file=sys.stderr)
    sys.exit(1)


def main() -> None:
    import jax
    import optax

    from distributed_tf_serving_tpu.models import (
        ModelConfig,
        Servable,
        build_model,
        ctr_signatures,
    )
    from distributed_tf_serving_tpu.ops.autotune import BASELINE, KernelManager
    from distributed_tf_serving_tpu.serving.batcher import DynamicBatcher
    from distributed_tf_serving_tpu.train.data import (
        SyntheticCTRConfig,
        SyntheticCTRStream,
    )
    from distributed_tf_serving_tpu.train.trainer import Trainer
    from distributed_tf_serving_tpu.utils.config import KernelsConfig

    block: dict = {}
    # Small dcn_v2 trained on a DENSE id catalog (the quality-soak CPU
    # finding: a full-size vocab stays at coin flip) so the AUC gate has
    # signal to protect.
    cfg = ModelConfig(
        name="DCN", num_fields=6, vocab_size=4096, embed_dim=8,
        mlp_dims=(32,), num_cross_layers=2, cross_full_matrix=True,
        compute_dtype="float32",
    )
    model = build_model("dcn_v2", cfg)
    stream_cfg = SyntheticCTRConfig(num_fields=6, id_space=1 << 10, seed=0)
    trainer = Trainer(
        model, learning_rate=optax.cosine_decay_schedule(3e-2, 200),
        seed=0, stream_config=stream_cfg,
    )
    trainer.fit(200, batch_size=256)
    servable = Servable(
        name="DCN", version=1, model=model, params=trainer.state.params,
        signatures=ctr_signatures(6),
    )
    held = SyntheticCTRStream(stream_cfg).batch(256, 999_983)
    eval_data = (
        {"feat_ids": held["feat_ids"], "feat_wts": held["feat_wts"]},
        held["labels"],
    )

    buckets = (16, 32)
    table_file = os.path.join(tempfile.mkdtemp(), "kernel_autotune.json")
    batcher = DynamicBatcher(buckets=buckets, max_wait_us=0).start()
    plain = DynamicBatcher(buckets=buckets, max_wait_us=0).start()
    try:
        batcher.warmup(servable)
        manager = KernelManager(KernelsConfig(
            enabled=True, measure_only=True, table_file=table_file,
            measure_iters=2,
        ))
        batcher.kernels = manager
        table = manager.autotune(
            batcher, servable, buckets=buckets, eval_data=eval_data
        )
        block["table"] = table

        # 1+2: well-formed, gates evaluated.
        if not table.get("measure_only"):
            fail("table does not record measure_only", block)
        if not table["gates"]["auc_evaluated"]:
            fail("AUC gate was not evaluated despite eval data", block)
        if table["auc"].get(BASELINE) is None:
            fail(f"baseline AUC missing: {table.get('auc_errors')}", block)
        if table["auc"][BASELINE] <= 0.6:
            fail(f"trained baseline AUC {table['auc'][BASELINE]} at coin "
                 "flip — the gate protects nothing", block)
        for b in buckets:
            row = table["buckets"].get(str(b))
            if row is None:
                fail(f"bucket {b} missing from the table", block)
            if row[BASELINE]["step_us"] <= 0:
                fail(f"bucket {b}: baseline was not timed", block)
            v = row.get("xla_int8")
            if v is None or "step_us" not in v:
                fail(f"bucket {b}: xla_int8 was not measured: {v}", block)
            if "max_abs_delta" not in v:
                fail(f"bucket {b}: accuracy gate not evaluated", block)
            if v.get("auc_gate") not in ("pass", "fail"):
                fail(f"bucket {b}: auc_gate not evaluated: {v}", block)
            # 3: measure-only must never enable.
            if v.get("enabled") or row.get("decision") != BASELINE:
                fail(f"bucket {b}: measure-only enabled a variant", block)
        for b in buckets:
            if manager.decision(servable, b) is not None:
                fail(f"bucket {b}: live decision despite measure-only", block)

        # Persistence well-formed.
        data = json.load(open(table_file))
        if "DCN:1" not in (data.get("entries") or {}):
            fail("persisted table missing the DCN:1 entry", block)
        if data.get("fingerprint") is None or data.get("device") is None:
            fail("persisted table missing device/fingerprint keys", block)
        block["table_file_ok"] = True

        # 4: off-by-default bit-identity — the measure-only manager is
        # ATTACHED to `batcher` (worst case: the plane is present but must
        # route nothing), `plain` never heard of the plane.
        rng = np.random.RandomState(3)
        arrays = {
            "feat_ids": rng.randint(0, 1 << 40, size=(24, 6)).astype(np.int64),
            "feat_wts": rng.rand(24, 6).astype(np.float32),
        }
        a = batcher.submit(servable, dict(arrays)).result(30)["prediction_node"]
        b = plain.submit(servable, dict(arrays)).result(30)["prediction_node"]
        if not np.array_equal(a, b):
            fail("measure-only plane changed served scores", block)
        block["off_bit_identical"] = True
    finally:
        batcher.stop()
        plain.stop()

    block_out = {
        "auc_baseline": table["auc"][BASELINE],
        "buckets": {
            str(b): {
                "baseline_us": table["buckets"][str(b)][BASELINE]["step_us"],
                "int8_us": table["buckets"][str(b)]["xla_int8"].get("step_us"),
                "int8_speedup": table["buckets"][str(b)]["xla_int8"].get("speedup"),
                "int8_max_abs_delta":
                    table["buckets"][str(b)]["xla_int8"].get("max_abs_delta"),
                "int8_auc_gate": table["buckets"][str(b)]["xla_int8"].get("auc_gate"),
                "decision": table["buckets"][str(b)]["decision"],
            }
            for b in buckets
        },
        "table_file_ok": True,
        "off_bit_identical": True,
    }
    print(json.dumps({"kernel_smoke": block_out, "ok": True}))
    print("kernel smoke OK", file=sys.stderr)


if __name__ == "__main__":
    main()
