#!/usr/bin/env python
"""Elastic mesh serving tier-1 smoke (ISSUE 15): a CPU-safe,
self-contained gate asserting the [elastic] plane's contract end to end
on 8 emulated devices —

- under FORCED pressure (the overload plane's `pressure` fault site pins
  the state machine in BROWNOUT for a bounded number of ticks) the
  serving split switches UP (toward the data-parallel/throughput end),
  and after the fault exhausts and pressure recovers it switches DOWN
  (back toward the configured split): >= 1 switch in each direction;
- EVERY request across the whole stream — including those in flight
  during both switch windows — succeeds, and every score is
  BIT-IDENTICAL to a pinned-split reference stack serving the same
  checkpoint (the hitless contract);
- every ladder rung's executables were warmup-compiled BEFORE the stream
  (params placed per rung at load — the switch-never-compiles contract),
  and the drain barrier closed behind every switch (zero in-flight on
  every rung at the end);
- the `elastic` surfaces answer: mesh_stats()//meshz carries the elastic
  block with a populated switch history, and the dts_tpu_elastic_*
  Prometheus series pass tools/check_prom.py.

Prints one JSON line; exit 0 = gate passed. Run by tools/ci_tier1.sh
under TIER1_ELASTIC_SMOKE=1.
"""

import json
import os
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

if os.environ.get("JAX_PLATFORMS") == "cpu":
    jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

from distributed_tf_serving_tpu import faults  # noqa: E402
from distributed_tf_serving_tpu.models import (  # noqa: E402
    ModelConfig,
    Servable,
    build_model,
    ctr_signatures,
)
from distributed_tf_serving_tpu.serving import overload as overload_mod  # noqa: E402
from distributed_tf_serving_tpu.serving.server import build_stack  # noqa: E402
from distributed_tf_serving_tpu.train import Trainer  # noqa: E402
from distributed_tf_serving_tpu.train.checkpoint import save_servable  # noqa: E402
from distributed_tf_serving_tpu.utils.config import (  # noqa: E402
    ElasticConfig,
    MeshConfig,
    OverloadConfig,
    ServerConfig,
)
from distributed_tf_serving_tpu.utils.metrics import ServerMetrics  # noqa: E402

NUM_FIELDS = 8
MODEL_CFG = ModelConfig(
    name="DCN", num_fields=NUM_FIELDS, vocab_size=1 << 12, embed_dim=4,
    mlp_dims=(16,), num_cross_layers=1, compute_dtype="float32",
)
BUCKETS = (10, 50)  # not mesh-shaped: the divisibility pad rides along
TRAIN_STEPS = int(os.environ.get("SMOKE_TRAIN_STEPS", "40"))
STREAM_REQUESTS = int(os.environ.get("ELASTIC_SMOKE_REQUESTS", "400"))
PRESSURE_TICKS = int(os.environ.get("ELASTIC_SMOKE_PRESSURE_TICKS", "40"))


def _server_cfg() -> ServerConfig:
    return ServerConfig(
        model_kind="dcn_v2", model_name="DCN", num_fields=NUM_FIELDS,
        buckets=BUCKETS, max_wait_us=200, warmup=True,
    )


def _payloads():
    out = []
    for n, seed in ((7, 1), (33, 2), (50, 3)):
        rng = np.random.RandomState(seed)
        out.append({
            "feat_ids": rng.randint(
                0, 1 << 40, size=(n, NUM_FIELDS)
            ).astype(np.int64),
            "feat_wts": rng.rand(n, NUM_FIELDS).astype(np.float32),
        })
    return out


def _score(batcher, sv, payload):
    return np.asarray(
        batcher.submit(
            sv, dict(payload), output_keys=("prediction_node",)
        ).result(timeout=60)["prediction_node"]
    )


def main() -> dict:
    out = {"errors": [], "ok": False}

    trainer = Trainer(build_model("dcn_v2", MODEL_CFG), seed=0)
    train = trainer.fit(steps=TRAIN_STEPS, batch_size=256)
    out["train_loss"] = round(float(train["loss"]), 4)
    servable = Servable(
        name="DCN", version=1, model=trainer.model,
        params=trainer.snapshot_params(),
        signatures=ctr_signatures(NUM_FIELDS),
    )
    ckpt = os.path.join(tempfile.mkdtemp(prefix="elastic_smoke_"), "ckpt")
    save_servable(ckpt, servable, kind="dcn_v2")
    payloads = _payloads()

    # Phase A: PINNED-split reference ({data:4, model:2}, no elastic, no
    # overload) — the bit-identity anchor.
    _r1, b1, impl1, sv1, _m1, _w1 = build_stack(
        _server_cfg(), checkpoint=ckpt, model_config=MODEL_CFG,
        mesh_config=MeshConfig(enabled=True, devices=8, model_parallel=2),
    )
    try:
        reference = [_score(b1, sv1, p) for p in payloads]
    finally:
        b1.stop()

    # Phase B: the ELASTIC stack — same checkpoint, [mesh] {4,2} initial,
    # ladder {8,1}/{4,2}, overload plane armed with a fast tick so the
    # pinned pressure escalates (and recovers) inside the smoke window.
    _r2, b2, impl2, sv2, _m2, _w2 = build_stack(
        _server_cfg(), checkpoint=ckpt, model_config=MODEL_CFG,
        mesh_config=MeshConfig(enabled=True, devices=8, model_parallel=2),
        elastic_config=ElasticConfig(
            enabled=True, splits=("8x1", "4x2"),
            tick_interval_s=0.02, dwell_s=0.2,
            up_after_ticks=2, down_after_ticks=3,
            load_up_threshold=0.9, load_down_threshold=0.3,
        ),
        overload_config=OverloadConfig(
            enabled=True, adjust_interval_s=0.02,
            brownout_after_intervals=2, recover_after_intervals=3,
        ),
    )
    ctrl = impl2.elastic
    ex = ctrl.executor
    try:
        # The switch-never-compiles precondition: warmup placed params
        # (and compiled the serve variants) on EVERY rung before any
        # live traffic.
        warm = {
            f"{d}x{m}": len(ex._executors[(d, m)]._placed)
            for d, m in ex.splits
        }
        out["warm_placed_per_split"] = warm
        if any(v < 1 for v in warm.values()):
            out["errors"].append(f"ladder not fully warmed: {warm}")

        # Forced pressure escalation: the `pressure` fault site pins the
        # overload state machine in BROWNOUT for PRESSURE_TICKS ticks,
        # then exhausts — the state machine recovers on its own under
        # the stream's tiny queue waits.
        faults.get().add(
            "pressure", kind="error", code="BROWNOUT",
            count=PRESSURE_TICKS,
        )
        failures = 0
        mismatches = 0

        def settle(pending):
            nonlocal failures, mismatches
            idx, fut = pending.pop(0)
            try:
                got = np.asarray(
                    fut.result(timeout=60)["prediction_node"]
                )
                if not np.array_equal(got, reference[idx]):
                    mismatches += 1
            except Exception:  # noqa: BLE001 — the gate counts failures
                failures += 1

        # A RAMPED stream, one seeded payload cycle throughout: a heavy
        # phase (4 outstanding submits — switches land with real batches
        # in flight on the old split, so the drain barrier does real
        # work) while the pinned pressure escalates, then a light phase
        # (1-deep, spaced) once the up-switch fired, so the recovered
        # state machine + drained queue earn the down-switch.
        pending: list = []
        t0 = time.perf_counter()
        i = 0
        while i < STREAM_REQUESTS or (
            # Keep streaming until both directions fired (bounded).
            (ex.switches_up < 1 or ex.switches_down < 1)
            and time.perf_counter() - t0 < 60
        ):
            heavy = ex.switches_up < 1
            p = i % len(payloads)
            pending.append((p, b2.submit(
                sv2, dict(payloads[p]), output_keys=("prediction_node",)
            )))
            while len(pending) >= (4 if heavy else 1):
                settle(pending)
            i += 1
            if not heavy:
                time.sleep(0.005)  # light phase: idle queue at tick time
            elif i % 25 == 0:
                time.sleep(0.01)  # let the wall clock advance the ticks
        while pending:
            settle(pending)
        out["stream_requests"] = i
        out["stream_seconds"] = round(time.perf_counter() - t0, 2)
        out["failures"] = failures
        out["score_mismatches"] = mismatches
        if failures:
            out["errors"].append(f"{failures} requests failed mid-stream")
        if mismatches:
            out["errors"].append(
                f"{mismatches} responses diverged from the pinned-split "
                "reference"
            )

        snap = ex.elastic_snapshot()
        out["switches_up"] = snap["switches_up"]
        out["switches_down"] = snap["switches_down"]
        out["history"] = snap["history"][-6:]
        out["final_split"] = snap["current_split"]
        out["controller"] = snap["controller"]
        if snap["switches_up"] < 1:
            out["errors"].append("no up-switch under forced pressure")
        if snap["switches_down"] < 1:
            out["errors"].append("no down-switch after pressure recovery")
        stuck = {
            s: blk["in_flight"]
            for s, blk in snap["per_split"].items() if blk["in_flight"]
        }
        if stuck:
            out["errors"].append(f"drain barrier never closed: {stuck}")
        if snap["pending_drain_from"] is not None:
            out["errors"].append(
                f"switch drain still pending from {snap['pending_drain_from']}"
            )

        # Surfaces: the elastic block inside mesh_stats (what /meshz
        # serves) and a lint-clean dts_tpu_elastic_* exposition.
        ms = impl2.mesh_stats()
        if "elastic" not in (ms or {}):
            out["errors"].append("mesh_stats()//meshz lacks the elastic block")
        text = ServerMetrics().prometheus_text(
            b2.stats, mesh=ms, elastic=impl2.elastic_stats(),
        )
        out["prom_elastic_series"] = sum(
            1 for ln in text.splitlines()
            if ln.startswith("dts_tpu_elastic_") and not ln.startswith("#")
        )
        if out["prom_elastic_series"] < 10:
            out["errors"].append(
                f"only {out['prom_elastic_series']} dts_tpu_elastic_* series"
            )
        from check_prom import lint_text

        lint = lint_text(text)
        if lint:
            out["errors"].append(f"prom lint: {lint[:3]}")
    finally:
        faults.reset()
        b2.stop()
        overload_mod.deactivate()

    out["ok"] = not out["errors"]
    return out


if __name__ == "__main__":
    result = main()
    print(json.dumps(result))
    sys.exit(0 if result["ok"] else 1)
