#!/bin/bash
# Round-5 on-rig measurement session — run the moment the relay recovers.
# (VERDICT r4 tasks 1/2/3/4/5/7: this one command produces every on-chip
# number the round needs.)
#
# Produces, in order:
#  1. the full bench artifact (now WITH the measured latency_mode and the
#     null-device host_ceiling / wide_wire_ceiling_qps inside the line) —
#     a good run refreshes the committed wedge-fallback measurement;
#  2. wide-vs-compact A/B sweep at adjacent points (same weather);
#  3. fused on/off A/B (wide wire);
#  4. unique-path run with the link-cap attribution fields;
#  5. a 5-minute mixed-surface soak (gRPC wide+compact+unique + REST
#     predict/classify on one loop) against the real chip's timing.
set -u
cd "$(dirname "$0")/.."
TS=$(date -u +%H%M%S)

echo "[session] 1/5 full bench (headline-first; salvage-protected)"
python bench.py 2>"artifacts/bench_r5_${TS}.log" | tail -1 > /tmp/r5_line.json
if python -c "import json,sys; l=json.load(open('/tmp/r5_line.json')); sys.exit(0 if l.get('value') and not l.get('salvaged') else 1)"; then
  python - <<EOF
import json
line = json.load(open('/tmp/r5_line.json'))
line['_dev_run'] = 'r5_${TS}_full'
with open('artifacts/bench_r5_dev_runs.jsonl', 'a') as f:
    f.write(json.dumps(line) + '\n')
print('recorded r5_${TS}_full:', line['value'], 'qps | compact:',
      line.get('qps_compact_wire'), '| unique:', line.get('qps_unique'),
      '| ceiling:', line.get('wide_wire_ceiling_qps'),
      '| p50_lat:', line.get('p50_latency_mode_ms'),
      '| train.auc:', (line.get('train') or {}).get('auc'))
EOF
  git add artifacts/last_good_bench.json artifacts/bench_r5_dev_runs.jsonl
  git commit -q -m "Record on-rig round-5 bench run (refreshes wedge-fallback measurement)

No-Verification-Needed: measurement artifact only" || true
else
  echo "[session] bench did not produce a live measurement; see artifacts/bench_r5_${TS}.log"
fi

echo "[session] 2/5 compact A/B sweep (adjacent points, same weather)"
EXP_AIO=1 EXP_PREPARED=1 EXP_CONCS=96,176 EXP_CHANNELS=3 \
  python tools/exp_load.py > "artifacts/exp_r5_${TS}_wide.json" \
  2>"artifacts/exp_r5_${TS}_wide.log"
EXP_AIO=1 EXP_PREPARED=1 EXP_CONCS=96,176 EXP_CHANNELS=3 EXP_COMPACT=1 \
  python tools/exp_load.py > "artifacts/exp_r5_${TS}_compact.json" \
  2>"artifacts/exp_r5_${TS}_compact.log"

echo "[session] 3/5 fused on/off A/B (wide wire)"
EXP_AIO=1 EXP_PREPARED=1 EXP_CONCS=96 EXP_CHANNELS=3 DTS_TPU_NO_FUSED=1 \
  python tools/exp_load.py > "artifacts/exp_r5_${TS}_nofused.json" \
  2>"artifacts/exp_r5_${TS}_nofused.log"

echo "[session] 4/5 unique-path with link attribution"
EXP_AIO=1 EXP_CONCS=32 EXP_CHANNELS=3 EXP_UNIQUE=1 \
  python tools/exp_load.py > "artifacts/exp_r5_${TS}_unique.json" \
  2>"artifacts/exp_r5_${TS}_unique.log"

echo "[session] 5/5 mixed-surface soak on the chip (5 min)"
SOAK_SECONDS=300 python tools/soak.py \
  > "artifacts/soak_r5_${TS}.json" 2>"artifacts/soak_r5_${TS}.log" \
  || echo "[session] soak failed; see artifacts/soak_r5_${TS}.log"

if [ "${SKIP_ZOO:-0}" != "1" ]; then
  echo "[session] bonus: zoo bench refresh (SKIP_ZOO=1 to skip)"
  if python tools/zoo_bench.py --out "artifacts/zoo_r5_${TS}.json" \
      > "artifacts/zoo_r5_${TS}.log" 2>&1; then
    # Only a TPU-device run may replace the committed on-chip artifact —
    # a CPU-fallback run exits 0 too and must never masquerade as chip
    # numbers (same gating posture as step 1's live-measurement check).
    if python -c "import json,sys; d=json.load(open('artifacts/zoo_r5_${TS}.json')); sys.exit(0 if 'tpu' in str(d.get('device','')).lower() else 1)"; then
      cp "artifacts/zoo_r5_${TS}.json" ZOO_BENCH_TPU.json
      git add ZOO_BENCH_TPU.json "artifacts/zoo_r5_${TS}.json"
      git commit -q -m "Refresh on-chip zoo bench (round-5 rig session)

No-Verification-Needed: measurement artifact only" || true
    else
      echo "[session] zoo run was not on a TPU device; committed artifact kept"
    fi
  else
    echo "[session] zoo bench failed; see artifacts/zoo_r5_${TS}.log"
  fi
fi

python - <<EOF
import glob, json
for p in sorted(glob.glob('artifacts/exp_r5_${TS}_*.json')):
    try:
        pts = json.load(open(p))
        print(p.split('/')[-1], [
            {k: pt.get(k) for k in ('concurrency', 'qps', 'p50_ms', 'compact',
                                    'fused_off', 'requests_per_batch')}
            for pt in pts
        ])
    except Exception as e:
        print(p, 'unreadable:', e)
try:
    soak = json.load(open('artifacts/soak_r5_${TS}.json'))
    print('soak:', {k: soak.get(k) for k in
                    ('requests_total', 'qps', 'grpc_err', 'rest_err',
                     'rss_gb_start', 'rss_gb_end')})
except Exception as e:
    print('soak unreadable:', e)
EOF
git add artifacts/ 2>/dev/null
git commit -q -m "Round-5 on-rig A/B sweeps and mixed-surface soak artifacts

No-Verification-Needed: measurement artifacts only" || true
echo "[session] done — review, tune operating point, re-run bench.py if warranted"
