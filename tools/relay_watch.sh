#!/bin/bash
# Watch for the TPU relay to recover. Probes jax.devices() with a hard
# timeout every interval; exits 0 the moment a probe sees a TPU device,
# exits 1 after the deadline. Logs each attempt to artifacts/relay_watch.log.
set -u
cd "$(dirname "$0")/.."
DEADLINE_S=${RELAY_WATCH_DEADLINE_S:-39600}   # 11 h
INTERVAL_S=${RELAY_WATCH_INTERVAL_S:-180}
START=$(date +%s)
LOG=artifacts/relay_watch.log
echo "[relay_watch] start $(date -u +%FT%TZ) deadline=${DEADLINE_S}s interval=${INTERVAL_S}s" >> "$LOG"
while true; do
  NOW=$(date +%s)
  if [ $((NOW - START)) -ge "$DEADLINE_S" ]; then
    echo "[relay_watch] deadline reached $(date -u +%FT%TZ) — relay never returned" >> "$LOG"
    exit 1
  fi
  OUT=$(timeout 150 python -c "import jax; ds=jax.devices(); print([str(d) for d in ds])" 2>&1)
  RC=$?
  if [ $RC -eq 0 ] && echo "$OUT" | grep -qi "tpu"; then
    echo "[relay_watch] UP $(date -u +%FT%TZ): $OUT" >> "$LOG"
    exit 0
  fi
  echo "[relay_watch] down $(date -u +%FT%TZ) rc=$RC: $(echo "$OUT" | tail -1 | cut -c1-160)" >> "$LOG"
  sleep "$INTERVAL_S"
done
