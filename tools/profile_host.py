#!/usr/bin/env python
"""Deterministic host-CPU profile of the serving data plane.

The serving stack is single-host-core-bound at ~450-520 QPS (round-3
decomposition: chip at ~1% of its 43k-QPS ceiling, process CPU >= 0.85 at
the knee) — so the round-4 perf lever is HOST CPU PER REQUEST, a quantity
that does not depend on the TPU or the relay tunnel at all. This harness
measures it on the CPU platform where it is reproducible to a few percent,
immune to tunnel weather (370-517 QPS drift made A/B tuning on the rig a
coin flip, artifacts/README.md).

Design choices that make the number honest:
- tiny model (8-dim embed, (16,) mlp) so XLA compute does not swamp the
  host path; the WIRE shape stays the flagship point (1k candidates x 43
  int64+f32 fields) so decode/pad/digest/encode costs are the real ones.
- cProfile wraps the one event loop carrying client+server+grpc-python;
  the batcher thread is profiled separately via its own profiler hook.
- os.times() deltas split Python-attributed CPU from C-core/XLA threads.
- a HostStackSampler (serving/utilization.py — the SAME sampler the
  on-demand POST /profilez/start capture runs) samples every thread's
  Python stack through the run, so the per-THREAD hot stacks ride the
  JSON line next to the cProfile totals. One implementation, two
  surfaces: this offline harness and the live endpoint cannot drift.

Outputs one JSON line: cpu_ms_per_request (the figure of merit), the
per-thread split, the sampled host_stacks block, and top cumulative
Python costs.
"""

import asyncio
import cProfile
import io
import json
import os
import pstats
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

CANDIDATES = 1000
NUM_FIELDS = 43


def main() -> None:
    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")

    from distributed_tf_serving_tpu.client import (
        ShardedPredictClient,
        make_payload,
        run_closed_loop,
    )
    from distributed_tf_serving_tpu.models import (
        ModelConfig,
        Servable,
        ServableRegistry,
        build_model,
        ctr_signatures,
    )
    from distributed_tf_serving_tpu.serving import DynamicBatcher, PredictionServiceImpl
    from distributed_tf_serving_tpu.serving.server import create_server_async
    from distributed_tf_serving_tpu.utils.tracing import request_trace
    from distributed_tf_serving_tpu import native

    native.ensure()  # the serving steady state has the native lib loaded

    requests = int(os.environ.get("PROF_REQUESTS", "1500"))
    concurrency = int(os.environ.get("PROF_CONCURRENCY", "32"))
    unique = os.environ.get("PROF_UNIQUE", "0") == "1"
    compact = os.environ.get("PROF_COMPACT", "0") == "1"
    prepared = not unique

    config = ModelConfig(
        name="DCN", num_fields=NUM_FIELDS, vocab_size=1 << 14, embed_dim=8,
        mlp_dims=(16,), num_cross_layers=1, cross_full_matrix=True,
    )
    model = build_model("dcn_v2", config)
    params = jax.jit(model.init)(jax.random.PRNGKey(0))
    registry = ServableRegistry()
    # PROF_NULL_DEVICE=1 injects a no-op run_fn: on the CPU platform the
    # XLA forward shares the one core with the data plane and swamps A/B
    # comparisons (readback ~70 ms/batch); nulling it measures the pure
    # host data plane — decode/batch/pack/encode/transport — which is the
    # quantity that transfers to the TPU rig.
    null_device = os.environ.get("PROF_NULL_DEVICE", "0") == "1"
    # PROF_DEVICE_DELAY_MS stalls the batcher thread that long per batch
    # (sleep drops the GIL like a real transfer wait): coalescing then
    # fills batches to rig-like requests_per_batch, where per-BATCH host
    # costs (generic pad vs fused pack) become visible. Applied on the
    # REAL dispatch path below — a null-device run_fn would disable the
    # input cache and the fused path entirely (batcher run_fn contract),
    # so the combination is rejected rather than silently measuring the
    # wrong thing.
    delay_s = float(os.environ.get("PROF_DEVICE_DELAY_MS", "0")) / 1e3
    if delay_s and null_device:
        raise SystemExit(
            "PROF_DEVICE_DELAY_MS requires the real dispatch path; "
            "unset PROF_NULL_DEVICE (run_fn disables cache + fused pack)"
        )
    run_fn = None
    if null_device:
        import numpy as _np

        def run_fn(servable, arrays):
            n = next(iter(arrays.values())).shape[0]
            return {"prediction_node": _np.zeros(n, _np.float32)}

    batcher = DynamicBatcher(
        buckets=(1024, 2048, 4096, 8192),
        max_wait_us=2000,
        completion_workers=4,
        run_fn=run_fn,
    ).start()
    if delay_s:
        # Stall both dispatch paths identically so the A/B isolates the
        # host-side assembly cost, not the stall.
        orig_exec = batcher._execute
        orig_fused = batcher._execute_fused

        def slow_exec(sv, arrays, *args, **kwargs):
            time.sleep(delay_s)
            return orig_exec(sv, arrays, *args, **kwargs)

        def slow_fused(ctx, bucket, *args, **kwargs):
            time.sleep(delay_s)
            return orig_fused(ctx, bucket, *args, **kwargs)

        batcher._execute = slow_exec
        batcher._execute_fused = slow_fused
    servable = Servable(
        name="DCN", version=1, model=model, params=params,
        signatures=ctr_signatures(NUM_FIELDS),
    )
    registry.load(servable)
    for b in (1024, 2048, 4096, 8192):
        batcher.warmup(servable, buckets=(b,))
    impl = PredictionServiceImpl(registry, batcher)

    payload = make_payload(candidates=CANDIDATES, num_fields=NUM_FIELDS)
    pool = (
        [make_payload(candidates=CANDIDATES, num_fields=NUM_FIELDS, seed=100 + i)
         for i in range(64)]
        if unique else None
    )
    if compact:
        from distributed_tf_serving_tpu.client import compact_payload

        payload = compact_payload(payload, config.vocab_size)
        if pool:
            pool = [compact_payload(p, config.vocab_size) for p in pool]

    async def drive():
        server, port = create_server_async(impl, "127.0.0.1:0")
        await server.start()
        try:
            async with ShardedPredictClient(
                [f"127.0.0.1:{port}"], "DCN", channels_per_host=3
            ) as client:
                return await run_closed_loop(
                    client, payload,
                    concurrency=concurrency,
                    requests_per_worker=requests // concurrency,
                    sort_scores=True,
                    warmup_requests=5,
                    payload_pool=pool,
                    prepared=prepared,
                )
        finally:
            await server.stop(0)

    from distributed_tf_serving_tpu.serving.utilization import HostStackSampler

    request_trace.reset()
    t0_wall = time.perf_counter()
    t0 = os.times()
    sampler = HostStackSampler(
        interval_s=float(os.environ.get("PROF_SAMPLE_INTERVAL_S", "0.02"))
    ).start()
    prof = cProfile.Profile()
    prof.enable()
    report = asyncio.run(drive())
    prof.disable()
    stacks = sampler.stop()
    t1 = os.times()
    wall = time.perf_counter() - t0_wall

    n = report.requests
    user, system = t1.user - t0.user, t1.system - t0.system
    out = io.StringIO()
    stats = pstats.Stats(prof, stream=out)
    stats.sort_stats("cumulative").print_stats(45)
    top = out.getvalue()

    line = {
        "mode": ("unique" if unique else "repeated_prepared")
                + ("_compact" if compact else "")
                + ("_nulldev" if null_device else ""),
        "requests": n,
        "wall_s": round(wall, 2),
        "qps": round(n / wall, 1),
        "cpu_user_s": round(user, 2),
        "cpu_system_s": round(system, 2),
        "cpu_util": round((user + system) / wall, 3),
        "cpu_ms_per_request": round((user + system) / n * 1e3, 3),
        "phases_us": {
            k: v["mean_us"] for k, v in request_trace.snapshot().items()
        },
        "batcher": {
            "requests_per_batch": round(batcher.stats.mean_requests_per_batch, 2),
            "batches": batcher.stats.batches,
        },
        # Sampled per-thread hot stacks (top 3 per thread, by sample
        # count): where each thread actually SPENDS its time — the
        # attribution cProfile's single-thread view cannot give.
        "host_stacks": {
            "samples": stacks["samples"],
            "interval_s": stacks["interval_s"],
            "threads": {
                name: entries[:3]
                for name, entries in stacks["threads"].items()
            },
        },
    }
    batcher.stop()
    print(json.dumps(line))
    print(top, file=sys.stderr)


if __name__ == "__main__":
    main()
