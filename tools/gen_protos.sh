#!/usr/bin/env bash
# Regenerate Python protobuf bindings for the wire-compatible serving protos.
# grpc_tools is not available in this image, so only message bindings are
# generated here; the gRPC service stub/servicer wiring is hand-written in
# distributed_tf_serving_tpu/proto/service_grpc.py.
set -euo pipefail
cd "$(dirname "$0")/../distributed_tf_serving_tpu/proto"

protoc -I. \
  --python_out=. \
  tf_framework.proto tf_graph.proto tf_example.proto tf_meta_graph.proto \
  tf_saved_model.proto \
  serving_apis.proto

# protoc emits absolute imports between generated modules; rewrite them to
# package-relative so the bindings live inside distributed_tf_serving_tpu.proto.
sed -i -E 's/^import (tf_[a-z_]+_pb2|serving_apis_pb2)/from . import \1/' ./*_pb2.py

echo "generated: $(ls ./*_pb2.py)"
