#!/usr/bin/env python
"""Elastic mesh serving A/B child (ISSUE 15): pinned-split vs elastic
serving of the SAME seeded ramped stream, printed as one JSON line.

Run standalone, or by bench.py's `elastic` block (DTS_BENCH_ELASTIC=1) —
the parent decides the device substrate and records it: on a live slice
with >= ELASTIC_AB_DEVICES chips this measures real hardware
(emulated=false); on CPU the parent forces
``XLA_FLAGS=--xla_force_host_platform_device_count=N`` so the numbers
are EMULATED-DEVICE trajectory points (emulated=true — the PR-13
standing-debt field: a CPU run is a functional trajectory point, never a
throughput claim; the live-TPU round flips the flag).

The stream is three pressure phases over one seeded payload cycle, both
runs replaying the SAME schedule:

- ``nominal``   light load (1 outstanding, spaced) — the latency regime;
- ``pressure``  saturating load (8 outstanding, large candidates) with
                the overload plane's queue-wait target set low, so the
                state machine escalates ORGANICALLY (no fault pin);
- ``recovery``  light again — the controller must come back down.

Pinned run: a static ShardedExecutor at {N/2, 2} (the [mesh] default
rung). Elastic run: the {N,1}/{N/2,2} ladder starting at {N/2,2} with an
ElasticController on the same overload signal. Reported per phase:
goodput (completed/s), refusals, p50 latency, the pressure state and the
serving split at phase end — plus the switch history, the first
post-switch request latency next to the steady p50 (the
no-serving-path-compile evidence: every rung was warmup-compiled), and a
bit-identity probe across both runs.
"""

import json
import os
import sys
import time

_need = int(os.environ.get("ELASTIC_AB_DEVICES", "8"))
if os.environ.get("ELASTIC_AB_FORCE_CPU") == "1":
    os.environ["JAX_PLATFORMS"] = "cpu"
if os.environ.get("JAX_PLATFORMS") == "cpu":
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + f" --xla_force_host_platform_device_count={_need}"
        ).strip()
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

if os.environ.get("JAX_PLATFORMS") == "cpu":
    jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

from distributed_tf_serving_tpu.models import (  # noqa: E402
    ModelConfig,
    Servable,
    build_model,
    ctr_signatures,
)
from distributed_tf_serving_tpu.parallel import (  # noqa: E402
    ElasticController,
    ElasticMeshExecutor,
    ShardedExecutor,
    make_mesh,
)
from distributed_tf_serving_tpu.serving import overload as overload_mod  # noqa: E402
from distributed_tf_serving_tpu.serving.batcher import DynamicBatcher  # noqa: E402
from distributed_tf_serving_tpu.utils.config import (  # noqa: E402
    ElasticConfig,
    OverloadConfig,
)

NUM_FIELDS = int(os.environ.get("ELASTIC_AB_FIELDS", "16"))
HEAVY_CANDIDATES = int(os.environ.get("ELASTIC_AB_CANDIDATES", "512"))
LIGHT_CANDIDATES = 64
BUCKETS = (64, 512)
PHASES = (
    ("nominal", float(os.environ.get("ELASTIC_AB_NOMINAL_S", "2")), 1),
    ("pressure", float(os.environ.get("ELASTIC_AB_PRESSURE_S", "4")), 8),
    ("recovery", float(os.environ.get("ELASTIC_AB_RECOVERY_S", "4")), 1),
)


def _payloads(candidates, count=4):
    out = []
    for seed in range(count):
        rng = np.random.RandomState(seed)
        out.append({
            "feat_ids": rng.randint(
                0, 1 << 40, size=(candidates, NUM_FIELDS)
            ).astype(np.int64),
            "feat_wts": rng.rand(candidates, NUM_FIELDS).astype(np.float32),
        })
    return out


def _overload():
    # queue_wait_window_s is deliberately SHORTER than the recovery
    # phase: the default 10 s window would still hold the heavy phase's
    # over-target waits through the whole recovery phase, so the state
    # machine (and with it the down-switch) could never recover inside
    # the bench window.
    return OverloadConfig(
        enabled=True, target_queue_wait_ms=5.0, adjust_interval_s=0.05,
        queue_wait_window_s=2.0,
        brownout_after_intervals=2, recover_after_intervals=3,
    ).build()


def _run(servable, run_fn, make_ctrl=None):
    """One run of the phased stream. make_ctrl(run_fn, overload, batcher)
    attaches the elastic controller (elastic run only)."""
    ov = _overload()
    batcher = DynamicBatcher(
        buckets=BUCKETS, max_wait_us=200, run_fn=run_fn, overload=ov,
    ).start()
    ctrl = make_ctrl(run_fn, ov, batcher) if make_ctrl is not None else None
    light = _payloads(LIGHT_CANDIDATES)
    heavy = _payloads(HEAVY_CANDIDATES)
    phases = {}
    try:
        batcher.warmup(servable)
        prev_switches = 0
        for name, seconds, outstanding in PHASES:
            payloads = heavy if name == "pressure" else light
            done = 0
            refused = 0
            lats = []  # completion order (p50 sorts a copy)
            marks = []  # lats-index right after each observed switch
            pending = []

            def settle():
                nonlocal done, refused
                t_sub, fut = pending.pop(0)
                try:
                    fut.result(timeout=120)
                    lats.append(time.perf_counter() - t_sub)
                    done += 1
                except Exception:  # noqa: BLE001 — refusals counted
                    refused += 1

            t0 = time.perf_counter()
            i = 0
            while time.perf_counter() - t0 < seconds:
                try:
                    fut = batcher.submit(
                        servable, dict(payloads[i % len(payloads)]),
                        output_keys=("prediction_node",),
                    )
                    pending.append((time.perf_counter(), fut))
                except Exception:  # noqa: BLE001 — admission refusal
                    refused += 1
                    time.sleep(0.001)  # honor the pushback, do not spin
                i += 1
                while len(pending) >= outstanding:
                    settle()
                if outstanding == 1:
                    time.sleep(0.002)
                if ctrl is not None and run_fn.switches_up + \
                        run_fn.switches_down > prev_switches:
                    # First completed request AFTER each switch: the
                    # no-compile-on-switch evidence rides its latency.
                    prev_switches = (
                        run_fn.switches_up + run_fn.switches_down
                    )
                    marks.append(len(lats))
            while pending:
                settle()
            wall = time.perf_counter() - t0
            lat_arr = np.asarray(sorted(lats)) if lats else np.asarray([0.0])
            phases[name] = {
                "seconds": round(wall, 2),
                "completed": done,
                "refused": refused,
                "goodput_qps": round(done / wall, 2),
                "candidates_per_s": round(
                    done * payloads[0]["feat_ids"].shape[0] / wall, 0
                ),
                "p50_ms": round(
                    1e3 * float(lat_arr[len(lat_arr) // 2]), 2
                ),
                "pressure_state_end": ov.state(),
            }
            if ctrl is not None:
                phases[name]["split_end"] = (
                    run_fn.elastic_snapshot()["current_split"]
                )
                # Warmup-built executables only: if a switch had paid a
                # compile on the serving path, this first-post-switch
                # latency would sit orders of magnitude over the p50.
                phases[name]["post_switch_first_ms"] = [
                    round(1e3 * lats[m], 2) for m in marks if m < len(lats)
                ]
        result = {"phases": phases}
        if ctrl is not None:
            snap = run_fn.elastic_snapshot()
            result["elastic"] = {
                "switches_up": snap["switches_up"],
                "switches_down": snap["switches_down"],
                "history": snap["history"],
                "per_split": snap["per_split"],
                "controller": snap["controller"],
            }
        # Bit-identity probe payloads (deliberately not mesh-shaped).
        probes = _payloads(37, count=2)
        result["_probe_scores"] = [
            np.asarray(
                batcher.submit(
                    servable, dict(p), output_keys=("prediction_node",)
                ).result(timeout=120)["prediction_node"]
            )
            for p in probes
        ]
        return result
    finally:
        batcher.stop()
        overload_mod.deactivate()


def main() -> dict:
    out = {
        "device": str(jax.devices()[0]),
        "devices_visible": len(jax.devices()),
        "emulated": jax.default_backend() == "cpu",
        "errors": [],
    }
    n = len(jax.devices())
    if n < 2 or n % 2:
        out["errors"].append(f"need an even device count >= 2, have {n}")
        out["ok"] = False
        return out
    cfg = ModelConfig(
        name="DCN", num_fields=NUM_FIELDS, vocab_size=1 << 14, embed_dim=8,
        mlp_dims=(64, 32), num_cross_layers=2, compute_dtype="float32",
    )
    model = build_model("dcn_v2", cfg)
    servable = Servable(
        name="DCN", version=1, model=model,
        params=jax.jit(model.init)(jax.random.PRNGKey(0)),
        signatures=ctr_signatures(NUM_FIELDS),
    )
    pinned_split = (n // 2, 2)

    pinned = _run(
        servable,
        ShardedExecutor(make_mesh(n, model_parallel=2)),
    )
    out["pinned"] = {k: v for k, v in pinned.items() if k != "_probe_scores"}
    out["pinned"]["split"] = f"{pinned_split[0]}x{pinned_split[1]}"

    def make_ctrl(run_fn, ov, batcher):
        return ElasticController(
            ElasticConfig(
                enabled=True, tick_interval_s=0.05, dwell_s=0.3,
                up_after_ticks=2, down_after_ticks=4,
                load_up_threshold=0.9, load_down_threshold=0.3,
            ),
            run_fn, overload=ov, load_fn=batcher.queue_load,
            largest_bucket=max(BUCKETS),
        )

    elastic = _run(
        servable,
        ElasticMeshExecutor(
            splits=[(n, 1), pinned_split], initial=pinned_split,
        ),
        make_ctrl=make_ctrl,
    )
    out["elastic"] = {k: v for k, v in elastic.items() if k != "_probe_scores"}

    same = all(
        np.array_equal(a, b)
        for a, b in zip(pinned["_probe_scores"], elastic["_probe_scores"])
    )
    out["bit_identical"] = same
    if not same:
        out["errors"].append("elastic probe scores != pinned-split probes")
    el = out["elastic"].get("elastic", {})
    out["switch_count"] = el.get("switches_up", 0) + el.get(
        "switches_down", 0
    )
    gain = {}
    for name, _s, _o in PHASES:
        p = out["pinned"]["phases"][name]["goodput_qps"]
        e = out["elastic"]["phases"][name]["goodput_qps"]
        gain[name] = round(e / p, 3) if p else None
    out["goodput_gain_by_phase"] = gain
    out["ok"] = not out["errors"]
    return out


if __name__ == "__main__":
    result = main()
    print(json.dumps(result))
    sys.exit(0 if result.get("ok") else 1)
