#!/usr/bin/env python
"""Gate for the tier-1 cascade smoke (tools/ci_tier1.sh
TIER1_CASCADE_SMOKE=1).

Reads the SOAK_CASCADE=1 soak's JSON line and asserts the multi-stage
cascade's acceptance conditions (ISSUE 19): NONZERO pruned rows from the
worker traffic (workload counters — probe counts subtracted),
rows_ranked/rows_requested strictly under 0.5 at the 25% survivor
fraction (the cascade must actually save full-model work), the
bit-identity probe reporting a match (survivor scores byte-equal to a
full-pass reference, pruned rows byte-equal to stage-1-only), zero gRPC
errors, the cascade spans + /cascadez + dts_tpu_cascade_* Prometheus
series live, and zero fallbacks (a healthy stage-1 must never be
bypassed). Exits nonzero with a reason otherwise, so CI fails with
evidence instead of a silent green.
"""

import json
import sys


def main() -> int:
    path = sys.argv[1] if len(sys.argv) > 1 else "/tmp/tier1_cascade_soak.json"
    lines = []
    with open(path) as f:
        for raw in f:
            raw = raw.strip()
            if raw.startswith("{"):
                try:
                    lines.append(json.loads(raw))
                except json.JSONDecodeError:
                    continue
    if not lines:
        print(f"cascade smoke: no JSON line in {path}", file=sys.stderr)
        return 1
    line = lines[-1]
    casc = line.get("cascade") or {}
    problems = []
    if casc.get("workload_pruned_rows", 0) <= 0:
        problems.append(
            f"zero workload pruned rows (cascade block: {casc})"
        )
    req = casc.get("workload_rows_requested", 0)
    ranked = casc.get("workload_rows_ranked", 0)
    if req <= 0:
        problems.append("zero rows entered the cascade")
    elif ranked / req >= 0.5:
        problems.append(
            f"rank_fraction {ranked}/{req} = {ranked / req:.3f} >= 0.5: "
            "the cascade saved no full-model work at survivor_fraction "
            "0.25"
        )
    if casc.get("scores_match") is not True:
        problems.append(
            f"scores_match != True (got {casc.get('scores_match')!r}): "
            "cascade survivor/pruned scores are not bit-identical to the "
            "full-pass / stage-1-only references"
        )
    if casc.get("fallbacks", 0):
        problems.append(
            f"{casc.get('fallbacks')} full-pass fallbacks with a healthy "
            "stage-1 (stage1_failures="
            f"{casc.get('stage1_failures')})"
        )
    if casc.get("cascadez_live") is not True:
        problems.append(
            f"/cascadez probe not live (got {casc.get('cascadez_live')!r})"
        )
    if casc.get("prometheus_series", 0) <= 0:
        problems.append("no dts_tpu_cascade_* Prometheus series")
    if casc.get("spans_present") is not True:
        problems.append(
            "cascade.stage1/cascade.prune/cascade.stage2 spans missing "
            "from the phase surface"
        )
    if line.get("grpc_err", 0):
        problems.append(
            f"gRPC errors during the cascade soak: {line.get('grpc_err')}"
        )
    if problems:
        for p in problems:
            print(f"cascade smoke FAILED: {p}", file=sys.stderr)
        return 1
    print(
        "cascade smoke ok: rows_ranked/rows_requested={}/{} ({:.3f}) "
        "pruned={} host_prunes={} survivor_buckets={} scores_match={} "
        "prom_series={}".format(
            ranked, req, ranked / req if req else 0.0,
            casc.get("workload_pruned_rows"), casc.get("host_prunes"),
            casc.get("survivor_buckets"), casc.get("scores_match"),
            casc.get("prometheus_series"),
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
