#!/usr/bin/env python
"""CI gate for the model-quality observability smoke (ISSUE 7).

Usage: python tools/check_quality_smoke.py SOAK_LINE_JSON

Reads the JSON line a SOAK_QUALITY=1 soak printed (tools/ci_tier1.sh tees
it to a file) and asserts what the plane promises:

- nonzero scores were sketched, with the warmup ladder EXCLUDED
  (observed_after_warmup == 0 — the completer hook skipped every warmup
  item before worker traffic began);
- labels were joined through the LIVE /labelz route, and the windowed
  AUC the monitor serves is (a) meaningfully above coin-flip (the soak
  trains the model on the teacher first) and (b) within 0.05 of the
  exact AUC the soak computed OFFLINE from its own (score, label) log
  over the same window — train/data.py::auc both times;
- the deliberately shifted traffic segment drove windowed PSI vs the
  pinned reference to/above the configured threshold;
- at least one `quality.drift` exemplar trace is visible in the LIVE
  /tracez body (annotated spans are force-kept by the tail sampler);
- the /monitoring?section=quality filter answered exactly one block;
- dts_tpu_quality_* Prometheus series were served, and the captured
  exposition text passes the lint (tools/check_prom.py) — unique
  families, HELP/TYPE per family, escaped labels, grouped samples.

Exits 0 on success; prints every failure and exits 1.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from check_prom import lint_text  # noqa: E402

AUC_TOLERANCE = 0.05
AUC_FLOOR = 0.55


def main() -> None:
    if len(sys.argv) != 2:
        print("usage: check_quality_smoke.py SOAK_LINE_JSON", file=sys.stderr)
        sys.exit(2)
    path = sys.argv[1]
    line = None
    try:
        with open(path) as f:
            for raw in reversed(f.read().strip().splitlines()):
                try:
                    parsed = json.loads(raw)
                except json.JSONDecodeError:
                    continue
                if isinstance(parsed, dict) and "quality" in parsed:
                    line = parsed
                    break
    except OSError as e:
        print(
            f"check_quality_smoke: FAIL: cannot read {path}: {e}",
            file=sys.stderr,
        )
        sys.exit(1)
    if line is None or not isinstance(line.get("quality"), dict):
        print(
            f"check_quality_smoke: FAIL: no JSON line with a `quality` "
            f"block in {path}", file=sys.stderr,
        )
        sys.exit(1)

    q = line["quality"]
    failures = []
    if q.get("error"):
        failures.append(f"probe error: {q['error']}")
    if q.get("observed_requests", 0) <= 0:
        failures.append(
            f"no scores sketched (observed_requests="
            f"{q.get('observed_requests')})"
        )
    if q.get("observed_after_warmup", -1) != 0:
        failures.append(
            "warmup traffic leaked into the sketch "
            f"(observed_after_warmup={q.get('observed_after_warmup')})"
        )
    if q.get("labels_joined", 0) <= 0:
        failures.append(f"no labels joined (joined={q.get('labels_joined')})")
    win_auc, off_auc = q.get("windowed_auc"), q.get("offline_auc_window")
    if win_auc is None:
        failures.append("windowed AUC missing (no joined pairs in window?)")
    elif win_auc <= AUC_FLOOR:
        failures.append(
            f"windowed AUC {win_auc} not meaningfully above coin-flip "
            f"(floor {AUC_FLOOR}; did the pre-soak training run?)"
        )
    if win_auc is not None and off_auc is not None:
        if abs(win_auc - off_auc) > AUC_TOLERANCE:
            failures.append(
                f"windowed AUC {win_auc} vs offline exact AUC {off_auc}: "
                f"|delta| > {AUC_TOLERANCE} — join/reservoir bug"
            )
    elif off_auc is None:
        failures.append("offline window AUC missing from the soak log")
    drift = q.get("drift") or {}
    ref = drift.get("reference") or {}
    threshold = drift.get("threshold_psi", 0.2)
    if ref.get("psi") is None:
        failures.append(
            "no reference drift computed (was the reference pinned? "
            f"pin={q.get('pin')})"
        )
    elif ref["psi"] < threshold:
        failures.append(
            f"shifted segment did not drive PSI over threshold "
            f"({ref['psi']} < {threshold})"
        )
    if q.get("exemplar_traces", 0) < 1:
        failures.append(
            "no quality.drift exemplar trace visible in the live /tracez "
            f"body (exemplar_traces={q.get('exemplar_traces')})"
        )
    if not q.get("section_filter_ok"):
        failures.append(
            "GET /monitoring?section=quality did not answer exactly the "
            "quality block"
        )
    if q.get("prom_quality_series", 0) <= 0:
        failures.append("no dts_tpu_quality_* Prometheus series served")
    prom_path = q.get("prom_path")
    if not prom_path:
        failures.append("no captured Prometheus text to lint (prom_path missing)")
    else:
        try:
            with open(prom_path) as f:
                lint_errors = lint_text(f.read())
        except OSError as e:
            lint_errors = [f"cannot read {prom_path}: {e}"]
        for err in lint_errors:
            failures.append(f"prometheus lint: {err}")

    if failures:
        for f_ in failures:
            print(f"check_quality_smoke: FAIL: {f_}", file=sys.stderr)
        sys.exit(1)
    print(
        "check_quality_smoke: OK: "
        f"observed={q['observed_requests']} joined={q['labels_joined']} "
        f"windowed_auc={win_auc} offline_auc={off_auc} "
        f"psi={ref.get('psi')} exemplars={q['exemplar_traces']} "
        f"prom_series={q['prom_quality_series']}"
    )


if __name__ == "__main__":
    main()
