#!/usr/bin/env python
"""Gate for the tier-1 row-cache smoke (tools/ci_tier1.sh
TIER1_ROWCACHE_SMOKE=1).

Reads the SOAK_ROWCACHE=1 soak's JSON line and asserts the row-granular
cache plane's acceptance conditions (ISSUE 14): a NONZERO per-row hit
rate on the skewed workload (workload counters — probe hits subtracted),
rows_executed strictly BELOW rows_requested (the plane's whole point:
only cold rows execute), the row-path bit-identity probe reporting a
match against the disarmed plane, and zero gRPC errors. Exits nonzero
with a reason otherwise, so CI fails with evidence instead of a silent
green.
"""

import json
import sys


def main() -> int:
    path = sys.argv[1] if len(sys.argv) > 1 else "/tmp/tier1_rowcache_soak.json"
    lines = []
    with open(path) as f:
        for raw in f:
            raw = raw.strip()
            if raw.startswith("{"):
                try:
                    lines.append(json.loads(raw))
                except json.JSONDecodeError:
                    continue
    if not lines:
        print(f"row-cache smoke: no JSON line in {path}", file=sys.stderr)
        return 1
    line = lines[-1]
    row = line.get("row_cache") or {}
    problems = []
    if row.get("workload_hits", 0) <= 0:
        problems.append(
            f"zero workload row hits (row_cache block: {row})"
        )
    req = row.get("workload_rows_requested", 0)
    execd = row.get("workload_rows_executed", 0)
    if req <= 0:
        problems.append("zero rows entered cold-row extraction")
    elif execd >= req:
        problems.append(
            f"rows_executed ({execd}) >= rows_requested ({req}): the row "
            "cache saved no device work"
        )
    if row.get("scores_match") is not True:
        problems.append(
            f"row scores_match != True (got {row.get('scores_match')!r}): "
            "row-assembled scores are not bit-identical to the disarmed "
            "plane"
        )
    if line.get("grpc_err", 0):
        problems.append(
            f"gRPC errors during the row-cache soak: {line.get('grpc_err')}"
        )
    if problems:
        for p in problems:
            print(f"row-cache smoke FAILED: {p}", file=sys.stderr)
        return 1
    print(
        "row-cache smoke ok: rows_executed/rows_requested={}/{} ({:.3f}) "
        "workload_row_hits={} coalesced={} full_hit_batches={} "
        "scores_match={}".format(
            execd, req, execd / req if req else 0.0,
            row.get("workload_hits"), row.get("workload_coalesced"),
            row.get("row_full_hit_batches"), row.get("scores_match"),
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
