#!/usr/bin/env python
"""CI gate for the continuous-freshness lifecycle smoke (ISSUE 8).

Usage: python tools/check_lifecycle_smoke.py SOAK_LINE_JSON

Reads the JSON line a SOAK_LIFECYCLE=1 soak printed (tools/ci_tier1.sh
tees it to a file) and asserts the acceptance criteria end to end:

- a GOOD canary was published through the real fine-tune publisher and
  AUTO-PROMOTED (promotes >= 1; the live /lifecyclez stable version is
  the published good version and the state settled back to idle);
- a POISONED canary was published and AUTO-ROLLED-BACK (rollbacks >= 1,
  rollback reason recorded with its pair-PSI evidence at/above the
  configured threshold);
- the watcher RETIRED + BLACKLISTED the bad version, and the blacklist
  held across subsequent reconcile passes while the bad directory still
  sat ready on disk (blacklist_survived_reconcile, bad version absent
  from the final loaded set, present in the live blacklist);
- real PAIRED traffic flowed: the canary router sent requests to both
  the canary (probe lane + ramped default share) and the stable version;
- ZERO failed requests attributable to either swap: the whole soak's
  gRPC error count is zero;
- the live surfaces answered: /lifecyclez enabled, the
  /monitoring?section=lifecycle filter served exactly one block, and
  dts_tpu_lifecycle_* Prometheus series were present.

Exits 0 on success; prints every failure and exits 1.
"""

import json
import sys


def main() -> None:
    if len(sys.argv) != 2:
        print("usage: check_lifecycle_smoke.py SOAK_LINE_JSON", file=sys.stderr)
        sys.exit(2)
    path = sys.argv[1]
    line = None
    try:
        with open(path) as f:
            for raw in reversed(f.read().strip().splitlines()):
                try:
                    parsed = json.loads(raw)
                except json.JSONDecodeError:
                    continue
                if isinstance(parsed, dict) and "lifecycle" in parsed:
                    line = parsed
                    break
    except OSError as e:
        print(
            f"check_lifecycle_smoke: FAIL: cannot read {path}: {e}",
            file=sys.stderr,
        )
        sys.exit(1)
    if line is None or not isinstance(line.get("lifecycle"), dict):
        print(
            f"check_lifecycle_smoke: FAIL: no JSON line with a `lifecycle` "
            f"block in {path}", file=sys.stderr,
        )
        sys.exit(1)

    lc = line["lifecycle"]
    counters = lc.get("counters") or {}
    failures = []
    if lc.get("error"):
        failures.append(f"probe error: {lc['error']}")
    good = (lc.get("published_good") or {}).get("version")
    bad = (lc.get("published_poisoned") or {}).get("version")
    if good is None:
        failures.append("good canary was never published")
    if bad is None:
        failures.append("poisoned canary was never published")
    if counters.get("promotes", 0) < 1:
        failures.append(
            f"good canary was not auto-promoted (promotes="
            f"{counters.get('promotes')}, waited "
            f"{lc.get('promote_wait_s')}s)"
        )
    elif lc.get("stable_version") != good:
        failures.append(
            f"promoted stable version {lc.get('stable_version')} != the "
            f"published good canary {good}"
        )
    if counters.get("rollbacks", 0) < 1:
        failures.append(
            f"poisoned canary was not auto-rolled-back (rollbacks="
            f"{counters.get('rollbacks')}, waited "
            f"{lc.get('rollback_wait_s')}s)"
        )
    else:
        rb = lc.get("last_rollback") or {}
        if rb.get("version") != bad:
            failures.append(
                f"rollback hit version {rb.get('version')}, expected the "
                f"poisoned canary {bad}"
            )
        if not rb.get("reason"):
            failures.append("rollback carries no recorded reason/evidence")
    if bad is not None:
        if bad in (lc.get("post_rollback_versions") or []):
            failures.append(
                f"poisoned version {bad} still loaded after rollback "
                f"(loaded={lc.get('post_rollback_versions')})"
            )
        if bad not in (lc.get("blacklisted") or []):
            failures.append(
                f"poisoned version {bad} missing from the live blacklist "
                f"({lc.get('blacklisted')})"
            )
    if not lc.get("blacklist_survived_reconcile"):
        failures.append(
            "blacklist did not survive the watcher's reconcile passes — "
            "the rolled-back version was reloaded from disk"
        )
    if counters.get("routed_canary", 0) <= 0:
        failures.append("no traffic was ever routed to a canary")
    if counters.get("routed_stable", 0) <= 0:
        failures.append(
            "no default-lane traffic stayed on stable during canary "
            "(the paired comparison had nothing to compare)"
        )
    grpc_err = line.get("grpc_err", -1)
    if grpc_err != 0:
        failures.append(
            f"swaps must not fail traffic: grpc_err={grpc_err} "
            f"(taxonomy={line.get('error_taxonomy')})"
        )
    if not lc.get("lifecyclez_enabled"):
        failures.append("live /lifecyclez did not answer enabled=true")
    if not lc.get("section_filter_ok"):
        failures.append(
            "GET /monitoring?section=lifecycle did not answer exactly the "
            "lifecycle block"
        )
    if lc.get("prom_lifecycle_series", 0) <= 0:
        failures.append("no dts_tpu_lifecycle_* Prometheus series served")

    if failures:
        for f_ in failures:
            print(f"check_lifecycle_smoke: FAIL: {f_}", file=sys.stderr)
        sys.exit(1)
    print(
        "check_lifecycle_smoke: OK: "
        f"promoted v{good} in {lc.get('promote_wait_s')}s, rolled back "
        f"v{bad} in {lc.get('rollback_wait_s')}s "
        f"(psi={((lc.get('last_rollback') or {}).get('pair') or {}).get('psi')}), "
        f"routed canary={counters.get('routed_canary')} "
        f"stable={counters.get('routed_stable')} "
        f"probe={counters.get('routed_probe')}, "
        f"blacklist held, {line.get('grpc_ok')} requests 0 errors, "
        f"prom_series={lc.get('prom_lifecycle_series')}"
    )


if __name__ == "__main__":
    main()
