#!/usr/bin/env python
"""CI gate for the fleet kill/restart chaos smoke (ISSUE 17).

Usage: python tools/check_fleet_smoke.py SOAK_LINE_JSON

Reads the JSON line a SOAK_FLEET=1 soak printed (tools/ci_tier1.sh tees
it to a file) and asserts the acceptance criteria end to end:

- the chaos script ran against a real multi-process fleet (>= 3 serving
  replica subprocesses behind the fleet.router subprocess) with edge
  traffic dialing ONLY the router;
- SIGKILLing one replica mid-traffic cost ZERO edge-visible errors (the
  router's scoreboard + failover absorbed it) and every per-1s goodput
  window of the kill/restart phase (kill -> canary publish) stayed at
  >= half the steady-state median — the rollout phase that follows is
  excluded from the goodput gate (three replicas warmup-compiling the
  canary at once starve a CPU host) and gated on zero errors + bounded
  propagation instead;
- the restarted replica REJOINED through gossip (its serving record
  re-admitted it to the router's rotation: state `serving` in the
  router's /fleetz view and healthy_backends back at full strength),
  within a bounded wall time;
- the canary published into the shared base dir went live on every
  replica, and ONE replica's operator rollback propagated FLEET-WIDE:
  the router's rollout coordinator blacklisted the version and every
  replica's lifecycle rolled it back within about one gossip interval
  of the router's state change;
- scores through the router stayed BIT-IDENTICAL to a direct backend
  call, both before the chaos and after the rollback settled;
- the observability surfaces answered: dts_tpu_fleet_* series on the
  router's gossip-port /metrics AND in a replica's REST exposition.

Exits 0 on success; prints every failure and exits 1.
"""

import json
import sys

REJOIN_BOUND_S = 45.0
# Propagation is measured between two polled observations (router
# blacklist seen -> last replica rolled back); delivery itself rides each
# replica's next push-pull exchange, i.e. at most one gossip interval,
# with the poll cadence on both ends as slack.
PROPAGATION_SLACK_S = 1.0
MIN_GOODPUT_RATIO = 0.5


def main() -> None:
    if len(sys.argv) != 2:
        print("usage: check_fleet_smoke.py SOAK_LINE_JSON", file=sys.stderr)
        sys.exit(2)
    path = sys.argv[1]
    line = None
    try:
        with open(path) as f:
            for raw in reversed(f.read().strip().splitlines()):
                try:
                    parsed = json.loads(raw)
                except json.JSONDecodeError:
                    continue
                if isinstance(parsed, dict) and "fleet" in parsed:
                    line = parsed
                    break
    except OSError as e:
        print(
            f"check_fleet_smoke: FAIL: cannot read {path}: {e}",
            file=sys.stderr,
        )
        sys.exit(1)
    if line is None or not isinstance(line.get("fleet"), dict):
        print(
            f"check_fleet_smoke: FAIL: no JSON line with a `fleet` block "
            f"in {path}", file=sys.stderr,
        )
        sys.exit(1)

    fl = line["fleet"]
    kill = fl.get("kill") or {}
    rollout = fl.get("rollout") or {}
    failures = []

    if fl.get("replicas", 0) < 3:
        failures.append(
            f"fleet ran with {fl.get('replicas')} replicas (need >= 3 "
            "for a kill to leave a quorum)"
        )
    if fl.get("requests", 0) < 50:
        failures.append(
            f"only {fl.get('requests')} edge requests — the soak never "
            "generated meaningful traffic"
        )
    # THE headline criterion: a replica died and came back mid-traffic
    # and no edge client ever saw it.
    if fl.get("errors", 0) != 0:
        failures.append(
            f"{fl.get('errors')} edge-visible error(s) — taxonomy: "
            f"{fl.get('error_taxonomy')}"
        )
    ratio = fl.get("min_chaos_window_ratio")
    if ratio is None or ratio < MIN_GOODPUT_RATIO:
        failures.append(
            f"goodput collapsed during chaos: min per-1s window ratio "
            f"{ratio} < {MIN_GOODPUT_RATIO} of the steady median "
            f"({fl.get('steady_window_median')}/s; chaos windows: "
            f"{fl.get('chaos_windows')})"
        )
    if not fl.get("bit_identical_pre"):
        failures.append(
            "pre-chaos probe: scores through the router were NOT "
            "bit-identical to a direct backend call"
        )
    if not fl.get("bit_identical_post"):
        failures.append(
            "post-rollback probe: scores through the router were NOT "
            "bit-identical to a direct backend call"
        )
    rejoin_s = kill.get("rejoin_s")
    if rejoin_s is None or rejoin_s > REJOIN_BOUND_S:
        failures.append(
            f"restarted replica {kill.get('victim')} did not rejoin via "
            f"gossip within {REJOIN_BOUND_S}s (took: {rejoin_s}s)"
        )
    if kill.get("healthy_backends") != fl.get("replicas"):
        failures.append(
            f"rotation never returned to full strength after the "
            f"restart (healthy_backends={kill.get('healthy_backends')} "
            f"of {fl.get('replicas')})"
        )
    if not rollout.get("rollback_accepted"):
        failures.append(
            "the operator rollback POST was never accepted — no canary "
            "was live to roll back"
        )
    interval = fl.get("gossip_interval_s") or 0.5
    prop = rollout.get("propagation_s")
    bound = interval + PROPAGATION_SLACK_S
    if prop is None or prop > bound:
        failures.append(
            f"fleet-wide rollback took {prop}s from the router's "
            f"blacklist to the last replica (bound: one gossip interval "
            f"{interval}s + {PROPAGATION_SLACK_S}s slack = {bound}s)"
        )
    per_replica = rollout.get("per_replica_rolled_back") or []
    if len(per_replica) != fl.get("replicas") or any(
        v != rollout.get("canary_version") for v in per_replica
    ):
        failures.append(
            f"not every replica rolled the canary back "
            f"(rolled_back_version per replica: {per_replica})"
        )
    counters = fl.get("router_counters") or {}
    if counters.get("requests", 0) < 50:
        failures.append(
            f"router forwarded only {counters.get('requests')} requests "
            "— edge traffic did not route through it"
        )
    if fl.get("prom_router_series", 0) < 10:
        failures.append(
            f"only {fl.get('prom_router_series')} dts_tpu_fleet_* series "
            "on the router's /metrics (expected >= 10)"
        )
    if fl.get("prom_replica_series", 0) < 5:
        failures.append(
            f"only {fl.get('prom_replica_series')} dts_tpu_fleet_* "
            "series in the replica's REST exposition (expected >= 5)"
        )

    if failures:
        print("check_fleet_smoke: FAIL", file=sys.stderr)
        for f_ in failures:
            print(f"  - {f_}", file=sys.stderr)
        sys.exit(1)
    print(
        "check_fleet_smoke: OK "
        f"(requests={fl.get('requests')} errors=0 "
        f"min_window_ratio={ratio} rejoin={rejoin_s}s "
        f"rollback_propagation={prop}s "
        f"fleet_series={fl.get('prom_router_series')}+"
        f"{fl.get('prom_replica_series')})"
    )


if __name__ == "__main__":
    main()
