#!/usr/bin/env python
"""Gate for the tier-1 cache smoke (tools/ci_tier1.sh TIER1_CACHE_SMOKE=1).

Reads the SOAK_CACHE=1 soak's JSON line and asserts the cache plane's
acceptance conditions: a NONZERO hit rate on the skewed workload, and the
pre-flight bit-identity probe (uncached-miss scores vs cached-hit scores)
reporting a match. Exits nonzero with a reason otherwise, so CI fails with
evidence instead of a silent green.
"""

import json
import sys


def main() -> int:
    path = sys.argv[1] if len(sys.argv) > 1 else "/tmp/tier1_cache_soak.json"
    lines = []
    with open(path) as f:
        for raw in f:
            raw = raw.strip()
            if raw.startswith("{"):
                try:
                    lines.append(json.loads(raw))
                except json.JSONDecodeError:
                    continue
    if not lines:
        print(f"cache smoke: no JSON line in {path}", file=sys.stderr)
        return 1
    line = lines[-1]
    cache = line.get("cache") or {}
    problems = []
    # WORKLOAD hits (probe counts subtracted): the pre-flight probe
    # guarantees one hit by construction, so gating on the raw counter
    # would pass even if worker traffic never hit once.
    if cache.get("workload_hits", 0) <= 0:
        problems.append(f"zero workload cache hits (cache block: {cache})")
    if cache.get("hit_rate", 0.0) <= 0.0:
        problems.append("hit_rate is zero")
    if cache.get("scores_match") is not True:
        problems.append(
            f"scores_match != True (got {cache.get('scores_match')!r}): "
            "cached scores are not bit-identical to uncached"
        )
    if line.get("grpc_err", 0) and not line.get("grpc_ok", 0):
        problems.append("every gRPC request errored during the cache soak")
    if problems:
        for p in problems:
            print(f"cache smoke FAILED: {p}", file=sys.stderr)
        return 1
    print(
        "cache smoke ok: hit_rate={} workload_hits={} coalesced={} "
        "dedup_rows={} scores_match={}".format(
            cache.get("hit_rate"), cache.get("workload_hits"),
            cache.get("coalesced"), cache.get("dedup_rows_collapsed"),
            cache.get("scores_match"),
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
