#!/usr/bin/env python
"""Streaming/transport tier-1 smoke (ISSUE 9): a CPU-safe, self-contained
gate asserting the PR's correctness contract end to end over REAL gRPC —

- streamed (PredictStream, chunked sub-batches) and unary Predict return
  BIT-IDENTICAL scores, over TCP loopback AND a Unix-domain socket, with
  the fault injector delaying readbacks so chunks genuinely complete out
  of order;
- the client's incremental merge survives the out-of-order arrival and
  records first-scores latency;
- the k-deep pipeline (depth 4, in-flight window 4, buffer ring) serves
  the same scores as the defaults would;
- a mid-stream deadline aborts DEADLINE_EXCEEDED instead of hanging.

Prints one JSON line; exit 0 = gate passed. Run by tools/ci_tier1.sh under
TIER1_STREAMING_SMOKE=1.
"""

import asyncio
import json
import os
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

if os.environ.get("JAX_PLATFORMS") == "cpu":
    jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

from distributed_tf_serving_tpu import faults  # noqa: E402
from distributed_tf_serving_tpu.client import (  # noqa: E402
    ShardedPredictClient,
    make_payload,
)
from distributed_tf_serving_tpu.models import ServableRegistry  # noqa: E402
from distributed_tf_serving_tpu.serving.batcher import DynamicBatcher  # noqa: E402
from distributed_tf_serving_tpu.serving.server import (  # noqa: E402
    create_server_async,
    load_demo_servable,
)
from distributed_tf_serving_tpu.serving.service import (  # noqa: E402
    PredictionServiceImpl,
    ServiceError,
)

CANDIDATES = int(os.environ.get("SMOKE_CANDIDATES", "200"))
CHUNK = int(os.environ.get("SMOKE_CHUNK", "48"))
NUM_FIELDS = 16


def build_stack():
    registry = ServableRegistry()
    batcher = DynamicBatcher(
        buckets=(32, 64, 128, 256),
        max_wait_us=200,
        pipeline_depth=4,
        inflight_window=4,
        buffer_ring=True,
    ).start()
    servable = load_demo_servable(
        registry, kind="dcn_v2", name="DCN",
        num_fields=NUM_FIELDS, vocab_size=1 << 12, embed_dim=4,
        mlp_dims=(16,), num_cross_layers=1, compute_dtype="float32",
    )
    batcher.warmup(servable)
    impl = PredictionServiceImpl(registry, batcher)
    impl.response_arena = True
    return registry, batcher, impl


async def main() -> dict:
    _registry, batcher, impl = build_stack()
    uds = os.path.join(tempfile.gettempdir(), f"dts_smoke_{os.getpid()}.sock")
    server, port = create_server_async(impl, "127.0.0.1:0", uds_path=uds)
    await server.start()
    out = {
        "bit_identical": {},
        "out_of_order_seen": False,
        "first_scores_p50_ms": None,
        "stream_chunks": 0,
        "deadline_aborted": False,
        "pipeline": None,
        "errors": [],
    }
    payloads = [
        make_payload(candidates=CANDIDATES, num_fields=NUM_FIELDS, seed=s)
        for s in (1, 2, 3)
    ]
    try:
        # Out-of-order pressure: every few readbacks stall 60 ms, so chunk
        # completion order decouples from offset order deterministically
        # enough to observe across the run.
        faults.get().add("readback", "delay", rate=0.34, delay_s=0.06)
        for target in (f"127.0.0.1:{port}", f"unix:{uds}"):
            async with ShardedPredictClient(
                [target], "DCN", stream_chunk_candidates=CHUNK,
            ) as client:
                identical = True
                for p in payloads:
                    unary = await client.predict(p, sort_scores=True)
                    streamed = await client.predict_streamed(
                        p, sort_scores=True
                    )
                    if not np.array_equal(unary, streamed):
                        identical = False
                        out["errors"].append(
                            f"{target}: streamed != unary (max delta "
                            f"{float(np.max(np.abs(unary - streamed)))})"
                        )
                out["bit_identical"][target] = identical
                stats = client.stream_stats()
                out["stream_chunks"] += stats["stream_chunks"]
                if stats["first_score_p50_ms"] is not None:
                    out["first_scores_p50_ms"] = stats["first_score_p50_ms"]
        faults.reset()

        # Direct generator probe for out-of-order arrival: delay exactly
        # the first sub-batch's readback; its chunk must flush last.
        from distributed_tf_serving_tpu.client import build_predict_request

        faults.get().add("readback", "delay", delay_s=0.3, count=1)
        req = build_predict_request(
            payloads[0], "DCN", output_filter=("prediction_node",)
        )
        offsets = [c.offset for c in impl.predict_stream(req, chunk=CHUNK)]
        faults.reset()
        out["out_of_order_seen"] = offsets != sorted(offsets)
        if not out["out_of_order_seen"]:
            out["errors"].append(
                f"chunks arrived in offset order {offsets} despite a "
                "delayed first readback"
            )

        # Deadline mid-stream: every dispatch stalls past the budget.
        faults.get().add("batcher.dispatch", "delay", delay_s=1.0)
        t0 = time.perf_counter()
        try:
            for _c in impl.predict_stream(req, deadline_s=0.25, chunk=CHUNK):
                pass
            out["errors"].append("mid-stream deadline did not abort")
        except ServiceError as e:
            out["deadline_aborted"] = e.code == "DEADLINE_EXCEEDED"
            if not out["deadline_aborted"]:
                out["errors"].append(f"aborted with {e.code}, not DEADLINE_EXCEEDED")
        if time.perf_counter() - t0 > 5.0:
            out["errors"].append("deadline abort took > 5s")
        faults.reset()

        out["pipeline"] = impl.pipeline_stats()
        if out["pipeline"]["inflight_peak"] < 2:
            out["errors"].append(
                "inflight_peak < 2: sub-batches never overlapped "
                f"({out['pipeline']})"
            )
        ring = out["pipeline"].get("buffer_ring") or {}
        if not ring.get("reuses"):
            out["errors"].append(f"buffer ring never reused: {ring}")
        if not all(out["bit_identical"].values()) or len(out["bit_identical"]) != 2:
            out["errors"].append("bit-identity did not hold on both transports")
    finally:
        faults.reset()
        await server.stop(0)
        batcher.stop()
        try:
            os.unlink(uds)
        except OSError:
            pass
    out["ok"] = not out["errors"] and out["deadline_aborted"]
    return out


if __name__ == "__main__":
    result = asyncio.run(main())
    print(json.dumps(result))
    sys.exit(0 if result["ok"] else 1)
