#!/usr/bin/env python
"""CI gate for the fleet observability smoke (ISSUE 18).

Usage: python tools/check_fleetobs_smoke.py SOAK_LINE_JSON

Reads the JSON line a SOAK_FLEET=1 SOAK_TRACE_OUT=... soak printed
(tools/ci_tier1.sh TIER1_FLEETOBS_SMOKE=1 tees it to a file) and
asserts the fleet observability plane's acceptance criteria:

- the router's TraceCollector stitched >= 1 cross-process trace that
  spans at least THREE processes (edge client + router + replica) —
  the whole point of trace stitching;
- the hop waterfall on a stitched trace CLOSES: the components plus the
  reported `other` residual sum to the root duration within 2% (the
  decomposition partitions by construction, so a miss means the export
  mangled it);
- the fleet aggregate qps equals the sum of the per-member qps within
  5% (the aggregate must be an honest sum, not a resample);
- the SLO monitor answered with sane burn rates: enabled, every
  short/long burn value a finite number >= 0, and the breach flag a
  bool (a CPU-host soak may legitimately breach a 100ms target — the
  gate checks sanity, not greenness);
- the Chrome multi-pid artifact is non-empty (its schema + multi-pid
  invariants are gated separately by check_trace.py --require-multi-pid).

Exits 0 on success; prints every failure and exits 1 — the CI step
uploads the soak line + trace artifact on failure.
"""

import json
import math
import sys

WATERFALL_CLOSE_TOL = 0.02
QPS_AGG_TOL = 0.05


def main() -> None:
    if len(sys.argv) != 2:
        print("usage: check_fleetobs_smoke.py SOAK_LINE_JSON", file=sys.stderr)
        sys.exit(2)
    path = sys.argv[1]
    line = None
    try:
        with open(path) as f:
            for raw in reversed(f.read().strip().splitlines()):
                try:
                    parsed = json.loads(raw)
                except json.JSONDecodeError:
                    continue
                if isinstance(parsed, dict) and "fleetobs" in parsed:
                    line = parsed
                    break
    except OSError as e:
        print(
            f"check_fleetobs_smoke: FAIL: cannot read {path}: {e}",
            file=sys.stderr,
        )
        sys.exit(1)
    if line is None or not isinstance(line.get("fleetobs"), dict):
        print(
            f"check_fleetobs_smoke: FAIL: no JSON line with a `fleetobs` "
            f"block in {path}", file=sys.stderr,
        )
        sys.exit(1)

    fo = line["fleetobs"]
    failures = []

    if fo.get("three_proc_traces", 0) < 1:
        failures.append(
            f"no stitched >=3-process trace "
            f"(three_proc_traces={fo.get('three_proc_traces')}, "
            f"stitched_traces={fo.get('stitched_traces')}) — the "
            "collector never joined client + router + replica"
        )
    wf = fo.get("waterfall")
    if not isinstance(wf, dict):
        failures.append(
            "no hop waterfall on any stitched 3-process trace"
        )
    else:
        total = wf.get("total_us") or 0
        comps = wf.get("components_us") or {}
        other = wf.get("other_us", 0)
        closed = sum(comps.values()) + other
        if total <= 0:
            failures.append(f"waterfall total_us={total} (must be > 0)")
        elif abs(closed - total) > max(WATERFALL_CLOSE_TOL * total, 1):
            failures.append(
                f"hop waterfall does not close: components + other = "
                f"{closed} vs total_us = {total} (tolerance "
                f"{WATERFALL_CLOSE_TOL:.0%}) — a residual was hidden"
            )
    agg_qps = fo.get("agg_qps")
    member_sum = fo.get("member_qps_sum")
    if not isinstance(agg_qps, (int, float)) or \
            not isinstance(member_sum, (int, float)) or member_sum <= 0:
        failures.append(
            f"aggregate qps unusable (agg_qps={agg_qps!r}, "
            f"member_qps_sum={member_sum!r})"
        )
    elif abs(agg_qps - member_sum) > QPS_AGG_TOL * member_sum:
        failures.append(
            f"aggregate qps {agg_qps} vs member sum {member_sum} "
            f"diverges past {QPS_AGG_TOL:.0%}"
        )
    slo = fo.get("slo")
    if not isinstance(slo, dict) or not slo.get("enabled"):
        failures.append(f"SLO monitor did not answer enabled (slo={slo!r})")
    else:
        burn = slo.get("burn") or {}
        if not burn:
            failures.append("SLO snapshot carries no burn rates")
        for name, windows in burn.items():
            for w, v in (windows or {}).items():
                if not isinstance(v, (int, float)) or \
                        isinstance(v, bool) or not math.isfinite(v) or v < 0:
                    failures.append(
                        f"burn rate {name}.{w} = {v!r} is not a finite "
                        "number >= 0"
                    )
        if not isinstance(slo.get("breached"), bool):
            failures.append(
                f"SLO breached flag is {slo.get('breached')!r}, not a bool"
            )
    if fo.get("trace_events", 0) < 3:
        failures.append(
            f"Chrome export holds only {fo.get('trace_events')} events — "
            "a stitched 3-process trace emits at least its process "
            "metadata + spans"
        )
    if not fo.get("trace_out"):
        failures.append("no trace artifact path recorded")

    if failures:
        print("check_fleetobs_smoke: FAIL", file=sys.stderr)
        for f_ in failures:
            print(f"  - {f_}", file=sys.stderr)
        sys.exit(1)
    print(
        "check_fleetobs_smoke: OK "
        f"(three_proc_traces={fo.get('three_proc_traces')} "
        f"waterfall_total_us={(wf or {}).get('total_us')} "
        f"agg_qps={agg_qps} member_qps_sum={member_sum} "
        f"slo_breached={(slo or {}).get('breached')} "
        f"trace_events={fo.get('trace_events')})"
    )


if __name__ == "__main__":
    main()
