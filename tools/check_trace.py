#!/usr/bin/env python
"""Schema validation for an exported Chrome-trace-event JSON artifact.

Usage: python tools/check_trace.py PATH [--min-events N]
       [--require-counter-track] [--require-multi-pid]

Asserts what Perfetto / chrome://tracing need to load the file — and what
the CI smoke step (tools/ci_tier1.sh TIER1_TRACE_SMOKE=1, on a
SOAK_CHAOS=1 traced soak) promises about the tracing plane:

- valid JSON with a non-empty `traceEvents` list;
- every event has name/ph/pid/tid; complete ("X") events carry integer,
  non-negative, monotonicity-safe ts/dur (ts >= 0, dur >= 0, and an
  event never ends before it starts by construction);
- at least one span event exists (the soak actually traced requests) and
  span events carry the trace/span-id args the /tracez JSON cross-links;
- counter ("C") events — the utilization plane's per-device occupancy
  track — carry integer non-negative ts, NON-DECREASING within each
  (pid, tid, name) track (Perfetto rejects time travel on counter
  tracks), at least one numeric arg value, and a per-device track NAME:
  every counter's (pid, tid) must have a thread_name metadata event with
  a non-empty name (the device label). `--require-counter-track` makes
  the track's presence mandatory (the SOAK_UTIL=1 smoke).
- `--require-multi-pid` (the TIER1_FLEETOBS_SMOKE=1 fleet soak): the
  file holds at least one STITCHED cross-process trace — every
  args.trace_id group spans >= 2 distinct pids, span ts are
  non-decreasing within each (pid, tid) track, and any hop-waterfall
  args (`wf_*_us`) are numeric and sum to the root event's dur within
  2% (the residual component `wf_other_us` is part of the sum, so an
  honest export closes exactly).

Exits 0 on success; prints the failure and exits 1 otherwise — the CI
step uploads the artifact on failure so the broken file is inspectable.
"""

import json
import sys


def fail(msg: str) -> "NoReturn":  # noqa: F821 — py3.10 typing comment only
    print(f"check_trace: FAIL: {msg}", file=sys.stderr)
    sys.exit(1)


def main() -> None:
    argv = sys.argv[1:]
    min_events = 1
    require_counters = False
    require_multi_pid = False
    positional = []
    i = 0
    while i < len(argv):
        a = argv[i]
        if a == "--min-events":
            if i + 1 >= len(argv):
                fail("--min-events needs a value")
            min_events = int(argv[i + 1])
            i += 2  # the value is NOT a positional
            continue
        if a.startswith("--min-events="):
            min_events = int(a.split("=", 1)[1])
        elif a == "--require-counter-track":
            require_counters = True
        elif a == "--require-multi-pid":
            require_multi_pid = True
        elif a.startswith("--"):
            fail(f"unknown flag {a!r}")
        else:
            positional.append(a)
        i += 1
    if not positional:
        fail(
            "usage: check_trace.py PATH [--min-events N] "
            "[--require-counter-track] [--require-multi-pid]"
        )
    path = positional[0]
    try:
        with open(path) as f:
            doc = json.load(f)
    except FileNotFoundError:
        fail(f"{path}: no such file")
    except json.JSONDecodeError as e:
        fail(f"{path}: invalid JSON: {e}")

    events = doc.get("traceEvents") if isinstance(doc, dict) else doc
    if not isinstance(events, list):
        fail("traceEvents is not a list")
    if len(events) < min_events:
        fail(f"only {len(events)} events (< {min_events})")

    spans = 0
    counters = 0
    track_names: dict[tuple, str] = {}  # (pid, tid) -> thread_name
    counter_last_ts: dict[tuple, int] = {}  # (pid, tid, name) -> last ts
    trace_pids: dict[str, set] = {}  # args.trace_id -> {pid}
    span_last_ts: dict[tuple, int] = {}  # (pid, tid) -> last span ts
    for i, ev in enumerate(events):
        if not isinstance(ev, dict):
            fail(f"event {i} is not an object")
        for key in ("name", "ph", "pid", "tid"):
            if key not in ev:
                fail(f"event {i} missing {key!r}: {ev}")
        if ev["ph"] == "M" and ev["name"] == "thread_name":
            track_names[(ev["pid"], ev["tid"])] = (
                (ev.get("args") or {}).get("name") or ""
            )
        if ev["ph"] == "X":
            spans += 1
            for key in ("ts", "dur"):
                val = ev.get(key)
                if not isinstance(val, int) or val < 0:
                    fail(
                        f"event {i} ({ev['name']!r}) {key}={val!r} must be "
                        "a non-negative integer"
                    )
            args_blk = ev.get("args", {})
            for key in ("trace_id", "span_id"):
                if not args_blk.get(key):
                    fail(f"span event {i} ({ev['name']!r}) missing args.{key}")
            trace_pids.setdefault(str(args_blk["trace_id"]), set()).add(
                ev["pid"]
            )
            track = (ev["pid"], ev["tid"])
            if require_multi_pid and ev["ts"] < span_last_ts.get(track, 0):
                fail(
                    f"span event {i} ({ev['name']!r}) ts={ev['ts']} goes "
                    f"BACKWARD on track {track} (last "
                    f"{span_last_ts[track]}) — the stitched export must "
                    "sort per-track"
                )
            span_last_ts[track] = ev["ts"]
            wf = {
                k: v for k, v in args_blk.items() if k.startswith("wf_")
            }
            if wf:
                for key, val in wf.items():
                    if not isinstance(val, (int, float)) or \
                            isinstance(val, bool):
                        fail(
                            f"span event {i} ({ev['name']!r}) waterfall "
                            f"arg {key}={val!r} must be numeric"
                        )
                total = sum(wf.values())
                dur = ev["dur"]
                if abs(total - dur) > max(0.02 * dur, 1):
                    fail(
                        f"span event {i} ({ev['name']!r}) hop waterfall "
                        f"sums to {total} but dur={dur} — components + "
                        "wf_other_us must close within 2%"
                    )
        if ev["ph"] == "C":
            counters += 1
            ts = ev.get("ts")
            if not isinstance(ts, int) or ts < 0:
                fail(
                    f"counter event {i} ({ev['name']!r}) ts={ts!r} must be "
                    "a non-negative integer"
                )
            track = (ev["pid"], ev["tid"], ev["name"])
            if ts < counter_last_ts.get(track, 0):
                fail(
                    f"counter event {i} ({ev['name']!r}) ts={ts} goes "
                    f"BACKWARD on track {track} (last "
                    f"{counter_last_ts[track]}) — Perfetto rejects "
                    "non-monotonic counter tracks"
                )
            counter_last_ts[track] = ts
            args_blk = ev.get("args")
            if not isinstance(args_blk, dict) or not any(
                isinstance(v, (int, float)) for v in args_blk.values()
            ):
                fail(
                    f"counter event {i} ({ev['name']!r}) needs at least "
                    "one numeric args value"
                )
    if spans == 0:
        fail("no complete ('X') span events — nothing was traced")
    if counters:
        # Per-device track names: every counter track must be labeled
        # with its device via thread_name metadata.
        for pid, tid, name in counter_last_ts:
            if not track_names.get((pid, tid)):
                fail(
                    f"counter track {name!r} on (pid={pid}, tid={tid}) has "
                    "no thread_name metadata (the per-device track label)"
                )
    if require_counters and counters == 0:
        fail(
            "no counter ('C') events — the device-occupancy counter track "
            "is required (--require-counter-track)"
        )
    multi_pid = sum(1 for pids in trace_pids.values() if len(pids) >= 2)
    if require_multi_pid:
        if not trace_pids:
            fail("--require-multi-pid: no traces in the file")
        single = {
            tid: pids for tid, pids in trace_pids.items() if len(pids) < 2
        }
        if single:
            tid, pids = next(iter(single.items()))
            fail(
                f"--require-multi-pid: trace {tid!r} spans only "
                f"{sorted(pids)} — every exported trace must stitch "
                f">= 2 processes ({len(single)}/{len(trace_pids)} failed)"
            )
    print(
        f"check_trace: OK: {len(events)} events, {spans} spans, "
        f"{counters} counter events, {multi_pid}/{len(trace_pids)} "
        f"multi-process traces ({path})"
    )


if __name__ == "__main__":
    main()
