#!/usr/bin/env python
"""Load-shape experiment: sweep client concurrency against the real serving
stack and report QPS / p50 / host-CPU utilization per point.

Decides the round-3 tuning question: is the rig Little's-law latency-bound
(QPS scales with concurrency) or single-core host-CPU-bound (QPS flat, CPU
util ~1.0)? Run directly; not part of the bench contract.
"""

import asyncio
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

CANDIDATES = 1000
NUM_FIELDS = 43


def main() -> None:
    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")

    from distributed_tf_serving_tpu.client import (
        ShardedPredictClient,
        make_payload,
        run_closed_loop,
    )
    from distributed_tf_serving_tpu.models import (
        ModelConfig,
        Servable,
        ServableRegistry,
        build_model,
        ctr_signatures,
    )
    from distributed_tf_serving_tpu.serving import DynamicBatcher, PredictionServiceImpl
    from distributed_tf_serving_tpu.serving.server import create_server

    platform = jax.devices()[0].platform
    tpu = platform != "cpu"
    print(f"[exp] device={jax.devices()[0]} platform={platform}", file=sys.stderr)

    config = ModelConfig(
        name="DCN", num_fields=NUM_FIELDS, vocab_size=1 << 20, embed_dim=16,
        mlp_dims=(256, 128, 64), num_cross_layers=3, cross_full_matrix=True,
    )
    model = build_model("dcn_v2", config)
    params = model.init(jax.random.PRNGKey(0))
    registry = ServableRegistry()
    ladder = (32, 64, 128, 256, 512, 1024, 2048, 4096, 8192, 16384, 32768)
    top = int(os.environ.get("EXP_TOP_BUCKET", "8192"))
    batcher = DynamicBatcher(
        buckets=tuple(b for b in ladder if b <= top),
        max_wait_us=int(os.environ.get("EXP_MAX_WAIT_US", "2000")),
        completion_workers=12,
        queue_capacity_candidates=32 * top,
    ).start()
    impl = PredictionServiceImpl(registry, batcher)
    servable = Servable(name="DCN", version=1, model=model, params=params,
                        signatures=ctr_signatures(config.num_fields))
    registry.load(servable)
    for b in (1024, 2048, 4096, 8192, 16384, 32768):
        if b > top:
            continue
        t0 = time.perf_counter()
        batcher.warmup(servable, buckets=(b,))
        print(f"[exp] warm bucket={b} {time.perf_counter()-t0:.1f}s", file=sys.stderr)

    si = float(os.environ.get("EXP_SWITCH_INTERVAL", "0"))
    if si > 0:
        sys.setswitchinterval(si)
    from distributed_tf_serving_tpu.utils.tracing import request_trace
    request_trace.reset()  # warmup compiles out of the phase means
    concs = [int(x) for x in os.environ.get("EXP_CONCS", "48,64,96,128,160").split(",")]
    use_aio = os.environ.get("EXP_AIO", "0") == "1"
    channels = int(os.environ.get("EXP_CHANNELS", "6"))
    payload = make_payload(candidates=CANDIDATES, num_fields=NUM_FIELDS)
    results = []

    # EXP_COMPACT=1: the framework-native wire (client-side fold + bf16,
    # half the bytes, bit-identical scores) — the round-4 on-rig A/B knob,
    # composable with EXP_UNIQUE. DTS_TPU_NO_FUSED=1 (batcher env) isolates
    # the native fused pack in the same sweeps.
    compact = os.environ.get("EXP_COMPACT", "0") == "1"
    if compact:
        from distributed_tf_serving_tpu.client import compact_payload

        payload = compact_payload(payload, config.vocab_size)
    pool = None
    if os.environ.get("EXP_UNIQUE", "0") == "1":
        pool = [
            make_payload(candidates=CANDIDATES, num_fields=NUM_FIELDS, seed=100 + i)
            for i in range(128)
        ]
        if compact:
            pool = [compact_payload(p, config.vocab_size) for p in pool]

    async def sweep(port: int):
        import dataclasses

        for conc in concs:
            # Size each point to ~10 s assuming ~500 qps upper bound.
            rpw = max(2, int((10.0 * 550) / conc)) if tpu else 3
            before = dataclasses.replace(batcher.stats)
            async with ShardedPredictClient(
                [f"127.0.0.1:{port}"], "DCN", channels_per_host=channels
            ) as client:
                cpu0, wall0 = time.process_time(), time.perf_counter()
                report = await run_closed_loop(
                    client, payload, concurrency=conc, requests_per_worker=rpw,
                    sort_scores=True, warmup_requests=5,
                    payload_pool=pool,
                    prepared=(pool is None)
                    and os.environ.get("EXP_PREPARED", "0") == "1",
                )
                cpu1, wall1 = time.process_time(), time.perf_counter()
            s = report.summary()
            stats = batcher.stats
            # Per-point counters (lifetime cumulative would blend the
            # previous concurrency points into every later one).
            d_req = stats.requests - before.requests
            d_batches = stats.batches - before.batches
            d_cand = stats.candidates - before.candidates
            d_padded = stats.padded_candidates - before.padded_candidates
            point = {
                "server": "aio" if use_aio else "threads",
                "compact": compact,
                "fused_off": os.environ.get("DTS_TPU_NO_FUSED") == "1",
                "concurrency": conc,
                "qps": round(s["qps"], 1),
                "p50_ms": round(s["p50_ms"], 1),
                "p99_ms": round(s["p99_ms"], 1),
                "requests": s["requests"],
                "wall_s": round(s["wall_s"], 1),
                "cpu_util": round((cpu1 - cpu0) / (wall1 - wall0), 3),
                "requests_per_batch": round(d_req / d_batches, 2) if d_batches else 0.0,
                "occupancy": round(d_cand / d_padded, 3) if d_padded else 0.0,
            }
            point["phases_us"] = {
                name: snap["mean_us"]
                for name, snap in request_trace.snapshot().items()
            }
            request_trace.reset()
            results.append(point)
            print(f"[exp] {json.dumps(point)}", file=sys.stderr)

    profile = os.environ.get("EXP_PROFILE", "0") == "1"
    if use_aio:
        from distributed_tf_serving_tpu.serving.server import create_server_async

        async def run_all():
            server, port = create_server_async(impl, "127.0.0.1:0")
            await server.start()
            try:
                if profile:
                    import cProfile
                    import pstats

                    prof = cProfile.Profile()
                    prof.enable()
                    await sweep(port)
                    prof.disable()
                    stats = pstats.Stats(prof, stream=sys.stderr)
                    stats.sort_stats("cumulative").print_stats(45)
                    stats.sort_stats("tottime").print_stats(45)
                else:
                    await sweep(port)
            finally:
                await server.stop(0)

        asyncio.run(run_all())
    else:
        server, port = create_server(impl, "127.0.0.1:0", max_workers=max(concs) + 8)
        server.start()
        asyncio.run(sweep(port))
        server.stop(0)
    batcher.stop()
    print(json.dumps(results))


if __name__ == "__main__":
    main()
