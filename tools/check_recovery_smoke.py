#!/usr/bin/env python
"""CI gate for the device-failure recovery smoke (ISSUE 11).

Usage: python tools/check_recovery_smoke.py SOAK_LINE_JSON

Reads the JSON line a SOAK_RECOVERY=1 soak printed (tools/ci_tier1.sh
tees it to a file) and asserts the acceptance criteria end to end:

- a deterministic WEDGE injected at pipeline depth 4 QUARANTINED the
  replica (watchdog_wedge_trips >= 1, quarantines >= 1) and the cycle
  completed back to `serving`;
- REINIT + REPLAY answered every captured in-flight/queued request:
  replayed_items >= 1, replay_budget_exhausted == 0, and the soak's
  whole gRPC error count is ZERO (clients rode their retry horizon
  through the quarantine window — non-poison requests never fail);
- MTTR (fault injection -> first post-recovery success) is recorded and
  bounded;
- the deliberately POISONED request was isolated by BISECTION: it alone
  failed with the distinct PoisonedInputError status while both clean
  companions coalesced into its batch replayed to success
  (poisoned_requests >= 1, bisections >= 1);
- the live surfaces answered: /recoveryz enabled, the
  /monitoring?section=recovery filter served exactly one block, and
  dts_tpu_recovery_* Prometheus series were present.

Exits 0 on success; prints every failure and exits 1.
"""

import json
import sys

MTTR_BOUND_S = 60.0


def main() -> None:
    if len(sys.argv) != 2:
        print("usage: check_recovery_smoke.py SOAK_LINE_JSON", file=sys.stderr)
        sys.exit(2)
    path = sys.argv[1]
    line = None
    try:
        with open(path) as f:
            for raw in reversed(f.read().strip().splitlines()):
                try:
                    parsed = json.loads(raw)
                except json.JSONDecodeError:
                    continue
                if isinstance(parsed, dict) and "recovery" in parsed:
                    line = parsed
                    break
    except OSError as e:
        print(
            f"check_recovery_smoke: FAIL: cannot read {path}: {e}",
            file=sys.stderr,
        )
        sys.exit(1)
    if line is None or not isinstance(line.get("recovery"), dict):
        print(
            f"check_recovery_smoke: FAIL: no JSON line with a `recovery` "
            f"block in {path}", file=sys.stderr,
        )
        sys.exit(1)

    rec = line["recovery"]
    counters = rec.get("counters") or {}
    failures = []
    if rec.get("error"):
        failures.append(f"probe error: {rec['error']}")
    if not rec.get("wedge_injected"):
        failures.append("the wedge was never injected")
    if counters.get("watchdog_wedge_trips", 0) < 1:
        failures.append(
            "the watchdog never escalated the wedge clock into a "
            f"quarantine (trips={counters.get('watchdog_wedge_trips')})"
        )
    if counters.get("quarantines", 0) < 1:
        failures.append(f"no quarantine ran ({counters.get('quarantines')})")
    if counters.get("cycles_completed", 0) < 1:
        failures.append("no recovery cycle ever completed")
    if counters.get("replayed_items", 0) < 1:
        failures.append(
            "nothing was replayed — the captured pipeline was lost"
        )
    if counters.get("replay_budget_exhausted", 0) != 0:
        failures.append(
            "replay budget exhausted for "
            f"{counters.get('replay_budget_exhausted')} item(s) — "
            "captured work FAILED instead of replaying"
        )
    mttr = rec.get("mttr_s")
    if mttr is None or mttr <= 0 or mttr > MTTR_BOUND_S:
        failures.append(f"MTTR missing or out of bounds: {mttr}s")
    if rec.get("final_state") != "serving":
        failures.append(
            f"replica did not settle back to serving "
            f"(state={rec.get('final_state')})"
        )
    # Zero failed non-poison requests: the poison is submitted DIRECTLY
    # to the batcher, so every client-visible gRPC error is a non-poison
    # failure by construction.
    if line.get("grpc_err", 0) != 0:
        failures.append(
            f"{line.get('grpc_err')} client-visible request failure(s) — "
            f"taxonomy: {line.get('error_taxonomy')}"
        )
    poison = rec.get("poison") or {}
    if not poison.get("poisoned"):
        failures.append(
            "the poisoned request did not fail with PoisonedInputError "
            f"(got: {poison.get('poison_error', '<nothing recorded>')})"
        )
    if poison.get("companions_ok", 0) != 2:
        failures.append(
            f"only {poison.get('companions_ok')}/2 clean companions "
            f"scored (errors: {poison.get('companion_errors')})"
        )
    if counters.get("poisoned_requests", 0) < 1:
        failures.append("controller recorded no poisoned request")
    if counters.get("bisections", 0) < 1:
        failures.append(
            "no bisection ran — the poison was never isolated out of a "
            "multi-request batch"
        )
    if not rec.get("recoveryz_enabled"):
        failures.append("/recoveryz did not answer enabled=true")
    if not rec.get("section_filter_ok"):
        failures.append("/monitoring?section=recovery filter failed")
    if rec.get("prom_recovery_series", 0) < 10:
        failures.append(
            f"only {rec.get('prom_recovery_series')} dts_tpu_recovery_* "
            "Prometheus series present (expected >= 10)"
        )

    if failures:
        print("check_recovery_smoke: FAIL", file=sys.stderr)
        for f_ in failures:
            print(f"  - {f_}", file=sys.stderr)
        sys.exit(1)
    print(
        "check_recovery_smoke: OK "
        f"(mttr={mttr}s quarantines={counters.get('quarantines')} "
        f"replayed={counters.get('replayed_items')} "
        f"bisections={counters.get('bisections')} "
        f"poisoned={counters.get('poisoned_requests')} "
        f"grpc_err=0)"
    )


if __name__ == "__main__":
    main()
