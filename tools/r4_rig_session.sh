#!/bin/bash
# Round-4 on-rig measurement session — run the moment the relay recovers.
# Produces: a full bench artifact (tagged dev run + refreshed committed
# last-good fallback) and the A/B sweeps that attribute this round's host
# work (compact wire, fused pack) on real hardware.
set -u
cd "$(dirname "$0")/.."
TS=$(date -u +%H%M%S)

echo "[session] 1/4 full bench (headline-first; salvage-protected)"
python bench.py 2>"artifacts/bench_r4_${TS}.log" | tail -1 > /tmp/r4_line.json
if python -c "import json,sys; l=json.load(open('/tmp/r4_line.json')); sys.exit(0 if l.get('value') and not l.get('salvaged') else 1)"; then
  python - <<EOF
import json
line = json.load(open('/tmp/r4_line.json'))
line['_dev_run'] = 'r4_${TS}_full'
with open('artifacts/bench_r4_dev_runs.jsonl', 'a') as f:
    f.write(json.dumps(line) + '\n')
print('recorded r4_${TS}_full:', line['value'], 'qps | compact:',
      line.get('qps_compact_wire'), '| unique:', line.get('qps_unique'))
EOF
  git add artifacts/last_good_bench.json artifacts/bench_r4_dev_runs.jsonl
  git commit -q -m "Record on-rig round-4 bench run (refreshes wedge-fallback measurement)

No-Verification-Needed: measurement artifact only" || true
else
  echo "[session] bench did not produce a live measurement; see artifacts/bench_r4_${TS}.log"
fi

echo "[session] 2/4 compact A/B sweep (adjacent points, same weather)"
EXP_AIO=1 EXP_PREPARED=1 EXP_CONCS=96,176 EXP_CHANNELS=3 \
  python tools/exp_load.py > "artifacts/exp_r4_${TS}_wide.json" 2>/dev/null
EXP_AIO=1 EXP_PREPARED=1 EXP_CONCS=96,176 EXP_CHANNELS=3 EXP_COMPACT=1 \
  python tools/exp_load.py > "artifacts/exp_r4_${TS}_compact.json" 2>/dev/null

echo "[session] 3/4 fused on/off A/B (wide wire)"
EXP_AIO=1 EXP_PREPARED=1 EXP_CONCS=96 EXP_CHANNELS=3 DTS_TPU_NO_FUSED=1 \
  python tools/exp_load.py > "artifacts/exp_r4_${TS}_nofused.json" 2>/dev/null

echo "[session] 4/4 unique-path with link attribution"
EXP_AIO=1 EXP_CONCS=32 EXP_CHANNELS=3 EXP_UNIQUE=1 \
  python tools/exp_load.py > "artifacts/exp_r4_${TS}_unique.json" 2>/dev/null

python - <<EOF
import glob, json
for p in sorted(glob.glob('artifacts/exp_r4_${TS}_*.json')):
    try:
        pts = json.load(open(p))
        print(p.split('/')[-1], [
            {k: pt[k] for k in ('concurrency', 'qps', 'p50_ms', 'compact',
                                'fused_off', 'requests_per_batch')}
            for pt in pts
        ])
    except Exception as e:
        print(p, 'unreadable:', e)
EOF
echo "[session] done — review, tune operating point, re-run bench.py if warranted"
