#!/usr/bin/env python
"""CI gate for the data-integrity smoke (ISSUE 20).

Usage: python tools/check_integrity_smoke.py SOAK_LINE_JSON

Reads the JSON line a SOAK_INTEGRITY=1 soak printed (tools/ci_tier1.sh
tees it to a file) and asserts the acceptance criteria end to end:

- the VERIFYING CLIENT received zero corrupted scores: every injected
  response-side wire flip was caught by the score-CRC verify before
  merge (corrupt_responses >= 1 proves the detector fired), no NaN row
  was ever merged into a ranking (nan_scores_merged == 0), and every
  client-visible error in the taxonomy is an integrity
  rejection/retry — never silently-wrong data;
- each DETECTION LAYER fired on its own fault site: the server rejected
  request-side wire corruption (wire.inputs_rejected >= 1) while clean
  requests kept verifying (inputs_verified >= 1, responses_stamped
  >= 1); the readback screen caught injected NaN rows (screen.trips
  >= 1); shadow verification caught injected bitflips bit-identically
  (shadow.batches >= 1, mismatches >= 1);
- detections ESCALATED into the recovery plane (escalations >= 1,
  quarantines >= 1, cycles completed) and detection->next-success MTTR
  is recorded and bounded;
- CLEAN traffic is bit-identical with the plane armed (forced shadow
  audit included), both before chaos and after it cleared — the plane
  never changes answers;
- the live surfaces answered: /integrityz enabled, POST
  /integrityz/audit accepted, the /monitoring?section=integrity filter
  served exactly one block, and dts_tpu_integrity_* Prometheus series
  were present.

Exits 0 on success; prints every failure and exits 1.
"""

import json
import sys

MTTR_BOUND_S = 60.0

# Every client-visible error under integrity chaos must be an integrity
# rejection or the retry/unavailability it causes. Anything else is an
# unexplained failure the gate refuses.
ALLOWED_ERROR_MARKERS = (
    "corrupt",        # corrupt-wire rejects + client-side corrupt response
    "UNAVAILABLE",    # screen-failed rows / quarantine window retries
    "unavailable",
    "readback",       # IntegrityScreenError detail
    "screen",
    "shard",          # failover exhaustion wrapper
)


def main() -> None:
    if len(sys.argv) != 2:
        print(
            "usage: check_integrity_smoke.py SOAK_LINE_JSON",
            file=sys.stderr,
        )
        sys.exit(2)
    path = sys.argv[1]
    line = None
    try:
        with open(path) as f:
            for raw in reversed(f.read().strip().splitlines()):
                try:
                    parsed = json.loads(raw)
                except json.JSONDecodeError:
                    continue
                if isinstance(parsed, dict) and "integrity" in parsed:
                    line = parsed
                    break
    except OSError as e:
        print(
            f"check_integrity_smoke: FAIL: cannot read {path}: {e}",
            file=sys.stderr,
        )
        sys.exit(1)
    if line is None or not isinstance(line.get("integrity"), dict):
        print(
            f"check_integrity_smoke: FAIL: no JSON line with an "
            f"`integrity` block in {path}", file=sys.stderr,
        )
        sys.exit(1)

    integ = line["integrity"]
    wire = integ.get("wire") or {}
    screen = integ.get("screen") or {}
    shadow = integ.get("shadow") or {}
    client = integ.get("client") or {}
    rc = integ.get("recovery_counters") or {}
    failures = []
    if integ.get("error"):
        failures.append(f"probe error: {integ['error']}")

    # --- zero corrupted scores delivered -----------------------------
    if client.get("nan_scores_merged", 0) != 0:
        failures.append(
            f"client merged {client.get('nan_scores_merged')} NaN "
            "score(s) into a ranking — corrupt data was DELIVERED"
        )
    if client.get("corrupt_responses", 0) < 1:
        failures.append(
            "client verify never caught a response-side wire flip "
            "(corrupt_responses=0) — the detector did not fire"
        )
    taxonomy = line.get("error_taxonomy") or {}
    unexplained = {
        k: v for k, v in taxonomy.items()
        if not any(m in k for m in ALLOWED_ERROR_MARKERS)
    }
    if unexplained:
        failures.append(
            f"unexplained client-visible errors (not integrity "
            f"rejections/retries): {unexplained}"
        )

    # --- layer 1: wire checksums -------------------------------------
    if wire.get("inputs_rejected", 0) < 1:
        failures.append(
            "server never rejected a request-side wire flip "
            f"(inputs_rejected={wire.get('inputs_rejected')})"
        )
    if wire.get("inputs_verified", 0) < 1:
        failures.append(
            "no clean request ever verified — the wire layer was idle"
        )
    if wire.get("responses_stamped", 0) < 1:
        failures.append("no response score CRC was ever stamped")

    # --- layer 2: readback screen ------------------------------------
    if screen.get("trips", 0) < 1:
        failures.append(
            "the readback screen never caught an injected NaN row "
            f"(trips={screen.get('trips')})"
        )

    # --- layer 3: shadow verification --------------------------------
    if shadow.get("batches", 0) < 1:
        failures.append("no batch ever shadow-verified")
    if shadow.get("mismatches", 0) < 1:
        failures.append(
            "shadow verification never caught an injected bitflip "
            f"(mismatches={shadow.get('mismatches')})"
        )
    if shadow.get("audits_run", 0) < 1:
        failures.append("no on-demand audit ever ran")

    # --- escalation into recovery + MTTR -----------------------------
    if integ.get("escalations", 0) < 1:
        failures.append("no detection ever escalated")
    if rc.get("quarantines", 0) < 1:
        failures.append(
            "escalation never reached the recovery plane "
            f"(quarantines={rc.get('quarantines')})"
        )
    if rc.get("cycles_completed", 0) < 1:
        failures.append("no recovery cycle ever completed")
    mttr = integ.get("detect_to_success_s")
    if mttr is None or mttr < 0 or mttr > MTTR_BOUND_S:
        failures.append(
            f"detection->success MTTR missing or out of bounds: {mttr}s"
        )

    # --- clean-traffic bit-identity ----------------------------------
    if integ.get("clean_bit_identical") is not True:
        failures.append(
            "pre-chaos clean traffic was NOT bit-identical plane-on vs "
            "plane-off"
        )
    if integ.get("clean_bit_identical_post") is not True:
        failures.append(
            "post-chaos clean traffic was NOT bit-identical to the "
            f"pre-chaos reference "
            f"({integ.get('closing_probe_error', 'mismatch')})"
        )

    # --- live surfaces -----------------------------------------------
    if not integ.get("integrityz_enabled"):
        failures.append("/integrityz did not answer enabled=true")
    if not integ.get("audit_post_ok"):
        failures.append("POST /integrityz/audit did not accept")
    if not integ.get("section_filter_ok"):
        failures.append("/monitoring?section=integrity filter failed")
    if integ.get("prom_integrity_series", 0) < 10:
        failures.append(
            f"only {integ.get('prom_integrity_series')} "
            "dts_tpu_integrity_* Prometheus series present "
            "(expected >= 10)"
        )

    if failures:
        print("check_integrity_smoke: FAIL", file=sys.stderr)
        for f_ in failures:
            print(f"  - {f_}", file=sys.stderr)
        sys.exit(1)
    print(
        "check_integrity_smoke: OK "
        f"(wire_rejected={wire.get('inputs_rejected')} "
        f"corrupt_responses={client.get('corrupt_responses')} "
        f"screen_trips={screen.get('trips')} "
        f"shadow_mismatches={shadow.get('mismatches')} "
        f"escalations={integ.get('escalations')} "
        f"mttr={mttr}s nan_merged=0 bit_identical=both)"
    )


if __name__ == "__main__":
    main()
