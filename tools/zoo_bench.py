#!/usr/bin/env python
"""Per-family device benchmark at the reference ecosystem's config points.

BASELINE.json lists five benchmark configs the framework must cover
(Wide&Deep 128-candidate, DeepFM 512, DCN-v2 1k, two-tower 10k retrieval,
DLRM 4k embedding-heavy). The headline bench (bench.py) drives the full
gRPC stack on the flagship DCN-v2 only; this tool measures the pure device
step for EVERY zoo family at its own workload point — the per-family
roofline the serving layer sits on. Timing method shared with bench.py:
steps chained inside one jitted fori_loop so host dispatch and the relay
tunnel's rtt jitter cannot contaminate the number (see
bench.device_loop_step_s, calibrated at 78% MFU on a bare matmul chain).

Run on the TPU (or JAX_PLATFORMS=cpu for a smoke):
    python tools/zoo_bench.py [--out ZOO_BENCH.json]
Prints one JSON line per family plus a `summary` line.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--out", help="also write the results to this JSON file")
    parser.add_argument("--iters", type=int, default=0,
                        help="override estimate iters (0 = auto per platform)")
    args = parser.parse_args(argv)

    import jax
    import numpy as np

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")

    from bench import device_loop_step_s, flops_per_example, peak_flops_for

    from distributed_tf_serving_tpu.models import ModelConfig, build_model
    from distributed_tf_serving_tpu.serving.batcher import fold_ids_host

    device = str(jax.devices()[0])
    tpu = jax.devices()[0].platform != "cpu"
    est, tgt = (args.iters or (100 if tpu else 4)), (0.12 if tpu else 0.01)

    # (family, candidates/batch, config) — the BASELINE.json config points.
    POINTS = [
        ("wide_deep", 128, ModelConfig(name="WD", num_fields=43)),
        ("deepfm", 512, ModelConfig(name="DeepFM", num_fields=39)),  # Criteo: 39 cat fields
        ("dcn_v2", 1024, ModelConfig(name="DCN", num_fields=43)),
        ("two_tower", 10240, ModelConfig(name="TT", num_fields=43, num_user_fields=8)),
        ("dlrm", 4096, ModelConfig(name="DLRM", num_fields=26, num_dense_features=13)),
    ]
    if not tpu:  # smoke: shrink the tables, keep the shapes' structure
        import dataclasses as dc

        POINTS = [
            (k, min(n, 512), dc.replace(
                c, vocab_size=1 << 14, embed_dim=4,
                # DLRM requires bottom_mlp_dims[-1] == embed_dim
                bottom_mlp_dims=(16, 4) if k == "dlrm" else c.bottom_mlp_dims,
            ))
            for k, n, c in POINTS
        ]

    results = []
    rng = np.random.RandomState(0)
    for kind, n, config in POINTS:
        t0 = time.perf_counter()
        model = build_model(kind, config)
        params = jax.jit(model.init)(jax.random.PRNGKey(0))
        jax.block_until_ready(params)
        batch = {
            "feat_ids": fold_ids_host(
                rng.randint(0, 1 << 40, size=(n, config.num_fields)), config.vocab_size
            ),
            "feat_wts": rng.rand(n, config.num_fields).astype(np.float32),
        }
        if kind == "dlrm":
            batch["dense_features"] = rng.rand(n, config.num_dense_features).astype(np.float32)
        dev = {k: jax.device_put(v) for k, v in batch.items()}
        jax.block_until_ready(dev)
        apply = jax.jit(model.apply)

        import jax.numpy as jnp

        def step(b, apply=apply, params=params):
            out = apply(params, b)
            eps = jnp.min(out["prediction_node"]) * 1e-30
            return {
                k: (v + eps.astype(v.dtype) if k == "feat_wts" else v)
                for k, v in b.items()
            }

        step_s = device_loop_step_s(step, dev, est, tgt)
        line = {
            "family": kind,
            "batch": n,
            # None = degenerate reading (relay flap spanned the min-of-2
            # walls); recorded as null rather than crashing the sweep.
            "device_step_us": None if step_s is None else round(step_s * 1e6, 1),
            "examples_per_s": None if step_s is None else round(n / step_s, 0),
            "qps_1k_equiv": None if step_s is None else round(n / 1000 / step_s, 1),
            "setup_s": round(time.perf_counter() - t0, 1),
        }
        peak = peak_flops_for(device)
        if peak and kind == "dcn_v2" and step_s:
            line["mfu"] = round(flops_per_example(config) * n / step_s / peak, 4)
        results.append(line)
        print(json.dumps(line), flush=True)

    summary = {
        "summary": True,
        "device": device,
        "families": {r["family"]: r["device_step_us"] for r in results},
    }
    print(json.dumps(summary), flush=True)
    if args.out:
        with open(args.out, "w") as f:
            json.dump({"device": device, "results": results}, f, indent=1)


if __name__ == "__main__":
    main()
