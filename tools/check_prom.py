#!/usr/bin/env python
"""Prometheus exposition lint for the aggregated monitoring endpoint
(ISSUE 7 satellite).

`GET /monitoring/prometheus/metrics` is now assembled from five planes
(request metrics, batcher, cache, overload, utilization) plus the quality
plane — and nothing guarded against one plane re-declaring another's
family name, emitting a duplicate series, or skipping the HELP/TYPE
header. This lint holds the text-format 0.0.4 contract:

- every non-comment line parses as `name{labels} value [timestamp]` with
  a valid metric name and cleanly escaped label values (an unescaped
  quote or raw newline breaks the line grammar and fails here);
- every sample's FAMILY carries a `# HELP` and a `# TYPE` line declared
  BEFORE its first sample (`_bucket`/`_sum`/`_count` suffixes resolve to
  their declared histogram/summary family);
- no family is declared twice — the duplicate-family-name failure mode
  of multi-plane assembly;
- a family's samples form ONE contiguous block (the format's grouping
  rule; interleaved families silently break some parsers);
- no two samples share (name, label set) — a duplicate series would be
  last-write-wins at the scraper, hiding one plane's value;
- every value parses as a float (+Inf/-Inf/NaN allowed).

Usage: `python tools/check_prom.py FILE` (or `-` for stdin). Importable:
`lint_text(text) -> list[str]` returns every violation. Exit 0 = clean.
"""

from __future__ import annotations

import re
import sys

_NAME = r"[a-zA-Z_:][a-zA-Z0-9_:]*"
_HELP_RE = re.compile(rf"^# HELP ({_NAME})(?: (.*))?$")
_TYPE_RE = re.compile(rf"^# TYPE ({_NAME}) (counter|gauge|histogram|summary|untyped)$")
_SAMPLE_RE = re.compile(rf"^({_NAME})(?:\{{(.*)\}})?\s+(\S+)(?:\s+(-?\d+))?$")
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\\n]|\\["\\n])*)"')

# Suffixes that address a declared histogram/summary family.
_SUFFIXES = {
    "_bucket": ("histogram",),
    "_sum": ("histogram", "summary"),
    "_count": ("histogram", "summary"),
}


def _parse_labels(raw: str, line_no: int, errors: list[str]) -> tuple | None:
    """Canonical (sorted) label tuple, or None on malformed labels."""
    pos = 0
    out = []
    while pos < len(raw):
        m = _LABEL_RE.match(raw, pos)
        if m is None:
            errors.append(
                f"line {line_no}: malformed label pair at {raw[pos:pos + 40]!r} "
                "(unescaped quote/backslash, or bad label name?)"
            )
            return None
        out.append((m.group(1), m.group(2)))
        pos = m.end()
        if pos < len(raw):
            if raw[pos] != ",":
                errors.append(
                    f"line {line_no}: expected ',' between labels, got "
                    f"{raw[pos:pos + 10]!r}"
                )
                return None
            pos += 1
    return tuple(sorted(out))


def _family_of(name: str, types: dict[str, str]) -> str | None:
    """The declared family a sample name belongs to, else None."""
    if name in types:
        return name
    for suffix, kinds in _SUFFIXES.items():
        if name.endswith(suffix):
            base = name[: -len(suffix)]
            if types.get(base) in kinds:
                return base
    return None


def lint_text(text: str) -> list[str]:
    errors: list[str] = []
    helps: dict[str, int] = {}
    types: dict[str, str] = {}
    sampled: set[str] = set()   # families that have emitted samples
    closed: set[str] = set()    # families whose sample block has ended
    last_family: str | None = None
    series: set[tuple] = set()
    for line_no, line in enumerate(text.splitlines(), start=1):
        if not line.strip():
            continue
        if line.startswith("#"):
            m = _HELP_RE.match(line)
            if m is not None:
                name = m.group(1)
                if name in helps:
                    errors.append(
                        f"line {line_no}: duplicate # HELP for family {name!r} "
                        f"(first at line {helps[name]})"
                    )
                helps[name] = line_no
                continue
            m = _TYPE_RE.match(line)
            if m is not None:
                name = m.group(1)
                if name in types:
                    errors.append(
                        f"line {line_no}: family {name!r} declared twice "
                        "(duplicate # TYPE — two planes claiming one name?)"
                    )
                if name in sampled:
                    errors.append(
                        f"line {line_no}: # TYPE for {name!r} appears AFTER "
                        "its samples"
                    )
                types[name] = m.group(2)
                continue
            if line.startswith("# HELP") or line.startswith("# TYPE"):
                errors.append(f"line {line_no}: malformed metadata line: {line!r}")
            continue  # other comments are legal and ignored
        m = _SAMPLE_RE.match(line)
        if m is None:
            errors.append(f"line {line_no}: unparseable sample line: {line!r}")
            continue
        name, raw_labels, value = m.group(1), m.group(2), m.group(3)
        try:
            float(value)  # +Inf/-Inf/NaN parse fine
        except ValueError:
            errors.append(
                f"line {line_no}: value {value!r} of {name!r} is not a number "
                "(label text leaking into the value position?)"
            )
        labels = _parse_labels(raw_labels, line_no, errors) if raw_labels else ()
        if labels is None:
            continue
        family = _family_of(name, types)
        if family is None:
            errors.append(
                f"line {line_no}: sample {name!r} has no preceding # TYPE "
                "for its family"
            )
            family = name  # keep grouping/duplicate checks meaningful
        if family not in helps:
            errors.append(
                f"line {line_no}: family {family!r} has no # HELP line"
            )
            helps[family] = line_no  # report once per family
        if family != last_family:
            if last_family is not None:
                closed.add(last_family)
            if family in closed:
                errors.append(
                    f"line {line_no}: family {family!r} samples are not "
                    "contiguous (block already closed earlier)"
                )
            last_family = family
        sampled.add(family)
        key = (name, labels)
        if key in series:
            errors.append(
                f"line {line_no}: duplicate series {name}{{{raw_labels or ''}}} "
                "(same name + label set emitted twice)"
            )
        series.add(key)
    return errors


def main() -> None:
    if len(sys.argv) != 2:
        print("usage: check_prom.py FILE|-", file=sys.stderr)
        sys.exit(2)
    path = sys.argv[1]
    try:
        text = sys.stdin.read() if path == "-" else open(path).read()
    except OSError as e:
        print(f"check_prom: FAIL: cannot read {path}: {e}", file=sys.stderr)
        sys.exit(1)
    errors = lint_text(text)
    if errors:
        for err in errors:
            print(f"check_prom: FAIL: {err}", file=sys.stderr)
        sys.exit(1)
    families = sum(1 for ln in text.splitlines() if ln.startswith("# TYPE"))
    samples = sum(
        1 for ln in text.splitlines() if ln.strip() and not ln.startswith("#")
    )
    print(f"check_prom: OK: {families} families, {samples} samples")


if __name__ == "__main__":
    main()
