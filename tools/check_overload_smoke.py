#!/usr/bin/env python
"""Gate for the tier-1 overload smoke (tools/ci_tier1.sh
TIER1_OVERLOAD_SMOKE=1).

Reads the SOAK_OVERLOAD=1 soak's JSON line and asserts the overload
plane's acceptance conditions (ISSUE 5):

- the adaptive controller actually SHED under the ~3x load (nonzero
  sheds, with RESOURCE_EXHAUSTED visible to clients as pushback);
- brownout stale-serve actually ANSWERED hot-key traffic from the score
  cache past its TTL (nonzero brownout serves);
- the shedding backend was NEVER ejected by its own client (zero
  scoreboard ejections — pushback registers as busy, not dead), and at
  least one client backoff honored a server retry-after-ms hint;
- goodput (in-deadline successes/s) stayed above a floor — the plane
  degrades, it does not collapse.

Exits nonzero with a reason otherwise, so CI fails with evidence instead
of a silent green. The floor defaults low enough for a shared CI core and
can be raised via OVERLOAD_GOODPUT_FLOOR.
"""

import json
import os
import sys


def main() -> int:
    path = sys.argv[1] if len(sys.argv) > 1 else "/tmp/tier1_overload_soak.json"
    floor = float(os.environ.get("OVERLOAD_GOODPUT_FLOOR", "10.0"))
    lines = []
    with open(path) as f:
        for raw in f:
            raw = raw.strip()
            if raw.startswith("{"):
                try:
                    lines.append(json.loads(raw))
                except json.JSONDecodeError:
                    continue
    if not lines:
        print(f"overload smoke: no JSON line in {path}", file=sys.stderr)
        return 1
    line = lines[-1]
    ov = line.get("overload") or {}
    ctrl = ov.get("controller") or {}
    problems = []
    if ctrl.get("sheds", 0) <= 0:
        problems.append(f"controller never shed (controller: {ctrl})")
    if ctrl.get("brownout_serves", 0) <= 0:
        problems.append(
            "zero brownout stale-serves (pressure state history: "
            f"state={ctrl.get('state')} changes={ctrl.get('state_changes')})"
        )
    if ov.get("client_pushbacks", 0) <= 0:
        problems.append(
            "clients saw no RESOURCE_EXHAUSTED pushback — sheds never "
            "reached a client, or the pushback accounting is broken"
        )
    if ov.get("client_retry_after_honored", 0) <= 0:
        problems.append(
            "no client backoff honored a retry-after-ms hint — refusals "
            "are missing the trailing-metadata hint, or the client ignores it"
        )
    if ov.get("client_ejections", 0) != 0:
        problems.append(
            f"{ov.get('client_ejections')} scoreboard ejection(s) of the "
            "overloaded backend — pushback must register as busy, never "
            "consume the ejection budget (the cascade this plane exists "
            "to prevent)"
        )
    if ov.get("goodput_qps", 0.0) < floor:
        problems.append(
            f"goodput {ov.get('goodput_qps')} qps below floor {floor} — "
            "the plane collapsed instead of degrading"
        )
    if line.get("grpc_err", 0) and not line.get("grpc_ok", 0):
        problems.append("every gRPC request errored during the overload soak")
    if problems:
        for p in problems:
            print(f"overload smoke FAILED: {p}", file=sys.stderr)
        return 1
    print(
        "overload smoke ok: goodput={} qps sheds={} (by_lane={}) doomed={} "
        "brownout_serves={} pushbacks={} retry_after_honored={} "
        "ejections=0 queue_wait_p99_ms={}".format(
            ov.get("goodput_qps"), ctrl.get("sheds"),
            ctrl.get("sheds_by_lane"), ctrl.get("doomed_refusals"),
            ctrl.get("brownout_serves"), ov.get("client_pushbacks"),
            ov.get("client_retry_after_honored"),
            ctrl.get("queue_wait_p99_ms"),
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
