#!/usr/bin/env bash
# Tier-1 verification — the EXACT command ROADMAP.md pins, wrapped so CI
# (.github/workflows/tier1.yml) and a local shell run identically:
#
#     tools/ci_tier1.sh
#
# Runs the non-slow test suite on the CPU platform, tees the log, prints a
# DOTS_PASSED count (the driver's pass-counting convention), and exits with
# pytest's status.
# With TIER1_TRACE_SMOKE=1 (CI sets it), a passing test run is followed by
# an observability smoke: a short traced chaos soak (SOAK_CHAOS=1 +
# SOAK_TRACE_OUT) whose /tracez-served Chrome-trace artifact must be
# non-empty and schema-valid (tools/check_trace.py). The artifact lands at
# $TIER1_TRACE_ARTIFACT (default /tmp/tier1_soak_trace.json) so CI can
# upload it for debugging when the step fails.
set -o pipefail
cd "$(dirname "$0")/.."

LOG="${TIER1_LOG:-/tmp/_t1.log}"
rm -f "$LOG"
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly \
    2>&1 | tee "$LOG"
rc=${PIPESTATUS[0]}
echo "DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' "$LOG" | tr -cd . | wc -c)"

if [ "$rc" -eq 0 ] && [ "${TIER1_TRACE_SMOKE:-0}" = "1" ]; then
    ARTIFACT="${TIER1_TRACE_ARTIFACT:-/tmp/tier1_soak_trace.json}"
    echo "tier1: trace smoke (SOAK_CHAOS=1 SOAK_UTIL=1, artifact $ARTIFACT)"
    # SOAK_UTIL=1 rides along so the exported Chrome trace carries the
    # per-device occupancy counter track, which check_trace.py now
    # schema-gates (monotonic counter ts, per-device track names).
    timeout -k 10 300 env JAX_PLATFORMS=cpu \
        SOAK_SECONDS="${TIER1_SMOKE_SECONDS:-8}" SOAK_CHAOS=1 SOAK_UTIL=1 \
        SOAK_GRPC_WORKERS=2 SOAK_REST_WORKERS=1 SOAK_CANDIDATES=64 \
        SOAK_TRACE_OUT="$ARTIFACT" SOAK_TRACE_SAMPLE=0.5 \
        python tools/soak.py || rc=1
    python tools/check_trace.py "$ARTIFACT" --min-events 10 \
        --require-counter-track || rc=1
fi

# Cache smoke (TIER1_CACHE_SMOKE=1): a short SOAK_CACHE=1 skewed soak must
# report a NONZERO hit rate and bit-identical scores with the cache on vs
# off (the soak's pre-flight miss/hit probe) — the cache plane's tier-1
# acceptance gate.
if [ "$rc" -eq 0 ] && [ "${TIER1_CACHE_SMOKE:-0}" = "1" ]; then
    CACHE_LINE="${TIER1_CACHE_LINE:-/tmp/tier1_cache_soak.json}"
    echo "tier1: cache smoke (SOAK_CACHE=1, line $CACHE_LINE)"
    timeout -k 10 300 env JAX_PLATFORMS=cpu \
        SOAK_SECONDS="${TIER1_SMOKE_SECONDS:-8}" SOAK_CACHE=1 \
        SOAK_GRPC_WORKERS=4 SOAK_REST_WORKERS=1 SOAK_CANDIDATES=64 \
        python tools/soak.py | tee "$CACHE_LINE" || rc=1
    python tools/check_cache_smoke.py "$CACHE_LINE" || rc=1
fi

# Row-cache smoke (TIER1_ROWCACHE_SMOKE=1): a short SOAK_ROWCACHE=1 zipfian
# soak — the row-granular cache (ISSUE 14) next to the request cache — must
# report a NONZERO per-row hit rate, rows_executed < rows_requested (only
# cold rows reached the device), bit-identical scores vs the disarmed
# plane, and zero gRPC errors (tools/check_rowcache_smoke.py).
if [ "$rc" -eq 0 ] && [ "${TIER1_ROWCACHE_SMOKE:-0}" = "1" ]; then
    ROWCACHE_LINE="${TIER1_ROWCACHE_LINE:-/tmp/tier1_rowcache_soak.json}"
    echo "tier1: row-cache smoke (SOAK_ROWCACHE=1, line $ROWCACHE_LINE)"
    timeout -k 10 300 env JAX_PLATFORMS=cpu \
        SOAK_SECONDS="${TIER1_SMOKE_SECONDS:-8}" SOAK_ROWCACHE=1 \
        SOAK_GRPC_WORKERS=4 SOAK_REST_WORKERS=1 SOAK_CANDIDATES=64 \
        python tools/soak.py | tee "$ROWCACHE_LINE" || rc=1
    python tools/check_rowcache_smoke.py "$ROWCACHE_LINE" || rc=1
fi

# Overload smoke (TIER1_OVERLOAD_SMOKE=1): a short SOAK_OVERLOAD=1 soak —
# ~3x sustainable load with a mid-run burst against the adaptive admission
# plane — must show nonzero sheds, nonzero brownout stale-serves, client
# pushback with a honored retry-after hint, ZERO scoreboard ejections of
# the overloaded backend, and goodput above a floor
# (tools/check_overload_smoke.py). Runs the soak's own overload defaults
# (24+12 burst workers, 1000-candidate requests): the mode's knobs were
# tuned as a set, and shrinking them piecemeal starves the shed path.
if [ "$rc" -eq 0 ] && [ "${TIER1_OVERLOAD_SMOKE:-0}" = "1" ]; then
    OVERLOAD_LINE="${TIER1_OVERLOAD_LINE:-/tmp/tier1_overload_soak.json}"
    echo "tier1: overload smoke (SOAK_OVERLOAD=1, line $OVERLOAD_LINE)"
    timeout -k 10 300 env JAX_PLATFORMS=cpu \
        SOAK_SECONDS="${TIER1_OVERLOAD_SECONDS:-12}" SOAK_OVERLOAD=1 \
        python tools/soak.py | tee "$OVERLOAD_LINE" || rc=1
    python tools/check_overload_smoke.py "$OVERLOAD_LINE" || rc=1
fi

# Utilization smoke (TIER1_UTIL_SMOKE=1): a short SOAK_UTIL=1 soak with
# the occupancy ledger armed must show nonzero device-busy intervals, a
# gap waterfall whose components sum to wall within 2%, a sane live
# achieved_fraction_of_device_limit, the /utilz route answering, and
# dts_tpu_utilization_* Prometheus series present
# (tools/check_util_smoke.py) — the utilization plane's tier-1 gate.
if [ "$rc" -eq 0 ] && [ "${TIER1_UTIL_SMOKE:-0}" = "1" ]; then
    UTIL_LINE="${TIER1_UTIL_LINE:-/tmp/tier1_util_soak.json}"
    echo "tier1: utilization smoke (SOAK_UTIL=1, line $UTIL_LINE)"
    timeout -k 10 300 env JAX_PLATFORMS=cpu \
        SOAK_SECONDS="${TIER1_SMOKE_SECONDS:-8}" SOAK_UTIL=1 \
        SOAK_GRPC_WORKERS=4 SOAK_REST_WORKERS=1 SOAK_CANDIDATES=64 \
        python tools/soak.py | tee "$UTIL_LINE" || rc=1
    python tools/check_util_smoke.py "$UTIL_LINE" || rc=1
fi

# Quality smoke (TIER1_QUALITY_SMOKE=1): a SOAK_QUALITY=1 soak — model
# trained on the synthetic teacher, labels reported to the live /labelz,
# reference pinned mid-run, shifted segment after it — must sketch scores
# with warmup excluded, join labels with the live windowed AUC within
# 0.05 of the soak's own offline exact AUC (and above coin-flip), drive
# PSI over threshold with >=1 quality.drift exemplar visible in /tracez,
# and serve dts_tpu_quality_* series whose captured exposition text
# passes tools/check_prom.py (tools/check_quality_smoke.py runs both).
# Slightly longer than the other smokes: the run needs a steady phase, a
# pin, and a drifted window inside one soak.
if [ "$rc" -eq 0 ] && [ "${TIER1_QUALITY_SMOKE:-0}" = "1" ]; then
    QUALITY_LINE="${TIER1_QUALITY_LINE:-/tmp/tier1_quality_soak.json}"
    QUALITY_PROM="${TIER1_QUALITY_PROM:-/tmp/tier1_quality_prom.txt}"
    echo "tier1: quality smoke (SOAK_QUALITY=1, line $QUALITY_LINE)"
    timeout -k 10 300 env JAX_PLATFORMS=cpu \
        SOAK_SECONDS="${TIER1_QUALITY_SECONDS:-12}" SOAK_QUALITY=1 \
        SOAK_QUALITY_PROM_OUT="$QUALITY_PROM" \
        python tools/soak.py | tee "$QUALITY_LINE" || rc=1
    python tools/check_quality_smoke.py "$QUALITY_LINE" || rc=1
fi

# Streaming smoke (TIER1_STREAMING_SMOKE=1): the ISSUE-9 correctness
# gate — streamed (PredictStream, chunked sub-batches) and unary Predict
# must return BIT-IDENTICAL scores over both TCP and a Unix-domain
# socket with the fault injector delaying readbacks (chunks genuinely
# complete out of order), the k-deep pipeline (depth 4, window 4,
# buffer ring) must overlap batches, and a mid-stream deadline must
# abort DEADLINE_EXCEEDED (tools/check_streaming_smoke.py).
if [ "$rc" -eq 0 ] && [ "${TIER1_STREAMING_SMOKE:-0}" = "1" ]; then
    STREAM_LINE="${TIER1_STREAMING_LINE:-/tmp/tier1_streaming_smoke.json}"
    echo "tier1: streaming smoke (line $STREAM_LINE)"
    timeout -k 10 300 env JAX_PLATFORMS=cpu \
        python tools/check_streaming_smoke.py | tee "$STREAM_LINE" || rc=1
fi

# Recovery smoke (TIER1_RECOVERY_SMOKE=1): a SOAK_RECOVERY=1 soak — the
# device-failure recovery plane under live traffic on a depth-4
# pipeline: an injected wedge at the device stage must quarantine the
# replica (watchdog escalation), reinit + replay the captured pipeline
# with ZERO client-visible non-poison failures and a bounded MTTR, and
# a content-keyed poisoned input coalesced with clean companions must
# fail ALONE via bisection (PoisonedInputError) while the companions
# replay to success (tools/check_recovery_smoke.py).
if [ "$rc" -eq 0 ] && [ "${TIER1_RECOVERY_SMOKE:-0}" = "1" ]; then
    RECOVERY_LINE="${TIER1_RECOVERY_LINE:-/tmp/tier1_recovery_soak.json}"
    echo "tier1: recovery smoke (SOAK_RECOVERY=1, line $RECOVERY_LINE)"
    timeout -k 10 300 env JAX_PLATFORMS=cpu \
        SOAK_SECONDS="${TIER1_RECOVERY_SECONDS:-14}" SOAK_RECOVERY=1 \
        python tools/soak.py | tee "$RECOVERY_LINE" || rc=1
    python tools/check_recovery_smoke.py "$RECOVERY_LINE" || rc=1
fi

# Kernel smoke (TIER1_KERNEL_SMOKE=1): the ISSUE-12 safety gate — the
# autotune harness runs end to end on CPU in MEASURE-ONLY mode against a
# trained model: every variant measured per bucket with the max-|dScore|
# and AUC accuracy gates evaluated, the persisted decision table
# well-formed, NOTHING enabled (measure-only's contract), and with the
# plane off served scores bit-identical to a plane-less batcher
# (tools/check_kernel_smoke.py — CPU-safe: Pallas variants are recorded
# as ineligible on the interpret backend, never timed as if real).
if [ "$rc" -eq 0 ] && [ "${TIER1_KERNEL_SMOKE:-0}" = "1" ]; then
    KERNEL_LINE="${TIER1_KERNEL_LINE:-/tmp/tier1_kernel_smoke.json}"
    echo "tier1: kernel smoke (line $KERNEL_LINE)"
    timeout -k 10 300 env JAX_PLATFORMS=cpu \
        python tools/check_kernel_smoke.py | tee "$KERNEL_LINE" || rc=1
fi

# Mesh smoke (TIER1_MESH_SMOKE=1): the ISSUE-13 serving-mode gate — the
# same trained model served single-chip and over a {data: 4, model: 2}
# mesh on 8 emulated CPU devices (the script forces
# XLA_FLAGS=--xla_force_host_platform_device_count=8 itself) must return
# BIT-IDENTICAL scores over real gRPC, with a deliberately
# non-mesh-shaped bucket ladder exercising the data-axis divisibility
# pad, and the live `mesh` monitoring block + dts_tpu_mesh_* Prometheus
# series (incl. per-device occupancy attribution) answering over HTTP
# (tools/check_mesh_smoke.py).
if [ "$rc" -eq 0 ] && [ "${TIER1_MESH_SMOKE:-0}" = "1" ]; then
    MESH_LINE="${TIER1_MESH_LINE:-/tmp/tier1_mesh_smoke.json}"
    echo "tier1: mesh smoke (line $MESH_LINE)"
    timeout -k 10 300 env JAX_PLATFORMS=cpu \
        python tools/check_mesh_smoke.py | tee "$MESH_LINE" || rc=1
fi

# Elastic smoke (TIER1_ELASTIC_SMOKE=1): the ISSUE-15 serving-mode gate —
# on 8 emulated CPU devices (the script forces the device count itself) a
# pinned `pressure` fault escalates the overload state machine to
# BROWNOUT under a ramped stream: the serving split must switch UP
# (toward data-parallel) under pressure and DOWN after recovery, with
# every response BIT-IDENTICAL to a pinned-split reference stack serving
# the same checkpoint, ZERO failed requests across both switch windows,
# every ladder rung warmup-compiled before the stream, and the
# dts_tpu_elastic_* series lint-clean (tools/check_elastic_smoke.py).
if [ "$rc" -eq 0 ] && [ "${TIER1_ELASTIC_SMOKE:-0}" = "1" ]; then
    ELASTIC_LINE="${TIER1_ELASTIC_LINE:-/tmp/tier1_elastic_smoke.json}"
    echo "tier1: elastic smoke (line $ELASTIC_LINE)"
    timeout -k 10 300 env JAX_PLATFORMS=cpu \
        python tools/check_elastic_smoke.py | tee "$ELASTIC_LINE" || rc=1
fi

# Lifecycle smoke (TIER1_LIFECYCLE_SMOKE=1): a SOAK_LIFECYCLE=1 soak —
# trained model behind a real version watcher + lifecycle controller;
# the driver publishes a fine-tuned GOOD canary (must auto-promote) and
# then a POISONED one (must auto-rollback: watcher retires + blacklists
# it, and the blacklist holds across reconcile passes while the bad dir
# still sits ready on disk) — with zero failed requests attributable to
# either swap and the live /lifecyclez + section filter + Prometheus
# series answering (tools/check_lifecycle_smoke.py). Slightly longer
# than the other smokes: one run holds a fine-tune, a promote ramp, a
# rollback, and post-rollback reconcile passes.
if [ "$rc" -eq 0 ] && [ "${TIER1_LIFECYCLE_SMOKE:-0}" = "1" ]; then
    LIFECYCLE_LINE="${TIER1_LIFECYCLE_LINE:-/tmp/tier1_lifecycle_soak.json}"
    echo "tier1: lifecycle smoke (SOAK_LIFECYCLE=1, line $LIFECYCLE_LINE)"
    timeout -k 10 300 env JAX_PLATFORMS=cpu \
        SOAK_SECONDS="${TIER1_LIFECYCLE_SECONDS:-20}" SOAK_LIFECYCLE=1 \
        python tools/soak.py | tee "$LIFECYCLE_LINE" || rc=1
    python tools/check_lifecycle_smoke.py "$LIFECYCLE_LINE" || rc=1
fi

# Fleet smoke (TIER1_FLEET_SMOKE=1): a SOAK_FLEET=1 chaos soak — 3
# serving-replica subprocesses (shared versioned base dir, lifecycle +
# gossip armed) behind the fleet.router subprocess, edge traffic dialing
# ONLY the router. SIGKILL one replica mid-traffic (zero edge-visible
# errors, per-1s goodput >= half the steady median), restart it (must
# rejoin the rotation via gossip), publish a canary into the shared base
# dir, then one replica's operator rollback must blacklist the version
# FLEET-WIDE within ~one gossip interval of the router's state change —
# with scores through the router bit-identical to a direct backend call
# before and after (tools/check_fleet_smoke.py). Longer budget: the run
# boots four processes and three of them compile a bucket ladder.
if [ "$rc" -eq 0 ] && [ "${TIER1_FLEET_SMOKE:-0}" = "1" ]; then
    FLEET_LINE="${TIER1_FLEET_LINE:-/tmp/tier1_fleet_soak.json}"
    echo "tier1: fleet smoke (SOAK_FLEET=1, line $FLEET_LINE)"
    timeout -k 10 420 env JAX_PLATFORMS=cpu \
        SOAK_SECONDS="${TIER1_FLEET_SECONDS:-20}" SOAK_FLEET=1 \
        python tools/soak.py | tee "$FLEET_LINE" || rc=1
    python tools/check_fleet_smoke.py "$FLEET_LINE" || rc=1
fi

# Fleet observability smoke (TIER1_FLEETOBS_SMOKE=1, ISSUE 18): the
# fleet chaos soak re-run with the observability plane armed fleet-wide
# (SOAK_TRACE_OUT triggers it in fleet mode) — tracing + trace export
# on every replica and the router, [slo] on the router, tracing in the
# edge process. Gated on: >= 1 stitched trace spanning client + router
# + replica, the hop waterfall closing within 2%, aggregate qps within
# 5% of the member sum, sane SLO burn rates
# (tools/check_fleetobs_smoke.py), and the multi-pid Chrome artifact
# passing tools/check_trace.py --require-multi-pid.
if [ "$rc" -eq 0 ] && [ "${TIER1_FLEETOBS_SMOKE:-0}" = "1" ]; then
    FLEETOBS_LINE="${TIER1_FLEETOBS_LINE:-/tmp/tier1_fleetobs_soak.json}"
    FLEETOBS_TRACE="${TIER1_FLEETOBS_TRACE:-/tmp/tier1_fleetobs_trace.json}"
    echo "tier1: fleet observability smoke (SOAK_FLEET=1 +" \
        "SOAK_TRACE_OUT=$FLEETOBS_TRACE, line $FLEETOBS_LINE)"
    timeout -k 10 420 env JAX_PLATFORMS=cpu \
        SOAK_SECONDS="${TIER1_FLEETOBS_SECONDS:-20}" SOAK_FLEET=1 \
        SOAK_TRACE_OUT="$FLEETOBS_TRACE" \
        python tools/soak.py | tee "$FLEETOBS_LINE" || rc=1
    python tools/check_fleetobs_smoke.py "$FLEETOBS_LINE" || rc=1
    python tools/check_trace.py "$FLEETOBS_TRACE" --min-events 10 \
        --require-multi-pid || rc=1
fi

# Cascade smoke (TIER1_CASCADE_SMOKE=1, ISSUE 19): a short SOAK_CASCADE=1
# soak — every score-filtered gRPC request runs retrieval->rank through
# the two-executable cascade (two_tower stage 1, on-device prune to 25%
# survivors, DCN over the survivor rung) — must report nonzero pruned
# rows, rows_ranked/rows_requested < 0.5, survivor scores bit-identical
# to a full-pass reference, zero gRPC errors, zero fallbacks, and the
# /cascadez + dts_tpu_cascade_* + cascade-span surfaces live
# (tools/check_cascade_smoke.py). Default candidates (1000): the prune
# must actually cross rungs (1024 -> 256).
if [ "$rc" -eq 0 ] && [ "${TIER1_CASCADE_SMOKE:-0}" = "1" ]; then
    CASCADE_LINE="${TIER1_CASCADE_LINE:-/tmp/tier1_cascade_soak.json}"
    echo "tier1: cascade smoke (SOAK_CASCADE=1, line $CASCADE_LINE)"
    timeout -k 10 300 env JAX_PLATFORMS=cpu \
        SOAK_SECONDS="${TIER1_SMOKE_SECONDS:-8}" SOAK_CASCADE=1 \
        SOAK_GRPC_WORKERS=4 SOAK_REST_WORKERS=1 \
        python tools/soak.py | tee "$CASCADE_LINE" || rc=1
    python tools/check_cascade_smoke.py "$CASCADE_LINE" || rc=1
fi

# Integrity smoke (TIER1_INTEGRITY_SMOKE=1, ISSUE 20): a SOAK_INTEGRITY=1
# chaos soak — wire flips both directions, readback bitflips, NaN score
# rows injected mid-run against the armed data-integrity plane
# (shadow_fraction=1.0, recovery controller live, verifying client) —
# must report detections on EVERY layer (server wire rejects, client
# corrupt-response catches, readback screen trips, shadow mismatches),
# zero NaN scores merged, every client-visible error an integrity
# rejection/retry, escalations landing in completed recovery cycles,
# bounded detection->success MTTR, clean traffic bit-identical plane-on
# vs off both before and after chaos, and the /integrityz +
# ?section=integrity + dts_tpu_integrity_* surfaces live
# (tools/check_integrity_smoke.py). Longer budget: shadow verification
# doubles the forward work and each escalation re-warms the ladder.
if [ "$rc" -eq 0 ] && [ "${TIER1_INTEGRITY_SMOKE:-0}" = "1" ]; then
    INTEGRITY_LINE="${TIER1_INTEGRITY_LINE:-/tmp/tier1_integrity_soak.json}"
    echo "tier1: integrity smoke (SOAK_INTEGRITY=1, line $INTEGRITY_LINE)"
    timeout -k 10 420 env JAX_PLATFORMS=cpu \
        SOAK_SECONDS="${TIER1_INTEGRITY_SECONDS:-25}" SOAK_INTEGRITY=1 \
        python tools/soak.py | tee "$INTEGRITY_LINE" || rc=1
    python tools/check_integrity_smoke.py "$INTEGRITY_LINE" || rc=1
fi
exit $rc
