#!/usr/bin/env bash
# Tier-1 verification — the EXACT command ROADMAP.md pins, wrapped so CI
# (.github/workflows/tier1.yml) and a local shell run identically:
#
#     tools/ci_tier1.sh
#
# Runs the non-slow test suite on the CPU platform, tees the log, prints a
# DOTS_PASSED count (the driver's pass-counting convention), and exits with
# pytest's status.
set -o pipefail
cd "$(dirname "$0")/.."

LOG="${TIER1_LOG:-/tmp/_t1.log}"
rm -f "$LOG"
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly \
    2>&1 | tee "$LOG"
rc=${PIPESTATUS[0]}
echo "DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' "$LOG" | tr -cd . | wc -c)"
exit $rc
