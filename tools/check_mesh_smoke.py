#!/usr/bin/env python
"""Mesh-serving tier-1 smoke (ISSUE 13): a CPU-safe, self-contained gate
asserting the [mesh] serving mode's contract end to end over REAL gRPC on
8 emulated devices —

- the SAME trained model served single-chip and over a {data: 4, model: 2}
  mesh returns BIT-IDENTICAL scores for the same requests;
- arbitrary bucket sizes are accepted (the bucket ladder is deliberately
  NOT mesh-shaped, so the data-axis divisibility pad is exercised and its
  counters move);
- the client's per-shard health/deadline semantics are unchanged over the
  new mode (same fan-out client, a deadline-bounded call still answers);
- the live `mesh` monitoring block and the dts_tpu_mesh_* Prometheus
  series answer over HTTP, with per-device occupancy attribution when the
  utilization ledger rides along.

Prints one JSON line; exit 0 = gate passed. Run by tools/ci_tier1.sh under
TIER1_MESH_SMOKE=1.
"""

import asyncio
import json
import os
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

if os.environ.get("JAX_PLATFORMS") == "cpu":
    jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

from distributed_tf_serving_tpu.client import (  # noqa: E402
    ShardedPredictClient,
    make_payload,
)
from distributed_tf_serving_tpu.models import (  # noqa: E402
    ModelConfig,
    Servable,
    build_model,
    ctr_signatures,
)
from distributed_tf_serving_tpu.serving.server import (  # noqa: E402
    build_stack,
    create_server_async,
    start_rest_in_thread,
)
from distributed_tf_serving_tpu.train import Trainer  # noqa: E402
from distributed_tf_serving_tpu.train.checkpoint import save_servable  # noqa: E402
from distributed_tf_serving_tpu.utils.config import (  # noqa: E402
    MeshConfig,
    ServerConfig,
    UtilizationConfig,
)
from distributed_tf_serving_tpu.utils.metrics import ServerMetrics  # noqa: E402

NUM_FIELDS = 8
MODEL_CFG = ModelConfig(
    name="DCN", num_fields=NUM_FIELDS, vocab_size=1 << 12, embed_dim=4,
    mlp_dims=(16,), num_cross_layers=1, compute_dtype="float32",
)
# Deliberately NOT mesh-shaped (10 and 50 are not multiples of the data
# axis 4): the divisibility pad must absorb them.
BUCKETS = (10, 50)
TRAIN_STEPS = int(os.environ.get("SMOKE_TRAIN_STEPS", "40"))


def _server_cfg() -> ServerConfig:
    return ServerConfig(
        model_kind="dcn_v2", model_name="DCN", num_fields=NUM_FIELDS,
        buckets=BUCKETS, max_wait_us=200, warmup=True,
    )


async def _score_over_grpc(impl, payloads, deadline_s=5.0):
    server, port = create_server_async(impl, "127.0.0.1:0")
    await server.start()
    try:
        async with ShardedPredictClient(
            [f"127.0.0.1:{port}"], "DCN", timeout_s=deadline_s,
        ) as client:
            return [np.asarray(await client.predict(p)) for p in payloads]
    finally:
        await server.stop(0)


async def _probe_http(port: int, out: dict) -> None:
    import aiohttp

    async with aiohttp.ClientSession() as sess:
        async with sess.get(
            f"http://127.0.0.1:{port}/monitoring?section=mesh"
        ) as resp:
            body = await resp.json()
            out["mesh_block"] = body.get("mesh")
        async with sess.get(
            f"http://127.0.0.1:{port}/monitoring/prometheus/metrics"
        ) as resp:
            out["prom_text"] = await resp.text()


def _prom_route_probe(impl, metrics, out):
    """Serve the REST gateway briefly and probe the live mesh surfaces."""
    port = start_rest_in_thread(impl, "127.0.0.1", 0, metrics)
    asyncio.run(_probe_http(port, out))


def main() -> dict:
    out = {"errors": [], "bit_identical": None}

    # One trained model, served by both stacks from the same checkpoint.
    trainer = Trainer(build_model("dcn_v2", MODEL_CFG), seed=0)
    train = trainer.fit(steps=TRAIN_STEPS, batch_size=256)
    out["train_loss"] = round(float(train["loss"]), 4)
    servable = Servable(
        name="DCN", version=1, model=trainer.model,
        params=trainer.snapshot_params(),
        signatures=ctr_signatures(NUM_FIELDS),
    )
    ckpt = os.path.join(tempfile.mkdtemp(prefix="mesh_smoke_"), "ckpt")
    save_servable(ckpt, servable, kind="dcn_v2")

    payloads = [
        make_payload(candidates=n, num_fields=NUM_FIELDS, seed=s)
        for n, s in ((7, 1), (33, 2), (50, 3))
    ]

    # Phase A: single-chip serving over real gRPC.
    _r1, batcher1, impl1, _sv1, mesh1, _w1 = build_stack(
        _server_cfg(), checkpoint=ckpt, model_config=MODEL_CFG,
    )
    try:
        single = asyncio.run(_score_over_grpc(impl1, payloads))
    finally:
        batcher1.stop()
    if mesh1 is not None:
        out["errors"].append("single-chip stack unexpectedly built a mesh")

    # Phase B: the {data: 4, model: 2} mesh mode, utilization riding
    # along for the per-device attribution surface.
    _r2, batcher2, impl2, _sv2, mesh2, _w2 = build_stack(
        _server_cfg(), checkpoint=ckpt, model_config=MODEL_CFG,
        mesh_config=MeshConfig(enabled=True, devices=8, model_parallel=2),
        utilization_config=UtilizationConfig(enabled=True),
    )
    metrics = ServerMetrics()
    try:
        if mesh2 is None or dict(mesh2.shape) != {"data": 4, "model": 2}:
            out["errors"].append(f"mesh shape wrong: {mesh2 and dict(mesh2.shape)}")
        meshed = asyncio.run(_score_over_grpc(impl2, payloads))
        out["bit_identical"] = all(
            np.array_equal(a, b) for a, b in zip(single, meshed)
        )
        if not out["bit_identical"]:
            deltas = [
                float(np.max(np.abs(a - b))) for a, b in zip(single, meshed)
            ]
            out["errors"].append(f"mesh scores != single-chip (max deltas {deltas})")

        # Deadline semantics unchanged over the mesh: a tightly-bounded
        # call still answers inside its budget.
        fast = asyncio.run(_score_over_grpc(impl2, payloads[:1], deadline_s=5.0))
        if not np.array_equal(fast[0], single[0]):
            out["errors"].append("deadline-bounded mesh call scored differently")

        snap = impl2.mesh_stats()
        out["mesh_stats"] = {
            "shape": snap["shape"],
            "devices": len(snap["devices"]),
            "executor": snap["executor"],
            "per_device": len(snap.get("per_device") or {}),
        }
        ex = snap["executor"]
        if not ex["pad_batches"] or not ex["data_pad_rows"]:
            out["errors"].append(
                f"divisibility pad never exercised: {ex} (bucket ladder "
                f"{BUCKETS} over data axis 4 must pad)"
            )
        if ex["layout"].get("DCN") != "rules:dcn_v2":
            out["errors"].append(f"named partition rules not used: {ex['layout']}")
        if len(snap.get("per_device") or {}) != 8:
            out["errors"].append("per-device occupancy attribution missing")

        # Live HTTP surfaces: the `mesh` monitoring block + Prometheus.
        _prom_route_probe(impl2, metrics, out)
        blk = (out.get("mesh_block") or {})
        if (blk.get("shape") or {}) != {"data": 4, "model": 2}:
            out["errors"].append(f"/monitoring?section=mesh wrong: {blk}")
        prom = out.pop("prom_text", "")
        needed = (
            "dts_tpu_mesh_devices 8",
            "dts_tpu_mesh_data_parallel 4",
            "dts_tpu_mesh_model_parallel 2",
            "dts_tpu_mesh_pad_batches_total",
            "dts_tpu_mesh_device_busy_fraction{",
        )
        missing = [m for m in needed if m not in prom]
        if missing:
            out["errors"].append(f"Prometheus mesh series missing: {missing}")
        out["prom_mesh_series"] = sum(
            1 for ln in prom.splitlines()
            if ln.startswith("dts_tpu_mesh_") and not ln.startswith("#")
        )
    finally:
        batcher2.stop()

    out["ok"] = not out["errors"] and bool(out["bit_identical"])
    return out


if __name__ == "__main__":
    result = main()
    print(json.dumps(result))
    sys.exit(0 if result["ok"] else 1)
