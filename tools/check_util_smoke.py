#!/usr/bin/env python
"""CI gate for the utilization-attribution smoke (ISSUE 6).

Usage: python tools/check_util_smoke.py SOAK_LINE_JSON

Reads the JSON line a SOAK_UTIL=1 soak printed (tools/ci_tier1.sh tees it
to a file) and asserts what the plane promises:

- the `utilization` block exists and the ledger saw NONZERO device-busy
  intervals (batches > 0, busy_s > 0) — the hooks actually fed it;
- the gap waterfall's components sum to the window's wall time within
  2% (the ISSUE 6 acceptance bound; the decomposition is
  sum-preserving by construction, so a violation means an accounting
  bug, not weather);
- a live achieved_fraction_of_device_limit estimate is present and sane
  (0 < f <= 1.5 — a busy-fraction estimate can exceed 1 only through an
  accounting bug; small headroom for rounding);
- the in-flight gauge returned to 0 (inc/dec stayed paired under load);
- the LIVE /utilz route answered enabled=true and the Prometheus
  endpoint served dts_tpu_utilization_* series.

Exits 0 on success; prints every failure and exits 1.
"""

import json
import sys


def main() -> None:
    if len(sys.argv) != 2:
        print("usage: check_util_smoke.py SOAK_LINE_JSON", file=sys.stderr)
        sys.exit(2)
    path = sys.argv[1]
    line = None
    try:
        with open(path) as f:
            for raw in reversed(f.read().strip().splitlines()):
                try:
                    parsed = json.loads(raw)
                except json.JSONDecodeError:
                    continue
                if isinstance(parsed, dict) and "utilization" in parsed:
                    line = parsed
                    break
    except OSError as e:
        print(f"check_util_smoke: FAIL: cannot read {path}: {e}", file=sys.stderr)
        sys.exit(1)
    if line is None:
        print(
            f"check_util_smoke: FAIL: no JSON line with a `utilization` "
            f"block in {path}", file=sys.stderr,
        )
        sys.exit(1)

    util = line.get("utilization")
    failures = []
    if not isinstance(util, dict):
        failures.append("`utilization` block missing or not an object")
        util = {}
    wf = util.get("waterfall") or {}
    if util.get("batches", 0) <= 0:
        failures.append(f"no device-busy intervals (batches={util.get('batches')})")
    if util.get("busy_s", 0.0) <= 0:
        failures.append(f"zero busy time (busy_s={util.get('busy_s')})")
    wall = wf.get("wall_s", 0.0)
    total = wf.get("sum_s", -1.0)
    if wall <= 0:
        failures.append(f"waterfall wall_s={wall!r} not positive")
    elif abs(total - wall) > 0.02 * wall:
        failures.append(
            f"waterfall components sum {total}s != wall {wall}s "
            f"(>2% off; components={wf.get('components_s')})"
        )
    frac = wf.get("achieved_fraction_of_device_limit")
    if frac is None or not (0.0 < frac <= 1.5):
        failures.append(
            f"achieved_fraction_of_device_limit={frac!r} missing or insane"
        )
    if util.get("in_flight", -1) != 0:
        failures.append(
            f"pipeline-depth gauge did not return to 0 "
            f"(in_flight={util.get('in_flight')})"
        )
    if not util.get("utilz_enabled"):
        failures.append("live GET /utilz did not answer enabled=true")
    if util.get("prometheus_series", 0) <= 0:
        failures.append("no dts_tpu_utilization_* Prometheus series served")

    if failures:
        for f_ in failures:
            print(f"check_util_smoke: FAIL: {f_}", file=sys.stderr)
        sys.exit(1)
    print(
        "check_util_smoke: OK: "
        f"batches={util['batches']} busy_s={util['busy_s']} "
        f"sum/wall={wf.get('sum_over_wall')} "
        f"achieved_fraction={frac} "
        f"prom_series={util['prometheus_series']}"
    )


if __name__ == "__main__":
    main()
