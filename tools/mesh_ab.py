#!/usr/bin/env python
"""Mesh serving A/B child (ISSUE 13): single-chip vs data-parallel vs
data×model serving throughput of ONE process, printed as one JSON line.

Run standalone, or by bench.py's `mesh` block (DTS_BENCH_MESH=1) — the
parent decides the device substrate and records it: on a live slice with
>= MESH_AB_DEVICES chips this measures real hardware (emulated=false); on
CPU the parent forces
``XLA_FLAGS=--xla_force_host_platform_device_count=N`` so the numbers are
EMULATED-DEVICE trajectory points (emulated=true — the standing-debt
field that lets the next live-TPU round tell the two apart).

Modes (all serving the SAME params through a DynamicBatcher, so the A/B
isolates the execution substrate, not the batching logic):

- ``single``:      the default single-chip jitted path (run_fn=None);
- ``data``:        ShardedExecutor over an {N, 1} mesh (pure candidate
                   sharding — the reference's layout, on-mesh);
- ``data_model``:  ShardedExecutor over an {N/2, 2} mesh (candidate
                   sharding × vocab-sharded embedding tables).

Gate: every mode must score the probe payloads BIT-IDENTICALLY (f32
compute); per-mode closed-loop throughput rides along as the measurement.
"""

import json
import os
import sys
import time

# Backend selection must happen BEFORE importing jax, and it must NOT
# default to CPU: on a live slice the parent (bench.py mesh_ab_block)
# passes the env through untouched so this child measures real hardware.
# Only an explicit emulation request (MESH_AB_FORCE_CPU=1, which the
# parent sets when no live slice is available — also the standalone
# CPU-run knob) or an already-CPU environment forces the emulated
# N-device mesh.
_need = int(os.environ.get("MESH_AB_DEVICES", "8"))
if os.environ.get("MESH_AB_FORCE_CPU") == "1":
    os.environ["JAX_PLATFORMS"] = "cpu"
if os.environ.get("JAX_PLATFORMS") == "cpu":
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + f" --xla_force_host_platform_device_count={_need}"
        ).strip()
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

if os.environ.get("JAX_PLATFORMS") == "cpu":
    jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

from distributed_tf_serving_tpu.client import make_payload  # noqa: E402
from distributed_tf_serving_tpu.models import (  # noqa: E402
    ModelConfig,
    Servable,
    build_model,
    ctr_signatures,
)
from distributed_tf_serving_tpu.parallel import (  # noqa: E402
    ShardedExecutor,
    make_mesh,
)
from distributed_tf_serving_tpu.serving.batcher import DynamicBatcher  # noqa: E402

NUM_FIELDS = int(os.environ.get("MESH_AB_FIELDS", "16"))
CANDIDATES = int(os.environ.get("MESH_AB_CANDIDATES", "512"))
WINDOW_S = float(os.environ.get("MESH_AB_WINDOW_S", "4"))
BUCKETS = (256, 1024)


def _mode_run(servable, run_fn, payloads, probes):
    """One mode: warm, score the probe payloads, then a closed-loop
    throughput window driven straight at the batcher (4 outstanding
    submits — the substrate A/B wants device-path rate, not RPC plumbing
    that is identical across modes)."""
    batcher = DynamicBatcher(
        buckets=BUCKETS, max_wait_us=200, run_fn=run_fn
    ).start()
    try:
        batcher.warmup(servable)
        scores = [
            np.asarray(
                batcher.submit(servable, p).result(timeout=120)["prediction_node"]
            )
            for p in probes
        ]
        inflight = []
        done = 0
        t0 = time.perf_counter()
        i = 0
        while time.perf_counter() - t0 < WINDOW_S:
            while len(inflight) < 4:
                inflight.append(
                    batcher.submit(servable, payloads[i % len(payloads)])
                )
                i += 1
            inflight.pop(0).result(timeout=120)
            done += 1
        for f in inflight:
            f.result(timeout=120)
            done += 1
        wall = time.perf_counter() - t0
        return scores, {
            "requests": done,
            "qps": round(done / wall, 2),
            "candidates_per_s": round(done * CANDIDATES / wall, 0),
            "window_s": round(wall, 2),
        }
    finally:
        batcher.stop()


def main() -> dict:
    out = {
        "device": str(jax.devices()[0]),
        "devices_visible": len(jax.devices()),
        "emulated": jax.default_backend() == "cpu",
        "modes": {},
        "errors": [],
    }
    n = len(jax.devices())
    if n < 2:
        out["errors"].append(f"need >= 2 devices, have {n}")
        out["ok"] = False
        return out
    cfg = ModelConfig(
        name="DCN", num_fields=NUM_FIELDS, vocab_size=1 << 14, embed_dim=8,
        mlp_dims=(64, 32), num_cross_layers=2, compute_dtype="float32",
    )
    model = build_model("dcn_v2", cfg)
    servable = Servable(
        name="DCN", version=1, model=model,
        params=jax.jit(model.init)(jax.random.PRNGKey(0)),
        signatures=ctr_signatures(NUM_FIELDS),
    )
    payloads = [
        make_payload(candidates=CANDIDATES, num_fields=NUM_FIELDS, seed=s)
        for s in range(4)
    ]
    probes = [
        make_payload(candidates=c, num_fields=NUM_FIELDS, seed=100 + c)
        for c in (37, 200)  # deliberately not mesh-shaped: pad exercised
    ]
    mp = 2 if n % 2 == 0 else 1
    modes = {
        "single": None,
        "data": make_mesh(n, model_parallel=1),
        "data_model": make_mesh(n, model_parallel=mp) if mp > 1 else None,
    }
    reference = None
    for name, mesh in modes.items():
        if name != "single" and mesh is None:
            continue
        run_fn = ShardedExecutor(mesh) if mesh is not None else None
        scores, block = _mode_run(servable, run_fn, payloads, probes)
        if mesh is not None:
            block["mesh"] = {str(k): int(v) for k, v in mesh.shape.items()}
            block["executor"] = run_fn.snapshot()["executor"]
        if reference is None:
            reference = scores
            block["bit_identical_to_single"] = True
        else:
            same = all(np.array_equal(a, b) for a, b in zip(reference, scores))
            block["bit_identical_to_single"] = same
            if not same:
                deltas = [
                    float(np.max(np.abs(a - b)))
                    for a, b in zip(reference, scores)
                ]
                out["errors"].append(
                    f"{name}: scores != single-chip (max deltas {deltas})"
                )
        out["modes"][name] = block
    out["bit_identical"] = all(
        b.get("bit_identical_to_single") for b in out["modes"].values()
    )
    out["ok"] = not out["errors"] and out["bit_identical"]
    return out


if __name__ == "__main__":
    result = main()
    print(json.dumps(result))
    sys.exit(0 if result.get("ok") else 1)
