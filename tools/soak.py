#!/usr/bin/env python
"""Mixed-surface robustness soak against the real serving stack.

Round-4 ran this scenario inline (ROUND4.md "Robustness soak": 54,746
zero-error requests on the CPU platform); VERDICT r4 task 7 asks for the
same pressure against the REAL chip's timing behavior, where relay jitter
and stalls are exactly the stress that matters. This makes the soak a
committed, re-runnable tool for both platforms.

Traffic mix on ONE event loop (the deployed topology):
- gRPC workers interleaving wide / compact / unique payloads every few
  requests (exercises the widening validator, the content-addressed device
  cache's regime detector, and the fused batch assembler under mixed
  dtypes);
- REST workers alternating :predict (columnar) with :classify Examples
  (exercises the JSON plane and the Example decode path into the same
  batcher);
- a control-plane worker hammering GetModelStatus and flipping a version
  label via HandleReloadConfigRequest every ~200 ms (the registry lock
  under data-plane pressure; labels route no soak traffic, so flips must
  never perturb scores or error counts).

Reports one JSON line: per-surface request/error counts, error taxonomy,
RSS start/end (leak watch), batcher + input-cache counters, wall/QPS, and
(when sampling is enabled) a request_log block with written/dropped/
parsed-back counts.
Env knobs: SOAK_SECONDS (default 300), SOAK_GRPC_WORKERS (8),
SOAK_REST_WORKERS (4), SOAK_CANDIDATES (1000),
SOAK_CACHE=1 (cache plane armed: score cache + single-flight + dedup on
the batcher, gRPC workers on a seeded zipfian workload —
SOAK_CACHE_SKEW/SOAK_CACHE_SEED — plus a pre-flight bit-identity probe;
the JSON line gains a `cache` block with hit/miss/coalesced/dedup
counters and `scores_match`),
SOAK_ROWCACHE=1 (cache mode plus the ROW-GRANULAR cache, ISSUE 14: only
cold rows execute; adds a `row_cache` block with per-row hit/miss
counters, rows_executed vs rows_requested, and a row-path bit-identity
probe — the TIER1_ROWCACHE_SMOKE gate reads it),
SOAK_CASCADE=1 (multi-stage cascade armed, ISSUE 19: a two_tower stage-1
servable joins the registry, every score-filtered gRPC request runs
retrieval->rank through serving/cascade.py — stage-1 full batch,
on-device prune to 25% survivors, DCN over the survivor rung only — with
a pre-flight bit-identity probe (cascade survivor scores vs a full-pass
reference; pruned rows vs stage-1-only) and live /cascadez + Prometheus
+ phase-span probes; the JSON line gains a `cascade` block the
TIER1_CASCADE_SMOKE gate reads),
SOAK_REQUEST_LOG_SAMPLING (default 0 = logging off; >0 stresses the
bounded-queue request logger under the mixed load — note it adds a
SerializeToString per sampled request, so A/Bs against logging-off soaks
are not apples-to-apples).

Overload mode (SOAK_OVERLOAD=1): the adaptive overload plane (ISSUE 5,
serving/overload.py) under ~3x sustainable load. Capacity is made
deterministic with an injected batcher.dispatch delay
(SOAK_OVERLOAD_DISPATCH_DELAY_S, default 0.03 -> ~33 batches/s), the
worker pool is sized ~3x what that drains, and a mid-run BURST
(SOAK_OVERLOAD_BURST_WORKERS, default +grpc_workers/2) runs from 40% to
70% of the soak. The batcher runs an AdmissionController (self-tuning
limit, criticality lanes, doomed-work refusal, brownout stale-serve
through a short-TTL score cache on a zipfian workload) and gRPC workers
carry a short deadline (SOAK_OVERLOAD_DEADLINE_S, default 2.0) so goodput
= in-deadline successes/s. One worker in three sends
criticality=sheddable. The client runs the scoreboard with
failover_attempts=1: RESOURCE_EXHAUSTED sheds must register as PUSHBACK
(busy), never ejection. The JSON line gains an `overload` block —
goodput_qps, the controller snapshot (sheds / doomed_refusals /
brownout_serves / limit / queue_wait_p99_ms), cache stale_serves, and
client pushback counters — gated in CI by tools/check_overload_smoke.py
(nonzero sheds, nonzero brownout serves, zero ejections, goodput floor).

Chaos mode (SOAK_CHAOS=1, seeded by SOAK_CHAOS_SEED): deterministic fault
injection (distributed_tf_serving_tpu/faults.py) rides the same soak —
low-rate injected RPC errors + delays at the client.rpc / batcher.dispatch
/ readback sites while the gRPC client runs with the health scoreboard on.
The JSON line gains `chaos` (per-site fire counts) and `resilience`
(client counters + scoreboard) blocks; injected UNAVAILABLEs land in the
error taxonomy, so a chaos soak PASSES when the taxonomy shows nothing
BUT the injected codes and the stack neither leaks nor wedges.

Utilization mode (SOAK_UTIL=1): the device-utilization attribution plane
(ISSUE 6, serving/utilization.py) rides the soak — the batcher runs an
OccupancyLedger (busy/idle timeline, idle-gap cause attribution,
pipeline-depth gauge), and before shutdown the soak probes the LIVE
`GET /utilz` route and the Prometheus endpoint over HTTP. The JSON line
gains a `utilization` block — the ledger snapshot (gap waterfall whose
components must sum to wall, live achieved_fraction_of_device_limit),
`utilz_enabled` from the live route, and `prometheus_series` (the count
of dts_tpu_utilization_* exposition lines) — gated in CI by
tools/check_util_smoke.py (nonzero busy intervals, components sum to
wall within 2%, Prometheus series present). When SOAK_TRACE_OUT is also
set, the exported Chrome trace carries the per-device occupancy counter
track (tools/check_trace.py --require-counter-track).

Quality mode (SOAK_QUALITY=1): the model-quality observability plane
(ISSUE 7, serving/quality.py) rides a purpose-built workload. The soak
model is first TRAINED briefly on the synthetic CTR stream
(SOAK_QUALITY_TRAIN_STEPS, default 200) so its scores carry real signal
against the stream's known teacher logits; gRPC workers then serve
payload pools generated from that same stream, generate each row's label
from the teacher (Bernoulli of the teacher logit — the data-gen's own
labeling), and report labels to the LIVE `POST /labelz` route keyed by
per-row digests (client.label_keys). Mid-run the reference distribution
is pinned via `POST /qualityz/snapshot` (~40%), and a deliberately
SHIFTED traffic segment (feature weights scaled, labels regenerated from
the teacher on the shifted rows) starts at ~55% — driving windowed PSI
vs the pinned reference above threshold, which must force-keep
`quality.drift` exemplar traces into /tracez. The JSON line gains a
`quality` block — windowed AUC from the live /qualityz route next to the
exact AUC the soak computes offline from its own (score, label) log,
joined/orphaned counts, the drift block, the exemplar-trace count found
in the live /tracez body, and the Prometheus text written to
SOAK_QUALITY_PROM_OUT for the exposition lint — gated in CI by
tools/check_quality_smoke.py (which also runs tools/check_prom.py on
the captured text).

Lifecycle mode (SOAK_LIFECYCLE=1): the continuous-freshness plane
(ISSUE 8, serving/lifecycle.py) end to end against live traffic. The
soak model trains briefly, lands as version 1 of a WATCHED base dir (a
real VersionWatcher with a fast poll), and a LifecycleController with
fast ramp/dwell knobs runs armed on the impl while gRPC workers (one on
the probe criticality lane) serve a steady payload pool. A driver task
then (a) fine-tunes and publishes a GOOD canary through
train/publisher.py::publish_finetuned — the watcher hot-loads it
mid-traffic, probe-lane then ramped default-lane traffic feeds its
quality sketches, and the controller auto-PROMOTES it; (b) publishes a
POISONED canary (params scaled, scores saturate) — version-pair PSI
crosses the rollback threshold and the controller auto-ROLLS-BACK:
the watcher retires + blacklists the version, and the soak lets several
reconcile passes run to prove the blacklist holds while the bad
directory still sits ready on disk. End probes hit the LIVE /lifecyclez,
/monitoring?section=lifecycle, and Prometheus surfaces. The JSON line
gains a `lifecycle` block — promote/rollback counters and waits, final
loaded versions, blacklist persistence, routed-traffic counters, live
route/series probes — gated in CI by tools/check_lifecycle_smoke.py
(promote AND rollback observed, blacklist survived reconcile, ZERO
failed requests attributable to either swap).

Recovery mode (SOAK_RECOVERY=1): the device-failure recovery plane
(ISSUE 11, serving/recovery.py) end to end against live traffic on a
depth-4 continuous-batching pipeline (inflight_window=4, buffer ring).
A RecoveryController with a fast watchdog runs armed while gRPC workers
(scoreboard + deep failover retries whose horizon outlasts the cycle,
plus the new per-request max_attempts_total budget) hammer the replica.
A driver task then (a) WEDGES the device stage (faults.py wedge rule) —
the watchdog must escalate the wedge clock into a quarantine (health
NOT_SERVING), replace the stranded worker pools, reinit + re-warm the
executor, and replay the captured pipeline with zero client-visible
failures; MTTR is measured from injection to the first post-recovery
success; (b) submits a content-keyed POISONED input (device_lost rule
keyed on batcher.poison_fault_key) coalesced with clean companions —
the bisection must fail exactly the poison with PoisonedInputError
(INVALID_ARGUMENT) while the companions replay to success. End probes
hit the LIVE /recoveryz, /monitoring?section=recovery, and Prometheus
surfaces. The JSON line gains a `recovery` block gated in CI by
tools/check_recovery_smoke.py (quarantine + replay observed, MTTR
bounded, zero non-poison failures, bisection isolating the poison).

Integrity mode (SOAK_INTEGRITY=1): the data-integrity plane (ISSUE 20,
serving/integrity.py) under live traffic with all three silent-corruption
fault sites armed mid-run. The plane runs with shadow_fraction=1.0
(SOAK_INTEGRITY_SHADOW) and the recovery controller armed; the gRPC
client verifies response checksums (integrity_checksums=True) with
scoreboard + deep failover. The scenario: pre-traffic CLEAN bit-identity
probe (plane detached vs attached with a forced shadow audit — the plane
must never change answers); phase 1 arms `score_nan` with shadow stood
down (the readback screen must catch NaN rows row-granularly and
escalate past screen_trips_per_window into an output_corrupt recovery
cycle); phase 2 re-arms shadow and injects `readback_bitflip` (the
bit-identical shadow compare must catch every flipped batch before
delivery and escalate) plus `wire_corrupt` both directions (request-side
keyed on feat_ids — the server must fail exactly the damaged request
with a corrupt-wire INVALID_ARGUMENT; response-side keyed "response" —
the client verify must catch the flip and retry, never merging corrupt
scores); detection-to-success MTTR is measured from the first shadow
mismatch; a closing clean bit-identity probe runs after faults clear.
The JSON line gains an `integrity` block — the plane snapshot, both
bit-identity verdicts, per-phase screen counters, MTTR, client
corrupt_responses / nan_scores_merged, recovery escalation counters, and
live /integrityz + ?section=integrity + Prometheus probes — gated in CI
(TIER1_INTEGRITY_SMOKE=1) by tools/check_integrity_smoke.py (detections
on every layer, zero NaN merges, zero corrupt deliveries, bit-identity
both ends, escalation observed).

Fleet mode (SOAK_FLEET=1): the fleet robustness plane (ISSUE 17,
fleet/) as REAL PROCESSES — SOAK_FLEET_REPLICAS (default 3) serving
replicas, each a full `serving.server` subprocess with a version watcher
+ lifecycle controller over ONE shared versioned base dir and an armed
[fleet] gossip agent, behind one `fleet.router` subprocess (embedded
ShardedPredictClient: scoreboard + jump-hash affinity + failover,
gossip-fed steering, grpc.health.v1 Watch subscriptions, rollout
coordinator). Edge traffic dials ONLY the router. The kill/restart
chaos script, all mid-traffic: steady window → bit-identity probe
(router response vs a direct backend call on the same payload) →
SIGKILL one replica (the router must absorb it: zero edge-visible
errors, per-1s goodput ≥ half the steady median) → restart it (it must
rejoin the rotation via gossip, measured) → publish a canary version
into the shared base dir (every replica's watcher hot-loads it, every
lifecycle starts its ramp) → POST /lifecyclez/rollback on ONE replica —
the router's rollout coordinator must blacklist the version FLEET-WIDE
(every replica's rolled_back_version flips) within about one gossip
interval of the router's state change, measured → closing bit-identity
probe. The JSON line gains a `fleet` block — request/error counts,
per-1s goodput windows, rejoin/propagation timings, both bit-identity
probes, router /fleetz counters, dts_tpu_fleet_* series counts from the
router's gossip-port /metrics and a replica's REST exposition — gated
in CI by tools/check_fleet_smoke.py. Knobs: SOAK_FLEET_REPLICAS,
SOAK_FLEET_GOSSIP_INTERVAL_S (0.25), SOAK_FLEET_FIELDS (8),
SOAK_CANDIDATES (24 here), SOAK_GRPC_WORKERS (4 here).

Fleet observability mode (SOAK_FLEET=1 + SOAK_TRACE_OUT=/path, ISSUE
18): the fleet soak additionally arms the fleet observability plane —
[observability] tracing + trace_export on every replica AND the router
(sample rate 1.0 so every request is kept), [slo] on the router with
soak-scale windows, and tracing enabled in THIS edge process. After the
chaos script settles, the edge recorder's span trees are POSTed to the
router's /tracez/ingest (source "client"), the router's /tracez is
polled until it serves >= 1 STITCHED trace spanning client + router +
replica, the multi-pid Chrome export (/tracez?format=chrome) is written
to SOAK_TRACE_OUT, and /fleet/monitoring + /sloz + /monitoring are
probed. The JSON line gains a `fleetobs` block (stitched/3-process
trace counts, the hop waterfall, aggregate-vs-member qps, the SLO
snapshot, Chrome event count + artifact path) — gated in CI
(TIER1_FLEETOBS_SMOKE=1) by tools/check_fleetobs_smoke.py plus
tools/check_trace.py --require-multi-pid on the artifact. The plain
fleet smoke (no SOAK_TRACE_OUT) is unchanged.

Tracing (SOAK_TRACE_OUT=/path/trace.json): per-request span tracing runs
for the whole soak (utils/tracing.py; SOAK_TRACE_SAMPLE sets the tail-
sampling rate, default 0.05 — errors/fault-annotated/slowest-N traces are
always kept), the live `/tracez?format=chrome` endpoint is probed over
HTTP before shutdown, and its Chrome-trace-event JSON (Perfetto-loadable)
is written to the given path. The JSON line gains a `trace` block
(recorded/retained/event counts + the artifact path) — the CI smoke step
(tools/ci_tier1.sh TIER1_TRACE_SMOKE=1) asserts the artifact is schema-
valid and non-empty via tools/check_trace.py.
"""

import asyncio
import contextlib
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

NUM_FIELDS = 43


def rss_gb() -> float:
    with open("/proc/self/status") as f:
        for ln in f:
            if ln.startswith("VmRSS:"):
                return round(int(ln.split()[1]) / 1e6, 3)
    return 0.0


def _fleet_soak(seconds: float) -> None:
    """SOAK_FLEET=1: the kill/restart chaos soak against a real
    multi-process fleet (module docstring, "Fleet mode"). Self-contained:
    the in-process soak stack below is the wrong shape for a scenario
    whose whole point is processes dying."""
    import shutil
    import socket
    import subprocess
    import tempfile
    import threading
    import urllib.error
    import urllib.request

    import grpc
    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from distributed_tf_serving_tpu.client import (
        ShardedPredictClient,
        make_payload,
    )
    from distributed_tf_serving_tpu.models import (
        ModelConfig,
        Servable,
        build_model,
        ctr_signatures,
    )
    from distributed_tf_serving_tpu.proto import health as health_proto
    from distributed_tf_serving_tpu.train.checkpoint import save_servable

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    fields = int(os.environ.get("SOAK_FLEET_FIELDS", "8"))
    replicas = int(os.environ.get("SOAK_FLEET_REPLICAS", "3"))
    candidates = int(os.environ.get("SOAK_CANDIDATES", "24"))
    workers = int(os.environ.get("SOAK_GRPC_WORKERS", "4"))
    gossip_interval = float(
        os.environ.get("SOAK_FLEET_GOSSIP_INTERVAL_S", "0.25")
    )
    ttl_s = max(gossip_interval * 6, 1.5)
    # Fleet observability mode (ISSUE 18): SOAK_TRACE_OUT in fleet mode
    # arms tracing + trace export fleet-wide and the SLO monitor on the
    # router; the Chrome multi-pid export lands at this path.
    trace_out = os.environ.get("SOAK_TRACE_OUT", "")
    fleetobs = bool(trace_out)
    if fleetobs:
        from distributed_tf_serving_tpu.utils import tracing as edge_tracing
        edge_tracing.enable(buffer_size=512, sample_rate=1.0)
    start_rss = rss_gb()
    t_start = time.time()

    tmp = tempfile.mkdtemp(prefix="soak_fleet_")
    base = os.path.join(tmp, "models")
    os.makedirs(base)

    # Tiny servable: the soak measures the fleet plane, not the forward.
    config = ModelConfig(
        name="DCN", num_fields=fields, vocab_size=1 << 12, embed_dim=8,
        mlp_dims=(16,), num_cross_layers=1, cross_full_matrix=True,
    )
    model = build_model("dcn_v2", config)
    params = jax.jit(model.init)(jax.random.PRNGKey(0))
    servable = Servable(
        name="DCN", version=1, model=model, params=params,
        signatures=ctr_signatures(fields),
    )
    save_servable(os.path.join(base, "1"), servable, kind="dcn_v2")

    def free_port() -> int:
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        p = s.getsockname()[1]
        s.close()
        return p

    grpc_ports = [free_port() for _ in range(replicas)]
    rest_ports = [free_port() for _ in range(replicas)]
    gossip_ports = [free_port() for _ in range(replicas)]
    router_port = free_port()
    router_gossip = free_port()
    backend_addrs = [f"127.0.0.1:{p}" for p in grpc_ports]
    router_addr = f"127.0.0.1:{router_port}"

    # Star topology: every replica gossips with the router only; push-pull
    # through the common peer converges the full membership view.
    for i in range(replicas):
        with open(os.path.join(tmp, f"replica{i}.toml"), "w") as f:
            f.write(
                f'[server]\n'
                f'host = "127.0.0.1"\n'
                f'port = {grpc_ports[i]}\n'
                f'model_kind = "dcn_v2"\n'
                f'model_name = "DCN"\n'
                f'num_fields = {fields}\n'
                f'buckets = [8, 16, 32]\n'
                f'max_workers = 8\n'
                f'file_system_poll_wait_seconds = 0.5\n'
                f'\n'
                f'[lifecycle]\n'
                f'enabled = true\n'
                f'tick_interval_s = 0.2\n'
                f'canary_probe_only_s = 0.5\n'
                f'canary_initial_fraction = 0.25\n'
                f'canary_ramp_step = 0.05\n'
                f'canary_step_dwell_s = 30.0\n'
                f'canary_max_fraction = 0.3\n'
                f'promote_after_s = 3600.0\n'
                f'rollback_hold_s = 60.0\n'
                f'\n'
                f'[fleet]\n'
                f'enabled = true\n'
                f'self_id = "{backend_addrs[i]}"\n'
                f'gossip_port = {gossip_ports[i]}\n'
                f'peers = ["127.0.0.1:{router_gossip}"]\n'
                f'gossip_interval_s = {gossip_interval}\n'
                f'record_ttl_s = {ttl_s}\n'
            )
            if fleetobs:
                f.write(
                    '\n'
                    '[observability]\n'
                    'tracing = true\n'
                    'trace_sample_rate = 1.0\n'
                    'trace_export = true\n'
                )
    router_toml = os.path.join(tmp, "router.toml")
    with open(router_toml, "w") as f:
        f.write(
            f'[server]\n'
            f'host = "127.0.0.1"\n'
            f'port = {router_port}\n'
            f'\n'
            f'[client]\n'
            f'hosts = {json.dumps(backend_addrs)}\n'
            f'model_name = "DCN"\n'
            f'num_fields = {fields}\n'
            f'timeout_s = 5.0\n'
            f'health_scoreboard = true\n'
            f'ejection_failures = 1\n'
            f'ejection_interval_s = 1.0\n'
            f'failover_attempts = 2\n'
            f'backoff_initial_ms = 10\n'
            f'partial_results = false\n'
            f'placement = "affinity"\n'
            f'\n'
            f'[fleet]\n'
            f'enabled = true\n'
            f'self_id = "router"\n'
            f'gossip_port = {router_gossip}\n'
            f'peers = {json.dumps([f"127.0.0.1:{p}" for p in gossip_ports])}\n'
            f'gossip_interval_s = {gossip_interval}\n'
            f'record_ttl_s = {ttl_s}\n'
            f'rollout_writer = true\n'
            f'rollout_state_file = "{os.path.join(tmp, "rollout.json")}"\n'
        )
        if fleetobs:
            # Soak-scale SLO windows: short/long must both fill within
            # the run so the burn rates carry real deltas.
            f.write(
                '\n'
                '[observability]\n'
                'tracing = true\n'
                'trace_sample_rate = 1.0\n'
                'trace_export = true\n'
                'trace_export_interval_s = 0.5\n'
                '\n'
                '[slo]\n'
                'enabled = true\n'
                'latency_target_ms = 100.0\n'
                'short_window_s = 2.0\n'
                'long_window_s = 8.0\n'
            )

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["PYTHONPATH"] = repo_root + (
        os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else ""
    )

    def _log_tails(note: str) -> None:
        print(f"# fleet soak FAILED: {note}", file=sys.stderr)
        for name in sorted(os.listdir(tmp)):
            if name.endswith(".log"):
                with open(os.path.join(tmp, name), "rb") as f:
                    tail = f.read()[-4000:].decode("utf-8", "replace")
                print(f"# ---- {name} tail ----\n{tail}", file=sys.stderr)

    def spawn_replica(i: int) -> subprocess.Popen:
        lf = open(os.path.join(tmp, f"replica{i}.log"), "ab")
        return subprocess.Popen(
            [sys.executable, "-m",
             "distributed_tf_serving_tpu.serving.server",
             "--config", os.path.join(tmp, f"replica{i}.toml"),
             "--model-base-path", base,
             "--rest-port", str(rest_ports[i])],
            stdout=lf, stderr=lf, env=env, cwd=repo_root,
        )

    def wait_serving(addr: str, proc, timeout: float) -> None:
        deadline = time.time() + timeout
        last = "<no attempt>"
        while time.time() < deadline:
            if proc.poll() is not None:
                raise RuntimeError(
                    f"server {addr} exited rc={proc.returncode}"
                )
            # Fresh channel per attempt: a channel created before the
            # server listens can sit out a reconnect backoff long after
            # the port is up; boot-time probing wants the connect NOW.
            ch = grpc.insecure_channel(addr)
            stub = health_proto.HealthStub(ch)
            try:
                resp = stub.Check(
                    health_proto.HealthCheckRequest(""), timeout=1.0
                )
                last = f"status={resp.status}"
                if resp.status == health_proto.SERVING:
                    return
            except grpc.RpcError as e:
                last = f"{e.code()} {e.details()!r}"
            finally:
                ch.close()
            time.sleep(0.3)
        raise RuntimeError(
            f"server {addr} not SERVING in {timeout}s (last: {last})"
        )

    def http_json(url: str, payload=None, timeout: float = 3.0):
        data = (
            json.dumps(payload).encode("utf-8")
            if payload is not None else None
        )
        req = urllib.request.Request(
            url, data=data,
            headers={"Content-Type": "application/json"} if data else {},
        )
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return json.loads(r.read().decode("utf-8"))

    def http_text(url: str, timeout: float = 3.0) -> str:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.read().decode("utf-8")

    def router_fleetz() -> dict:
        return http_json(f"http://127.0.0.1:{router_gossip}/fleetz")

    def poll_until(fn, timeout: float, what: str, poll_s: float = 0.05):
        """fn() -> truthy value | falsy; returns (value, elapsed_s)."""
        t0 = time.time()
        while time.time() - t0 < timeout:
            try:
                v = fn()
            except Exception:  # noqa: BLE001 — surfaces settle async
                v = None
            if v:
                return v, round(time.time() - t0, 3)
            time.sleep(poll_s)
        raise RuntimeError(f"timed out ({timeout}s) waiting for {what}")

    # Traffic runs on its own thread + event loop for the whole scenario;
    # the main thread drives the chaos script.
    events: list = []  # (wall_t, ok, error_repr)
    stop_traffic = threading.Event()
    payloads = [make_payload(candidates, fields, seed=s) for s in range(8)]

    def traffic_thread() -> None:
        async def run() -> None:
            edge = ShardedPredictClient(
                [router_addr], "DCN", timeout_s=5.0, failover_attempts=1,
                backoff_initial_s=0.02,
            )

            async def worker(wid: int) -> None:
                n = 0
                while not stop_traffic.is_set():
                    n += 1
                    try:
                        await edge.predict(payloads[(wid + n) % len(payloads)])
                        events.append((time.time(), True, ""))
                    except Exception as e:  # noqa: BLE001 — counted, gated
                        events.append((time.time(), False, repr(e)[:200]))
                    await asyncio.sleep(0.02)

            await asyncio.gather(*(worker(w) for w in range(workers)))
            await edge.close()

        asyncio.run(run())

    def probe_bit_identity() -> bool:
        """The same payload through the router and direct to one backend
        must score bit-identically (the router re-encodes through the
        same codec; affinity sub-batching must not perturb scores)."""
        async def run():
            probe = make_payload(candidates, fields, seed=99)
            edge = ShardedPredictClient([router_addr], "DCN", timeout_s=10.0)
            direct = ShardedPredictClient(
                [backend_addrs[0]], "DCN", timeout_s=10.0
            )
            try:
                via_router = await edge.predict(probe)
                direct_hit = await direct.predict(probe)
            finally:
                await edge.close()
                await direct.close()
            return via_router, direct_hit

        a, b = asyncio.run(run())
        return bool(
            np.asarray(a).tobytes() == np.asarray(b).tobytes()
        )

    procs: list = []
    router_proc = None
    rfd = None
    traffic = None
    try:
        # ---- boot the fleet -------------------------------------------
        procs = [spawn_replica(i) for i in range(replicas)]
        for i in range(replicas):
            wait_serving(backend_addrs[i], procs[i], 120.0)
        rfd, wfd = os.pipe()
        router_log = open(os.path.join(tmp, "router.log"), "ab")
        router_proc = subprocess.Popen(
            [sys.executable, "-m", "distributed_tf_serving_tpu.fleet.router",
             "--config", router_toml, "--ready-fd", str(wfd)],
            stdout=router_log, stderr=router_log, env=env, cwd=repo_root,
            pass_fds=(wfd,),
        )
        os.close(wfd)
        import select

        ready_raw = b""
        deadline = time.time() + 60.0
        while b"\n" not in ready_raw and time.time() < deadline:
            if router_proc.poll() is not None:
                raise RuntimeError(
                    f"router exited rc={router_proc.returncode}"
                )
            r, _, _ = select.select([rfd], [], [], 0.5)
            if r:
                chunk = os.read(rfd, 4096)
                if not chunk:
                    break
                ready_raw += chunk
        if b"\n" not in ready_raw:
            raise RuntimeError("router never wrote its readiness line")
        ready = json.loads(ready_raw.decode("utf-8").splitlines()[0])
        # Membership converges through the star: router sees everyone.
        _, converge_s = poll_until(
            lambda: router_fleetz()["gossip"]["member_count"]
            >= replicas + 1,
            timeout=30.0, what="gossip membership convergence",
        )

        # ---- steady traffic + reference probe -------------------------
        traffic = threading.Thread(target=traffic_thread, daemon=True)
        traffic_start = time.time()
        traffic.start()
        steady_s = max(seconds * 0.25, 3.0)
        time.sleep(steady_s)
        bit_identical_pre = probe_bit_identity()

        # ---- chaos: SIGKILL one replica mid-traffic -------------------
        victim = 1 % replicas
        procs[victim].kill()
        procs[victim].wait()
        kill_t = time.time()
        time.sleep(max(seconds * 0.15, 2.0))

        # ---- restart it: rejoin is a gossip event, measured -----------
        procs[victim] = spawn_replica(victim)
        restart_t = time.time()
        wait_serving(backend_addrs[victim], procs[victim], 120.0)

        def rejoined():
            fz = router_fleetz()
            members = fz.get("gossip", {}).get("members", {})
            rec = members.get(backend_addrs[victim])
            return (
                fz
                if rec is not None and rec.get("state") == "serving"
                and fz.get("healthy_backends") == replicas
                else None
            )

        fz_rejoin, rejoin_poll_s = poll_until(rejoined, 60.0, "fleet rejoin")
        rejoin_s = round(time.time() - restart_t, 3)

        # ---- canary publish into the SHARED base dir ------------------
        # (After the rejoin on purpose: a replica booting onto a dir that
        # already holds the canary adopts LATEST as stable — the fleet
        # could then never blacklist it out. Same params as v1, so the
        # closing bit-identity probe holds straight through the ramp.)
        servable2 = Servable(
            name="DCN", version=2, model=model, params=params,
            signatures=ctr_signatures(fields),
        )
        save_servable(os.path.join(base, "2"), servable2, kind="dcn_v2")
        publish_t = time.time()
        for i in range(replicas):
            poll_until(
                lambda i=i: http_json(
                    f"http://127.0.0.1:{rest_ports[i]}/lifecyclez"
                ).get("canary_version") == 2,
                timeout=30.0, what=f"replica {i} canary live",
            )
        canary_live_s = round(time.time() - publish_t, 3)

        # ---- fleet-coordinated rollback -------------------------------
        # One replica's operator rollback; the router's coordinator must
        # blacklist v2 for the WHOLE fleet within ~a gossip interval.
        def post_rollback():
            try:
                return http_json(
                    f"http://127.0.0.1:{rest_ports[0]}/lifecyclez/rollback",
                    {"reason": "fleet-soak-chaos"},
                )
            except urllib.error.HTTPError:
                return None  # 409: canary not live yet — retried

        rollback_resp, _ = poll_until(
            post_rollback, 20.0, "operator rollback accepted"
        )
        rollback_post_t = time.time()
        _, router_blacklist_s = poll_until(
            lambda: 2 in (
                router_fleetz().get("rollout", {})
                .get("state", {}).get("blacklist", [])
            ),
            timeout=15.0, what="router fleet blacklist",
        )
        router_blacklist_t = time.time()

        def all_rolled_back():
            states = [
                http_json(f"http://127.0.0.1:{rest_ports[i]}/lifecyclez")
                for i in range(replicas)
            ]
            return (
                states
                if all(s.get("rolled_back_version") == 2 for s in states)
                else None
            )

        lifecycle_states, propagation_s = poll_until(
            all_rolled_back, 15.0, "fleet-wide rollback"
        )
        post_to_all_s = round(time.time() - rollback_post_t, 3)

        # ---- post-chaos traffic + closing probe -----------------------
        time.sleep(max(seconds * 0.2, 3.0))
        stop_traffic.set()
        traffic.join(timeout=15.0)
        traffic_stop = time.time()
        bit_identical_post = probe_bit_identity()

        fz_final = router_fleetz()
        router_prom = http_text(
            f"http://127.0.0.1:{router_gossip}/metrics"
        )
        replica_prom = http_text(
            f"http://127.0.0.1:{rest_ports[0]}"
            f"/monitoring/prometheus/metrics"
        )

        # ---- fleet observability probes (ISSUE 18) --------------------
        fleetobs_block = None
        if fleetobs:
            # Push the edge recorder's span trees — the first hop of
            # every stitched trace. Loop the cursor until drained.
            cursor = 0
            pushed = 0
            while True:
                export = edge_tracing.recorder().export_since(cursor)
                if not export.get("spans"):
                    break
                resp = http_json(
                    f"http://127.0.0.1:{router_gossip}/tracez/ingest",
                    {"source": "client", **export},
                )
                pushed += int(resp.get("accepted") or 0)
                cursor = int(export.get("cursor") or cursor)

            def stitched_three():
                tz = http_json(
                    f"http://127.0.0.1:{router_gossip}/tracez?limit=100"
                )
                three = [
                    t for t in tz.get("traces") or []
                    if t.get("num_processes", 0) >= 3
                    and t.get("stitched_hops", 0) >= 2
                ]
                return (tz, three) if three else None

            (tz, three), _ = poll_until(
                stitched_three, 30.0,
                "a stitched trace spanning client + router + replica",
            )
            chrome = http_json(
                f"http://127.0.0.1:{router_gossip}"
                f"/tracez?format=chrome&limit=100"
            )
            with open(trace_out, "w") as f:
                json.dump(chrome, f)
            fleet_mon = http_json(
                f"http://127.0.0.1:{router_gossip}/fleet/monitoring"
            )
            slo = http_json(f"http://127.0.0.1:{router_gossip}/sloz")
            router_mon = http_json(
                f"http://127.0.0.1:{router_gossip}/monitoring"
            )
            agg = fleet_mon.get("aggregate") or {}
            member_qps_sum = sum(
                float(st.get("qps") or 0.0)
                for st in (fleet_mon.get("members") or {}).values()
            )
            wf = next(
                (t["waterfall"] for t in three if t.get("waterfall")),
                None,
            )
            fleetobs_block = {
                "client_spans_pushed": pushed,
                "stitched_traces": sum(
                    1 for t in tz.get("traces") or []
                    if t.get("num_processes", 0) >= 2
                ),
                "three_proc_traces": len(three),
                "waterfall": wf,
                "waterfall_window": fleet_mon.get("waterfall"),
                "agg_qps": agg.get("qps"),
                "member_qps_sum": round(member_qps_sum, 3),
                "agg": agg,
                "slo": slo,
                "router_monitoring_keys": sorted(router_mon),
                "trace_events": len(chrome.get("traceEvents") or []),
                "trace_out": trace_out,
            }

        # ---- goodput windows ------------------------------------------
        ok_times = sorted(t for t, ok, _ in events if ok)
        errors = [e for _, ok, e in events if not ok]

        from bisect import bisect_left as _bisect_left

        def windows(t0: float, t1: float) -> list:
            out, w = [], t0
            while w + 1.0 <= t1:
                lo = _bisect_left(ok_times, w)
                hi = _bisect_left(ok_times, w + 1.0)
                out.append(hi - lo)
                w += 1.0
            return out

        steady_windows = windows(traffic_start + 1.0, kill_t - 0.2)
        # The goodput gate covers the KILL/RESTART phase only: from the
        # SIGKILL until the canary publish. The rollout phase that follows
        # dips for a different, expected reason — every replica
        # orbax-restores and warmup-compiles v2 at once, and on a CPU host
        # three concurrent compile ladders starve the serving threads.
        # That phase is gated on zero errors + bounded propagation instead;
        # its windows are reported separately for eyeballing.
        chaos_windows = windows(kill_t, publish_t - 0.2)
        rollout_windows = windows(publish_t, traffic_stop - 0.2)
        steady_median = (
            sorted(steady_windows)[len(steady_windows) // 2]
            if steady_windows else 0
        )
        min_ratio = (
            round(min(chaos_windows) / steady_median, 3)
            if chaos_windows and steady_median else None
        )

        taxonomy: dict = {}
        for e in errors:
            taxonomy[e] = taxonomy.get(e, 0) + 1

        line = {
            "mode": "fleet",
            "seconds": seconds,
            "wall_s": round(time.time() - t_start, 1),
            "rss_gb": {"start": start_rss, "end": rss_gb()},
            "fleet": {
                "replicas": replicas,
                "router": ready,
                "gossip_interval_s": gossip_interval,
                "converge_s": converge_s,
                "requests": len(events),
                "ok": len(ok_times),
                "errors": len(errors),
                "error_taxonomy": dict(list(taxonomy.items())[:5]),
                "steady_window_median": steady_median,
                "steady_windows": steady_windows,
                "chaos_windows": chaos_windows,
                "rollout_windows": rollout_windows,
                "min_chaos_window_ratio": min_ratio,
                "bit_identical_pre": bit_identical_pre,
                "bit_identical_post": bit_identical_post,
                "kill": {
                    "victim": backend_addrs[victim],
                    "rejoin_s": rejoin_s,
                    "rejoin_poll_s": rejoin_poll_s,
                    "healthy_backends": fz_rejoin.get("healthy_backends"),
                },
                "rollout": {
                    "canary_version": 2,
                    "canary_live_s": canary_live_s,
                    "rollback_origin": backend_addrs[0],
                    "rollback_accepted": bool(
                        rollback_resp.get("rolled_back")
                    ),
                    "router_blacklist_s": router_blacklist_s,
                    "propagation_s": propagation_s,
                    "post_to_all_s": post_to_all_s,
                    "per_replica_rolled_back": [
                        s.get("rolled_back_version")
                        for s in lifecycle_states
                    ],
                },
                "router_counters": fz_final.get("counters", {}),
                "router_healthy_backends": fz_final.get(
                    "healthy_backends"
                ),
                "prom_router_series": sum(
                    1 for ln in router_prom.splitlines()
                    if ln.startswith("dts_tpu_fleet_")
                ),
                "prom_replica_series": sum(
                    1 for ln in replica_prom.splitlines()
                    if ln.startswith("dts_tpu_fleet_")
                ),
            },
        }
        if fleetobs_block is not None:
            line["fleetobs"] = fleetobs_block
        print(json.dumps(line))
    except BaseException as e:
        _log_tails(repr(e))
        raise
    finally:
        stop_traffic.set()
        if traffic is not None and traffic.is_alive():
            traffic.join(timeout=10.0)
        if rfd is not None:
            with contextlib.suppress(OSError):
                os.close(rfd)
        for p in [router_proc, *procs]:
            if p is not None and p.poll() is None:
                with contextlib.suppress(OSError):
                    p.terminate()
        deadline = time.time() + 15.0
        for p in [router_proc, *procs]:
            if p is None:
                continue
            with contextlib.suppress(Exception):
                p.wait(timeout=max(deadline - time.time(), 0.1))
            if p.poll() is None:
                with contextlib.suppress(OSError):
                    p.kill()
        shutil.rmtree(tmp, ignore_errors=True)


def main() -> None:
    if os.environ.get("SOAK_FLEET", "0") == "1":
        _fleet_soak(float(os.environ.get("SOAK_SECONDS", "30")))
        return

    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")

    import aiohttp
    import numpy as np

    from distributed_tf_serving_tpu.client import (
        PredictClientError,
        ShardedPredictClient,
        compact_payload,
        make_payload,
        make_zipfian_payloads,
        zipfian_indices,
    )
    from distributed_tf_serving_tpu.models import (
        ModelConfig,
        Servable,
        ServableRegistry,
        build_model,
        ctr_signatures,
    )
    from distributed_tf_serving_tpu.serving import DynamicBatcher, PredictionServiceImpl
    from distributed_tf_serving_tpu.serving.rest import start_rest_gateway
    from distributed_tf_serving_tpu.serving.server import create_server_async

    platform = jax.devices()[0].platform
    tpu = platform != "cpu"
    seconds = float(os.environ.get("SOAK_SECONDS", "300"))
    # Overload mode (SOAK_OVERLOAD=1): adaptive admission under ~3x
    # sustainable load with a mid-run burst; see module docstring.
    overload_mode = os.environ.get("SOAK_OVERLOAD", "0") == "1"
    overload_deadline_s = float(os.environ.get("SOAK_OVERLOAD_DEADLINE_S", "2.0"))
    dispatch_delay_s = float(
        os.environ.get("SOAK_OVERLOAD_DISPATCH_DELAY_S", "0.03")
    )
    grpc_workers = int(
        os.environ.get("SOAK_GRPC_WORKERS", "24" if overload_mode else "8")
    )
    rest_workers = int(os.environ.get("SOAK_REST_WORKERS", "4"))
    candidates = int(os.environ.get("SOAK_CANDIDATES", "1000"))
    burst_workers = int(
        os.environ.get("SOAK_OVERLOAD_BURST_WORKERS", str(max(grpc_workers // 2, 4)))
    ) if overload_mode else 0
    chaos = os.environ.get("SOAK_CHAOS", "0") == "1"
    # Cache mode (SOAK_CACHE=1): the batcher runs with the score cache +
    # single-flight + intra-batch dedup armed, and the gRPC workers switch
    # to a seeded zipfian workload (hot payloads AND hot rows recur) so
    # the hit/coalesced/dedup counters actually move. A pre-flight probe
    # pins correctness: the same payload scored uncached (the filling
    # miss) and cached (the hit) must be bit-identical.
    cache_mode = os.environ.get("SOAK_CACHE", "0") == "1"
    # Row-cache mode (SOAK_ROWCACHE=1, ISSUE 14): the cache-mode zipfian
    # workload with the ROW-GRANULAR cache armed next to the request
    # cache + dedup — distinct payloads sharing hot catalog rows execute
    # only their cold rows. The probe additionally pins row-path
    # bit-identity (disarmed reference vs row-filling miss vs
    # row-assembled hit), and the JSON line gains a `row_cache` block
    # (per-row hit/miss counters, rows_executed vs rows_requested) the
    # TIER1_ROWCACHE_SMOKE gate reads.
    rowcache_mode = os.environ.get("SOAK_ROWCACHE", "0") == "1"
    cache_mode = cache_mode or rowcache_mode
    cache_skew = float(os.environ.get("SOAK_CACHE_SKEW", "1.1"))
    util_mode = os.environ.get("SOAK_UTIL", "0") == "1"
    # Quality mode (SOAK_QUALITY=1): trained model, teacher-labeled
    # payload pools, live /labelz feedback, a pinned reference and a
    # shifted segment; see module docstring. Small requests (row digests
    # are the join keys) and no REST mixer (unshifted REST traffic would
    # dilute the drift the gate must observe) unless overridden.
    quality_mode = os.environ.get("SOAK_QUALITY", "0") == "1"
    # Lifecycle mode (SOAK_LIFECYCLE=1): trained model behind a REAL
    # version watcher + lifecycle controller; a driver publishes a good
    # then a poisoned canary and the controller must promote then roll
    # back, mid-traffic, with zero failed requests. Small requests and
    # no REST mixer, like quality mode.
    lifecycle_mode = os.environ.get("SOAK_LIFECYCLE", "0") == "1"
    # Recovery mode (SOAK_RECOVERY=1): the device-failure recovery plane
    # under live traffic on a depth-4 pipeline — a scenario driver
    # injects a WEDGE at the device stage (the watchdog must quarantine,
    # reinit, and replay with zero client-visible failures) and then a
    # content-keyed poisoned input coalesced with clean companions (the
    # bisection must fail exactly the poison with its distinct status
    # while the companions replay to success).
    recovery_mode = os.environ.get("SOAK_RECOVERY", "0") == "1"
    # Integrity mode (SOAK_INTEGRITY=1): the data-integrity plane's
    # chaos scenario — wire flips, readback bitflips, NaN rows — with
    # the recovery controller armed for the escalation path and the
    # client verifying response checksums; see module docstring.
    integrity_mode = os.environ.get("SOAK_INTEGRITY", "0") == "1"
    # Cascade mode (SOAK_CASCADE=1): multi-stage retrieval->rank through
    # serving/cascade.py on every score-filtered gRPC request — stage-1
    # two_tower over the full candidate batch, on-device prune to 25%
    # survivors, DCN over the survivor rung only. A pre-flight probe
    # pins bit-identity (survivor scores vs a full-pass reference,
    # pruned rows vs stage-1-only), and the JSON line gains a `cascade`
    # block with row dispositions + live-route probe results.
    cascade_mode = os.environ.get("SOAK_CASCADE", "0") == "1"
    if quality_mode or lifecycle_mode:
        candidates = int(os.environ.get("SOAK_CANDIDATES", "16"))
        grpc_workers = int(os.environ.get("SOAK_GRPC_WORKERS", "4"))
        rest_workers = int(os.environ.get("SOAK_REST_WORKERS", "0"))
    elif recovery_mode or integrity_mode:
        # Small bucket + modest load: each reinit round re-warms the
        # ladder, so the cycle time (and with it the client retry
        # horizon) must stay in low seconds on a CPU-only CI host.
        # (Integrity mode escalates into the same reinit cycles, and
        # its shadow_fraction=1.0 doubles the forward work besides.)
        candidates = int(os.environ.get("SOAK_CANDIDATES", "200"))
        grpc_workers = int(os.environ.get("SOAK_GRPC_WORKERS", "4"))
        rest_workers = int(os.environ.get("SOAK_REST_WORKERS", "0"))
    trace_out = os.environ.get("SOAK_TRACE_OUT", "")
    if trace_out or quality_mode:
        from distributed_tf_serving_tpu.utils import tracing

        # Quality mode needs the span plane live either way: drift
        # exemplars are span annotations, and annotated spans are what
        # the tail sampler force-keeps into /tracez.
        tracing.enable(
            buffer_size=int(os.environ.get("SOAK_TRACE_BUFFER", "256")),
            sample_rate=float(
                os.environ.get(
                    "SOAK_TRACE_SAMPLE", "0.2" if quality_mode else "0.05"
                )
            ),
            slowest_n=int(os.environ.get("SOAK_TRACE_SLOWEST", "32")),
        )
    if chaos:
        from distributed_tf_serving_tpu import faults

        faults.get().seed = int(os.environ.get("SOAK_CHAOS_SEED", "0"))
        # Low-rate, latency-shaped chaos: enough pressure to exercise the
        # failover/scoreboard/shed paths continuously, low enough that the
        # soak still measures the stack (not the injector).
        faults.get().add("client.rpc", "error", rate=0.02, code="UNAVAILABLE")
        faults.get().add("client.rpc", "delay", rate=0.05, delay_s=0.02)
        faults.get().add("batcher.dispatch", "delay", rate=0.05, delay_s=0.01)
        faults.get().add("readback", "delay", rate=0.05, delay_s=0.005)
    if overload_mode:
        from distributed_tf_serving_tpu import faults

        # Deterministic capacity: EVERY dispatch eats a fixed injected
        # delay, so "sustainable load" is ~1/delay batches/s regardless of
        # how fast this host's CPU runs the tiny soak model — the worker
        # pool above is sized ~3x that, which is the overload.
        faults.get().add(
            "batcher.dispatch", "delay", rate=1.0, delay_s=dispatch_delay_s
        )

    # Bench-scale servable on the accelerator; small on the CPU platform so
    # the one core spends its budget on the serving stack, not the forward.
    config = ModelConfig(
        name="DCN",
        num_fields=NUM_FIELDS,
        vocab_size=(1 << 20) if tpu else (1 << 14),
        embed_dim=16 if tpu else 8,
        mlp_dims=(256, 128, 64) if tpu else (16,),
        num_cross_layers=3 if tpu else 1,
        cross_full_matrix=True,
    )
    model = build_model("dcn_v2", config)
    quality_monitor = None
    q_window_s = max(seconds * 0.35, 3.0)
    if quality_mode or lifecycle_mode:
        # Train briefly on the synthetic stream so the served scores
        # carry REAL signal against the stream's teacher labels — a
        # random-init model would pin the label-feedback AUC at ~0.5 and
        # the gate would measure nothing.
        from distributed_tf_serving_tpu.serving.quality import QualityMonitor
        from distributed_tf_serving_tpu.train import Trainer
        from distributed_tf_serving_tpu.train.data import SyntheticCTRConfig

        # Dense id catalog (the bench's CPU train_id_space): each id gets
        # enough noisy Bernoulli views inside a short fit that the model
        # actually generalizes — at the full vocab the same steps leave
        # AUC at coin-flip (bench.py train_on_chip's finding).
        stream_cfg = SyntheticCTRConfig(
            num_fields=NUM_FIELDS,
            id_space=min(1 << 12, config.vocab_size),
            seed=7,
        )
        trainer = Trainer(model, stream_config=stream_cfg, learning_rate=3e-3)
        fit = trainer.fit(
            steps=int(os.environ.get("SOAK_QUALITY_TRAIN_STEPS", "400")),
            batch_size=256,
        )
        print(
            f"# {'lifecycle' if lifecycle_mode else 'quality'} soak: "
            f"trained {fit['steps']} steps, loss={fit['loss']:.4f}",
            file=sys.stderr,
        )
        params = trainer.snapshot_params()
        if lifecycle_mode:
            # Long window (everything stays in-window for the soak's
            # horizon): the lifecycle controller reads pair_drift /
            # version_auc with ITS OWN evidence floor, so the monitor's
            # drift cadence only feeds the passive surfaces here.
            quality_monitor = QualityMonitor(
                window_s=max(seconds, 10.0),
                slices=4,
                drift_check_interval_s=0.5,
                min_drift_count=60,
            )
        else:
            quality_monitor = QualityMonitor(
                # Short window so the post-shift window is dominated by
                # shifted traffic well before the soak ends; fast drift
                # cadence so short CI smokes (~12 s) get several ticks.
                window_s=q_window_s,
                slices=4,
                drift_check_interval_s=max(seconds / 24, 0.25),
                drift_threshold_psi=float(
                    os.environ.get("SOAK_QUALITY_PSI_THRESHOLD", "0.2")
                ),
                exemplar_traces=8,
            )
    else:
        params = jax.jit(model.init)(jax.random.PRNGKey(0))
    registry = ServableRegistry()
    servable = Servable(
        name="DCN", version=1, model=model, params=params,
        signatures=ctr_signatures(NUM_FIELDS),
    )
    if not lifecycle_mode:
        # Lifecycle mode serves through the WATCHED base dir instead: the
        # trained servable lands as version 1 on disk below, and the real
        # VersionWatcher loads (and queue-warms) it like production.
        registry.load(servable)
    score_cache = None
    row_cache = None
    if cache_mode:
        from distributed_tf_serving_tpu.cache import ScoreCache

        # TTL comfortably past the soak horizon: this mode measures the
        # cache plane's behavior under load, not TTL churn (TTL/eviction
        # correctness is tests/test_cache.py's job).
        score_cache = ScoreCache(ttl_s=max(seconds * 2, 600.0))
        if rowcache_mode:
            from distributed_tf_serving_tpu.cache import RowScoreCache

            row_cache = RowScoreCache(ttl_s=max(seconds * 2, 600.0))
    elif overload_mode:
        from distributed_tf_serving_tpu.cache import ScoreCache

        # SHORT TTL on purpose: hot zipfian entries must actually expire
        # mid-soak so the brownout stale-serve window (entries past TTL
        # still answering while pressure > NOMINAL) gets exercised.
        score_cache = ScoreCache(
            ttl_s=float(os.environ.get("SOAK_OVERLOAD_CACHE_TTL_S", "1.5"))
        )
    overload_ctrl = None
    if overload_mode:
        from distributed_tf_serving_tpu.utils.config import OverloadConfig

        # Faster-than-default control cadence so short CI smokes (8-12s)
        # traverse NOMINAL -> BROWNOUT and shed well inside the run.
        overload_ctrl = OverloadConfig(
            enabled=True,
            target_queue_wait_ms=float(
                os.environ.get("SOAK_OVERLOAD_TARGET_MS", "50")
            ),
            adjust_interval_s=0.25,
            brownout_after_intervals=3,
            shed_after_intervals=10,
            recover_after_intervals=8,
            stale_while_overloaded_s=float(
                os.environ.get("SOAK_OVERLOAD_STALE_S", "60")
            ),
            # Tighter-than-auto ceiling: the limit starts at max and only
            # ratchets DOWN from observed queue wait, so the static-bound
            # default (16x the largest bucket) would let the opening
            # stampede queue several seconds deep — blowing every client
            # deadline before the controller's first shrink tick.
            max_limit_candidates=int(
                os.environ.get("SOAK_OVERLOAD_MAX_LIMIT", "6144")
            ),
            # Let the limit shrink BELOW one largest bucket (the auto min):
            # at 1024 the sheddable lane's ceiling (0.7x) is smaller than
            # one 1000-candidate request, so sustained pressure visibly
            # sheds the sheddable lane — the ordering the smoke gate reads.
            min_limit_candidates=int(
                os.environ.get("SOAK_OVERLOAD_MIN_LIMIT", "1024")
            ),
        ).build()
    ledger = None
    if util_mode:
        from distributed_tf_serving_tpu.serving.utilization import OccupancyLedger
        from distributed_tf_serving_tpu.utils import tracing as tracing_mod

        ledger = OccupancyLedger(device=str(jax.devices()[0]))
        # Counter-track source: a SOAK_TRACE_OUT export then carries the
        # per-device occupancy track next to the request spans.
        tracing_mod.register_counter_source(ledger)
    if lifecycle_mode:
        # One small bucket: three versions each warm the ladder through
        # the queue mid-soak, and the candidates are 16-row requests.
        buckets = (64,)
    elif recovery_mode or integrity_mode:
        # One small bucket: every reinit round re-warms the whole ladder
        # through the queue, and the recovery cycle must finish inside
        # the client retry horizon.
        buckets = (256,)
    elif cascade_mode:
        # A survivor rung BELOW the candidate rung: the cascade's win is
        # stage-2 traffic landing in the smaller bucket (25% of 1000
        # candidates packs into 256), so the ladder must carry one.
        buckets = (256, 1024, 2048) if tpu else (256, 1024)
    else:
        buckets = (1024, 2048, 4096, 8192, 16384) if tpu else (1024, 2048)
    batcher_kw = {}
    if recovery_mode:
        # The acceptance scenario: a wedge at PIPELINE DEPTH 4 — several
        # batches in flight behind the stuck one, all captured + replayed.
        batcher_kw = dict(
            pipeline_depth=4, inflight_window=4, buffer_ring=True
        )
    batcher = DynamicBatcher(
        buckets=buckets, max_wait_us=2000, completion_workers=12,
        score_cache=score_cache, row_cache=row_cache, dedup=cache_mode,
        overload=overload_ctrl,
        utilization=ledger, quality=quality_monitor, **batcher_kw,
    ).start()
    batcher.max_batch_candidates = buckets[-1]
    if not lifecycle_mode:
        for b in buckets:
            batcher.warmup(servable, buckets=(b,))
            batcher.submit(
                servable,
                compact_payload(batcher.warmup_arrays(servable, b), config.vocab_size),
                _warmup=True,
            ).result(timeout=600)

    lifecycle_block: dict = {}
    lifecycle_ctrl = None
    lifecycle_watcher = None
    lc_pool: list = []
    if lifecycle_mode:
        import tempfile

        from distributed_tf_serving_tpu.serving.lifecycle import (
            LifecycleController,
        )
        from distributed_tf_serving_tpu.serving.server import (
            _servable_change_hook,
        )
        from distributed_tf_serving_tpu.serving.version_watcher import (
            VersionWatcher,
            VersionWatcherConfig,
        )
        from distributed_tf_serving_tpu.train.checkpoint import save_servable
        from distributed_tf_serving_tpu.train.data import SyntheticCTRStream
        from distributed_tf_serving_tpu.utils.config import LifecycleConfig

        lc_base = tempfile.mkdtemp(prefix="soak_lifecycle_")
        save_servable(os.path.join(lc_base, "1"), servable, kind="dcn_v2")
        lifecycle_watcher = VersionWatcher(
            lc_base, registry,
            VersionWatcherConfig(
                poll_interval_s=float(
                    os.environ.get("SOAK_LIFECYCLE_POLL_S", "0.5")
                ),
                model_name="DCN", model_kind="dcn_v2",
            ),
            # Queue warmup: each hot-loaded version compiles on the
            # batching thread BEFORE its registry flip, exactly like the
            # production server — a canary's first live request must not
            # pay the jit.
            warmup=batcher.warmup_via_queue,
            model_config=config,
            on_servable_change=_servable_change_hook(None, quality_monitor),
        ).start()
        lifecycle_ctrl = LifecycleController(
            LifecycleConfig(
                enabled=True,
                tick_interval_s=0.2,
                canary_probe_only_s=0.6,
                canary_initial_fraction=0.25,
                canary_ramp_step=0.25,
                canary_step_dwell_s=0.5,
                canary_max_fraction=0.5,
                promote_after_s=float(
                    os.environ.get("SOAK_LIFECYCLE_PROMOTE_AFTER", "2.0")
                ),
                min_canary_scores=int(
                    os.environ.get("SOAK_LIFECYCLE_MIN_SCORES", "120")
                ),
                rollback_psi=float(
                    os.environ.get("SOAK_LIFECYCLE_ROLLBACK_PSI", "0.4")
                ),
                rollback_hold_s=0.5,
            ),
            registry=registry,
            model_name="DCN",
            watcher=lifecycle_watcher,
            quality=quality_monitor,
        ).start()
        # Steady payload pool from the trained distribution: both
        # versions' sketches fill with in-distribution scores, so a
        # healthy canary reads as pair PSI ~ 0 and a poisoned one does
        # not hide behind workload drift.
        lc_stream = SyntheticCTRStream(stream_cfg)
        for i in range(int(os.environ.get("SOAK_LIFECYCLE_POOL", "24"))):
            b = lc_stream.batch(candidates, 5_000 + i)
            lc_pool.append(
                {"feat_ids": b["feat_ids"], "feat_wts": b["feat_wts"]}
            )
    impl = PredictionServiceImpl(registry, batcher)
    if lifecycle_mode:
        impl.lifecycle = lifecycle_ctrl
        impl.version_watcher = lifecycle_watcher

    recovery_block: dict = {}
    recovery_ctrl = None
    if recovery_mode or integrity_mode:
        from distributed_tf_serving_tpu.serving.recovery import (
            RecoveryController,
        )
        from distributed_tf_serving_tpu.utils.config import RecoveryConfig

        # Integrity mode arms the SAME controller: the plane's screen
        # threshold and shadow mismatches escalate through take_group
        # into the output_corrupt quarantine->reinit->replay cycle.
        recovery_ctrl = RecoveryController(
            RecoveryConfig(
                enabled=True,
                watchdog_interval_s=0.2,
                wedge_quarantine_s=float(
                    os.environ.get("SOAK_RECOVERY_WEDGE_S", "1.0")
                ),
                replay_drain_s=15.0,
            ),
            batcher, registry=registry, impl=impl,
        ).start()
        impl.recovery = recovery_ctrl

    quality_block: dict = {}
    q_pools: dict = {}
    if quality_mode:
        # Warmup exclusion is an acceptance criterion: the bucket-ladder
        # warmups above went through the full completer path, and the
        # sketch must have seen NONE of them.
        quality_block["observed_after_warmup"] = quality_monitor.observed_requests
        # Payload pools from the synthetic stream, with each row's label
        # generated from the KNOWN teacher (the data-gen's own Bernoulli)
        # and each row's join key digested client-side over the exact
        # arrays sent. The shifted pool scales feature weights (the
        # teacher is linear in weights, so ranking — and therefore AUC —
        # survives while the score DISTRIBUTION saturates outward), with
        # labels regenerated from the teacher on the shifted rows.
        from distributed_tf_serving_tpu.client import label_keys
        from distributed_tf_serving_tpu.train.data import (
            SyntheticCTRStream,
            _sigmoid,
        )

        q_stream = SyntheticCTRStream(stream_cfg)
        pool_n = int(os.environ.get("SOAK_QUALITY_POOL", "32"))
        shift_scale = float(os.environ.get("SOAK_QUALITY_SHIFT_SCALE", "3.0"))
        for phase, (offset, scale) in enumerate(
            ((0, 1.0), (100_000, shift_scale))
        ):
            payloads, labels, keys = [], [], []
            for i in range(pool_n):
                b = q_stream.batch(candidates, offset + i)
                wts = (b["feat_wts"] * scale).astype(np.float32)
                score = q_stream._teacher_score(b["feat_ids"], wts)
                rng = np.random.RandomState(7_000_003 + offset + i)
                row_labels = (
                    rng.rand(candidates) < _sigmoid(score)
                ).astype(np.float32)
                payload = {"feat_ids": b["feat_ids"], "feat_wts": wts}
                payloads.append(payload)
                labels.append(row_labels)
                keys.append(label_keys(payload))
            q_pools[phase] = (payloads, labels, keys)

    wide = make_payload(candidates=candidates, num_fields=NUM_FIELDS)
    compact = compact_payload(wide, config.vocab_size)
    unique_pool = [
        make_payload(candidates=candidates, num_fields=NUM_FIELDS, seed=500 + i)
        for i in range(32)
    ]
    cache_block: dict = {}
    zipf_pool, zipf_sched = None, None
    use_zipf = cache_mode or overload_mode
    if use_zipf:
        # Zipfian workload: hot payloads repeat (score-cache hits +
        # coalescing) and hot rows recur across distinct payloads
        # (intra-batch dedup). Seeded, so reruns replay the same stream.
        # Overload mode rides the same stream but over a WIDER pool: hot
        # keys + a short cache TTL make brownout stale-serve observable,
        # while the cold tail keeps real misses flowing into admission so
        # the shed path stays exercised (a fully-cached pool would let
        # stale-serve absorb everything and the gate's shed counter idle).
        zipf_pool = make_zipfian_payloads(
            int(os.environ.get("SOAK_OVERLOAD_POOL", "128"))
            if overload_mode and not cache_mode else 32,
            candidates, NUM_FIELDS, skew=cache_skew,
            seed=int(os.environ.get("SOAK_CACHE_SEED", "0")),
            catalog=max(candidates * 4, 256),
        )
        zipf_sched = zipfian_indices(
            4096, len(zipf_pool), skew=cache_skew,
            seed=int(os.environ.get("SOAK_CACHE_SEED", "0")) + 1,
        )
    if cache_mode:
        # Pre-flight bit-identity probe through the real batcher. The
        # reference is computed with the WHOLE cache plane disarmed
        # (score cache detached, dedup off) — comparing a cached copy
        # against its own filling miss would be tautological and blind to
        # a dedup/scatter bug changing answers. Then the same payload runs
        # armed: the miss (dedup path, fills) and the hit (cached copy)
        # must both be bit-identical to the disarmed reference.
        probe = zipf_pool[0]
        batcher.score_cache, batcher.dedup = None, False
        batcher.row_cache = None
        ref = batcher.submit(
            servable, probe, output_keys=("prediction_node",)
        ).result(timeout=600)["prediction_node"]
        batcher.score_cache, batcher.dedup = score_cache, True
        batcher.row_cache = row_cache
        miss = batcher.submit(
            servable, probe, output_keys=("prediction_node",)
        ).result(timeout=600)["prediction_node"]
        hit = batcher.submit(
            servable, probe, output_keys=("prediction_node",)
        ).result(timeout=600)["prediction_node"]
        cache_block["scores_match"] = bool(
            np.array_equal(ref, miss) and np.array_equal(ref, hit)
        )
        if rowcache_mode:
            # Row-path bit-identity: with the REQUEST cache detached, the
            # same payload must answer identically from a flushed row
            # cache (the filling miss — every row cold) and from the
            # fully-warm row cache (zero device work, pure assembly).
            batcher.score_cache, batcher.dedup = None, False
            row_cache.flush()
            row_miss = batcher.submit(
                servable, probe, output_keys=("prediction_node",)
            ).result(timeout=600)["prediction_node"]
            row_hit = batcher.submit(
                servable, probe, output_keys=("prediction_node",)
            ).result(timeout=600)["prediction_node"]
            batcher.score_cache, batcher.dedup = score_cache, True
            cache_block["row_scores_match"] = bool(
                np.array_equal(ref, row_miss)
                and np.array_equal(ref, row_hit)
            )
            cache_block["row_probe_snapshot"] = {
                k: row_cache.snapshot()[k]
                for k in ("hits", "misses", "coalesced",
                          "rows_requested", "rows_executed")
            }
        # Counter baseline AFTER the probe: the reported hit/miss/coalesced
        # workload numbers (and the CI gate) must come from worker traffic,
        # not from the probe's guaranteed hit.
        cache_block["probe_snapshot"] = {
            k: score_cache.snapshot()[k]
            for k in ("hits", "misses", "coalesced")
        }
    cascade_block: dict = {}
    if cascade_mode:
        import dataclasses

        from distributed_tf_serving_tpu.models import build_model
        from distributed_tf_serving_tpu.serving.cascade import (
            STAGE2,
            CascadeOrchestrator,
        )

        # The stage-1 servable is an ordinary registry entry under its
        # own name — exactly how build_stack publishes it — scored over
        # the candidate rung(s) while stage 2 runs the survivor rung.
        s1_config = dataclasses.replace(config, name="stage1")
        s1_model = build_model("two_tower", s1_config)
        s1_params = jax.jit(s1_model.init)(jax.random.PRNGKey(3))
        stage1 = Servable(
            name="stage1", version=1, model=s1_model, params=s1_params,
            signatures=ctr_signatures(NUM_FIELDS),
        )
        registry.load(stage1)
        for b in buckets[1:]:
            batcher.warmup(stage1, buckets=(b,))
        impl.cascade = CascadeOrchestrator(
            registry, batcher, stage1_model="stage1",
            survivor_fraction=0.25,
        )
        # Pre-flight bit-identity probe (the gate's correctness bar):
        # the cascade's survivor rows must be byte-equal to the SAME
        # rows of a cascade-off full pass, and its pruned rows
        # byte-equal to a stage-1-only pass — or the cascade is
        # changing answers, not saving work.
        probe = unique_pool[0]
        sk = servable.model.score_output
        s1k = s1_model.score_output
        out = impl.cascade.run(impl, servable, probe, (sk,), None, None)
        ref = impl._run(servable, probe, output_keys=(sk,))
        ref1 = impl._run(stage1, probe, output_keys=(s1k,))
        surv = out["cascade_stage"] == STAGE2
        cascade_block["scores_match"] = bool(
            np.array_equal(out[sk][surv], ref[sk][surv])
            and np.array_equal(
                out[sk][~surv], ref1[s1k].astype(np.float32)[~surv]
            )
        )
        # Counter baseline AFTER the probe: the gate reads workload
        # deltas, so the probe's guaranteed prune can never green-wash
        # a cascade idle under load.
        cascade_block["probe_snapshot"] = {
            k: impl.cascade.snapshot()[k]
            for k in ("requests", "rows_requested", "rows_ranked",
                      "pruned_rows")
        }

    integrity_block: dict = {}
    integrity_plane = None
    integrity_ref: dict = {}
    if integrity_mode:
        from distributed_tf_serving_tpu.utils.config import IntegrityConfig

        integrity_plane = IntegrityConfig(
            enabled=True,
            shadow_fraction=float(
                os.environ.get("SOAK_INTEGRITY_SHADOW", "1.0")
            ),
            screen_trips_per_window=int(
                os.environ.get("SOAK_INTEGRITY_TRIPS", "3")
            ),
            screen_window_s=5.0,
        ).build()
        batcher.integrity = integrity_plane
        impl.integrity = integrity_plane
        # Pre-flight CLEAN bit-identity probe (the gate's correctness
        # bar): the plane must never change answers. Reference with the
        # plane detached; then the same payload armed — with a FORCED
        # shadow audit so the compare path itself runs — must answer
        # byte-identically.
        probe = unique_pool[0]
        batcher.integrity = None
        ref = batcher.submit(
            servable, probe, output_keys=("prediction_node",)
        ).result(timeout=600)["prediction_node"]
        batcher.integrity = integrity_plane
        integrity_plane.request_audit()
        armed = batcher.submit(
            servable, probe, output_keys=("prediction_node",)
        ).result(timeout=600)["prediction_node"]
        integrity_ref["scores"] = ref
        integrity_block["clean_bit_identical"] = bool(
            np.array_equal(ref, armed)
        )
        integrity_block["probe_audits_run"] = (
            integrity_plane.snapshot()["shadow"]["audits_run"]
        )

    rest_cols = {
        "feat_ids": wide["feat_ids"][:64].tolist(),
        "feat_wts": wide["feat_wts"][:64].tolist(),
    }
    rest_examples = [
        {"feat_ids": wide["feat_ids"][i].tolist(),
         "feat_wts": wide["feat_wts"][i].tolist()}
        for i in range(8)
    ]

    # Sampled request logging under load (SOAK_REQUEST_LOG_SAMPLING > 0,
    # OPT-IN so default soaks stay comparable to prior rounds' baselines):
    # the bounded-queue writer must keep up or shed cleanly while every
    # surface hammers the impl.
    request_logger = None
    log_sampling = float(os.environ.get("SOAK_REQUEST_LOG_SAMPLING", "0"))
    if log_sampling > 0:
        import tempfile

        from distributed_tf_serving_tpu.serving.request_log import RequestLogger

        log_path = os.path.join(tempfile.gettempdir(), f"soak_requests_{os.getpid()}.log")
        request_logger = RequestLogger(log_path, sampling_rate=log_sampling)
        impl.request_logger = request_logger

    counts = {
        "grpc_ok": 0, "grpc_err": 0,
        "rest_ok": 0, "rest_err": 0,
        "control_ok": 0, "control_err": 0,
        "errors": {},
    }
    rss_start = rss_gb()
    deadline = time.perf_counter() + seconds

    def note_error(kind: str, detail: str) -> None:
        counts[f"{kind}_err"] += 1
        key = detail[:120]
        counts["errors"][key] = counts["errors"].get(key, 0) + 1

    async def one_grpc_request(client, wid: int, i: int) -> None:
        if use_zipf:
            # Seeded zipfian stream: worker w walks the schedule from
            # its own offset, so concurrent workers frequently hold
            # the SAME hot payload in flight (single-flight coverage)
            # while the tail keeps misses coming.
            payload = zipf_pool[
                zipf_sched[(wid * 997 + i) % len(zipf_sched)]
            ]
        else:
            # Interleave regimes every 7 requests, like the r4 soak:
            # the cache's regime detector must ride the transitions
            # without false bypass or stale hits.
            phase = (i // 7 + wid) % 3
            payload = (
                wide, compact, unique_pool[(i + wid) % len(unique_pool)]
            )[phase]
        try:
            await client.predict(payload, sort_scores=True)
            counts["grpc_ok"] += 1
        except PredictClientError as e:
            note_error("grpc", f"{getattr(e.code, 'name', e.code)}: {e}")
        except Exception as e:  # noqa: BLE001 — taxonomy, keep soaking
            note_error("grpc", f"{type(e).__name__}: {e}")

    async def grpc_worker(client, wid: int):
        if overload_mode:
            # Staggered ramp: real load arrives as a ramp, not a step.
            # An instantaneous 24-worker stampede onto a cold controller
            # (limit still at max, no service-time EWMA yet) would queue
            # past every deadline before the first shrink tick.
            await asyncio.sleep(min(wid, 40) * 0.05)
        i = 0
        while time.perf_counter() < deadline:
            i += 1
            await one_grpc_request(client, wid, i)

    # Mid-run burst (overload mode): extra workers spike the offered load
    # from 40% to 70% of the soak — the adaptive limit must absorb the
    # step up (shed harder / brown out) and recover after it steps down.
    burst_t0 = deadline - seconds * 0.6
    burst_t1 = deadline - seconds * 0.3

    async def burst_worker(client, wid: int):
        now = time.perf_counter()
        if now < burst_t0:
            await asyncio.sleep(burst_t0 - now)
        i = 0
        while time.perf_counter() < min(burst_t1, deadline):
            i += 1
            await one_grpc_request(client, 1000 + wid, i)

    async def rest_worker(session, wid: int):
        i = 0
        while time.perf_counter() < deadline:
            i += 1
            try:
                if (i + wid) % 5 == 0:
                    async with session.post(
                        "/v1/models/DCN:classify", json={"examples": rest_examples}
                    ) as r:
                        body = await r.json()
                        ok = r.status == 200 and len(body.get("results", ())) == len(rest_examples)
                else:
                    async with session.post(
                        "/v1/models/DCN:predict", json={"inputs": rest_cols}
                    ) as r:
                        body = await r.json()
                        ok = r.status == 200 and "outputs" in body
                if ok:
                    counts["rest_ok"] += 1
                else:
                    note_error("rest", f"http {r.status}: {json.dumps(body)[:80]}")
            except Exception as e:  # noqa: BLE001 — taxonomy, keep soaking
                note_error("rest", f"{type(e).__name__}: {e}")

    # Quality mode: (score, label, t) log the gate's OFFLINE exact-AUC
    # baseline is computed from, and once-per-round labeling bookkeeping.
    quality_log: list[tuple[float, float, float]] = []
    q_labeled: set = set()
    q_shift_t = deadline - seconds * (
        1.0 - float(os.environ.get("SOAK_QUALITY_SHIFT_AT", "0.55"))
    )
    q_round_s = max(q_window_s / 3.0, 1.0)

    async def quality_worker(client, session, wid: int):
        i = 0
        while time.perf_counter() < deadline:
            i += 1
            now = time.perf_counter()
            phase = 0 if now < q_shift_t else 1
            payloads, labels_pool, keys_pool = q_pools[phase]
            idx = (wid * 131 + i) % len(payloads)
            try:
                scores = await client.predict(payloads[idx], sort_scores=False)
                counts["grpc_ok"] += 1
            except PredictClientError as e:
                note_error("grpc", f"{getattr(e.code, 'name', e.code)}: {e}")
                continue
            except Exception as e:  # noqa: BLE001 — taxonomy, keep soaking
                note_error("grpc", f"{type(e).__name__}: {e}")
                continue
            # Label each payload once per labeling round (and afresh per
            # phase): the reservoir keeps refreshing, the windowed AUC
            # always has recent pairs, and the same label is never
            # spammed every request.
            round_id = int((now - (deadline - seconds)) / q_round_s)
            mark = (phase, idx, round_id)
            if mark in q_labeled:
                continue
            q_labeled.add(mark)
            row_labels = labels_pool[idx]
            try:
                async with session.post("/labelz", json={"labels": [
                    {"id": key, "label": float(lb)}
                    for key, lb in zip(keys_pool[idx], row_labels)
                ]}) as r:
                    body = await r.json()
                    if r.status != 200:
                        note_error("rest", f"labelz http {r.status}: {body}")
                        continue
                t = time.monotonic()
                quality_log.extend(
                    (float(s), float(lb), t)
                    for s, lb in zip(np.asarray(scores).ravel(), row_labels)
                )
            except Exception as e:  # noqa: BLE001 — taxonomy, keep soaking
                note_error("rest", f"labelz {type(e).__name__}: {e}")

    async def quality_pin(session):
        """Pin the drift reference over LIVE HTTP at ~40% — steady
        traffic only, so the shifted segment drifts AGAINST it."""
        pin_at = float(os.environ.get("SOAK_QUALITY_PIN_AT", "0.40"))
        await asyncio.sleep(seconds * pin_at)
        try:
            async with session.post("/qualityz/snapshot") as r:
                quality_block["pin"] = await r.json()
        except Exception as e:  # noqa: BLE001 — report, keep line
            quality_block["pin"] = {"error": f"{type(e).__name__}: {e}"}

    async def probe_quality(session) -> None:
        """End-of-run probes against the LIVE surfaces (the bytes an
        operator's curl would get): /qualityz, the ?section= monitoring
        filter, /tracez exemplar annotations, and the Prometheus text
        (written to disk for the exposition lint)."""
        async with session.get("/qualityz") as r:
            qz = await r.json()
        quality_block["qualityz"] = qz
        async with session.get("/monitoring?section=quality") as r:
            sec = await r.json()
            quality_block["section_filter_ok"] = (
                r.status == 200
                and set(sec) == {"quality"}
                and bool(sec["quality"].get("enabled"))
            )
        async with session.get("/tracez?limit=200") as r:
            tz_raw = await r.read()
        quality_block["exemplar_traces"] = tz_raw.count(b'"quality.drift"')
        async with session.get("/monitoring/prometheus/metrics") as r:
            prom_text = await r.text()
        prom_out = os.environ.get(
            "SOAK_QUALITY_PROM_OUT",
            os.path.join(
                __import__("tempfile").gettempdir(),
                f"soak_quality_prom_{os.getpid()}.txt",
            ),
        )
        with open(prom_out, "w") as f:
            f.write(prom_text)
        quality_block["prom_path"] = prom_out
        quality_block["prom_quality_series"] = sum(
            1 for ln in prom_text.splitlines()
            if ln.startswith("dts_tpu_quality_")
        )

    async def lifecycle_worker(client, wid: int):
        """Steady in-distribution gRPC traffic for lifecycle mode; worker
        0 rides the probe criticality lane, so a fresh canary gets its
        first real traffic the moment CANARY is entered."""
        i = 0
        while time.perf_counter() < deadline:
            i += 1
            payload = lc_pool[(wid * 131 + i) % len(lc_pool)]
            try:
                await client.predict(payload, sort_scores=False)
                counts["grpc_ok"] += 1
            except PredictClientError as e:
                note_error("grpc", f"{getattr(e.code, 'name', e.code)}: {e}")
            except Exception as e:  # noqa: BLE001 — taxonomy, keep soaking
                note_error("grpc", f"{type(e).__name__}: {e}")

    async def lifecycle_driver():
        """The scenario script: publish a GOOD fine-tuned canary (must
        auto-promote), then a POISONED one (must auto-rollback +
        blacklist), all against live traffic."""
        import dataclasses as dc

        from distributed_tf_serving_tpu.interop.export import publish_version
        from distributed_tf_serving_tpu.train.checkpoint import (
            save_servable as save_ckpt,
        )
        from distributed_tf_serving_tpu.train.publisher import (
            publish_finetuned,
        )

        loop_ = asyncio.get_running_loop()
        await asyncio.sleep(
            seconds * float(os.environ.get("SOAK_LIFECYCLE_PUBLISH_AT", "0.10"))
        )
        # --- good canary: the REAL fine-tune publisher path -------------
        stable_sv = registry.resolve("DCN")
        good = await loop_.run_in_executor(None, lambda: publish_finetuned(
            lc_base, stable_sv, kind="dcn_v2",
            steps=int(os.environ.get("SOAK_LIFECYCLE_FT_STEPS", "25")),
            batch_size=128, learning_rate=1e-4, seed=1,
            stream_config=stream_cfg,
        ))
        good_v = good["version"]
        lifecycle_block["published_good"] = {
            "version": good_v, "steps": good["steps"],
            "loss": round(good.get("loss", 0.0), 4),
        }
        t0 = time.perf_counter()
        while time.perf_counter() < deadline - seconds * 0.25:
            snap = lifecycle_ctrl.snapshot()
            if snap["counters"]["promotes"] >= 1 and snap["state"] == "idle" \
                    and snap["stable_version"] == good_v:
                break
            await asyncio.sleep(0.15)
        lifecycle_block["promote_wait_s"] = round(time.perf_counter() - t0, 2)
        lifecycle_block["promoted_version"] = (
            lifecycle_ctrl.snapshot()["stable_version"]
        )
        # --- poisoned canary: params scaled -> saturated scores ---------
        import jax as jax_mod

        poisoned_sv = registry.resolve("DCN")
        poisoned_params = jax_mod.tree_util.tree_map(
            lambda a: a * 1.8, poisoned_sv.params
        )

        def publish_poisoned():
            def write(tmp):
                save_ckpt(
                    tmp,
                    dc.replace(
                        poisoned_sv, params=poisoned_params,
                        version=good_v + 1,
                    ),
                    kind="dcn_v2",
                )
            v, p = publish_version(lc_base, write, at_least=good_v + 1)
            return {"version": v, "path": p}

        bad = await loop_.run_in_executor(None, publish_poisoned)
        lifecycle_block["published_poisoned"] = {"version": bad["version"]}
        t0 = time.perf_counter()
        while time.perf_counter() < deadline - 1.5:
            if lifecycle_ctrl.snapshot()["counters"]["rollbacks"] >= 1:
                break
            await asyncio.sleep(0.15)
        lifecycle_block["rollback_wait_s"] = round(time.perf_counter() - t0, 2)
        # Blacklist persistence: the bad version's directory still sits
        # READY on disk — let several watcher reconcile passes run and
        # prove it stays retired.
        await asyncio.sleep(
            3 * float(os.environ.get("SOAK_LIFECYCLE_POLL_S", "0.5")) + 0.2
        )
        post = registry.models().get("DCN", [])
        lifecycle_block["post_rollback_versions"] = post
        lifecycle_block["blacklist_survived_reconcile"] = (
            bad["version"] not in post
        )

    async def probe_lifecycle(session) -> None:
        """End-of-run probes against the LIVE surfaces (the bytes an
        operator's curl would get): /lifecyclez, the ?section= filter,
        and the dts_tpu_lifecycle_* Prometheus series."""
        async with session.get("/lifecyclez") as r:
            lz = await r.json()
        lifecycle_block["lifecyclez_enabled"] = bool(lz.get("enabled"))
        lifecycle_block["state"] = lz.get("state")
        lifecycle_block["stable_version"] = lz.get("stable_version")
        lifecycle_block["counters"] = lz.get("counters")
        lifecycle_block["last_rollback"] = lz.get("last_rollback")
        lifecycle_block["blacklisted"] = (
            (lz.get("watcher") or {}).get("blacklisted", [])
        )
        async with session.get("/monitoring?section=lifecycle") as r:
            sec = await r.json()
            lifecycle_block["section_filter_ok"] = (
                r.status == 200
                and set(sec) == {"lifecycle"}
                and bool(sec["lifecycle"].get("enabled"))
            )
        async with session.get("/monitoring/prometheus/metrics") as r:
            prom_text = await r.text()
        lifecycle_block["prom_lifecycle_series"] = sum(
            1 for ln in prom_text.splitlines()
            if ln.startswith("dts_tpu_lifecycle_")
        )

    async def recovery_driver(client):
        """The scenario script: (1) wedge the device stage mid-run — the
        watchdog must quarantine, reinit, and replay with the in-flight
        depth-4 pipeline's work answered, MTTR measured to the first
        post-recovery success; (2) submit a content-keyed poisoned input
        coalesced with clean companions — the bisection must fail exactly
        the poison (PoisonedInputError) while the companions score."""
        from distributed_tf_serving_tpu import faults as faults_mod
        from distributed_tf_serving_tpu.serving.batcher import (
            PoisonedInputError,
            poison_fault_key,
            prepare_inputs,
        )

        loop_ = asyncio.get_running_loop()
        # --- phase 1: wedge at pipeline depth 4 -------------------------
        await asyncio.sleep(
            seconds * float(os.environ.get("SOAK_RECOVERY_WEDGE_AT", "0.3"))
        )
        t_inject = time.perf_counter()
        # delay_s doubles as the stranded thread's safety release; count=1
        # so the REPLAYED batch does not re-wedge.
        faults_mod.get().add(
            "batcher.dispatch", "wedge", delay_s=10.0, count=1
        )
        recovery_block["wedge_injected"] = True
        while time.perf_counter() < deadline:
            if recovery_ctrl.snapshot()["counters"]["quarantines"] >= 1:
                break
            await asyncio.sleep(0.05)
        recovery_block["quarantine_wait_s"] = round(
            time.perf_counter() - t_inject, 3
        )
        while time.perf_counter() < deadline:
            if (recovery_ctrl.state() == "serving"
                    and not recovery_ctrl.cycle_active()):
                break
            await asyncio.sleep(0.05)
        probe = make_payload(candidates=64, num_fields=NUM_FIELDS, seed=901)
        while time.perf_counter() < deadline:
            try:
                await client.predict(probe)
                break
            except Exception:  # noqa: BLE001 — still recovering
                await asyncio.sleep(0.05)
        recovery_block["mttr_s"] = round(time.perf_counter() - t_inject, 3)
        faults_mod.get().clear("batcher.dispatch")
        # --- phase 2: poisoned input + bisection ------------------------
        poison = make_payload(candidates=32, num_fields=NUM_FIELDS, seed=777)
        companions = [
            make_payload(candidates=32, num_fields=NUM_FIELDS, seed=778 + i)
            for i in range(2)
        ]
        key = poison_fault_key(
            prepare_inputs(model, poison, fold_ids=False)
        )
        faults_mod.get().add(
            "device_lost", "error", code="DATA_LOSS", key=key
        )

        def submit_all():
            # Companions first, poison in the middle, tight sequence: all
            # three land inside one 2ms coalesce window, so the first
            # kill hits a MULTI-request batch and the bisection has
            # something to split.
            f1 = batcher.submit(servable, companions[0])
            fp = batcher.submit(servable, poison)
            f2 = batcher.submit(servable, companions[1])
            return fp, [f1, f2]

        fp, fcs = await loop_.run_in_executor(None, submit_all)

        def harvest():
            out = {"poisoned": False, "companions_ok": 0}
            try:
                fp.result(timeout=90)
                out["poison_error"] = "succeeded (rule did not fire?)"
            except PoisonedInputError:
                out["poisoned"] = True
            except Exception as e:  # noqa: BLE001 — report the taxonomy
                out["poison_error"] = type(e).__name__
            for fc in fcs:
                try:
                    fc.result(timeout=90)
                    out["companions_ok"] += 1
                except Exception as e:  # noqa: BLE001
                    out.setdefault("companion_errors", []).append(
                        type(e).__name__
                    )
            return out

        recovery_block["poison"] = await loop_.run_in_executor(None, harvest)
        faults_mod.get().clear("device_lost")

    async def probe_recovery(session) -> None:
        """End-of-run probes against the LIVE surfaces: /recoveryz, the
        ?section= filter, and the dts_tpu_recovery_* Prometheus series."""
        async with session.get("/recoveryz") as r:
            rz = await r.json()
        recovery_block["recoveryz_enabled"] = bool(rz.get("enabled"))
        recovery_block["final_state"] = rz.get("state")
        recovery_block["counters"] = rz.get("counters")
        recovery_block["last_cycle"] = rz.get("last_cycle")
        async with session.get("/monitoring?section=recovery") as r:
            sec = await r.json()
            recovery_block["section_filter_ok"] = (
                r.status == 200
                and set(sec) == {"recovery"}
                and bool(sec["recovery"].get("enabled"))
            )
        async with session.get("/monitoring/prometheus/metrics") as r:
            prom_text = await r.text()
        recovery_block["prom_recovery_series"] = sum(
            1 for ln in prom_text.splitlines()
            if ln.startswith("dts_tpu_recovery_")
        )

    async def integrity_driver(client):
        """Integrity chaos scenario: NaN rows against the readback screen
        (shadow stood down so the row-granular path is the one proving
        itself), then readback bitflips against shadow verification plus
        wire corruption both directions, then a clean closing window with
        a post-chaos bit-identity probe. Detection latencies and the
        detection->success MTTR land in integrity_block for the gate."""
        import dataclasses as _dc

        from distributed_tf_serving_tpu import faults as faults_mod

        loop_ = asyncio.get_running_loop()
        shadow_cfg = integrity_plane.config
        # --- phase 1: NaN rows -> the readback screen -------------------
        await asyncio.sleep(seconds * 0.15)
        # Shadow stands down for this phase: the compare runs pre-widen
        # and would catch the NaN first, masking the screen under test.
        integrity_plane.config = _dc.replace(
            shadow_cfg, shadow_fraction=0.0
        )
        faults_mod.get().add(
            "score_nan", "error",
            rate=float(os.environ.get("SOAK_INTEGRITY_NAN_RATE", "0.08")),
        )
        integrity_block["nan_injected"] = True
        t_nan = time.perf_counter()
        while time.perf_counter() < deadline:
            snap = integrity_plane.snapshot()
            if snap["screen"]["trips"] >= 1 and snap["escalations"] >= 1:
                break
            await asyncio.sleep(0.05)
        integrity_block["screen_detect_s"] = round(
            time.perf_counter() - t_nan, 3
        )
        faults_mod.get().clear("score_nan")
        integrity_block["screen_after_nan"] = (
            integrity_plane.snapshot()["screen"]
        )
        # --- phase 2: bitflips + wire corruption, shadow re-armed -------
        integrity_plane.config = shadow_cfg
        faults_mod.get().add(
            "readback_bitflip", "error",
            rate=float(os.environ.get("SOAK_INTEGRITY_FLIP_RATE", "0.02")),
        )
        # Request-side wire flip, keyed on the tensor name the client
        # stamps: the server must reject EXACTLY the damaged request
        # (corrupt-wire INVALID_ARGUMENT) while batchmates deliver.
        faults_mod.get().add(
            "wire_corrupt", "error",
            rate=float(os.environ.get("SOAK_INTEGRITY_WIRE_RATE", "0.03")),
            key="feat_ids",
        )
        # Response-side wire flip: the verifying client must catch the
        # checksum mismatch (scoreboard kind="corrupt"), never merge the
        # corrupt scores, and retry the shard.
        faults_mod.get().add(
            "wire_corrupt", "error",
            rate=float(os.environ.get("SOAK_INTEGRITY_RESP_RATE", "0.05")),
            key="response",
        )
        integrity_block["chaos_injected"] = True
        t_flip = time.perf_counter()
        while time.perf_counter() < deadline:
            if integrity_plane.snapshot()["shadow"]["mismatches"] >= 1:
                break
            await asyncio.sleep(0.05)
        integrity_block["shadow_detect_s"] = round(
            time.perf_counter() - t_flip, 3
        )
        # Detection -> next clean answer is the MTTR the gate bounds:
        # the mismatch escalated into a recovery cycle, so a fresh
        # request succeeding means the replica came back serving.
        probe = make_payload(candidates=64, num_fields=NUM_FIELDS, seed=911)
        t_detect = time.perf_counter()
        while time.perf_counter() < deadline:
            try:
                await client.predict(probe)
                break
            except Exception:  # noqa: BLE001 — still recovering
                await asyncio.sleep(0.05)
        integrity_block["detect_to_success_s"] = round(
            time.perf_counter() - t_detect, 3
        )
        # Keep the wire sites firing under steady traffic, then clear
        # everything so the run ends on a clean window.
        await asyncio.sleep(
            max(0.0, (deadline - time.perf_counter()) - seconds * 0.25)
        )
        faults_mod.get().clear("wire_corrupt")
        faults_mod.get().clear("readback_bitflip")
        integrity_block["faults_cleared"] = True
        # --- closing clean bit-identity probe ---------------------------
        # Wait out any in-flight recovery cycle first: the probe measures
        # the steady state after chaos, not mid-reinit unavailability.
        while time.perf_counter() < deadline:
            if (recovery_ctrl.state() == "serving"
                    and not recovery_ctrl.cycle_active()):
                break
            await asyncio.sleep(0.05)

        def closing_probe():
            integrity_plane.request_audit()
            out = batcher.submit(
                servable, unique_pool[0], output_keys=("prediction_node",)
            ).result(timeout=600)["prediction_node"]
            return bool(np.array_equal(integrity_ref["scores"], out))

        try:
            integrity_block["clean_bit_identical_post"] = (
                await loop_.run_in_executor(None, closing_probe)
            )
        except Exception as e:  # noqa: BLE001 — report, keep the line
            integrity_block["closing_probe_error"] = (
                f"{type(e).__name__}: {e}"
            )

    async def probe_integrity(session) -> None:
        """End-of-run probes against the LIVE surfaces: /integrityz, the
        on-demand audit POST, the ?section= filter, and the
        dts_tpu_integrity_* Prometheus series."""
        async with session.get("/integrityz") as r:
            iz = await r.json()
        integrity_block["integrityz_enabled"] = bool(iz.get("enabled"))
        async with session.post("/integrityz/audit?batches=2") as r:
            body = await r.json()
            integrity_block["audit_post_ok"] = (
                r.status == 200 and body.get("pending_audits", 0) >= 1
            )
        async with session.get("/monitoring?section=integrity") as r:
            sec = await r.json()
            integrity_block["section_filter_ok"] = (
                r.status == 200
                and set(sec) == {"integrity"}
                and bool(sec["integrity"].get("enabled"))
            )
        async with session.get("/monitoring/prometheus/metrics") as r:
            prom_text = await r.text()
        integrity_block["prom_integrity_series"] = sum(
            1 for ln in prom_text.splitlines()
            if ln.startswith("dts_tpu_integrity_")
        )

    async def control_worker(gport: int):
        import grpc as grpc_mod

        from distributed_tf_serving_tpu.proto import ModelServiceStub
        from distributed_tf_serving_tpu.proto import serving_apis_pb2 as apis

        async with grpc_mod.aio.insecure_channel(f"127.0.0.1:{gport}") as ch:
            stub = ModelServiceStub(ch)
            i = 0
            while time.perf_counter() < deadline:
                i += 1
                try:
                    sreq = apis.GetModelStatusRequest()
                    sreq.model_spec.name = "DCN"
                    resp = await stub.GetModelStatus(sreq, timeout=30)
                    state = resp.model_version_status[0].state
                    if state != apis.ModelVersionStatus.AVAILABLE:
                        raise RuntimeError(f"unexpected model state {state}")
                    rreq = apis.ReloadConfigRequest()
                    mc = rreq.config.model_config_list.config.add()
                    mc.name = "DCN"
                    if i % 2:  # alternate: label present / declared away
                        mc.version_labels["soak"] = 1
                    await stub.HandleReloadConfigRequest(rreq, timeout=30)
                    counts["control_ok"] += 1
                except Exception as e:  # noqa: BLE001 — taxonomy, keep soaking
                    note_error("control", f"{type(e).__name__}: {e}")
                await asyncio.sleep(0.2)

    resilience: dict = {}
    trace_block: dict = {}
    util_block: dict = {}

    async def probe_utilz(session) -> None:
        """Probe the LIVE utilization surfaces (the same bytes an
        operator's curl would get): /utilz route liveness + the
        dts_tpu_utilization_* Prometheus series count."""
        async with session.get("/utilz") as r:
            body = await r.json()
            util_block["utilz_enabled"] = (
                r.status == 200 and bool(body.get("enabled"))
            )
        async with session.get("/monitoring/prometheus/metrics") as r:
            text = await r.text()
        util_block["prometheus_series"] = sum(
            1 for ln in text.splitlines()
            if ln.startswith("dts_tpu_utilization_")
        )

    async def probe_cascade(session) -> None:
        """Probe the LIVE cascade surfaces (the same bytes an operator's
        curl would get): /cascadez liveness + moving counters, the
        dts_tpu_cascade_* Prometheus series count, and the cascade phase
        spans in /monitoring?section=phases."""
        async with session.get("/cascadez") as r:
            body = await r.json()
            cascade_block["cascadez_live"] = (
                r.status == 200 and body.get("requests", 0) > 0
            )
        async with session.get("/monitoring/prometheus/metrics") as r:
            text = await r.text()
        cascade_block["prometheus_series"] = sum(
            1 for ln in text.splitlines()
            if ln.startswith("dts_tpu_cascade_")
        )
        async with session.get("/monitoring?section=phases") as r:
            phases = (await r.json()).get("phases") or {}
        cascade_block["spans_present"] = all(
            p in phases
            for p in ("cascade.stage1", "cascade.prune", "cascade.stage2")
        )

    async def export_trace(session) -> None:
        """Probe the LIVE /tracez surface (the same bytes an operator's
        curl would get) and persist the Chrome trace artifact."""
        async with session.get("/tracez?format=chrome") as r:
            body = await r.read()
            if r.status != 200:
                trace_block["error"] = f"http {r.status}"
                return
        with open(trace_out, "wb") as f:
            f.write(body)
        doc = json.loads(body)
        from distributed_tf_serving_tpu.utils import tracing

        trace_block.update({
            "path": trace_out,
            "events": len(doc.get("traceEvents", ())),
            "recorded": tracing.recorder().recorded,
            "retained": len(tracing.recorder().spans()),
        })

    client_counters: list[dict] = []

    async def drive():
        server, gport = create_server_async(impl, "127.0.0.1:0")
        await server.start()
        runner, rport = await start_rest_gateway(impl, port=0)
        try:
            client_kwargs = dict(
                channels_per_host=3,
                # Chaos soaks run the resilience layer live: scoreboard on,
                # one failover attempt so injected UNAVAILABLEs reroute
                # (same single host — exercises the backoff path). Overload
                # soaks run it too: sheds must land as PUSHBACK (busy) on
                # the scoreboard and the one retry honors retry-after-ms.
                scoreboard=(
                    chaos or overload_mode or recovery_mode or integrity_mode
                ),
                failover_attempts=(
                    8 if (recovery_mode or integrity_mode)
                    else 1 if (chaos or overload_mode) else 0
                ),
            )
            if integrity_mode:
                # The client half of the wire layer: stamp request CRCs
                # and verify the server's score CRC before merging —
                # corrupt responses must surface as retries, never data.
                client_kwargs["integrity_checksums"] = True
            if recovery_mode or integrity_mode:
                # Retries must OUTLAST the recovery cycles (quarantined
                # submits answer UNAVAILABLE until REPLAY, and the wedge
                # + poison phases can run 2-3 back-to-back cycles of a
                # few seconds each on a CPU host — in production the
                # scoreboard reroutes to another replica instead). The
                # new per-request attempt budget rides along, sized so
                # it never binds here while still exercising the knob
                # end to end.
                client_kwargs.update(
                    backoff_initial_s=0.3, backoff_max_s=2.0,
                    timeout_s=25.0, max_attempts_total=16,
                )
            if overload_mode:
                # The RPC deadline IS the goodput bar: a success under
                # this client is by construction an in-deadline success.
                client_kwargs["timeout_s"] = overload_deadline_s
            async with contextlib.AsyncExitStack() as stack:
                client = await stack.enter_async_context(
                    ShardedPredictClient(
                        [f"127.0.0.1:{gport}"], "DCN", **client_kwargs
                    )
                )
                # One worker in three sends criticality=sheddable — the
                # lane an overloaded server drops first.
                shed_client = (
                    await stack.enter_async_context(
                        ShardedPredictClient(
                            [f"127.0.0.1:{gport}"], "DCN",
                            criticality="sheddable", **client_kwargs,
                        )
                    )
                    if overload_mode else None
                )
                # Lifecycle mode: one worker rides the probe lane — the
                # canary's first traffic (probe-lane-first admission).
                probe_client = (
                    await stack.enter_async_context(
                        ShardedPredictClient(
                            [f"127.0.0.1:{gport}"], "DCN",
                            criticality="probe", **client_kwargs,
                        )
                    )
                    if lifecycle_mode else None
                )
                session = await stack.enter_async_context(
                    aiohttp.ClientSession(f"http://127.0.0.1:{rport}")
                )
                try:
                    # Quality mode swaps the standard gRPC mixers for the
                    # teacher-labeled workload (unshifted mixer traffic
                    # would dilute the drift segment the gate measures)
                    # plus the mid-run reference pin.
                    if quality_mode:
                        data_workers = [
                            quality_worker(client, session, w)
                            for w in range(grpc_workers)
                        ] + [quality_pin(session)]
                    elif lifecycle_mode:
                        # The scenario driver rides next to the workers;
                        # the control-plane label flipper is skipped (it
                        # pins version 1, which retention legitimately
                        # retires mid-scenario).
                        data_workers = [
                            lifecycle_worker(
                                probe_client if w == 0 else client, w
                            )
                            for w in range(grpc_workers)
                        ] + [lifecycle_driver()]
                    else:
                        data_workers = [
                            grpc_worker(
                                shed_client
                                if (shed_client is not None and w % 3 == 2)
                                else client,
                                w,
                            )
                            for w in range(grpc_workers)
                        ]
                    await asyncio.gather(
                        *data_workers,
                        *([recovery_driver(client)] if recovery_mode else []),
                        *([integrity_driver(client)] if integrity_mode else []),
                        *(burst_worker(client, w) for w in range(burst_workers)),
                        *(rest_worker(session, w) for w in range(rest_workers)),
                        *([] if lifecycle_mode else [control_worker(gport)]),
                    )
                finally:
                    resilience.update(client.resilience_counters())
                    client_counters.append(client.resilience_counters())
                    if shed_client is not None:
                        client_counters.append(shed_client.resilience_counters())
                    prom_out = os.environ.get("SOAK_PROM_OUT", "")
                    if prom_out:
                        # Client resilience state in Prometheus text, next
                        # to the soak artifact (the client has no scrape
                        # port of its own).
                        with open(prom_out, "w") as f:
                            f.write(client.resilience_prometheus_text())
                    if util_mode:
                        try:
                            await probe_utilz(session)
                        except Exception as e:  # noqa: BLE001 — report, keep line
                            util_block["error"] = f"{type(e).__name__}: {e}"
                    if quality_mode:
                        try:
                            await probe_quality(session)
                        except Exception as e:  # noqa: BLE001 — report, keep line
                            quality_block["error"] = f"{type(e).__name__}: {e}"
                    if lifecycle_mode:
                        try:
                            await probe_lifecycle(session)
                        except Exception as e:  # noqa: BLE001 — report, keep line
                            lifecycle_block["error"] = f"{type(e).__name__}: {e}"
                    if recovery_mode:
                        try:
                            await probe_recovery(session)
                        except Exception as e:  # noqa: BLE001 — report, keep line
                            recovery_block["error"] = f"{type(e).__name__}: {e}"
                    if cascade_mode:
                        try:
                            await probe_cascade(session)
                        except Exception as e:  # noqa: BLE001 — report, keep line
                            cascade_block["error"] = f"{type(e).__name__}: {e}"
                    if integrity_mode:
                        try:
                            await probe_integrity(session)
                        except Exception as e:  # noqa: BLE001 — report, keep line
                            integrity_block["error"] = f"{type(e).__name__}: {e}"
                    if trace_out:
                        try:
                            await export_trace(session)
                        except Exception as e:  # noqa: BLE001 — report, keep line
                            trace_block["error"] = f"{type(e).__name__}: {e}"
        finally:
            await runner.cleanup()
            await server.stop(0)

    t0 = time.perf_counter()
    try:
        asyncio.run(drive())
    finally:
        # Always drain/close (a crashed drive must not leak the writer or
        # leave an append-mode file for a pid-recycled later run).
        if request_logger is not None:
            request_logger.close()
    wall = time.perf_counter() - t0
    total = counts["grpc_ok"] + counts["rest_ok"]
    # Leak-watch RSS BEFORE the parse-back pass below reads the whole log
    # file into memory (malloc arenas rarely shrink; sampling after would
    # report a phantom leak).
    rss_end = rss_gb()
    request_log_block = None
    if request_logger is not None:
        from distributed_tf_serving_tpu.serving.warmup import read_tfrecords

        try:
            parsed = sum(1 for _ in read_tfrecords(log_path))
            parse_err = None
        except Exception as e:  # noqa: BLE001 — report, don't crash the line
            parsed, parse_err = -1, f"{type(e).__name__}: {e}"[:200]
        request_log_block = {
            "sampling": log_sampling,
            "written": request_logger.written,
            "dropped": request_logger.dropped,
            "parsed_back": parsed,
            "parse_error": parse_err,
        }
        if parse_err is None:
            os.remove(log_path)
        else:
            request_log_block["kept_file"] = log_path  # evidence for triage
    if quality_mode:
        # The acceptance comparison: the LIVE windowed AUC (served by
        # /qualityz from the monitor's joined pairs) vs the EXACT AUC the
        # soak computes offline from its own (score, label) log over the
        # same window — train/data.py::auc both times, so a disagreement
        # is a join/reservoir bug, not a metric-definition mismatch.
        from distributed_tf_serving_tpu.train.data import auc as exact_auc

        qz = quality_block.get("qualityz") or {}
        labels_blk = qz.get("labels") or {}
        cutoff = time.monotonic() - q_window_s
        offline_all = offline_window = None
        try:
            if quality_log:
                arr = np.asarray([(s, lb) for s, lb, _t in quality_log])
                offline_all = round(float(exact_auc(arr[:, 1], arr[:, 0])), 6)
            recent = [(s, lb) for s, lb, t in quality_log if t >= cutoff]
            if recent:
                arr = np.asarray(recent)
                offline_window = round(float(exact_auc(arr[:, 1], arr[:, 0])), 6)
        except ValueError:
            pass  # single-class log: AUC undefined, reported as null
        drift_blk = (
            ((qz.get("models") or {}).get("DCN") or {}).get("drift") or {}
        )
        quality_block.update({
            "window_s": q_window_s,
            "windowed_auc": labels_blk.get("auc"),
            "offline_auc_window": offline_window,
            "offline_auc_all": offline_all,
            "offline_pairs": len(quality_log),
            "labels_joined": labels_blk.get("joined", 0),
            "labels_orphaned": labels_blk.get("orphaned", 0),
            "drift": drift_blk,
            "observed_requests": qz.get("observed_requests", 0),
        })
        # The full /qualityz body served its numbers; keep the line lean.
        quality_block.pop("qualityz", None)
    line = {
        "soak_seconds": round(wall, 1),
        "platform": str(jax.devices()[0]),
        "requests_total": total,
        "qps": round(total / wall, 1),
        **{k: v for k, v in counts.items() if k != "errors"},
        "error_taxonomy": counts["errors"],
        "rss_gb_start": rss_start,
        "rss_gb_end": rss_end,
        "request_log": request_log_block,
        "batcher": {
            "batches": batcher.stats.batches,
            "fused_batches": batcher.stats.fused_batches,
            "requests_per_batch": round(batcher.stats.mean_requests_per_batch, 2),
            "deadline_sheds": batcher.stats.deadline_sheds,
            "dedup_batches": batcher.stats.dedup_batches,
            "dedup_rows_collapsed": batcher.stats.dedup_rows_collapsed,
        },
        "cache": (
            {
                **{k: v for k, v in score_cache.snapshot().items()
                   if k != "models"},
                "skew": cache_skew,
                "dedup_batches": batcher.stats.dedup_batches,
                "dedup_rows_collapsed": batcher.stats.dedup_rows_collapsed,
                **cache_block,
                # Workload-only deltas (probe counts subtracted): what the
                # zipfian WORKER traffic did — the CI gate reads these, so
                # the probe's guaranteed hit can never green-wash a cache
                # that stopped hitting under load.
                **{
                    f"workload_{k}": (
                        score_cache.snapshot()[k]
                        - cache_block.get("probe_snapshot", {}).get(k, 0)
                    )
                    for k in ("hits", "misses", "coalesced")
                },
            }
            if cache_mode else None
        ),
        "row_cache": (
            {
                **{k: v for k, v in row_cache.snapshot().items()
                   if k != "models"},
                "scores_match": cache_block.get("row_scores_match"),
                "row_batches": batcher.stats.row_batches,
                "row_full_hit_batches": batcher.stats.row_full_hit_batches,
                "batcher_rows_requested": batcher.stats.rows_requested,
                "batcher_rows_executed": batcher.stats.rows_executed,
                # Workload-only deltas (probe counts subtracted): the CI
                # gate reads these, so the probe's guaranteed row hits
                # can never green-wash a row cache idle under load.
                **{
                    f"workload_{k}": (
                        row_cache.snapshot()[k]
                        - cache_block.get("row_probe_snapshot", {}).get(k, 0)
                    )
                    for k in ("hits", "misses", "coalesced",
                              "rows_requested", "rows_executed")
                },
            }
            if rowcache_mode else None
        ),
        "resilience": resilience or None,
        "overload": (
            {
                # Goodput: every grpc_ok ran under timeout_s == the
                # deadline, so successes ARE in-deadline successes.
                "goodput_qps": round(counts["grpc_ok"] / wall, 1),
                "deadline_s": overload_deadline_s,
                "dispatch_delay_s": dispatch_delay_s,
                "grpc_workers": grpc_workers,
                "burst_workers": burst_workers,
                "controller": batcher.overload.snapshot(),
                "stale_serves": score_cache.snapshot()["stale_serves"],
                # Aggregated across BOTH clients (default + sheddable):
                # the smoke gate reads these — sheds must register as
                # pushback (busy), never as ejection.
                "client_pushbacks": sum(
                    c.get("pushbacks_received", 0) for c in client_counters
                ),
                "client_retry_after_honored": sum(
                    c.get("retry_after_honored", 0) for c in client_counters
                ),
                "client_ejections": sum(
                    c.get("scoreboard", {}).get("ejections", 0)
                    for c in client_counters
                ),
            }
            if overload_mode else None
        ),
        "trace": trace_block or None,
        # Utilization plane (SOAK_UTIL=1): ledger snapshot (gap waterfall
        # summing to wall + live achieved fraction) plus the live-route
        # probes — the CI gate (tools/check_util_smoke.py) reads this.
        "utilization": (
            {**ledger.snapshot(window_s=wall), **util_block}
            if util_mode else None
        ),
        # Quality plane (SOAK_QUALITY=1): live-route probes + the
        # windowed-vs-offline AUC comparison — the CI gate
        # (tools/check_quality_smoke.py) reads this.
        "quality": quality_block if quality_mode else None,
        # Lifecycle plane (SOAK_LIFECYCLE=1): promote + rollback +
        # blacklist-persistence evidence with live-route probes — the CI
        # gate (tools/check_lifecycle_smoke.py) reads this.
        "lifecycle": lifecycle_block if lifecycle_mode else None,
        # Recovery plane (SOAK_RECOVERY=1): wedge-trip MTTR + poison
        # bisection evidence with live-route probes — the CI gate
        # (tools/check_recovery_smoke.py) reads this.
        "recovery": recovery_block if recovery_mode else None,
        # Cascade plane (SOAK_CASCADE=1): the full snapshot (row
        # dispositions, per-stage seconds, survivor-bucket histogram)
        # plus the bit-identity probe verdict, live-route probe results,
        # and workload-only deltas (probe counts subtracted) — the CI
        # gate (tools/check_cascade_smoke.py) reads this.
        "cascade": (
            {
                **impl.cascade.snapshot(),
                **cascade_block,
                **{
                    f"workload_{k}": (
                        impl.cascade.snapshot()[k]
                        - cascade_block.get("probe_snapshot", {}).get(k, 0)
                    )
                    for k in ("requests", "rows_requested", "rows_ranked",
                              "pruned_rows")
                },
            }
            if cascade_mode else None
        ),
        # Integrity plane (SOAK_INTEGRITY=1): the full plane snapshot,
        # both clean bit-identity verdicts, per-layer detection evidence,
        # the verifying client's corrupt/NaN counters, recovery
        # escalation counters, and live-route probes — the CI gate
        # (tools/check_integrity_smoke.py) reads this.
        "integrity": (
            {
                **integrity_plane.snapshot(),
                **integrity_block,
                "client": {
                    "corrupt_responses": resilience.get(
                        "corrupt_responses", 0
                    ),
                    "nan_scores_merged": resilience.get(
                        "nan_scores_merged", 0
                    ),
                },
                "recovery_counters": (
                    recovery_ctrl.snapshot()["counters"]
                    if recovery_ctrl is not None else None
                ),
            }
            if integrity_mode else None
        ),
        "chaos": None,
        "input_cache": (
            {
                "hits": batcher.input_cache.hits,
                "misses": batcher.input_cache.misses,
                "bypassed": batcher.input_cache.bypassed,
                "bypass_cycles": batcher.input_cache.bypass_cycles,
                "mb_upload_skipped": round(batcher.input_cache.bytes_skipped / 1e6, 1),
            }
            if batcher.input_cache is not None
            else None
        ),
    }
    if chaos or overload_mode or recovery_mode or integrity_mode:
        from distributed_tf_serving_tpu import faults

        if chaos:
            line["chaos"] = faults.get().snapshot()
        faults.reset()
    if recovery_ctrl is not None:
        recovery_ctrl.stop()
    if lifecycle_ctrl is not None:
        lifecycle_ctrl.stop()
    if lifecycle_watcher is not None:
        lifecycle_watcher.stop()
    batcher.stop()
    print(json.dumps(line))


if __name__ == "__main__":
    main()
