"""Online fine-tune publisher — the train-side leg of the lifecycle plane
(serving/lifecycle.py, ISSUE 8).

`fine_tune` continues training FROM a serving servable's current params
(never from a fresh init: freshness means carrying yesterday's knowledge
forward) on fresh labeled rows, reusing the exact jitted step
train/trainer.py serves the from-scratch path with. `publish_finetuned`
wraps it with the atomic versioned-dir commit (interop/export.py
publish_version + train/checkpoint.py save_servable), so the serving
process's version watcher hot-loads the result as the next numeric
version with no coordination beyond the filesystem contract.

The default data source is the synthetic CTR stream (the in-tree label
oracle); embedded callers pass `data_fn(step) -> batch` to train on real
feedback — the /labelz plane joins labels to SCORES, not features, so a
production fine-tune loop needs a feature log alongside it (README
"Continuous freshness" notes the gap)."""

from __future__ import annotations

import dataclasses
import time


def fine_tune(
    servable,
    steps: int = 200,
    batch_size: int = 256,
    learning_rate: float = 1e-4,
    seed: int = 0,
    stream_config=None,
    data_fn=None,
):
    """Continue training `servable`'s params for `steps`; returns
    (new_params, metrics). The servable's own params are deep-copied
    before the first donating step — the serving registry keeps handing
    out the originals mid-flight, and donation would delete them under
    live traffic."""
    import jax
    import jax.numpy as jnp
    import optax

    from .. import native
    from .data import SyntheticCTRConfig, SyntheticCTRStream
    from .trainer import TrainState, make_train_step

    model = servable.model
    optimizer = optax.adamw(learning_rate)
    params = jax.tree_util.tree_map(jnp.copy, servable.params)
    state = TrainState(
        params=params,
        opt_state=jax.jit(optimizer.init)(params),
        step=jnp.asarray(0),
    )
    step_fn = make_train_step(model, optimizer)
    if data_fn is None:
        stream = SyntheticCTRStream(
            stream_config
            or SyntheticCTRConfig(
                num_fields=model.config.num_fields,
                id_space=min(1 << 18, model.config.vocab_size),
                seed=seed,
            )
        )
        # Offset the stream per seed so successive publish rounds train
        # on FRESH rows, not a replay of the last round's batches.
        base = (seed + 1) * 1_000_000

        def data_fn(i, _stream=stream, _base=base):  # noqa: A001
            return _stream.batch(batch_size, _base + i)

    metrics: dict = {}
    t0 = time.perf_counter()
    for i in range(steps):
        raw = data_fn(i)
        batch = {
            "feat_ids": native.fold_ids(
                raw["feat_ids"], model.config.vocab_size
            ),
            "feat_wts": raw["feat_wts"],
            "labels": raw["labels"],
        }
        state, metrics = step_fn(state, batch)
    jax.block_until_ready(state.params)
    return state.params, {
        "steps": steps,
        "wall_s": round(time.perf_counter() - t0, 3),
        **{k: float(v) for k, v in metrics.items()},
    }


def publish_finetuned(
    base_dir,
    servable,
    kind: str,
    steps: int = 200,
    batch_size: int = 256,
    learning_rate: float = 1e-4,
    seed: int = 0,
    stream_config=None,
    data_fn=None,
) -> dict:
    """fine_tune + atomic publish into the watched base dir as the next
    numeric version. The checkpoint manifest records a best-guess version
    number; the DIRECTORY number allocated at commit is authoritative
    (the version watcher's loader contract), so a publish race that
    renumbers the landing slot stays correct. Returns a summary dict
    {version, path, steps, loss, ...}."""
    from ..interop.export import publish_version
    from .checkpoint import save_servable

    new_params, metrics = fine_tune(
        servable,
        steps=steps,
        batch_size=batch_size,
        learning_rate=learning_rate,
        seed=seed,
        stream_config=stream_config,
        data_fn=data_fn,
    )

    def write(tmp_dir: str) -> None:
        save_servable(
            tmp_dir,
            dataclasses.replace(
                servable, params=new_params, version=servable.version + 1
            ),
            kind=kind,
        )

    version, path = publish_version(
        base_dir, write, at_least=servable.version + 1
    )
    return {"version": version, "path": path, **metrics}
