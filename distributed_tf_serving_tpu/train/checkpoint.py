"""Servable checkpointing: params + model metadata on disk.

The reference's checkpoint story is the vendored SaverDef schema consumed by
the external SavedModel loader (saver.proto:11-47, meta_graph.proto:75 —
SURVEY.md §5); serving itself is stateless. Here the equivalent is direct:
an Orbax param checkpoint next to a JSON manifest (model kind + ModelConfig
+ name/version), from which load_servable reconstructs a registry-ready
Servable. Sharded param trees save/restore transparently (Orbax records
layouts; restore_args can re-place onto a mesh).
"""

from __future__ import annotations

import dataclasses
import json
import pathlib

import jax
import orbax.checkpoint as ocp

from ..models.base import ModelConfig, build_model
from ..models.registry import Servable, ctr_signatures

MANIFEST = "servable.json"
PARAMS_DIR = "params"


def save_servable(path, servable: Servable, kind: str) -> None:
    """Write params + manifest. `kind` is the model-zoo family name.

    Write order is a commit protocol: params first, manifest LAST — the
    manifest's existence marks the checkpoint complete, so a concurrent
    reader (serving/version_watcher.py polling a base path) never loads a
    half-written params tree."""
    path = pathlib.Path(path)
    path.mkdir(parents=True, exist_ok=True)
    manifest = {
        "name": servable.name,
        "version": servable.version,
        "kind": kind,
        "config": dataclasses.asdict(servable.model.config),
    }
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save((path / PARAMS_DIR).absolute(), servable.params, force=True)
    (path / MANIFEST).write_text(json.dumps(manifest, indent=2))


def load_servable(
    path, mesh=None, tensor_parallel: bool = False, host: bool = False
) -> Servable:
    """Reconstruct a Servable; with a mesh, params restore pre-placed
    (vocab tables over the model axis; dense weights model-axis split too
    when tensor_parallel) instead of replicated — restoring straight into
    the serving layout avoids a second full-tree resharding pass.

    host=True restores plain numpy arrays with NO device placement — the
    mode multi-process serving needs: under jax.distributed, a device
    restore demands explicit cross-process shardings orbax cannot infer
    from a single-process checkpoint, whereas every process can read the
    full tree to host and let the caller place it at a protocol-aligned
    point (parallel/multihost.py MultiHostRunner._place)."""
    import numpy as np

    path = pathlib.Path(path)
    manifest = json.loads((path / MANIFEST).read_text())
    config = ModelConfig(**{**manifest["config"], "mlp_dims": tuple(manifest["config"]["mlp_dims"]),
                            "bottom_mlp_dims": tuple(manifest["config"]["bottom_mlp_dims"])})
    model = build_model(manifest["kind"], config)

    target = jax.eval_shape(model.init, jax.random.PRNGKey(0))
    if host:
        if mesh is not None:
            raise ValueError("host=True restores unplaced arrays; mesh is exclusive")
        # A host restore is a purely LOCAL read, so it must opt out of
        # orbax's cross-process barrier: under jax.distributed the default
        # Checkpointer syncs every process on restore, and the multihost
        # serving protocol restores at different protocol points on leader
        # (before the RELOAD broadcast) vs followers (after) — the barrier
        # would interleave with the runner's own collectives and deadlock
        # the slice (observed: leader in orbax sync_global_processes,
        # follower in the header broadcast).
        local_only = ocp.options.MultiprocessingOptions(
            primary_host=jax.process_index(),
            active_processes={jax.process_index()},
            barrier_sync_key_prefix=f"dts_local_{jax.process_index()}",
        )
        with ocp.Checkpointer(
            ocp.PyTreeCheckpointHandler(), multiprocessing_options=local_only
        ) as ckptr:
            params = ckptr.restore(
                (path / PARAMS_DIR).absolute(),
                restore_args=jax.tree.map(
                    lambda _: ocp.RestoreArgs(restore_type=np.ndarray), target
                ),
            )
    else:
        if mesh is not None:
            from ..parallel.sharding import param_shardings

            shardings = param_shardings(target, mesh, tensor_parallel)
            target = jax.tree.map(
                lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
                target,
                shardings,
            )
        with ocp.StandardCheckpointer() as ckptr:
            params = ckptr.restore((path / PARAMS_DIR).absolute(), target)

    dense = config.num_dense_features if manifest["kind"] == "dlrm" else None
    return Servable(
        name=manifest["name"],
        version=manifest["version"],
        model=model,
        params=params,
        signatures=ctr_signatures(config.num_fields, with_dense=dense),
    )
