"""Sharded training loop for the CTR model zoo.

The reference has no training (SURVEY.md §0: models are externally-exported
SavedModels); the framework closes that gap so served models can be produced
in-tree. TPU-first mechanics:

- One jitted train step (BCE-with-logits via optax, adamw default), gradients
  under the same bf16-compute/f32-accumulate numerics as serving.
- Sharding by placement: params are laid out by parallel.sharding
  (vocab-major tables split over the model axis, rest replicated) and
  batches candidate-sharded over the data axis; the jitted step inherits
  those layouts, so XLA emits the dp gradient psums and EP gather/scatter
  collectives without explicit pmap/shard_map code.
- donate_argnums on the state keeps HBM flat across steps.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh

from .. import native
from ..models.base import Model
from ..parallel.sharding import batch_shardings, place_params
from .data import SyntheticCTRConfig, SyntheticCTRStream, auc


@dataclasses.dataclass
class TrainState:
    params: Any
    opt_state: Any
    step: jnp.ndarray  # scalar int32


def bce_with_logits(logits: jax.Array, labels: jax.Array) -> jax.Array:
    # Numerically-stable sigmoid cross-entropy in f32.
    logits = logits.astype(jnp.float32)
    labels = labels.astype(jnp.float32)
    return jnp.mean(jnp.maximum(logits, 0) - logits * labels + jnp.log1p(jnp.exp(-jnp.abs(logits))))


def make_train_step(model: Model, optimizer: optax.GradientTransformation):
    """Build the jitted (state, batch) -> (state, metrics) step."""

    def loss_fn(params, batch):
        out = model.apply(params, batch)
        loss = bce_with_logits(out["logits"], batch["labels"])
        return loss, out["logits"]

    def step(state: TrainState, batch) -> tuple[TrainState, dict]:
        (loss, logits), grads = jax.value_and_grad(loss_fn, has_aux=True)(state.params, batch)
        updates, opt_state = optimizer.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        metrics = {
            "loss": loss,
            "accuracy": jnp.mean(
                (jax.nn.sigmoid(logits.astype(jnp.float32)) > 0.5)
                == (batch["labels"] > 0.5)
            ),
        }
        return TrainState(params=params, opt_state=opt_state, step=state.step + 1), metrics

    return jax.jit(step, donate_argnums=0)


jax.tree_util.register_dataclass(
    TrainState, data_fields=["params", "opt_state", "step"], meta_fields=[]
)


class Trainer:
    """Synthetic-data training orchestrator (also drives the parity harness)."""

    def __init__(
        self,
        model: Model,
        mesh: Mesh | None = None,
        learning_rate: float | optax.Schedule = 1e-3,
        seed: int = 0,
        tensor_parallel: bool = False,
        stream_config: SyntheticCTRConfig | None = None,
    ):
        self.model = model
        self.mesh = mesh
        # learning_rate may be an optax schedule (bench.py passes
        # warmup+cosine: the synthetic task is id memorization from noisy
        # Bernoulli views, where a hot constant LR stops short of the
        # information limit — the tail needs decay to average the noise).
        self.optimizer = optax.adamw(learning_rate)
        params = jax.jit(model.init)(jax.random.PRNGKey(seed))
        if mesh is not None:
            params = place_params(params, mesh, tensor_parallel)
        opt_state = jax.jit(self.optimizer.init)(params)
        self.state = TrainState(params=params, opt_state=opt_state, step=jnp.asarray(0))
        self.step_fn = make_train_step(model, self.optimizer)
        self._eval_apply = jax.jit(model.apply)  # compiled once, reused per eval
        # stream_config sets the data's difficulty: id catalog density
        # decides how many noisy Bernoulli views each embedding row gets per
        # epoch-equivalent (short bench runs want a denser catalog — see
        # bench.py train_on_chip). The default keeps the catalog within the
        # vocab so folding is injective and every id's embedding can learn
        # its teacher weight.
        self.stream = SyntheticCTRStream(
            stream_config
            or SyntheticCTRConfig(
                num_fields=model.config.num_fields,
                id_space=min(1 << 18, model.config.vocab_size),
                seed=seed,
            )
        )

    def snapshot_params(self):
        """Donation-safe copy of the current params, sharding preserved.

        The train step donates its state (donate_argnums — HBM stays flat),
        so `trainer.state.params` leaves are DELETED by the next fit() call.
        A Servable built directly from state.params therefore dies the
        moment training continues (and device_put/place_params alias rather
        than copy when the sharding already matches). Serve-while-training
        callers must hand the registry this snapshot instead."""
        return jax.tree_util.tree_map(jnp.copy, self.state.params)

    def _prepare(self, batch: dict[str, np.ndarray]) -> dict[str, jnp.ndarray]:
        out = {
            "feat_ids": native.fold_ids(batch["feat_ids"], self.model.config.vocab_size),
            "feat_wts": batch["feat_wts"],
            "labels": batch["labels"],
        }
        if self.mesh is not None:
            out = jax.device_put(out, batch_shardings(out, self.mesh))
        return out

    def fit(
        self, steps: int, batch_size: int = 512, log_every: int = 0,
        auc_every: int = 0,
    ) -> dict:
        """auc_every > 0 records a held-out AUC curve at that step cadence
        (plus the final step) under "auc_curve": the steps-vs-AUC evidence
        that separates an optimization plateau from an information limit
        (VERDICT r3 weak #7). Eval wall time is excluded from
        examples_per_s."""
        metrics = {}
        curve: list[list[float]] = []
        eval_wall = 0.0
        t0 = time.perf_counter()
        for i in range(steps):
            batch = self._prepare(self.stream.batch(batch_size, i))
            self.state, metrics = self.step_fn(self.state, batch)
            if log_every and (i + 1) % log_every == 0:
                print(f"step {i + 1}: loss={float(metrics['loss']):.4f}")
            if auc_every and ((i + 1) % auc_every == 0 or i + 1 == steps):
                jax.block_until_ready(self.state.params)
                te = time.perf_counter()
                curve.append([i + 1, round(self.eval_auc(batches=2, batch_size=batch_size), 4)])
                eval_wall += time.perf_counter() - te
        jax.block_until_ready(self.state.params)
        wall = time.perf_counter() - t0 - eval_wall
        out = {
            "steps": steps,
            "wall_s": wall,
            "examples_per_s": steps * batch_size / wall,
            **{k: float(v) for k, v in metrics.items()},
        }
        if curve:
            out["auc_curve"] = curve
        return out

    def eval_auc(
        self,
        batches: int = 8,
        batch_size: int = 1024,
        offset: int = 1_000_000,
        with_bayes: bool = False,
    ):
        """Held-out AUC (indices disjoint from training). with_bayes=True
        also returns the teacher's own AUC on the same rows — the Bayes
        ceiling the model number should be read against."""
        scores, labels, teacher = [], [], []
        apply = self._eval_apply
        for i in range(batches):
            raw = self.stream.batch(batch_size, offset + i)
            batch = self._prepare(raw)
            out = apply(self.state.params, {k: batch[k] for k in ("feat_ids", "feat_wts")})
            scores.append(np.asarray(out["prediction_node"]))
            labels.append(raw["labels"])
            if with_bayes:
                teacher.append(self.stream._teacher_score(raw["feat_ids"], raw["feat_wts"]))
        labels = np.concatenate(labels)
        model_auc = auc(labels, np.concatenate(scores))
        if with_bayes:
            return model_auc, auc(labels, np.concatenate(teacher))
        return model_auc


def main(argv=None) -> None:
    """Train on the synthetic CTR stream and write a servable checkpoint:
    the train -> checkpoint -> serve workflow's first leg."""
    import argparse

    from ..models.base import ModelConfig, build_model
    from ..models.registry import Servable, ctr_signatures
    from .checkpoint import save_servable

    parser = argparse.ArgumentParser(description="Train a CTR model, save a servable")
    parser.add_argument("--out", required=True, help="checkpoint output dir")
    parser.add_argument("--kind", default="dcn_v2")
    parser.add_argument("--name", default="DCN")
    parser.add_argument("--version", type=int, default=1)
    parser.add_argument("--steps", type=int, default=1000)
    parser.add_argument("--batch-size", type=int, default=512)
    parser.add_argument("--learning-rate", type=float, default=1e-3)
    parser.add_argument("--num-fields", type=int, default=43)
    parser.add_argument("--vocab-size", type=int, default=1 << 20)
    parser.add_argument("--embed-dim", type=int, default=16)
    parser.add_argument("--mesh-devices", type=int, default=0,
                        help=">0: shard training over the first n devices")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--id-space", type=int, default=0,
                        help="synthetic catalog size (0 = min(2^18, vocab)); "
                        "denser catalogs give each embedding row more views "
                        "per step — see bench.py train_on_chip")
    args = parser.parse_args(argv)

    config = ModelConfig(
        name=args.name, num_fields=args.num_fields,
        vocab_size=args.vocab_size, embed_dim=args.embed_dim,
    )
    mesh = None
    if args.mesh_devices:
        from ..parallel.mesh import make_mesh

        mesh = make_mesh(args.mesh_devices)
    model = build_model(args.kind, config)
    stream_config = None
    if args.id_space:
        # Clamp to the vocab: past it the fold stops being injective and
        # colliding ids carry contradictory labels (silent AUC damage).
        id_space = min(args.id_space, args.vocab_size)
        if id_space != args.id_space:
            print(f"--id-space {args.id_space} clamped to vocab size {id_space}")
        stream_config = SyntheticCTRConfig(
            num_fields=args.num_fields, id_space=id_space, seed=args.seed
        )
    trainer = Trainer(
        model, mesh=mesh, learning_rate=args.learning_rate, seed=args.seed,
        stream_config=stream_config,
    )
    metrics = trainer.fit(args.steps, batch_size=args.batch_size, log_every=max(args.steps // 10, 1))
    auc_val = trainer.eval_auc()
    servable = Servable(
        name=args.name, version=args.version, model=model,
        params=trainer.state.params, signatures=ctr_signatures(config.num_fields),
    )
    save_servable(args.out, servable, kind=args.kind)
    print(
        f"trained {args.kind} {args.steps} steps: loss={metrics['loss']:.4f} "
        f"auc={auc_val:.4f} ({metrics['examples_per_s']:.0f} ex/s) -> {args.out}"
    )


if __name__ == "__main__":
    main()
