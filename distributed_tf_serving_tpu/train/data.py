"""Synthetic CTR data — a deterministic Criteo-like stream.

The reference repo has no training or data pipeline (models arrive as
external SavedModels, SURVEY.md §0); the framework still needs labeled
batches to train the in-tree model zoo and to run AUC-parity checks
(BASELINE.md). Labels come from a fixed random "teacher": each (field, id)
pair contributes a hash-derived weight, the row score is their
feature-weighted sum, and the label is Bernoulli(sigmoid(score)) — so every
model family has learnable signal and a known Bayes-optimal ranking to
measure AUC against.
"""

from __future__ import annotations

import dataclasses

import numpy as np


@dataclasses.dataclass(frozen=True)
class SyntheticCTRConfig:
    num_fields: int = 43  # FIELD_NUM, DCNClient.java:25
    # Finite id catalog: ids must recur across batches or nothing
    # generalizes (the teacher keys on raw ids; an id seen only once carries
    # no transferable signal). Sized to fit common vocab settings so the
    # model-side fold stays injective.
    id_space: int = 1 << 18
    # Scaled so the teacher logit std lands ~3.5 (Bayes AUC ~0.9): a test
    # that "training learns" needs a ceiling well clear of coin-flip.
    teacher_scale: float = 6.0
    seed: int = 0


class SyntheticCTRStream:
    """Deterministic batch generator: batch(i) is reproducible for any i."""

    def __init__(self, config: SyntheticCTRConfig = SyntheticCTRConfig()):
        self.config = config
        # Teacher weights live in a small hashed space so scores depend on
        # ids through a fixed pseudo-random map.
        rng = np.random.RandomState(config.seed)
        self._teacher = rng.randn(1 << 16).astype(np.float32) * config.teacher_scale

    def _teacher_score(self, ids: np.ndarray, wts: np.ndarray) -> np.ndarray:
        # Fibonacci hash in uint64 (the multiplier exceeds int64 range).
        h = (ids.astype(np.uint64) * np.uint64(0x9E3779B97F4A7C15)) >> np.uint64(48)
        w = self._teacher[(h & np.uint64(0xFFFF)).astype(np.int64)]
        # sum/sqrt(F): logit variance independent of field count.
        return (w * wts).sum(axis=1) / np.sqrt(wts.shape[1])

    def batch(self, batch_size: int, index: int) -> dict[str, np.ndarray]:
        cfg = self.config
        rng = np.random.RandomState((cfg.seed * 1_000_003 + index) & 0x7FFFFFFF)
        ids = rng.randint(0, cfg.id_space, size=(batch_size, cfg.num_fields)).astype(np.int64)
        wts = rng.rand(batch_size, cfg.num_fields).astype(np.float32)
        score = self._teacher_score(ids, wts)
        labels = (rng.rand(batch_size) < _sigmoid(score)).astype(np.float32)
        return {"feat_ids": ids, "feat_wts": wts, "labels": labels}


def _sigmoid(x: np.ndarray) -> np.ndarray:
    return 1.0 / (1.0 + np.exp(-x))


def auc(labels: np.ndarray, scores: np.ndarray) -> float:
    """Rank-based AUC (Mann-Whitney U), ties handled by average rank — the
    parity metric from BASELINE.md."""
    labels = np.asarray(labels).astype(np.float64)
    order = np.argsort(scores, kind="mergesort")
    ranks = np.empty_like(order, dtype=np.float64)
    sorted_scores = np.asarray(scores)[order]
    # average ranks for ties
    i = 0
    n = len(sorted_scores)
    while i < n:
        j = i
        while j + 1 < n and sorted_scores[j + 1] == sorted_scores[i]:
            j += 1
        ranks[order[i : j + 1]] = (i + j) / 2.0 + 1.0
        i = j + 1
    pos = labels.sum()
    neg = n - pos
    if pos == 0 or neg == 0:
        raise ValueError("AUC undefined: single-class labels")
    return float((ranks[labels == 1].sum() - pos * (pos + 1) / 2) / (pos * neg))
