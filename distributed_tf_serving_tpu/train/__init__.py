"""Training: sharded optax loop, synthetic CTR data, servable checkpoints."""

from .checkpoint import load_servable, save_servable
from .data import SyntheticCTRConfig, SyntheticCTRStream, auc
from .publisher import fine_tune, publish_finetuned
from .trainer import Trainer, TrainState, bce_with_logits, make_train_step

__all__ = [
    "Trainer",
    "TrainState",
    "make_train_step",
    "bce_with_logits",
    "SyntheticCTRStream",
    "SyntheticCTRConfig",
    "auc",
    "save_servable",
    "load_servable",
    "fine_tune",
    "publish_finetuned",
]
