"""Hand-written gRPC wiring for tensorflow.serving.PredictionService.

grpc_tools (the protoc gRPC plugin) is not available in this image, so the
stub and servicer glue that `protoc --grpc_python_out` would emit is written
by hand. Method paths match the reference service definition
(prediction_service.proto:15-31): /tensorflow.serving.PredictionService/<M>.

Works with both `grpc.Channel`/`grpc.Server` and their `grpc.aio` variants —
the channel/server object itself decides sync vs async semantics.
"""

from __future__ import annotations

import grpc

from . import serving_apis_pb2 as apis

SERVICE_NAME = "tensorflow.serving.PredictionService"

# Channel/server tuning for half-MB-per-request traffic, shared by the
# client (client/client.py) and both server factories (serving/server.py).
# A 516 KB message spans 32 default-size (16 KB) HTTP/2 data frames, each
# with its own framing and flow-control bookkeeping; one big frame cuts
# that to a single pass.
LARGE_MESSAGE_CHANNEL_OPTIONS = (
    ("grpc.max_receive_message_length", 64 * 1024 * 1024),
    ("grpc.max_send_message_length", 64 * 1024 * 1024),
    ("grpc.http2.max_frame_size", 1 * 1024 * 1024),
    ("grpc.optimization_target", "throughput"),
)

# Server-side tolerance for client keepalive pings (the client channels run
# grpc.keepalive_time_ms ~10s to detect silently-dead backends fast): grpc's
# server default treats data-free pings more often than 5 minutes as abuse
# and GOAWAYs the connection with ENHANCE_YOUR_CALM/too_many_pings — which
# would turn the resilience feature into a connection-flapping bug. Both
# server factories (serving/server.py) append these.
KEEPALIVE_SERVER_OPTIONS = (
    ("grpc.http2.min_recv_ping_interval_without_data_ms", 5000),
    ("grpc.http2.max_ping_strikes", 0),  # never GOAWAY a keepalive-ing client
    ("grpc.keepalive_permit_without_calls", 1),
)

# method name -> (request class, response class); order matches the reference
# service definition.
_METHODS = {
    "Classify": (apis.ClassificationRequest, apis.ClassificationResponse),
    "Regress": (apis.RegressionRequest, apis.RegressionResponse),
    "Predict": (apis.PredictRequest, apis.PredictResponse),
    "MultiInference": (apis.MultiInferenceRequest, apis.MultiInferenceResponse),
    "GetModelMetadata": (apis.GetModelMetadataRequest, apis.GetModelMetadataResponse),
}


class PredictionServiceStub:
    """Client stub: one unary-unary callable per RPC.

    Each attribute (e.g. ``stub.Predict``) is a grpc multicallable supporting
    ``stub.Predict(request, timeout=...)`` and ``.future(...)`` on sync
    channels, or awaitables on ``grpc.aio`` channels.
    """

    def __init__(self, channel: grpc.Channel):
        for name, (req_cls, resp_cls) in _METHODS.items():
            setattr(
                self,
                name,
                channel.unary_unary(
                    f"/{SERVICE_NAME}/{name}",
                    request_serializer=req_cls.SerializeToString,
                    response_deserializer=resp_cls.FromString,
                ),
            )
        # Raw-bytes variant of the hot RPC: callers that hold an already
        # serialized PredictRequest (client.PreparedRequest) skip the
        # per-call SerializeToString — the wire bytes are identical, grpc
        # passes a bytes request through untouched when the serializer is
        # None.
        self.PredictRaw = channel.unary_unary(
            f"/{SERVICE_NAME}/Predict",
            request_serializer=None,
            response_deserializer=_METHODS["Predict"][1].FromString,
        )
        # Server-streaming Predict (framework extension, ISSUE 9): the
        # request is the ordinary PredictRequest; the response is a stream
        # of PredictStreamChunk sub-batch results, each flushed as its
        # readback completes (possibly out of order — chunks carry
        # offset/count for the client-side incremental merge).
        self.PredictStream = channel.unary_stream(
            f"/{SERVICE_NAME}/PredictStream",
            request_serializer=apis.PredictRequest.SerializeToString,
            response_deserializer=apis.PredictStreamChunk.FromString,
        )
        # Raw-bytes flavor for PreparedRequest callers (same contract as
        # PredictRaw: identical wire bytes, no per-call serialize).
        self.PredictStreamRaw = channel.unary_stream(
            f"/{SERVICE_NAME}/PredictStream",
            request_serializer=None,
            response_deserializer=apis.PredictStreamChunk.FromString,
        )


class PredictionServiceServicer:
    """Service base class; override the RPCs the server implements."""

    def Classify(self, request, context):
        context.abort(grpc.StatusCode.UNIMPLEMENTED, "Classify not implemented")

    def Regress(self, request, context):
        context.abort(grpc.StatusCode.UNIMPLEMENTED, "Regress not implemented")

    def Predict(self, request, context):
        context.abort(grpc.StatusCode.UNIMPLEMENTED, "Predict not implemented")

    def MultiInference(self, request, context):
        context.abort(grpc.StatusCode.UNIMPLEMENTED, "MultiInference not implemented")

    def GetModelMetadata(self, request, context):
        context.abort(grpc.StatusCode.UNIMPLEMENTED, "GetModelMetadata not implemented")

    def PredictStream(self, request, context):
        context.abort(grpc.StatusCode.UNIMPLEMENTED, "PredictStream not implemented")


def add_PredictionServiceServicer_to_server(servicer, server) -> None:
    handlers = {
        name: grpc.unary_unary_rpc_method_handler(
            getattr(servicer, name),
            request_deserializer=req_cls.FromString,
            response_serializer=resp_cls.SerializeToString,
        )
        for name, (req_cls, resp_cls) in _METHODS.items()
    }
    # The one non-unary method rides a unary_stream handler; both the
    # threaded server (a plain generator servicer method) and grpc.aio
    # (an async generator) accept this registration shape.
    handlers["PredictStream"] = grpc.unary_stream_rpc_method_handler(
        servicer.PredictStream,
        request_deserializer=apis.PredictRequest.FromString,
        response_serializer=apis.PredictStreamChunk.SerializeToString,
    )
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(SERVICE_NAME, handlers),)
    )


# --- tensorflow.serving.ModelService --------------------------------------
# The model server's second service (model_service.proto upstream): version
# status for readiness probes + runtime config reload (version-label
# retargeting here). Same hand-written pattern as PredictionService.

MODEL_SERVICE_NAME = "tensorflow.serving.ModelService"

_MODEL_METHODS = {
    "GetModelStatus": (apis.GetModelStatusRequest, apis.GetModelStatusResponse),
    "HandleReloadConfigRequest": (apis.ReloadConfigRequest, apis.ReloadConfigResponse),
}


class ModelServiceStub:
    """Client stub for ModelService (unary-unary callables per RPC)."""

    def __init__(self, channel: grpc.Channel):
        for name, (req_cls, resp_cls) in _MODEL_METHODS.items():
            setattr(
                self,
                name,
                channel.unary_unary(
                    f"/{MODEL_SERVICE_NAME}/{name}",
                    request_serializer=req_cls.SerializeToString,
                    response_deserializer=resp_cls.FromString,
                ),
            )


class ModelServiceServicer:
    """Service base class; override the RPCs the server implements."""

    def GetModelStatus(self, request, context):
        context.abort(grpc.StatusCode.UNIMPLEMENTED, "GetModelStatus not implemented")

    def HandleReloadConfigRequest(self, request, context):
        context.abort(
            grpc.StatusCode.UNIMPLEMENTED, "HandleReloadConfigRequest not implemented"
        )


def add_ModelServiceServicer_to_server(servicer, server) -> None:
    handlers = {
        name: grpc.unary_unary_rpc_method_handler(
            getattr(servicer, name),
            request_deserializer=req_cls.FromString,
            response_serializer=resp_cls.SerializeToString,
        )
        for name, (req_cls, resp_cls) in _MODEL_METHODS.items()
    }
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(MODEL_SERVICE_NAME, handlers),)
    )
