"""Hand-written grpc.health.v1 bindings (Check + streaming Watch).

The standard `grpcio-health-checking` package is not in this image, and the
two messages involved are trivial, so — like service_grpc.py — the wire
format is written by hand and byte-compatible with the canonical
health/v1/health.proto:

    message HealthCheckRequest  { string service = 1; }
    message HealthCheckResponse { ServingStatus status = 1; }
    enum ServingStatus { UNKNOWN=0; SERVING=1; NOT_SERVING=2; SERVICE_UNKNOWN=3; }

Standard health-checking clients (grpc_health_probe, Kubernetes gRPC
probes, the upstream HealthStub) interoperate unchanged. Both RPCs are
wired: unary `Check` (the scoreboard's half-open probes and orchestration
probes poll it) and server-streaming `Watch` (a subscriber gets the
current status immediately, then a message on every change — fleet
routers subscribe instead of polling). Per the health.proto contract,
Watch answers status SERVICE_UNKNOWN for a service the server does not
know — it does NOT abort, so the watcher keeps the stream and sees the
service appear later.
"""

from __future__ import annotations

import grpc

HEALTH_SERVICE_NAME = "grpc.health.v1.Health"

# ServingStatus values (health.proto enum, canonical numbering).
UNKNOWN = 0
SERVING = 1
NOT_SERVING = 2
SERVICE_UNKNOWN = 3

STATUS_NAMES = {
    UNKNOWN: "UNKNOWN",
    SERVING: "SERVING",
    NOT_SERVING: "NOT_SERVING",
    SERVICE_UNKNOWN: "SERVICE_UNKNOWN",
}


def _encode_varint(value: int) -> bytes:
    out = bytearray()
    while True:
        bits = value & 0x7F
        value >>= 7
        if value:
            out.append(bits | 0x80)
        else:
            out.append(bits)
            return bytes(out)


def _read_varint(data: bytes, pos: int) -> tuple[int, int]:
    result = shift = 0
    while True:
        if pos >= len(data):
            raise ValueError("truncated varint")
        b = data[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 63:
            raise ValueError("varint too long")


def _skip_field(data: bytes, pos: int, wire_type: int) -> int:
    """Unknown-field tolerance: future additions to the canonical proto
    must not break this parser."""
    if wire_type == 0:  # varint
        _, pos = _read_varint(data, pos)
        return pos
    if wire_type == 1:  # 64-bit
        return pos + 8
    if wire_type == 2:  # length-delimited
        length, pos = _read_varint(data, pos)
        return pos + length
    if wire_type == 5:  # 32-bit
        return pos + 4
    raise ValueError(f"unsupported wire type {wire_type}")


class HealthCheckRequest:
    __slots__ = ("service",)

    def __init__(self, service: str = ""):
        self.service = service

    def SerializeToString(self) -> bytes:
        if not self.service:
            return b""
        payload = self.service.encode("utf-8")
        return b"\x0a" + _encode_varint(len(payload)) + payload

    @classmethod
    def FromString(cls, data: bytes) -> "HealthCheckRequest":
        msg = cls()
        pos = 0
        while pos < len(data):
            tag, pos = _read_varint(data, pos)
            if tag == 0x0A:  # field 1, length-delimited
                length, pos = _read_varint(data, pos)
                msg.service = data[pos : pos + length].decode("utf-8")
                pos += length
            else:
                pos = _skip_field(data, pos, tag & 0x07)
        return msg


class HealthCheckResponse:
    __slots__ = ("status",)

    def __init__(self, status: int = UNKNOWN):
        self.status = status

    def SerializeToString(self) -> bytes:
        if not self.status:
            return b""  # proto3: default-valued scalar is omitted
        return b"\x08" + _encode_varint(self.status)

    @classmethod
    def FromString(cls, data: bytes) -> "HealthCheckResponse":
        msg = cls()
        pos = 0
        while pos < len(data):
            tag, pos = _read_varint(data, pos)
            if tag == 0x08:  # field 1, varint
                msg.status, pos = _read_varint(data, pos)
            else:
                pos = _skip_field(data, pos, tag & 0x07)
        return msg


class HealthStub:
    """Client stub: `stub.Check(HealthCheckRequest(...), timeout=...)`.
    Works on both sync and grpc.aio channels."""

    def __init__(self, channel: grpc.Channel):
        self.Check = channel.unary_unary(
            f"/{HEALTH_SERVICE_NAME}/Check",
            request_serializer=HealthCheckRequest.SerializeToString,
            response_deserializer=HealthCheckResponse.FromString,
        )
        self.Watch = channel.unary_stream(
            f"/{HEALTH_SERVICE_NAME}/Watch",
            request_serializer=HealthCheckRequest.SerializeToString,
            response_deserializer=HealthCheckResponse.FromString,
        )


class HealthServicer:
    """Service base class; override Check and Watch."""

    def Check(self, request, context):
        context.abort(grpc.StatusCode.UNIMPLEMENTED, "Check not implemented")

    def Watch(self, request, context):
        context.abort(grpc.StatusCode.UNIMPLEMENTED, "Watch not implemented")


def add_HealthServicer_to_server(servicer, server) -> None:
    handlers = {
        "Check": grpc.unary_unary_rpc_method_handler(
            servicer.Check,
            request_deserializer=HealthCheckRequest.FromString,
            response_serializer=HealthCheckResponse.SerializeToString,
        ),
        "Watch": grpc.unary_stream_rpc_method_handler(
            servicer.Watch,
            request_deserializer=HealthCheckRequest.FromString,
            response_serializer=HealthCheckResponse.SerializeToString,
        ),
    }
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(HEALTH_SERVICE_NAME, handlers),)
    )
