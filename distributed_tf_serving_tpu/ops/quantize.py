"""Post-training int8 weight quantization for the serving hot path (ISSUE 12).

ROADMAP item 3 / the Gemma-on-TPU serving recipe: serving-time matmuls are
HBM-bandwidth-bound at CTR batch sizes, so shrinking the weight bytes the
MXU streams per step is a direct speedup — int8 weights are 4x smaller than
f32 (2x smaller than the bf16 compute cast) — and the "300M predictions/s"
paper's fleet argument applies to every byte the serving path moves.

Scheme: **per-channel symmetric weight-only** quantization of the 2-D dense
matrices (DCN cross W_l, MLP layers, the output head):

    scale[o] = max|w[:, o]| / 127        (per OUTPUT channel)
    qw[i, o] = round(w[i, o] / scale[o])   in int8 [-127, 127]

Activations stay in the model's compute dtype (bf16 by default). At apply
time the matmul runs  x_bf16 @ qw.astype(bf16)  (int8 magnitudes <= 127 are
exactly representable in bf16, so the cast is lossless) with float32
accumulation, and the per-channel scale folds into the OUTPUT —
algebraically identical to dequantizing the weights first, but the scale
multiplies an [n, out] tile instead of materializing an [in, out] f32
matrix:

    y[n, o] = (x @ qw)[n, o] * scale[o] + b[o]

Quantization happens ONCE per servable (at load / first autotune), never
per request. The quantized tree uses the key triplet {"qw", "qscale", "b"}
in place of {"w", "b"}; models/base.py dense_apply and models/dcn.py
cross_apply accept either form, so the SAME model.apply serves both — the
batcher's jit cache retraces on the different param-tree structure and the
f32 and int8 executables coexist per bucket (the autotune harness in
ops/autotune.py decides per bucket which one live traffic gets).

Embedding tables are deliberately NOT quantized: the gather is
row-sparse (HBM reads only the looked-up rows), so int8 tables save
little live bandwidth while adding a dequant to the dominant op; the
dense matmuls are where the bytes-per-step win is.
"""

from __future__ import annotations

import numpy as np

Q8_MAX = 127  # symmetric int8 range [-127, 127]; -128 unused by design


def quantize_channelwise(w, axis: int = -1):
    """Per-channel symmetric int8 quantization of a float matrix.

    Returns (qw int8, scale float32) with scale shaped to broadcast along
    `axis` (the channel axis — the OUTPUT dim for dense weights). Works on
    numpy arrays and jax arrays alike (pure np on host is the load-time
    path); all-zero channels get scale 1.0 so dequant stays exact."""
    w = np.asarray(w, np.float32)
    reduce_axes = tuple(i for i in range(w.ndim) if i != (axis % w.ndim))
    amax = np.max(np.abs(w), axis=reduce_axes, keepdims=True)
    scale = np.where(amax > 0, amax / Q8_MAX, 1.0).astype(np.float32)
    qw = np.clip(np.rint(w / scale), -Q8_MAX, Q8_MAX).astype(np.int8)
    return qw, np.squeeze(scale, axis=reduce_axes).astype(np.float32)


def dequantize_channelwise(qw, scale, axis: int = -1) -> np.ndarray:
    """Inverse of quantize_channelwise (float32)."""
    qw = np.asarray(qw)
    shape = [1] * qw.ndim
    shape[axis % qw.ndim] = qw.shape[axis % qw.ndim]
    return qw.astype(np.float32) * np.asarray(scale, np.float32).reshape(shape)


def is_quantized_dense(p) -> bool:
    """True for the quantized dense-layer dict form {"qw","qscale","b"}."""
    return isinstance(p, dict) and "qw" in p


def _quantize_dense(p: dict) -> dict:
    qw, scale = quantize_channelwise(np.asarray(p["w"], np.float32), axis=-1)
    return {"qw": qw, "qscale": scale, "b": np.asarray(p["b"])}


def quantize_params(params, _top: bool = True):
    """Walk a model param tree and swap every 2-D float dense layer
    {"w": [in,out], "b": [out]} for its int8 weight-only form
    {"qw", "qscale", "b"}. Covers the DCN cross stack (full-matrix v2
    layers), MLP lists, and output heads across the zoo; everything else —
    embedding tables, DCN-v1 rank-1 cross vectors, biases — passes through
    unchanged (shared by reference, not copied: quantization never mutates
    the servable's live params)."""
    if isinstance(params, dict):
        w = params.get("w")
        if (
            w is not None
            and "b" in params
            and getattr(w, "ndim", 0) == 2
            and np.issubdtype(np.asarray(w).dtype, np.floating)
        ):
            return _quantize_dense(params)
        return {k: quantize_params(v, _top=False) for k, v in params.items()}
    if isinstance(params, (list, tuple)):
        out = [quantize_params(v, _top=False) for v in params]
        return type(params)(out) if isinstance(params, tuple) else out
    return params


def count_quantized(params) -> int:
    """Number of dense layers in their quantized form (test/telemetry)."""
    if isinstance(params, dict):
        if "qw" in params:
            return 1
        return sum(count_quantized(v) for v in params.values())
    if isinstance(params, (list, tuple)):
        return sum(count_quantized(v) for v in params)
    return 0


def quantized_param_bytes(params) -> tuple[int, int]:
    """(quantized_bytes, f32_equivalent_bytes) over the dense layers —
    the weight-stream shrink the autotune table reports."""
    q = f = 0
    if isinstance(params, dict):
        if "qw" in params:
            n = int(np.prod(params["qw"].shape))
            return n + params["qscale"].nbytes, n * 4
        for v in params.values():
            a, b = quantized_param_bytes(v)
            q, f = q + a, f + b
    elif isinstance(params, (list, tuple)):
        for v in params:
            a, b = quantized_param_bytes(v)
            q, f = q + a, f + b
    return q, f
