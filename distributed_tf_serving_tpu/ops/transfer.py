"""Host->device transfer compression for the serving hot path.

HBM/PCIe (and on this rig, relay-tunnel) bandwidth is the serving
bottleneck once compute is batched: the wire pays bytes-per-candidate, so
the batcher shrinks what crosses the host<->device boundary and undoes it
on-device inside the jitted executable (free: fuses into the embedding
lookup's index arithmetic).

Two lossless-under-the-model transforms:
- feat_ids: folded ids are < vocab_size; when vocab_size <= 2^24 the int32
  rows travel as 3 little-endian bytes each (u24), -25% id bytes. Unpack is
  three shifts+ors on device.
- feat_wts: when the model's compute dtype is bfloat16 AND the model
  consumes weights only through that cast (Model.wts_in_compute_dtype — true
  for dcn/dcn_v2/two_tower/dlrm via field_embed, false for wide_deep/deepfm
  whose sparse-linear term is f32), the f32 weights are pre-cast on host and
  travel as bf16 (-50% weight bytes) with bit-identical scores.

Together: 344 -> 215 bytes/candidate at 43 fields for the reference
workload (DCNClient.java:98-108 shapes).
"""

from __future__ import annotations

import jax.numpy as jnp
import ml_dtypes
import numpy as np

from ..models.base import Model

U24_MAX = 1 << 24


def transfer_spec(model: Model) -> dict[str, str]:
    """Per-input packing spec for a model; keys absent = pass-through."""
    config = model.config
    spec: dict[str, str] = {}
    if config.vocab_size <= U24_MAX and model.folds_ids_on_host:
        # u24 presumes host-folded int32 ids; graph-executor models ship
        # raw int64 ids to the device untouched.
        spec["feat_ids"] = "u24"
    if config.compute_dtype == "bfloat16" and model.wts_in_compute_dtype:
        spec["feat_wts"] = "bf16"
    return spec


def pack_host(arrays: dict[str, np.ndarray], spec: dict[str, str]) -> dict[str, np.ndarray]:
    """Apply the spec on host numpy arrays (post-fold, post-pad).

    Each transform runs through the native one-pass kernels
    (native/hostops.cc) when built, with bit-identical numpy fallbacks.
    """
    from .. import native

    use_native = bool(spec) and native.available()
    out = {}
    for key, arr in arrays.items():
        how = spec.get(key)
        if how == "u24":
            if arr.dtype != np.int32:
                raise ValueError(f"u24 packing expects folded int32 ids, got {arr.dtype}")
            if use_native:
                out[key] = native.pack_u24_i32(arr)
            else:
                b = np.ascontiguousarray(arr).view(np.uint8).reshape(*arr.shape, 4)
                out[key] = np.ascontiguousarray(b[..., :3])  # LE low 3 bytes
        elif how == "bf16":
            if arr.dtype == ml_dtypes.bfloat16:
                out[key] = arr  # compact-wire client already cast (RNE)
            elif use_native:
                out[key] = native.f32_to_bf16(arr)
            else:
                out[key] = arr.astype(ml_dtypes.bfloat16)
        else:
            out[key] = arr
    return out


def unpack_device(packed: dict[str, jnp.ndarray], spec: dict[str, str]) -> dict[str, jnp.ndarray]:
    """Inverse of pack_host, traced inside the jitted executable."""
    out = {}
    for key, arr in packed.items():
        how = spec.get(key)
        if how == "u24":
            b = arr.astype(jnp.int32)
            out[key] = b[..., 0] | (b[..., 1] << 8) | (b[..., 2] << 16)
        else:
            out[key] = arr  # bf16 weights feed the model directly
    return out


# ------------------------------------------------- combined single buffer
#
# Beyond shrinking bytes, the number of host->device TRANSFERS matters: on
# a relay-tunnel rig every device_put is a round trip, and even on PCIe
# each transfer has fixed submit cost. The combined path concatenates every
# (already spec-packed) input's bytes into ONE uint8 buffer — one upload
# per batch — and splits it back inside the jitted executable with static
# slices + bitcasts (free: fuses with the consumers).


# --------------------------------------------------- output compaction
#
# The inverse problem of the input spec above: the serving path must never
# ship full fp32 output tensors synchronously back to the host (the
# "300M predictions/s" paper attributes its serving wins to exactly this).
# Scores are downcast to a wire dtype ON-DEVICE (traced into the jitted
# entry, so the D2H transfer carries the small bytes) and widened back to
# float32 on the host by the batch completer before anything user-visible
# sees them; retrieval-style servables can go further and return only the
# top-k (score, index) pairs.

_WIRE_DTYPES = {"float32": None, "bfloat16": "bf16", "float16": "f16",
                "int8": "q8"}

# int8 score wire (ISSUE 12): f32 outputs cross the D2H link as affine-
# quantized int8 — 4x fewer bytes than f32, 2x fewer than the bf16
# compaction — with the per-tensor (scale, min) pair riding along as two
# 4-byte sidecar outputs the completer consumes (and strips) when it
# dequantizes back to f32. 254 levels over the tensor's live range keeps
# the worst-case error at range/508 (~0.002 for sigmoid CTR scores).
Q8_LEVELS = 254.0
Q8_SCALE_SUFFIX = "::q8scale"
Q8_MIN_SUFFIX = "::q8min"


def is_wire_sidecar(key: str) -> bool:
    """True for the scale/min sidecar keys the int8 wire mints — they must
    ride the D2H fetch even when an output filter narrowed the batch (the
    quantized score is undecodable without them), and they are stripped by
    restore_outputs_host before anything user-visible sees the dict."""
    return key.endswith(Q8_SCALE_SUFFIX) or key.endswith(Q8_MIN_SUFFIX)


def output_wire_dtype(name: str) -> np.dtype | None:
    """Validated numpy dtype for an output wire-dtype knob; None means
    float32 (no downcast — the full-precision fallback path)."""
    if name not in _WIRE_DTYPES:
        raise ValueError(
            f"unknown output wire dtype {name!r}; have {sorted(_WIRE_DTYPES)}"
        )
    if name == "float32":
        return None
    if name == "int8":
        return np.dtype(np.int8)
    return np.dtype(ml_dtypes.bfloat16 if name == "bfloat16" else np.float16)


def quantize_output_device(v: jnp.ndarray):
    """Traced affine int8 quantization of one f32 output tensor: returns
    (q int8, scale [1] f32, min [1] f32). Dynamic per-tensor range so
    logits (unbounded) quantize as well as sigmoid scores; a constant
    tensor gets the epsilon scale and round-trips exactly."""
    v32 = v.astype(jnp.float32)
    mn = jnp.min(v32)
    scale = jnp.maximum((jnp.max(v32) - mn) / Q8_LEVELS, 1e-8)
    q = jnp.clip(jnp.round((v32 - mn) / scale), 0.0, Q8_LEVELS) - 127.0
    return q.astype(jnp.int8), scale.reshape(1), mn.reshape(1)


def compact_outputs_device(
    outputs: dict[str, jnp.ndarray], wire_dt
) -> dict[str, jnp.ndarray]:
    """Traced into the jitted entry: downcast float32 outputs to the wire
    dtype on-device so only the compact bytes cross the D2H boundary.
    Non-f32 outputs (int tensors, an imported graph's f64) pass through —
    the transform must stay losslessly invertible by restore_outputs_host.
    The int8 wire additionally emits the per-tensor (scale, min) sidecar
    pair restore_outputs_host dequantizes with (and strips)."""
    if wire_dt is None:
        return dict(outputs)
    if wire_dt == np.dtype(np.int8):
        out: dict[str, jnp.ndarray] = {}
        for k, v in outputs.items():
            if v.dtype == jnp.float32:
                q, scale, mn = quantize_output_device(v)
                out[k] = q
                out[k + Q8_SCALE_SUFFIX] = scale
                out[k + Q8_MIN_SUFFIX] = mn
            else:
                out[k] = v
        return out
    return {
        k: v.astype(wire_dt) if v.dtype == jnp.float32 else v
        for k, v in outputs.items()
    }


def restore_outputs_host(host: dict[str, np.ndarray]) -> dict[str, np.ndarray]:
    """Completer-side inverse of compact_outputs_device: widen wire-dtype
    arrays back to float32 (dequantizing int8 entries via their sidecars,
    which are consumed here and never reach response assembly) so every
    downstream consumer (codec encode, Classify/Regress, request slicing)
    sees the signature dtype."""
    # Lazy: codec pulls the vendored proto bindings, and this module must
    # stay importable in the TF-export process (interop/export.py), which
    # forbids them at import time (descriptor-pool collision).
    from ..codec import dequantize_scores as _dequantize_scores

    out = {}
    for k, v in host.items():
        if is_wire_sidecar(k):
            continue
        if v.dtype == ml_dtypes.bfloat16 or v.dtype == np.float16:
            v = v.astype(np.float32)
        elif v.dtype == np.int8:
            scale = host.get(k + Q8_SCALE_SUFFIX)
            mn = host.get(k + Q8_MIN_SUFFIX)
            if scale is not None and mn is not None:
                # Genuine int8 model outputs carry no sidecars and pass
                # through untouched — only the wire's own quantization
                # (which minted the pair) is undone. ONE dequant
                # implementation (codec.dequantize_scores) serves both
                # the D2H and the response wires, so they cannot drift.
                v = _dequantize_scores(v, float(scale[0]), float(mn[0]))
        out[k] = v
    return out


def topk_compact_device(scores: jnp.ndarray, n_valid, k: int, wire_dt) -> dict:
    """Top-k output compaction, traced into the jitted entry: only the k
    best (score, index) pairs of the first `n_valid` rows cross the wire
    (padding rows are masked to -inf so they can never outrank a real
    candidate). `n_valid` is a traced scalar — one executable per
    (bucket, k), not per request size."""
    import jax

    mask = jnp.arange(scores.shape[0]) < n_valid
    masked = jnp.where(mask, scores.astype(jnp.float32), -jnp.inf)
    vals, idx = jax.lax.top_k(masked, k)
    if wire_dt is not None:
        if wire_dt == np.dtype(np.int8):
            # The top-k wire is already k pairs — int8 would save a
            # handful of bytes while complicating the host scatter with
            # sidecars; bf16 keeps the compaction without the machinery.
            wire_dt = np.dtype(ml_dtypes.bfloat16)
        vals = vals.astype(wire_dt)
    return {"topk_scores": vals, "topk_indices": idx.astype(jnp.int32)}


def cascade_prune_device(scores: jnp.ndarray, n_valid, k: int, wire_dt) -> dict:
    """Stage-1 prune for the multi-stage cascade, traced into the jitted
    entry: the k best (score, index) survivor pairs PLUS the full stage-1
    score vector cross the wire — the vector because cascade responses
    fill non-survivor positions from stage-1 scores, so it must come back
    anyway, and shipping it at wire dtype alongside the pairs is one
    readback instead of a second submit. Padding rows are masked to -inf
    for the selection exactly like topk_compact_device (they can never
    survive); the returned vector is unmasked because the completer
    slices it to the request's n rows before anything user-visible sees
    it. `n_valid` is a traced scalar — one executable per (bucket, k)."""
    import jax

    mask = jnp.arange(scores.shape[0]) < n_valid
    masked = jnp.where(mask, scores.astype(jnp.float32), -jnp.inf)
    vals, idx = jax.lax.top_k(masked, k)
    full = scores.astype(jnp.float32)
    if wire_dt is not None:
        if wire_dt == np.dtype(np.int8):
            # Same call as the top-k wire: int8 would drag quantization
            # sidecars through the survivor scatter for a handful of
            # bytes; bf16 keeps the compaction without the machinery.
            wire_dt = np.dtype(ml_dtypes.bfloat16)
        vals = vals.astype(wire_dt)
        full = full.astype(wire_dt)
    return {
        "survivor_scores": vals,
        "survivor_indices": idx.astype(jnp.int32),
        "stage1_scores": full,
    }


def topk_restore_host(vals, idx, n: int, score_key: str) -> dict[str, np.ndarray]:
    """Host-side inverse of topk_compact_device: scatter the k pairs back
    into a full-length float32 vector with 0.0 off the head. Sigmoid CTR
    scores are strictly positive, so ranking consumers (the reference
    client sorts and takes the head) see the exact same top-k order; the
    tail is explicitly "not ranked", not an approximation."""
    scores = np.zeros(n, np.float32)
    scores[np.asarray(idx)] = np.asarray(vals).astype(np.float32)
    return {score_key: scores}


def combined_supported(arrays: dict[str, np.ndarray]) -> bool:
    """True when every array can be reconstructed by the device-side
    bitcast: fixed-width numerics up to 4 bytes. ml_dtypes.bfloat16 is
    explicitly included — its numpy dtype.kind is 'V' (void), not 'f', so
    a kind test alone rejects exactly the compact-wire weights this path
    exists to carry (round-4 review finding: the first compact request
    permanently demoted the servable to the per-key path). Excluded (these
    pin the per-key fallback in the batcher): bool (bitcast_convert_type
    rejects it), 8-byte dtypes (x32 canonicalization makes the
    8-trailing-bytes bitcast unsatisfiable — the per-key path's device_put
    downcast is the documented behavior for those), strings/objects."""
    return all(
        (a.dtype.kind in "iuf" and a.dtype.itemsize in (1, 2, 4))
        or a.dtype == ml_dtypes.bfloat16
        for a in arrays.values()
    )


def combined_layout(arrays: dict[str, np.ndarray], spec: dict[str, str]) -> tuple:
    """Pure-metadata layout for the combined buffer: a hashable tuple of
    per-input entries (key, kind, trailing_shape, per_candidate_bytes,
    packed_dtype_str), key-sorted. Static under jit (rides static_argnums)
    and computable WITHOUT packing — the content cache derives its key from
    the raw arrays plus this layout, so a hit skips the pack entirely."""
    layout = []
    for key in sorted(arrays):
        arr = arrays[key]
        kind = spec.get(key, "raw")
        trailing = tuple(int(t) for t in arr.shape[1:])
        inner = int(np.prod(trailing)) if trailing else 1
        if kind == "u24":
            layout.append((key, "u24", trailing, inner * 3, "u24"))
        elif kind == "bf16":
            layout.append((key, "raw", trailing, inner * 2, "bfloat16"))
        else:
            layout.append(
                (key, "raw", trailing, inner * arr.dtype.itemsize, arr.dtype.name)
            )
    return tuple(layout)


def pack_host_combined(
    arrays: dict[str, np.ndarray], spec: dict[str, str]
) -> np.ndarray:
    """Spec-pack each input, then concatenate the raw bytes into one uint8
    buffer (same sorted key order as combined_layout)."""
    packed = pack_host(arrays, spec)
    segs = [
        np.ascontiguousarray(packed[key]).view(np.uint8).ravel()
        for key in sorted(packed)
    ]
    return np.concatenate(segs) if len(segs) > 1 else segs[0]


def unpack_device_combined(buf: jnp.ndarray, layout: tuple) -> dict[str, jnp.ndarray]:
    """Inverse of pack_host_combined, traced inside the jitted executable.
    Slices are static (n derives from the buffer length and the layout's
    per-candidate byte totals), bitcasts collapse the byte dim."""
    from jax import lax

    total_pcb = sum(e[3] for e in layout)
    n = buf.shape[0] // total_pcb
    out = {}
    off = 0
    for key, kind, trailing, per_cand, dtype_str in layout:
        nb = n * per_cand
        seg = buf[off:off + nb]
        off += nb
        if kind == "u24":
            b = seg.reshape((n, *trailing, 3)).astype(jnp.int32)
            out[key] = b[..., 0] | (b[..., 1] << 8) | (b[..., 2] << 16)
        else:
            dt = jnp.dtype(dtype_str)
            if dt.itemsize == 1:
                out[key] = lax.bitcast_convert_type(seg.reshape((n, *trailing)), dt)
            else:
                out[key] = lax.bitcast_convert_type(
                    seg.reshape((n, *trailing, dt.itemsize)), dt
                )
    return out
