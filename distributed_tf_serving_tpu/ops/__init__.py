"""Hot-path ops: transfer compression, Pallas kernels (cross-layer, lookup)."""

from .transfer import pack_host, transfer_spec, unpack_device

__all__ = ["pack_host", "transfer_spec", "unpack_device"]
