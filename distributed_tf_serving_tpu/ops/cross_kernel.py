"""Pallas TPU kernel: fused DCN-v2 cross-layer stack.

The cross network applies L layers of x = x0 * (x @ W_l + b_l) + x
(models/dcn.py cross_apply). Under plain XLA each layer's output round-trips
through HBM between matmuls; this kernel keeps the activation tile resident
in VMEM across ALL layers — one HBM read of the x0 tile, L MXU matmuls
against VMEM-resident weights, one HBM write — turning an
HBM-bandwidth-bound stack into an MXU-bound one for serving-sized tiles.

Numerics mirror cross_apply exactly: matmul in the model's compute dtype
with f32 accumulation (preferred_element_type), the elementwise update in
f32, the carried activation cast back to compute dtype per layer — so the
kernel is a drop-in for the XLA path (test_cross_kernel.py pins equality).

Shapes are padded to TPU tiling (d -> multiple of 128 lanes, rows -> the
row-tile size): zero-padded W rows/cols and b lanes keep padded activation
columns identically zero through every layer, so padding never leaks into
real outputs.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

LANE = 128
DEFAULT_ROW_TILE = 256
# Conservative per-core VMEM working budget (v4/v5e have ~16 MB; leave room
# for Mosaic's own scratch and double-buffered DMA).
VMEM_BUDGET_BYTES = 12 * 1024 * 1024


def fits_vmem(
    d: int,
    num_layers: int,
    compute_dtype=jnp.bfloat16,
    row_tile: int = DEFAULT_ROW_TILE,
) -> bool:
    """Whether the fused kernel's resident set fits in VMEM.

    The constant-index weight BlockSpec keeps ALL L (dp x dp) matrices
    resident at once; past the budget Mosaic fails to lower (or thrashes),
    so callers must fall back to the per-layer XLA path."""
    dp = _pad_to(d, LANE)
    itemsize = jnp.dtype(compute_dtype).itemsize
    weights = num_layers * dp * dp * itemsize
    biases = num_layers * dp * 4
    # x0 tile (cd) + x0_f32 + f32 layer temps + out tile ~ 12 bytes/elem.
    tiles = row_tile * dp * 12
    return weights + biases + tiles <= VMEM_BUDGET_BYTES


def _cross_kernel(x0_ref, w_ref, b_ref, out_ref, *, num_layers: int, compute_dtype):
    x0 = x0_ref[:]  # (BN, dp) in compute dtype
    x0_f32 = x0.astype(jnp.float32)

    def layer(l, x):
        xw = jax.lax.dot_general(
            x,
            w_ref[l],
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        b = b_ref[l].astype(jnp.float32)
        nxt = x0_f32 * (xw + b) + x.astype(jnp.float32)
        return nxt.astype(compute_dtype)

    out_ref[:] = jax.lax.fori_loop(0, num_layers, layer, x0)


def _pad_to(value: int, multiple: int) -> int:
    return (value + multiple - 1) // multiple * multiple


@functools.partial(
    jax.jit, static_argnames=("compute_dtype", "row_tile", "interpret")
)
def fused_cross_apply(
    x0: jax.Array,  # [n, d]
    w: jax.Array,  # [L, d, d]
    b: jax.Array,  # [L, d]
    *,
    compute_dtype=jnp.bfloat16,
    row_tile: int = DEFAULT_ROW_TILE,
    interpret: bool = False,
) -> jax.Array:
    """Apply the full DCN-v2 cross stack in one fused kernel; returns [n, d]
    in compute_dtype (matching models/dcn.py cross_apply output)."""
    n, d = x0.shape
    num_layers = w.shape[0]
    if not fits_vmem(d, num_layers, compute_dtype, row_tile):
        raise ValueError(
            f"fused cross stack (d={d}, L={num_layers}) exceeds the "
            f"{VMEM_BUDGET_BYTES >> 20} MB VMEM budget; use cross_apply "
            "(models/dcn.py falls back automatically via fits_vmem)"
        )
    dp = _pad_to(d, LANE)
    bn = min(row_tile, _pad_to(n, 8))
    np_ = _pad_to(n, bn)

    cd = jnp.dtype(compute_dtype)
    x0p = jnp.zeros((np_, dp), cd).at[:n, :d].set(x0.astype(cd))
    wp = jnp.zeros((num_layers, dp, dp), cd).at[:, :d, :d].set(w.astype(cd))
    bp = jnp.zeros((num_layers, dp), jnp.float32).at[:, :d].set(b.astype(jnp.float32))

    kernel = functools.partial(
        _cross_kernel, num_layers=num_layers, compute_dtype=cd
    )
    out = pl.pallas_call(
        kernel,
        grid=(np_ // bn,),
        in_specs=[
            pl.BlockSpec((bn, dp), lambda i: (i, 0), memory_space=pltpu.VMEM),
            # Constant index maps: weights/biases DMA'd into VMEM once and
            # stay resident across all row tiles.
            pl.BlockSpec((num_layers, dp, dp), lambda i: (0, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((num_layers, dp), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((bn, dp), lambda i: (i, 0), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((np_, dp), cd),
        interpret=interpret,
    )(x0p, wp, bp)
    return out[:n, :d]


def cross_params_to_stacked(cross_layers: list) -> tuple[jax.Array, jax.Array]:
    """models/dcn.py stores cross params as a list of {'w': [d,d], 'b': [d]};
    stack them for the kernel. Only full-matrix (DCN-v2) layers qualify."""
    if not cross_layers or cross_layers[0]["w"].ndim != 2:
        raise ValueError("fused cross kernel requires DCN-v2 (full-matrix) layers")
    w = jnp.stack([p["w"] for p in cross_layers])
    b = jnp.stack([p["b"] for p in cross_layers])
    return w, b


# ===========================================================================
# Fused SERVING kernel (ISSUE 12): embedding-gather + cross + MLP head
# ===========================================================================
#
# The cross-only kernel above lost to XLA on-chip (BENCH r2-r5: 0.81-0.96x)
# because it fused the one stage XLA already runs near the roofline. This
# rework fuses the WHOLE per-candidate serving step into one kernel so the
# intermediate activations (the [n, F, D] gathered embeddings, the [n, d]
# cross/MLP activations) never round-trip through HBM at all:
#
#   ids --(per-row DMA gather from the HBM-resident table)--> x0 in VMEM
#      --> L cross layers --> MLP stack --> output head --> sigmoid
#
# int8 weights are FIRST-CLASS operands: the quantized variant streams the
# ops/quantize.py per-channel int8 matrices (4x fewer weight bytes than
# f32) and folds the per-output-channel scale into the f32 accumulator —
# the same algebra as models/base.py dense_apply, inside the kernel.
#
# Mosaic/interpret caveats, stated honestly: the gather issues one small
# (1, D) DMA per (row, field) pair — correct everywhere (interpret mode
# included; CPU tests run it), but on real hardware its win depends on the
# DMA engine hiding the latency, which is exactly why ops/autotune.py
# enables this kernel per bucket ONLY where it measures faster than the
# XLA path on the live device (a kernel that fails to lower or loses is
# recorded and left disabled — the BENCH_r05 lesson, now enforced by
# machinery instead of a docstring).

_SERVE_ROW_TILE = 128


def serve_fits_vmem(
    d: int,
    num_layers: int,
    mlp_dims: tuple[int, ...],
    compute_dtype=jnp.bfloat16,
    row_tile: int = _SERVE_ROW_TILE,
    quantized: bool = False,
) -> bool:
    """Whether the fused serving kernel's VMEM-resident set fits: all cross
    + MLP + head weights (int8 when quantized) plus the per-tile activation
    scratch. The embedding table stays in HBM and never counts."""
    dp = _pad_to(d, LANE)
    itemsize = 1 if quantized else jnp.dtype(compute_dtype).itemsize
    weights = num_layers * dp * dp * itemsize + num_layers * dp * 8
    d_in = dp
    for m in mlp_dims:
        mp = _pad_to(m, LANE)
        weights += d_in * mp * itemsize + mp * 8
        d_in = mp
    weights += (dp + d_in) * LANE * 4  # output head (f32 col block)
    # x0 f32 + compute-dtype copy + cross/mlp f32 temps + two out tiles.
    tiles = row_tile * dp * 16 + row_tile * LANE * 8
    return weights + tiles <= VMEM_BUDGET_BYTES


def serve_params_supported(params) -> bool:
    """True when a servable's param tree has the dcn_v2 shape the fused
    serving kernel understands: an embedding table, a full-matrix cross
    stack, an MLP list, and a 1-wide output head — in either the float
    {"w"} or the ops/quantize.py {"qw"} form."""
    try:
        emb = params["embedding"]
        cross, mlp, out = params["cross"], params["mlp"], params["out"]
    except (KeyError, TypeError):
        return False

    def dense_ok(p, out_dim=None):
        w = p.get("qw", p.get("w"))
        if w is None or w.ndim != 2:
            return False
        return out_dim is None or w.shape[1] == out_dim

    if getattr(emb, "ndim", 0) != 2 or not cross or not mlp:
        return False
    return (
        all(dense_ok(p) for p in cross)
        and all(dense_ok(p) for p in mlp)
        and dense_ok(out, out_dim=1)
    )


def _pad2(arr, rows: int, cols: int, dtype) -> jnp.ndarray:
    out = jnp.zeros((rows, cols), dtype)
    a = jnp.asarray(arr)
    return out.at[: a.shape[0], : a.shape[1]].set(a.astype(dtype))


def _pad1(arr, cols: int, dtype=jnp.float32) -> jnp.ndarray:
    a = jnp.asarray(arr)
    return jnp.zeros((cols,), dtype).at[: a.shape[0]].set(a.astype(dtype))


def _prep_dense(p: dict, rows: int, cols: int, cd):
    """(w_padded, scale_padded_or_None, b_padded) for one dense layer in
    either param form. int8 weights stay int8 (the operand win); scales
    pad with ONES so padded output channels stay exactly zero after the
    zero-padded weights."""
    if "qw" in p:
        w = _pad2(p["qw"], rows, cols, jnp.int8)
        s = jnp.ones((cols,), jnp.float32).at[: p["qscale"].shape[0]].set(
            jnp.asarray(p["qscale"], jnp.float32)
        )
    else:
        w = _pad2(p["w"], rows, cols, cd)
        s = None
    return w, s, _pad1(p["b"], cols)


def build_fused_serve(params, config, *, interpret: bool = False,
                      row_tile: int = _SERVE_ROW_TILE):
    """Build the fused-serving callable for ONE servable's params
    (float or ops/quantize.py-quantized tree).

    Returns apply_fn(params, batch) -> {"prediction_node", "logits"} with
    the model.apply contract the batcher's jitted entries expect. The
    weight operands are prepared (stacked/padded/cast) HERE, once, and
    closed over — they enter the jaxpr as constants, so per-call tracing
    never re-pads the parameter set; the `params` argument is accepted for
    signature compatibility and deliberately unused (ops/autotune.py
    rebuilds this callable when a servable's params object is swapped).
    `batch` must carry host-folded int32 feat_ids and feat_wts."""
    cfg = config
    cd = cfg.cdtype
    F, D = cfg.num_fields, cfg.embed_dim
    d = F * D
    dp = _pad_to(d, LANE)
    L = len(params["cross"])
    mlp_dims = tuple(
        (p.get("qw", p.get("w"))).shape[1] for p in params["mlp"]
    )
    quantized = "qw" in params["cross"][0]
    if not serve_params_supported(params):
        raise ValueError("fused serving kernel requires a dcn_v2 param tree")
    if not serve_fits_vmem(d, L, mlp_dims, cd, row_tile, quantized):
        raise ValueError(
            f"fused serving kernel (d={d}, L={L}, mlp={mlp_dims}) exceeds "
            f"the {VMEM_BUDGET_BYTES >> 20} MB VMEM budget"
        )

    table = jnp.asarray(params["embedding"], jnp.float32)  # HBM-resident
    # Cross stack: [L, dp, dp] (+ [L, dp] scales when quantized) + biases.
    if quantized:
        wc = jnp.stack([_pad2(p["qw"], dp, dp, jnp.int8) for p in params["cross"]])
        sc = jnp.stack([
            jnp.ones((dp,), jnp.float32).at[: p["qscale"].shape[0]].set(
                jnp.asarray(p["qscale"], jnp.float32))
            for p in params["cross"]
        ])
    else:
        wc = jnp.stack([_pad2(p["w"], dp, dp, cd) for p in params["cross"]])
        sc = None
    bc = jnp.stack([_pad1(p["b"], dp) for p in params["cross"]])
    # MLP stack: per-layer padded operands (dims differ per layer).
    mlp_ops = []
    d_in = dp
    for p, m in zip(params["mlp"], mlp_dims):
        mp = _pad_to(m, LANE)
        mlp_ops.append(_prep_dense(p, d_in, mp, cd))
        d_in = mp
    mp_last = d_in
    # Output head: [dp + mp_last, LANE] f32 column block, col 0 real. The
    # head is one [*, 1] matvec — f32 operands cost nothing material and
    # skip a quantization step whose win would be ~512 bytes.
    out_p = params["out"]
    w_out = out_p.get("qw")
    if w_out is not None:
        w_full = np.asarray(w_out, np.float32) * np.asarray(
            out_p["qscale"], np.float32
        )[None, :]
    else:
        w_full = np.asarray(out_p["w"], np.float32)
    wo = jnp.zeros((dp + mp_last, LANE), jnp.float32)
    wo = wo.at[:d, 0].set(jnp.asarray(w_full[:d, 0]))
    wo = wo.at[dp: dp + mlp_dims[-1], 0].set(jnp.asarray(w_full[d:, 0]))
    bo = jnp.zeros((1, LANE), jnp.float32).at[0, 0].set(
        jnp.asarray(out_p["b"], jnp.float32)[0]
    )

    def kernel(ids_ref, *refs):
        # Positional layout mirrors in_specs + out_specs + scratch_shapes:
        # wts, cross (w[, s], b), per-mlp-layer (w[, s], b), head (w, b),
        # table, then the two out tiles and the three scratch operands.
        it = iter(refs)
        wts_ref = next(it)
        wc_ref = next(it)
        sc_ref = next(it) if quantized else None
        bc_ref = next(it)
        mlp_refs = []
        for _, s, _ in mlp_ops:
            wr = next(it)
            sr = next(it) if s is not None else None
            br = next(it)
            mlp_refs.append((wr, sr, br))
        wo_ref, bo_ref = next(it), next(it)
        table_ref = next(it)
        pred_ref, logit_ref = next(it), next(it)
        x0_s, emb_s, sem = next(it), next(it), next(it)
        i = pl.program_id(0)
        bn = x0_s.shape[0]

        # ---- embedding gather: one (1, D) DMA per (row, field) from the
        # HBM table, weighted into the VMEM-resident x0 tile. Fields are a
        # static Python loop (F is small and the f*D slice start must be
        # static); rows ride fori_loop. The scalar-prefetched ids (SMEM)
        # are exactly what computes the DMA source index.
        def gather_row(r, carry):
            wrow = wts_ref[pl.ds(r, 1), :]  # (1, F_pad) f32
            for f in range(F):
                idx = ids_ref[i * bn + r, f]
                copy = pltpu.make_async_copy(
                    table_ref.at[pl.ds(idx, 1), :], emb_s, sem
                )
                copy.start()
                copy.wait()
                x0_s[pl.ds(r, 1), pl.ds(f * D, D)] = (
                    emb_s[:, :] * wrow[0, f]
                )
            return carry

        # Scratch arrives uninitialized: the padded lane tail [d, dp) must
        # be EXACTLY zero (garbage there rides NaN*0=NaN through the
        # zero-padded weights), and only [0, d) is written by the gather.
        x0_s[:, :] = jnp.zeros_like(x0_s)
        jax.lax.fori_loop(0, bn, gather_row, 0)

        x0_f32 = x0_s[:, :]
        x0 = x0_f32.astype(cd)

        # ---- cross stack (the existing _cross_kernel math, quantized-
        # aware: per-channel scale folds into the f32 xw).
        def cross_layer(l, x):
            xw = jax.lax.dot_general(
                x, wc_ref[l].astype(cd), (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            if sc_ref is not None:
                xw = xw * sc_ref[pl.ds(l, 1), :]
            nxt = x0_f32 * (xw + bc_ref[pl.ds(l, 1), :]) + x.astype(jnp.float32)
            return nxt.astype(cd)

        xc = jax.lax.fori_loop(0, L, cross_layer, x0)

        # ---- MLP stack over x0 (models/base.py mlp_apply, final relu).
        h = x0
        for wr, sr, br in mlp_refs:
            y = jax.lax.dot_general(
                h, wr[:, :].astype(cd), (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            if sr is not None:
                y = y * sr[:].reshape(1, -1)
            y = y + br[:].reshape(1, -1)
            h = jax.nn.relu(y).astype(cd)

        # ---- output head: logit = [xc | xd] @ w_out + b (col 0 real).
        lo = (
            jax.lax.dot_general(
                xc.astype(jnp.float32), wo_ref[:dp, :],
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            + jax.lax.dot_general(
                h.astype(jnp.float32), wo_ref[dp:, :],
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            + bo_ref[:, :]
        )
        logit_ref[:, :] = lo
        pred_ref[:, :] = jax.nn.sigmoid(lo)

    def apply_fn(_params, batch):
        ids = batch["feat_ids"].astype(jnp.int32)
        wts = batch["feat_wts"].astype(jnp.float32)
        n = ids.shape[0]
        bn = min(row_tile, _pad_to(n, 8))
        np_ = _pad_to(n, bn)
        f_pad = _pad_to(F, LANE)
        ids_p = jnp.zeros((np_, F), jnp.int32).at[:n, :].set(ids)
        wts_p = jnp.zeros((np_, f_pad), jnp.float32).at[:n, :F].set(wts)

        weight_args = [wc] + ([sc] if quantized else []) + [bc]
        in_specs = [
            pl.BlockSpec((bn, f_pad), lambda i, *_: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((L, dp, dp), lambda i, *_: (0, 0, 0),
                         memory_space=pltpu.VMEM),
        ]
        if quantized:
            in_specs.append(pl.BlockSpec((L, dp), lambda i, *_: (0, 0),
                                         memory_space=pltpu.VMEM))
        in_specs.append(pl.BlockSpec((L, dp), lambda i, *_: (0, 0),
                                     memory_space=pltpu.VMEM))
        for (w, s, b) in mlp_ops:
            weight_args.append(w)
            in_specs.append(pl.BlockSpec(w.shape, lambda i, *_: (0, 0),
                                         memory_space=pltpu.VMEM))
            if s is not None:
                weight_args.append(s)
                in_specs.append(pl.BlockSpec(s.shape, lambda i, *_: (0,),
                                             memory_space=pltpu.VMEM))
            weight_args.append(b)
            in_specs.append(pl.BlockSpec(b.shape, lambda i, *_: (0,),
                                         memory_space=pltpu.VMEM))
        weight_args += [wo, bo]
        in_specs += [
            pl.BlockSpec(wo.shape, lambda i, *_: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec(bo.shape, lambda i, *_: (0, 0), memory_space=pltpu.VMEM),
        ]
        # The table: whole-array, compiler-placed (HBM) — gathered by DMA.
        weight_args.append(table)
        in_specs.append(pl.BlockSpec(memory_space=pltpu.ANY))

        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(np_ // bn,),
            in_specs=in_specs,
            out_specs=[
                pl.BlockSpec((bn, LANE), lambda i, *_: (i, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((bn, LANE), lambda i, *_: (i, 0),
                             memory_space=pltpu.VMEM),
            ],
            scratch_shapes=[
                pltpu.VMEM((bn, dp), jnp.float32),
                pltpu.VMEM((1, D), jnp.float32),
                pltpu.SemaphoreType.DMA,
            ],
        )
        pred, logit = pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=[
                jax.ShapeDtypeStruct((np_, LANE), jnp.float32),
                jax.ShapeDtypeStruct((np_, LANE), jnp.float32),
            ],
            interpret=interpret,
        )(ids_p, wts_p, *weight_args)
        return {
            "prediction_node": pred[:n, 0],
            "logits": logit[:n, 0],
        }

    return apply_fn
