"""Pallas TPU kernel: fused DCN-v2 cross-layer stack.

The cross network applies L layers of x = x0 * (x @ W_l + b_l) + x
(models/dcn.py cross_apply). Under plain XLA each layer's output round-trips
through HBM between matmuls; this kernel keeps the activation tile resident
in VMEM across ALL layers — one HBM read of the x0 tile, L MXU matmuls
against VMEM-resident weights, one HBM write — turning an
HBM-bandwidth-bound stack into an MXU-bound one for serving-sized tiles.

Numerics mirror cross_apply exactly: matmul in the model's compute dtype
with f32 accumulation (preferred_element_type), the elementwise update in
f32, the carried activation cast back to compute dtype per layer — so the
kernel is a drop-in for the XLA path (test_cross_kernel.py pins equality).

Shapes are padded to TPU tiling (d -> multiple of 128 lanes, rows -> the
row-tile size): zero-padded W rows/cols and b lanes keep padded activation
columns identically zero through every layer, so padding never leaks into
real outputs.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

LANE = 128
DEFAULT_ROW_TILE = 256
# Conservative per-core VMEM working budget (v4/v5e have ~16 MB; leave room
# for Mosaic's own scratch and double-buffered DMA).
VMEM_BUDGET_BYTES = 12 * 1024 * 1024


def fits_vmem(
    d: int,
    num_layers: int,
    compute_dtype=jnp.bfloat16,
    row_tile: int = DEFAULT_ROW_TILE,
) -> bool:
    """Whether the fused kernel's resident set fits in VMEM.

    The constant-index weight BlockSpec keeps ALL L (dp x dp) matrices
    resident at once; past the budget Mosaic fails to lower (or thrashes),
    so callers must fall back to the per-layer XLA path."""
    dp = _pad_to(d, LANE)
    itemsize = jnp.dtype(compute_dtype).itemsize
    weights = num_layers * dp * dp * itemsize
    biases = num_layers * dp * 4
    # x0 tile (cd) + x0_f32 + f32 layer temps + out tile ~ 12 bytes/elem.
    tiles = row_tile * dp * 12
    return weights + biases + tiles <= VMEM_BUDGET_BYTES


def _cross_kernel(x0_ref, w_ref, b_ref, out_ref, *, num_layers: int, compute_dtype):
    x0 = x0_ref[:]  # (BN, dp) in compute dtype
    x0_f32 = x0.astype(jnp.float32)

    def layer(l, x):
        xw = jax.lax.dot_general(
            x,
            w_ref[l],
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        b = b_ref[l].astype(jnp.float32)
        nxt = x0_f32 * (xw + b) + x.astype(jnp.float32)
        return nxt.astype(compute_dtype)

    out_ref[:] = jax.lax.fori_loop(0, num_layers, layer, x0)


def _pad_to(value: int, multiple: int) -> int:
    return (value + multiple - 1) // multiple * multiple


@functools.partial(
    jax.jit, static_argnames=("compute_dtype", "row_tile", "interpret")
)
def fused_cross_apply(
    x0: jax.Array,  # [n, d]
    w: jax.Array,  # [L, d, d]
    b: jax.Array,  # [L, d]
    *,
    compute_dtype=jnp.bfloat16,
    row_tile: int = DEFAULT_ROW_TILE,
    interpret: bool = False,
) -> jax.Array:
    """Apply the full DCN-v2 cross stack in one fused kernel; returns [n, d]
    in compute_dtype (matching models/dcn.py cross_apply output)."""
    n, d = x0.shape
    num_layers = w.shape[0]
    if not fits_vmem(d, num_layers, compute_dtype, row_tile):
        raise ValueError(
            f"fused cross stack (d={d}, L={num_layers}) exceeds the "
            f"{VMEM_BUDGET_BYTES >> 20} MB VMEM budget; use cross_apply "
            "(models/dcn.py falls back automatically via fits_vmem)"
        )
    dp = _pad_to(d, LANE)
    bn = min(row_tile, _pad_to(n, 8))
    np_ = _pad_to(n, bn)

    cd = jnp.dtype(compute_dtype)
    x0p = jnp.zeros((np_, dp), cd).at[:n, :d].set(x0.astype(cd))
    wp = jnp.zeros((num_layers, dp, dp), cd).at[:, :d, :d].set(w.astype(cd))
    bp = jnp.zeros((num_layers, dp), jnp.float32).at[:, :d].set(b.astype(jnp.float32))

    kernel = functools.partial(
        _cross_kernel, num_layers=num_layers, compute_dtype=cd
    )
    out = pl.pallas_call(
        kernel,
        grid=(np_ // bn,),
        in_specs=[
            pl.BlockSpec((bn, dp), lambda i: (i, 0), memory_space=pltpu.VMEM),
            # Constant index maps: weights/biases DMA'd into VMEM once and
            # stay resident across all row tiles.
            pl.BlockSpec((num_layers, dp, dp), lambda i: (0, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((num_layers, dp), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((bn, dp), lambda i: (i, 0), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((np_, dp), cd),
        interpret=interpret,
    )(x0p, wp, bp)
    return out[:n, :d]


def cross_params_to_stacked(cross_layers: list) -> tuple[jax.Array, jax.Array]:
    """models/dcn.py stores cross params as a list of {'w': [d,d], 'b': [d]};
    stack them for the kernel. Only full-matrix (DCN-v2) layers qualify."""
    if not cross_layers or cross_layers[0]["w"].ndim != 2:
        raise ValueError("fused cross kernel requires DCN-v2 (full-matrix) layers")
    w = jnp.stack([p["w"] for p in cross_layers])
    b = jnp.stack([p["b"] for p in cross_layers])
    return w, b
