"""Per-bucket kernel autotune harness (ISSUE 12) — the machinery that
turned the BENCH_r05 lesson ("the Pallas kernel loses to XLA; leave it
dead") into an enforced invariant: **no execution variant serves live
traffic unless it measured faster than the baseline on THIS device at
THIS bucket and passed the accuracy gates.**

Variants per (servable, bucket), all minted through the batcher's OWN
jitted entries so measurement and serving share compiled executables:

  - baseline:   XLA, float params (today's path — always available)
  - xla_int8:   XLA, ops/quantize.py int8 weight-only params
  - pallas:     ops/cross_kernel.py fused gather+cross+MLP kernel, float
  - pallas_int8: the fused kernel with int8 weight operands

Gates (config, [kernels] section): measured speedup >= min_speedup AND
max |Δscore| vs the f32 baseline <= max_abs_delta AND — when a labeled
eval set is supplied (bench.py's trained-model block, the CI smoke) —
|AUC_f32 - AUC_variant| <= auc_margin. A variant that fails to compile,
lower, or gate is recorded with its reason and left DISABLED; in
measure_only mode everything is recorded and nothing is enabled (the CI
smoke's contract). The per-bucket decision picks the fastest enabled
variant.

The decision table persists to artifacts/kernel_autotune.json keyed by
(model, version, PARAMS DIGEST, device kind, gate fingerprint) so a
restart adopts its own prior measurements instead of re-tuning, while a
version hot-swap or a same-version retrain misses the key by
construction; live decisions are additionally identity-guarded per tuned
Servable object, so a new canary never inherits the old version's
enablement and the stable version keeps its measured win across registry
events.

Also owns the module-level gate for the int8 score RESPONSE wire (the
x-dts-score-wire metadata opt-in — servers scan request metadata only
while a kernels plane armed it; the overload/lifecycle `active()`
precedent).
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
import weakref

import numpy as np

log = logging.getLogger("dts_tpu.kernels")

# Request-metadata key for the int8 score response wire (client opt-in).
SCORE_WIRE_KEY = "x-dts-score-wire"

_WIRE_ACTIVE = False


def wire_active() -> bool:
    """True while a kernels plane with int8_score_wire is armed — the
    transport adapters scan request metadata only then (two module reads
    per RPC otherwise zero)."""
    return _WIRE_ACTIVE


def set_wire_active(on: bool) -> None:
    global _WIRE_ACTIVE
    _WIRE_ACTIVE = bool(on)


# Variant names (stable table/JSON vocabulary).
BASELINE = "xla_f32"
XLA_INT8 = "xla_int8"
PALLAS_F32 = "pallas_f32"
PALLAS_INT8 = "pallas_int8"
VARIANTS = (XLA_INT8, PALLAS_F32, PALLAS_INT8)

_VARIANT_FLAGS = {
    BASELINE: (False, False),
    XLA_INT8: (True, False),
    PALLAS_F32: (False, True),
    PALLAS_INT8: (True, True),
}


def _device_kind() -> str:
    import jax

    try:
        return jax.devices()[0].device_kind
    except Exception:  # noqa: BLE001 — a label, never a dependency
        return "unknown"


def params_digest(params) -> str:
    """Cheap, deterministic digest of a param tree's WEIGHTS — the
    persisted decision table's staleness guard: a version number alone
    does not identify the weights (bench always serves v1; a checkpoint
    can be retrained in place), and gates measured against different
    weights must never be adopted. Strided sampling keeps it O(leaves),
    not O(bytes): path + shape + dtype + head/tail bytes per leaf."""
    import hashlib

    h = hashlib.blake2b(digest_size=16)

    def walk(node, path):
        if isinstance(node, dict):
            for k in sorted(node):
                walk(node[k], f"{path}/{k}")
        elif isinstance(node, (list, tuple)):
            for i, v in enumerate(node):
                walk(v, f"{path}[{i}]")
        else:
            arr = np.asarray(node)
            h.update(path.encode())
            h.update(str(arr.shape).encode())
            h.update(str(arr.dtype).encode())
            raw = np.ascontiguousarray(arr).view(np.uint8).ravel()
            h.update(raw[:64].tobytes())
            h.update(raw[-64:].tobytes())

    walk(params, "")
    return h.hexdigest()


class KernelManager:
    """The per-bucket variant router + autotune harness the batcher holds
    as `batcher.kernels` (None when the plane is off — one attribute read
    per dispatch, the tracing/cache/overload precedent).

    Fast path: decision(servable, bucket) is a dict probe under no lock
    (the decisions dict is replaced atomically, never mutated in place).
    """

    def __init__(self, config, clock=time.perf_counter):
        self.config = config
        self._clock = clock
        self._lock = threading.Lock()
        # (model_name, version) -> (weakref-to-the-tuned-Servable,
        # {bucket: (quantized, pallas)}). The weakref is the staleness
        # guard: decision() serves an entry only to the EXACT servable
        # object it was tuned for, so a same-version reload (new Servable,
        # possibly new weights) or a recycled object address can never
        # inherit another generation's enablement — while the stable
        # version keeps its measured win across unrelated registry events.
        self._decisions: dict[tuple[str, int], tuple] = {}
        # (model_name, version) -> the full measured table (snapshot/bench).
        self._tables: dict[tuple[str, int], dict] = {}
        self._qparams: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
        # servable -> (params identity, {quantized: apply_fn})
        self._pallas: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
        self.autotunes = 0
        self.table_saves = 0
        self.table_reuses = 0
        self.quantized_batches = 0
        self.pallas_batches = 0
        # The batcher whose entries adopted-enablement warm compiles run
        # through (set by prepare()/autotune(); reuse happens inside them).
        self._warm_batcher = None

    # ------------------------------------------------------------ fast path

    def decision(self, servable, bucket: int) -> tuple[bool, bool] | None:
        """(quantized, pallas) for this (servable, bucket), or None for
        the baseline. The entry answers only for the exact Servable it
        was tuned for (see _decisions) — anything else is baseline.
        Counters ride here (plain int += under the GIL — telemetry, not
        accounting)."""
        entry = self._decisions.get((servable.name, servable.version))
        if entry is None or entry[0]() is not servable:
            return None
        dec = entry[1].get(int(bucket))
        if dec is None:
            return None
        if dec[0]:
            self.quantized_batches += 1
        if dec[1]:
            self.pallas_batches += 1
        return dec

    def params_for(self, servable, quantized: bool):
        """The servable's params in the requested precision; the int8
        tree is minted once per servable (post-training, at first need)
        and cached under a weak key so an unloaded servable frees it."""
        if not quantized:
            return servable.params
        with self._lock:
            entry = self._qparams.get(servable)
            if entry is None or entry[0] is not servable.params:
                from .quantize import quantize_params

                entry = (servable.params, quantize_params(servable.params))
                self._qparams[servable] = entry
        return entry[1]

    def pallas_apply_for(self, servable, quantized: bool):
        """The fused-serving apply callable for this servable (built once
        per (servable, precision); rebuilt when params are swapped).
        Raises for ineligible param trees — eligibility is checked before
        a decision ever routes here (autotune gates on it)."""
        import jax

        from .cross_kernel import build_fused_serve

        # Resolve the (possibly quantized) params BEFORE taking the lock:
        # params_for acquires the same non-reentrant lock, and the build
        # below is idempotent — a racing double-build wastes one trace,
        # a nested acquire would deadlock the dispatch thread forever.
        params = (
            self.params_for(servable, True) if quantized else servable.params
        )
        with self._lock:
            entry = self._pallas.get(servable)
            if entry is None or entry[0] is not servable.params:
                entry = (servable.params, {})
                self._pallas[servable] = entry
            cache = entry[1]
            fn = cache.get(quantized)
            if fn is None:
                fn = cache[quantized] = build_fused_serve(
                    params, servable.model.config,
                    interpret=jax.default_backend() == "cpu",
                )
        return fn

    # ------------------------------------------------------------- autotune

    def _pallas_eligible(self, servable, arrays) -> tuple[bool, str]:
        from .cross_kernel import serve_fits_vmem, serve_params_supported

        model = servable.model
        cfg = model.config
        if model.needs_x64 or not model.folds_ids_on_host:
            return False, "model input contract (x64 / raw ids)"
        if set(arrays) != {"feat_ids", "feat_wts"}:
            return False, "inputs beyond feat_ids/feat_wts"
        if not serve_params_supported(servable.params):
            return False, "param tree is not dcn_v2-shaped"
        mlp_dims = tuple(
            p.get("qw", p.get("w")).shape[1] for p in servable.params["mlp"]
        )
        if not serve_fits_vmem(
            cfg.num_fields * cfg.embed_dim, len(servable.params["cross"]),
            mlp_dims, cfg.cdtype,
        ):
            return False, "over VMEM budget"
        return True, ""

    @staticmethod
    def _tune_arrays(batcher, servable, bucket: int, seed: int = 7) -> dict:
        """Representative random batch: warmup_arrays' geometry with live
        value distributions (random gather addresses defeat the content
        cache's trivial all-zero hit and exercise real HBM reads)."""
        rng = np.random.RandomState(seed + bucket)
        arrays = {}
        for k, v in batcher.warmup_arrays(servable, bucket).items():
            if np.issubdtype(v.dtype, np.integer):
                arrays[k] = rng.randint(0, 1 << 40, size=v.shape).astype(v.dtype)
            else:
                arrays[k] = rng.rand(*v.shape).astype(v.dtype)
        return arrays

    def _scores_of(self, batcher, servable, arrays, override) -> np.ndarray:
        from .transfer import restore_outputs_host

        score_key = servable.model.score_output
        out = batcher._execute(
            servable, dict(arrays), out_keys=(score_key,),
            _kernel_override=override,
        )
        host = restore_outputs_host({k: np.asarray(v) for k, v in out.items()})
        return np.asarray(host[score_key], np.float32)

    def _time_variant(self, batcher, servable, arrays, override,
                      iters: int) -> float:
        import jax

        score_key = servable.model.score_output
        run = lambda: batcher._execute(  # noqa: E731
            servable, dict(arrays), out_keys=(score_key,),
            _kernel_override=override,
        )
        jax.block_until_ready(run())  # compile + warm
        best = float("inf")
        for _ in range(max(iters, 1)):
            t0 = self._clock()
            jax.block_until_ready(run())
            best = min(best, self._clock() - t0)
        return best

    def _auc_of(self, batcher, servable, eval_data, override):
        """Windowed-eval AUC of one variant over the supplied labeled
        arrays (padded into the nearest bucket; scores sliced back)."""
        from ..serving.batcher import bucket_for
        from ..train.data import auc as exact_auc

        arrays, labels = eval_data
        n = int(next(iter(arrays.values())).shape[0])
        top = int(batcher.buckets[-1])
        if n > top:
            # Clamp to the ladder: ranking quality over the first
            # bucket's worth of held-out rows is the same statistic.
            arrays = {k: v[:top] for k, v in arrays.items()}
            labels = np.asarray(labels)[:top]
            n = top
        bucket = bucket_for(n, batcher.buckets)
        padded = {}
        for k, v in arrays.items():
            buf = np.zeros((bucket,) + v.shape[1:], v.dtype)
            buf[:n] = v
            padded[k] = buf
        scores = self._scores_of(batcher, servable, padded, override)[:n]
        return float(exact_auc(np.asarray(labels, np.float64), scores))

    def prepare(self, batcher, servable, buckets=None, eval_data=None) -> None:
        """Load-time entry: adopt a persisted decision table when one
        matches exactly, else run the measurement harness (config
        permitting — autotune=false serves the baseline rather than
        measuring at every restart)."""
        buckets = tuple(
            int(b) for b in (buckets or self.config.autotune_buckets or batcher.buckets)
        )
        self._warm_batcher = batcher  # for adopted-enablement warm compiles
        if self._try_reuse(servable, buckets) is not None:
            return
        if self.config.autotune:
            self.autotune(batcher, servable, buckets, eval_data=eval_data)

    def autotune(self, batcher, servable, buckets=None, eval_data=None,
                 force: bool = False) -> dict:
        """Measure every candidate variant per bucket, gate, decide,
        persist. Returns this servable's table block (also served via
        snapshot()/ /monitoring / bench). `eval_data` = (arrays, labels)
        arms the AUC gate; without it the gate records "skipped" and the
        decision rests on speedup + max|Δscore| alone. `force` skips the
        persisted-table adoption and ALWAYS measures — the bench A/B's
        contract is fresh numbers per round, not round 1's replayed."""
        import jax

        cfg = self.config
        self.autotunes += 1
        key = (servable.name, servable.version)
        buckets = tuple(
            int(b) for b in (buckets or cfg.autotune_buckets or batcher.buckets)
        )
        self._warm_batcher = batcher
        if not force:
            reused = self._try_reuse(servable, buckets)
            if reused is not None:
                return reused
        on_cpu = jax.default_backend() == "cpu"
        force_pallas = os.environ.get("DTS_KERNELS_FORCE_PALLAS") == "1"
        iters = int(cfg.measure_iters) or (4 if on_cpu else 30)
        sample = self._tune_arrays(batcher, servable, buckets[0])
        pallas_ok, pallas_why = self._pallas_eligible(servable, sample)
        if pallas_ok and on_cpu and not force_pallas:
            pallas_ok, pallas_why = False, (
                "cpu backend runs the kernel in interpret mode — timing it "
                "would be meaningless (and slow); gates run on real devices"
            )
        candidates = []
        if cfg.quantize:
            candidates.append(XLA_INT8)
        if cfg.pallas and pallas_ok:
            candidates.extend([PALLAS_F32] + ([PALLAS_INT8] if cfg.quantize else []))

        # AUC gate: one evaluation per variant KIND (rank quality is
        # bucket-independent), against the f32 baseline's AUC.
        aucs: dict[str, float | None] = {BASELINE: None}
        auc_errors: dict[str, str] = {}
        if eval_data is not None:
            try:
                aucs[BASELINE] = self._auc_of(
                    batcher, servable, eval_data, _VARIANT_FLAGS[BASELINE]
                )
            except Exception as exc:  # noqa: BLE001 — record, keep tuning
                auc_errors[BASELINE] = f"{type(exc).__name__}: {exc}"[:200]
            for name in candidates:
                try:
                    aucs[name] = self._auc_of(
                        batcher, servable, eval_data, _VARIANT_FLAGS[name]
                    )
                except Exception as exc:  # noqa: BLE001
                    auc_errors[name] = f"{type(exc).__name__}: {exc}"[:200]

        table: dict = {
            "model": servable.name,
            "version": servable.version,
            "params_digest": params_digest(servable.params),
            "device": _device_kind(),
            "measure_iters": iters,
            "measure_only": bool(cfg.measure_only),
            "gates": {
                "min_speedup": cfg.min_speedup,
                "max_abs_delta": cfg.max_abs_delta,
                "auc_margin": cfg.auc_margin,
                "auc_evaluated": eval_data is not None,
            },
            "pallas_eligible": pallas_ok,
            **({"pallas_ineligible_reason": pallas_why} if not pallas_ok else {}),
            "auc": {
                k: (round(v, 4) if v is not None else None)
                for k, v in aucs.items()
            },
            **({"auc_errors": auc_errors} if auc_errors else {}),
            "buckets": {},
        }
        decisions: dict[int, tuple[bool, bool]] = {}
        for bucket in buckets:
            arrays = self._tune_arrays(batcher, servable, bucket)
            row: dict = {}
            try:
                base_scores = self._scores_of(
                    batcher, servable, arrays, _VARIANT_FLAGS[BASELINE]
                )
                base_t = self._time_variant(
                    batcher, servable, arrays, _VARIANT_FLAGS[BASELINE], iters
                )
            except Exception as exc:  # noqa: BLE001 — baseline broken: skip bucket
                table["buckets"][str(bucket)] = {
                    "error": f"{type(exc).__name__}: {exc}"[:300]
                }
                continue
            row[BASELINE] = {"step_us": round(base_t * 1e6, 1)}
            best: tuple[float, str] | None = None
            for name in candidates:
                flags = _VARIANT_FLAGS[name]
                entry: dict = {}
                try:
                    scores = self._scores_of(batcher, servable, arrays, flags)
                    t = self._time_variant(batcher, servable, arrays, flags, iters)
                    entry["step_us"] = round(t * 1e6, 1)
                    entry["speedup"] = round(base_t / t, 3) if t > 0 else None
                    entry["max_abs_delta"] = round(
                        float(np.max(np.abs(scores - base_scores))), 6
                    )
                    auc_v, auc_b = aucs.get(name), aucs.get(BASELINE)
                    if auc_v is not None and auc_b is not None:
                        entry["auc_delta"] = round(abs(auc_b - auc_v), 5)
                        entry["auc_gate"] = (
                            "pass" if entry["auc_delta"] <= cfg.auc_margin
                            else "fail"
                        )
                    elif eval_data is not None:
                        # Eval data was SUPPLIED but this variant's (or
                        # the baseline's) AUC evaluation errored: the
                        # gate fails CLOSED — an un-evaluated ranking-
                        # quality gate must never read as passed.
                        entry["auc_gate"] = "error"
                    else:
                        entry["auc_gate"] = "skipped"
                    enabled = (
                        entry["speedup"] is not None
                        and entry["speedup"] >= cfg.min_speedup
                        and entry["max_abs_delta"] <= cfg.max_abs_delta
                        and entry["auc_gate"] in ("pass", "skipped")
                        and not cfg.measure_only
                    )
                    entry["enabled"] = enabled
                    if enabled and (best is None or entry["speedup"] > best[0]):
                        best = (entry["speedup"], name)
                except Exception as exc:  # noqa: BLE001 — a variant that
                    # fails to compile/lower is a disabled variant, never
                    # a serving error.
                    entry["error"] = f"{type(exc).__name__}: {exc}"[:300]
                    entry["enabled"] = False
                row[name] = entry
            if best is not None:
                decisions[bucket] = _VARIANT_FLAGS[best[1]]
                row["decision"] = best[1]
            else:
                row["decision"] = BASELINE
            table["buckets"][str(bucket)] = row
        if decisions:
            self._warm_enabled(batcher, servable, decisions)
        with self._lock:
            new = dict(self._decisions)
            new[key] = (weakref.ref(servable), decisions)
            self._decisions = new  # atomic swap: decision() reads lock-free
            self._tables[key] = table
        if decisions:
            log.info(
                "kernel autotune %s v%d: %s", servable.name, servable.version,
                {b: table["buckets"][str(b)]["decision"] for b in decisions},
            )
        self._save_table()
        return table

    def _warm_enabled(self, batcher, servable, decisions: dict) -> None:
        """Compile the entry variants LIVE traffic hits for every enabled
        (bucket, decision): the harness only measured the score-only
        non-donating entry, but live buckets serve the all-outputs entry
        (unfiltered requests) and the donating combined variant — left
        cold, the first live batch after enablement would pay a fresh
        XLA/Pallas compile on the dispatch path under the wedge clock
        (with [recovery] armed, a >15s compile trips a spurious
        quarantine). The warmup contract applies to variants too."""
        import jax

        b = batcher if batcher is not None else self._warm_batcher
        if b is None:
            return
        score_only = (servable.model.score_output,)
        for bucket, flags in sorted(decisions.items()):
            try:
                arrays = self._tune_arrays(b, servable, bucket)
                for out_keys in (None, score_only):
                    jax.block_until_ready(b._execute(
                        servable, dict(arrays), out_keys=out_keys,
                        _kernel_override=flags,
                    ))
                _, _, combined = b.jit_entry(servable)
                if combined and b._donation_ok():
                    for out_keys in (None, score_only):
                        jax.block_until_ready(b._execute(
                            servable, dict(arrays), out_keys=out_keys,
                            _force_donate=True, _kernel_override=flags,
                        ))
            except Exception:  # noqa: BLE001 — a failed warm compiles at
                # first use instead; never blocks enablement itself.
                log.exception(
                    "kernel variant warm failed (%s:%s bucket %s)",
                    servable.name, servable.version, bucket,
                )

    # --------------------------------------------------------- persistence

    def _fingerprint(self) -> dict:
        cfg = self.config
        return {
            "min_speedup": cfg.min_speedup,
            "max_abs_delta": cfg.max_abs_delta,
            "auc_margin": cfg.auc_margin,
            "quantize": cfg.quantize,
            "pallas": cfg.pallas,
        }

    def _try_reuse(self, servable, buckets: tuple[int, ...]):
        """Adopt a persisted decision table for this exact (model,
        version, PARAMS DIGEST, device, gate fingerprint, bucket set) —
        restarts skip re-tuning; anything else (a version swap, a
        same-version retrain, changed gates) re-measures. The params
        digest is the load-bearing part: a version number alone does not
        identify the weights the gates were measured against."""
        key = (servable.name, servable.version)
        path = self.config.table_file
        if not path or not os.path.exists(path) or self.config.measure_only:
            return None
        try:
            with open(path) as f:
                data = json.load(f)
        except Exception:  # noqa: BLE001 — a corrupt table is re-tuned
            return None
        if data.get("device") != _device_kind() or \
                data.get("fingerprint") != self._fingerprint():
            return None
        entry = (data.get("entries") or {}).get(f"{key[0]}:{key[1]}")
        if entry is None:
            return None
        if entry.get("measure_only"):
            # A measure-only run's table records decisions that were
            # never allowed to enable anything; adopting it would make a
            # real serving process skip the harness and serve the
            # baseline forever. Re-measure instead.
            return None
        if entry.get("params_digest") != params_digest(servable.params):
            return None
        if sorted(entry.get("buckets") or {}) != sorted(str(b) for b in buckets):
            return None
        decisions = {
            int(b): tuple(_VARIANT_FLAGS[row.get("decision", BASELINE)])
            for b, row in entry["buckets"].items()
            if "error" not in row
        }
        decisions = {b: d for b, d in decisions.items() if d != (False, False)}
        if decisions:
            # Adopted enablement compiles here, at load — the first live
            # batch of an enabled bucket must not pay the variant compile
            # under the wedge clock (the warmup contract).
            self._warm_enabled(batcher=None, servable=servable,
                               decisions=decisions)
        entry = dict(entry)
        entry["reused_from"] = path
        with self._lock:
            new = dict(self._decisions)
            new[key] = (weakref.ref(servable), decisions)
            self._decisions = new
            self._tables[key] = entry
        self.table_reuses += 1
        log.info("kernel autotune: reused persisted table for %s:%s", *key)
        return entry

    def _save_table(self) -> None:
        path = self.config.table_file
        if not path:
            return
        with self._lock:
            entries = {
                f"{name}:{ver}": table
                for (name, ver), table in self._tables.items()
            }
        # MERGE with what is already on disk (same device + gates only —
        # a fingerprint change invalidates the whole file): a process
        # serving v2 must not erase v1's measured entry, or a rollback
        # (and every other model/process sharing the file) re-pays the
        # measurement the persistence layer exists to skip.
        try:
            with open(path) as f:
                prior = json.load(f)
            if prior.get("device") == _device_kind() and \
                    prior.get("fingerprint") == self._fingerprint():
                entries = {**(prior.get("entries") or {}), **entries}
        except Exception:  # noqa: BLE001 — absent/corrupt prior: fresh file
            pass
        data = {
            "version": 1,
            "device": _device_kind(),
            "fingerprint": self._fingerprint(),
            "entries": entries,
        }
        try:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(data, f, indent=1, sort_keys=True)
            os.replace(tmp, path)  # atomic: readers never see half a table
            self.table_saves += 1
        except Exception:  # noqa: BLE001 — persistence is best-effort
            log.exception("kernel autotune: table save failed (%s)", path)

    # ------------------------------------------------------------ lifecycle

    def invalidate_model(self, name: str) -> None:
        """Drop a model's live decisions and tables (operator/test
        surface). NOT wired as the version-watcher hook: decision() is
        identity-guarded per tuned Servable, so a hot-loaded or reloaded
        version can never inherit another generation's enablement anyway
        — and blunt invalidation on every registry event would strip the
        STABLE version's measured win for the rest of the process (a
        silent loss /monitoring would still show as an armed plane)."""
        with self._lock:
            self._decisions = {
                k: v for k, v in self._decisions.items() if k[0] != name
            }
            for k in [k for k in self._tables if k[0] == name]:
                self._tables.pop(k, None)

    # -------------------------------------------------------------- surface

    def snapshot(self) -> dict:
        """The /monitoring `kernels` block + dts_tpu_kernel_* source."""
        cfg = self.config
        with self._lock:
            decisions = {
                f"{name}:{ver}": {
                    str(b): {"quantized": q, "pallas": p}
                    for b, (q, p) in sorted(entry[1].items())
                }
                for (name, ver), entry in self._decisions.items()
                if entry[0]() is not None  # tuned servable still alive
            }
            tables = {
                f"{name}:{ver}": table
                for (name, ver), table in self._tables.items()
            }
        return {
            "enabled": True,
            "measure_only": bool(cfg.measure_only),
            "int8_score_wire": bool(cfg.int8_score_wire),
            "counters": {
                "autotunes": self.autotunes,
                "table_saves": self.table_saves,
                "table_reuses": self.table_reuses,
                "quantized_batches": self.quantized_batches,
                "pallas_batches": self.pallas_batches,
            },
            "decisions": decisions,
            "tables": tables,
            "gates": {
                "min_speedup": cfg.min_speedup,
                "max_abs_delta": cfg.max_abs_delta,
                "auc_margin": cfg.auc_margin,
            },
        }
