"""ctypes bindings for the native host-ops library, with lazy build.

The shared library builds from hostops.cc on first use (g++ -O3, cached in
native/build/). Absence of a compiler or DTS_TPU_NO_NATIVE=1 degrades
gracefully to the numpy implementations in ops/transfer.py — callers probe
`available()` and fall back. Bindings use ctypes because pybind11 is not in
this image; the C ABI keeps them trivial.
"""

from __future__ import annotations

import ctypes
import logging
import os
import pathlib
import subprocess
import threading

import numpy as np

log = logging.getLogger("dts_tpu.native")

_DIR = pathlib.Path(__file__).resolve().parent
_SRC = _DIR / "hostops.cc"
_SO = _DIR / "build" / "libhostops.so"

_lib: ctypes.CDLL | None = None
_tried = False
_lock = threading.Lock()


def _build() -> bool:
    _SO.parent.mkdir(exist_ok=True)
    # Build to a temp path + atomic rename: a killed/failed compile must
    # never leave a partial .so that later passes the staleness check.
    tmp = _SO.with_suffix(f".tmp{os.getpid()}.so")
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-o", str(tmp), str(_SRC)]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, _SO)
        return True
    except (OSError, subprocess.SubprocessError) as e:
        log.warning("native hostops build failed (%s); using numpy fallback", e)
        tmp.unlink(missing_ok=True)
        return False


def _load() -> ctypes.CDLL | None:
    global _lib, _tried
    if _tried:
        return _lib
    with _lock:
        if _tried:
            return _lib
        lib = _load_locked()
        # _tried flips only after the outcome is final, under the lock, so
        # concurrent first callers cannot race the compile or CDLL a
        # half-written file.
        _lib = lib
        _tried = True
        return _lib


def _probe() -> ctypes.CDLL | None:
    """Non-blocking, non-building _load: never compiles (that is exclusively
    warm_async/_load territory — a g++ run on the dispatch thread would stall
    every in-flight request) and never waits on the build lock. Until a
    fresh .so exists, hot-path callers fall back to numpy; _tried stays
    unset so they pick the library up once the build lands."""
    global _lib, _tried
    if _tried:
        return _lib
    if not _lock.acquire(blocking=False):
        return None
    try:
        if _tried:
            return _lib
        lib = _load_locked(build=False)
        if lib is not None:
            # Only a successful load is final here; a missing .so may still
            # be produced by an in-flight/future warm_async build.
            _lib = lib
            _tried = True
        return lib
    finally:
        _lock.release()


def _load_locked(build: bool = True) -> ctypes.CDLL | None:
    if os.environ.get("DTS_TPU_NO_NATIVE") == "1":
        return None
    if not _SO.exists() or _SO.stat().st_mtime < _SRC.stat().st_mtime:
        if not build or not _build():
            return None
    try:
        lib = ctypes.CDLL(str(_SO))
    except OSError as e:
        log.warning("native hostops load failed (%s); using numpy fallback", e)
        # A cached .so that will not load is useless; drop it so the next
        # process attempts a fresh build instead of failing forever.
        _SO.unlink(missing_ok=True)
        return None
    lib.fold_i32.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64, ctypes.c_void_p,
    ]
    lib.pack_u24_i32.argtypes = [ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p]
    lib.f32_to_bf16.argtypes = [ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p]
    lib.hash128.argtypes = [ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p]
    lib.hash128_rows.argtypes = [
        ctypes.c_char_p, ctypes.c_int64,
        ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64, ctypes.c_void_p,
    ]
    lib.pack_batch_u24_bf16.argtypes = [
        ctypes.POINTER(ctypes.c_void_p), ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_void_p), ctypes.c_void_p,
        ctypes.c_void_p, ctypes.c_int64,
        ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, ctypes.c_void_p,
    ]
    return lib


_warm_kicked = False


def available() -> bool:
    """True once the native library is loaded. Never blocks: while the
    library isn't ready it kicks the build off-thread (once) and returns
    False, so callers use their numpy fallbacks and transparently upgrade
    to the native path when the build lands."""
    global _warm_kicked
    lib = _probe()
    if lib is None and not _tried and not _warm_kicked:
        _warm_kicked = True
        warm_async()
    return lib is not None


def ensure() -> bool:
    """Blocking availability: builds the library if needed (seconds of g++).
    For tests and setup paths that need a definite answer, never for the
    serving hot path."""
    return _load() is not None


def warm_async() -> None:
    """Kick the (possibly compiling) load off-thread so no request pays the
    first-use g++ latency; callers keep using the numpy fallback until the
    native path is ready."""
    threading.Thread(target=_load, name="native-build", daemon=True).start()


def _ptr(arr: np.ndarray) -> ctypes.c_void_p:
    return ctypes.c_void_p(arr.ctypes.data)


def fold_i32(ids: np.ndarray, vocab: int) -> np.ndarray:
    """int64 ids -> int32 ids mod vocab (one pass)."""
    lib = _load()
    assert lib is not None
    ids = np.ascontiguousarray(ids, dtype=np.int64)
    out = np.empty(ids.shape, np.int32)
    lib.fold_i32(_ptr(ids), ids.size, vocab, _ptr(out))
    return out


def fold_ids(ids: np.ndarray, vocab: int) -> np.ndarray:
    """THE canonical exact host fold (int64 -> int32 mod vocab): native
    one-pass kernel when built, numpy remainder+astype otherwise —
    bit-identical either way. Lives here (jax-free, importable by the
    client) so the server's batcher and the client's compact_payload cannot
    drift on the fold contract."""
    if ids.dtype == np.int64 and available():
        return fold_i32(ids, vocab)
    return np.remainder(ids, np.int64(vocab)).astype(np.int32)


def pack_u24_i32(ids: np.ndarray) -> np.ndarray:
    """Folded int32 ids [..] -> u24 bytes [.., 3] (one pass)."""
    lib = _load()
    assert lib is not None
    ids = np.ascontiguousarray(ids, dtype=np.int32)
    out = np.empty(ids.shape + (3,), np.uint8)
    lib.pack_u24_i32(_ptr(ids), ids.size, _ptr(out))
    return out


def hash128(arr: np.ndarray) -> bytes:
    """16-byte content digest of a contiguous array's bytes (one pass)."""
    lib = _load()
    assert lib is not None
    arr = np.ascontiguousarray(arr)
    out = np.empty(2, np.uint64)
    lib.hash128(_ptr(arr), arr.nbytes, _ptr(out))
    return out.tobytes()


def hash128_rows(blob: np.ndarray, header: bytes = b"") -> np.ndarray:
    """Batched per-row blake2b-128 (ISSUE 15 satellite): a [n, B] uint8
    row matrix -> [n, 16] uint8 digests, row i = blake2b(header +
    blob[i].tobytes(), digest_size=16) — BYTE-IDENTICAL to hashlib's
    blake2b (RFC 7693 in hostops.cc), because these digests are wire
    contracts (row-cache keys, dedup identity, client label-join keys)
    that must not depend on whether the host ops are built. One
    GIL-released call hashes the whole batch."""
    lib = _load()
    assert lib is not None
    blob = np.ascontiguousarray(blob, dtype=np.uint8)
    if blob.ndim != 2:
        raise ValueError(f"hash128_rows wants [n, B] uint8, got {blob.shape}")
    header = bytes(header)
    out = np.empty((blob.shape[0], 16), np.uint8)
    lib.hash128_rows(
        header, len(header), _ptr(blob), blob.shape[0], blob.shape[1],
        _ptr(out),
    )
    return out


def f32_to_bf16(wts: np.ndarray) -> np.ndarray:
    """f32 -> bf16 with round-to-nearest-even (one pass)."""
    import ml_dtypes

    lib = _load()
    assert lib is not None
    wts = np.ascontiguousarray(wts, dtype=np.float32)
    out = np.empty(wts.shape, ml_dtypes.bfloat16)
    lib.f32_to_bf16(_ptr(wts), wts.size, _ptr(out))
    return out


def pack_batch_u24_bf16(
    ids_parts: list[np.ndarray],
    wts_parts: list[np.ndarray],
    fields: int,
    bucket: int,
    vocab: int,
) -> np.ndarray:
    """Fused batch assembly (see hostops.cc): per-request [n_p, F] id/weight
    arrays -> the final padded combined uint8 buffer
    [bucket*F*3 u24 | bucket*F*2 bf16] in one pass per input, zero padding
    included. ids int64 are folded mod vocab; int32 (compact wire) pass
    through; wts f32 are RNE-cast; bf16 copied. The per-part arrays must be
    C-contiguous [n, fields] (the batcher's prepare_inputs guarantees it
    for wire-decoded arrays; anything else is made contiguous here)."""
    import ml_dtypes

    lib = _load()
    if lib is None:
        raise RuntimeError("native hostops library unavailable")
    nparts = len(ids_parts)
    if nparts == 0 or nparts != len(wts_parts):
        raise ValueError(f"part-count mismatch: {nparts} ids vs {len(wts_parts)} wts")
    ids_c = [np.ascontiguousarray(a) for a in ids_parts]
    wts_c = [np.ascontiguousarray(a) for a in wts_parts]
    # Real raises, not asserts: these are the ONLY guards between caller
    # mistakes and an out-of-bounds write in C (review finding — asserts
    # vanish under python -O, turning a shape bug into heap corruption).
    for i, (a, w) in enumerate(zip(ids_c, wts_c)):
        if a.dtype not in (np.int64, np.int32):
            raise ValueError(f"ids part {i}: dtype {a.dtype} not int64/int32")
        if w.dtype not in (np.float32, ml_dtypes.bfloat16):
            raise ValueError(f"wts part {i}: dtype {w.dtype} not f32/bf16")
        if a.ndim != 2 or a.shape[1] != fields or w.shape != a.shape:
            raise ValueError(
                f"part {i}: shapes ids {a.shape} / wts {w.shape} do not "
                f"match [n, {fields}]"
            )
    ids_ptrs = (ctypes.c_void_p * nparts)(*(a.ctypes.data for a in ids_c))
    wts_ptrs = (ctypes.c_void_p * nparts)(*(a.ctypes.data for a in wts_c))
    ids_is64 = np.fromiter(
        (a.dtype == np.int64 for a in ids_c), np.uint8, nparts
    )
    wts_isf32 = np.fromiter(
        (a.dtype == np.float32 for a in wts_c), np.uint8, nparts
    )
    ns = np.fromiter((a.shape[0] for a in ids_c), np.int64, nparts)
    if int(ns.sum()) > bucket:
        raise ValueError(f"{int(ns.sum())} rows exceed bucket {bucket}")
    out = np.empty(bucket * fields * 5, np.uint8)  # 3 (u24) + 2 (bf16)
    lib.pack_batch_u24_bf16(
        ids_ptrs, _ptr(ids_is64), wts_ptrs, _ptr(wts_isf32),
        _ptr(ns), nparts, fields, bucket, vocab, _ptr(out),
    )
    return out
