// Native host-side hot path for the serving batcher.
//
// The reference keeps its entire runtime on the JVM and delegates native
// execution to external binaries (SURVEY.md §2.3); here the TPU compute path
// is XLA/Pallas and THIS file is the native runtime for the host side of the
// request path: the fold/pack/pad batch assembly that sits between protobuf
// decode and device transfer. The numpy implementation of the same steps
// (ops/transfer.py + batcher padding) makes several full passes and
// temporaries per batch; these kernels do each transform in one pass.
//
// Exposed via a C ABI for ctypes (pybind11 is not in this image). All
// functions are thread-safe (pure element-wise transforms on caller-owned
// buffers).

#include <cstdint>
#include <cstring>

namespace {

// One multiply-xor round: full 128-bit product folded to 64 bits. The
// multiply diffuses every input bit across the word; the xor of hi/lo keeps
// both halves.
inline uint64_t mix64(uint64_t a, uint64_t b) {
  __uint128_t m = static_cast<__uint128_t>(a) * b;
  return static_cast<uint64_t>(m) ^ static_cast<uint64_t>(m >> 64);
}

// THE fold: mathematical mod (result in [0, vocab)), pow2 fast path.
// Shared by fold_i32 and the fused batch pack so the semantics cannot
// drift between them.
inline int64_t fold1(int64_t v, int64_t vocab, bool pow2, int64_t mask) {
  if (pow2) return v & mask;
  int64_t r = v % vocab;
  return r < 0 ? r + vocab : r;
}

inline void write_u24(uint8_t* dst, uint32_t v) {
  dst[0] = static_cast<uint8_t>(v);
  dst[1] = static_cast<uint8_t>(v >> 8);
  dst[2] = static_cast<uint8_t>(v >> 16);
}

// f32 bits -> bf16 bits, round-to-nearest-even with NaN quieting (the one
// rounding rule, shared by the exported f32_to_bf16 and the fused pack).
inline uint16_t bf16_bits(uint32_t u) {
  if ((u & 0x7fffffffu) > 0x7f800000u) {   // NaN: keep quiet, drop payload
    return static_cast<uint16_t>((u >> 16) | 0x0040u);
  }
  uint32_t rounding = 0x7fffu + ((u >> 16) & 1u);
  return static_cast<uint16_t>((u + rounding) >> 16);
}

}  // namespace

extern "C" {

// 128-bit content digest (two independently-keyed 64-bit lanes, 32 bytes
// per iteration) for the batcher's device-input cache. Non-cryptographic
// but well-mixed: at the cache's scale (<=1e6 distinct batches) the
// 128-bit collision probability is ~1e-27. ~5x faster than blake2b, and
// ctypes releases the GIL for the call, so hashing a ~2 MB batch never
// stalls the request handlers.
void hash128(const uint8_t* p, int64_t n, uint64_t* out) {
  const uint64_t K0 = 0x9E3779B185EBCA87ull, K1 = 0xC2B2AE3D27D4EB4Full,
                 K2 = 0x165667B19E3779F9ull, K3 = 0x27D4EB2F165667C5ull;
  uint64_t h0 = K0 ^ static_cast<uint64_t>(n);
  uint64_t h1 = K1 + static_cast<uint64_t>(n);
  int64_t i = 0;
  for (; i + 32 <= n; i += 32) {
    uint64_t a, b, c, d;
    std::memcpy(&a, p + i, 8);
    std::memcpy(&b, p + i + 8, 8);
    std::memcpy(&c, p + i + 16, 8);
    std::memcpy(&d, p + i + 24, 8);
    h0 = mix64(a ^ h0, K2 ^ b);
    h1 = mix64(c ^ h1, K3 ^ d);
  }
  if (i < n) {
    uint8_t tail[32] = {0};
    std::memcpy(tail, p + i, static_cast<size_t>(n - i));
    uint64_t a, b, c, d;
    std::memcpy(&a, tail, 8);
    std::memcpy(&b, tail + 8, 8);
    std::memcpy(&c, tail + 16, 8);
    std::memcpy(&d, tail + 24, 8);
    h0 = mix64(a ^ h0, K2 ^ b);
    h1 = mix64(c ^ h1, K3 ^ d);
  }
  out[0] = mix64(h0 ^ K1, h1 ^ K0);  // cross-mix: each output depends on
  out[1] = mix64(h1 ^ K3, h0 ^ K2);  // both lanes
}

// ids[i] -> int32(ids[i] mod vocab) — the uncompressed fold. Power-of-two
// vocabs (the common config) take the mask path: two's-complement AND equals
// the mathematical mod, and skips the 64-bit division.
void fold_i32(const int64_t* ids, int64_t n, int64_t vocab, int32_t* out) {
  const bool pow2 = (vocab & (vocab - 1)) == 0;
  const int64_t mask = vocab - 1;
  for (int64_t i = 0; i < n; ++i) {
    out[i] = static_cast<int32_t>(fold1(ids[i], vocab, pow2, mask));
  }
}

// Already-folded int32 ids -> 3 little-endian bytes each (the u24 transfer
// packing of ops/transfer.py, one pass, no intermediate view/copy).
// Requires 0 <= ids[i] < 2^24.
void pack_u24_i32(const int32_t* ids, int64_t n, uint8_t* out) {
  for (int64_t i = 0; i < n; ++i) {
    uint32_t v = static_cast<uint32_t>(ids[i]);
    out[3 * i + 0] = static_cast<uint8_t>(v);
    out[3 * i + 1] = static_cast<uint8_t>(v >> 8);
    out[3 * i + 2] = static_cast<uint8_t>(v >> 16);
  }
}

// f32 -> bf16 with round-to-nearest-even (numpy/ml_dtypes-compatible,
// including NaN quieting).
void f32_to_bf16(const float* in, int64_t n, uint16_t* out) {
  const uint32_t* bits = reinterpret_cast<const uint32_t*>(in);
  for (int64_t i = 0; i < n; ++i) {
    out[i] = bf16_bits(bits[i]);
  }
}

// Fused batch assembly for the flagship combined layout
// ({feat_ids: u24, feat_wts: bf16}, key-sorted so the ids segment precedes
// the weights segment): reads each request's arrays ONCE and writes the
// final padded device buffer directly —
//   out = [bucket*F*3 bytes u24(fold(ids))][bucket*F*2 bytes bf16(wts)]
// replacing the python path's pad copy + fold pass + pack pass + concat
// (4 full passes and 3 temporaries per batch, serving/batcher.py _dispatch
// + ops/transfer.py). Per part p: ids_ptrs[p] is int64 (wide wire; folded
// here) or int32 when ids_is64[p]==0 (compact wire, pre-folded by the
// client and range-checked by the service; low 3 bytes taken either way,
// matching the numpy path's truncation semantics). wts_ptrs[p] is f32
// (cast here, RNE) or bf16 bits when wts_isf32[p]==0 (compact; copied).
// Rows [total..bucket) are zero in both segments. Thread-safe; ctypes
// releases the GIL for the whole call.
void pack_batch_u24_bf16(const void** ids_ptrs, const uint8_t* ids_is64,
                         const void** wts_ptrs, const uint8_t* wts_isf32,
                         const int64_t* ns, int64_t num_parts,
                         int64_t fields, int64_t bucket, int64_t vocab,
                         uint8_t* out) {
  uint8_t* ids_base = out;
  uint8_t* wts_base = out + bucket * fields * 3;
  const bool pow2 = (vocab & (vocab - 1)) == 0;
  const int64_t mask = vocab - 1;
  int64_t row = 0;
  for (int64_t p = 0; p < num_parts; ++p) {
    const int64_t n = ns[p] * fields;
    uint8_t* idst = ids_base + row * fields * 3;
    if (ids_is64[p]) {
      const int64_t* ids = static_cast<const int64_t*>(ids_ptrs[p]);
      for (int64_t i = 0; i < n; ++i) {
        write_u24(idst + 3 * i,
                  static_cast<uint32_t>(fold1(ids[i], vocab, pow2, mask)));
      }
    } else {
      // int32 (compact wire): pre-folded by contract (service-validated
      // range [0, vocab)), so the low 3 bytes ARE the value — plain
      // truncation, exactly what the python generic path does for an
      // all-int32 group. (For OUT-of-contract ids in a MIXED group the
      // python path widens to int64 and folds while this path truncates —
      // an intentional, documented divergence reachable only by direct
      // submit() callers violating the compact contract.)
      const int32_t* ids = static_cast<const int32_t*>(ids_ptrs[p]);
      for (int64_t i = 0; i < n; ++i) {
        write_u24(idst + 3 * i, static_cast<uint32_t>(ids[i]));
      }
    }
    // Byte-granular stores: the weights segment starts at bucket*fields*3,
    // which is ODD for odd bucket*fields — a uint16_t* store there would be
    // misaligned UB (unreachable with the shipped pow2 buckets, but the
    // layout must be correct for arbitrary configs). memcpy of 2 bytes
    // compiles to a single unaligned store on x86/arm.
    uint8_t* wdst = wts_base + row * fields * 2;
    if (wts_isf32[p]) {
      const uint32_t* bits =
          static_cast<const uint32_t*>(wts_ptrs[p]);
      for (int64_t i = 0; i < n; ++i) {
        uint16_t v = bf16_bits(bits[i]);
        std::memcpy(wdst + 2 * i, &v, 2);
      }
    } else {
      std::memcpy(wdst, wts_ptrs[p], static_cast<size_t>(n) * 2);
    }
    row += ns[p];
  }
  if (row < bucket) {
    std::memset(ids_base + row * fields * 3, 0,
                static_cast<size_t>(bucket - row) * fields * 3);
    std::memset(wts_base + row * fields * 2, 0,
                static_cast<size_t>(bucket - row) * fields * 2);
  }
}

}  // extern "C"
