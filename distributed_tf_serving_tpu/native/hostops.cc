// Native host-side hot path for the serving batcher.
//
// The reference keeps its entire runtime on the JVM and delegates native
// execution to external binaries (SURVEY.md §2.3); here the TPU compute path
// is XLA/Pallas and THIS file is the native runtime for the host side of the
// request path: the fold/pack/pad batch assembly that sits between protobuf
// decode and device transfer. The numpy implementation of the same steps
// (ops/transfer.py + batcher padding) makes several full passes and
// temporaries per batch; these kernels do each transform in one pass.
//
// Exposed via a C ABI for ctypes (pybind11 is not in this image). All
// functions are thread-safe (pure element-wise transforms on caller-owned
// buffers).

#include <cstdint>

extern "C" {

// ids[i] -> int32(ids[i] mod vocab) — the uncompressed fold. Power-of-two
// vocabs (the common config) take the mask path: two's-complement AND equals
// the mathematical mod, and skips the 64-bit division.
void fold_i32(const int64_t* ids, int64_t n, int64_t vocab, int32_t* out) {
  if ((vocab & (vocab - 1)) == 0) {
    const int64_t mask = vocab - 1;
    for (int64_t i = 0; i < n; ++i) {
      out[i] = static_cast<int32_t>(ids[i] & mask);
    }
    return;
  }
  for (int64_t i = 0; i < n; ++i) {
    int64_t r = ids[i] % vocab;
    if (r < 0) r += vocab;
    out[i] = static_cast<int32_t>(r);
  }
}

// Already-folded int32 ids -> 3 little-endian bytes each (the u24 transfer
// packing of ops/transfer.py, one pass, no intermediate view/copy).
// Requires 0 <= ids[i] < 2^24.
void pack_u24_i32(const int32_t* ids, int64_t n, uint8_t* out) {
  for (int64_t i = 0; i < n; ++i) {
    uint32_t v = static_cast<uint32_t>(ids[i]);
    out[3 * i + 0] = static_cast<uint8_t>(v);
    out[3 * i + 1] = static_cast<uint8_t>(v >> 8);
    out[3 * i + 2] = static_cast<uint8_t>(v >> 16);
  }
}

// f32 -> bf16 with round-to-nearest-even (numpy/ml_dtypes-compatible,
// including NaN quieting).
void f32_to_bf16(const float* in, int64_t n, uint16_t* out) {
  const uint32_t* bits = reinterpret_cast<const uint32_t*>(in);
  for (int64_t i = 0; i < n; ++i) {
    uint32_t u = bits[i];
    if ((u & 0x7fffffffu) > 0x7f800000u) {   // NaN: keep quiet, drop payload
      out[i] = static_cast<uint16_t>((u >> 16) | 0x0040u);
    } else {
      uint32_t rounding = 0x7fffu + ((u >> 16) & 1u);
      out[i] = static_cast<uint16_t>((u + rounding) >> 16);
    }
  }
}

}  // extern "C"
