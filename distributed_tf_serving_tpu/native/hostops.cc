// Native host-side hot path for the serving batcher.
//
// The reference keeps its entire runtime on the JVM and delegates native
// execution to external binaries (SURVEY.md §2.3); here the TPU compute path
// is XLA/Pallas and THIS file is the native runtime for the host side of the
// request path: the fold/pack/pad batch assembly that sits between protobuf
// decode and device transfer. The numpy implementation of the same steps
// (ops/transfer.py + batcher padding) makes several full passes and
// temporaries per batch; these kernels do each transform in one pass.
//
// Exposed via a C ABI for ctypes (pybind11 is not in this image). All
// functions are thread-safe (pure element-wise transforms on caller-owned
// buffers).

#include <cstdint>
#include <cstring>

namespace {

// One multiply-xor round: full 128-bit product folded to 64 bits. The
// multiply diffuses every input bit across the word; the xor of hi/lo keeps
// both halves.
inline uint64_t mix64(uint64_t a, uint64_t b) {
  __uint128_t m = static_cast<__uint128_t>(a) * b;
  return static_cast<uint64_t>(m) ^ static_cast<uint64_t>(m >> 64);
}

// THE fold: mathematical mod (result in [0, vocab)), pow2 fast path.
// Shared by fold_i32 and the fused batch pack so the semantics cannot
// drift between them.
inline int64_t fold1(int64_t v, int64_t vocab, bool pow2, int64_t mask) {
  if (pow2) return v & mask;
  int64_t r = v % vocab;
  return r < 0 ? r + vocab : r;
}

inline void write_u24(uint8_t* dst, uint32_t v) {
  dst[0] = static_cast<uint8_t>(v);
  dst[1] = static_cast<uint8_t>(v >> 8);
  dst[2] = static_cast<uint8_t>(v >> 16);
}

// f32 bits -> bf16 bits, round-to-nearest-even with NaN quieting (the one
// rounding rule, shared by the exported f32_to_bf16 and the fused pack).
inline uint16_t bf16_bits(uint32_t u) {
  if ((u & 0x7fffffffu) > 0x7f800000u) {   // NaN: keep quiet, drop payload
    return static_cast<uint16_t>((u >> 16) | 0x0040u);
  }
  uint32_t rounding = 0x7fffu + ((u >> 16) & 1u);
  return static_cast<uint16_t>((u + rounding) >> 16);
}

// ----------------------------------------------------------------------
// blake2b (RFC 7693), keyless, 16-byte digest — the EXACT function
// hashlib.blake2b(digest_size=16) computes. Unlike hash128 above (a fast
// non-cryptographic mix private to the device-input cache), these digests
// are a WIRE contract: the row-cache keys, the dedup row identity, and
// the label-join keys clients compute over the bytes they sent must all
// be byte-identical with or without the compiled host ops — so the native
// batched path must be the real blake2b, not a lookalike.

constexpr uint64_t kB2bIV[8] = {
    0x6a09e667f3bcc908ull, 0xbb67ae8584caa73bull, 0x3c6ef372fe94f82bull,
    0xa54ff53a5f1d36f1ull, 0x510e527fade682d1ull, 0x9b05688c2b3e6c1full,
    0x1f83d9abfb41bd6bull, 0x5be0cd19137e2179ull};

constexpr uint8_t kB2bSigma[12][16] = {
    {0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15},
    {14, 10, 4, 8, 9, 15, 13, 6, 1, 12, 0, 2, 11, 7, 5, 3},
    {11, 8, 12, 0, 5, 2, 15, 13, 10, 14, 3, 6, 7, 1, 9, 4},
    {7, 9, 3, 1, 13, 12, 11, 14, 2, 6, 5, 10, 4, 0, 15, 8},
    {9, 0, 5, 7, 2, 4, 10, 15, 14, 1, 11, 12, 6, 8, 3, 13},
    {2, 12, 6, 10, 0, 11, 8, 3, 4, 13, 7, 5, 15, 14, 1, 9},
    {12, 5, 1, 15, 14, 13, 4, 10, 0, 7, 6, 3, 9, 2, 8, 11},
    {13, 11, 7, 14, 12, 1, 3, 9, 5, 0, 15, 4, 8, 6, 2, 10},
    {6, 15, 14, 9, 11, 3, 0, 8, 12, 2, 13, 7, 1, 4, 10, 5},
    {10, 2, 8, 4, 7, 6, 1, 5, 15, 11, 9, 14, 3, 12, 13, 0},
    {0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15},
    {14, 10, 4, 8, 9, 15, 13, 6, 1, 12, 0, 2, 11, 7, 5, 3}};

inline uint64_t rotr64(uint64_t x, int n) { return (x >> n) | (x << (64 - n)); }

inline void b2b_g(uint64_t* v, int a, int b, int c, int d, uint64_t x,
                  uint64_t y) {
  v[a] = v[a] + v[b] + x;
  v[d] = rotr64(v[d] ^ v[a], 32);
  v[c] = v[c] + v[d];
  v[b] = rotr64(v[b] ^ v[c], 24);
  v[a] = v[a] + v[b] + y;
  v[d] = rotr64(v[d] ^ v[a], 16);
  v[c] = v[c] + v[d];
  v[b] = rotr64(v[b] ^ v[c], 63);
}

// One 128-byte block. `t` is the byte counter INCLUDING this block (the
// messages here are < 2^64 bytes, so the high counter word stays 0).
void b2b_compress(uint64_t h[8], const uint8_t block[128], uint64_t t,
                  bool last) {
  uint64_t m[16], v[16];
  for (int i = 0; i < 16; ++i) std::memcpy(&m[i], block + 8 * i, 8);
  for (int i = 0; i < 8; ++i) {
    v[i] = h[i];
    v[i + 8] = kB2bIV[i];
  }
  v[12] ^= t;
  if (last) v[14] = ~v[14];
  for (int r = 0; r < 12; ++r) {
    const uint8_t* s = kB2bSigma[r];
    b2b_g(v, 0, 4, 8, 12, m[s[0]], m[s[1]]);
    b2b_g(v, 1, 5, 9, 13, m[s[2]], m[s[3]]);
    b2b_g(v, 2, 6, 10, 14, m[s[4]], m[s[5]]);
    b2b_g(v, 3, 7, 11, 15, m[s[6]], m[s[7]]);
    b2b_g(v, 0, 5, 10, 15, m[s[8]], m[s[9]]);
    b2b_g(v, 1, 6, 11, 12, m[s[10]], m[s[11]]);
    b2b_g(v, 2, 7, 8, 13, m[s[12]], m[s[13]]);
    b2b_g(v, 3, 4, 9, 14, m[s[14]], m[s[15]]);
  }
  for (int i = 0; i < 8; ++i) h[i] ^= v[i] ^ v[i + 8];
}

// blake2b-128 of the two-segment message header||body (the row digest's
// shape: a per-batch structure header prefixed to every row's bytes,
// without materializing the concatenation).
void blake2b16_2seg(const uint8_t* s1, int64_t n1, const uint8_t* s2,
                    int64_t n2, uint8_t* out16) {
  uint64_t h[8];
  for (int i = 0; i < 8; ++i) h[i] = kB2bIV[i];
  h[0] ^= 0x01010000ull ^ 16ull;  // digest_length=16, key=0, fanout=depth=1
  uint8_t block[128];
  const int64_t total = n1 + n2;
  if (total == 0) {
    std::memset(block, 0, 128);
    b2b_compress(h, block, 0, true);
  } else {
    int64_t off = 0;
    uint64_t t = 0;
    while (off < total) {
      const int64_t take = (total - off < 128) ? (total - off) : 128;
      int64_t filled = 0;
      while (filled < take) {
        const int64_t pos = off + filled;
        if (pos < n1) {
          const int64_t c =
              (n1 - pos < take - filled) ? (n1 - pos) : (take - filled);
          std::memcpy(block + filled, s1 + pos, static_cast<size_t>(c));
          filled += c;
        } else {
          const int64_t c = take - filled;
          std::memcpy(block + filled, s2 + (pos - n1),
                      static_cast<size_t>(c));
          filled += c;
        }
      }
      if (take < 128) {
        std::memset(block + take, 0, static_cast<size_t>(128 - take));
      }
      off += take;
      t += static_cast<uint64_t>(take);
      b2b_compress(h, block, t, off >= total);
    }
  }
  std::memcpy(out16, h, 16);  // little-endian h[0..1] = the first 16 bytes
}

}  // namespace

extern "C" {

// 128-bit content digest (two independently-keyed 64-bit lanes, 32 bytes
// per iteration) for the batcher's device-input cache. Non-cryptographic
// but well-mixed: at the cache's scale (<=1e6 distinct batches) the
// 128-bit collision probability is ~1e-27. ~5x faster than blake2b, and
// ctypes releases the GIL for the call, so hashing a ~2 MB batch never
// stalls the request handlers.
void hash128(const uint8_t* p, int64_t n, uint64_t* out) {
  const uint64_t K0 = 0x9E3779B185EBCA87ull, K1 = 0xC2B2AE3D27D4EB4Full,
                 K2 = 0x165667B19E3779F9ull, K3 = 0x27D4EB2F165667C5ull;
  uint64_t h0 = K0 ^ static_cast<uint64_t>(n);
  uint64_t h1 = K1 + static_cast<uint64_t>(n);
  int64_t i = 0;
  for (; i + 32 <= n; i += 32) {
    uint64_t a, b, c, d;
    std::memcpy(&a, p + i, 8);
    std::memcpy(&b, p + i + 8, 8);
    std::memcpy(&c, p + i + 16, 8);
    std::memcpy(&d, p + i + 24, 8);
    h0 = mix64(a ^ h0, K2 ^ b);
    h1 = mix64(c ^ h1, K3 ^ d);
  }
  if (i < n) {
    uint8_t tail[32] = {0};
    std::memcpy(tail, p + i, static_cast<size_t>(n - i));
    uint64_t a, b, c, d;
    std::memcpy(&a, tail, 8);
    std::memcpy(&b, tail + 8, 8);
    std::memcpy(&c, tail + 16, 8);
    std::memcpy(&d, tail + 24, 8);
    h0 = mix64(a ^ h0, K2 ^ b);
    h1 = mix64(c ^ h1, K3 ^ d);
  }
  out[0] = mix64(h0 ^ K1, h1 ^ K0);  // cross-mix: each output depends on
  out[1] = mix64(h1 ^ K3, h0 ^ K2);  // both lanes
}

// Batched per-row blake2b-128 (ISSUE 15 satellite): N rows of a
// contiguous [n_rows, row_bytes] uint8 matrix -> N 16-byte digests, each
// blake2b(header || row, digest_size=16) — byte-identical to the
// hashlib.blake2b python fallback in cache/row_cache.py digest_rows and
// cache/digest.py row_label_keys (header empty there). ONE ctypes call
// releases the GIL for the whole batch, replacing the per-row python
// hash loop the row-cache plane otherwise pays on every armed batch.
void hash128_rows(const uint8_t* header, int64_t header_len,
                  const uint8_t* rows, int64_t n_rows, int64_t row_bytes,
                  uint8_t* out) {
  for (int64_t r = 0; r < n_rows; ++r) {
    blake2b16_2seg(header, header_len, rows + r * row_bytes, row_bytes,
                   out + r * 16);
  }
}

// ids[i] -> int32(ids[i] mod vocab) — the uncompressed fold. Power-of-two
// vocabs (the common config) take the mask path: two's-complement AND equals
// the mathematical mod, and skips the 64-bit division.
void fold_i32(const int64_t* ids, int64_t n, int64_t vocab, int32_t* out) {
  const bool pow2 = (vocab & (vocab - 1)) == 0;
  const int64_t mask = vocab - 1;
  for (int64_t i = 0; i < n; ++i) {
    out[i] = static_cast<int32_t>(fold1(ids[i], vocab, pow2, mask));
  }
}

// Already-folded int32 ids -> 3 little-endian bytes each (the u24 transfer
// packing of ops/transfer.py, one pass, no intermediate view/copy).
// Requires 0 <= ids[i] < 2^24.
void pack_u24_i32(const int32_t* ids, int64_t n, uint8_t* out) {
  for (int64_t i = 0; i < n; ++i) {
    uint32_t v = static_cast<uint32_t>(ids[i]);
    out[3 * i + 0] = static_cast<uint8_t>(v);
    out[3 * i + 1] = static_cast<uint8_t>(v >> 8);
    out[3 * i + 2] = static_cast<uint8_t>(v >> 16);
  }
}

// f32 -> bf16 with round-to-nearest-even (numpy/ml_dtypes-compatible,
// including NaN quieting).
void f32_to_bf16(const float* in, int64_t n, uint16_t* out) {
  const uint32_t* bits = reinterpret_cast<const uint32_t*>(in);
  for (int64_t i = 0; i < n; ++i) {
    out[i] = bf16_bits(bits[i]);
  }
}

// Fused batch assembly for the flagship combined layout
// ({feat_ids: u24, feat_wts: bf16}, key-sorted so the ids segment precedes
// the weights segment): reads each request's arrays ONCE and writes the
// final padded device buffer directly —
//   out = [bucket*F*3 bytes u24(fold(ids))][bucket*F*2 bytes bf16(wts)]
// replacing the python path's pad copy + fold pass + pack pass + concat
// (4 full passes and 3 temporaries per batch, serving/batcher.py _dispatch
// + ops/transfer.py). Per part p: ids_ptrs[p] is int64 (wide wire; folded
// here) or int32 when ids_is64[p]==0 (compact wire, pre-folded by the
// client and range-checked by the service; low 3 bytes taken either way,
// matching the numpy path's truncation semantics). wts_ptrs[p] is f32
// (cast here, RNE) or bf16 bits when wts_isf32[p]==0 (compact; copied).
// Rows [total..bucket) are zero in both segments. Thread-safe; ctypes
// releases the GIL for the whole call.
void pack_batch_u24_bf16(const void** ids_ptrs, const uint8_t* ids_is64,
                         const void** wts_ptrs, const uint8_t* wts_isf32,
                         const int64_t* ns, int64_t num_parts,
                         int64_t fields, int64_t bucket, int64_t vocab,
                         uint8_t* out) {
  uint8_t* ids_base = out;
  uint8_t* wts_base = out + bucket * fields * 3;
  const bool pow2 = (vocab & (vocab - 1)) == 0;
  const int64_t mask = vocab - 1;
  int64_t row = 0;
  for (int64_t p = 0; p < num_parts; ++p) {
    const int64_t n = ns[p] * fields;
    uint8_t* idst = ids_base + row * fields * 3;
    if (ids_is64[p]) {
      const int64_t* ids = static_cast<const int64_t*>(ids_ptrs[p]);
      for (int64_t i = 0; i < n; ++i) {
        write_u24(idst + 3 * i,
                  static_cast<uint32_t>(fold1(ids[i], vocab, pow2, mask)));
      }
    } else {
      // int32 (compact wire): pre-folded by contract (service-validated
      // range [0, vocab)), so the low 3 bytes ARE the value — plain
      // truncation, exactly what the python generic path does for an
      // all-int32 group. (For OUT-of-contract ids in a MIXED group the
      // python path widens to int64 and folds while this path truncates —
      // an intentional, documented divergence reachable only by direct
      // submit() callers violating the compact contract.)
      const int32_t* ids = static_cast<const int32_t*>(ids_ptrs[p]);
      for (int64_t i = 0; i < n; ++i) {
        write_u24(idst + 3 * i, static_cast<uint32_t>(ids[i]));
      }
    }
    // Byte-granular stores: the weights segment starts at bucket*fields*3,
    // which is ODD for odd bucket*fields — a uint16_t* store there would be
    // misaligned UB (unreachable with the shipped pow2 buckets, but the
    // layout must be correct for arbitrary configs). memcpy of 2 bytes
    // compiles to a single unaligned store on x86/arm.
    uint8_t* wdst = wts_base + row * fields * 2;
    if (wts_isf32[p]) {
      const uint32_t* bits =
          static_cast<const uint32_t*>(wts_ptrs[p]);
      for (int64_t i = 0; i < n; ++i) {
        uint16_t v = bf16_bits(bits[i]);
        std::memcpy(wdst + 2 * i, &v, 2);
      }
    } else {
      std::memcpy(wdst, wts_ptrs[p], static_cast<size_t>(n) * 2);
    }
    row += ns[p];
  }
  if (row < bucket) {
    std::memset(ids_base + row * fields * 3, 0,
                static_cast<size_t>(bucket - row) * fields * 3);
    std::memset(wts_base + row * fields * 2, 0,
                static_cast<size_t>(bucket - row) * fields * 2);
  }
}

}  // extern "C"
