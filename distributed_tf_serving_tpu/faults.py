"""Deterministic fault injection for the serving stack.

The paper's fan-out design means the tail of the sickest backend is the
tail of every request — and the degraded modes that defend against it
(scoreboard ejection, hedging, partial merges, deadline shedding, the
batcher's circuit breaker) are exactly the paths ordinary traffic never
exercises. This module makes them testable ON DEMAND and DETERMINISTICALLY:
named sites inside the stack call `fire()` / `fire_async()`, which is a
no-op until rules are installed (one module-bool check on the hot path).

Named sites (the instrumented hooks):

- ``decode``            service-side request decode/validation
                        (service._predict_prepare)
- ``batcher.dispatch``  the device stage of one batch (batcher._run_stage)
- ``readback``          the completer's D2H fetch (batcher._complete)
- ``client.rpc``        one per-backend shard RPC (client._shard_call;
                        ``key`` is the backend host string, so a rule can
                        target one backend of a fan-out)
- ``pressure``          the overload controller's tick
                        (serving/overload.py _maybe_tick): an ``error``
                        rule whose ``code`` names a pressure state
                        (``BROWNOUT``/``SHED``/``NOMINAL``) pins the
                        NOMINAL->BROWNOUT->SHED state machine there while
                        the rule is installed — brownout stale-serve and
                        shed-lane behavior become testable without
                        generating real overload
- ``device_lost``       the device stage of one batch, fired once per
                        member request with ``key`` = that request's
                        poison digest (batcher.poison_fault_key over its
                        prepared arrays) — a KEYLESS rule kills any batch
                        (the device-died scenario the recovery plane
                        quarantines on), a KEYED rule kills exactly the
                        batches containing one specific request's content
                        (the deterministic poisoned-input the bisection
                        isolates). Only fired while a device_lost rule is
                        installed (has_site), so chaos runs without one
                        never pay the per-item digest
- ``executor_abort``    the completer's result path (batcher._complete,
                        next to ``readback``): the executor aborted after
                        dispatch — the recovery plane classifies it
                        device-fatal exactly like device_lost
- ``wire_corrupt``      request-tensor bytes flipped in flight (client
                        _one_rpc, after CRC stamping — the checksum
                        describes the ORIGINAL bytes, so the server-side
                        verify must catch it). ``error`` kind; ``key`` is
                        the input tensor name, so a rule can corrupt one
                        input of a multi-tensor request. Content-keyed
                        determinism rides the per-rule seeded RNG
- ``readback_bitflip``  one bit flipped in the completer's host score
                        tensor AFTER D2H (batcher._complete, post-widen),
                        fired once per member request with ``key`` = that
                        request's poison digest like ``device_lost`` — the
                        silent-corruption scenario the shadow re-execute
                        and the client's response-CRC verify both catch.
                        ``error`` kind used as a marker: the site catches
                        the raise and applies the flip instead of failing
- ``score_nan``         a row of the completer's host score tensor set to
                        NaN after D2H (same keying as readback_bitflip) —
                        the scenario the readback sanity screen catches
                        row-granularly (batchmates deliver)

Rule kinds:

- ``delay``  sleep ``delay_s`` then proceed (tail-latency injection);
- ``error``  raise InjectedFaultError carrying a grpc status-code NAME —
             the client treats it like an AioRpcError (failover/ejection),
             the service maps it onto the matching RPC status;
- ``wedge``  block until ``clear()`` (or ``delay_s`` as a safety cap when
             set) — the stuck-backend / stuck-device scenario.

Determinism: every rule gets its own ``random.Random`` seeded from
``(injector seed, site, kind, key)``, so a given rule/traffic interleaving
reproduces exactly; ``rate=1.0`` rules never consult the RNG at all.

Config: programmatic (``faults.get().add(...)``) or the ``DTS_TPU_FAULTS``
env var — semicolon-separated rules, each ``site=kind[,rate=R][,delay=D]
[,code=NAME][,count=N][,key=K]``, e.g.::

    DTS_TPU_FAULTS="client.rpc=error,rate=0.05,code=UNAVAILABLE;readback=delay,delay=0.02"
    DTS_TPU_FAULT_SEED=7

tools/soak.py's chaos mode (SOAK_CHAOS=1) rides this surface.
"""

from __future__ import annotations

import asyncio
import dataclasses
import os
import random
import threading
import time

from .utils import tracing

SITES = (
    "decode", "batcher.dispatch", "readback", "client.rpc",
    "device_lost", "executor_abort",
    "wire_corrupt", "readback_bitflip", "score_nan",
)
KINDS = ("delay", "error", "wedge")


class _Code:
    """Duck-type of grpc.StatusCode: `.name` is what the stack matches on."""

    __slots__ = ("name",)

    def __init__(self, name: str):
        self.name = name

    def __repr__(self):
        return f"StatusCode.{self.name}"


class InjectedFaultError(RuntimeError):
    """Raised by `error` rules. code()/details() mimic grpc.aio.AioRpcError
    closely enough that the client's failover/scoreboard path and the
    service's status mapping handle injected and real failures identically."""

    def __init__(self, site: str, code_name: str = "UNAVAILABLE", details: str | None = None):
        self.site = site
        self.code_name = code_name
        self._details = details or f"injected fault at {site!r}"
        super().__init__(self._details)

    def code(self) -> _Code:
        return _Code(self.code_name)

    def details(self) -> str:
        return self._details


@dataclasses.dataclass
class FaultRule:
    site: str
    kind: str
    rate: float = 1.0
    delay_s: float = 0.0
    code: str = "UNAVAILABLE"
    count: int | None = None  # max fires; None = unlimited
    key: str | None = None  # only fire when the call site's key matches
    fired: int = 0

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r}; have {KINDS}")
        # Per-rule deterministic stream: independent of every other rule's
        # draw order, reproducible across runs for the same seed.
        self._rng: random.Random | None = None
        self._unwedge = threading.Event()


class FaultInjector:
    """Rule registry + the fire sites. One process-global instance (get());
    tests may also construct private ones and pass them explicitly."""

    def __init__(self, seed: int = 0):
        self.seed = seed
        self._lock = threading.Lock()
        self._rules: list[FaultRule] = []
        self.fires: dict[str, int] = {}

    # -------------------------------------------------------------- config

    def add(
        self,
        site: str,
        kind: str = "error",
        rate: float = 1.0,
        delay_s: float = 0.0,
        code: str = "UNAVAILABLE",
        count: int | None = None,
        key: str | None = None,
    ) -> FaultRule:
        rule = FaultRule(
            site=site, kind=kind, rate=rate, delay_s=delay_s,
            code=code, count=count, key=key,
        )
        rule._rng = random.Random(f"{self.seed}:{site}:{kind}:{key}")
        with self._lock:
            self._rules.append(rule)
        if self is _GLOBAL:
            _set_active(True)
        return rule

    def clear(self, site: str | None = None) -> None:
        """Remove matching rules (all when site is None) and release every
        wedge they hold — the recovery edge of a wedged-backend scenario."""
        with self._lock:
            gone = [r for r in self._rules if site is None or r.site == site]
            self._rules = [r for r in self._rules if r not in gone]
            empty = not self._rules
        for r in gone:
            r._unwedge.set()
        if self is _GLOBAL and empty:
            _set_active(False)

    def reset(self, seed: int | None = None) -> None:
        self.clear()
        with self._lock:
            self.fires.clear()
            if seed is not None:
                self.seed = seed

    def has_site(self, site: str) -> bool:
        """True when ANY rule (spent or not) targets `site` — the cheap
        pre-gate call sites use before paying per-item key derivation
        (the device_lost poison digests)."""
        with self._lock:
            return any(r.site == site for r in self._rules)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "fires": dict(self.fires),
                "rules": [
                    {"site": r.site, "kind": r.kind, "rate": r.rate,
                     "key": r.key, "fired": r.fired}
                    for r in self._rules
                ],
            }

    # --------------------------------------------------------------- sites

    def _match(self, site: str, key: str | None) -> FaultRule | None:
        with self._lock:
            for rule in self._rules:
                if rule.site != site:
                    continue
                if rule.key is not None and key != rule.key:
                    continue
                if rule.count is not None and rule.fired >= rule.count:
                    continue
                if rule.rate < 1.0 and rule._rng.random() >= rule.rate:
                    continue
                rule.fired += 1
                self.fires[site] = self.fires.get(site, 0) + 1
                return rule
        return None

    @staticmethod
    def _annotate(site: str, rule: FaultRule, key: str | None) -> None:
        """Mark the active request span (or the batcher's phase sink) with
        the injected fault, so a chaos run's trace shows exactly where the
        delay/error/wedge landed (no-op when tracing is off)."""
        tracing.annotate(
            f"fault.{site}",
            kind=rule.kind,
            code=rule.code if rule.kind == "error" else None,
            delay_s=rule.delay_s or None,
            key=key,
        )

    def fire(self, site: str, key: str | None = None) -> None:
        """Synchronous site (server threads). Sleeps, raises, or wedges
        according to the first matching rule; returns untouched otherwise."""
        rule = self._match(site, key)
        if rule is None:
            return
        self._annotate(site, rule, key)
        if rule.kind == "delay":
            time.sleep(rule.delay_s)
        elif rule.kind == "wedge":
            # delay_s > 0 doubles as a safety cap so a forgotten clear()
            # cannot hang a thread forever.
            rule._unwedge.wait(rule.delay_s or None)
        else:
            raise InjectedFaultError(site, rule.code)

    async def fire_async(self, site: str, key: str | None = None) -> None:
        """Coroutine site (the asyncio client) — never blocks the loop."""
        rule = self._match(site, key)
        if rule is None:
            return
        self._annotate(site, rule, key)
        if rule.kind == "delay":
            await asyncio.sleep(rule.delay_s)
        elif rule.kind == "wedge":
            cap = time.perf_counter() + rule.delay_s if rule.delay_s else None
            while not rule._unwedge.is_set():
                if cap is not None and time.perf_counter() >= cap:
                    break
                await asyncio.sleep(0.02)
        else:
            raise InjectedFaultError(site, rule.code)


# ------------------------------------------------------- process-global API

_GLOBAL = FaultInjector()
_ACTIVE = False  # fast-path gate: one bool read when no faults configured


def _set_active(value: bool) -> None:
    global _ACTIVE
    _ACTIVE = value


def get() -> FaultInjector:
    return _GLOBAL


def active() -> bool:
    return _ACTIVE


def fire(site: str, key: str | None = None) -> None:
    if _ACTIVE:
        _GLOBAL.fire(site, key)


async def fire_async(site: str, key: str | None = None) -> None:
    if _ACTIVE:
        await _GLOBAL.fire_async(site, key)


def reset(seed: int | None = None) -> None:
    _GLOBAL.reset(seed)


def configure_from_env(env: str = "DTS_TPU_FAULTS") -> int:
    """Install rules from the env spec (see module docstring); returns the
    number installed. A malformed spec raises — a chaos run with a typo'd
    rule set must not silently run fault-free."""
    spec = os.environ.get(env, "").strip()
    if not spec:
        return 0
    _GLOBAL.seed = int(os.environ.get("DTS_TPU_FAULT_SEED", "0"))
    n = 0
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        head, _, tail = part.partition(",")
        site, sep, kind = head.partition("=")
        if not sep:
            raise ValueError(f"{env}: rule {part!r} needs site=kind")
        kwargs: dict = {}
        for kv in filter(None, (s.strip() for s in tail.split(","))):
            k, sep, v = kv.partition("=")
            if not sep:
                raise ValueError(f"{env}: bad option {kv!r} in {part!r}")
            if k == "rate":
                kwargs["rate"] = float(v)
            elif k == "delay":
                kwargs["delay_s"] = float(v)
            elif k == "code":
                kwargs["code"] = v
            elif k == "count":
                kwargs["count"] = int(v)
            elif k == "key":
                kwargs["key"] = v
            else:
                raise ValueError(f"{env}: unknown option {k!r} in {part!r}")
        _GLOBAL.add(site.strip(), kind.strip(), **kwargs)
        n += 1
    return n


if os.environ.get("DTS_TPU_FAULTS"):
    configure_from_env()
