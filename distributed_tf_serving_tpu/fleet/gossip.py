"""Cross-replica health gossip (ISSUE 17 tentpole, part 2).

Every fleet member — replica or router — runs one `GossipAgent`: a tiny
stdlib HTTP (or Unix-domain-socket) listener plus a push-pull exchange
loop. Each interval the agent POSTs its full view (its own fresh
`HealthRecord` + everything it has heard) to every configured peer; the
peer merges, then answers with ITS full view, which the caller merges
back. One round therefore moves information BOTH ways, so the fleet
converges through any live peer in common — no seed ordering, no leader.

Records are versioned and monotonic: each carries a `seq` stamped from
`time.time_ns()` at publish, and a member's record is replaced only by a
HIGHER seq for the same member id. A restarted process (fresh memory,
same id) keeps winning because wall-clock nanoseconds outrun any seq it
could have published before dying — the classic gossip resurrection
guard without persisted epochs. Records unheard for `ttl_s` expire from
the view: a SIGKILLed member says no goodbye, it just goes silent.

The record is deliberately compact — the fleet's steering inputs only:

    {"id": "r1", "seq": 173..., "role": "replica",
     "state": "serving" | "draining" | "quarantined" | "starting",
     "pressure": "ok" | "overloaded" | "",
     "versions": [1, 2], "canary": 2, "canary_fraction": 0.25,
     "rolled_back": null, "rollout": {...} | null, "wall_ts": 173...}

`rollout` piggybacks the shared rollout state (fleet/rollout.py) on the
same exchange, so rollout distribution needs no second protocol.

Everything here is jax-free and thread-based (the listener is a
ThreadingHTTPServer; the exchange loop is one daemon thread), so a
replica embeds it next to the grpc server without touching the event
loop, and tests drive `exchange_once()` with no threads at all.
"""

from __future__ import annotations

import dataclasses
import http.client
import http.server
import json
import logging
import socket
import threading
import time

log = logging.getLogger("dts_tpu.fleet.gossip")

# Health-record states (what the router folds into scoreboard steering).
SERVING = "serving"
DRAINING = "draining"
QUARANTINED = "quarantined"
STARTING = "starting"


@dataclasses.dataclass
class HealthRecord:
    """One member's published health, versioned by `seq` (time_ns at
    publish — monotonic across process restarts of the same id)."""

    id: str
    seq: int
    role: str = "replica"  # "replica" | "router"
    state: str = STARTING
    pressure: str = ""
    versions: tuple[int, ...] = ()
    canary: int | None = None
    canary_fraction: float = 0.0
    rolled_back: int | None = None
    rollout: dict | None = None
    # Cheap observability digest (ISSUE 18): {"addr": gossip listen addr,
    # "qps", "p50_ms", "p99_ms", "requests", "errors", "trace_export"} —
    # the router's fleet aggregator falls back to these self-reported
    # numbers when a member's /monitoring scrape fails, and learns where
    # (and whether) to pull the member's span-tree export.
    obs: dict | None = None
    # Data-integrity verdict (ISSUE 20): True while the member's
    # integrity plane holds itself suspect (shadow mismatch / screen-trip
    # escalation not yet rehabilitated). Routers steer around suspect
    # replicas; older peers' from_dict drops the key harmlessly
    # (wire-compatible, the obs-field precedent).
    suspect: bool = False
    wall_ts: float = 0.0

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["versions"] = list(self.versions)
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "HealthRecord":
        known = {f.name for f in dataclasses.fields(cls)}
        kwargs = {k: v for k, v in d.items() if k in known}
        kwargs["versions"] = tuple(int(v) for v in kwargs.get("versions", ()))
        return cls(**kwargs)


class _UdsHTTPServer(http.server.ThreadingHTTPServer):
    """ThreadingHTTPServer over AF_UNIX (gossip_uds: co-located fleets
    skip the TCP stack, the transport-floor precedent from ISSUE 9)."""

    address_family = socket.AF_UNIX

    def server_bind(self):
        import os

        try:
            if os.path.exists(self.server_address):
                os.unlink(self.server_address)
        except OSError:
            pass  # bind below gives the actionable error
        self.socket.bind(self.server_address)

    def server_close(self):
        import os

        super().server_close()
        try:
            os.unlink(self.server_address)
        except OSError:
            pass


class _UdsHTTPConnection(http.client.HTTPConnection):
    def __init__(self, path: str, timeout: float):
        super().__init__("localhost", timeout=timeout)
        self._uds_path = path

    def connect(self):
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        sock.settimeout(self.timeout)
        sock.connect(self._uds_path)
        self.sock = sock


def _open_connection(peer: str, timeout: float) -> http.client.HTTPConnection:
    """Dial a peer endpoint: "host:port" (TCP) or "unix:/path"."""
    if peer.startswith("unix:"):
        return _UdsHTTPConnection(peer[len("unix:"):], timeout)
    host, _, port = peer.rpartition(":")
    return http.client.HTTPConnection(host, int(port), timeout=timeout)


class GossipAgent:
    """One fleet member's gossip half: listener + push-pull exchanger.

    `record_fn()` returns the member's CURRENT health as a dict of
    HealthRecord fields (sans id/seq/wall_ts — the agent stamps those at
    publish). `on_update(record)` fires for every accepted REMOTE record
    change (the router folds these into its scoreboard; a replica's
    rollout follower applies coordinator state). `extra_routes` maps GET
    paths to zero-arg callables returning a JSON-able body — the router
    mounts /metrics there so one port serves gossip and scrape.
    `query_routes` is the same for routes that take URL query parameters
    (called with a {key: first value} dict — the trace-export pull's
    `?since=` cursor), and `post_routes` maps POST paths to callables
    taking the decoded JSON body (the router's /tracez/ingest push).
    """

    def __init__(
        self,
        self_id: str,
        *,
        role: str = "replica",
        host: str = "127.0.0.1",
        port: int = 0,
        uds_path: str = "",
        peers: tuple[str, ...] = (),
        interval_s: float = 0.5,
        ttl_s: float = 5.0,
        record_fn=None,
        on_update=None,
        extra_routes: dict | None = None,
        query_routes: dict | None = None,
        post_routes: dict | None = None,
        clock=time.time,
        seq_fn=time.time_ns,
        dial_timeout_s: float = 2.0,
    ):
        self.self_id = self_id
        self.role = role
        self.peers = tuple(peers)
        self.interval_s = interval_s
        self.ttl_s = ttl_s
        self.record_fn = record_fn or (lambda: {})
        self.on_update = on_update
        self.extra_routes = dict(extra_routes or {})
        self.query_routes = dict(query_routes or {})
        self.post_routes = dict(post_routes or {})
        self._clock = clock
        self._seq = seq_fn
        self._dial_timeout_s = dial_timeout_s
        self._lock = threading.Lock()
        # id -> (HealthRecord, local receipt time) — receipt time drives
        # TTL expiry (a peer's wall clock never gates ITS liveness here).
        self._view: dict[str, tuple[HealthRecord, float]] = {}
        # Counters (all monotonic; /fleetz + dts_tpu_fleet_*).
        self.exchanges_ok = 0
        self.exchanges_failed = 0
        self.records_accepted = 0
        self.records_stale = 0
        self.records_expired = 0
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        self._server: http.server.ThreadingHTTPServer | None = None
        self._server_thread: threading.Thread | None = None
        self._uds_path = uds_path
        self._host, self._port = host, port

    # ------------------------------------------------------------ records

    def self_record(self) -> HealthRecord:
        """Stamp the member's current health as a fresh record."""
        fields = dict(self.record_fn() or {})
        fields.pop("id", None)
        fields.pop("seq", None)
        fields.setdefault("role", self.role)
        rec = HealthRecord(
            id=self.self_id,
            seq=self._seq(),
            wall_ts=round(self._clock(), 3),
            **{k: v for k, v in fields.items()
               if k in {f.name for f in dataclasses.fields(HealthRecord)}},
        )
        if isinstance(rec.versions, list):
            rec.versions = tuple(rec.versions)
        return rec

    def merge(self, records) -> list[HealthRecord]:
        """Fold remote records into the view (higher seq per id wins; own
        id ignored — a member is the sole authority on itself). Returns
        the accepted changes; fires on_update for each."""
        now = self._clock()
        changed: list[HealthRecord] = []
        with self._lock:
            for raw in records or ():
                try:
                    rec = (
                        raw if isinstance(raw, HealthRecord)
                        else HealthRecord.from_dict(raw)
                    )
                except (TypeError, ValueError, KeyError):
                    continue  # malformed record: skip, never poison a round
                if not rec.id or rec.id == self.self_id:
                    continue
                held = self._view.get(rec.id)
                if held is not None and held[0].seq >= rec.seq:
                    self.records_stale += 1
                    # Still a liveness signal: ANY heartbeat-fresh copy
                    # of the same record proves the member spoke
                    # recently somewhere in the fleet — refresh receipt.
                    if held[0].seq == rec.seq:
                        self._view[rec.id] = (held[0], now)
                    continue
                self._view[rec.id] = (rec, now)
                self.records_accepted += 1
                changed.append(rec)
        if self.on_update is not None:
            for rec in changed:
                try:
                    self.on_update(rec)
                except Exception:  # noqa: BLE001 — a fold bug must not
                    log.exception("gossip on_update failed")  # kill gossip
        return changed

    def _expire_locked(self, now: float) -> None:
        dead = [
            mid for mid, (_, seen) in self._view.items()
            if now - seen > self.ttl_s
        ]
        for mid in dead:
            del self._view[mid]
            self.records_expired += 1

    def view(self, include_self: bool = True) -> dict[str, HealthRecord]:
        """Fresh records by member id (TTL-expired members dropped)."""
        now = self._clock()
        with self._lock:
            self._expire_locked(now)
            out = {mid: rec for mid, (rec, _) in self._view.items()}
        if include_self:
            out[self.self_id] = self.self_record()
        return out

    def wire_view(self) -> dict:
        return {
            "records": [r.to_dict() for r in self.view().values()],
        }

    # ----------------------------------------------------------- exchange

    def exchange_once(self, peer: str) -> bool:
        """One push-pull round with one peer: POST our view, merge the
        response view. Returns success (for tests and the loop's
        counters)."""
        body = json.dumps(self.wire_view()).encode("utf-8")
        try:
            conn = _open_connection(peer, self._dial_timeout_s)
            try:
                conn.request(
                    "POST", "/gossip", body=body,
                    headers={"Content-Type": "application/json"},
                )
                resp = conn.getresponse()
                data = resp.read()
                if resp.status != 200:
                    raise OSError(f"gossip peer answered {resp.status}")
            finally:
                conn.close()
            self.merge(json.loads(data).get("records"))
        except Exception:  # noqa: BLE001 — a dead peer is the NORMAL case
            self.exchanges_failed += 1
            return False
        self.exchanges_ok += 1
        return True

    def _loop(self, stop: threading.Event) -> None:
        while not stop.wait(self.interval_s):
            for peer in self.peers:
                if stop.is_set():
                    return
                self.exchange_once(peer)

    # ----------------------------------------------------------- listener

    def _make_handler(self):
        agent = self

        class Handler(http.server.BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def _json(self, status: int, payload) -> None:
                body = json.dumps(payload).encode("utf-8")
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):  # noqa: N802 — BaseHTTPRequestHandler API
                try:
                    n = int(self.headers.get("Content-Length", "0"))
                    payload = json.loads(self.rfile.read(n) or b"{}")
                except ValueError:
                    self._json(400, {"error": "bad payload"})
                    return
                if self.path == "/gossip":
                    try:
                        agent.merge(payload.get("records"))
                    except (ValueError, KeyError, AttributeError):
                        self._json(400, {"error": "bad gossip payload"})
                        return
                    self._json(200, agent.wire_view())
                    return
                route = agent.post_routes.get(self.path)
                if route is None:
                    self._json(404, {"error": "not found"})
                    return
                try:
                    self._json(200, route(payload) or {})
                except Exception:  # noqa: BLE001 — a sick route must not
                    log.exception("gossip post route %s failed", self.path)
                    self._json(500, {"error": "route failed"})

            def do_GET(self):  # noqa: N802
                # Extra routes first: the router overrides /fleetz with
                # its richer fleet snapshot on the same port. Query
                # strings are split off so `/route?k=v` matches the
                # `/route` key; query_routes receive the parsed params.
                path, _, qs = self.path.partition("?")
                route = agent.query_routes.get(path)
                if route is not None:
                    import urllib.parse

                    query = {
                        k: v[0]
                        for k, v in urllib.parse.parse_qs(qs).items()
                    }
                    try:
                        self._json(200, route(query))
                    except Exception:  # noqa: BLE001
                        log.exception("gossip query route %s failed", path)
                        self._json(500, {"error": "route failed"})
                    return
                route = agent.extra_routes.get(path)
                if route is None and path == "/gossip":
                    self._json(200, agent.wire_view())
                    return
                if route is None and path == "/fleetz":
                    self._json(200, agent.snapshot())
                    return
                if route is not None:
                    try:
                        payload = route()
                    except Exception:  # noqa: BLE001
                        log.exception("gossip extra route %s failed",
                                      self.path)
                        self._json(500, {"error": "route failed"})
                        return
                    if isinstance(payload, (bytes, str)):
                        body = (
                            payload.encode("utf-8")
                            if isinstance(payload, str) else payload
                        )
                        self.send_response(200)
                        self.send_header(
                            "Content-Type", "text/plain; charset=utf-8"
                        )
                        self.send_header("Content-Length", str(len(body)))
                        self.end_headers()
                        self.wfile.write(body)
                    else:
                        self._json(200, payload)
                    return
                self._json(404, {"error": "not found"})

            def log_message(self, fmt, *args):  # quiet: gossip is chatty
                log.debug("gossip http: " + fmt, *args)

        return Handler

    def start(self) -> "GossipAgent":
        """Bind the listener and start the exchange loop. Idempotent."""
        if self._server is None:
            handler = self._make_handler()
            if self._uds_path:
                self._server = _UdsHTTPServer(self._uds_path, handler)
            else:
                self._server = http.server.ThreadingHTTPServer(
                    (self._host, self._port), handler
                )
                self._port = self._server.server_address[1]
            self._server.daemon_threads = True
            self._server_thread = threading.Thread(
                target=self._server.serve_forever,
                kwargs={"poll_interval": 0.1},
                name="gossip-http", daemon=True,
            )
            self._server_thread.start()
        if self._thread is None or not self._thread.is_alive():
            stop = threading.Event()
            self._stop = stop
            self._thread = threading.Thread(
                target=self._loop, args=(stop,), name="gossip", daemon=True
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            if self._server_thread is not None:
                self._server_thread.join(timeout=2)
                self._server_thread = None
            self._server = None

    @property
    def listen_addr(self) -> str:
        """How peers reach this member ("host:port" or "unix:/path")."""
        if self._uds_path:
            return f"unix:{self._uds_path}"
        return f"{self._host}:{self._port}"

    # ----------------------------------------------------------- surfaces

    def snapshot(self) -> dict:
        """The /fleetz body and the dts_tpu_fleet_* Prometheus source."""
        view = self.view()
        return {
            "enabled": True,
            "self_id": self.self_id,
            "role": self.role,
            "listen": self.listen_addr,
            "peers": list(self.peers),
            "members": {mid: rec.to_dict() for mid, rec in view.items()},
            "member_count": len(view),
            "counters": {
                "exchanges_ok": self.exchanges_ok,
                "exchanges_failed": self.exchanges_failed,
                "records_accepted": self.records_accepted,
                "records_stale": self.records_stale,
                "records_expired": self.records_expired,
            },
        }
