"""Fleet robustness plane (ISSUE 17): the tier ABOVE one replica.

Three pieces, composing the per-replica planes into a fleet:

- `gossip.py` — cross-replica health gossip. Every member (replica or
  router) runs a `GossipAgent`: a tiny HTTP/UDS listener plus a push-pull
  exchange loop. Each member publishes a compact, versioned
  `HealthRecord` (serving/draining/quarantined state from the recovery
  plane, pressure from the overload plane, loaded versions, canary
  state); records merge by highest sequence number, so the fleet view
  converges through ANY live peer in common.
- `rollout.py` — fleet-coordinated rollout. The PR-8 per-replica canary
  ramp lifted to shared state: a single writer (the router) adopts the
  ramp leader's fraction fleet-wide and turns any one replica's rollback
  into a fleet-wide version blacklist in the same tick. State rides the
  gossip records; followers apply it through
  `LifecycleController.set_fleet_fraction` / `fleet_blacklist`.
- `router.py` — the router process. Speaks the PredictionService wire
  protocol on both transports and embeds `ShardedPredictClient`
  server-side, so the scoreboard/hedging/failover/affinity machinery
  built for the fan-out client becomes the fleet's steering brain.
  Gossip folds into the scoreboard, so a replica's quarantine or drain
  steers the fleet BEFORE its first failed RPC.

Everything here is jax-free and off by default: a replica without
`[fleet] enabled = true` pays one attribute read per hook, and scores
through the router are bit-identical to a direct backend call.
"""

from .gossip import GossipAgent, HealthRecord  # noqa: F401
from .rollout import RolloutCoordinator, RolloutFollower, RolloutState  # noqa: F401
