"""The fleet router (ISSUE 17 tentpole, part 1).

A standalone process speaking the PredictionService wire protocol on
both transports (TCP + optional UDS) that embeds `ShardedPredictClient`
SERVER-side: every steering mechanism built for the fan-out client —
scoreboard ejection/half-open probes, hedging, failover, jittered
backoff, retry budgets, jump-hash row affinity (`placement="affinity"`),
partial results — becomes the fleet's routing brain, with zero new
steering code. An edge client dials ONE address; a replica's death is a
router-local failover, not a client-visible error.

Request metadata rides through the hop: the edge's deadline becomes the
embedded client's per-attempt timeout (context.time_remaining), its
`x-dts-criticality` lane and `traceparent` forward verbatim, and its
`x-dts-retry-budget` caps the router's own attempt budget at
min(local, advertised) — the fleet never multiplies the edge's retry
intent (all via `client.request_overrides`, a contextvar scope, so one
embedded client serves many concurrent edge requests).

Health arrives three ways, fastest wins:
- gossip (fleet/gossip.py): a replica announcing draining/quarantined
  steers the whole fleet BEFORE its first failed RPC;
- grpc.health.v1 Watch subscriptions per backend (the satellite: push,
  not half-open polling);
- the RPC outcomes themselves (the scoreboard's native signal).

The router is also the rollout coordinator (fleet/rollout.py,
`rollout_writer=true`): its gossip record carries the shared rollout
state every replica follows.

Run it as `python -m distributed_tf_serving_tpu.fleet.router --config
router.toml` or `... .serving.server --router --config router.toml`:
[server] is the router's bind address, [client] its backend list +
steering knobs, [fleet] gossip/rollout. jax-free by construction — the
router never loads a model.

Scores through the router are bit-identical to a direct backend call:
inputs decode/re-encode through the same codec both hops, and float32
tensors round-trip exactly. Deliberate simplifications, documented:
the router serves the client's single configured model + score output
(NOT_FOUND otherwise), and PredictStream answers as ONE final chunk —
the stream's incremental-merge benefit needs row ownership the router
already spent on fleet affinity.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import logging
import signal
import threading
import time

import grpc
import grpc.aio

import numpy as np

from .. import codec
from ..client.client import (
    PredictClientError,
    PredictResult,
    build_predict_request,
    client_from_config,
)
from ..client.health import HEALTHY
from ..proto import health as health_proto
from ..proto import serving_apis_pb2 as apis
from ..proto.service_grpc import (
    KEEPALIVE_SERVER_OPTIONS,
    LARGE_MESSAGE_CHANNEL_OPTIONS,
    add_PredictionServiceServicer_to_server,
)
from ..utils import tracing
from ..utils.config import load_config
from ..utils.metrics import WindowedLatency
from . import gossip as gossip_mod
from .gossip import GossipAgent
from .observability import FleetObservabilityPlane
from .rollout import RolloutCoordinator

log = logging.getLogger("dts_tpu.fleet.router")

_CRITICALITY_KEY = "x-dts-criticality"
_RETRY_BUDGET_KEY = "x-dts-retry-budget"
_DEGRADED_KEY = "x-dts-degraded"
_PEER_ROLE_KEY = "x-dts-peer-role"


def _metadata_of(context) -> dict[str, str]:
    try:
        return {k: v for k, v in context.invocation_metadata() or ()
                if isinstance(v, str)}
    except Exception:  # noqa: BLE001 — metadata quirks must not fail RPCs
        return {}


def _deadline_of(context) -> float | None:
    remaining = context.time_remaining()
    if remaining is None or remaining == float("inf") or remaining <= 0:
        return None
    return remaining


class Router:
    """The wiring: embedded client + gossip agent + rollout coordinator
    + counters. Servicers below are thin adapters over `forward()`."""

    def __init__(self, cfgs: dict, *, clock=time.time):
        self.client = client_from_config(cfgs["client"])
        self.fleet_cfg = cfgs.get("fleet")
        self.obs_cfg = cfgs.get("observability")
        self.slo_cfg = cfgs.get("slo")
        self._clock = clock
        # Router-side rolling latency window (always on — the /monitoring
        # parity surface needs "what is the router doing NOW" even with
        # tracing off; one histogram record per forward).
        self.window = WindowedLatency(
            window_s=(
                self.obs_cfg.window_seconds
                if self.obs_cfg is not None else 60.0
            )
        )
        # Per-backend windows on the embedded client (the /monitoring
        # parity satellite: windowed latency per replica as steered).
        self.client.enable_backend_windows(self.window.window_s)
        # Gossip record id -> backend index in the client's host list.
        # Convention: a replica's [fleet] self_id is its SERVING address
        # exactly as the router's [client] hosts lists it.
        self._backend_idx = {h: i for i, h in enumerate(self.client.hosts)}
        self.coordinator: RolloutCoordinator | None = None
        self.gossip: GossipAgent | None = None
        self.plane: FleetObservabilityPlane | None = None
        if self.fleet_cfg is not None and self.fleet_cfg.enabled:
            if self.fleet_cfg.rollout_writer:
                self.coordinator = RolloutCoordinator(
                    self.fleet_cfg.rollout_state_file, clock=clock
                )
            # The aggregation half (ISSUE 18): member scrape + trace
            # stitch + SLO burn. Created with gossip — member discovery
            # rides the gossip view — and ticked by its own daemon thread
            # once run_router starts it.
            self.plane = FleetObservabilityPlane(
                members_fn=self._members,
                self_source=self.fleet_cfg.self_id or "router",
                local_export=(
                    lambda since: tracing.recorder().export_since(since)
                ),
                slo_cfg=self.slo_cfg,
                interval_s=(
                    self.obs_cfg.trace_export_interval_s
                    if self.obs_cfg is not None else 1.0
                ),
                clock=clock,
            )
            self.gossip = GossipAgent(
                self.fleet_cfg.self_id or "router",
                role="router",
                host=self.fleet_cfg.gossip_host,
                port=self.fleet_cfg.gossip_port,
                uds_path=self.fleet_cfg.gossip_uds,
                peers=self.fleet_cfg.peers,
                interval_s=self.fleet_cfg.gossip_interval_s,
                ttl_s=self.fleet_cfg.record_ttl_s,
                record_fn=self._gossip_record,
                on_update=self.fold_gossip,
                extra_routes={
                    "/fleetz": self.fleetz,
                    "/metrics": self.prometheus_text,
                    "/monitoring": self.monitoring,
                    "/fleet/monitoring": self.plane.aggregate_snapshot,
                    "/sloz": self.plane.slo_snapshot,
                },
                query_routes={
                    "/tracez": self._tracez_route,
                    "/tracez/export": self._trace_export_route,
                },
                post_routes={
                    "/tracez/ingest": self.plane.ingest_push,
                },
                clock=clock,
            )
        # Counters (monotonic; /fleetz + dts_tpu_fleet_*).
        self.requests = 0
        self.errors = 0
        self.degraded = 0
        self.gossip_steers = 0
        self.gossip_rejoins = 0
        self.watch_updates = 0
        # Router-side integrity audit (ISSUE 20): a sampled fraction of
        # forwards ALSO fans the same tensors to two replicas and
        # compares the score bytes bit-identically — the only corruption
        # detector that works when a replica's own plane is lying (or
        # off). Armed by [integrity] router_audit_fraction in the
        # router's config; the deterministic accumulator mirrors the
        # replica-side shadow sampler (no RNG).
        self.integrity_cfg = cfgs.get("integrity")
        self._audit_acc = 0.0
        self.audits = 0
        self.audit_disagreements = 0
        self.audit_suspects_marked = 0
        self.suspect_steers = 0
        self._audit_tasks: set[asyncio.Task] = set()
        self._started_t = clock()
        self._watch_tasks: list[asyncio.Task] = []

    # ------------------------------------------------------ observability

    def _members(self) -> dict:
        return (
            self.gossip.view(include_self=False)
            if self.gossip is not None else {}
        )

    def _tracez_route(self, query: dict):
        """GET /tracez on the router's gossip port: the STITCHED
        cross-process view (json default; ?format=chrome for the
        multi-pid Perfetto export)."""
        if not tracing.enabled() or self.plane is None:
            return {"enabled": False, "traces": []}
        limit = 50
        try:
            limit = max(1, int(query.get("limit", limit)))
        except (TypeError, ValueError):
            pass
        if query.get("format") == "chrome":
            return self.plane.collector.chrome_trace(limit)
        return self.plane.collector.tracez(limit)

    def _trace_export_route(self, query: dict) -> dict:
        """GET /tracez/export on the router: the router's OWN local span
        trees (a higher-tier collector could stitch routers too)."""
        if not tracing.enabled():
            return {"enabled": False, "cursor": 0, "spans": []}
        try:
            since = int(query.get("since", 0) or 0)
        except (TypeError, ValueError):
            since = 0
        return tracing.recorder().export_since(since)

    # ------------------------------------------------------------- gossip

    def _gossip_record(self) -> dict:
        rec = {"state": gossip_mod.SERVING}
        if self.coordinator is not None and self.gossip is not None:
            # Coordination rides the publish cadence: fold the current
            # view (sans self — self_record() is what's being built) and
            # attach the resulting shared state to the outgoing record.
            view = self.gossip.view(include_self=False)
            rec["rollout"] = self.coordinator.tick(view).to_dict()
        return rec

    def fold_gossip(self, rec) -> None:
        """Gossip -> scoreboard steering: quarantine/drain announcements
        steer the fleet BEFORE the first failed RPC lands on them; a
        fresh serving record from a non-healthy backend is the rejoin
        path (the restarted process re-admits itself by speaking)."""
        sb = self.client.scoreboard
        idx = self._backend_idx.get(rec.id)
        if sb is None or idx is None:
            return
        if rec.state == gossip_mod.DRAINING:
            if sb.state(idx) != gossip_mod.DRAINING:
                self.gossip_steers += 1
            sb.record_failure(idx, kind="draining")
        elif rec.state in (gossip_mod.QUARANTINED, gossip_mod.STARTING):
            if sb.state(idx) == HEALTHY:
                self.gossip_steers += 1
                sb.record_failure(idx, kind="rebuilding")
        elif getattr(rec, "suspect", False):
            # SERVING but integrity-suspect (ISSUE 20): the replica's own
            # plane caught its data path miscomputing (shadow mismatch /
            # screen burst) and gossiped the verdict. Busy-bias steer
            # (kind="corrupt" — the pushback shape, never ejection on a
            # verdict alone): traffic prefers other replicas while the
            # suspect rehabilitates, and the next clean gossip record
            # rejoins it below.
            self.suspect_steers += 1
            sb.record_failure(idx, kind="corrupt")
        elif rec.state == gossip_mod.SERVING and sb.state(idx) != HEALTHY:
            self.gossip_rejoins += 1
            sb.record_success(idx)

    # --------------------------------------------------- health watchers

    async def watch_backends(self) -> None:
        """Subscribe to every backend's grpc.health.v1 Watch stream (the
        satellite: push replaces half-open polling). Each status CHANGE
        folds into the scoreboard; a broken stream retries with capped
        backoff forever — a dead backend simply has no stream."""
        for idx in range(len(self.client.hosts)):
            self._watch_tasks.append(
                asyncio.ensure_future(self._watch_one(idx))
            )

    async def _watch_one(self, idx: int) -> None:
        backoff = 0.5
        while True:
            try:
                # The client's channel for this backend: one connection
                # serves Predict traffic and the Watch subscription.
                stub = health_proto.HealthStub(
                    self.client._channels[idx][0]
                )
                call = stub.Watch(health_proto.HealthCheckRequest(""))
                async for resp in call:
                    backoff = 0.5
                    self._fold_watch(idx, resp.status)
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — a dead backend is normal
                pass
            await asyncio.sleep(backoff)
            backoff = min(backoff * 2, 5.0)

    def _fold_watch(self, idx: int, status: int) -> None:
        sb = self.client.scoreboard
        if sb is None:
            return
        self.watch_updates += 1
        if status == health_proto.SERVING:
            if sb.state(idx) != HEALTHY:
                self.gossip_rejoins += 1
                sb.record_success(idx)
        elif status == health_proto.NOT_SERVING and sb.state(idx) == HEALTHY:
            # No reason trailer on a stream message: steer-around bias
            # (rebuilding), not ejection — gossip carries the distinction
            # between drain and quarantine.
            sb.record_failure(idx, kind="rebuilding")

    def stop_watchers(self) -> None:
        for t in self._watch_tasks:
            t.cancel()
        self._watch_tasks = []
        for t in list(self._audit_tasks):
            t.cancel()
        self._audit_tasks.clear()

    # ----------------------------------------- integrity audit (ISSUE 20)

    def _want_audit(self) -> bool:
        cfg = self.integrity_cfg
        if (
            cfg is None
            or not cfg.enabled
            or cfg.router_audit_fraction <= 0.0
            or len(self.client.hosts) < 2
        ):
            return False
        self._audit_acc += cfg.router_audit_fraction
        if self._audit_acc >= 1.0:
            self._audit_acc -= 1.0
            return True
        return False

    async def audit(self, arrays: dict) -> bool | None:
        """Two-replica bit-identity audit of one sampled request: the
        SAME tensors scored independently by two healthy replicas must
        produce byte-identical score vectors (same model version, same
        deterministic executable). Disagreement means one of them is
        corrupting silently; a third replica (when the fleet has one)
        breaks the tie and the MINORITY is marked in the scoreboard
        (kind="corrupt" — busy-bias steer, the gossip-suspect shape).
        Returns True (agreed), False (disagreed), None (not enough
        answers to judge)."""
        sb = self.client.scoreboard
        healthy = [
            i for i in range(len(self.client.hosts))
            if sb is None or sb.state(i) == HEALTHY
        ]
        if len(healthy) < 2:
            return None
        self.audits += 1
        a, b = healthy[0], healthy[1]
        ra = await self._audit_call(a, arrays)
        rb = await self._audit_call(b, arrays)
        if ra is None or rb is None:
            return None
        if self._bits_eq(ra, rb):
            return True
        self.audit_disagreements += 1
        minority = None
        if len(healthy) >= 3:
            rc = await self._audit_call(healthy[2], arrays)
            if rc is not None:
                if self._bits_eq(rc, ra):
                    minority = b
                elif self._bits_eq(rc, rb):
                    minority = a
                # Three distinct answers: nobody is a majority — mark
                # no one (a wrong conviction steers traffic away from a
                # healthy replica).
        if minority is not None and sb is not None:
            self.audit_suspects_marked += 1
            sb.record_failure(minority, kind="corrupt")
            log.warning(
                "integrity audit: replica %s disagreed with the majority "
                "score bytes — marked suspect (busy-bias steer)",
                self.client.hosts[minority],
            )
        return False

    @staticmethod
    def _bits_eq(a: np.ndarray, b: np.ndarray) -> bool:
        return (
            a.dtype == b.dtype
            and a.shape == b.shape
            and a.tobytes() == b.tobytes()
        )

    async def _audit_call(self, idx: int, arrays: dict):
        """One audit probe straight at one backend: no failover, no
        hedging, no scoreboard recording — a probe that fails is simply
        an inconclusive audit, never a health signal (the RPC path
        already owns that)."""
        try:
            req = build_predict_request(
                arrays,
                self.client.model_name,
                self.client.signature_name,
                output_filter=(self.client.output_key,),
                version_label=self.client.version_label,
                use_tensor_content=self.client.use_tensor_content,
            )
            stub = self.client._stubs[idx][0]
            resp = await stub.Predict(req, timeout=self.client.timeout_s)
            return np.ascontiguousarray(
                codec.to_ndarray(resp.outputs[self.client.output_key])
            )
        except Exception:  # noqa: BLE001 — an unanswerable probe is inconclusive
            return None

    # ------------------------------------------------------------ forward

    def healthy_backends(self) -> int:
        sb = self.client.scoreboard
        if sb is None:
            return len(self.client.hosts)
        return sum(
            1 for i in range(len(self.client.hosts))
            if sb.state(i) == HEALTHY
        )

    async def forward(self, request: apis.PredictRequest, context):
        """One edge Predict through the embedded client. Returns the
        merged score array (+ degraded flag); raises PredictClientError
        for the servicer to map."""
        name = request.model_spec.name
        if name and name != self.client.model_name:
            raise ServiceRefusal(
                grpc.StatusCode.NOT_FOUND,
                f"router serves model {self.client.model_name!r}, "
                f"not {name!r}",
            )
        try:
            arrays = {
                k: codec.to_ndarray(request.inputs[k])
                for k in request.inputs
            }
        except (codec.CodecError, ValueError) as e:
            raise ServiceRefusal(
                grpc.StatusCode.INVALID_ARGUMENT, f"bad input tensor: {e}"
            ) from e
        if not arrays:
            raise ServiceRefusal(
                grpc.StatusCode.INVALID_ARGUMENT, "request has no inputs"
            )
        md = _metadata_of(context)
        budget = md.get(_RETRY_BUDGET_KEY)
        try:
            budget = max(int(budget), 1) if budget else None
        except ValueError:
            budget = None
        self.requests += 1
        # Root router span (ISSUE 18): adopts the edge's traceparent, so
        # the edge client / router / replica trees share one trace id; the
        # embedded client re-roots ITS spans under this one (the override
        # traceparent below), so per-attempt/hedge `client.rpc` children
        # stitch in as grandchildren. One enabled() read when tracing is
        # off — the disabled path is the pre-ISSUE code shape.
        span_cm = (
            tracing.start_root(
                "router.route",
                traceparent=md.get("traceparent"),
                attrs={
                    "backends": len(self.client.hosts),
                    "healthy_backends": self.healthy_backends(),
                    "criticality": md.get(_CRITICALITY_KEY) or "default",
                },
            )
            if tracing.enabled() else None
        )
        if span_cm is not None:
            # Peer-role attribution for the EDGE's client.rpc span
            # (ISSUE 18 satellite): answered on initial metadata —
            # trailing metadata already carries the degraded marker.
            try:
                await context.send_initial_metadata(
                    ((_PEER_ROLE_KEY, "router"),)
                )
            except Exception:  # noqa: BLE001 — advisory only
                pass
        t0 = time.perf_counter()
        try:
            if span_cm is None:
                with self.client.request_overrides(
                    criticality=md.get(_CRITICALITY_KEY),
                    timeout_s=_deadline_of(context),
                    traceparent=md.get("traceparent"),
                    max_attempts_total=budget,
                ):
                    result = await self.client.predict(arrays)
            else:
                with span_cm as span:
                    with self.client.request_overrides(
                        criticality=md.get(_CRITICALITY_KEY),
                        timeout_s=_deadline_of(context),
                        traceparent=tracing.make_traceparent(
                            span.trace_id, span.span_id
                        ),
                        max_attempts_total=budget,
                    ):
                        result = await self.client.predict(arrays)
                    if self.plane is not None and self.plane.slo_breached:
                        # Burn-rate breach in progress: mark the span so
                        # the tail sampler force-keeps it — the traces
                        # that EXPLAIN the breach survive sampling.
                        span.annotate(
                            "slo.burn",
                            breaches=self.plane.slo.breaches,
                        )
        finally:
            self.window.record(time.perf_counter() - t0)
        if self._want_audit():
            # Fire-and-forget: the audit must never add latency to the
            # forwarded answer it samples. Task refs held so the loop
            # cannot GC a running audit mid-flight.
            task = asyncio.ensure_future(self.audit(arrays))
            self._audit_tasks.add(task)
            task.add_done_callback(self._audit_tasks.discard)
        if isinstance(result, PredictResult):
            if result.degraded:
                self.degraded += 1
                try:
                    context.set_trailing_metadata(((_DEGRADED_KEY, "partial"),))
                except Exception:  # noqa: BLE001 — advisory only
                    pass
            return result.scores
        return result

    def encode_response(self, request, scores) -> apis.PredictResponse:
        resp = apis.PredictResponse()
        resp.model_spec.name = self.client.model_name
        resp.model_spec.signature_name = (
            request.model_spec.signature_name or "serving_default"
        )
        # Mirror the edge's tensor encoding (the server's own rule, so
        # the bytes match a direct backend response).
        mirror = any(
            request.inputs[name].tensor_content for name in request.inputs
        )
        codec.from_ndarray(
            scores, use_tensor_content=mirror,
            out=resp.outputs[self.client.output_key],
        )
        return resp

    # ----------------------------------------------------------- surfaces

    def fleetz(self) -> dict:
        out = {
            "enabled": True,
            "role": "router",
            "model": self.client.model_name,
            "backends": list(self.client.hosts),
            "healthy_backends": self.healthy_backends(),
            "uptime_s": round(self._clock() - self._started_t, 3),
            "counters": {
                "requests": self.requests,
                "errors": self.errors,
                "degraded": self.degraded,
                "gossip_steers": self.gossip_steers,
                "gossip_rejoins": self.gossip_rejoins,
                "watch_updates": self.watch_updates,
                "suspect_steers": self.suspect_steers,
                "integrity_audits": self.audits,
                "audit_disagreements": self.audit_disagreements,
                "audit_suspects_marked": self.audit_suspects_marked,
            },
            "resilience": self.client.resilience_counters(),
        }
        if self.gossip is not None:
            out["gossip"] = self.gossip.snapshot()
        if self.coordinator is not None:
            out["rollout"] = self.coordinator.snapshot()
        return out

    def monitoring(self) -> dict:
        """GET /monitoring parity for the router (ISSUE 18 satellite):
        the steering scoreboard, per-backend windowed latency, and
        gossip/rollout counters in ONE JSON — the replica's /monitoring
        sibling, so fleet dashboards scrape both roles the same way."""
        resilience = self.client.resilience_counters()
        out = {
            "role": "router",
            "model": self.client.model_name,
            "uptime_s": round(self._clock() - self._started_t, 3),
            "window": self.window.snapshot(),
            "counters": {
                "requests": self.requests,
                "errors": self.errors,
                "degraded": self.degraded,
                "gossip_steers": self.gossip_steers,
                "gossip_rejoins": self.gossip_rejoins,
                "watch_updates": self.watch_updates,
                "suspect_steers": self.suspect_steers,
                "integrity_audits": self.audits,
                "audit_disagreements": self.audit_disagreements,
                "audit_suspects_marked": self.audit_suspects_marked,
            },
            "healthy_backends": self.healthy_backends(),
            "scoreboard": resilience.get("scoreboard"),
            "backend_windows": self.client.backend_window_snapshots(),
            "resilience": resilience,
        }
        if self.gossip is not None:
            out["gossip"] = self.gossip.snapshot()
        if self.coordinator is not None:
            out["rollout"] = self.coordinator.snapshot()
        if self.plane is not None:
            out["fleet_aggregate"] = self.plane.agg_block()
            slo = self.plane.slo_block()
            if slo is not None:
                out["slo"] = slo
        return out

    def fleet_stats(self) -> dict:
        """The shape utils.metrics._fleet_prometheus_lines consumes (the
        replica side builds the same shape in service.fleet_stats)."""
        stats = {
            "role": "router",
            "router": {
                "requests": self.requests,
                "errors": self.errors,
                "degraded": self.degraded,
                "gossip_steers": self.gossip_steers,
                "gossip_rejoins": self.gossip_rejoins,
                "watch_updates": self.watch_updates,
                "suspect_steers": self.suspect_steers,
                "integrity_audits": self.audits,
                "audit_disagreements": self.audit_disagreements,
                "audit_suspects_marked": self.audit_suspects_marked,
                "healthy_backends": self.healthy_backends(),
                "backends": len(self.client.hosts),
            },
        }
        if self.gossip is not None:
            stats["gossip"] = self.gossip.snapshot()
        if self.coordinator is not None:
            stats["rollout"] = self.coordinator.snapshot()
        if self.plane is not None:
            agg = self.plane.agg_block()
            if agg:
                stats["agg"] = agg
            slo = self.plane.slo_block()
            if slo is not None:
                stats["slo"] = slo
        return stats

    def prometheus_text(self) -> str:
        from ..utils.metrics import fleet_prometheus_text

        return fleet_prometheus_text(self.fleet_stats())


class ServiceRefusal(Exception):
    """A router-local refusal with a grpc status (the ServiceError shape
    without the serving package's jax-linked import)."""

    def __init__(self, code, details: str):
        super().__init__(details)
        self.code = code
        self.details = details


class RouterPredictionService:
    """PredictionService servicer over Router.forward. Predict and
    PredictStream proxy; GetModelMetadata forwards to a healthy backend;
    the tf.Example RPCs answer UNIMPLEMENTED (the fleet tier fronts the
    tensor path — the reference deployment's shape)."""

    def __init__(self, router: Router):
        self.router = router

    async def _abort(self, context, e) -> None:
        self.router.errors += 1
        code = getattr(e, "code", None)
        if not isinstance(code, grpc.StatusCode):
            code = grpc.StatusCode.UNAVAILABLE
        await context.abort(code, getattr(e, "details", str(e)))

    async def Predict(self, request, context):
        try:
            scores = await self.router.forward(request, context)
            return self.router.encode_response(request, scores)
        except (ServiceRefusal, PredictClientError) as e:
            await self._abort(context, e)
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001 — surface as INTERNAL
            log.exception("router Predict failed")
            self.router.errors += 1
            await context.abort(
                grpc.StatusCode.INTERNAL, f"router internal error: {e}"
            )

    async def PredictStream(self, request, context):
        """One FINAL chunk carrying the whole merged result (documented
        simplification: the router already fanned the rows out by
        affinity; a second chunking layer would re-split the merge it
        just paid for). Wire-compatible with the incremental client —
        offset 0, count == total, final=True."""
        try:
            scores = await self.router.forward(request, context)
        except (ServiceRefusal, PredictClientError) as e:
            await self._abort(context, e)
            return
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001
            log.exception("router PredictStream failed")
            self.router.errors += 1
            await context.abort(
                grpc.StatusCode.INTERNAL, f"router internal error: {e}"
            )
            return
        chunk = apis.PredictStreamChunk()
        chunk.model_spec.name = self.router.client.model_name
        chunk.model_spec.signature_name = (
            request.model_spec.signature_name or "serving_default"
        )
        n = int(scores.shape[0]) if scores.ndim else 1
        chunk.offset = 0
        chunk.count = n
        chunk.total = n
        chunk.final = True
        mirror = any(
            request.inputs[name].tensor_content for name in request.inputs
        )
        codec.from_ndarray(
            scores, use_tensor_content=mirror,
            out=chunk.outputs[self.router.client.output_key],
        )
        yield chunk

    async def GetModelMetadata(self, request, context):
        """Proxied to one healthy backend (metadata is fleet-uniform: the
        replicas serve the same model dirs)."""
        client = self.router.client
        sb = client.scoreboard
        idx = (sb.pick(0) if sb is not None else 0) or 0
        stub = client._stubs[idx][0]
        try:
            return await stub.GetModelMetadata(
                request, timeout=client.timeout_s
            )
        except grpc.aio.AioRpcError as e:
            self.router.errors += 1
            await context.abort(e.code(), e.details() or "backend error")

    async def Classify(self, request, context):
        await context.abort(
            grpc.StatusCode.UNIMPLEMENTED,
            "the fleet router proxies the tensor Predict path only",
        )

    async def Regress(self, request, context):
        await context.abort(
            grpc.StatusCode.UNIMPLEMENTED,
            "the fleet router proxies the tensor Predict path only",
        )

    async def MultiInference(self, request, context):
        await context.abort(
            grpc.StatusCode.UNIMPLEMENTED,
            "the fleet router proxies the tensor Predict path only",
        )


class RouterHealthService:
    """grpc.health.v1 for the router itself: SERVING while at least one
    backend is believed healthy (the router without backends is down in
    every way that matters to an edge client)."""

    watch_poll_s = 0.2

    def __init__(self, router: Router):
        self.router = router

    def _status(self, service: str) -> int | None:
        if service and service != self.router.client.model_name:
            return None
        return (
            health_proto.SERVING
            if self.router.healthy_backends() > 0
            else health_proto.NOT_SERVING
        )

    async def Check(self, request, context):
        st = self._status(request.service)
        if st is None:
            await context.abort(
                grpc.StatusCode.NOT_FOUND,
                f"unknown service {request.service!r}",
            )
        return health_proto.HealthCheckResponse(status=st)

    async def Watch(self, request, context):
        last = None
        while True:
            st = self._status(request.service)
            if st is None:
                st = health_proto.SERVICE_UNKNOWN
            if st != last:
                last = st
                yield health_proto.HealthCheckResponse(status=st)
            await asyncio.sleep(self.watch_poll_s)


async def run_router(
    cfgs: dict,
    *,
    host: str | None = None,
    port: int | None = None,
    uds_path: str | None = None,
    ready_cb=None,
) -> None:
    """Build and serve a router until cancelled/SIGTERM. `ready_cb(port,
    router)` fires after bind (tests + the soak's readiness line)."""
    obs = cfgs.get("observability")
    if obs is not None:
        # Same process-level arming the replica server does: enables the
        # router's own span plane when [observability] tracing=true.
        obs.apply()
    router = Router(cfgs)
    server = grpc.aio.server(
        options=list(LARGE_MESSAGE_CHANNEL_OPTIONS)
        + list(KEEPALIVE_SERVER_OPTIONS),
    )
    add_PredictionServiceServicer_to_server(
        RouterPredictionService(router), server
    )
    health_proto.add_HealthServicer_to_server(
        RouterHealthService(router), server
    )
    srv_cfg = cfgs["server"]
    bind_host = host if host is not None else srv_cfg.host
    bind_port = port if port is not None else srv_cfg.port
    bound = server.add_insecure_port(f"{bind_host}:{bind_port}")
    if bound == 0:
        raise RuntimeError(f"could not bind {bind_host}:{bind_port}")
    transport = cfgs.get("transport")
    eff_uds = uds_path if uds_path is not None else (
        getattr(transport, "uds_path", "") or ""
    )
    if eff_uds:
        import os

        try:
            if os.path.exists(eff_uds):
                os.unlink(eff_uds)
        except OSError:
            pass
        if server.add_insecure_port(f"unix:{eff_uds}") == 0:
            raise RuntimeError(f"could not bind unix:{eff_uds}")
    await server.start()
    if router.gossip is not None:
        router.gossip.start()
    if router.plane is not None:
        router.plane.start()
    await router.watch_backends()
    log.info(
        "fleet router up on %s:%d -> %d backends%s", bind_host, bound,
        len(router.client.hosts),
        f" (gossip {router.gossip.listen_addr})" if router.gossip else "",
    )
    if ready_cb is not None:
        ready_cb(bound, router)
    stop_evt = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            loop.add_signal_handler(sig, stop_evt.set)
        except (NotImplementedError, RuntimeError):
            pass  # non-main thread / platform without signal support
    try:
        await stop_evt.wait()
    finally:
        router.stop_watchers()
        if router.plane is not None:
            router.plane.stop()
        if router.gossip is not None:
            router.gossip.stop()
        await server.stop(grace=2.0)
        try:
            await router.client.close()
        except Exception:  # noqa: BLE001 — channels may already be gone
            pass


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(
        description="TPU-serving fleet router: PredictionService front "
        "for a replica fleet, steered by scoreboard + health gossip"
    )
    parser.add_argument("--config", required=True,
                        help="TOML with [server] (bind), [client] "
                        "(backends + steering), [fleet] (gossip/rollout)")
    parser.add_argument("--host", default=None)
    parser.add_argument("--port", type=int, default=None)
    parser.add_argument("--uds-path", default=None)
    parser.add_argument("--ready-fd", type=int, default=None,
                        help="fd to write one readiness JSON line to "
                        "after bind (harness plumbing)")
    args = parser.parse_args(argv)
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )
    cfgs = load_config(args.config)

    def _ready(port: int, router: Router) -> None:
        if args.ready_fd is None:
            return
        import os

        line = json.dumps({
            "port": port,
            "gossip": router.gossip.listen_addr if router.gossip else None,
        })
        os.write(args.ready_fd, (line + "\n").encode("utf-8"))
        os.close(args.ready_fd)

    asyncio.run(run_router(
        cfgs, host=args.host, port=args.port, uds_path=args.uds_path,
        ready_cb=_ready,
    ))


if __name__ == "__main__":
    main()
