"""Replica-side fleet plane: the gossip agent a serving replica embeds
plus the rollout follower that applies the router's coordinated state.

The serving build (serving/server.py) constructs this with a `record_fn`
closure over the live stack — serving/draining/quarantined/starting from
the recovery plane + GracefulShutdown, pressure from the overload plane,
loaded versions from the registry, canary state from the lifecycle
controller — so this module stays jax-free and testable with fakes.
"""

from __future__ import annotations

import time

from .gossip import GossipAgent
from .rollout import RolloutFollower


class ReplicaFleetPlane:
    """One replica's fleet membership. `record_fn()` returns the
    HealthRecord field dict for this replica's current state; rollout
    state arriving in ANY peer's record (the router's, usually) applies
    to `lifecycle` through a RolloutFollower exactly once per seq."""

    def __init__(
        self, cfg, *, record_fn, lifecycle=None, clock=time.time,
        extra_routes=None, query_routes=None, post_routes=None,
    ):
        self.config = cfg
        self_id = cfg.self_id or cfg.advertise_addr
        self.follower = (
            RolloutFollower(lifecycle, self_id) if lifecycle is not None
            else None
        )
        self.agent = GossipAgent(
            self_id or "replica",
            role="replica",
            host=cfg.gossip_host,
            port=cfg.gossip_port,
            uds_path=cfg.gossip_uds,
            peers=cfg.peers,
            interval_s=cfg.gossip_interval_s,
            ttl_s=cfg.record_ttl_s,
            record_fn=record_fn,
            on_update=self._on_update,
            # ISSUE 18: the serving build mounts its /monitoring wire +
            # /tracez/export surfaces on the gossip port so the router's
            # aggregator scrapes members without touching the REST tier.
            extra_routes=extra_routes,
            query_routes=query_routes,
            post_routes=post_routes,
            clock=clock,
        )

    def _on_update(self, rec) -> None:
        if self.follower is not None and rec.rollout:
            self.follower.apply(rec.rollout)

    def start(self) -> "ReplicaFleetPlane":
        self.agent.start()
        return self

    def stop(self) -> None:
        self.agent.stop()

    def announce(self) -> None:
        """One immediate push-pull round with every peer — called when
        state just changed in a way the fleet should hear NOW (drain
        start), instead of waiting out the interval."""
        for peer in self.agent.peers:
            self.agent.exchange_once(peer)

    # ----------------------------------------------------------- surfaces

    def snapshot(self) -> dict:
        """The replica's /fleetz body."""
        out = {"role": "replica", **self.agent.snapshot()}
        if self.follower is not None:
            out["rollout_follower"] = self.follower.snapshot()
        return out

    def fleet_stats(self) -> dict:
        """The shape utils.metrics._fleet_prometheus_lines consumes."""
        stats: dict = {"role": "replica", "gossip": self.agent.snapshot()}
        if self.follower is not None:
            stats["follower"] = self.follower.snapshot()
        return stats
