"""Fleet-coordinated rollout (ISSUE 17 tentpole, part 3).

The PR-8 lifecycle plane ramps a canary PER REPLICA: each
LifecycleController walks its own fraction schedule and judges its own
quality window. Fine for one process; across a fleet it means replicas
disagree about the ramp (skewed start times) and — worse — a version one
replica's judge already rolled back keeps serving everywhere else until
each judge independently re-learns the lesson.

This module lifts that to shared rollout state with ONE writer:

- `RolloutCoordinator` (runs inside the router, `rollout_writer=true`):
  each tick it reads the gossip view, elects the RAMP LEADER — the
  lexicographically smallest replica currently reporting a canary
  (sticky while that replica keeps reporting it) — and copies the
  leader's (canary_version, fraction) into the shared state. Any replica
  reporting `rolled_back=v` gets v appended to the fleet blacklist and
  the ramp cleared IN THE SAME TICK. State carries a monotonic `seq`
  (bumped on every change) and is persisted by atomic rename so a
  restarted router resumes the rollout instead of re-running it.

- `RolloutFollower` (runs inside every replica): applies coordinator
  state as it arrives via gossip. Followers mirror the leader's fraction
  through `LifecycleController.set_fleet_fraction`; the leader itself
  keeps its LOCAL schedule (it is the clock the fleet mirrors — if it
  also followed, the ramp would freeze at its first adopted value).
  Blacklist entries apply through `fleet_blacklist`: the live canary
  rolls back, loaded versions retire, unseen versions pre-blacklist.

Distribution is free: the state dict rides the router's gossip record
(`rollout` field), so one gossip interval bounds fleet-wide propagation
— the acceptance criterion's "blacklisted on ALL replicas within one
gossip interval".
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import time

log = logging.getLogger("dts_tpu.fleet.rollout")


@dataclasses.dataclass
class RolloutState:
    """The fleet-global rollout picture. seq is bumped on every change;
    followers apply a state only when its seq advances past the last one
    they applied."""

    seq: int = 0
    canary_version: int | None = None
    fraction: float = 0.0
    leader: str = ""
    blacklist: tuple[int, ...] = ()
    wall_ts: float = 0.0

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["blacklist"] = list(self.blacklist)
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "RolloutState":
        known = {f.name for f in dataclasses.fields(cls)}
        kwargs = {k: v for k, v in (d or {}).items() if k in known}
        kwargs["blacklist"] = tuple(
            int(v) for v in kwargs.get("blacklist", ())
        )
        return cls(**kwargs)


class RolloutCoordinator:
    """The single writer. `tick(view)` folds the gossip view into the
    shared state; the caller (router) publishes `state().to_dict()` in
    its own gossip record."""

    def __init__(self, state_file: str = "", *, clock=time.time):
        self._clock = clock
        self._state_file = state_file
        self._state = RolloutState()
        # Counters (monotonic; /fleetz + dts_tpu_fleet_*).
        self.adoptions = 0
        self.blacklists = 0
        self.clears = 0
        if state_file and os.path.exists(state_file):
            try:
                with open(state_file, "r", encoding="utf-8") as f:
                    self._state = RolloutState.from_dict(json.load(f))
                log.info("rollout state resumed from %s (seq=%d)",
                         state_file, self._state.seq)
            except (OSError, ValueError):
                log.exception("rollout state file unreadable; starting "
                              "fresh (the gossip view re-derives it)")

    def state(self) -> RolloutState:
        return self._state

    def tick(self, view: dict) -> RolloutState:
        """One coordination pass over the gossip view (id ->
        HealthRecord). Blacklist first — a rollback anywhere beats a ramp
        anywhere — then leader election and fraction adoption."""
        st = self._state
        replicas = {
            mid: rec for mid, rec in view.items()
            if getattr(rec, "role", "replica") == "replica"
        }
        changed = False
        blacklist = list(st.blacklist)
        canary, fraction, leader = st.canary_version, st.fraction, st.leader
        for mid in sorted(replicas):
            rb = replicas[mid].rolled_back
            if rb is not None and int(rb) not in blacklist:
                # One replica's judgment is the FLEET's judgment: the
                # version is dead everywhere in this same tick.
                blacklist.append(int(rb))
                self.blacklists += 1
                changed = True
                log.info("fleet blacklist: v%s (rolled back on %s)", rb, mid)
        if canary is not None and canary in blacklist:
            canary, fraction, leader = None, 0.0, ""
            self.clears += 1
            changed = True
        # Leader: sticky while it still reports a (non-blacklisted)
        # canary; else the smallest replica id reporting one.
        def _reports_canary(mid: str) -> bool:
            rec = replicas.get(mid)
            return (
                rec is not None
                and rec.canary is not None
                and int(rec.canary) not in blacklist
            )

        if not (leader and _reports_canary(leader)):
            leader_new = next(
                (mid for mid in sorted(replicas) if _reports_canary(mid)), ""
            )
            if leader_new != leader:
                leader = leader_new
                changed = True
        if leader:
            rec = replicas[leader]
            new_canary = int(rec.canary)
            new_fraction = float(rec.canary_fraction or 0.0)
            if new_canary != canary or new_fraction != fraction:
                canary, fraction = new_canary, new_fraction
                self.adoptions += 1
                changed = True
        elif canary is not None:
            # No replica reports the canary anymore (promoted or
            # vanished): clear the fleet ramp.
            canary, fraction = None, 0.0
            self.clears += 1
            changed = True
        if changed:
            self._state = RolloutState(
                seq=st.seq + 1,
                canary_version=canary,
                fraction=fraction,
                leader=leader,
                blacklist=tuple(blacklist),
                wall_ts=round(self._clock(), 3),
            )
            self._persist()
        return self._state

    def _persist(self) -> None:
        if not self._state_file:
            return
        tmp = f"{self._state_file}.tmp"
        try:
            d = os.path.dirname(self._state_file)
            if d:
                os.makedirs(d, exist_ok=True)
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(self._state.to_dict(), f)
            os.replace(tmp, self._state_file)  # atomic: readers never see
        except OSError:  # a torn write
            log.exception("rollout state persist failed (state is still "
                          "live in memory and gossip)")

    def snapshot(self) -> dict:
        return {
            "state": self._state.to_dict(),
            "counters": {
                "adoptions": self.adoptions,
                "blacklists": self.blacklists,
                "clears": self.clears,
            },
        }


class RolloutFollower:
    """Every replica's applier. Feed it rollout-state dicts as gossip
    delivers them (`GossipAgent.on_update` → record.rollout); it applies
    each NEW seq to the local LifecycleController exactly once."""

    def __init__(self, lifecycle, self_id: str):
        self.lifecycle = lifecycle
        self.self_id = self_id
        self.applied_seq = -1
        self._applied_blacklist: set[int] = set()
        # Monotonic counters + last actions (the /fleetz rollout block).
        self.applies = 0
        self.blacklists_applied = 0
        self.last_actions: dict = {}

    def apply(self, rollout) -> dict | None:
        """Apply one rollout-state payload; returns the actions taken or
        None when the payload is stale/absent."""
        if rollout is None:
            return None
        st = (
            rollout if isinstance(rollout, RolloutState)
            else RolloutState.from_dict(rollout)
        )
        if st.seq <= self.applied_seq:
            return None
        self.applied_seq = st.seq
        self.applies += 1
        actions: dict = {"seq": st.seq}
        lc = self.lifecycle
        for v in st.blacklist:
            if v in self._applied_blacklist:
                continue
            self._applied_blacklist.add(v)
            self.blacklists_applied += 1
            actions.setdefault("blacklist", {})[str(v)] = lc.fleet_blacklist(v)
        if st.leader == self.self_id or st.canary_version is None:
            # The leader keeps its LOCAL ramp schedule (it IS the fleet
            # clock); with no fleet canary everyone does.
            lc.set_fleet_fraction(None)
            actions["fraction"] = None
        else:
            lc.set_fleet_fraction(st.fraction)
            actions["fraction"] = st.fraction
        self.last_actions = actions
        return actions

    def snapshot(self) -> dict:
        return {
            "applied_seq": self.applied_seq,
            "applies": self.applies,
            "blacklists_applied": self.blacklists_applied,
            "last_actions": self.last_actions,
        }
