"""Fleet-wide observability plane (ISSUE 18 tentpole).

Three router-side pieces, all jax-free and stdlib-only:

- **TraceCollector**: ingests `Span.to_dict` trees exported by every
  process in a request's path — the router's own recorder, each
  replica's `/tracez/export?since=` pull surface, and edge clients
  pushing to `/tracez/ingest` — and STITCHES the local trees sharing a
  trace_id into one cross-process tree. Every exported payload carries a
  clock anchor (tracing.clock_anchor), so spans land on a shared
  wall-clock timeline first; the residual per-hop skew is then solved
  NTP-style from the RPC send/recv pair (the parent `client.rpc` span in
  one process and the remote-parented server root in the next bracket
  the same wire exchange), and the child tree is shifted so it nests
  inside its parent. `/tracez` on the router serves the stitched trees
  and a multi-pid Chrome export Perfetto loads with one process track
  per fleet member.

- **Hop waterfall**: each stitched tree is decomposed into the fleet
  hops — client_send, router_queue, replica_queue_wait, device,
  readback_wait, merge — with the unattributed remainder reported as
  `other`, never hidden (the PR 6 waterfall invariant at fleet scope);
  a windowed ring aggregates the per-trace decompositions.

- **FleetObservabilityPlane + SloMonitor**: a periodic tick scrapes each
  member's `/monitoring` wire (utils.metrics fleet_wire) off the gossip
  port and merges the windowed histograms into one fleet aggregate
  (`GET /fleet/monitoring`, dts_tpu_fleet_agg_*); members that fail the
  scrape degrade to the cheap summary piggybacked on their gossip
  records instead of vanishing. The same tick feeds monotonic
  (restart-clamped) request/error/over-latency-target counters into the
  SLO monitor, which computes multi-window error-budget burn rates for
  the configured latency and availability objectives (`GET /sloz`,
  dts_tpu_slo_*). While both burn windows exceed the fast threshold the
  router annotates in-flight `router.route` spans with `slo.burn`, so
  the tail sampler force-keeps exactly the traces that explain the
  breach.
"""

from __future__ import annotations

import json
import logging
import threading
import time
from collections import OrderedDict, deque

from ..utils.metrics import _EDGES_US, WindowedLatency
from ..utils import tracing
from .gossip import _open_connection

log = logging.getLogger("dts_tpu.fleet.observability")

# Hop components in pipeline order. Extraction is by span/phase NAME —
# the names are the tracing plane's stable vocabulary (client/client.py,
# serving/batcher.py); a hop whose spans are absent contributes 0 and its
# time lands in `other`.
WATERFALL_COMPONENTS = (
    "client_send", "router_queue", "replica_queue_wait",
    "device", "readback_wait", "merge",
)


def _http_get_json(addr: str, path: str, timeout: float):
    """GET a JSON body from a gossip-style endpoint ("host:port" or
    "unix:/path")."""
    conn = _open_connection(addr, timeout)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        data = resp.read()
        if resp.status != 200:
            raise OSError(f"{addr}{path} answered {resp.status}")
    finally:
        conn.close()
    return json.loads(data)


def _walk(node: dict):
    yield node
    for c in node.get("children") or ():
        yield from _walk(c)


def _copy_tree(node: dict) -> dict:
    out = dict(node)
    out["attrs"] = dict(node.get("attrs") or {})
    out["children"] = [_copy_tree(c) for c in node.get("children") or ()]
    return out


def _shift_tree(node: dict, delta_us: int) -> None:
    for n in _walk(node):
        n["start_us"] = int(n["start_us"]) + delta_us
        if n.get("annotations"):
            n["annotations"] = [
                {**a, "t": int(a.get("t", 0)) + delta_us}
                for a in n["annotations"]
            ]


def _find_all(node: dict, names: tuple) -> list[dict]:
    return [n for n in _walk(node) if n.get("name") in names]


def _earliest(nodes: list[dict]) -> dict | None:
    return min(nodes, key=lambda n: n["start_us"]) if nodes else None


def hop_waterfall(top: dict) -> dict | None:
    """Decompose one stitched tree into the fleet hop components.

    The components partition the ROOT's duration by construction:
    sum(components) + other == total exactly (`other` may dip slightly
    negative when hops overlap — reported, never clamped away silently;
    individual components clamp at 0 so a skew-misordered pair cannot
    produce a negative hop)."""
    total = int(top.get("duration_us") or 0)
    if total <= 0:
        return None
    t0 = int(top["start_us"])

    routers = _find_all(top, ("router.route",))
    router = _earliest([r for r in routers if r is not top]) or (
        top if top.get("name") == "router.route" else None
    )
    scope = router or top

    # The RPC hop that carried the request to a replica: prefer an
    # attempt with a stitched server-side tree under it.
    rpcs = _find_all(scope, ("client.rpc",))
    server = None
    rpc = None
    for cand in sorted(rpcs, key=lambda n: n["start_us"]):
        srv = _earliest([
            c for c in cand.get("children") or ()
            if str(c.get("name", "")).startswith("server.")
        ])
        if srv is not None:
            rpc, server = cand, srv
            break
    if rpc is None:
        rpc = _earliest(rpcs)

    comps = dict.fromkeys(WATERFALL_COMPONENTS, 0)
    if router is not None and router is not top:
        comps["client_send"] = int(router["start_us"]) - t0
    if rpc is not None:
        base = router if router is not None else top
        comps["router_queue"] = (
            int(rpc["start_us"]) - int(base["start_us"])
        )
    if server is not None:
        comps["replica_queue_wait"] = sum(
            int(n.get("duration_us") or 0)
            for n in _find_all(server, ("batch.queue_wait",))
        )
        device = _find_all(server, ("batch.dispatch",)) or _find_all(
            server, ("batch.jitcall", "predict.execute")
        )
        comps["device"] = sum(int(n.get("duration_us") or 0) for n in device)
        comps["readback_wait"] = sum(
            int(n.get("duration_us") or 0)
            for n in _find_all(server, ("readback.wait", "batch.readback"))
        )
    own_source = top.get("source")
    merges = [
        n for n in _find_all(top, ("client.merge",))
        if n.get("source") == own_source
    ]
    comps["merge"] = sum(int(n.get("duration_us") or 0) for n in merges)

    comps = {k: max(0, int(v)) for k, v in comps.items()}
    other = total - sum(comps.values())
    return {
        "total_us": total,
        "components_us": comps,
        "other_us": int(other),
    }


class TraceCollector:
    """Bounded store of exported span trees keyed by trace_id, with
    cross-process stitching, the windowed hop waterfall, and the
    multi-pid Chrome export. Thread-safe: gossip handler threads push,
    the plane tick pulls, and operator requests read concurrently."""

    def __init__(
        self,
        *,
        max_traces: int = 512,
        waterfall_window_s: float = 120.0,
        clock=time.time,
    ):
        self.max_traces = max(1, int(max_traces))
        self.waterfall_window_s = float(waterfall_window_s)
        self._clock = clock
        self._lock = threading.Lock()
        # trace_id -> {"roots": {span_id: node}, "t": last ingest wall}
        self._traces: "OrderedDict[str, dict]" = OrderedDict()
        # source -> {"pid": anchor pid, "t": last ingest}
        self._sources: dict[str, dict] = {}
        # trace_id -> (wall t, waterfall dict) — latest decomposition per
        # stitched trace; the windowed aggregate reads values in-window.
        self._waterfalls: "OrderedDict[str, tuple[float, dict]]" = OrderedDict()
        self.ingested_spans = 0
        self.ingested_payloads = 0
        self.stitch_attached = 0

    # ------------------------------------------------------------- ingest

    def ingest(self, source: str, payload: dict) -> int:
        """Fold one export payload (tracing.TraceRecorder.export_since
        shape) into the store. Every node is shifted onto the wall clock
        via the payload's anchor and tagged with its source."""
        clock = payload.get("clock") or {}
        try:
            wall_off = int(clock["unix_us"]) - int(clock["perf_us"])
        except (KeyError, TypeError, ValueError):
            return 0  # no anchor -> cannot place on the shared timeline
        pid = clock.get("pid")
        now = self._clock()
        accepted = 0
        with self._lock:
            self._sources[source] = {"pid": pid, "t": now}
            for tree in payload.get("spans") or ():
                if not isinstance(tree, dict) or "span_id" not in tree:
                    continue
                root = _copy_tree(tree)
                _shift_tree(root, wall_off)
                for n in _walk(root):
                    n["source"] = source
                trace_id = str(root.get("trace_id") or "")
                if not trace_id:
                    continue
                entry = self._traces.get(trace_id)
                if entry is None:
                    entry = {"roots": OrderedDict(), "t": now}
                    self._traces[trace_id] = entry
                    while len(self._traces) > self.max_traces:
                        dropped_id, _ = self._traces.popitem(last=False)
                        self._waterfalls.pop(dropped_id, None)
                entry["roots"][root["span_id"]] = root
                entry["t"] = now
                self._traces.move_to_end(trace_id)
                accepted += 1
                self.ingested_spans += 1
            self.ingested_payloads += 1
        return accepted

    # -------------------------------------------------------- stitching

    @staticmethod
    def _stitch(roots: list[dict]) -> tuple[list[dict], int]:
        """Stitch one trace's local roots (fresh copies) into as few
        trees as possible. Returns (top-level trees, hops attached).

        Shifts are resolved top-down BEFORE attachment: a child root's
        total shift is its parent root's total shift minus the locally
        measured skew, so chains (edge client -> router -> replica) never
        double-shift."""
        roots = [_copy_tree(r) for r in roots]
        nodes: dict[str, dict] = {}
        owner: dict[str, dict] = {}
        for r in roots:
            for n in _walk(r):
                nodes[n["span_id"]] = n
                owner[n["span_id"]] = r
        edges: dict[int, tuple[dict, dict, float]] = {}  # id(child root)
        children_of: dict[int, list[dict]] = {}
        tops: list[dict] = []
        for r in roots:
            parent = nodes.get(r.get("parent_id") or "")
            if parent is None or owner[parent["span_id"]] is r:
                tops.append(r)
                continue
            skew = 0.0
            if r.get("source") != parent.get("source"):
                c0 = int(r["start_us"])
                c1 = c0 + int(r.get("duration_us") or 0)
                p0 = int(parent["start_us"])
                p1 = p0 + int(parent.get("duration_us") or 0)
                # NTP pair: the parent span brackets the child span's
                # wire exchange; half the sum of the edge offsets is the
                # child clock's residual lead over the parent clock.
                skew = ((c0 - p0) + (c1 - p1)) / 2.0
            edges[id(r)] = (parent, owner[parent["span_id"]], skew)
            children_of.setdefault(id(owner[parent["span_id"]]), []).append(r)
        # Total shift per root, walked from the tops down.
        shift: dict[int, int] = {}
        stack = [(t, 0) for t in tops]
        while stack:
            root, total = stack.pop()
            if id(root) in shift:
                continue  # cycle guard (corrupt parent links)
            shift[id(root)] = total
            for child in children_of.get(id(root), ()):
                _parent, _powner, skew = edges[id(child)]
                stack.append((child, total - int(round(skew))))
        attached = 0
        for r in roots:
            if id(r) not in shift:  # unreachable from any top: keep as top
                shift[id(r)] = 0
                tops.append(r)
        for r in roots:
            delta = shift[id(r)]
            if delta:
                _shift_tree(r, delta)
            edge = edges.get(id(r))
            if edge is not None:
                parent, _powner, skew = edge
                r["stitched"] = True
                if skew:
                    r["clock_skew_us"] = int(round(skew))
                parent.setdefault("children", []).append(r)
                attached += 1
        tops.sort(key=lambda n: n["start_us"])
        return tops, attached

    def stitched(self, limit: int = 50) -> list[dict]:
        """The newest `limit` traces, stitched. Also refreshes the
        windowed waterfall ring for every multi-process trace seen."""
        with self._lock:
            items = list(self._traces.items())[-max(1, int(limit)):]
        now = self._clock()
        out = []
        for trace_id, entry in reversed(items):
            tops, attached = self._stitch(list(entry["roots"].values()))
            sources = sorted({
                n.get("source") or "?" for t in tops for n in _walk(t)
            })
            wf = hop_waterfall(tops[0]) if len(tops) == 1 else None
            tr = {
                "trace_id": trace_id,
                "processes": sources,
                "num_processes": len(sources),
                "stitched_hops": attached,
                "duration_us": (
                    int(tops[0].get("duration_us") or 0)
                    if len(tops) == 1 else int(
                        max(
                            int(t["start_us"]) + int(t.get("duration_us") or 0)
                            for t in tops
                        ) - min(int(t["start_us"]) for t in tops)
                    )
                ),
                "waterfall": wf,
                "spans": tops,
            }
            out.append(tr)
            if wf is not None and len(sources) >= 2:
                with self._lock:
                    self._waterfalls[trace_id] = (entry["t"], wf)
                    self._waterfalls.move_to_end(trace_id)
                    while len(self._waterfalls) > self.max_traces:
                        self._waterfalls.popitem(last=False)
        if attached_total := sum(t["stitched_hops"] for t in out):
            self.stitch_attached = max(self.stitch_attached, attached_total)
        _ = now
        return out

    # -------------------------------------------------------- waterfall

    def waterfall_window(self) -> dict:
        """Windowed mean of the per-trace hop decompositions."""
        now = self._clock()
        with self._lock:
            recent = [
                wf for (t, wf) in self._waterfalls.values()
                if now - t <= self.waterfall_window_s
            ]
        n = len(recent)
        means = dict.fromkeys(WATERFALL_COMPONENTS, 0.0)
        other = total = 0.0
        for wf in recent:
            for k in WATERFALL_COMPONENTS:
                means[k] += wf["components_us"].get(k, 0)
            other += wf["other_us"]
            total += wf["total_us"]
        if n:
            means = {k: round(v / n, 1) for k, v in means.items()}
            other, total = round(other / n, 1), round(total / n, 1)
        return {
            "window_s": self.waterfall_window_s,
            "traces": n,
            "mean_components_us": means,
            "mean_other_us": other,
            "mean_total_us": total,
        }

    # --------------------------------------------------------- surfaces

    def counters(self) -> dict:
        with self._lock:
            multi = sum(
                1 for e in self._traces.values()
                if len({
                    n.get("source") for r in e["roots"].values()
                    for n in _walk(r)
                }) >= 2
            )
            return {
                "traces_retained": len(self._traces),
                "multi_process_traces": multi,
                "ingested_spans": self.ingested_spans,
                "ingested_payloads": self.ingested_payloads,
                "sources": {
                    s: dict(meta) for s, meta in self._sources.items()
                },
            }

    def tracez(self, limit: int = 50) -> dict:
        """The router's /tracez body: stitched cross-process trees plus
        collector counters and the windowed waterfall."""
        traces = self.stitched(limit)
        return {
            "enabled": True,
            "role": "collector",
            **self.counters(),
            "waterfall": self.waterfall_window(),
            "traces": traces,
        }

    def chrome_trace(self, limit: int = 100) -> dict:
        """Multi-pid Chrome trace-event export of the STITCHED traces
        (single-process traces are omitted — the member's own /tracez
        already serves those): one pid per fleet process (the exporter's
        real OS pid when known), one tid per trace, hop-waterfall
        components as `wf_*_us` args on each root event."""
        stitched = [
            t for t in self.stitched(limit) if t["num_processes"] >= 2
        ]
        pid_map: dict[str, int] = {}
        with self._lock:
            known = {s: m.get("pid") for s, m in self._sources.items()}
        used: set[int] = set()
        for tr in stitched:
            for src in tr["processes"]:
                if src in pid_map:
                    continue
                pid = known.get(src)
                if not isinstance(pid, int) or pid in used:
                    pid = 100000 + len(pid_map)
                    while pid in used:
                        pid += 1
                pid_map[src] = pid
                used.add(pid)
        events: list[dict] = []
        for src, pid in pid_map.items():
            events.append({
                "ph": "M", "name": "process_name", "pid": pid, "tid": 0,
                "args": {"name": src},
            })
        starts = [
            int(n["start_us"])
            for tr in stitched for top in tr["spans"] for n in _walk(top)
        ]
        t_base = min(starts, default=0)
        span_events: list[dict] = []
        for tid, tr in enumerate(stitched):
            for top in tr["spans"]:
                for sp in _walk(top):
                    args = {
                        "trace_id": sp.get("trace_id"),
                        "span_id": sp.get("span_id"),
                        "parent_id": sp.get("parent_id"),
                        "status": sp.get("status"),
                        "source": sp.get("source"),
                        **(sp.get("attrs") or {}),
                    }
                    if sp.get("stitched"):
                        args["stitched"] = True
                        args["clock_skew_us"] = sp.get("clock_skew_us", 0)
                    if sp is top and tr.get("waterfall"):
                        wf = tr["waterfall"]
                        for k, v in wf["components_us"].items():
                            args[f"wf_{k}_us"] = int(v)
                        args["wf_other_us"] = int(wf["other_us"])
                    span_events.append({
                        "ph": "X",
                        "name": sp.get("name", "span"),
                        "cat": "span" if sp is top else "phase",
                        "pid": pid_map.get(sp.get("source"), 0),
                        "tid": tid,
                        "ts": max(0, int(sp["start_us"]) - t_base),
                        "dur": max(0, int(sp.get("duration_us") or 0)),
                        "args": args,
                    })
        # Non-decreasing ts within every (pid, tid) track — sorted
        # globally, which subsumes the per-track requirement.
        span_events.sort(key=lambda e: e["ts"])
        events.extend(span_events)
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {
                "producer": "distributed_tf_serving_tpu.fleet",
                "stitched_traces": len(stitched),
            },
        }


class SloMonitor:
    """Multi-window error-budget burn rates over the aggregated fleet
    counter stream (the SRE-workbook alerting shape: page when BOTH a
    short and a long window burn faster than the fast threshold).

    Ingests CUMULATIVE fleet counters (the plane clamps per-member deltas
    at >= 0 across member restarts, so these only grow): request/error
    totals for the availability SLI, lifetime-latency totals and
    over-target counts for the latency SLI. Burn rate over a window =
    (bad fraction in the window) / (1 - objective)."""

    def __init__(self, cfg, clock=time.time):
        self.cfg = cfg
        self._clock = clock
        self._lock = threading.Lock()
        # (t, requests, errors, lat_total, lat_over) cumulative samples.
        self._samples: deque[tuple] = deque(maxlen=8192)
        self.breached = False
        self.warn = False
        self.breaches = 0

    def ingest(
        self, *, requests: int, errors: int, lat_total: int, lat_over: int
    ) -> bool:
        """Append one cumulative sample; re-evaluates and returns the
        breach state."""
        now = self._clock()
        with self._lock:
            self._samples.append(
                (now, int(requests), int(errors), int(lat_total),
                 int(lat_over))
            )
        burn = self.burn_rates()
        fast, slow = self.cfg.burn_threshold_fast, self.cfg.burn_threshold_slow
        breached = any(
            w["short"] >= fast and w["long"] >= fast for w in burn.values()
        )
        self.warn = any(
            w["short"] >= slow and w["long"] >= slow for w in burn.values()
        )
        if breached and not self.breached:
            self.breaches += 1
        self.breached = breached
        return breached

    def _window_deltas(self, window_s: float) -> tuple[int, int, int, int]:
        """Clamped deltas between now and the sample nearest the window's
        far edge."""
        now = self._clock()
        with self._lock:
            if not self._samples:
                return 0, 0, 0, 0
            cur = self._samples[-1]
            base = None
            for s in self._samples:
                if s[0] >= now - window_s:
                    base = s
                    break
            if base is None or base is cur:
                # Window older than retention, or a single sample: no
                # measurable delta yet.
                base = self._samples[0]
        return tuple(
            max(0, cur[i] - base[i]) for i in range(1, 5)
        )  # type: ignore[return-value]

    def burn_rates(self) -> dict:
        out = {}
        lat_budget = max(1e-9, 1.0 - self.cfg.latency_objective)
        avail_budget = max(1e-9, 1.0 - self.cfg.availability_objective)
        for name, window_s in (
            ("short", self.cfg.short_window_s),
            ("long", self.cfg.long_window_s),
        ):
            d_req, d_err, d_lat_total, d_lat_over = self._window_deltas(
                window_s
            )
            avail_bad = d_err / d_req if d_req else 0.0
            lat_bad = d_lat_over / d_lat_total if d_lat_total else 0.0
            out.setdefault("availability", {})[name] = round(
                avail_bad / avail_budget, 4
            )
            out.setdefault("latency", {})[name] = round(
                lat_bad / lat_budget, 4
            )
        return out

    def snapshot(self) -> dict:
        burn = self.burn_rates()
        with self._lock:
            last = self._samples[-1] if self._samples else (0, 0, 0, 0, 0)
            n = len(self._samples)
        return {
            "enabled": True,
            "latency_target_ms": self.cfg.latency_target_ms,
            "objectives": {
                "latency": self.cfg.latency_objective,
                "availability": self.cfg.availability_objective,
            },
            "windows": {
                "short_s": self.cfg.short_window_s,
                "long_s": self.cfg.long_window_s,
            },
            "thresholds": {
                "fast": self.cfg.burn_threshold_fast,
                "slow": self.cfg.burn_threshold_slow,
            },
            "burn": burn,
            # Long-window budget view: burn 1.0 over the long window
            # consumes exactly that window's share of the budget.
            "budget_remaining": {
                slo: round(max(0.0, 1.0 - w["long"]), 4)
                for slo, w in burn.items()
            },
            "breached": self.breached,
            "warn": self.warn,
            "breaches": self.breaches,
            "samples": n,
            "totals": {
                "requests": last[1],
                "errors": last[2],
                "lat_total": last[3],
                "lat_over_target": last[4],
            },
        }


def _over_target(lifetime: dict, target_us: float) -> int:
    """Requests in a lifetime wire histogram slower than the target.
    Bucket-resolution approximate (a request counts as good only when
    its bucket's upper edge is under the target — 12.5% edge growth)."""
    total = int(lifetime.get("total") or 0)
    good = 0
    for k, c in (lifetime.get("buckets") or {}).items():
        i = int(k)
        if 0 <= i < len(_EDGES_US) and _EDGES_US[i] <= target_us:
            good += int(c)
    return max(0, total - good)


class FleetObservabilityPlane:
    """The router's aggregation half: one daemon thread ticks every
    `interval_s`, scraping member wires + pulling member trace exports,
    folding the results into the aggregate, the SLO monitor, and the
    trace collector. All member discovery rides the gossip view (the
    piggybacked `obs` digest names each member's scrape address)."""

    def __init__(
        self,
        *,
        members_fn,
        self_source: str = "router",
        local_export=None,
        slo_cfg=None,
        interval_s: float = 1.0,
        dial_timeout_s: float = 1.0,
        clock=time.time,
    ):
        self.members_fn = members_fn
        self.self_source = self_source
        self.local_export = local_export
        self.interval_s = max(0.05, float(interval_s))
        self.dial_timeout_s = float(dial_timeout_s)
        self._clock = clock
        self.collector = TraceCollector(clock=clock)
        self.slo = (
            SloMonitor(slo_cfg, clock=clock)
            if slo_cfg is not None and slo_cfg.enabled else None
        )
        self._lock = threading.Lock()
        self._agg: dict = {}
        self._member_stats: dict = {}
        self._trace_cursors: dict[str, int] = {}
        self._local_cursor = 0
        # Per-member cumulative baselines for the SLO stream (clamped so
        # a member restart never subtracts from the fleet counters).
        self._member_last: dict[str, tuple[int, int, int, int]] = {}
        self._cum = [0, 0, 0, 0]  # requests, errors, lat_total, lat_over
        self.ticks = 0
        self.scrape_failures = 0
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()

    # The router's forward() hot path reads this one attribute per
    # request when deciding whether to annotate — no lock, no call.
    @property
    def slo_breached(self) -> bool:
        return self.slo is not None and self.slo.breached

    # --------------------------------------------------------------- tick

    def tick(self) -> None:
        members = {}
        try:
            members = dict(self.members_fn() or {})
        except Exception:  # noqa: BLE001 — discovery must not kill the loop
            log.exception("fleet obs: members_fn failed")
        tracing_on = tracing.enabled()
        wires: dict[str, dict] = {}
        summaries: dict[str, dict] = {}
        for mid, rec in members.items():
            role = getattr(rec, "role", None) or (rec or {}).get("role")
            if role != "replica":
                continue
            obs = getattr(rec, "obs", None)
            if obs is None and isinstance(rec, dict):
                obs = rec.get("obs")
            obs = obs or {}
            summaries[mid] = obs
            addr = obs.get("addr")
            if addr:
                try:
                    wires[mid] = _http_get_json(
                        addr, "/monitoring", self.dial_timeout_s
                    )
                except Exception:  # noqa: BLE001 — scrape-unreachable is
                    self.scrape_failures += 1  # the designed degradation
                if tracing_on and obs.get("trace_export"):
                    self._pull_traces(mid, addr)
        if tracing_on and self.local_export is not None:
            try:
                payload = self.local_export(self._local_cursor)
                self.collector.ingest(self.self_source, payload)
                self._local_cursor = int(payload.get("cursor") or 0)
            except Exception:  # noqa: BLE001
                log.exception("fleet obs: local trace export failed")
        self._aggregate(wires, summaries)
        if tracing_on:
            # Refresh stitching so the waterfall window fills even when
            # nobody is hitting /tracez.
            self.collector.stitched(limit=25)
        self.ticks += 1

    def _pull_traces(self, mid: str, addr: str) -> None:
        since = self._trace_cursors.get(mid, 0)
        try:
            payload = _http_get_json(
                addr, f"/tracez/export?since={since}", self.dial_timeout_s
            )
        except Exception:  # noqa: BLE001
            return
        if not payload.get("enabled", True):
            return
        self.collector.ingest(mid, payload)
        try:
            self._trace_cursors[mid] = int(payload.get("cursor") or 0)
        except (TypeError, ValueError):
            pass

    def _aggregate(self, wires: dict, summaries: dict) -> None:
        member_stats: dict[str, dict] = {}
        member_qps: dict[str, float] = {}
        win_wires: list[dict] = []
        tick_counts: dict[str, tuple[int, int, int, int]] = {}
        for mid, summary in summaries.items():
            wire = wires.get(mid)
            if wire is not None:
                try:
                    stats = WindowedLatency.wire_stats(wire["window"])
                    requests = int(wire.get("ok", 0)) + int(
                        wire.get("errors", 0)
                    )
                    errors = int(wire.get("errors", 0))
                    lifetime = wire.get("lifetime") or {}
                    lat_total = int(lifetime.get("total") or 0)
                    lat_over = (
                        _over_target(
                            lifetime,
                            self.slo.cfg.latency_target_ms * 1e3,
                        ) if self.slo is not None else 0
                    )
                    member_stats[mid] = {
                        "scraped": True,
                        "requests": requests,
                        "errors": errors,
                        **stats,
                    }
                    member_qps[mid] = stats["qps"]
                    win_wires.append(wire["window"])
                    tick_counts[mid] = (requests, errors, lat_total, lat_over)
                    continue
                except (KeyError, TypeError, ValueError):
                    pass  # malformed wire -> gossip fallback below
            if "qps" in summary:
                requests = int(summary.get("requests") or 0)
                errors = int(summary.get("errors") or 0)
                member_stats[mid] = {
                    "scraped": False,
                    "requests": requests,
                    "errors": errors,
                    "qps": float(summary.get("qps") or 0.0),
                    "p50_ms": summary.get("p50_ms"),
                    "p99_ms": summary.get("p99_ms"),
                }
                member_qps[mid] = float(summary.get("qps") or 0.0)
                # No lifetime histogram on the gossip digest: carry the
                # availability counters, hold the latency stream flat.
                tick_counts[mid] = (requests, errors, 0, 0)
        # Fleet cumulative counters with per-member restart clamping.
        for mid, counts in tick_counts.items():
            last = self._member_last.get(mid)
            if last is not None:
                for i in range(4):
                    self._cum[i] += max(0, counts[i] - last[i])
            else:
                for i in range(4):
                    self._cum[i] += counts[i]
            self._member_last[mid] = counts
        for gone in set(self._member_last) - set(tick_counts):
            # TTL-expired member: drop the baseline so a rejoin re-counts
            # from its fresh totals instead of clamping against history.
            del self._member_last[gone]
        merged = WindowedLatency.merge_dicts(win_wires)
        merged_stats = WindowedLatency.wire_stats(merged)
        degraded = [
            m for m, st in member_stats.items() if not st["scraped"]
        ]
        agg = {
            "qps": round(sum(member_qps.values()), 3),
            "p50_ms": merged_stats["p50_ms"],
            "p99_ms": merged_stats["p99_ms"],
            "requests": sum(st["requests"] for st in member_stats.values()),
            "errors": sum(st["errors"] for st in member_stats.values()),
            "members": len(member_stats),
            "members_degraded": len(degraded),
            "member_qps": member_qps,
        }
        with self._lock:
            self._agg = agg
            self._member_stats = member_stats
        if self.slo is not None:
            self.slo.ingest(
                requests=self._cum[0], errors=self._cum[1],
                lat_total=self._cum[2], lat_over=self._cum[3],
            )

    def _loop(self, stop: threading.Event) -> None:
        while not stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — the plane must outlive a
                log.exception("fleet obs tick failed")  # bad tick

    def start(self) -> "FleetObservabilityPlane":
        if self._thread is None or not self._thread.is_alive():
            self._stop = threading.Event()
            self._thread = threading.Thread(
                target=self._loop, args=(self._stop,),
                name="fleet-obs", daemon=True,
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None

    # ----------------------------------------------------------- surfaces

    def ingest_push(self, payload: dict) -> dict:
        """POST /tracez/ingest body: an export_since payload plus a
        `source` name — edge clients push their span trees here so
        stitched traces include the first hop."""
        source = str((payload or {}).get("source") or "client")
        accepted = self.collector.ingest(source, payload or {})
        return {"accepted": accepted}

    def aggregate_snapshot(self) -> dict:
        """The GET /fleet/monitoring body."""
        with self._lock:
            agg = dict(self._agg)
            member_stats = {
                m: dict(st) for m, st in self._member_stats.items()
            }
        out = {
            "interval_s": self.interval_s,
            "ticks": self.ticks,
            "scrape_failures": self.scrape_failures,
            "aggregate": agg,
            "members": member_stats,
            "waterfall": self.collector.waterfall_window(),
            "traces": self.collector.counters(),
        }
        if self.slo is not None:
            out["slo"] = self.slo.snapshot()
        return out

    def slo_snapshot(self) -> dict:
        """The GET /sloz body."""
        if self.slo is None:
            return {"enabled": False}
        return self.slo.snapshot()

    def agg_block(self) -> dict:
        """The `agg` block fleet_stats() feeds dts_tpu_fleet_agg_*."""
        with self._lock:
            return dict(self._agg)

    def slo_block(self) -> dict | None:
        """The `slo` block fleet_stats() feeds dts_tpu_slo_*."""
        return None if self.slo is None else self.slo.snapshot()
