"""Request-path tracing: per-request span trees + aggregate phase timers.

The reference's only tracing is System.nanoTime() around whole requests
(DCNClient.java:141,198-199; SURVEY.md §5). PhaseTrace (below) improved
that in AGGREGATE — mean wall time per named phase across all requests —
but an aggregate cannot explain ONE slow request: which shard hedged, how
long it sat in the batcher queue, whether the D2H wait or a failover retry
ate the budget. This module adds the per-request plane:

- **Span / start_span / start_root**: an explicit span-tree recorder.
  The client opens a root span per logical Predict and injects a W3C
  ``traceparent`` into gRPC metadata; the servers extract it, so the
  server-side span tree shares the client's trace id and parents onto the
  exact shard attempt that carried it. Cross-thread producers (the
  batcher's dispatch/completer threads) attach child spans to an explicit
  handle instead of the contextvar.
- **TraceRecorder**: bounded in-memory retention with TAIL sampling —
  errors and degraded/fault-annotated traces are always kept, the
  slowest-N are always kept, everything else is sampled. `/tracez`
  (serving/rest.py) serves its contents as JSON; `chrome_trace()` exports
  Chrome-trace-event JSON that Perfetto / chrome://tracing load directly
  (bench.py --trace-out and tools/soak.py write it to disk).
- **collect_phases**: a thread-local sink that lets the batcher's existing
  PhaseTrace call sites double as per-request span producers — one pair of
  clock reads feeds both the aggregate and the span tree.
- **annotate()**: attaches an annotation to the current span (or the
  active phase sink) — faults.py marks injection sites with it so a chaos
  run's trace shows exactly where the delay/error/wedge landed.

Tracing is OFF by default and gated on one module bool: every hot-path
hook is a single global read when disabled (the bench gate is <=1%
overhead with tracing off).

PhaseTrace keeps its original role (aggregate phase means with ~50ns
overhead), and profile_trace() still wraps a block in a jax.profiler trace
for XLA-level deep dives.
"""

from __future__ import annotations

import contextlib
import contextvars
import heapq
import itertools
import json
import os
import random
import threading
import time
import weakref
from collections import defaultdict, deque

# --------------------------------------------------------------------------
# Aggregate phase timing (the original plane).

_ENABLED = False  # per-request tracing; flipped by enable()/disable()


class PhaseTrace:
    """Accumulates wall time per named phase, aggregated across requests."""

    def __init__(self):
        self._totals: dict[str, float] = defaultdict(float)
        self._counts: dict[str, int] = defaultdict(int)
        self._lock = threading.Lock()

    @contextlib.contextmanager
    def span(self, phase: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.add(phase, time.perf_counter() - t0)

    def add(self, phase: str, seconds: float) -> None:
        """Record an externally timed duration under `phase`. For callers
        that already hold the wall time for their own accounting (the
        batcher's readback-overlap bookkeeping times the fetch once and
        feeds both this trace and the overlap counters) — a nested span
        would pay a second pair of clock reads for the same interval."""
        with self._lock:
            self._totals[phase] += seconds
            self._counts[phase] += 1
        if _ENABLED:
            # Per-request plane: the same interval becomes a child span of
            # whatever request context is active on this thread — the
            # batcher's phase sink when one is installed, else the
            # contextvar span (the service/REST handler threads). One
            # global read when tracing is off.
            end = time.perf_counter()
            sink = getattr(_SINK, "phases", None)
            if sink is not None:
                sink.append((phase, end - seconds, end))
            else:
                cur = _CURRENT.get()
                if cur is not None:
                    cur.add_interval(phase, end - seconds, end)

    def snapshot(self) -> dict[str, dict]:
        with self._lock:
            return {
                phase: {
                    "total_ms": round(self._totals[phase] * 1e3, 3),
                    "count": self._counts[phase],
                    "mean_us": round(
                        self._totals[phase] / self._counts[phase] * 1e6, 1
                    ),
                }
                for phase in sorted(self._totals)
            }

    def reset(self) -> None:
        with self._lock:
            self._totals.clear()
            self._counts.clear()


@contextlib.contextmanager
def profile_trace(log_dir: str):
    """jax.profiler trace around a block (XLA + host timeline)."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


# Process-wide default trace used by the serving path.
request_trace = PhaseTrace()


# --------------------------------------------------------------------------
# W3C trace context (the `traceparent` header, version 00).

_TRACEPARENT_VERSION = "00"


def make_traceparent(trace_id: str, span_id: str, sampled: bool = True) -> str:
    return f"{_TRACEPARENT_VERSION}-{trace_id}-{span_id}-{'01' if sampled else '00'}"


def parse_traceparent(header: str | None) -> tuple[str, str] | None:
    """(trace_id, parent_span_id) from a W3C traceparent, or None when the
    header is absent/malformed — a bad header must degrade to a fresh
    trace, never fail the request."""
    if not header:
        return None
    parts = header.strip().split("-")
    if len(parts) != 4:
        return None
    _version, trace_id, span_id, _flags = parts
    if len(trace_id) != 32 or len(span_id) != 16:
        return None
    try:
        if int(trace_id, 16) == 0 or int(span_id, 16) == 0:
            return None
    except ValueError:
        return None
    return trace_id.lower(), span_id.lower()


def _new_trace_id() -> str:
    return os.urandom(16).hex()


def _new_span_id() -> str:
    return os.urandom(8).hex()


def clock_anchor() -> dict:
    """Pair this process's span clock (perf_counter) with the wall clock,
    plus the pid, so an exported span tree can be placed on a shared
    fleet timeline: unix_us(span) = start_us - perf_us + unix_us. The two
    reads are not atomic; the fleet stitcher refines residual error from
    RPC send/recv pairs, so sub-millisecond anchor noise is acceptable."""
    return {
        "perf_us": int(time.perf_counter() * 1e6),
        "unix_us": time.time_ns() // 1000,
        "pid": os.getpid(),
    }


# --------------------------------------------------------------------------
# Spans.


class Span:
    """One timed operation in a request's tree.

    Timestamps are time.perf_counter() — monotonic, so exported Chrome
    events never go backwards even across NTP steps. Child mutation is
    list-append under the GIL plus an explicit lock for cross-thread
    attachment (the batcher's dispatch/completer threads attach to a span
    owned by an RPC handler)."""

    __slots__ = (
        "name", "trace_id", "span_id", "parent_id", "remote_parent",
        "start", "end", "status", "attrs", "annotations", "children",
        "_lock",
    )

    def __init__(
        self,
        name: str,
        trace_id: str | None = None,
        parent_id: str | None = None,
        remote_parent: bool = False,
        attrs: dict | None = None,
    ):
        self.name = name
        self.trace_id = trace_id or _new_trace_id()
        self.span_id = _new_span_id()
        self.parent_id = parent_id
        self.remote_parent = remote_parent
        self.start = time.perf_counter()
        self.end: float | None = None
        self.status = "OK"
        self.attrs = dict(attrs) if attrs else {}
        self.annotations: list[dict] = []
        self.children: list[Span] = []
        self._lock = threading.Lock()

    # ------------------------------------------------------------- building

    def child(self, name: str, attrs: dict | None = None) -> "Span":
        """Open (started-now) child span; the caller ends it."""
        sp = Span(
            name, trace_id=self.trace_id, parent_id=self.span_id, attrs=attrs
        )
        with self._lock:
            self.children.append(sp)
        return sp

    def add_interval(
        self, name: str, start: float, end: float, attrs: dict | None = None
    ) -> "Span":
        """Attach an already-timed child interval (the batcher's phase
        sink replay; safe from any thread)."""
        sp = Span(
            name, trace_id=self.trace_id, parent_id=self.span_id, attrs=attrs
        )
        sp.start = start
        sp.end = end
        with self._lock:
            self.children.append(sp)
        return sp

    def annotate(self, message: str, **attrs) -> None:
        with self._lock:
            self.annotations.append(
                {"t": time.perf_counter(), "message": message, **attrs}
            )

    def set_error(self, exc: BaseException | None = None) -> None:
        self.status = "ERROR"
        if exc is not None:
            self.attrs.setdefault("error", f"{type(exc).__name__}: {exc}")

    def finish(self) -> None:
        if self.end is None:
            self.end = time.perf_counter()

    # -------------------------------------------------------------- reading

    @property
    def duration_s(self) -> float:
        return ((self.end if self.end is not None else time.perf_counter())
                - self.start)

    def has_error(self) -> bool:
        return self.status == "ERROR" or any(
            c.has_error() for c in self.children
        )

    def has_annotations(self) -> bool:
        return bool(self.annotations) or any(
            c.has_annotations() for c in self.children
        )

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "start_us": int(self.start * 1e6),
            "duration_us": int(self.duration_s * 1e6),
            "status": self.status,
            "attrs": self.attrs,
            "annotations": [
                {**a, "t": int(a["t"] * 1e6)} for a in self.annotations
            ],
            "children": [c.to_dict() for c in self.children],
        }

    def walk(self):
        yield self
        for c in self.children:
            yield from c.walk()


# Contextvar current span: propagates through asyncio tasks (context is
# captured at task creation) and stays per-thread in threaded servers.
_CURRENT: contextvars.ContextVar[Span | None] = contextvars.ContextVar(
    "dts_tpu_current_span", default=None
)

# Thread-local phase sink for producers that run OUTSIDE the request's
# context (the batcher's dispatch/completer threads): a list of
# (phase, t0, t1) tuples plus annotation dicts, replayed onto every
# co-batched request's span by the batcher.
_SINK = threading.local()


def current_span() -> Span | None:
    return _CURRENT.get()


def enabled() -> bool:
    return _ENABLED


class _NoopSpanCtx:
    """Returned by start_span/start_root when tracing is disabled: one
    shared instance, no allocation on the disabled hot path."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NOOP = _NoopSpanCtx()


class _SpanCtx:
    __slots__ = ("span", "_token", "_record")

    def __init__(self, span: Span, record: bool):
        self.span = span
        self._token = None
        self._record = record

    def __enter__(self) -> Span:
        self._token = _CURRENT.set(self.span)
        return self.span

    def __exit__(self, exc_type, exc, tb):
        _CURRENT.reset(self._token)
        if exc is not None:
            if isinstance(exc, Exception):
                self.span.set_error(exc)
            else:
                # BaseException-only exits (asyncio.CancelledError — the
                # hedge loser's DESIGNED fate — GeneratorExit, shutdown):
                # not failures. Marking them ERROR would roll up to the
                # root, defeat tail sampling, and report every healthy
                # hedged request as an error in /tracez.
                self.span.status = "CANCELLED"
        self.span.finish()
        if self._record:
            _RECORDER.record(self.span)
        return False


def start_span(name: str, attrs: dict | None = None):
    """Child span of the current context span (a fresh local root when no
    context is set). Context manager yielding the Span; no-op when tracing
    is disabled."""
    if not _ENABLED:
        return _NOOP
    parent = _CURRENT.get()
    if parent is not None:
        sp = parent.child(name, attrs=attrs)
        return _SpanCtx(sp, record=False)
    return _SpanCtx(Span(name, attrs=attrs), record=True)


def start_root(name: str, traceparent: str | None = None, attrs: dict | None = None):
    """LOCAL-ROOT span: a fresh trace, or — when a valid W3C traceparent
    arrives — a remote-parented span in the caller's trace (the server
    side of a propagated request). Recorded into the global recorder on
    exit regardless of any ambient context."""
    if not _ENABLED:
        return _NOOP
    ctx = parse_traceparent(traceparent)
    if ctx is not None:
        sp = Span(
            name, trace_id=ctx[0], parent_id=ctx[1],
            remote_parent=True, attrs=attrs,
        )
    else:
        sp = Span(name, attrs=attrs)
    return _SpanCtx(sp, record=True)


def annotate(message: str, **attrs) -> None:
    """Attach an annotation to whatever request context is active: the
    thread's phase sink when installed (batcher threads — the batcher
    replays it onto every co-batched request), else the contextvar span.
    One global read when tracing is off."""
    if not _ENABLED:
        return
    sink = getattr(_SINK, "phases", None)
    if sink is not None:
        sink.append(
            {"t": time.perf_counter(), "message": message, **attrs}
        )
        return
    cur = _CURRENT.get()
    if cur is not None:
        cur.annotate(message, **attrs)


@contextlib.contextmanager
def collect_phases(sink: list):
    """Install `sink` as this thread's phase sink: request_trace phase
    timings (and annotate() calls) land in it as (phase, t0, t1) tuples /
    annotation dicts until the block exits. The batcher uses one sink per
    batch and replays it onto every member request's span."""
    prev = getattr(_SINK, "phases", None)
    _SINK.phases = sink
    try:
        yield sink
    finally:
        _SINK.phases = prev


def replay_phases(span: Span, phases: list) -> None:
    """Attach a collect_phases sink's contents to `span`: tuples become
    child intervals, annotation dicts become annotations."""
    for entry in phases:
        if isinstance(entry, dict):
            span.annotations.append(dict(entry))
        else:
            name, t0, t1 = entry
            span.add_interval(name, t0, t1)


# --------------------------------------------------------------------------
# Counter-track sources for the Chrome export (the utilization plane's
# per-device occupancy track, ISSUE 6). Registered objects expose
# `chrome_counter_events(t_base, pid) -> list[dict]`; a WeakSet so a
# retired ledger (bench teardown, tests) drops out of every later export
# without an unregister call.

_COUNTER_SOURCES: "weakref.WeakSet" = weakref.WeakSet()


def register_counter_source(source) -> None:
    """Add a counter-track provider to every future chrome_trace()
    export. Weakly held: dropping the object deregisters it."""
    _COUNTER_SOURCES.add(source)


# --------------------------------------------------------------------------
# Recorder: bounded retention + tail sampling + exporters.


class TraceRecorder:
    """Bounded in-memory store of finished local-root spans.

    Tail sampling (decided at span END, when the outcome is known):

    - error spans (own or any descendant) and annotated spans (fault
      injections, degraded merges) are ALWAYS kept, in a dedicated ring;
    - the slowest `slowest_n` spans are ALWAYS kept (min-heap on
      duration), independent of the sample draw;
    - everything else enters the recent ring with probability
      `sample_rate` (1.0 and 0.0 never consult the RNG — deterministic
      for tests and for the keep-nothing-but-tails production setting).

    Rings are deques: retention is bounded regardless of traffic, and an
    idle server holds exactly what it last saw."""

    def __init__(
        self,
        buffer_size: int = 256,
        sample_rate: float = 1.0,
        slowest_n: int = 32,
        seed: int | None = None,
    ):
        self.buffer_size = max(1, int(buffer_size))
        self.sample_rate = float(sample_rate)
        self.slowest_n = max(0, int(slowest_n))
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self._recent: deque[Span] = deque(maxlen=self.buffer_size)
        self._errors: deque[Span] = deque(maxlen=self.buffer_size)
        self._slow: list[tuple[float, int, Span]] = []  # min-heap
        self._seq = itertools.count()
        self.recorded = 0
        self.dropped = 0
        # Export ring (the fleet trace-export surface): every KEPT span
        # gets a monotonically increasing export sequence number, so a
        # remote collector can pull incrementally with a `since` cursor.
        # Bounded like the retention rings — a collector that falls more
        # than a ring behind misses spans, by design.
        self._export: deque[tuple[int, Span]] = deque(maxlen=self.buffer_size)
        self._export_seq = 0

    # ------------------------------------------------------------ ingestion

    def record(self, span: Span) -> None:
        keep_tail = span.has_error() or span.has_annotations()
        dur = span.duration_s
        with self._lock:
            self.recorded += 1
            kept = False
            if keep_tail:
                self._errors.append(span)
                kept = True
            evicted: Span | None = None
            if self.slowest_n:
                if len(self._slow) < self.slowest_n:
                    heapq.heappush(self._slow, (dur, next(self._seq), span))
                    kept = True
                elif dur > self._slow[0][0]:
                    evicted = heapq.heapreplace(
                        self._slow, (dur, next(self._seq), span)
                    )[2]
                    kept = True
            if self.sample_rate >= 1.0 or (
                0.0 < self.sample_rate and self._rng.random() < self.sample_rate
            ):
                self._recent.append(span)
                kept = True
            if kept:
                self._export_seq += 1
                self._export.append((self._export_seq, span))
            # dropped is APPROXIMATE: spans retained nowhere at record
            # time, plus heap evictions that had no tail claim when the
            # sampler was keeping less than everything. (An exact count
            # would need an O(buffer) ring-membership scan under this
            # lock on every heap replacement — a per-request critical
            # section not worth a diagnostics counter.)
            if not kept:
                self.dropped += 1
            if (
                evicted is not None
                and self.sample_rate < 1.0
                and not (evicted.has_error() or evicted.has_annotations())
            ):
                self.dropped += 1

    def clear(self) -> None:
        with self._lock:
            self._recent.clear()
            self._errors.clear()
            self._slow.clear()
            self._export.clear()
            self.recorded = 0
            self.dropped = 0

    # -------------------------------------------------------------- queries

    def _all_spans_locked(self) -> list[Span]:
        """Distinct retained roots, newest-first-stable (a span can sit in
        several rings; report it once)."""
        seen: set[int] = set()
        out: list[Span] = []
        for sp in itertools.chain(
            self._recent, self._errors, (s for _, _, s in self._slow)
        ):
            if id(sp) not in seen:
                seen.add(id(sp))
                out.append(sp)
        return out

    def spans(self) -> list[Span]:
        with self._lock:
            return self._all_spans_locked()

    def slowest(self, n: int | None = None) -> list[Span]:
        with self._lock:
            ordered = sorted(self._slow, key=lambda e: -e[0])
        return [s for _, _, s in ordered[: n or self.slowest_n]]

    def traces(self) -> list[dict]:
        """Retained local roots grouped by trace id — one entry per
        distributed trace, with every local root (client predict, each
        server RPC) as a tree under it."""
        return self._traces_from(self.spans())

    @staticmethod
    def _traces_from(roots: list[Span]) -> list[dict]:
        groups: dict[str, list[Span]] = {}
        for sp in roots:
            groups.setdefault(sp.trace_id, []).append(sp)
        out = []
        for trace_id, roots in groups.items():
            roots.sort(key=lambda s: s.start)
            out.append({
                "trace_id": trace_id,
                "duration_us": int(
                    (max(s.end or s.start for s in roots)
                     - min(s.start for s in roots)) * 1e6
                ),
                "status": (
                    "ERROR" if any(s.has_error() for s in roots) else "OK"
                ),
                "spans": [s.to_dict() for s in roots],
            })
        out.sort(key=lambda t: -t["duration_us"])
        return out

    def tracez(self, limit: int = 50) -> dict:
        """The /tracez JSON body: recorder config + counters, the
        slowest-N trees, and the most recent traces. ONE lock acquisition
        snapshots everything, so the counters and the serialized trace
        list cannot disagree within a response."""
        with self._lock:
            roots = self._all_spans_locked()
            slow_sorted = [
                s for _, _, s in sorted(self._slow, key=lambda e: -e[0])
            ]
            recorded, dropped = self.recorded, self.dropped
        return {
            "config": {
                "buffer_size": self.buffer_size,
                "sample_rate": self.sample_rate,
                "slowest_n": self.slowest_n,
            },
            "recorded": recorded,
            "dropped": dropped,
            "num_retained": len(roots),
            "slowest": [s.to_dict() for s in slow_sorted],
            "traces": self._traces_from(roots)[: max(1, int(limit))],
        }

    def export_since(self, since: int = 0, limit: int = 64) -> dict:
        """Incremental span-tree export for a remote TraceCollector
        (`GET /tracez/export?since=CURSOR`): every kept local root after
        `since`, as `Span.to_dict` trees, with this process's clock
        anchor so the collector can map perf_counter timestamps onto the
        shared wall-clock timeline. The returned `cursor` feeds the next
        call. A cursor AHEAD of the ring (this process restarted and the
        sequence reset) replays from the start instead of going silent."""
        since = max(0, int(since))
        with self._lock:
            if since > self._export_seq:
                since = 0
            pending = [(seq, sp) for seq, sp in self._export if seq > since]
        pending = pending[: max(1, int(limit))]
        return {
            "enabled": True,
            "clock": clock_anchor(),
            "cursor": pending[-1][0] if pending else since,
            "spans": [sp.to_dict() for _, sp in pending],
        }

    # ------------------------------------------------------------ exporters

    def chrome_trace(self) -> dict:
        """Chrome trace-event JSON (Perfetto / chrome://tracing loadable):
        one complete ("X") event per span with microsecond ts/dur, one
        instant ("i") event per annotation, grouped into one pid per trace
        with the span tree flattened onto tids by root. Monotonic by
        construction — ts derives from perf_counter."""
        events: list[dict] = []
        trace_pids: dict[str, int] = {}
        tid_counters: dict[int, int] = {}
        with self._lock:
            roots = self._all_spans_locked()
        # Stable base so every ts is a small non-negative number.
        t_base = min((s.start for s in roots), default=0.0)
        for root in sorted(roots, key=lambda s: s.start):
            pid = trace_pids.setdefault(root.trace_id, len(trace_pids))
            # One tid per local root inside its trace's pid (sibling RPC
            # attempts render as parallel tracks); O(1) per root — a full
            # export can hold hundreds of roots and runs on the event loop.
            tid = tid_counters.get(pid, 0)
            tid_counters[pid] = tid + 1
            events.append({
                "ph": "M", "name": "thread_name", "pid": pid, "tid": tid,
                "args": {"name": root.name},
            })
            for sp in root.walk():
                events.append({
                    "ph": "X",
                    "name": sp.name,
                    "cat": "span" if sp is root else "phase",
                    "pid": pid,
                    "tid": tid,
                    "ts": max(0, int((sp.start - t_base) * 1e6)),
                    "dur": max(0, int(sp.duration_s * 1e6)),
                    "args": {
                        "trace_id": sp.trace_id,
                        "span_id": sp.span_id,
                        "parent_id": sp.parent_id,
                        "status": sp.status,
                        **sp.attrs,
                    },
                })
                for a in sp.annotations:
                    events.append({
                        "ph": "i",
                        "name": a.get("message", "annotation"),
                        "cat": "annotation",
                        "pid": pid,
                        "tid": tid,
                        "ts": max(0, int((a["t"] - t_base) * 1e6)),
                        "s": "t",
                        "args": {
                            k: v for k, v in a.items()
                            if k not in ("t", "message")
                        },
                    })
        # Counter tracks (per-device occupancy from the utilization
        # ledger): appended on their own pids AFTER the span pids, sharing
        # t_base so the tracks align with the spans on the timeline.
        pid_next = len(trace_pids)
        for source in list(_COUNTER_SOURCES):
            try:
                events.extend(source.chrome_counter_events(t_base, pid_next))
                pid_next += 1
            except Exception:  # noqa: BLE001 — a sick source must not
                pass           # poison the whole export
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {
                "recorded": self.recorded,
                "producer": "distributed_tf_serving_tpu",
            },
        }

    def write_chrome_trace(self, path: str) -> int:
        """Serialize chrome_trace() to `path`; returns the event count."""
        doc = self.chrome_trace()
        with open(path, "w") as f:
            json.dump(doc, f)
        return len(doc["traceEvents"])


# Process-global recorder (the /tracez surface); enable() swaps config.
_RECORDER = TraceRecorder()


def recorder() -> TraceRecorder:
    return _RECORDER


def enable(
    buffer_size: int = 256,
    sample_rate: float = 1.0,
    slowest_n: int = 32,
    seed: int | None = None,
) -> TraceRecorder:
    """Turn the per-request plane on with a fresh recorder; returns it."""
    global _ENABLED, _RECORDER
    _RECORDER = TraceRecorder(
        buffer_size=buffer_size, sample_rate=sample_rate,
        slowest_n=slowest_n, seed=seed,
    )
    _ENABLED = True
    return _RECORDER


def disable() -> None:
    global _ENABLED
    _ENABLED = False
