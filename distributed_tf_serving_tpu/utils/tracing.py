"""Request-path tracing: per-phase timers + jax.profiler integration.

The reference's only tracing is System.nanoTime() around whole requests
(DCNClient.java:141,198-199; SURVEY.md §5). Serving needs to know where the
budget goes — decode / queue / pad+pack / compute / readback / encode — so
PhaseTrace accumulates named spans per request with ~50ns overhead, and
profile_trace() wraps a block in a jax.profiler trace for deep dives
(XLA-level timelines viewable in TensorBoard/Perfetto).
"""

from __future__ import annotations

import contextlib
import threading
import time
from collections import defaultdict


class PhaseTrace:
    """Accumulates wall time per named phase, aggregated across requests."""

    def __init__(self):
        self._totals: dict[str, float] = defaultdict(float)
        self._counts: dict[str, int] = defaultdict(int)
        self._lock = threading.Lock()

    @contextlib.contextmanager
    def span(self, phase: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.add(phase, time.perf_counter() - t0)

    def add(self, phase: str, seconds: float) -> None:
        """Record an externally timed duration under `phase`. For callers
        that already hold the wall time for their own accounting (the
        batcher's readback-overlap bookkeeping times the fetch once and
        feeds both this trace and the overlap counters) — a nested span
        would pay a second pair of clock reads for the same interval."""
        with self._lock:
            self._totals[phase] += seconds
            self._counts[phase] += 1

    def snapshot(self) -> dict[str, dict]:
        with self._lock:
            return {
                phase: {
                    "total_ms": round(self._totals[phase] * 1e3, 3),
                    "count": self._counts[phase],
                    "mean_us": round(
                        self._totals[phase] / self._counts[phase] * 1e6, 1
                    ),
                }
                for phase in sorted(self._totals)
            }

    def reset(self) -> None:
        with self._lock:
            self._totals.clear()
            self._counts.clear()


@contextlib.contextmanager
def profile_trace(log_dir: str):
    """jax.profiler trace around a block (XLA + host timeline)."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


# Process-wide default trace used by the serving path.
request_trace = PhaseTrace()
