"""Config system: dataclasses + TOML, covering the reference's knob set.

The reference hard-codes every knob as private static finals — changing
hosts or batch size means recompiling (DCNClient.java:25-42, SURVEY.md §5).
This maps that exact knob set (field_num, candidate_num, hosts, port,
concurrency, request_num, model name/signature/output key, async mode) plus
the TPU-side knobs (mesh, buckets, batching) onto TOML-loadable dataclasses.
"""

from __future__ import annotations

import dataclasses
import pathlib
from typing import Any

try:
    import tomllib
except ModuleNotFoundError:  # Python < 3.11: the vendored-API backport
    import tomli as tomllib


@dataclasses.dataclass(frozen=True)
class ServerConfig:
    """Serving frontend + batcher + mesh knobs."""

    host: str = "0.0.0.0"
    port: int = 9999  # reference default, DCNClient.java:28
    max_workers: int = 16  # reference thread pool size, DCNClient.java:42
    model_kind: str = "dcn_v2"
    model_name: str = "DCN"  # DCNClient.java:33
    num_fields: int = 43  # FIELD_NUM, DCNClient.java:25
    buckets: tuple[int, ...] = (32, 64, 128, 256, 512, 1024, 2048, 4096)
    max_wait_us: int = 200
    completion_workers: int = 4  # threads finishing readback+delivery
    compress_transfer: bool = True
    # ---- output-transfer pipeline (serving/batcher.py) -------------------
    # Wire dtype for device->host score readback: scores are downcast
    # ON-DEVICE before the D2H transfer and widened back to float32 on the
    # host, so responses stay signature-typed. "float32" = the full-
    # precision fallback (bit-exact); "bfloat16"/"float16" halve the
    # readback bytes at <=1e-2 relative score error.
    output_wire_dtype: str = "float32"
    # >0: retrieval-style compaction — single-request batches return only
    # the top-k (score, index) pairs over the wire; the host rebuilds a
    # full-length score vector with 0.0 off the head (sigmoid scores are
    # strictly positive, so ranking consumers see the same head). 0 = off.
    output_top_k: int = 0
    # Issue copy_to_host_async() at dispatch so the completer's fetch waits
    # on an in-flight transfer (readback.issue / readback.wait phases)
    # instead of starting one (batch.readback). False = the synchronous
    # fallback path.
    async_readback: bool = True
    # Run the device stage (cache/pack/upload/jit-call) on a dedicated
    # dispatch thread so the batching thread's collect+pad of batch k+1
    # overlaps batch k's H2D upload and dispatch. False = the previous
    # single-threaded dispatch.
    pipelined_dispatch: bool = True
    # Donate single-use combined input buffers to the jitted entry (XLA
    # reuses their HBM for outputs). Only effective off-CPU and only for
    # buffers the DeviceInputCache did not retain.
    donate_buffers: bool = True
    warmup: bool = True
    # Coalescing keeps filling past max_wait while this many batches are in
    # flight (latency-free: the dispatch would queue behind device work
    # anyway — serving/batcher.py pipeline-aware fill; min 1, default 2).
    # The [batching] section's pipeline_depth (when nonzero) wins over
    # this legacy location; the new in-flight window / buffer-ring /
    # streaming knobs live only there.
    pipeline_depth: int = 2
    # Admission bound in queued candidates (None = 16 max-size batches);
    # past it requests shed with RESOURCE_EXHAUSTED instead of queueing
    # beyond any deadline.
    queue_capacity_candidates: int | None = None
    # mesh: 0 = single device; >0 = shard over first n devices
    mesh_devices: int = 0
    model_parallel: int = 1
    # shard dense MLP/cross weights over the model axis (§2.4 TP row;
    # embedding tables are always vocab-sharded when a mesh is used)
    tensor_parallel: bool = False
    # Version-label routing (tensorflow_model_server's version_labels map:
    # "stable"/"canary" -> version number). TOML: version_labels = {stable
    # = 2, canary = 3}; stored as sorted (label, version) pairs so the
    # frozen config stays hashable.
    version_labels: tuple[tuple[str, int], ...] = ()
    # Sampled request logging (upstream LoggingConfig): PredictionLog
    # TFRecords usable directly as warmup files. "" = disabled.
    request_log_file: str = ""
    request_log_sampling: float = 0.01
    # Version-watcher knobs (--model-base-path lifecycle), named for their
    # tensorflow_model_server flags: --file_system_poll_wait_seconds and
    # --max_num_load_retries (upstream semantics: retries AFTER the first
    # attempt; 2 retries = the watcher's historical 3 total attempts).
    file_system_poll_wait_seconds: float = 5.0
    max_num_load_retries: int = 2
    # Multi-model serving (upstream --model_config_file): a text-format
    # ModelServerConfig whose model_config_list entries each get their own
    # version watcher (name, base_path, optional model_platform = zoo
    # family, version_labels). "" = single-model modes.
    model_config_file: str = ""


@dataclasses.dataclass(frozen=True)
class ClientConfig:
    """Fan-out client + closed-loop bench knobs (the DCNClient constants)."""

    hosts: tuple[str, ...] = ("127.0.0.1:9999",)  # DCNClient.java:38
    model_name: str = "DCN"  # DCNClient.java:33
    signature_name: str = "serving_default"  # DCNClient.java:34
    output_key: str = "prediction_node"  # DCNClient.java:35
    num_fields: int = 43  # FIELD_NUM
    candidate_num: int = 1500  # DCNClient.java:29
    request_num: int = 1000  # DCNClient.java:30
    concurrent_num: int = 6  # DCNClient.java:31
    # DCNClient.java:27 — True: concurrent per-shard fan-out; False: shards
    # issued sequentially in host order (ShardedPredictClient.full_async).
    full_async_mode: bool = True
    sort_scores: bool = True  # the ranking sort, DCNClient.java:195
    timeout_s: float = 10.0
    use_tensor_content: bool = True
    # Beyond the reference: reroute a failed shard to the next host(s) on
    # UNAVAILABLE/DEADLINE_EXCEEDED/RESOURCE_EXHAUSTED, up to this many
    # extra attempts (0 = the reference's fail-fast behavior).
    failover_attempts: int = 0
    # Candidate-to-backend placement (ROADMAP 4a seed, ISSUE 13
    # satellite). "contiguous" = the reference's positional split
    # (DCNClient.java:46-55). "affinity" = rows route to backends by a
    # consistent (jump) hash of each row's canonical feature digest
    # (cache/digest.py row identity), so a hot candidate row always lands
    # on the same replica's warm score cache instead of being re-scored
    # everywhere; the scoreboard still steers a group away from its
    # affine backend while that backend is ejected/busy/rebuilding.
    placement: str = "contiguous"
    # Retry budget (ISSUE 11 satellite): cap on TOTAL backend attempts
    # per logical request across every shard's failover hops, hedges,
    # and streamed reroutes — one recovering/quarantined replica must
    # not be able to multiply a request into a fleet-wide retry storm.
    # Each shard's FIRST attempt is always allowed (the request needs
    # it); the budget bounds everything beyond. 0 = unlimited (the
    # historical behavior). Exhaustion counts as
    # `retry_budget_exhausted` in the scoreboard snapshot.
    max_attempts_total: int = 0
    # ---- resilience layer (client/health.py + client.py) -----------------
    # Per-backend scoreboard: EWMA latency + consecutive-failure ejection
    # with half-open probing; steers shard placement and failover rotation
    # away from ejected hosts.
    health_scoreboard: bool = False
    # Consecutive reroutable failures before a backend is ejected, and the
    # first ejection interval (doubles per failed half-open probe).
    ejection_failures: int = 3
    ejection_interval_s: float = 5.0
    # Hedged shard RPCs: fire a second attempt on another healthy host
    # after this delay; first answer wins, the loser is cancelled. 0 = off.
    hedge_delay_ms: int = 0
    # Jittered exponential backoff between failover attempts.
    backoff_initial_ms: int = 50
    backoff_max_ms: int = 2000
    # Exhausted shards degrade the merge (PredictResult.missing_ranges +
    # degraded flag) instead of failing the whole request.
    partial_results: bool = False
    # Half-open backends get a grpc.health.v1 Check before real traffic.
    health_probe: bool = False
    # HTTP/2 keepalive pings on the backend channels: a silently-dead
    # backend is detected in ~time+timeout instead of hanging until the
    # RPC deadline. 0 disables (for stock gRPC backends whose default
    # ping-abuse policy would GOAWAY a 10s pinger; the in-tree servers
    # tolerate it via KEEPALIVE_SERVER_OPTIONS).
    keepalive_time_ms: int = 10000
    keepalive_timeout_ms: int = 5000
    # Route by version label instead of latest ("" = unset; upstream
    # ModelSpec.version_label routing, e.g. "stable"/"canary").
    version_label: str = ""
    # Request criticality lane sent in gRPC metadata (x-dts-criticality):
    # "critical" / "default" / "sheddable". Overloaded servers running the
    # [overload] plane shed sheddable traffic first. "" = unset (servers
    # treat it as "default").
    criticality: str = ""
    # TLS toward an --ssl-config-file server ("" = plaintext). PATHS here
    # (unlike the server's inline-PEM textproto): client configs name the
    # deployed cert files. key+cert both set => mTLS identity.
    tls_root_certs_file: str = ""
    tls_client_key_file: str = ""
    tls_client_cert_file: str = ""
    # Integrity wire checksums (ISSUE 20): stamp x-dts-input-crc CRC32C
    # sidecars on requests and verify the server's x-dts-score-crc
    # response stamps before merging — a mismatch steers (scoreboard
    # kind="corrupt") and fails the shard over to another backend.
    # Advisory both ways: servers without [integrity] ignore/omit the
    # metadata.
    integrity_checksums: bool = False


@dataclasses.dataclass(frozen=True)
class BatchingConfig:
    """Continuous-batching pipeline knobs (serving/batcher.py, ISSUE 9):
    the k-deep dispatch/in-flight window, donation-safe padded-batch
    buffer reuse, and the server-side sub-batch split PredictStream uses.
    Every NEW behavior defaults off — pipeline_depth 0 inherits the
    [server] value (historically 2), inflight_window 0 keeps in-flight
    readbacks unbounded, buffer_ring false allocates per batch, and
    stream_chunk_candidates 0 serves PredictStream as a single chunk."""

    # Staged-dispatch depth: how many assembled batches may queue ahead
    # of the device stage (the coalescer's free-ride gate reads it too).
    # 0 = inherit [server] pipeline_depth; >= 1 otherwise (1 serializes
    # assembly against the device stage).
    pipeline_depth: int = 0
    # Max batches simultaneously IN FLIGHT (executing or awaiting D2H
    # readback): the dispatch thread keeps issuing batch k+2 while k
    # awaits readback until the window fills. 0 = unbounded (historical).
    inflight_window: int = 0
    # Reuse padded-batch host buffers across batches (released only after
    # the owning batch's readback completes — donation-safe).
    buffer_ring: bool = False
    # Default candidates per PredictStream sub-batch (the server-side
    # split; requests may override via x-dts-stream-chunk metadata).
    # 0 = no split: the streaming RPC answers with one chunk.
    stream_chunk_candidates: int = 0

    def __post_init__(self):
        for name in ("pipeline_depth", "inflight_window",
                     "stream_chunk_candidates"):
            v = getattr(self, name)
            if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                raise ValueError(
                    f"[batching] {name} must be a non-negative integer, "
                    f"got {v!r}"
                )
        if self.inflight_window and self.inflight_window > 64:
            raise ValueError(
                "[batching] inflight_window > 64 would pin that many "
                "batches of HBM at once; this is almost certainly a typo"
            )


@dataclasses.dataclass(frozen=True)
class TransportConfig:
    """Transport-floor knobs (ISSUE 9): the Unix-domain-socket listener
    for co-located fan-out clients and the reusable response-encode
    arenas. Both default off (TCP-only, allocate-per-call — the
    historical behavior)."""

    # Also bind the gRPC server to this Unix-domain socket path (next to
    # the TCP port). Co-located clients dial "unix:<path>" as the host
    # string. "" = TCP only.
    uds_path: str = ""
    # Route response encodes through per-thread codec.EncodeArena scratch
    # (and reuse one PredictStreamChunk message per stream) instead of
    # allocating per call.
    response_arena: bool = False

    def __post_init__(self):
        if self.uds_path:
            if not isinstance(self.uds_path, str):
                raise ValueError("[transport] uds_path must be a string")
            # The kernel's sockaddr_un limit is ~107 bytes; failing at
            # config parse beats failing at bind time inside serve().
            if len(self.uds_path.encode()) > 100:
                raise ValueError(
                    "[transport] uds_path exceeds the AF_UNIX path limit "
                    f"(~107 bytes): {self.uds_path!r}"
                )
            if ":" in self.uds_path:
                raise ValueError(
                    "[transport] uds_path is a filesystem path, not a "
                    f"host:port or URI: {self.uds_path!r}"
                )


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Mesh serving mode (ISSUE 13): shard serving over a ("data",
    "model") device mesh — candidate rows split over the data axis,
    embedding vocab over the model axis (parallel/mesh.py axis
    conventions; DLRM-scale CTR models are embedding-dominated, so the
    model axis is what lets a table that does not fit one chip serve at
    all). Off by default: with the section absent serving is single-chip
    and bit-identical to the pre-mesh stack.

    Arming it installs a hardened parallel/executor.ShardedExecutor as
    the batcher's run_fn: same wire protocol, same client semantics, one
    process spanning N chips. Mode conflicts ([kernels], [recovery], the
    legacy [server] mesh_devices knob, output_top_k) are refused at
    build time — see build_stack."""

    # Master switch: construct the mesh and install the ShardedExecutor.
    enabled: bool = False
    # Devices in the mesh; 0 = every visible device. Must be divisible by
    # model_parallel (the ("data", "model") factorization).
    devices: int = 0
    # Chips sharding the embedding vocab (the EP axis); the rest of the
    # factorization shards candidates. 1 = pure candidate sharding.
    model_parallel: int = 1
    # Also shard dense MLP/cross weights over the model axis (the TP row;
    # embedding tables are vocab-sharded regardless).
    tensor_parallel: bool = False

    def __post_init__(self):
        for name in ("devices", "model_parallel"):
            v = getattr(self, name)
            if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                raise ValueError(
                    f"[mesh] {name} must be a non-negative integer, got {v!r}"
                )
        if self.model_parallel < 1:
            raise ValueError(
                f"[mesh] model_parallel must be >= 1, got {self.model_parallel!r}"
            )
        if self.devices and self.devices % self.model_parallel != 0:
            raise ValueError(
                f"[mesh] devices={self.devices} is not divisible by "
                f"model_parallel={self.model_parallel} (the mesh is the "
                "(devices/model_parallel, model_parallel) factorization)"
            )


@dataclasses.dataclass(frozen=True)
class ElasticConfig:
    """Elastic mesh serving (ISSUE 15, parallel/elastic.py): a ladder of
    ("data", "model") splits over the SAME devices, pre-built and
    pre-warmed at load time, with a pressure-driven controller switching
    the serving split at runtime — hitlessly (in-flight batches on the
    old split drain behind the per-split in-flight barrier; new
    dispatches route to the target immediately; no serving-path
    compiles). Requires [mesh] enabled (the initial split IS the [mesh]
    factorization); off by default — with the section absent, mesh
    serving is exactly the static PR-13 mode."""

    # Master switch: build an ElasticMeshExecutor + ElasticController
    # instead of the static ShardedExecutor.
    enabled: bool = False
    # The split ladder, e.g. ["8x1", "4x2", "2x4"] (DATAxMODEL; every
    # entry must factorize the [mesh] device count). Empty = derived:
    # {n,1}, {n/2,2} (n even), and the [mesh] split. Sorted
    # throughput-first internally; "up" switches move toward the
    # data-parallel end.
    splits: tuple = ()
    # Controller cadence (opportunistic — ticked from dispatches and
    # monitoring scrapes, no thread; the overload plane's precedent).
    tick_interval_s: float = 0.5
    # Minimum time between switches (the anti-flap floor; also the time
    # the FIRST switch waits after arming).
    dwell_s: float = 5.0
    # Consecutive over/under ticks before a one-rung move. Down is
    # deliberately slower: relaxing parallelism is a latency nicety,
    # escalating it is a survival move.
    up_after_ticks: int = 2
    down_after_ticks: int = 6
    # Load-EWMA thresholds (queue fraction / bucket occupancy, max of
    # both): >= up counts an up tick even at NOMINAL pressure; <= down
    # (at NOMINAL) counts a down tick; between is the hysteresis band
    # (streaks reset, split holds).
    load_up_threshold: float = 0.75
    load_down_threshold: float = 0.20
    load_ewma_alpha: float = 0.3
    # Retained switch-history events (the /meshz ring).
    history_events: int = 64

    def __post_init__(self):
        for s in self.splits:
            d, sep, m = str(s).strip().lower().partition("x")
            if not sep or not d.isdigit() or not m.isdigit() \
                    or int(d) < 1 or int(m) < 1:
                raise ValueError(
                    f"[elastic] splits entry {s!r} is not 'DATAxMODEL' "
                    "with positive integer axes (e.g. '4x2')"
                )
        for name in ("tick_interval_s", "dwell_s", "load_ewma_alpha"):
            v = getattr(self, name)
            if not isinstance(v, (int, float)) or isinstance(v, bool) or v <= 0:
                raise ValueError(
                    f"[elastic] {name} must be a positive number, got {v!r}"
                )
        for name in ("up_after_ticks", "down_after_ticks", "history_events"):
            v = getattr(self, name)
            if not isinstance(v, int) or isinstance(v, bool) or v < 1:
                raise ValueError(
                    f"[elastic] {name} must be a positive integer, got {v!r}"
                )
        up, down = self.load_up_threshold, self.load_down_threshold
        for name, v in (("load_up_threshold", up), ("load_down_threshold", down)):
            if not isinstance(v, (int, float)) or isinstance(v, bool) \
                    or not 0.0 <= v <= 1.0:
                raise ValueError(
                    f"[elastic] {name} must be in [0, 1], got {v!r}"
                )
        if down >= up:
            raise ValueError(
                f"[elastic] load_down_threshold ({down}) must be below "
                f"load_up_threshold ({up}) — the gap IS the hysteresis "
                "band; equal thresholds would flap on every load wiggle"
            )
        if self.load_ewma_alpha > 1.0:
            raise ValueError(
                f"[elastic] load_ewma_alpha must be in (0, 1], got "
                f"{self.load_ewma_alpha!r}"
            )


@dataclasses.dataclass(frozen=True)
class ObservabilityConfig:
    """Telemetry-plane knobs (utils/tracing.py + utils/metrics.py): the
    per-request trace recorder behind GET /tracez and the rolling-window
    horizon of /monitoring and the Prometheus endpoint."""

    # Per-request span tracing (W3C traceparent propagation, /tracez,
    # Chrome-trace export). Off by default: the hot path then pays one
    # global bool read per hook.
    tracing: bool = False
    # Retained local-root spans per ring (recent / error) — memory bound.
    trace_buffer: int = 256
    # Tail-sampling rate for unremarkable traces (errors, degraded
    # results, and fault-annotated traces are ALWAYS kept). 0.0 keeps
    # nothing but the tails; 1.0 keeps everything the buffer can hold.
    trace_sample_rate: float = 1.0
    # The slowest-N traces are always retained regardless of sampling.
    trace_slowest_n: int = 32
    # Rolling-window horizon for sliding QPS + windowed p50/p99.
    window_seconds: float = 60.0
    # Fleet trace export (ISSUE 18): when on (and tracing is on), the
    # replica serves its kept span trees incrementally at
    # GET /tracez/export?since= — the pull surface the router-side
    # TraceCollector stitches cross-process traces from. Off by
    # default; costs nothing when off (the route answers
    # {"enabled": false}).
    trace_export: bool = False
    # How often the router's fleet observability plane ticks: scrapes
    # member /monitoring wires, pulls trace exports, advances the SLO
    # monitor.
    trace_export_interval_s: float = 1.0

    def __post_init__(self):
        v = self.trace_export_interval_s
        if not isinstance(v, (int, float)) or isinstance(v, bool) \
                or v <= 0:
            raise ValueError(
                "[observability] trace_export_interval_s must be a "
                f"positive number, got {v!r}"
            )

    def apply(self):
        """Flip the global tracing plane to this config; returns the
        active TraceRecorder (or None when tracing stays off)."""
        from . import tracing as tracing_mod

        if not self.tracing:
            tracing_mod.disable()
            return None
        return tracing_mod.enable(
            buffer_size=self.trace_buffer,
            sample_rate=self.trace_sample_rate,
            slowest_n=self.trace_slowest_n,
        )


@dataclasses.dataclass(frozen=True)
class CacheConfig:
    """Cache-plane knobs (cache/score_cache.py + cache/dedup.py): the
    exact-match score cache with single-flight coalescing at
    batcher.submit, and intra-batch duplicate collapse in the batcher.
    Everything defaults OFF and, when off, costs one attribute read on the
    hot path (the tracing/faults precedent)."""

    # Master switch: build a ScoreCache and hand it to the batcher.
    enabled: bool = False
    # LRU capacity in entries and in cached-score bytes (whichever binds
    # first; split across the sharded locks).
    max_entries: int = 8192
    max_bytes: int = 64 << 20
    # Shelf life per entry: CTR scores decay with state not in the request
    # (user history, budget pacing), so exact-match hits are only served
    # this long after the computation that produced them. Version swaps
    # invalidate eagerly regardless (version-watcher hook).
    ttl_s: float = 30.0
    # Single-flight: concurrent IDENTICAL misses ride one computation
    # (one leader executes, every waiter gets its scores).
    coalesce: bool = True
    # Intra-batch duplicate collapse: exact-duplicate rows within a
    # combined batch execute once, scores scattered back per requester.
    dedup: bool = False
    # Row-granular score caching (cache/row_cache.py, ISSUE 14): cache
    # scores PER CANDIDATE ROW so a request with 90% hot rows executes
    # only the cold 10% — the batcher consults the row cache after
    # collect, dispatches only the cold rows (possibly a smaller bucket),
    # and scatters device + cached scores back per request. Master-gated
    # by `enabled` like dedup (enabled=false arms nothing). The
    # whole-request cache stays in front: a full hit never reaches the
    # row path.
    row_granular: bool = False
    # Row-tier LRU capacity (entries are single rows — small values, so
    # the entry bound usually binds first) and shelf life. Row entries
    # ride the same generation invalidation (version swaps drop them
    # eagerly) and the same brownout stale window as request entries.
    row_max_entries: int = 131072
    row_max_bytes: int = 32 << 20
    row_ttl_s: float = 30.0
    # Per-row single-flight: two co-resident batches sharing a cold row
    # execute it once (the second assembles from the first's fill).
    row_coalesce: bool = True

    def build(self):
        """ScoreCache per this config, or None when disabled."""
        if not self.enabled:
            return None
        from ..cache import ScoreCache

        return ScoreCache(
            max_entries=self.max_entries,
            max_bytes=self.max_bytes,
            ttl_s=self.ttl_s,
            coalesce=self.coalesce,
        )

    def build_row(self):
        """RowScoreCache per this config, or None when the plane (or the
        [cache] master switch) is off — enabled=false with
        row_granular=true must arm nothing, the dedup precedent."""
        if not (self.enabled and self.row_granular):
            return None
        from ..cache import RowScoreCache

        return RowScoreCache(
            max_entries=self.row_max_entries,
            max_bytes=self.row_max_bytes,
            ttl_s=self.row_ttl_s,
            coalesce=self.row_coalesce,
        )


@dataclasses.dataclass(frozen=True)
class OverloadConfig:
    """Overload-control knobs (serving/overload.py): the adaptive
    admission controller, criticality lanes, brownout stale-serve, and
    the drain grace the SIGTERM handler honors. Everything defaults OFF;
    when off the batcher keeps its static queue_capacity_candidates bound
    and pays one attribute read per submit."""

    # Master switch: build an AdmissionController and hand it to the
    # batcher (replacing the static queue_capacity_candidates check).
    enabled: bool = False
    # The controlled variable: windowed queue-wait p99 is steered toward
    # this target by growing/shrinking the admission limit.
    target_queue_wait_ms: float = 50.0
    # Sliding window the p99 is computed over, and how often the AIMD
    # controller ticks (opportunistically, from the submit path).
    queue_wait_window_s: float = 10.0
    adjust_interval_s: float = 0.5
    # AIMD step sizes: additive growth while under target, multiplicative
    # shrink while over.
    increase_candidates: int = 1024
    decrease_factor: float = 0.7
    # Limit clamp in candidates. 0 = auto: min one largest bucket (a
    # full-size request always admits on an idle queue), max the static
    # queue capacity the controller replaces.
    min_limit_candidates: int = 0
    max_limit_candidates: int = 0
    # EWMA smoothing for per-candidate service time (deadline pricing).
    service_ewma_alpha: float = 0.2
    # Refuse at enqueue when the backlog's estimated wait already exceeds
    # the request's remaining deadline budget (doomed work).
    deadline_refusal: bool = True
    # Pressure state machine: consecutive over-target ticks before
    # NOMINAL->BROWNOUT and before BROWNOUT->SHED; consecutive under-
    # target ticks before stepping one level back down.
    brownout_after_intervals: int = 4
    shed_after_intervals: int = 12
    recover_after_intervals: int = 6
    # Brownout stale-serve: while pressure is past NOMINAL, score-cache
    # entries up to this far past their TTL still serve (marked degraded,
    # never re-filled). 0 disables stale serving.
    stale_while_overloaded_s: float = 30.0
    # Clamp for the retry-after-ms pushback hint on refusals.
    retry_after_floor_ms: int = 25
    retry_after_cap_ms: int = 2000
    # SIGTERM drain: how long the server waits for queued + in-flight
    # batches to finish before stopping (honored whether or not the
    # adaptive controller is enabled).
    drain_grace_s: float = 5.0

    def build(self):
        """AdmissionController per this config, or None when disabled."""
        if not self.enabled:
            return None
        from ..serving.overload import AdmissionController

        return AdmissionController(self)


@dataclasses.dataclass(frozen=True)
class UtilizationConfig:
    """Utilization-attribution knobs (serving/utilization.py): the
    per-device occupancy ledger + gap waterfall behind GET /utilz, the
    `utilization` block in /monitoring, the dts_tpu_utilization_*
    Prometheus series, and the Perfetto counter track in the Chrome
    export. Off by default; when off every batcher hook is one attribute
    read (the tracing/cache/overload precedent)."""

    # Master switch: build an OccupancyLedger and hand it to the batcher.
    enabled: bool = False
    # Ring bound for retained batch intervals / idle gaps / wait records
    # (the windowed waterfall's memory + lookback bound).
    ring: int = 4096
    # Default waterfall window for /utilz and /monitoring.
    window_seconds: float = 60.0
    # Optional per-bucket pure-device-step table (us) calibrating the
    # live achieved_fraction_of_device_limit estimate — the bench's
    # artifacts/device_envelope.json format ({bucket: us} or
    # {bucket: [lo, hi]}). "" = uncalibrated (busy-fraction fallback,
    # labeled as such in the waterfall).
    calibration_file: str = ""
    # Where POST /profilez/start drops capture artifacts (jax profiler
    # trace + host_stacks.json). "" = a tempdir subfolder.
    profile_dir: str = ""

    def build(self):
        """OccupancyLedger per this config (registered as a Chrome
        counter-track source), or None when disabled. Applies
        profile_dir to the process-global capture slot either way —
        /profilez is on-demand and available regardless of the ledger."""
        from ..serving import utilization as util_mod

        if self.profile_dir:
            util_mod.profiler_capture().base_dir = self.profile_dir
        if not self.enabled:
            return None
        calibration = (
            util_mod.load_calibration(self.calibration_file)
            if self.calibration_file else None
        )
        ledger = util_mod.OccupancyLedger(
            ring=self.ring,
            window_s=self.window_seconds,
            calibration=calibration,
        )
        from . import tracing as tracing_mod

        tracing_mod.register_counter_source(ledger)
        return ledger


@dataclasses.dataclass(frozen=True)
class QualityConfig:
    """Model-quality observability knobs (serving/quality.py): the
    per-(model, version) score-distribution sketches, PSI/JS drift vs a
    pinned reference and between live versions, the /labelz label-
    feedback join (windowed AUC + calibration), and drift-linked trace
    exemplars. Off by default; when off the batcher completer pays one
    attribute read per batch (the tracing/cache/overload/utilization
    precedent)."""

    # Master switch: build a QualityMonitor and hand it to the batcher.
    enabled: bool = False
    # Fixed-bin score histogram geometry. CTR scores are sigmoid
    # probabilities, so [0, 1]; out-of-range scores clamp to edge bins.
    bins: int = 50
    range_lo: float = 0.0
    range_hi: float = 1.0
    # Rolling window the drift math and windowed AUC read over, and how
    # many ring slices it is built from (granularity = window/slices).
    window_seconds: float = 300.0
    slices: int = 6
    # Drift alerting: current-window PSI vs the pinned reference (or
    # between live versions) at/above this threshold arms exemplar
    # capture. 0.2 = the standard "moderate shift" PSI band.
    drift_threshold_psi: float = 0.2
    # How often the drift math runs (opportunistically from the observe
    # path — no background thread), and how many of the next traced
    # requests get the force-keep `quality.drift` annotation per check
    # interval while drift stays above threshold.
    drift_check_interval_s: float = 5.0
    exemplar_traces: int = 8
    # Minimum window samples (each side) before a drift number is
    # computed — PSI on a handful of scores is noise, not signal.
    min_drift_count: int = 50
    # Label-feedback join bounds: score-reservoir keys retained (LRU; a
    # label for an evicted key counts as orphaned, never silently
    # dropped), joined (score, label) pairs retained, and the largest
    # request (candidates) that gets per-row digest keys computed.
    reservoir_keys: int = 8192
    label_window: int = 8192
    digest_rows_limit: int = 256
    # Pinned-reference artifact: loaded at build when present, written by
    # POST /qualityz/snapshot. "" disables persistence (pin-only).
    reference_file: str = "artifacts/quality_reference.json"

    def build(self):
        """QualityMonitor per this config, or None when disabled."""
        if not self.enabled:
            return None
        from ..serving.quality import QualityMonitor

        return QualityMonitor(
            bins=self.bins,
            lo=self.range_lo,
            hi=self.range_hi,
            window_s=self.window_seconds,
            slices=self.slices,
            drift_threshold_psi=self.drift_threshold_psi,
            drift_check_interval_s=self.drift_check_interval_s,
            exemplar_traces=self.exemplar_traces,
            min_drift_count=self.min_drift_count,
            reservoir_keys=self.reservoir_keys,
            label_window=self.label_window,
            digest_rows_limit=self.digest_rows_limit,
            reference_file=self.reference_file,
        )


@dataclasses.dataclass(frozen=True)
class LifecycleConfig:
    """Continuous-freshness lifecycle knobs (serving/lifecycle.py): the
    online fine-tune publisher, canary admission ramp, and the drift/AUC
    auto-rollback controller. Off by default; when off the service pays
    one attribute read per version resolution (the tracing/cache/overload
    precedent). Arming it requires --model-base-path (the watched
    versioned dir is both the publish target and the hot-swap mechanism)
    and [quality] enabled (the rollback gate reads the quality plane's
    version-pair drift and per-version label AUC) — build_stack refuses
    a lifecycle with no signal or no actuator rather than arming a
    controller that can only ever promote blind."""

    # Master switch: build a LifecycleController and hand it to the impl.
    enabled: bool = False
    # Control-loop cadence: the background thread's tick interval, also
    # the opportunistic-tick spacing on the routing path.
    tick_interval_s: float = 1.0
    # Canary admission ramp: probe-lane-only warm phase, then a
    # deterministic fraction of default-lane traffic stepping up per
    # dwell until max_fraction.
    canary_probe_only_s: float = 10.0
    canary_initial_fraction: float = 0.05
    canary_ramp_step: float = 0.10
    canary_step_dwell_s: float = 10.0
    canary_max_fraction: float = 0.5
    # Promotion: total healthy CANARY time (past the probe phase) at max
    # fraction, with at least min_canary_scores windowed canary scores,
    # before the routing override drops away and latest serves everyone.
    promote_after_s: float = 60.0
    min_canary_scores: int = 200
    # Rollback: version-pair PSI at/above this (0.5 = well past the
    # "major shift" band — rollback wants stronger evidence than the
    # quality plane's 0.2 alert), or a windowed label-feedback AUC drop
    # of at least rollback_auc_drop with min_auc_pairs joined on each
    # side. The rolled-back state holds rollback_hold_s before the
    # controller re-arms for the next rollout.
    rollback_psi: float = 0.5
    # The rollback PSI is computed over this many MERGED bins, not the
    # quality plane's fine histogram: a fresh canary's window is small,
    # and same-distribution PSI over 50 thin bins at a few hundred
    # samples reads 0.2-0.3 of pure sampling noise (measured) — within
    # reach of the threshold — while ~10 merged bins put the noise floor
    # at ~0.03 with a genuine shift still reading >1.
    rollback_compare_bins: int = 10
    rollback_auc_drop: float = 0.05
    min_auc_pairs: int = 100
    rollback_hold_s: float = 30.0
    # Fine-tune publisher cadence: every interval (while IDLE), continue
    # training the stable servable on fresh rows and publish the result
    # as the next version. 0 = publisher off (canary/rollback still
    # manage externally published versions).
    fine_tune_interval_s: float = 0.0
    fine_tune_steps: int = 200
    fine_tune_batch_size: int = 256
    fine_tune_learning_rate: float = 1e-4
    # Retained transition-event history (/lifecyclez `events`).
    history_events: int = 64


@dataclasses.dataclass(frozen=True)
class RecoveryConfig:
    """Device-failure recovery knobs (serving/recovery.py): the watchdog
    that escalates the batcher's wedge clock into a quarantine, the
    in-process executor reinit, the in-flight/queued replay budget, and
    the poisoned-input bisection thresholds. Off by default; when off
    the batcher pays one attribute read per hook and behavior is
    bit-identical to the pre-plane stack (the tracing/cache/overload
    precedent)."""

    # Master switch: build a RecoveryController and attach it to the
    # batcher + impl.
    enabled: bool = False
    # Watchdog poll cadence (the background thread; failure-triggered
    # cycles wake it early).
    watchdog_interval_s: float = 0.5
    # A dispatched/in-flight batch outstanding this long quarantines the
    # replica — the ESCALATION threshold, far below the circuit
    # breaker's fail-fast bound (default 90s): the breaker protects
    # handler threads, this protects the replica.
    wedge_quarantine_s: float = 15.0
    # Max re-dispatches per work item across the whole recovery history;
    # past it the item fails with the original device error. Sized for
    # bisection: isolating one poison row in a 64-request batch takes
    # ~log2(64)+2 replays of the innocent rows.
    replay_budget: int = 8
    # A SINGLE-request batch that has killed the executor this many
    # times is the poison: it alone fails (INVALID_ARGUMENT).
    poison_kills: int = 2
    # A MULTI-request batch whose members have this many kills is
    # bisected into halves instead of replayed whole.
    bisect_after_kills: int = 2
    # Re-warm every registered servable's bucket ladder through the
    # queue after the executor rebuild (recommended: the first replayed
    # batch must not pay a compile storm under the wedge clock).
    reinit_warmup: bool = True
    rewarm_timeout_s: float = 120.0
    # Also tear down the jax backend client itself (process-global,
    # heavyweight; only for genuinely lost devices — never the default).
    reinit_clear_backend: bool = False
    # How long REPLAY waits for the requeued items to complete before
    # declaring the cycle done (failures re-trigger; this only bounds
    # the state machine's dwell).
    replay_drain_s: float = 30.0
    # Hard bound on reinit+replay rounds inside one cycle (bisection of
    # pathological batches); past it the remaining items fail.
    max_cycle_rounds: int = 20
    # Retained transition-event history (/recoveryz `events`).
    history_events: int = 64
    # Recovery unit. "executor" (the only implemented scope): the whole
    # serving executor quarantines/reinits/replays as ONE unit — over a
    # [mesh] that means the entire mesh (an SPMD executable spans every
    # chip; there is no half-alive mesh to keep serving). "per_chip" is
    # refused at build time when a mesh is armed (documented future
    # work); on a single chip the two scopes are the same thing.
    scope: str = "executor"

    def __post_init__(self):
        if self.scope not in ("executor", "per_chip"):
            raise ValueError(
                f"[recovery] scope must be 'executor' or 'per_chip', "
                f"got {self.scope!r}"
            )
        for name in ("replay_budget", "poison_kills", "bisect_after_kills",
                     "max_cycle_rounds"):
            v = getattr(self, name)
            if not isinstance(v, int) or isinstance(v, bool) or v < 1:
                raise ValueError(
                    f"[recovery] {name} must be a positive integer, got {v!r}"
                )
        for name in ("watchdog_interval_s", "wedge_quarantine_s",
                     "replay_drain_s", "rewarm_timeout_s"):
            v = getattr(self, name)
            if not isinstance(v, (int, float)) or isinstance(v, bool) \
                    or v <= 0:
                # Refuse up front (the other planes' precedent) instead
                # of silently flooring a 0/negative into hair-trigger
                # quarantines or unbounded dwells downstream.
                raise ValueError(
                    f"[recovery] {name} must be a positive number, got {v!r}"
                )


@dataclasses.dataclass(frozen=True)
class KernelsConfig:
    """Kernel/quantization plane knobs (ops/quantize.py + ops/autotune.py
    + ops/cross_kernel.py fused serving kernel, ISSUE 12): post-training
    int8 weight quantization, the fused Pallas gather+cross+MLP serving
    kernel, and the per-bucket autotune harness that enables each variant
    ONLY where it measured faster than the XLA/f32 baseline on the live
    device AND passed the accuracy gates. Everything defaults OFF; when
    off the batcher pays one attribute read per dispatch and served
    scores are bit-identical to the pre-plane stack."""

    # Master switch: build a KernelManager, attach it to the batcher, and
    # run the autotune harness at warmup.
    enabled: bool = False
    # Candidate families the autotune may consider (a family disabled
    # here is never even measured).
    quantize: bool = True
    pallas: bool = True
    # Run the measurement harness at servable warmup. False = serve only
    # decisions adopted from a persisted table_file (none = baseline).
    autotune: bool = True
    # Measure and record everything, ENABLE nothing (the CI smoke's
    # contract: the harness is exercised, live serving is untouched).
    measure_only: bool = False
    # Decision-table persistence: restarts with the same (model, version,
    # device, gates) adopt their prior measurements instead of re-tuning.
    # "" disables persistence.
    table_file: str = "artifacts/kernel_autotune.json"
    # Enablement gates: a variant serves a bucket only when measured
    # speedup >= min_speedup AND max |Δscore| vs the f32 baseline <=
    # max_abs_delta AND (when a labeled eval set is supplied — bench/CI)
    # |AUC_f32 - AUC_variant| <= auc_margin.
    min_speedup: float = 1.0
    max_abs_delta: float = 0.005
    auc_margin: float = 0.005
    # Timing iterations per (bucket, variant); 0 = auto (device-scaled).
    measure_iters: int = 0
    # Subset of the bucket ladder to tune; empty = the whole ladder.
    autotune_buckets: tuple[int, ...] = ()
    # int8 score RESPONSE wire: with this on, a client that sends
    # x-dts-score-wire: int8 metadata receives the score tensor as
    # DT_INT8 plus (scale, min) sidecar outputs and dequantizes locally —
    # 4x fewer response bytes per score than f32 tensor_content. Clients
    # that do not opt in are byte-identical to today.
    int8_score_wire: bool = False

    def __post_init__(self):
        for name in ("min_speedup", "max_abs_delta", "auc_margin"):
            v = getattr(self, name)
            if not isinstance(v, (int, float)) or isinstance(v, bool) or v <= 0:
                raise ValueError(
                    f"[kernels] {name} must be a positive number, got {v!r}"
                )
        if not isinstance(self.measure_iters, int) or \
                isinstance(self.measure_iters, bool) or self.measure_iters < 0:
            raise ValueError(
                "[kernels] measure_iters must be a non-negative integer, "
                f"got {self.measure_iters!r}"
            )
        for b in self.autotune_buckets:
            if not isinstance(b, int) or b <= 0:
                raise ValueError(
                    "[kernels] autotune_buckets must be positive integers, "
                    f"got {self.autotune_buckets!r}"
                )

    def build(self):
        """KernelManager per this config, or None when disabled. The
        module-level int8 score-wire gate tracks this build EITHER way:
        a disabled plane DISARMS it, so a process that built an armed
        stack earlier (tests, embedded use) cannot leak int8 responses
        out of a later plane-less stack."""
        from ..ops.autotune import KernelManager, set_wire_active

        if not self.enabled:
            set_wire_active(False)
            return None
        set_wire_active(self.int8_score_wire)
        return KernelManager(self)


@dataclasses.dataclass(frozen=True)
class FleetConfig:
    """Fleet robustness plane knobs (fleet/ package, ISSUE 17): the
    cross-replica health gossip every member runs, and the shared rollout
    state the router (single writer) coordinates. The router process
    itself is `--router` / `python -m distributed_tf_serving_tpu.fleet.router`
    over the SAME file: [server] is its bind address, [client] its
    backend list + steering knobs, [fleet] this section. Off by default;
    a disarmed replica pays one attribute read per hook."""

    # Master switch: start a GossipAgent next to the server and register
    # /fleetz.
    enabled: bool = False
    # Stable member name in gossip records. "" = derive from the gossip
    # listen address (fine for static fleets; set it when replicas sit
    # behind NAT or get respawned on new ports).
    self_id: str = ""
    # Address PEERS use to reach this member's gossip listener
    # ("host:port" or "unix:/path"). "" = the listener's own bind
    # address.
    advertise_addr: str = ""
    # Other members' gossip endpoints ("host:port" or "unix:/path").
    # Every member gossips with every listed peer each interval
    # (push-pull, so one live peer in common converges the fleet).
    peers: tuple[str, ...] = ()
    # Gossip listener bind. Port 0 = ephemeral (tests); production sets
    # a fixed port so peers can list it. gossip_uds switches the
    # listener (and dialing peers given as unix:...) to AF_UNIX.
    gossip_host: str = "127.0.0.1"
    gossip_port: int = 0
    gossip_uds: str = ""
    # Push-pull exchange cadence; fleet-wide convergence is one or two
    # intervals (record rides both the push and the response).
    gossip_interval_s: float = 0.5
    # A member silent this long is dropped from the view (SIGKILL with
    # no goodbye). Must exceed a few intervals or flaky peers flap.
    record_ttl_s: float = 5.0
    # Rollout coordination (fleet/rollout.py). Exactly ONE member — the
    # router — sets rollout_writer=true and owns the state file; every
    # other member follows the rollout state it sees in gossip.
    rollout_writer: bool = False
    # Where the writer persists rollout state (atomic rename). "" on
    # the writer = in-memory only (still distributed via gossip, lost
    # on router restart).
    rollout_state_file: str = ""

    def __post_init__(self):
        for name in ("gossip_interval_s", "record_ttl_s"):
            v = getattr(self, name)
            if not isinstance(v, (int, float)) or isinstance(v, bool) \
                    or v <= 0:
                raise ValueError(
                    f"[fleet] {name} must be a positive number, got {v!r}"
                )
        if not isinstance(self.gossip_port, int) or \
                isinstance(self.gossip_port, bool) or self.gossip_port < 0:
            raise ValueError(
                f"[fleet] gossip_port must be a non-negative integer, "
                f"got {self.gossip_port!r}"
            )
        if self.record_ttl_s <= self.gossip_interval_s:
            raise ValueError(
                "[fleet] record_ttl_s must exceed gossip_interval_s "
                f"(got ttl={self.record_ttl_s!r} <= "
                f"interval={self.gossip_interval_s!r}) — a member would "
                "expire between its own heartbeats"
            )


@dataclasses.dataclass(frozen=True)
class SloConfig:
    """SLO burn-rate monitor knobs (fleet/observability.py, ISSUE 18):
    the router's multi-window error-budget monitor over aggregated
    fleet telemetry, served at GET /sloz and as dts_tpu_slo_* series.
    Off by default; when on, a breach annotates in-flight router spans
    (`slo.burn`) so the tail sampler force-keeps explaining traces."""

    enabled: bool = False
    # Latency SLO: fraction of requests under latency_target_ms must
    # meet latency_objective.
    latency_target_ms: float = 50.0
    latency_objective: float = 0.99
    # Availability SLO: fraction of non-error requests.
    availability_objective: float = 0.999
    # Multi-window burn rates (Google SRE workbook shape): a page fires
    # only when BOTH the short and long window burn fast — short alone
    # is noise, long alone is stale.
    short_window_s: float = 300.0
    long_window_s: float = 3600.0
    # burn = bad_fraction / error_budget. 14.4x exhausts a 30-day
    # budget in 2 days (page); 6x in 5 days (ticket/warn).
    burn_threshold_fast: float = 14.4
    burn_threshold_slow: float = 6.0

    def __post_init__(self):
        for name in (
            "latency_target_ms", "short_window_s", "long_window_s",
            "burn_threshold_fast", "burn_threshold_slow",
        ):
            v = getattr(self, name)
            if not isinstance(v, (int, float)) or isinstance(v, bool) \
                    or v <= 0:
                raise ValueError(
                    f"[slo] {name} must be a positive number, got {v!r}"
                )
        for name in ("latency_objective", "availability_objective"):
            v = getattr(self, name)
            if not isinstance(v, (int, float)) or isinstance(v, bool) \
                    or not (0.0 < v < 1.0):
                raise ValueError(
                    f"[slo] {name} must be in (0, 1), got {v!r} — an "
                    "objective of 1.0 leaves zero error budget and "
                    "every burn rate divides by zero"
                )
        if self.long_window_s <= self.short_window_s:
            raise ValueError(
                "[slo] long_window_s must exceed short_window_s "
                f"(got long={self.long_window_s!r} <= "
                f"short={self.short_window_s!r}) — multi-window burn "
                "alerting needs distinct horizons"
            )


@dataclasses.dataclass(frozen=True)
class CascadeConfig:
    """Multi-stage ranking cascade knobs (serving/cascade.py, ISSUE 19):
    a cheap first-stage servable prunes the candidate set on-device and
    the full model ranks only the survivors — retrieval->rank in one
    RPC. Off by default (one attribute read per Predict when disabled).
    Refused alongside output_top_k (its wire replaces the score vector
    the cascade's scatter needs) and [mesh]/[elastic] (the sharded
    executor has no prune entry)."""

    enabled: bool = False
    # Registry name the first-stage servable is published/resolved under
    # — a NORMAL model name: the version watcher, lifecycle, and quality
    # planes see it like any other servable.
    stage1_model: str = "stage1"
    # Registered model kind built for the demo stage-1 servable when no
    # stage1_base_path supplies checkpoints (two_tower: the user-tower /
    # item-tower dot product is the classic cheap retrieval scorer).
    stage1_kind: str = "two_tower"
    # Versioned base path for watcher-managed stage-1 rollouts; empty =
    # build the demo stage-1 servable in-process.
    stage1_base_path: str = ""
    # Survivor budget: a fixed top-k when > 0, else ceil of this fraction
    # of the request's candidates.
    survivor_k: int = 0
    survivor_fraction: float = 0.25
    # Optional host-side filter on stage-1 survivor scores: survivors
    # scoring below this are pruned too (0 disables; applied AFTER the
    # top-k selection, so it only ever shrinks the ranked set).
    score_threshold: float = 0.0
    # Requests smaller than this skip the cascade outright — two device
    # round trips cost more than ranking a tiny batch once.
    min_candidates: int = 8

    def __post_init__(self):
        if not self.stage1_model:
            raise ValueError("[cascade] stage1_model must be non-empty")
        if not isinstance(self.survivor_k, int) or isinstance(
            self.survivor_k, bool
        ) or self.survivor_k < 0:
            raise ValueError(
                "[cascade] survivor_k must be a non-negative int, got "
                f"{self.survivor_k!r}"
            )
        if not isinstance(self.survivor_fraction, (int, float)) or isinstance(
            self.survivor_fraction, bool
        ) or not (0.0 < self.survivor_fraction <= 1.0):
            raise ValueError(
                "[cascade] survivor_fraction must be in (0, 1], got "
                f"{self.survivor_fraction!r}"
            )
        if not isinstance(self.min_candidates, int) or isinstance(
            self.min_candidates, bool
        ) or self.min_candidates < 2:
            raise ValueError(
                "[cascade] min_candidates must be an int >= 2, got "
                f"{self.min_candidates!r} — a 1-candidate cascade prunes "
                "nothing and pays two submits"
            )
        if not isinstance(self.score_threshold, (int, float)) or isinstance(
            self.score_threshold, bool
        ) or self.score_threshold < 0.0:
            raise ValueError(
                "[cascade] score_threshold must be >= 0, got "
                f"{self.score_threshold!r}"
            )


@dataclasses.dataclass(frozen=True)
class IntegrityConfig:
    """Data-integrity plane knobs (serving/integrity.py, ISSUE 20): wire
    CRC32C sidecars, the post-D2H readback sanity screen, and sampled
    bit-identity shadow verification — three detection ladders against
    SILENT corruption (flipped D2H bits, decaying host buffers, plausible
    wrong scores) that every other robustness plane is blind to because
    nothing errors. Verdicts escalate into the EXISTING recovery (PR 11)
    and gossip/router (PR 17) machinery instead of new quarantine logic.
    Off by default; when off every hook is one attribute read and served
    bytes are bit-identical to the pre-plane stack."""

    # Master switch: build an IntegrityPlane and attach it to the impl +
    # batcher; arms the server-side wire verify and response stamping.
    enabled: bool = False
    # Layer 1 — wire integrity. Verify x-dts-input-crc request sidecars
    # at decode (the corrupted request alone fails INVALID_ARGUMENT with
    # a corrupt-wire detail) and stamp x-dts-score-crc over the response
    # score tensor for opted-in clients to verify before merge.
    wire_checksums: bool = True
    # Layer 2 — readback sanity screen. Post-D2H NaN/Inf check over the
    # score tensor in the batcher completer; a failing ROW fails its own
    # request while batchmates deliver (the PR-11 per-item machinery).
    screen: bool = True
    # Optional plausible-score interval [screen_min, screen_max] the
    # screen also enforces; (0, 0) disables the range check (NaN/Inf
    # only). CTR scores are probabilities, so (0, 1) is the natural
    # production setting — but the default must not reject imported
    # graphs with logit-scale outputs.
    screen_min: float = 0.0
    screen_max: float = 0.0
    # Screen trips past this count inside screen_window_s escalate to
    # RecoveryController.take_group (output_corrupt): one cosmic-ray row
    # is row-failed and forgotten, a persistently-corrupting executor
    # walks the QUARANTINED->REINIT->REPLAY cycle.
    screen_trips_per_window: int = 3
    screen_window_s: float = 10.0
    # Layer 3 — shadow verification. Fraction of batches re-executed
    # through the SAME jitted entry and compared bit-identically on
    # host; any mismatch is nondeterminism or silent corruption ->
    # recovery escalation + the suspect verdict gossiped fleet-wide.
    # 0.0 = sampled shadowing off (POST /integrityz/audit still works).
    shadow_fraction: float = 0.0
    # Router tier: fraction of forwarded requests additionally fanned to
    # TWO replicas with bit-identical compare; disagreement marks the
    # minority replica suspect in gossip. 0.0 = off.
    router_audit_fraction: float = 0.0
    # Consecutive clean shadow passes that clear a replica's suspect
    # verdict (self-check rehabilitation).
    suspect_clear_passes: int = 3
    # Retained detection-event history (/integrityz `events`).
    history_events: int = 64

    def __post_init__(self):
        for name in ("screen_trips_per_window", "suspect_clear_passes",
                     "history_events"):
            v = getattr(self, name)
            if not isinstance(v, int) or isinstance(v, bool) or v < 1:
                raise ValueError(
                    f"[integrity] {name} must be a positive integer, "
                    f"got {v!r}"
                )
        for name in ("shadow_fraction", "router_audit_fraction"):
            v = getattr(self, name)
            if not isinstance(v, (int, float)) or isinstance(v, bool) \
                    or not 0.0 <= v <= 1.0:
                raise ValueError(
                    f"[integrity] {name} must be in [0, 1], got {v!r}"
                )
        if not isinstance(self.screen_window_s, (int, float)) or isinstance(
            self.screen_window_s, bool
        ) or self.screen_window_s <= 0:
            raise ValueError(
                f"[integrity] screen_window_s must be a positive number, "
                f"got {self.screen_window_s!r}"
            )
        for name in ("screen_min", "screen_max"):
            v = getattr(self, name)
            if not isinstance(v, (int, float)) or isinstance(v, bool):
                raise ValueError(
                    f"[integrity] {name} must be a number, got {v!r}"
                )
        if self.screen_max < self.screen_min:
            raise ValueError(
                f"[integrity] screen_max ({self.screen_max!r}) must be >= "
                f"screen_min ({self.screen_min!r}); use (0, 0) to disable "
                "the range check"
            )

    def build(self):
        from ..serving.integrity import IntegrityPlane

        return IntegrityPlane(self)


def _model_config_cls():
    from ..models.base import ModelConfig

    return ModelConfig


_SECTIONS = {
    "server": ServerConfig,
    "client": ClientConfig,
    "mesh": MeshConfig,
    "elastic": ElasticConfig,
    "batching": BatchingConfig,
    "transport": TransportConfig,
    "observability": ObservabilityConfig,
    "cache": CacheConfig,
    "overload": OverloadConfig,
    "utilization": UtilizationConfig,
    "quality": QualityConfig,
    "lifecycle": LifecycleConfig,
    "recovery": RecoveryConfig,
    "kernels": KernelsConfig,
    "fleet": FleetConfig,
    "slo": SloConfig,
    "cascade": CascadeConfig,
    "integrity": IntegrityConfig,
}


def _coerce(cls, data: dict[str, Any]):
    fields = {f.name: f for f in dataclasses.fields(cls)}
    unknown = set(data) - set(fields)
    if unknown:
        raise ValueError(f"unknown {cls.__name__} keys: {sorted(unknown)}")
    kwargs = {}
    for key, value in data.items():
        if isinstance(value, list):
            value = tuple(value)
        elif isinstance(value, dict) and key == "version_labels":
            # TOML inline table -> the hashable pair form the frozen
            # dataclass stores.
            value = tuple(sorted((str(k), int(v)) for k, v in value.items()))
        kwargs[key] = value
    return cls(**kwargs)


def validate_model_config_entries(entries, source: str):
    """Shared shape validation for model_config_list entries — ONE rule
    set for startup (--model-config-file) and runtime reloads
    (HandleReloadConfigRequest), so the two paths cannot drift. Raises
    ValueError; returns the entries as a list."""
    seen = set()
    for mc in entries:
        if not mc.name or not mc.base_path:
            raise ValueError(
                f"{source}: every model config needs name and base_path "
                f"(got name={mc.name!r} base_path={mc.base_path!r})"
            )
        if mc.name in seen:
            raise ValueError(f"{source}: duplicate model {mc.name!r}")
        seen.add(mc.name)
    return list(entries)


def apply_batching_parameters(cfg: ServerConfig, path) -> ServerConfig:
    """Map a tensorflow_model_server --batching_parameters_file (text-format
    BatchingParameters, session_bundle_config.proto upstream) onto the
    ServerConfig's batcher knobs, so existing TF-Serving deployments bring
    their tuning file unchanged:

    - allowed_batch_sizes        -> the bucket ladder (upstream rule kept:
                                    when both are set, the largest allowed
                                    size must equal max_batch_size);
    - max_batch_size             -> max_batch_candidates (top bucket);
    - batch_timeout_micros       -> max_wait_us;
    - max_enqueued_batches       -> queue_capacity_candidates (upstream
                                    bounds queued BATCHES; ours bounds
                                    queued candidates, so x max_batch);
    - num_batch_threads          -> completion_workers (upstream's batch
                                    compute threads; device compute here is
                                    the XLA stream, so threads go to
                                    readback/delivery);
    - thread_pool_name, pad_variable_length_inputs: no analog (a named
      shared pool / ragged inputs don't exist here) — ignored, logged.
    """
    import logging

    from google.protobuf import text_format

    from ..proto import serving_apis_pb2 as apis

    log = logging.getLogger("dts_tpu.config")
    bp = text_format.Parse(
        pathlib.Path(path).read_text(), apis.BatchingParameters()
    )
    updates: dict[str, Any] = {}
    max_batch = bp.max_batch_size.value if bp.HasField("max_batch_size") else None
    if max_batch is not None and max_batch <= 0:
        raise ValueError(f"max_batch_size must be positive, got {max_batch}")
    if bp.allowed_batch_sizes:
        buckets = tuple(sorted(int(b) for b in bp.allowed_batch_sizes))
        if any(b <= 0 for b in buckets):
            raise ValueError(f"allowed_batch_sizes must be positive, got {buckets}")
        if max_batch is not None and buckets[-1] != max_batch:
            raise ValueError(
                f"largest allowed_batch_sizes entry ({buckets[-1]}) must equal "
                f"max_batch_size ({max_batch}) — the upstream batching rule"
            )
        updates["buckets"] = buckets
    elif max_batch is not None:
        kept = tuple(b for b in cfg.buckets if b < max_batch)
        updates["buckets"] = kept + (int(max_batch),)
    if bp.HasField("batch_timeout_micros"):
        updates["max_wait_us"] = int(bp.batch_timeout_micros.value)
    if bp.HasField("max_enqueued_batches"):
        top = max_batch or (updates.get("buckets") or cfg.buckets)[-1]
        updates["queue_capacity_candidates"] = int(
            bp.max_enqueued_batches.value * top
        )
    if bp.HasField("num_batch_threads"):
        threads = int(bp.num_batch_threads.value)
        if threads <= 0:
            raise ValueError(f"num_batch_threads must be positive, got {threads}")
        updates["completion_workers"] = threads
    for field in ("thread_pool_name", "pad_variable_length_inputs"):
        if bp.HasField(field):
            log.info("batching parameter %s has no analog here; ignored", field)
    return dataclasses.replace(cfg, **updates)


def load_config(path) -> dict[str, Any]:
    """Parse a TOML file with optional [server] / [client] / [model]
    sections. [model] carries the architecture knobs (ModelConfig) — present
    only when the file sets any, so callers can tell "explicit architecture"
    from "use defaults"."""
    raw = tomllib.loads(pathlib.Path(path).read_text())
    out: dict[str, Any] = {}
    for section, cls in _SECTIONS.items():
        out[section] = _coerce(cls, raw.get(section, {}))
    if "model" in raw:
        out["model"] = _coerce(_model_config_cls(), raw["model"])
    extra = set(raw) - set(_SECTIONS) - {"model"}
    if extra:
        raise ValueError(f"unknown config sections: {sorted(extra)}")
    return out
