"""Version-bridging shims for the jax API surface.

The repo targets the jax the image bakes in; APIs that moved between
releases get ONE canonical import here so hot-path modules never repeat
the try/except dance (and a future jax bump touches one file).
"""

from __future__ import annotations

import jax

try:
    enable_x64 = jax.enable_x64  # newer jax re-exports it at top level
except AttributeError:  # jax 0.4.x keeps the context manager in experimental
    from jax.experimental import enable_x64  # noqa: F401

try:
    from jax import shard_map  # newer jax exports it at top level
except ImportError:  # jax 0.4.x keeps shard_map under experimental
    from jax.experimental.shard_map import shard_map  # noqa: F401

__all__ = ["enable_x64", "shard_map"]
