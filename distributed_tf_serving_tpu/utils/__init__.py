"""Utilities: config, metrics, tracing."""

from .config import ClientConfig, MeshConfig, ServerConfig, load_config
from .metrics import LatencyHistogram, ServerMetrics
from .tracing import PhaseTrace, profile_trace, request_trace

__all__ = [
    "ServerConfig",
    "ClientConfig",
    "MeshConfig",
    "load_config",
    "LatencyHistogram",
    "ServerMetrics",
    "PhaseTrace",
    "profile_trace",
    "request_trace",
]
