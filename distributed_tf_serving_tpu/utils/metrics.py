"""Serving metrics: latency histograms, QPS, per-RPC counters.

The reference's entire metrics system is a synchronized list of per-request
wall times printed as a mean (timeLists, DCNClient.java:44,198-202,234-236).
BASELINE.md's target metric set (p50/p99, QPS/chip) needs percentile-capable
aggregation, so the core here is a fixed-bucket log-scale histogram: O(1)
record, lock-free-ish (GIL-atomic list ops), percentiles from bucket
interpolation, mergeable across RPCs.
"""

from __future__ import annotations

import dataclasses
import math
import threading
import time

# Log-spaced bucket edges: 1us .. ~107s, 12.5% resolution.
_BASE_US = 1.0
_GROWTH = 1.125
_NUM_BUCKETS = 156


def _bucket_index(us: float) -> int:
    if us <= _BASE_US:
        return 0
    return min(int(math.log(us / _BASE_US, _GROWTH)) + 1, _NUM_BUCKETS - 1)


_EDGES_US = [_BASE_US * _GROWTH**i for i in range(_NUM_BUCKETS)]


class LatencyHistogram:
    """Log-bucketed latency histogram with percentile readout."""

    def __init__(self):
        self._counts = [0] * _NUM_BUCKETS
        self._total = 0
        self._sum_us = 0.0
        self._min_us = math.inf
        self._max_us = 0.0
        self._lock = threading.Lock()

    def record(self, seconds: float) -> None:
        us = seconds * 1e6
        with self._lock:
            self._counts[_bucket_index(us)] += 1
            self._total += 1
            self._sum_us += us
            self._min_us = min(self._min_us, us)
            self._max_us = max(self._max_us, us)

    @property
    def count(self) -> int:
        return self._total

    def mean_ms(self) -> float:
        return self._sum_us / self._total / 1e3 if self._total else 0.0

    def percentile_ms(self, q: float) -> float:
        """q in [0, 100]; linear interpolation inside the winning bucket."""
        with self._lock:
            if self._total == 0:
                return 0.0
            target = q / 100.0 * self._total
            acc = 0
            for i, c in enumerate(self._counts):
                if acc + c >= target and c > 0:
                    lo = _EDGES_US[i - 1] if i > 0 else 0.0
                    hi = _EDGES_US[i]
                    frac = (target - acc) / c
                    val = lo + (hi - lo) * frac
                    return min(max(val, self._min_us), self._max_us) / 1e3
                acc += c
            return self._max_us / 1e3

    def snapshot(self) -> dict:
        return {
            "count": self.count,
            "mean_ms": round(self.mean_ms(), 3),
            "p50_ms": round(self.percentile_ms(50), 3),
            "p90_ms": round(self.percentile_ms(90), 3),
            "p99_ms": round(self.percentile_ms(99), 3),
        }

    def prometheus_buckets(self) -> tuple[list[tuple[float, int]], float, int]:
        """(cumulative (le_us, count) pairs, sum_us, total) for Prometheus
        histogram exposition. Trimmed past the last occupied bucket — the
        +Inf bucket the caller appends covers the remainder — so an idle
        RPC costs 1 line, not 156."""
        with self._lock:
            counts = list(self._counts)
            total, sum_us = self._total, self._sum_us
        last = max((i for i, c in enumerate(counts) if c), default=-1)
        out, acc = [], 0
        for i in range(last + 1):
            acc += counts[i]
            out.append((_EDGES_US[i], acc))
        return out, sum_us, total


@dataclasses.dataclass
class RpcMetrics:
    latency: LatencyHistogram = dataclasses.field(default_factory=LatencyHistogram)
    ok: int = 0
    errors: int = 0


class ServerMetrics:
    """Per-RPC latency/outcome metrics + a QPS window, exported as one dict
    (the /metrics analog; the reference had only a final stdout mean)."""

    def __init__(self):
        self._rpcs: dict[str, RpcMetrics] = {}
        self._lock = threading.Lock()
        self._start = time.monotonic()

    def rpc(self, name: str) -> RpcMetrics:
        with self._lock:
            if name not in self._rpcs:
                self._rpcs[name] = RpcMetrics()
            return self._rpcs[name]

    def observe(self, name: str, seconds: float, ok: bool) -> None:
        m = self.rpc(name)
        m.latency.record(seconds)
        with self._lock:  # counters race across handler threads otherwise
            if ok:
                m.ok += 1
            else:
                m.errors += 1

    def snapshot(self, batcher_stats=None) -> dict:
        uptime = time.monotonic() - self._start
        out: dict = {"uptime_s": round(uptime, 1), "rpcs": {}}
        total = 0
        with self._lock:  # rpc() may insert concurrently
            items = sorted(self._rpcs.items())
        for name, m in items:
            out["rpcs"][name] = {
                **m.latency.snapshot(),
                "ok": m.ok,
                "errors": m.errors,
            }
            total += m.ok + m.errors
        out["qps"] = round(total / uptime, 2) if uptime > 0 else 0.0
        if batcher_stats is not None:
            out["batcher"] = {
                "batches": batcher_stats.batches,
                "requests": batcher_stats.requests,
                "mean_occupancy": round(batcher_stats.mean_occupancy, 3),
                "mean_requests_per_batch": round(batcher_stats.mean_requests_per_batch, 2),
                "max_queue_depth": batcher_stats.max_queue_depth,
                # D2H transfer attribution (output compaction + async
                # readback pipeline): actual wire bytes fetched, the
                # full-fp32 all-outputs baseline they're charged against,
                # and how much of the in-flight transfer window the
                # completers actually blocked on.
                "bytes_downloaded": batcher_stats.bytes_downloaded,
                "bytes_download_full_f32": batcher_stats.bytes_download_full_f32,
                "download_compaction_ratio": round(
                    batcher_stats.download_compaction_ratio, 2
                ),
                "readback_overlap_fraction": round(
                    batcher_stats.readback_overlap_fraction, 3
                ),
                "topk_batches": batcher_stats.topk_batches,
                # Resilience layer: queued work shed because its propagated
                # client deadline expired before a dispatch slot opened.
                "deadline_sheds": getattr(batcher_stats, "deadline_sheds", 0),
            }
        return out

    def prometheus_text(self, batcher_stats=None) -> str:
        """Prometheus exposition (text format 0.0.4) of the same data
        snapshot() serves as JSON. Metric names mirror tensorflow_model_
        server's monitoring surface (`:tensorflow:serving:request_count` /
        `:tensorflow:serving:request_latency`, microsecond buckets) so
        existing TF-Serving dashboards and alert rules scrape unchanged;
        batcher gauges are framework-native and ride the dts_tpu_ prefix."""
        rc, rl = ":tensorflow:serving:request_count", ":tensorflow:serving:request_latency"
        lines = [f"# TYPE {rc} counter"]
        with self._lock:
            items = sorted(self._rpcs.items())
        for name, m in items:
            lines.append(f'{rc}{{entrypoint="{name}",status="OK"}} {m.ok}')
            if m.errors:
                lines.append(f'{rc}{{entrypoint="{name}",status="ERROR"}} {m.errors}')
        lines.append(f"# TYPE {rl} histogram")
        for name, m in items:
            buckets, sum_us, total = m.latency.prometheus_buckets()
            for le_us, cum in buckets:
                lines.append(
                    f'{rl}_bucket{{entrypoint="{name}",le="{le_us:.6g}"}} {cum}'
                )
            lines.append(f'{rl}_bucket{{entrypoint="{name}",le="+Inf"}} {total}')
            lines.append(f'{rl}_sum{{entrypoint="{name}"}} {sum_us:.6g}')
            lines.append(f'{rl}_count{{entrypoint="{name}"}} {total}')
        if batcher_stats is not None:
            for metric, kind, value in (
                ("dts_tpu_batcher_batches_total", "counter", batcher_stats.batches),
                ("dts_tpu_batcher_requests_total", "counter", batcher_stats.requests),
                ("dts_tpu_batcher_mean_occupancy", "gauge",
                 round(batcher_stats.mean_occupancy, 4)),
                ("dts_tpu_batcher_mean_requests_per_batch", "gauge",
                 round(batcher_stats.mean_requests_per_batch, 3)),
                ("dts_tpu_batcher_max_queue_depth", "gauge",
                 batcher_stats.max_queue_depth),
                ("dts_tpu_batcher_bytes_downloaded_total", "counter",
                 batcher_stats.bytes_downloaded),
                ("dts_tpu_batcher_bytes_download_full_f32_total", "counter",
                 batcher_stats.bytes_download_full_f32),
                ("dts_tpu_batcher_topk_batches_total", "counter",
                 batcher_stats.topk_batches),
                ("dts_tpu_batcher_readback_overlap_fraction", "gauge",
                 round(batcher_stats.readback_overlap_fraction, 4)),
                ("dts_tpu_batcher_deadline_sheds_total", "counter",
                 getattr(batcher_stats, "deadline_sheds", 0)),
            ):
                lines.append(f"# TYPE {metric} {kind}")
                lines.append(f"{metric} {value}")
        return "\n".join(lines) + "\n"
