"""Serving metrics: latency histograms, QPS, per-RPC counters.

The reference's entire metrics system is a synchronized list of per-request
wall times printed as a mean (timeLists, DCNClient.java:44,198-202,234-236).
BASELINE.md's target metric set (p50/p99, QPS/chip) needs percentile-capable
aggregation, so the core here is a fixed-bucket log-scale histogram: O(1)
record, lock-free-ish (GIL-atomic list ops), percentiles from bucket
interpolation, mergeable across RPCs.

Two time horizons per metric (ISSUE 3): LIFETIME aggregates (unchanged —
the totals dashboards trend on) and ROLLING WINDOWS — sliding-window QPS
and windowed p50/p99 over the last `window_s` seconds, so `/monitoring`
answers "what is the server doing NOW" instead of a lifetime average that
decays toward 0 on an idle server. Both surfaces carry per-model labels
when the transport adapters pass the resolved model name.
"""

from __future__ import annotations

import dataclasses
import math
import threading
import time

# Log-spaced bucket edges: 1us .. ~107s, 12.5% resolution.
_BASE_US = 1.0
_GROWTH = 1.125
_NUM_BUCKETS = 156


def _bucket_index(us: float) -> int:
    if us <= _BASE_US:
        return 0
    return min(int(math.log(us / _BASE_US, _GROWTH)) + 1, _NUM_BUCKETS - 1)


_EDGES_US = [_BASE_US * _GROWTH**i for i in range(_NUM_BUCKETS)]


def _percentile_ms(
    counts: list[int], total: int, min_us: float, max_us: float, q: float
) -> float:
    """q in [0, 100] over a consistent (counts, total) snapshot; linear
    interpolation inside the winning bucket. Shared by the lifetime
    histogram and the rolling-window slices (merged counts)."""
    if total == 0:
        return 0.0
    target = q / 100.0 * total
    acc = 0
    for i, c in enumerate(counts):
        if acc + c >= target and c > 0:
            lo = _EDGES_US[i - 1] if i > 0 else 0.0
            hi = _EDGES_US[i]
            frac = (target - acc) / c
            val = lo + (hi - lo) * frac
            return min(max(val, min_us), max_us) / 1e3
        acc += c
    return max_us / 1e3


class LatencyHistogram:
    """Log-bucketed latency histogram with percentile readout."""

    def __init__(self):
        self._counts = [0] * _NUM_BUCKETS
        self._total = 0
        self._sum_us = 0.0
        self._min_us = math.inf
        self._max_us = 0.0
        self._lock = threading.Lock()

    def record(self, seconds: float) -> None:
        us = seconds * 1e6
        with self._lock:
            self._counts[_bucket_index(us)] += 1
            self._total += 1
            self._sum_us += us
            self._min_us = min(self._min_us, us)
            self._max_us = max(self._max_us, us)

    @property
    def count(self) -> int:
        with self._lock:  # pairs count with the same instant's sums
            return self._total

    def mean_ms(self) -> float:
        # total and sum read under ONE lock: a snapshot racing a record()
        # must never pair a new count with an old sum (ISSUE 3 satellite).
        with self._lock:
            return self._sum_us / self._total / 1e3 if self._total else 0.0

    def _state(self) -> tuple[list[int], int, float, float, float]:
        """One consistent copy of the mutable state."""
        with self._lock:
            return (
                list(self._counts), self._total, self._sum_us,
                self._min_us, self._max_us,
            )

    def percentile_ms(self, q: float) -> float:
        counts, total, _sum_us, min_us, max_us = self._state()
        return _percentile_ms(counts, total, min_us, max_us, q)

    def snapshot(self) -> dict:
        # One locked copy feeds count/mean AND every percentile, so the
        # block is internally consistent even mid-record.
        counts, total, sum_us, min_us, max_us = self._state()
        return {
            "count": total,
            "mean_ms": round(sum_us / total / 1e3 if total else 0.0, 3),
            "p50_ms": round(_percentile_ms(counts, total, min_us, max_us, 50), 3),
            "p90_ms": round(_percentile_ms(counts, total, min_us, max_us, 90), 3),
            "p99_ms": round(_percentile_ms(counts, total, min_us, max_us, 99), 3),
        }

    def prometheus_buckets(self) -> tuple[list[tuple[float, int]], float, int]:
        """(cumulative (le_us, count) pairs, sum_us, total) for Prometheus
        histogram exposition. Trimmed past the last occupied bucket — the
        +Inf bucket the caller appends covers the remainder — so an idle
        RPC costs 1 line, not 156."""
        with self._lock:
            counts = list(self._counts)
            total, sum_us = self._total, self._sum_us
        last = max((i for i, c in enumerate(counts) if c), default=-1)
        out, acc = [], 0
        for i in range(last + 1):
            acc += counts[i]
            out.append((_EDGES_US[i], acc))
        return out, sum_us, total


class WindowedLatency:
    """Sliding-window latency + rate over the last `window_s` seconds.

    A ring of `slices` sub-histograms, each covering window_s/slices of
    wall time; record() lands in the current slice (lazily reset when its
    slot is reused), and readout merges only the slices still inside the
    window. O(1) record, bounded memory, no background thread — the
    standard cheap approximation to a true sliding window (granularity =
    one slice; with the 60s/6-slice default, 10s).
    """

    def __init__(
        self,
        window_s: float = 60.0,
        slices: int = 6,
        clock=time.monotonic,
    ):
        self.window_s = float(window_s)
        self.slices = max(2, int(slices))
        self.slice_s = self.window_s / self.slices
        self._clock = clock
        self._created = clock()
        self._lock = threading.Lock()
        self._counts = [[0] * _NUM_BUCKETS for _ in range(self.slices)]
        self._totals = [0] * self.slices
        self._sums_us = [0.0] * self.slices
        self._mins_us = [math.inf] * self.slices
        self._maxs_us = [0.0] * self.slices
        self._epochs = [-1] * self.slices  # which slice-epoch each slot holds

    def _slot(self, now: float) -> int:
        """Current slot index, reset if its epoch is stale. Caller holds
        the lock."""
        epoch = int(now / self.slice_s)
        idx = epoch % self.slices
        if self._epochs[idx] != epoch:
            self._epochs[idx] = epoch
            self._counts[idx] = [0] * _NUM_BUCKETS
            self._totals[idx] = 0
            self._sums_us[idx] = 0.0
            self._mins_us[idx] = math.inf
            self._maxs_us[idx] = 0.0
        return idx

    def record(self, seconds: float) -> None:
        us = seconds * 1e6
        with self._lock:
            idx = self._slot(self._clock())
            self._counts[idx][_bucket_index(us)] += 1
            self._totals[idx] += 1
            self._sums_us[idx] += us
            self._mins_us[idx] = min(self._mins_us[idx], us)
            self._maxs_us[idx] = max(self._maxs_us[idx], us)

    def _merged(self) -> tuple[list[int], int, float, float, float]:
        """Merge the in-window slices into one consistent histogram."""
        with self._lock:
            now = self._clock()
            current_epoch = int(now / self.slice_s)
            counts = [0] * _NUM_BUCKETS
            total, sum_us = 0, 0.0
            min_us, max_us = math.inf, 0.0
            for idx in range(self.slices):
                # In-window = one of the last `slices` epochs (the current
                # partial slice counts; the slot about to be recycled does
                # not).
                if current_epoch - self._epochs[idx] >= self.slices:
                    continue
                if self._epochs[idx] < 0:
                    continue
                sl = self._counts[idx]
                for i, c in enumerate(sl):
                    if c:
                        counts[i] += c
                total += self._totals[idx]
                sum_us += self._sums_us[idx]
                min_us = min(min_us, self._mins_us[idx])
                max_us = max(max_us, self._maxs_us[idx])
            return counts, total, sum_us, min_us, max_us

    def count(self) -> int:
        return self._merged()[1]

    def effective_window_s(self) -> float:
        """Rate divisor: the nominal window, shrunk while the recorder is
        YOUNGER than it (a server 8 s old serving 100 req/s must report
        ~100 qps, not 800/60) and floored at 1 s so a burst in the first
        milliseconds doesn't quote an absurd spike."""
        return min(self.window_s, max(self._clock() - self._created, 1.0))

    def qps(self) -> float:
        return self._merged()[1] / self.effective_window_s()

    def snapshot(self) -> dict:
        counts, total, sum_us, min_us, max_us = self._merged()
        return {
            "window_s": self.window_s,
            "count": total,
            "qps": round(total / self.effective_window_s(), 2),
            "mean_ms": round(sum_us / total / 1e3 if total else 0.0, 3),
            "p50_ms": round(_percentile_ms(counts, total, min_us, max_us, 50), 3),
            "p99_ms": round(_percentile_ms(counts, total, min_us, max_us, 99), 3),
        }

    # ------------------------------------------------------------ wire form
    # The fleet aggregator (ISSUE 18) ships merged windows between
    # processes as JSON: sparse bucket counts keyed by bucket index (the
    # edges are a shared constant on both sides), so a member's whole
    # window is a few dozen ints, and the router can re-merge any number
    # of members' wires into one fleet histogram with exact counts.

    def to_dict(self) -> dict:
        counts, total, sum_us, min_us, max_us = self._merged()
        return {
            "window_s": self.window_s,
            "effective_window_s": round(self.effective_window_s(), 3),
            "total": total,
            "sum_us": round(sum_us, 1),
            "min_us": None if total == 0 else round(min_us, 1),
            "max_us": round(max_us, 1),
            "buckets": {str(i): c for i, c in enumerate(counts) if c},
        }

    @staticmethod
    def from_dict(d: dict) -> tuple[list[int], int, float, float, float]:
        """Wire dict back to a merged-histogram state tuple — the same
        shape `_merged()` returns, so `_percentile_ms` works on it."""
        counts = [0] * _NUM_BUCKETS
        for k, c in (d.get("buckets") or {}).items():
            i = int(k)
            if 0 <= i < _NUM_BUCKETS:
                counts[i] += int(c)
        total = int(d.get("total") or 0)
        sum_us = float(d.get("sum_us") or 0.0)
        min_us = d.get("min_us")
        min_us = math.inf if min_us is None else float(min_us)
        max_us = float(d.get("max_us") or 0.0)
        return counts, total, sum_us, min_us, max_us

    @staticmethod
    def merge_dicts(wires: list[dict]) -> dict:
        """Sum several wire dicts into one (the fleet aggregate). The
        merged rate uses each member's own effective window — members
        report their local qps; the aggregate is the sum."""
        counts = [0] * _NUM_BUCKETS
        total, sum_us = 0, 0.0
        min_us, max_us = math.inf, 0.0
        window_s, eff_s, qps = 0.0, 0.0, 0.0
        for w in wires:
            c, t, s, mn, mx = WindowedLatency.from_dict(w)
            for i, v in enumerate(c):
                if v:
                    counts[i] += v
            total += t
            sum_us += s
            min_us = min(min_us, mn)
            max_us = max(max_us, mx)
            window_s = max(window_s, float(w.get("window_s") or 0.0))
            e = float(w.get("effective_window_s") or 0.0)
            eff_s = max(eff_s, e)
            if e > 0:
                qps += t / e
        return {
            "window_s": window_s,
            "effective_window_s": round(eff_s, 3),
            "total": total,
            "sum_us": round(sum_us, 1),
            "min_us": None if total == 0 else round(min_us, 1),
            "max_us": round(max_us, 1),
            "qps": round(qps, 3),
            "buckets": {str(i): c for i, c in enumerate(counts) if c},
        }

    @staticmethod
    def wire_stats(wire: dict) -> dict:
        """Human-facing summary of a wire dict (member or merged)."""
        counts, total, sum_us, min_us, max_us = WindowedLatency.from_dict(wire)
        eff = float(wire.get("effective_window_s") or 0.0)
        qps = wire.get("qps")
        if qps is None:
            qps = total / eff if eff > 0 else 0.0
        return {
            "count": total,
            "qps": round(float(qps), 3),
            "mean_ms": round(sum_us / total / 1e3 if total else 0.0, 3),
            "p50_ms": round(_percentile_ms(counts, total, min_us, max_us, 50), 3),
            "p99_ms": round(_percentile_ms(counts, total, min_us, max_us, 99), 3),
        }


@dataclasses.dataclass
class RpcMetrics:
    latency: LatencyHistogram = dataclasses.field(default_factory=LatencyHistogram)
    window: WindowedLatency = dataclasses.field(default_factory=WindowedLatency)
    ok: int = 0
    errors: int = 0


def escape_label_value(value) -> str:
    """Prometheus text-format 0.0.4 label-value escaping: backslash, double
    quote, and line feed must be escaped or the exposition line is
    malformed (ISSUE 3 satellite — a model named `he"llo` or a path-ish
    entrypoint must not corrupt the scrape)."""
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


# Curated HELP text for the families whose meaning is not readable off the
# name; everything else derives a serviceable line from the name itself.
# Every family in the aggregated exposition goes through _family_lines, so
# the lint invariant (tools/check_prom.py: HELP + TYPE present per family,
# no family declared twice) holds by construction.
_HELP = {
    ":tensorflow:serving:request_count":
        "Requests per entrypoint and status (TF-Serving-compatible name)",
    ":tensorflow:serving:request_latency":
        "Request latency in microseconds (TF-Serving-compatible name)",
    "dts_tpu_qps_window": "Rolling-window overall request rate",
    "dts_tpu_cache_row_hits_total":
        "Candidate rows answered from the row-granular score cache "
        "instead of executing on device",
    "dts_tpu_cache_row_misses_total":
        "Candidate rows not in the row cache (cold — this batch executes "
        "them and fills on completion)",
    "dts_tpu_cache_row_coalesced_total":
        "Cold rows that joined another in-flight batch's fill instead of "
        "executing again (per-row single-flight)",
    "dts_tpu_cache_row_stale_serves_total":
        "Rows served past TTL inside the brownout stale window "
        "(responses touching them are marked degraded, never re-filled)",
    "dts_tpu_cache_row_evictions_total":
        "Row entries evicted by the LRU entry/byte bounds",
    "dts_tpu_cache_row_expirations_total":
        "Row entries dropped on sight past their TTL (and any stale "
        "window)",
    "dts_tpu_cache_row_invalidations_total":
        "Row entries dropped by generation invalidation (version swaps, "
        "operator flushes)",
    "dts_tpu_cache_row_fills_total":
        "Executed rows stored into the row cache",
    "dts_tpu_cache_row_hit_rate":
        "row hits / (row hits + row misses) over the process lifetime",
    "dts_tpu_cache_row_entries":
        "Live row entries in the row-granular store",
    "dts_tpu_cache_row_value_bytes":
        "Bytes of cached per-row output values in the row-granular store",
    "dts_tpu_cache_rows_requested_total":
        "Rows that entered cold-row extraction (the denominator of the "
        "row plane's executed-vs-requested ratio)",
    "dts_tpu_cache_rows_executed_total":
        "Rows actually packed, bucketed, and dispatched to the device "
        "after row-cache extraction",
    "dts_tpu_cache_rows_executed_fraction":
        "rows_executed / rows_requested — the row-granular cache's "
        "headline: well below 1.0 at zipfian skew",
    "dts_tpu_quality_score":
        "Predicted-score distribution per model and version",
    "dts_tpu_quality_drift_psi":
        "Population Stability Index of the windowed score distribution "
        "vs the pinned reference (kind=reference) or the concurrently "
        "serving previous version (kind=version_pair)",
    "dts_tpu_quality_drift_js":
        "Jensen-Shannon divergence (base 2) companion to the PSI series",
    "dts_tpu_quality_auc":
        "Windowed AUC over label-feedback (score, label) joins",
    "dts_tpu_quality_calibration_error":
        "Count-weighted |mean predicted - observed rate| over predicted-"
        "probability deciles (expected calibration error)",
    "dts_tpu_lifecycle_state":
        "Continuous-freshness state machine, one-hot over idle/canary/"
        "promoting/rolled_back",
    "dts_tpu_lifecycle_canary_fraction":
        "Share of default-lane traffic currently routed to the canary "
        "version (probe-lane traffic always routes to it)",
    "dts_tpu_lifecycle_routed_total":
        "Requests the canary router resolved, labeled by target version "
        "role",
    "dts_tpu_lifecycle_blacklisted_versions":
        "Versions the watcher excludes from reconcile after a rollback",
    "dts_tpu_pipeline_in_flight":
        "Batches currently executing or awaiting D2H readback "
        "(the continuous-batching pipeline's live occupancy)",
    "dts_tpu_pipeline_readback_overlap_fraction":
        "Fraction of the in-flight D2H window the completers did NOT "
        "block on (1.0 = readback fully hidden behind other work)",
    "dts_tpu_pipeline_window_waits_total":
        "Times the dispatch thread waited for the k-deep in-flight "
        "window to open before issuing the next batch",
    "dts_tpu_recovery_state":
        "Device-failure recovery state machine, one-hot over serving/"
        "quarantined/reinit/replay",
    "dts_tpu_recovery_replayed_items_total":
        "In-flight/queued requests re-dispatched by the replay path "
        "instead of failed on device death",
    "dts_tpu_recovery_poisoned_requests_total":
        "Requests isolated by bisection as deterministic executor "
        "killers and failed alone (INVALID_ARGUMENT)",
    "dts_tpu_recovery_mttr_mean_seconds":
        "Mean recovery-cycle duration over the retained MTTR history ring",
    "dts_tpu_kernel_quantized_batches_total":
        "Batches served by the int8 weight-quantized executables",
    "dts_tpu_kernel_pallas_batches_total":
        "Batches served by the fused Pallas serving kernel",
    "dts_tpu_kernel_bucket_quantized":
        "Per-bucket autotune decision: 1 = int8 weight path enabled",
    "dts_tpu_kernel_bucket_pallas":
        "Per-bucket autotune decision: 1 = fused Pallas kernel enabled",
    "dts_tpu_kernel_variant_speedup":
        "Measured step-time speedup of a kernel variant vs the XLA/f32 "
        "baseline at one bucket (autotune harness, live device)",
    "dts_tpu_recovery_last_cycle_seconds":
        "Duration of the last completed quarantine->reinit->replay "
        "cycle (the live MTTR evidence)",
    "dts_tpu_mesh_data_pad_rows_total":
        "Zero rows the sharded executor added to make batches divisible "
        "by the mesh data axis (sliced off on readback)",
    "dts_tpu_mesh_device_busy_fraction":
        "Per-device busy fraction over the utilization window (SPMD "
        "attribution: every batch occupies all mesh chips, so each "
        "device carries the ledger's busy timeline)",
    "dts_tpu_elastic_data_parallel":
        "Data-axis degree of the CURRENT serving split (elastic mesh "
        "serving resizes this at runtime)",
    "dts_tpu_elastic_model_parallel":
        "Model-axis degree of the CURRENT serving split",
    "dts_tpu_elastic_splits":
        "Rungs in the configured split ladder",
    "dts_tpu_elastic_switches_total":
        "Completed split switches, labeled by direction (up = toward "
        "the data-parallel/throughput end, down = toward the "
        "model-parallel/latency end)",
    "dts_tpu_elastic_switch_drain_pending":
        "1 while the last switch's old split still has batches in "
        "flight (the hitless-drain barrier; further switches wait)",
    "dts_tpu_elastic_last_drain_seconds":
        "How long the last switch's old split took to drain its "
        "in-flight batches (0 = switched idle)",
    "dts_tpu_elastic_controller_ticks_total":
        "Elastic controller decision ticks (opportunistic — dispatches "
        "and monitoring scrapes drive them)",
    "dts_tpu_elastic_holds_total":
        "Switch decisions deferred, labeled by reason (dwell = inside "
        "the anti-flap floor; drain = previous switch still draining)",
    "dts_tpu_elastic_load_ewma":
        "The controller's load signal: EWMA of max(queue fraction, "
        "dispatched-bucket occupancy)",
    "dts_tpu_elastic_split_batches_total":
        "Batches served per ladder rung over the process lifetime",
    "dts_tpu_elastic_split_in_flight":
        "Batches currently executing or awaiting readback per ladder "
        "rung (the switch drain barrier reads the old rung's gauge)",
    "dts_tpu_cascade_requests_total":
        "Requests that entered the multi-stage ranking cascade (stage-1 "
        "prune + stage-2 rank in one RPC)",
    "dts_tpu_cascade_fallbacks_total":
        "Cascade requests that fell back to a single full-model pass "
        "(stage-1 resolve/submit failure — e.g. mid-hot-swap — or an "
        "ineligible composition detected at run time); the request "
        "still succeeds",
    "dts_tpu_cascade_stage1_failures_total":
        "Stage-1 submits that raised and were absorbed by the full-pass "
        "fallback (a version hot-swap window, typically)",
    "dts_tpu_cascade_host_prunes_total":
        "Prunes computed host-side from the full stage-1 score vector "
        "because the on-device top-k variant did not arm for that batch",
    "dts_tpu_cascade_rows_total":
        "Candidate rows through the cascade by disposition: requested = "
        "all rows entering stage 1, survivor = rows selected for stage "
        "2, pruned = rows answered with their stage-1 score",
    "dts_tpu_cascade_rows_ranked_total":
        "Rows actually scored by the full model (survivors, plus every "
        "row of fallback requests) — the numerator of the goodput win: "
        "rank_fraction = ranked / requested",
    "dts_tpu_cascade_zero_survivor_requests_total":
        "Requests whose score threshold eliminated every candidate "
        "(answered entirely from stage-1 scores; stage 2 skipped)",
    "dts_tpu_cascade_stage_seconds_total":
        "Wall time per cascade stage (stage1 = cheap-model submit, "
        "prune = survivor selection + gather, stage2 = full-model "
        "submit over survivors)",
    "dts_tpu_cascade_survivor_fraction":
        "Observed survivor_rows / rows_requested over the process "
        "lifetime (the configured target is survivor_k or "
        "survivor_fraction)",
    "dts_tpu_cascade_rank_fraction":
        "Observed rows_ranked / rows_requested — under 1.0 means the "
        "full model is doing less work than a cascade-off server",
    "dts_tpu_cascade_survivor_bucket_total":
        "Stage-2 submits by the padded batch rung the survivors packed "
        "into (the cascade's win shows as survivor traffic landing in "
        "smaller rungs than the candidate batches)",
    "dts_tpu_fleet_router_integrity_audits_total":
        "Router-side two-replica bit-identity audits by outcome: run = "
        "sampled forwards fanned to two replicas, disagreed = the score "
        "bytes differed, suspect_marked = a third replica broke the tie "
        "and the minority was busy-biased in the scoreboard",
    "dts_tpu_integrity_wire_inputs_verified_total":
        "Requests whose input tensors carried an x-dts-input-crc stamp "
        "and matched it at decode (CRC32C over dtype/shape + payload "
        "bytes)",
    "dts_tpu_integrity_wire_inputs_rejected_total":
        "Requests failed INVALID_ARGUMENT at decode because the input "
        "bytes did not match the client's checksum stamp — corruption "
        "in transit, caught before the batch formed (only the damaged "
        "request fails)",
    "dts_tpu_integrity_wire_responses_stamped_total":
        "Responses stamped with an x-dts-score-crc trailing-metadata "
        "sidecar for opted-in clients to verify before merging scores",
    "dts_tpu_integrity_screen_trips_total":
        "Score rows the post-readback sanity screen rejected (NaN/Inf, "
        "or outside the configured plausible range); each trip fails "
        "only its own request while batchmates deliver",
    "dts_tpu_integrity_screen_window_trips":
        "Screen trips inside the current escalation window — crossing "
        "screen_trips_per_window hands the group to the recovery "
        "plane's output_corrupt cycle",
    "dts_tpu_integrity_shadow_batches_total":
        "Batches re-executed through the same jitted entry and "
        "compared bit-identically on host (sampled by shadow_fraction "
        "plus operator-forced audits)",
    "dts_tpu_integrity_shadow_mismatches_total":
        "Shadow re-executions whose bytes differed from the primary "
        "pass — same program, same inputs, different bits: the silent-"
        "corruption signature (escalates to recovery + gossips "
        "suspect)",
    "dts_tpu_integrity_audits_requested_total":
        "Operator-forced shadow verifications requested via POST "
        "/integrityz/audit",
    "dts_tpu_integrity_audits_run_total":
        "Operator-forced shadow verifications actually consumed by a "
        "dispatched batch",
    "dts_tpu_integrity_escalations_total":
        "Detections the plane escalated into the recovery controller's "
        "output_corrupt cycle (screen-trip threshold or shadow "
        "mismatch)",
    "dts_tpu_integrity_suspect":
        "1 while this replica's own shadow verification has it marked "
        "suspect (also gossiped in the fleet record so routers steer "
        "around it); clears after suspect_clear_passes clean compares",
    "dts_tpu_fleet_agg_qps":
        "Fleet-aggregated rolling request rate: the sum of member-"
        "reported windowed qps (scraped /monitoring wires; gossip-"
        "piggybacked summaries when a member is scrape-unreachable)",
    "dts_tpu_fleet_agg_latency_ms":
        "Fleet windowed latency quantiles from the merged member bucket "
        "counts (an exact histogram merge, not an average of member "
        "percentiles)",
    "dts_tpu_fleet_agg_requests":
        "Sum of member-reported lifetime requests (gauge: member churn "
        "and restarts can lower it)",
    "dts_tpu_fleet_agg_errors":
        "Sum of member-reported lifetime errors (gauge: member churn "
        "and restarts can lower it)",
    "dts_tpu_fleet_agg_members":
        "Members contributing to the current fleet aggregate",
    "dts_tpu_fleet_agg_members_degraded":
        "Members whose contribution fell back to the gossip-piggybacked "
        "summary because the /monitoring scrape failed",
    "dts_tpu_fleet_agg_member_qps":
        "Per-member windowed request rate as the router aggregated it",
    "dts_tpu_slo_latency_target_ms":
        "Configured latency SLO target: a request is `good` for the "
        "latency SLI when it completes under this",
    "dts_tpu_slo_objective":
        "Configured good-fraction objective per SLO",
    "dts_tpu_slo_burn_rate":
        "Error-budget burn rate per SLO and window: bad fraction over "
        "the window divided by the budget (1 - objective); 1.0 consumes "
        "the budget exactly at the sustainable rate",
    "dts_tpu_slo_budget_remaining":
        "Fraction of the long-window error budget not yet consumed",
    "dts_tpu_slo_breached":
        "1 while BOTH burn windows of some SLO exceed the fast "
        "threshold (the multi-window page condition; breaching traces "
        "are force-kept via the slo.burn span annotation)",
    "dts_tpu_slo_breaches_total":
        "Breach episodes since the monitor started (0->1 transitions "
        "of dts_tpu_slo_breached)",
}


def _family_lines(lines: list, name: str, kind: str) -> None:
    """Append the # HELP + # TYPE pair declaring a metric family. The ONE
    way families enter the exposition: the Prometheus lint requires a
    HELP and TYPE line for every family and forbids re-declaration, and
    text-format HELP must escape backslash and line feed."""
    text = (
        _HELP.get(name, name.replace("_", " ").strip())
        .replace("\\", "\\\\")
        .replace("\n", "\\n")
    )
    lines.append(f"# HELP {name} {text}")
    lines.append(f"# TYPE {name} {kind}")


class ServerMetrics:
    """Per-RPC latency/outcome metrics + rolling windows, exported as one
    dict (the /monitoring analog; the reference had only a final stdout
    mean). `observe(..., model=...)` additionally aggregates under the
    resolved model name, so both surfaces carry per-model labels."""

    # Per-model series are keyed on CLIENT-SUPPLIED model names (a
    # NOT_FOUND still observes under the name it asked for), so the key
    # space must be bounded or a fuzzer's ever-new names would grow
    # memory and scrape cardinality without limit. Real deployments serve
    # a handful of models; past the cap, overflow traffic aggregates
    # under one sentinel label instead of allocating new series.
    MAX_MODEL_LABELS = 64
    OVERFLOW_MODEL = "_other"

    def __init__(self, window_s: float = 60.0, clock=time.monotonic):
        self.window_s = float(window_s)
        self._clock = clock
        self._rpcs: dict[str, RpcMetrics] = {}
        self._models: dict[tuple[str, str], RpcMetrics] = {}
        self._model_names: set[str] = set()
        self._lock = threading.Lock()
        self._start = clock()

    def _new_rpc_metrics(self) -> RpcMetrics:
        return RpcMetrics(
            window=WindowedLatency(window_s=self.window_s, clock=self._clock)
        )

    def rpc(self, name: str) -> RpcMetrics:
        with self._lock:
            if name not in self._rpcs:
                self._rpcs[name] = self._new_rpc_metrics()
            return self._rpcs[name]

    def _model_rpc(self, name: str, model: str) -> RpcMetrics:
        with self._lock:
            if (
                model not in self._model_names
                and len(self._model_names) >= self.MAX_MODEL_LABELS
            ):
                model = self.OVERFLOW_MODEL
            self._model_names.add(model)
            key = (name, model)
            if key not in self._models:
                self._models[key] = self._new_rpc_metrics()
            return self._models[key]

    def observe(
        self, name: str, seconds: float, ok: bool, model: str | None = None
    ) -> None:
        targets = [self.rpc(name)]
        if model:
            targets.append(self._model_rpc(name, model))
        for m in targets:
            m.latency.record(seconds)
            m.window.record(seconds)
            with self._lock:  # counters race across handler threads otherwise
                if ok:
                    m.ok += 1
                else:
                    m.errors += 1

    @staticmethod
    def _rpc_block(m: RpcMetrics) -> tuple[dict, int]:
        """ONE construction of the per-entrypoint stats block — lifetime
        histogram + counters + the rolling-window horizon — shared by the
        aggregate and per-model surfaces so they can never drift. Returns
        (block, windowed count) so callers never re-merge the window
        slices for a count this snapshot already produced."""
        win = m.window.snapshot()
        block = {
            **m.latency.snapshot(),
            "ok": m.ok,
            "errors": m.errors,
            # Rolling horizon next to the lifetime values: what this
            # entrypoint is doing NOW (windowed qps + percentiles).
            "window": {
                "qps": win["qps"],
                "p50_ms": win["p50_ms"],
                "p99_ms": win["p99_ms"],
            },
        }
        return block, win["count"]

    def snapshot(self, batcher_stats=None) -> dict:
        uptime = self._clock() - self._start
        out: dict = {
            "uptime_s": round(uptime, 1),
            "window_s": self.window_s,
            "rpcs": {},
        }
        total = 0
        window_count = 0
        with self._lock:  # rpc() may insert concurrently
            items = sorted(self._rpcs.items())
            model_items = sorted(self._models.items())
        for name, m in items:
            out["rpcs"][name], win_count = self._rpc_block(m)
            total += m.ok + m.errors
            window_count += win_count
        if model_items:
            models: dict = {}
            for (name, model), m in model_items:
                models.setdefault(model, {})[name] = self._rpc_block(m)[0]
            out["models"] = models
        # `qps` is the ROLLING rate (what the server is doing now); the
        # lifetime average — which decays toward 0 on an idle server and
        # under-reports after any idle stretch — stays visible as
        # qps_lifetime (ISSUE 3 satellite). The divisor shrinks while the
        # server is younger than the window (see effective_window_s).
        out["qps"] = round(
            window_count / min(self.window_s, max(uptime, 1.0)), 2
        )
        out["qps_lifetime"] = round(total / uptime, 2) if uptime > 0 else 0.0
        if batcher_stats is not None:
            out["batcher"] = {
                "batches": batcher_stats.batches,
                "requests": batcher_stats.requests,
                "mean_occupancy": round(batcher_stats.mean_occupancy, 3),
                "mean_requests_per_batch": round(batcher_stats.mean_requests_per_batch, 2),
                "max_queue_depth": batcher_stats.max_queue_depth,
                # D2H transfer attribution (output compaction + async
                # readback pipeline): actual wire bytes fetched, the
                # full-fp32 all-outputs baseline they're charged against,
                # and how much of the in-flight transfer window the
                # completers actually blocked on.
                "bytes_downloaded": batcher_stats.bytes_downloaded,
                "bytes_download_full_f32": batcher_stats.bytes_download_full_f32,
                "download_compaction_ratio": round(
                    batcher_stats.download_compaction_ratio, 2
                ),
                "readback_overlap_fraction": round(
                    batcher_stats.readback_overlap_fraction, 3
                ),
                "topk_batches": batcher_stats.topk_batches,
                # Resilience layer: queued work shed because its propagated
                # client deadline expired.
                "deadline_sheds": getattr(batcher_stats, "deadline_sheds", 0),
                # Cache plane: combined batches whose duplicate rows were
                # collapsed before upload, and the rows never executed.
                "dedup_batches": getattr(batcher_stats, "dedup_batches", 0),
                "dedup_rows_collapsed": getattr(
                    batcher_stats, "dedup_rows_collapsed", 0
                ),
                # Row-granular cache tier (ISSUE 14): rows dispatched to
                # the device vs rows requested across row-planned batches.
                "row_batches": getattr(batcher_stats, "row_batches", 0),
                "rows_requested": getattr(batcher_stats, "rows_requested", 0),
                "rows_executed": getattr(batcher_stats, "rows_executed", 0),
                "row_full_hit_batches": getattr(
                    batcher_stats, "row_full_hit_batches", 0
                ),
            }
        return out

    # -------------------------------------------------------- fleet wire
    # The fleet aggregator's member-side surfaces (ISSUE 18): a full wire
    # snapshot served on the gossip port's /monitoring route, and a cheap
    # digest piggybacked on every gossip record so the router's aggregate
    # degrades gracefully when the scrape fails.

    def _window_wires_and_counters(self) -> tuple[dict, int, int]:
        with self._lock:
            items = sorted(self._rpcs.items())
        window = WindowedLatency.merge_dicts(
            [m.window.to_dict() for _, m in items]
        )
        ok = sum(m.ok for _, m in items)
        errors = sum(m.errors for _, m in items)
        return window, ok, errors

    def fleet_wire(self) -> dict:
        """Every entrypoint's rolling window merged into ONE wire
        histogram (the router re-merges members' wires with exact bucket
        counts), plus lifetime ok/error counters and the lifetime latency
        bucket counts the SLO monitor diffs — monotonic within a process,
        so the router clamps per-member deltas across restarts."""
        window, ok, errors = self._window_wires_and_counters()
        with self._lock:
            items = sorted(self._rpcs.items())
        life_counts = [0] * _NUM_BUCKETS
        life_total, life_sum = 0, 0.0
        for _, m in items:
            c, t, s, _mn, _mx = m.latency._state()
            for i, v in enumerate(c):
                if v:
                    life_counts[i] += v
            life_total += t
            life_sum += s
        return {
            "uptime_s": round(self._clock() - self._start, 1),
            "ok": ok,
            "errors": errors,
            "window": window,
            "lifetime": {
                "total": life_total,
                "sum_us": round(life_sum, 1),
                "buckets": {
                    str(i): c for i, c in enumerate(life_counts) if c
                },
            },
        }

    def fleet_summary(self) -> dict:
        """Digest of fleet_wire() small enough to ride every gossip
        record: qps + quantiles only, no mergeable histogram — a
        gossip-only member contributes its self-reported numbers to the
        aggregate instead of exact bucket counts."""
        window, ok, errors = self._window_wires_and_counters()
        stats = WindowedLatency.wire_stats(window)
        return {
            "qps": stats["qps"],
            "p50_ms": stats["p50_ms"],
            "p99_ms": stats["p99_ms"],
            "requests": ok + errors,
            "errors": errors,
        }

    def prometheus_text(
        self, batcher_stats=None, cache=None, row_cache=None, overload=None,
        utilization=None, quality=None, lifecycle=None, pipeline=None,
        recovery=None, kernels=None, mesh=None, elastic=None, fleet=None,
        cascade=None, integrity=None,
    ) -> str:
        """Prometheus exposition (text format 0.0.4) of the same data
        snapshot() serves as JSON. Metric names mirror tensorflow_model_
        server's monitoring surface (`:tensorflow:serving:request_count` /
        `:tensorflow:serving:request_latency`, microsecond buckets) so
        existing TF-Serving dashboards and alert rules scrape unchanged;
        rolling-window gauges, per-model series, and batcher gauges are
        framework-native and ride the dts_tpu_ prefix."""
        rc, rl = ":tensorflow:serving:request_count", ":tensorflow:serving:request_latency"
        esc = escape_label_value
        lines: list[str] = []
        _family_lines(lines, rc, "counter")
        with self._lock:
            items = sorted(self._rpcs.items())
            model_items = sorted(self._models.items())
        for name, m in items:
            lines.append(f'{rc}{{entrypoint="{esc(name)}",status="OK"}} {m.ok}')
            if m.errors:
                lines.append(
                    f'{rc}{{entrypoint="{esc(name)}",status="ERROR"}} {m.errors}'
                )
        _family_lines(lines, rl, "histogram")
        for name, m in items:
            buckets, sum_us, total = m.latency.prometheus_buckets()
            for le_us, cum in buckets:
                lines.append(
                    f'{rl}_bucket{{entrypoint="{esc(name)}",le="{le_us:.6g}"}} {cum}'
                )
            lines.append(f'{rl}_bucket{{entrypoint="{esc(name)}",le="+Inf"}} {total}')
            lines.append(f'{rl}_sum{{entrypoint="{esc(name)}"}} {sum_us:.6g}')
            lines.append(f'{rl}_count{{entrypoint="{esc(name)}"}} {total}')
        # Rolling-window horizon: sliding QPS + windowed percentiles per
        # entrypoint, plus the overall rolling rate `snapshot()["qps"]`
        # reports (ISSUE 3).
        win_qps = "dts_tpu_request_window_qps"
        win_lat = "dts_tpu_request_window_latency_ms"
        _family_lines(lines, win_qps, "gauge")
        overall = 0.0
        win_snaps = [(name, m.window.snapshot()) for name, m in items]
        for name, win in win_snaps:
            overall += win["qps"]
            lines.append(f'{win_qps}{{entrypoint="{esc(name)}"}} {win["qps"]}')
        _family_lines(lines, "dts_tpu_qps_window", "gauge")
        lines.append(f"dts_tpu_qps_window {round(overall, 2)}")
        _family_lines(lines, win_lat, "gauge")
        for name, win in win_snaps:
            for q, key in (("0.5", "p50_ms"), ("0.99", "p99_ms")):
                lines.append(
                    f'{win_lat}{{entrypoint="{esc(name)}",quantile="{q}"}} '
                    f'{win[key]}'
                )
        if model_items:
            mrc = "dts_tpu_model_request_count"
            mqps = "dts_tpu_model_window_qps"
            mlat = "dts_tpu_model_window_latency_ms"
            _family_lines(lines, mrc, "counter")
            for (name, model), m in model_items:
                base = f'entrypoint="{esc(name)}",model_name="{esc(model)}"'
                lines.append(f'{mrc}{{{base},status="OK"}} {m.ok}')
                if m.errors:
                    lines.append(f'{mrc}{{{base},status="ERROR"}} {m.errors}')
            # Families stay GROUPED (declaration followed by all of its
            # samples): the exposition lint enforces the text-format rule
            # that a family's lines form one contiguous block.
            qps_lines, lat_lines = [], []
            for (name, model), m in model_items:
                base = f'entrypoint="{esc(name)}",model_name="{esc(model)}"'
                win = m.window.snapshot()
                qps_lines.append(f'{mqps}{{{base}}} {win["qps"]}')
                for q, key in (("0.5", "p50_ms"), ("0.99", "p99_ms")):
                    lat_lines.append(
                        f'{mlat}{{{base},quantile="{q}"}} {win[key]}'
                    )
            _family_lines(lines, mqps, "gauge")
            lines.extend(qps_lines)
            _family_lines(lines, mlat, "gauge")
            lines.extend(lat_lines)
        if batcher_stats is not None:
            for metric, kind, value in (
                ("dts_tpu_batcher_batches_total", "counter", batcher_stats.batches),
                ("dts_tpu_batcher_requests_total", "counter", batcher_stats.requests),
                ("dts_tpu_batcher_mean_occupancy", "gauge",
                 round(batcher_stats.mean_occupancy, 4)),
                ("dts_tpu_batcher_mean_requests_per_batch", "gauge",
                 round(batcher_stats.mean_requests_per_batch, 3)),
                ("dts_tpu_batcher_max_queue_depth", "gauge",
                 batcher_stats.max_queue_depth),
                ("dts_tpu_batcher_bytes_downloaded_total", "counter",
                 batcher_stats.bytes_downloaded),
                ("dts_tpu_batcher_bytes_download_full_f32_total", "counter",
                 batcher_stats.bytes_download_full_f32),
                ("dts_tpu_batcher_topk_batches_total", "counter",
                 batcher_stats.topk_batches),
                ("dts_tpu_batcher_readback_overlap_fraction", "gauge",
                 round(batcher_stats.readback_overlap_fraction, 4)),
                ("dts_tpu_batcher_deadline_sheds_total", "counter",
                 getattr(batcher_stats, "deadline_sheds", 0)),
                ("dts_tpu_batcher_dedup_batches_total", "counter",
                 getattr(batcher_stats, "dedup_batches", 0)),
                ("dts_tpu_batcher_dedup_rows_collapsed_total", "counter",
                 getattr(batcher_stats, "dedup_rows_collapsed", 0)),
            ):
                _family_lines(lines, metric, kind)
                lines.append(f"{metric} {value}")
        if pipeline is not None:
            # Continuous-batching pipeline (ISSUE 9): the
            # batcher.pipeline_stats() snapshot as dts_tpu_pipeline_*
            # series — configured depth/window, live in-flight occupancy
            # (total + per bucket), high-water marks, and the
            # readback-overlap fraction the CPU bench gate reads.
            for metric, kind, value in (
                ("dts_tpu_pipeline_depth_configured", "gauge",
                 pipeline.get("depth", 0)),
                ("dts_tpu_pipeline_inflight_window", "gauge",
                 pipeline.get("inflight_window", 0)),
                ("dts_tpu_pipeline_in_flight", "gauge",
                 pipeline.get("in_flight", 0)),
                ("dts_tpu_pipeline_inflight_peak", "gauge",
                 pipeline.get("inflight_peak", 0)),
                ("dts_tpu_pipeline_dispatch_pending", "gauge",
                 pipeline.get("dispatch_pending", 0)),
                ("dts_tpu_pipeline_window_waits_total", "counter",
                 pipeline.get("inflight_window_waits", 0)),
                ("dts_tpu_pipeline_readback_overlap_fraction", "gauge",
                 pipeline.get("readback_overlap_fraction", 0.0)),
            ):
                _family_lines(lines, metric, kind)
                lines.append(f"{metric} {value}")
            per_bucket = pipeline.get("per_bucket_in_flight") or {}
            if per_bucket:
                bm = "dts_tpu_pipeline_bucket_in_flight"
                _family_lines(lines, bm, "gauge")
                for bucket, n in sorted(per_bucket.items()):
                    lines.append(f'{bm}{{bucket="{esc(bucket)}"}} {n}')
            ring = pipeline.get("buffer_ring")
            if ring is not None:
                for metric, kind, value in (
                    ("dts_tpu_pipeline_buffer_ring_reuses_total", "counter",
                     ring.get("reuses", 0)),
                    ("dts_tpu_pipeline_buffer_ring_allocs_total", "counter",
                     ring.get("allocs", 0)),
                    ("dts_tpu_pipeline_buffer_ring_free", "gauge",
                     ring.get("free_buffers", 0)),
                ):
                    _family_lines(lines, metric, kind)
                    lines.append(f"{metric} {value}")
        if cache is not None:
            # Cache plane (ISSUE 4): the ScoreCache snapshot dict as
            # dts_tpu_cache_* series — aggregate counters/gauges plus
            # per-model hit/miss/coalesced/eviction counters.
            for metric, kind, value in (
                ("dts_tpu_cache_hits_total", "counter", cache.get("hits", 0)),
                ("dts_tpu_cache_misses_total", "counter", cache.get("misses", 0)),
                ("dts_tpu_cache_coalesced_total", "counter",
                 cache.get("coalesced", 0)),
                ("dts_tpu_cache_evictions_total", "counter",
                 cache.get("evictions", 0)),
                ("dts_tpu_cache_expirations_total", "counter",
                 cache.get("expirations", 0)),
                ("dts_tpu_cache_invalidations_total", "counter",
                 cache.get("invalidations", 0)),
                # Brownout stale-serves (overload plane): expired entries
                # answered inside the stale window while pressure was on.
                ("dts_tpu_cache_stale_serves_total", "counter",
                 cache.get("stale_serves", 0)),
                ("dts_tpu_cache_hit_rate", "gauge", cache.get("hit_rate", 0.0)),
                ("dts_tpu_cache_entries", "gauge", cache.get("entries", 0)),
                ("dts_tpu_cache_value_bytes", "gauge",
                 cache.get("value_bytes", 0)),
            ):
                _family_lines(lines, metric, kind)
                lines.append(f"{metric} {value}")
            models = cache.get("models") or {}
            if models:
                mc = "dts_tpu_cache_model_events_total"
                _family_lines(lines, mc, "counter")
                for model, counters in sorted(models.items()):
                    base = f'model_name="{esc(model)}"'
                    for event in ("hits", "misses", "coalesced", "evictions"):
                        lines.append(
                            f'{mc}{{{base},event="{event}"}} '
                            f'{counters.get(event, 0)}'
                        )
        if row_cache is not None:
            # Row-granular cache tier (ISSUE 14): per-ROW hit/miss/
            # coalesce counters plus the plane's headline ratio — rows
            # actually executed on device vs rows requested.
            for metric, kind, value in (
                ("dts_tpu_cache_row_hits_total", "counter",
                 row_cache.get("hits", 0)),
                ("dts_tpu_cache_row_misses_total", "counter",
                 row_cache.get("misses", 0)),
                ("dts_tpu_cache_row_coalesced_total", "counter",
                 row_cache.get("coalesced", 0)),
                ("dts_tpu_cache_row_stale_serves_total", "counter",
                 row_cache.get("stale_serves", 0)),
                ("dts_tpu_cache_row_evictions_total", "counter",
                 row_cache.get("evictions", 0)),
                ("dts_tpu_cache_row_expirations_total", "counter",
                 row_cache.get("expirations", 0)),
                ("dts_tpu_cache_row_invalidations_total", "counter",
                 row_cache.get("invalidations", 0)),
                ("dts_tpu_cache_row_fills_total", "counter",
                 row_cache.get("fills", 0)),
                ("dts_tpu_cache_row_hit_rate", "gauge",
                 row_cache.get("hit_rate", 0.0)),
                ("dts_tpu_cache_row_entries", "gauge",
                 row_cache.get("entries", 0)),
                ("dts_tpu_cache_row_value_bytes", "gauge",
                 row_cache.get("value_bytes", 0)),
                ("dts_tpu_cache_rows_requested_total", "counter",
                 row_cache.get("rows_requested", 0)),
                ("dts_tpu_cache_rows_executed_total", "counter",
                 row_cache.get("rows_executed", 0)),
                ("dts_tpu_cache_rows_executed_fraction", "gauge",
                 row_cache.get("rows_executed_fraction", 0.0)),
            ):
                _family_lines(lines, metric, kind)
                lines.append(f"{metric} {value}")
        if overload is not None:
            # Overload plane (ISSUE 5): the AdmissionController snapshot
            # dict as dts_tpu_overload_* series — the adaptive limit +
            # controlled-variable gauges, shed/doomed/brownout counters,
            # per-lane sheds, and a one-hot pressure-state gauge (the
            # standard Prometheus encoding for an enum, so dashboards can
            # `max by (state)` it).
            for metric, kind, value in (
                ("dts_tpu_overload_limit_candidates", "gauge",
                 overload.get("limit", 0)),
                ("dts_tpu_overload_queue_wait_p99_ms", "gauge",
                 overload.get("queue_wait_p99_ms", 0.0)),
                ("dts_tpu_overload_target_queue_wait_ms", "gauge",
                 overload.get("target_queue_wait_ms", 0.0)),
                ("dts_tpu_overload_admitted_total", "counter",
                 overload.get("admitted", 0)),
                ("dts_tpu_overload_sheds_total", "counter",
                 overload.get("sheds", 0)),
                ("dts_tpu_overload_doomed_refusals_total", "counter",
                 overload.get("doomed_refusals", 0)),
                ("dts_tpu_overload_brownout_serves_total", "counter",
                 overload.get("brownout_serves", 0)),
                ("dts_tpu_overload_limit_increases_total", "counter",
                 overload.get("limit_increases", 0)),
                ("dts_tpu_overload_limit_decreases_total", "counter",
                 overload.get("limit_decreases", 0)),
                ("dts_tpu_overload_state_changes_total", "counter",
                 overload.get("state_changes", 0)),
            ):
                _family_lines(lines, metric, kind)
                lines.append(f"{metric} {value}")
            by_lane = overload.get("sheds_by_lane") or {}
            if by_lane:
                ls = "dts_tpu_overload_lane_sheds_total"
                _family_lines(lines, ls, "counter")
                for lane, n in sorted(by_lane.items()):
                    lines.append(f'{ls}{{lane="{esc(lane)}"}} {n}')
            st = "dts_tpu_overload_pressure_state"
            _family_lines(lines, st, "gauge")
            current = overload.get("state", "nominal")
            for state in ("nominal", "brownout", "shed"):
                lines.append(
                    f'{st}{{state="{esc(state)}"}} '
                    f'{1 if state == current else 0}'
                )
        if utilization is not None:
            # Utilization plane (ISSUE 6): the OccupancyLedger snapshot as
            # dts_tpu_utilization_* series — busy/achieved fractions and
            # the pipeline-depth gauge, the windowed waterfall components
            # (labeled), and the lifetime idle-gap attribution counters
            # (labeled by blocking cause).
            wf = utilization.get("waterfall") or {}
            for metric, kind, value in (
                ("dts_tpu_utilization_busy_fraction", "gauge",
                 wf.get("busy_fraction", 0.0)),
                ("dts_tpu_utilization_achieved_fraction_of_device_limit",
                 "gauge", wf.get("achieved_fraction_of_device_limit", 0.0)),
                ("dts_tpu_utilization_window_wall_seconds", "gauge",
                 wf.get("wall_s", 0.0)),
                ("dts_tpu_utilization_waterfall_sum_over_wall", "gauge",
                 wf.get("sum_over_wall", 0.0)),
                ("dts_tpu_utilization_in_flight", "gauge",
                 utilization.get("in_flight", 0)),
                ("dts_tpu_utilization_max_in_flight", "gauge",
                 utilization.get("max_in_flight", 0)),
                ("dts_tpu_utilization_batches_total", "counter",
                 utilization.get("batches", 0)),
                ("dts_tpu_utilization_busy_seconds_total", "counter",
                 utilization.get("busy_s", 0.0)),
                ("dts_tpu_utilization_sheds_total", "counter",
                 utilization.get("sheds", 0)),
            ):
                _family_lines(lines, metric, kind)
                lines.append(f"{metric} {value}")
            comps = wf.get("components_s") or {}
            if comps:
                cm = "dts_tpu_utilization_component_seconds"
                _family_lines(lines, cm, "gauge")
                for comp, secs in sorted(comps.items()):
                    lines.append(f'{cm}{{component="{esc(comp)}"}} {secs}')
            gaps = utilization.get("idle_gaps") or {}
            if gaps:
                gc = "dts_tpu_utilization_idle_gaps_total"
                gs = "dts_tpu_utilization_idle_gap_seconds_total"
                # Grouped, not interleaved: a family's samples must form
                # one contiguous block (the exposition lint's rule).
                _family_lines(lines, gc, "counter")
                for cause, blk in sorted(gaps.items()):
                    lines.append(
                        f'{gc}{{cause="{esc(cause)}"}} {blk.get("count", 0)}'
                    )
                _family_lines(lines, gs, "counter")
                for cause, blk in sorted(gaps.items()):
                    lines.append(
                        f'{gs}{{cause="{esc(cause)}"}} {blk.get("total_s", 0.0)}'
                    )
        if quality is not None:
            lines.extend(_quality_prometheus_lines(quality))
        if lifecycle is not None:
            lines.extend(_lifecycle_prometheus_lines(lifecycle))
        if recovery is not None:
            lines.extend(_recovery_prometheus_lines(recovery))
        if kernels is not None:
            lines.extend(_kernel_prometheus_lines(kernels))
        if mesh is not None:
            lines.extend(_mesh_prometheus_lines(mesh))
        if elastic is not None:
            lines.extend(_elastic_prometheus_lines(elastic))
        if fleet is not None:
            lines.extend(_fleet_prometheus_lines(fleet))
        if cascade is not None:
            lines.extend(_cascade_prometheus_lines(cascade))
        if integrity is not None:
            lines.extend(_integrity_prometheus_lines(integrity))
        return "\n".join(lines) + "\n"


def _quality_prometheus_lines(quality: dict) -> list[str]:
    """dts_tpu_quality_* exposition from a QualityMonitor snapshot dict
    (ISSUE 7): plane counters, label-join counters + windowed AUC /
    calibration error, per-(model, version) score counts / means / the
    score histogram family, and per-model drift gauges (PSI + JS, labeled
    by kind: vs the pinned reference or between live versions). Families
    are grouped and declared exactly once — the exposition lint's
    invariants."""
    esc = escape_label_value
    lines: list[str] = []
    exemplars = quality.get("exemplars") or {}
    for metric, kind, value in (
        ("dts_tpu_quality_observed_requests_total", "counter",
         quality.get("observed_requests", 0)),
        ("dts_tpu_quality_version_changes_total", "counter",
         quality.get("version_changes", 0)),
        ("dts_tpu_quality_exemplars_marked_total", "counter",
         exemplars.get("marked", 0)),
        ("dts_tpu_quality_drift_events_total", "counter",
         exemplars.get("drift_events", 0)),
    ):
        _family_lines(lines, metric, kind)
        lines.append(f"{metric} {value}")
    labels_blk = quality.get("labels") or {}
    for metric, kind, value in (
        ("dts_tpu_quality_labels_joined_total", "counter",
         labels_blk.get("joined", 0)),
        ("dts_tpu_quality_labels_orphaned_total", "counter",
         labels_blk.get("orphaned", 0)),
        ("dts_tpu_quality_labels_late_total", "counter",
         labels_blk.get("late", 0)),
        ("dts_tpu_quality_label_window_pairs", "gauge",
         labels_blk.get("window_pairs", 0)),
    ):
        _family_lines(lines, metric, kind)
        lines.append(f"{metric} {value}")
    if labels_blk.get("auc") is not None:
        _family_lines(lines, "dts_tpu_quality_auc", "gauge")
        lines.append(f'dts_tpu_quality_auc {labels_blk["auc"]}')
    cal_err = (labels_blk.get("calibration") or {}).get("error")
    if cal_err is not None:
        _family_lines(lines, "dts_tpu_quality_calibration_error", "gauge")
        lines.append(f"dts_tpu_quality_calibration_error {cal_err}")
    models = quality.get("models") or {}
    if not models:
        return lines
    count_lines, mean_lines, wmean_lines, hist_lines = [], [], [], []
    for model, blk in sorted(models.items()):
        for ver, vs in sorted(
            (blk.get("versions") or {}).items(), key=lambda kv: int(kv[0])
        ):
            base = f'model_name="{esc(model)}",version="{esc(ver)}"'
            total = vs.get("count", 0)
            count_lines.append(f"dts_tpu_quality_scores_total{{{base}}} {total}")
            mean_lines.append(
                f'dts_tpu_quality_score_mean{{{base}}} {vs.get("mean", 0.0)}'
            )
            wmean_lines.append(
                f"dts_tpu_quality_score_window_mean{{{base}}} "
                f'{(vs.get("window") or {}).get("mean", 0.0)}'
            )
            hg = vs.get("histogram") or {}
            counts = hg.get("counts") or []
            lo, hi = hg.get("lo", 0.0), hg.get("hi", 1.0)
            width = (hi - lo) / len(counts) if counts else 0.0
            last = max((i for i, c in enumerate(counts) if c), default=-1)
            acc = 0
            for i in range(last + 1):
                acc += counts[i]
                le = lo + width * (i + 1)
                hist_lines.append(
                    f'dts_tpu_quality_score_bucket{{{base},le="{le:.6g}"}} {acc}'
                )
            hist_lines.append(
                f'dts_tpu_quality_score_bucket{{{base},le="+Inf"}} {total}'
            )
            hist_lines.append(
                f"dts_tpu_quality_score_sum{{{base}}} "
                f'{round(vs.get("mean", 0.0) * total, 6)}'
            )
            hist_lines.append(f"dts_tpu_quality_score_count{{{base}}} {total}")
    _family_lines(lines, "dts_tpu_quality_scores_total", "counter")
    lines.extend(count_lines)
    _family_lines(lines, "dts_tpu_quality_score_mean", "gauge")
    lines.extend(mean_lines)
    _family_lines(lines, "dts_tpu_quality_score_window_mean", "gauge")
    lines.extend(wmean_lines)
    _family_lines(lines, "dts_tpu_quality_score", "histogram")
    lines.extend(hist_lines)
    psi_lines, js_lines, exceeded_lines = [], [], []
    for model, blk in sorted(models.items()):
        drift = blk.get("drift") or {}
        for kind_name in ("reference", "version_pair"):
            entry = drift.get(kind_name)
            if entry:
                lbl = f'model_name="{esc(model)}",kind="{kind_name}"'
                psi_lines.append(
                    f'dts_tpu_quality_drift_psi{{{lbl}}} {entry["psi"]}'
                )
                js_lines.append(
                    f'dts_tpu_quality_drift_js{{{lbl}}} {entry["js"]}'
                )
        exceeded_lines.append(
            f'dts_tpu_quality_drift_exceeded{{model_name="{esc(model)}"}} '
            f'{1 if drift.get("exceeded") else 0}'
        )
    if psi_lines:
        _family_lines(lines, "dts_tpu_quality_drift_psi", "gauge")
        lines.extend(psi_lines)
        _family_lines(lines, "dts_tpu_quality_drift_js", "gauge")
        lines.extend(js_lines)
    _family_lines(lines, "dts_tpu_quality_drift_exceeded", "gauge")
    lines.extend(exceeded_lines)
    return lines


def _lifecycle_prometheus_lines(lifecycle: dict) -> list[str]:
    """dts_tpu_lifecycle_* exposition from a LifecycleController snapshot
    dict (ISSUE 8): the one-hot state gauge (the overload plane's enum
    encoding, so dashboards `max by (state)` it), the live canary
    fraction + version gauges, tick/publish/promote/rollback counters,
    routed-request counters labeled by target role, and the watcher's
    blacklist size. Families grouped and declared once — the exposition
    lint's invariants."""
    esc = escape_label_value
    lines: list[str] = []
    st = "dts_tpu_lifecycle_state"
    _family_lines(lines, st, "gauge")
    current = lifecycle.get("state", "idle")
    for state in ("idle", "canary", "promoting", "rolled_back"):
        lines.append(
            f'{st}{{state="{esc(state)}"}} {1 if state == current else 0}'
        )
    counters = lifecycle.get("counters") or {}
    for metric, kind, value in (
        ("dts_tpu_lifecycle_canary_fraction", "gauge",
         lifecycle.get("canary_fraction", 0.0)),
        ("dts_tpu_lifecycle_stable_version", "gauge",
         lifecycle.get("stable_version") or 0),
        ("dts_tpu_lifecycle_canary_version", "gauge",
         lifecycle.get("canary_version") or 0),
        ("dts_tpu_lifecycle_ticks_total", "counter",
         counters.get("ticks", 0)),
        ("dts_tpu_lifecycle_publishes_total", "counter",
         counters.get("publishes", 0)),
        ("dts_tpu_lifecycle_publish_failures_total", "counter",
         counters.get("publish_failures", 0)),
        ("dts_tpu_lifecycle_promotes_total", "counter",
         counters.get("promotes", 0)),
        ("dts_tpu_lifecycle_rollbacks_total", "counter",
         counters.get("rollbacks", 0)),
        ("dts_tpu_lifecycle_blacklisted_versions", "gauge",
         len((lifecycle.get("watcher") or {}).get("blacklisted", ()))),
    ):
        _family_lines(lines, metric, kind)
        lines.append(f"{metric} {value}")
    rt = "dts_tpu_lifecycle_routed_total"
    _family_lines(lines, rt, "counter")
    for target, key in (
        ("canary", "routed_canary"),
        ("stable", "routed_stable"),
    ):
        lines.append(
            f'{rt}{{target="{esc(target)}"}} {counters.get(key, 0)}'
        )
    # Probe-lane routes are a SUBSET of target="canary" (the lane always
    # routes there), so they get their own family, not a third target.
    pr = "dts_tpu_lifecycle_probe_routed_total"
    _family_lines(lines, pr, "counter")
    lines.append(f"{pr} {counters.get('routed_probe', 0)}")
    return lines


def _recovery_prometheus_lines(recovery: dict) -> list[str]:
    """dts_tpu_recovery_* exposition from a RecoveryController snapshot
    dict (ISSUE 11): the one-hot state gauge (the overload/lifecycle enum
    encoding), the quarantine/reinit/replay/bisection counters, the
    pending-replay gauge, and the last cycle's duration (the live MTTR
    evidence). Families grouped and declared once — the exposition lint's
    invariants."""
    esc = escape_label_value
    lines: list[str] = []
    st = "dts_tpu_recovery_state"
    _family_lines(lines, st, "gauge")
    current = recovery.get("state", "serving")
    for state in ("serving", "quarantined", "reinit", "replay"):
        lines.append(
            f'{st}{{state="{esc(state)}"}} {1 if state == current else 0}'
        )
    counters = recovery.get("counters") or {}
    last = recovery.get("last_cycle") or {}
    for metric, kind, value in (
        ("dts_tpu_recovery_quarantines_total", "counter",
         counters.get("quarantines", 0)),
        ("dts_tpu_recovery_reinits_total", "counter",
         counters.get("reinits", 0)),
        ("dts_tpu_recovery_cycles_completed_total", "counter",
         counters.get("cycles_completed", 0)),
        ("dts_tpu_recovery_device_failures_total", "counter",
         counters.get("device_failures", 0)),
        ("dts_tpu_recovery_replayed_items_total", "counter",
         counters.get("replayed_items", 0)),
        ("dts_tpu_recovery_replay_budget_exhausted_total", "counter",
         counters.get("replay_budget_exhausted", 0)),
        ("dts_tpu_recovery_poisoned_requests_total", "counter",
         counters.get("poisoned_requests", 0)),
        ("dts_tpu_recovery_bisections_total", "counter",
         counters.get("bisections", 0)),
        ("dts_tpu_recovery_watchdog_wedge_trips_total", "counter",
         counters.get("watchdog_wedge_trips", 0)),
        ("dts_tpu_recovery_thread_deaths_total", "counter",
         counters.get("thread_deaths", 0)),
        ("dts_tpu_recovery_pending_replay_items", "gauge",
         recovery.get("pending_replay_items", 0)),
        ("dts_tpu_recovery_last_cycle_seconds", "gauge",
         last.get("duration_s", 0.0)),
        ("dts_tpu_recovery_mttr_mean_seconds", "gauge",
         (recovery.get("mttr") or {}).get("mean_s") or 0.0),
    ):
        _family_lines(lines, metric, kind)
        lines.append(f"{metric} {value}")
    return lines


def _kernel_prometheus_lines(kernels: dict) -> list[str]:
    """dts_tpu_kernel_* exposition from a KernelManager snapshot dict
    (ISSUE 12): plane counters, the per-bucket decision gauges (which
    variant each bucket serves), and the measured per-variant speedups —
    the autotune evidence, scrapeable. Families grouped and declared once
    — the exposition lint's invariants."""
    esc = escape_label_value
    lines: list[str] = []
    counters = kernels.get("counters") or {}
    for metric, kind, value in (
        ("dts_tpu_kernel_autotunes_total", "counter",
         counters.get("autotunes", 0)),
        ("dts_tpu_kernel_table_reuses_total", "counter",
         counters.get("table_reuses", 0)),
        ("dts_tpu_kernel_quantized_batches_total", "counter",
         counters.get("quantized_batches", 0)),
        ("dts_tpu_kernel_pallas_batches_total", "counter",
         counters.get("pallas_batches", 0)),
        ("dts_tpu_kernel_measure_only", "gauge",
         1 if kernels.get("measure_only") else 0),
        ("dts_tpu_kernel_int8_score_wire", "gauge",
         1 if kernels.get("int8_score_wire") else 0),
    ):
        _family_lines(lines, metric, kind)
        lines.append(f"{metric} {value}")
    decisions = kernels.get("decisions") or {}
    if decisions:
        q_lines, p_lines = [], []
        for mv, per_bucket in sorted(decisions.items()):
            for bucket, dec in sorted(
                per_bucket.items(), key=lambda kv: int(kv[0])
            ):
                base = (
                    f'model_version="{esc(mv)}",bucket="{esc(bucket)}"'
                )
                q_lines.append(
                    f"dts_tpu_kernel_bucket_quantized{{{base}}} "
                    f'{1 if dec.get("quantized") else 0}'
                )
                p_lines.append(
                    f"dts_tpu_kernel_bucket_pallas{{{base}}} "
                    f'{1 if dec.get("pallas") else 0}'
                )
        _family_lines(lines, "dts_tpu_kernel_bucket_quantized", "gauge")
        lines.extend(q_lines)
        _family_lines(lines, "dts_tpu_kernel_bucket_pallas", "gauge")
        lines.extend(p_lines)
    speed_lines = []
    for mv, table in sorted((kernels.get("tables") or {}).items()):
        for bucket, row in sorted(
            (table.get("buckets") or {}).items(), key=lambda kv: int(kv[0])
        ):
            for variant, entry in row.items():
                if not isinstance(entry, dict):
                    continue
                sp = entry.get("speedup")
                if sp is None:
                    continue
                speed_lines.append(
                    f'dts_tpu_kernel_variant_speedup{{model_version='
                    f'"{esc(mv)}",bucket="{esc(bucket)}",variant='
                    f'"{esc(variant)}"}} {sp}'
                )
    if speed_lines:
        _family_lines(lines, "dts_tpu_kernel_variant_speedup", "gauge")
        lines.extend(speed_lines)
    return lines


def _mesh_prometheus_lines(mesh: dict) -> list[str]:
    """dts_tpu_mesh_* exposition from a mesh_stats() snapshot (ISSUE 13):
    mesh geometry gauges, executor batch/row/pad counters (the data-axis
    divisibility pad made visible as ongoing work, not a startup fact),
    and — when the utilization ledger rides along — the per-device
    occupancy attribution gauge. Families grouped via _family_lines, so
    the one-lint-covers-all invariant (tools/check_prom.py) holds."""
    esc = escape_label_value
    lines: list[str] = []
    shape = mesh.get("shape") or {}
    ex = mesh.get("executor") or {}
    for metric, kind, value in (
        ("dts_tpu_mesh_devices", "gauge", len(mesh.get("devices") or ())),
        ("dts_tpu_mesh_data_parallel", "gauge", shape.get("data", 0)),
        ("dts_tpu_mesh_model_parallel", "gauge", shape.get("model", 0)),
        ("dts_tpu_mesh_tensor_parallel", "gauge",
         1 if mesh.get("tensor_parallel") else 0),
        ("dts_tpu_mesh_batches_total", "counter", ex.get("batches", 0)),
        ("dts_tpu_mesh_rows_total", "counter", ex.get("rows", 0)),
        ("dts_tpu_mesh_pad_batches_total", "counter",
         ex.get("pad_batches", 0)),
        ("dts_tpu_mesh_data_pad_rows_total", "counter",
         ex.get("data_pad_rows", 0)),
        ("dts_tpu_mesh_placed_servables", "gauge",
         ex.get("placed_servables", 0)),
    ):
        _family_lines(lines, metric, kind)
        lines.append(f"{metric} {value}")
    per_device = mesh.get("per_device") or {}
    if per_device:
        bd = "dts_tpu_mesh_device_busy_fraction"
        _family_lines(lines, bd, "gauge")
        for device, blk in sorted(per_device.items()):
            lines.append(
                f'{bd}{{device="{esc(device)}"}} '
                f'{blk.get("busy_fraction", 0.0)}'
            )
    return lines


def _elastic_prometheus_lines(elastic: dict) -> list[str]:
    """dts_tpu_elastic_* exposition from an elastic_stats() snapshot
    (ISSUE 15): current-split geometry gauges, switch counters by
    direction, the drain-barrier gauge, controller tick/hold counters +
    load EWMA, and per-split serve counters labeled by rung. Families
    grouped via _family_lines, so the one-lint-covers-all invariant
    (tools/check_prom.py) holds."""
    esc = escape_label_value
    lines: list[str] = []
    cur = str(elastic.get("current_split") or "0x1")
    d, _, m = cur.partition("x")
    ctrl = elastic.get("controller") or {}
    for metric, kind, value in (
        ("dts_tpu_elastic_data_parallel", "gauge", int(d or 0)),
        ("dts_tpu_elastic_model_parallel", "gauge", int(m or 0)),
        ("dts_tpu_elastic_splits", "gauge", len(elastic.get("splits") or ())),
        ("dts_tpu_elastic_switch_drain_pending", "gauge",
         1 if elastic.get("pending_drain_from") else 0),
        ("dts_tpu_elastic_last_drain_seconds", "gauge",
         elastic.get("last_drain_s") or 0.0),
        ("dts_tpu_elastic_controller_ticks_total", "counter",
         ctrl.get("ticks", 0)),
    ):
        _family_lines(lines, metric, kind)
        lines.append(f"{metric} {value}")
    sw = "dts_tpu_elastic_switches_total"
    _family_lines(lines, sw, "counter")
    lines.append(f'{sw}{{direction="up"}} {elastic.get("switches_up", 0)}')
    lines.append(f'{sw}{{direction="down"}} {elastic.get("switches_down", 0)}')
    holds = "dts_tpu_elastic_holds_total"
    _family_lines(lines, holds, "counter")
    lines.append(f'{holds}{{reason="dwell"}} {ctrl.get("holds_dwell", 0)}')
    lines.append(f'{holds}{{reason="drain"}} {ctrl.get("holds_drain", 0)}')
    ewma = ctrl.get("load_ewma")
    if ewma is not None:
        _family_lines(lines, "dts_tpu_elastic_load_ewma", "gauge")
        lines.append(f"dts_tpu_elastic_load_ewma {ewma}")
    per_split = elastic.get("per_split") or {}
    if per_split:
        sb = "dts_tpu_elastic_split_batches_total"
        _family_lines(lines, sb, "counter")
        for split, blk in sorted(per_split.items()):
            lines.append(
                f'{sb}{{split="{esc(split)}"}} {blk.get("batches", 0)}'
            )
        si = "dts_tpu_elastic_split_in_flight"
        _family_lines(lines, si, "gauge")
        for split, blk in sorted(per_split.items()):
            lines.append(
                f'{si}{{split="{esc(split)}"}} {blk.get("in_flight", 0)}'
            )
    return lines


def _cascade_prometheus_lines(cascade: dict) -> list[str]:
    """dts_tpu_cascade_* exposition from a cascade_stats() snapshot
    (ISSUE 19): request/fallback counters, row dispositions (requested /
    survivor / pruned), per-stage wall time, observed survivor- and
    rank-fraction gauges, and the survivor-bucket histogram. Families
    grouped via _family_lines so the one-lint-covers-all invariant
    (tools/check_prom.py) holds."""
    esc = escape_label_value
    lines: list[str] = []
    for metric, kind, value in (
        ("dts_tpu_cascade_requests_total", "counter",
         cascade.get("requests", 0)),
        ("dts_tpu_cascade_fallbacks_total", "counter",
         cascade.get("fallbacks", 0)),
        ("dts_tpu_cascade_stage1_failures_total", "counter",
         cascade.get("stage1_failures", 0)),
        ("dts_tpu_cascade_host_prunes_total", "counter",
         cascade.get("host_prunes", 0)),
        ("dts_tpu_cascade_rows_ranked_total", "counter",
         cascade.get("rows_ranked", 0)),
        ("dts_tpu_cascade_zero_survivor_requests_total", "counter",
         cascade.get("zero_survivor_requests", 0)),
        ("dts_tpu_cascade_survivor_fraction", "gauge",
         cascade.get("survivor_fraction_observed", 0.0)),
        ("dts_tpu_cascade_rank_fraction", "gauge",
         cascade.get("rank_fraction", 0.0)),
    ):
        _family_lines(lines, metric, kind)
        lines.append(f"{metric} {value}")
    rows = "dts_tpu_cascade_rows_total"
    _family_lines(lines, rows, "counter")
    for disposition, key in (
        ("requested", "rows_requested"),
        ("survivor", "survivor_rows"),
        ("pruned", "pruned_rows"),
    ):
        lines.append(
            f'{rows}{{disposition="{disposition}"}} {cascade.get(key, 0)}'
        )
    st = "dts_tpu_cascade_stage_seconds_total"
    _family_lines(lines, st, "counter")
    for stage, key in (
        ("stage1", "stage1_seconds_total"),
        ("prune", "prune_seconds_total"),
        ("stage2", "stage2_seconds_total"),
    ):
        lines.append(f'{st}{{stage="{stage}"}} {cascade.get(key, 0.0)}')
    buckets = cascade.get("survivor_buckets") or {}
    if buckets:
        sb = "dts_tpu_cascade_survivor_bucket_total"
        _family_lines(lines, sb, "counter")
        for bucket, count in sorted(
            buckets.items(), key=lambda kv: int(kv[0])
        ):
            lines.append(f'{sb}{{bucket="{esc(str(bucket))}"}} {count}')
    return lines


def _integrity_prometheus_lines(integrity: dict) -> list[str]:
    """dts_tpu_integrity_* exposition from an integrity_stats() snapshot
    (ISSUE 20): wire verify/reject/stamp counters, readback-screen
    trips (lifetime + current escalation window), shadow-verification
    batches/mismatches + forced-audit counters, recovery escalations,
    and the replica's live suspect verdict. Families grouped via
    _family_lines so the one-lint-covers-all invariant holds."""
    wire = integrity.get("wire") or {}
    screen = integrity.get("screen") or {}
    shadow = integrity.get("shadow") or {}
    lines: list[str] = []
    for metric, kind, value in (
        ("dts_tpu_integrity_wire_inputs_verified_total", "counter",
         wire.get("inputs_verified", 0)),
        ("dts_tpu_integrity_wire_inputs_rejected_total", "counter",
         wire.get("inputs_rejected", 0)),
        ("dts_tpu_integrity_wire_responses_stamped_total", "counter",
         wire.get("responses_stamped", 0)),
        ("dts_tpu_integrity_screen_trips_total", "counter",
         screen.get("trips", 0)),
        ("dts_tpu_integrity_screen_window_trips", "gauge",
         screen.get("window_trips", 0)),
        ("dts_tpu_integrity_shadow_batches_total", "counter",
         shadow.get("batches", 0)),
        ("dts_tpu_integrity_shadow_mismatches_total", "counter",
         shadow.get("mismatches", 0)),
        ("dts_tpu_integrity_audits_requested_total", "counter",
         shadow.get("audits_requested", 0)),
        ("dts_tpu_integrity_audits_run_total", "counter",
         shadow.get("audits_run", 0)),
        ("dts_tpu_integrity_escalations_total", "counter",
         integrity.get("escalations", 0)),
        ("dts_tpu_integrity_suspect", "gauge",
         int(bool(integrity.get("suspect")))),
    ):
        _family_lines(lines, metric, kind)
        lines.append(f"{metric} {value}")
    return lines


def _fleet_prometheus_lines(fleet: dict) -> list[str]:
    """dts_tpu_fleet_* exposition from a fleet_stats() snapshot (ISSUE
    17): gossip membership (member count + members-by-state), exchange /
    record-disposition counters, the coordinated rollout picture (seq /
    fraction / blacklist on the router's coordinator; applied seq +
    apply counters on a replica's follower), and the router's forwarding
    counters. One function serves BOTH shapes — `role: "router"` carries
    `router`/`rollout` blocks, `role: "replica"` carries `follower` —
    so the lint's families-declared-once invariant holds either way."""
    esc = escape_label_value
    lines: list[str] = []
    role = str(fleet.get("role") or "replica")
    rl = "dts_tpu_fleet_role"
    _family_lines(lines, rl, "gauge")
    lines.append(f'{rl}{{role="{esc(role)}"}} 1')
    gossip = fleet.get("gossip") or {}
    members = gossip.get("members") or {}
    mc = "dts_tpu_fleet_members"
    _family_lines(lines, mc, "gauge")
    lines.append(f"{mc} {gossip.get('member_count', len(members))}")
    by_state: dict[str, int] = {}
    for rec in members.values():
        st = str((rec or {}).get("state") or "unknown")
        by_state[st] = by_state.get(st, 0) + 1
    ms = "dts_tpu_fleet_members_by_state"
    _family_lines(lines, ms, "gauge")
    for st, n in sorted(by_state.items()):
        lines.append(f'{ms}{{state="{esc(st)}"}} {n}')
    counters = gossip.get("counters") or {}
    ex = "dts_tpu_fleet_gossip_exchanges_total"
    _family_lines(lines, ex, "counter")
    lines.append(f'{ex}{{status="ok"}} {counters.get("exchanges_ok", 0)}')
    lines.append(
        f'{ex}{{status="failed"}} {counters.get("exchanges_failed", 0)}'
    )
    rec_t = "dts_tpu_fleet_gossip_records_total"
    _family_lines(lines, rec_t, "counter")
    for disp in ("accepted", "stale", "expired"):
        lines.append(
            f'{rec_t}{{disposition="{esc(disp)}"}} '
            f'{counters.get(f"records_{disp}", 0)}'
        )
    rollout = fleet.get("rollout") or {}
    follower = fleet.get("follower") or {}
    state = rollout.get("state") or {}
    if state or follower:
        seq = "dts_tpu_fleet_rollout_seq"
        _family_lines(lines, seq, "gauge")
        if state:
            lines.append(f'{seq}{{side="coordinator"}} {state.get("seq", 0)}')
        if follower:
            lines.append(
                f'{seq}{{side="applied"}} {follower.get("applied_seq", -1)}'
            )
    if state:
        for metric, value in (
            ("dts_tpu_fleet_rollout_fraction", state.get("fraction", 0.0)),
            ("dts_tpu_fleet_rollout_canary_version",
             state.get("canary_version") or 0),
            ("dts_tpu_fleet_rollout_blacklist_size",
             len(state.get("blacklist") or ())),
        ):
            _family_lines(lines, metric, "gauge")
            lines.append(f"{metric} {value}")
        rc = rollout.get("counters") or {}
        ch = "dts_tpu_fleet_rollout_changes_total"
        _family_lines(lines, ch, "counter")
        for kind in ("adoptions", "blacklists", "clears"):
            lines.append(f'{ch}{{kind="{esc(kind)}"}} {rc.get(kind, 0)}')
    if follower:
        ap = "dts_tpu_fleet_rollout_applies_total"
        _family_lines(lines, ap, "counter")
        lines.append(f"{ap} {follower.get('applies', 0)}")
        bl = "dts_tpu_fleet_rollout_blacklists_applied_total"
        _family_lines(lines, bl, "counter")
        lines.append(f"{bl} {follower.get('blacklists_applied', 0)}")
    router = fleet.get("router") or {}
    if router:
        rr = "dts_tpu_fleet_router_requests_total"
        _family_lines(lines, rr, "counter")
        lines.append(f'{rr}{{status="ok"}} {router.get("requests", 0)}')
        lines.append(f'{rr}{{status="error"}} {router.get("errors", 0)}')
        lines.append(
            f'{rr}{{status="degraded"}} {router.get("degraded", 0)}'
        )
        st = "dts_tpu_fleet_router_steers_total"
        _family_lines(lines, st, "counter")
        lines.append(
            f'{st}{{source="gossip"}} {router.get("gossip_steers", 0)}'
        )
        lines.append(
            f'{st}{{source="watch"}} {router.get("watch_updates", 0)}'
        )
        lines.append(
            f'{st}{{source="suspect"}} {router.get("suspect_steers", 0)}'
        )
        au = "dts_tpu_fleet_router_integrity_audits_total"
        _family_lines(lines, au, "counter")
        for outcome, key in (
            ("run", "integrity_audits"),
            ("disagreed", "audit_disagreements"),
            ("suspect_marked", "audit_suspects_marked"),
        ):
            lines.append(
                f'{au}{{outcome="{esc(outcome)}"}} {router.get(key, 0)}'
            )
        rj = "dts_tpu_fleet_router_rejoins_total"
        _family_lines(lines, rj, "counter")
        lines.append(f"{rj} {router.get('gossip_rejoins', 0)}")
        hb = "dts_tpu_fleet_router_healthy_backends"
        _family_lines(lines, hb, "gauge")
        lines.append(f"{hb} {router.get('healthy_backends', 0)}")
        tb = "dts_tpu_fleet_router_backends"
        _family_lines(lines, tb, "gauge")
        lines.append(f"{tb} {router.get('backends', 0)}")
    # Fleet aggregate + SLO blocks (ISSUE 18): present only on a router
    # whose observability plane is armed — fleet_stats() attaches them.
    agg = fleet.get("agg") or {}
    if agg:
        aq = "dts_tpu_fleet_agg_qps"
        _family_lines(lines, aq, "gauge")
        lines.append(f"{aq} {agg.get('qps', 0.0)}")
        al = "dts_tpu_fleet_agg_latency_ms"
        _family_lines(lines, al, "gauge")
        for q in ("p50", "p99"):
            lines.append(
                f'{al}{{quantile="{q}"}} {agg.get(f"{q}_ms", 0.0)}'
            )
        for metric, value in (
            ("dts_tpu_fleet_agg_requests", agg.get("requests", 0)),
            ("dts_tpu_fleet_agg_errors", agg.get("errors", 0)),
            ("dts_tpu_fleet_agg_members", agg.get("members", 0)),
            ("dts_tpu_fleet_agg_members_degraded",
             agg.get("members_degraded", 0)),
        ):
            _family_lines(lines, metric, "gauge")
            lines.append(f"{metric} {value}")
        per = agg.get("member_qps") or {}
        if per:
            mq = "dts_tpu_fleet_agg_member_qps"
            _family_lines(lines, mq, "gauge")
            for member, v in sorted(per.items()):
                lines.append(f'{mq}{{member="{esc(member)}"}} {v}')
    slo = fleet.get("slo") or {}
    if slo:
        lt = "dts_tpu_slo_latency_target_ms"
        _family_lines(lines, lt, "gauge")
        lines.append(f"{lt} {slo.get('latency_target_ms', 0.0)}")
        ob = "dts_tpu_slo_objective"
        _family_lines(lines, ob, "gauge")
        for name, v in sorted((slo.get("objectives") or {}).items()):
            lines.append(f'{ob}{{slo="{esc(name)}"}} {v}')
        br = "dts_tpu_slo_burn_rate"
        _family_lines(lines, br, "gauge")
        for name, wins in sorted((slo.get("burn") or {}).items()):
            for win in ("short", "long"):
                lines.append(
                    f'{br}{{slo="{esc(name)}",window="{win}"}} '
                    f'{(wins or {}).get(win, 0.0)}'
                )
        bu = "dts_tpu_slo_budget_remaining"
        _family_lines(lines, bu, "gauge")
        for name, v in sorted((slo.get("budget_remaining") or {}).items()):
            lines.append(f'{bu}{{slo="{esc(name)}"}} {v}')
        bd = "dts_tpu_slo_breached"
        _family_lines(lines, bd, "gauge")
        lines.append(f"{bd} {1 if slo.get('breached') else 0}")
        bt = "dts_tpu_slo_breaches_total"
        _family_lines(lines, bt, "counter")
        lines.append(f"{bt} {slo.get('breaches', 0)}")
    return lines


def fleet_prometheus_text(fleet: dict) -> str:
    """Standalone dts_tpu_fleet_* exposition — the router's /metrics body
    (the router has no ServerMetrics; its only Prometheus surface is the
    fleet plane itself). Replica-side fleet series ride the main
    prometheus_text(fleet=...) path instead."""
    return "\n".join(_fleet_prometheus_lines(fleet)) + "\n"


def resilience_prometheus_text(resilience: dict) -> str:
    """Prometheus text exposition of the CLIENT resilience state — the
    dict client.ShardedPredictClient.resilience_counters() returns
    (ResilienceCounters fields + an optional BackendScoreboard snapshot).
    The client has no scrape port of its own; bench.py/soak write this
    next to their artifacts so fleet dashboards ingest client-side hedging
    /failover/ejection state in the same format as the server plane."""
    esc = escape_label_value
    lines = []
    for key in (
        "hedges_fired", "hedges_won", "failovers",
        "backoff_sleeps", "partial_responses",
    ):
        if key in resilience:
            metric = f"dts_tpu_client_{key}_total"
            lines.append(f"# TYPE {metric} counter")
            lines.append(f"{metric} {int(resilience[key])}")
    sb = resilience.get("scoreboard")
    if sb:
        for key in ("ejections", "probes", "recoveries"):
            if key in sb:
                metric = f"dts_tpu_client_{key}_total"
                lines.append(f"# TYPE {metric} counter")
                lines.append(f"{metric} {int(sb[key])}")
        backends = sb.get("backends", {})
        if backends:
            lines.append("# TYPE dts_tpu_client_backend_up gauge")
            lines.append("# TYPE dts_tpu_client_backend_ewma_ms gauge")
            lines.append("# TYPE dts_tpu_client_backend_successes_total counter")
            lines.append("# TYPE dts_tpu_client_backend_failures_total counter")
            for host, st in backends.items():
                label = f'host="{esc(host)}"'
                up = 1 if st.get("state") == "healthy" else 0
                lines.append(
                    f'dts_tpu_client_backend_up{{{label},'
                    f'state="{esc(st.get("state", ""))}"}} {up}'
                )
                if st.get("ewma_ms") is not None:
                    lines.append(
                        f"dts_tpu_client_backend_ewma_ms{{{label}}} "
                        f'{st["ewma_ms"]}'
                    )
                lines.append(
                    f"dts_tpu_client_backend_successes_total{{{label}}} "
                    f'{st.get("successes", 0)}'
                )
                lines.append(
                    f"dts_tpu_client_backend_failures_total{{{label}}} "
                    f'{st.get("failures", 0)}'
                )
    return "\n".join(lines) + "\n" if lines else ""
