"""Exact-match prediction score cache + single-flight coalescing.

CTR candidate traffic is heavily zipfian: the same hot (user-bucket,
candidate-set) request recurs across requests within seconds, yet every
duplicate rides the full pad/pack/H2D/jit/D2H pipeline. The cheapest
inference is the one never run — this cache short-circuits EXACT repeats
at `batcher.submit`, before the queue, the device, or a dispatch slot is
touched.

Design:

- **Exact match only.** Keys are (model, version, output-selection,
  canonical-feature-bytes digest) — cache/digest.py's canonicalization, so
  two protobuf encodings of the same features hit the same entry while the
  compact and wide wires (different decoded bytes) stay apart. Cached
  scores are BIT-IDENTICAL to a fresh computation because they ARE a prior
  computation's outputs.
- **Sharded-lock LRU + TTL.** N independent (OrderedDict, Lock) shards
  keyed by digest hash: submit-path lookups from many RPC handler threads
  never serialize on one cache-wide lock. Capacity is bounded by entry
  count AND value bytes (split per shard); entries expire ttl_s after
  fill — CTR scores go stale with features not in the request (user state,
  budget pacing), so a bounded shelf life is part of the contract.
- **Generation invalidation.** Each model name carries a generation;
  `invalidate_model` (wired to the version watcher's on_servable_change
  hook) bumps it and drops that model's entries — a version swap can never
  serve the old version's scores even inside the TTL window. The version
  in the key already isolates entries; the generation makes the swap
  RECLAIM memory and kill in-flight fills that started under the old
  generation.
- **Single-flight coalescing.** Concurrent identical misses register on an
  in-flight map: one leader computes, every waiter's Future is resolved
  from the leader's result — N simultaneous hot-key misses cost one device
  pass, not N. A leader that fails fans its failure out (waiters would
  otherwise hang); a leader whose future is CANCELLED (service deadline)
  fails waiters with CoalescedLeaderCancelled (a TimeoutError, so the
  service maps it to DEADLINE_EXCEEDED).
- **Never filled from degraded/faulted/partial results.** fill() is only
  reached from a fully-successful completion (the batcher's completer
  success path; the client's non-degraded merge); failures and
  cancellations resolve waiters without touching the store, and a fill
  whose generation went stale mid-flight is dropped.

Thread-safe throughout; everything is plain-Python + numpy (no jax), so
the client package can reuse the same core for its optional local cache.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from concurrent.futures import Future, InvalidStateError

import numpy as np

from .digest import features_digest


class CoalescedLeaderCancelled(TimeoutError):
    """The coalesced leader request was cancelled (its waiter's deadline
    expired) before producing a result: followers fail with a
    TimeoutError so the RPC layer answers DEADLINE_EXCEEDED — the shared
    computation timed out for everyone riding it."""


class _Entry:
    __slots__ = ("value", "expires_t", "gen", "nbytes")

    def __init__(self, value, expires_t, gen, nbytes):
        self.value = value
        self.expires_t = expires_t
        self.gen = gen
        self.nbytes = nbytes


class _Flight:
    __slots__ = ("gen", "waiters")

    def __init__(self, gen: int):
        self.gen = gen
        self.waiters: list[Future] = []


class CacheHandle:
    """One submit's cache context: the computed key, the generation it was
    minted under, and the role the caller drew (hit / coalesced waiter /
    leader). Leaders pass this back to complete()/abort(); a leader handle
    also pins ITS _Flight object, so closing the flight can never pop (and
    resolve) a DIFFERENT flight that replaced it in the map after a
    generation bump. `stale` marks a hit served PAST its TTL under the
    brownout stale-window (serving/overload.py): the caller must flag the
    response degraded and must never re-fill from it."""

    __slots__ = ("key", "model", "gen", "hit", "waiter", "leader", "flight",
                 "stale")

    def __init__(self, key, model, gen, hit=None, waiter=None, leader=False,
                 flight=None, stale=False):
        self.key = key
        self.model = model
        self.gen = gen
        self.hit = hit
        self.waiter = waiter
        self.leader = leader
        self.flight = flight
        self.stale = stale


class ScoreCache:
    """Sharded-lock LRU+TTL exact-match score cache with single-flight."""

    def __init__(
        self,
        max_entries: int = 8192,
        max_bytes: int = 64 << 20,
        ttl_s: float = 30.0,
        coalesce: bool = True,
        shards: int = 8,
        clock=time.monotonic,
    ):
        if max_entries <= 0:
            raise ValueError(f"max_entries must be positive, got {max_entries}")
        self.max_entries = int(max_entries)
        self.max_bytes = int(max_bytes)
        self.ttl_s = float(ttl_s)
        self.coalesce = bool(coalesce)
        self._clock = clock
        self._nshards = max(1, int(shards))
        # Per-shard capacity: independent shards cannot share a global
        # counter without a global lock, which is exactly what sharding
        # exists to avoid. The digest is uniform, so the split is fair.
        self._shard_entries = max(1, self.max_entries // self._nshards)
        self._shard_bytes = max(1, self.max_bytes // self._nshards)
        self._shards: list[OrderedDict] = [OrderedDict() for _ in range(self._nshards)]
        self._locks = [threading.Lock() for _ in range(self._nshards)]
        # Running value-byte total per shard (kept under the shard lock) so
        # fill's byte-budget eviction is O(evictions), not O(entries).
        self._bytes = [0] * self._nshards
        # model name -> generation; bumped by invalidate_model.
        self._gens: dict[str, int] = {}
        self._gen_lock = threading.Lock()
        # Single-flight: key -> _Flight, one map (misses are the slow path
        # and already heading for the device; a per-shard split buys
        # nothing measurable there).
        self._flights: dict = {}
        self._flight_lock = threading.Lock()
        # Per-model counters, one small lock (counter bumps are nanoseconds
        # next to the digest the lookup already paid).
        self._stats_lock = threading.Lock()
        self._per_model: dict[str, dict[str, int]] = {}

    # ------------------------------------------------------------- plumbing

    def _shard_of(self, key) -> int:
        # key[-1] is the 16-byte feature digest — already uniform.
        return key[-1][0] % self._nshards

    def _gen_of(self, model: str) -> int:
        with self._gen_lock:
            return self._gens.get(model, 0)

    _COUNTER_KEYS = ("hits", "misses", "coalesced", "evictions",
                     "expirations", "invalidations", "fills", "stale_serves")

    def _count(self, model: str, field: str, n: int = 1) -> None:
        with self._stats_lock:
            m = self._per_model.setdefault(
                model, {k: 0 for k in self._COUNTER_KEYS}
            )
            m[field] += n

    @staticmethod
    def make_key(
        model: str, version, output_keys, arrays: dict, salt: bytes = b""
    ) -> tuple:
        """(model, version, output-selection, canonical digest). version and
        output_keys are any hashables the caller resolves requests by (the
        batcher uses servable.version + the fetch-key tuple; the client its
        version label + output key). `salt` rides the digest fold — the
        cascade prune mode keys apart from full-vector runs there (see
        features_digest) — and the digest stays the LAST tuple element
        (_shard_of addresses key[-1])."""
        return (model, version, output_keys, features_digest(arrays, salt=salt))

    # ------------------------------------------------------------ hot path

    def lookup(self, key: tuple):
        """Cached value for `key`, or None. TTL-expired and stale-generation
        entries are dropped on sight (and counted)."""
        value = self._get(key)
        self._count(key[0], "hits" if value is not None else "misses")
        return value

    def _get(self, key: tuple):
        """Store read without hit/miss accounting (begin() attributes the
        outcome itself, so a coalesced join counts as coalesced — not as
        a miss on top)."""
        return self._get_within(key, 0.0)[0]

    def _get_within(self, key: tuple, stale_s: float):
        """(value, stale) store read: a FRESH entry reads as (value,
        False); an entry past its TTL but within `stale_s` of it reads as
        (value, True) WITHOUT being dropped or LRU-promoted — the brownout
        stale-serve path (serving/overload.py) borrows it, it does not
        revalidate it. Past the stale window (or on a stale generation)
        the entry is dropped on sight exactly as before."""
        model = key[0]
        gen = self._gen_of(model)
        idx = self._shard_of(key)
        now = self._clock()
        stale = False
        with self._locks[idx]:
            shard = self._shards[idx]
            entry = shard.get(key)
            if entry is not None:
                if entry.gen != gen:
                    del shard[key]
                    self._bytes[idx] -= entry.nbytes
                    entry = None
                elif now >= entry.expires_t + stale_s:
                    del shard[key]
                    self._bytes[idx] -= entry.nbytes
                    self._count(model, "expirations")
                    entry = None
                elif now >= entry.expires_t:
                    stale = True  # expired but inside the stale window
                else:
                    shard.move_to_end(key)
        return (entry.value if entry is not None else None), stale

    def begin(
        self, model: str, version, output_keys, arrays: dict,
        stale_s: float = 0.0, salt: bytes = b"",
    ) -> CacheHandle:
        """One-stop submit-path entry: digest + lookup + single-flight join.
        Returns a handle where exactly one of these holds:
        - handle.hit is the cached outputs (serve it, done) — with
          handle.stale True when `stale_s` > 0 allowed an expired entry
          (brownout: mark the response degraded, never re-fill);
        - handle.waiter is a Future another in-flight identical request
          will resolve (hand it to the caller, done);
        - handle.leader is True: compute, then complete(handle, future).
        """
        key = self.make_key(model, version, output_keys, arrays, salt=salt)
        gen = self._gen_of(model)
        hit, stale = self._get_within(key, stale_s)
        if hit is not None:
            self._count(model, "stale_serves" if stale else "hits")
            return CacheHandle(key, model, gen, hit=hit, stale=stale)
        flight = None
        if self.coalesce:
            with self._flight_lock:
                existing = self._flights.get(key)
                if existing is not None and existing.gen == gen:
                    waiter: Future = Future()
                    existing.waiters.append(waiter)
                    self._count(model, "coalesced")
                    return CacheHandle(key, model, gen, waiter=waiter)
                # Either no flight, or a STALE-generation one (its leader
                # started before an invalidation): replace it in the map —
                # the old leader still resolves its own waiters through
                # the flight object pinned on its handle.
                flight = _Flight(gen)
                self._flights[key] = flight
        self._count(model, "misses")
        return CacheHandle(key, model, gen, leader=True, flight=flight)

    def fill(self, key: tuple, value: dict, gen: int | None = None) -> bool:
        """Store `value` (dict[str, np.ndarray], COPIED so a cached entry
        never pins a whole batch buffer via a slice view). Refused — False —
        when the model's generation moved past `gen` (a version swap landed
        while this result was in flight) or the value alone exceeds a
        shard's byte budget."""
        model = key[0]
        if gen is None:
            gen = self._gen_of(model)
        elif gen != self._gen_of(model):
            return False
        value = {k: np.array(v, copy=True) for k, v in value.items()}
        nbytes = sum(v.nbytes for v in value.values())
        if nbytes > self._shard_bytes:
            return False
        entry = _Entry(value, self._clock() + self.ttl_s, gen, nbytes)
        idx = self._shard_of(key)
        evicted = 0
        with self._locks[idx]:
            shard = self._shards[idx]
            prev = shard.get(key)
            if prev is not None:
                self._bytes[idx] -= prev.nbytes
            shard[key] = entry
            shard.move_to_end(key)
            self._bytes[idx] += nbytes
            while len(shard) > self._shard_entries or (
                self._bytes[idx] > self._shard_bytes and len(shard) > 1
            ):
                _, old = shard.popitem(last=False)
                self._bytes[idx] -= old.nbytes
                evicted += 1
        self._count(model, "fills")
        if evicted:
            self._count(model, "evictions", evicted)
        return True

    # ------------------------------------------------- single-flight close

    def _pop_waiters(self, handle: CacheHandle) -> list[Future]:
        """Close the LEADER'S OWN flight: its waiters come from the flight
        object the handle pinned, and the map entry is removed only when
        it still holds that same flight (a stale-generation leader whose
        slot was replaced must not pop — and resolve — the newer flight's
        waiters with old-generation results)."""
        if handle.flight is None:
            return []
        with self._flight_lock:
            if self._flights.get(handle.key) is handle.flight:
                del self._flights[handle.key]
        return handle.flight.waiters

    def take_waiters(self, handle: CacheHandle) -> list[Future]:
        """Close a leader's flight WITHOUT resolving its waiters — the
        caller assumes responsibility for every returned Future (the
        batcher's deadline-retry path re-dispatches the computation for
        them instead of handing them the leader's deadline fate)."""
        return self._pop_waiters(handle)

    def complete(self, handle: CacheHandle, fut: Future) -> None:
        """Close a leader's flight from its finished Future: fill on
        success (same-generation only), fan result/failure out to every
        coalesced waiter. Safe to call from any thread (the batcher calls
        it via add_done_callback on a completer thread). Never raises —
        a cache bookkeeping failure must not poison the leader's own
        already-delivered result."""
        try:
            waiters = self._pop_waiters(handle)
            if fut.cancelled():
                result, exc = None, CoalescedLeaderCancelled(
                    "coalesced leader request was cancelled before completing"
                )
            else:
                exc = fut.exception()
                result = fut.result() if exc is None else None
            if exc is None:
                self.fill(handle.key, result, gen=handle.gen)
            for w in waiters:
                if w.cancelled():
                    continue
                try:
                    if exc is None:
                        w.set_result(result)
                    else:
                        w.set_exception(exc)
                except InvalidStateError:
                    pass  # waiter withdrawn concurrently; it is gone
        except Exception:  # noqa: BLE001 — bookkeeping must not cost a request
            import logging

            logging.getLogger("dts_tpu.cache").exception("cache complete failed")

    def abort(self, handle: CacheHandle, exc: BaseException) -> None:
        """A leader that never got its computation enqueued (admission
        refused, prepare failed): close the flight by failing any waiters
        that joined in the window."""
        for w in self._pop_waiters(handle):
            if not w.cancelled():
                try:
                    w.set_exception(exc)
                except InvalidStateError:
                    pass

    def fail_flights(self, exc: BaseException) -> int:
        """Pop EVERY registered in-flight map entry and fail its waiters
        with `exc` — the quarantine-capture hook (serving/recovery.py):
        when the device is torn down for rebuild, the leaders of these
        flights may be stranded in wedged threads that never unwind, so
        nothing else would ever close them; a foreign (or future) request
        joining a zombie flight would hang to its deadline. Stranded
        leaders that DO eventually complete resolve only their own
        plan/handle waiter lists — already failed here, InvalidStateError
        guarded. Returns the number of waiters failed."""
        with self._flight_lock:
            flights = list(self._flights.values())
            self._flights.clear()
        failed = 0
        for fl in flights:
            for w in fl.waiters:
                if w.cancelled():
                    continue
                try:
                    w.set_exception(exc)
                    failed += 1
                except InvalidStateError:
                    pass
        return failed

    # -------------------------------------------------------- invalidation

    def invalidate_model(self, model: str) -> int:
        """Generation bump + eager purge of `model`'s entries (the version-
        watcher hook: a swap must drop the old generation's scores NOW, not
        at TTL). Returns the number of entries dropped."""
        with self._gen_lock:
            self._gens[model] = self._gens.get(model, 0) + 1
        dropped = 0
        for idx in range(self._nshards):
            with self._locks[idx]:
                shard = self._shards[idx]
                stale = [k for k in shard if k[0] == model]
                for k in stale:
                    self._bytes[idx] -= shard.pop(k).nbytes
                dropped += len(stale)
        if dropped:
            self._count(model, "invalidations", dropped)
        return dropped

    def flush(self, model: str | None = None) -> int:
        """Operator flush control (/cachez): drop everything, or one
        model's entries (generation-bumped, so in-flight fills die too)."""
        if model is not None:
            return self.invalidate_model(model)
        dropped = 0
        with self._gen_lock:
            models = set(self._gens)
        with self._flight_lock:
            # A model whose ONLY activity is an in-flight leader (no
            # entries yet, never invalidated) must still be bumped, or
            # that fill would land after the flush.
            models.update(k[0] for k in self._flights)
        per_model: dict[str, int] = {}
        for idx in range(self._nshards):
            with self._locks[idx]:
                shard = self._shards[idx]
                for k in shard:
                    per_model[k[0]] = per_model.get(k[0], 0) + 1
                models.update(k[0] for k in shard)
                dropped += len(shard)
                shard.clear()
                self._bytes[idx] = 0
        with self._gen_lock:
            for m in models:
                self._gens[m] = self._gens.get(m, 0) + 1
        # Same accounting as the per-model flush form: a full flush must
        # move the invalidation counters too, or dashboards watching
        # dts_tpu_cache_invalidations_total miss it entirely.
        for m, c in per_model.items():
            self._count(m, "invalidations", c)
        return dropped

    # ----------------------------------------------------------- telemetry

    def entry_count(self) -> int:
        return sum(len(s) for s in self._shards)

    def value_bytes(self) -> int:
        return sum(self._bytes)

    def snapshot(self) -> dict:
        """The /cachez + /monitoring block: aggregate and per-model
        hit/miss/coalesced/eviction counters, occupancy, config."""
        with self._stats_lock:
            per_model = {m: dict(c) for m, c in sorted(self._per_model.items())}
        totals = {
            k: sum(c.get(k, 0) for c in per_model.values())
            for k in self._COUNTER_KEYS
        } if per_model else {k: 0 for k in self._COUNTER_KEYS}
        looked = totals["hits"] + totals["misses"]
        return {
            "enabled": True,
            **totals,
            "hit_rate": round(totals["hits"] / looked, 4) if looked else 0.0,
            "entries": self.entry_count(),
            "value_bytes": self.value_bytes(),
            "config": {
                "max_entries": self.max_entries,
                "max_bytes": self.max_bytes,
                "ttl_s": self.ttl_s,
                "coalesce": self.coalesce,
                "shards": self._nshards,
            },
            "models": per_model,
        }
