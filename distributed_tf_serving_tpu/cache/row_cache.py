"""Row-granular score cache — execute only the cold rows (ISSUE 14).

The exact-match ScoreCache (score_cache.py) answers WHOLE-request repeats;
at zipfian fleet traffic most requests are distinct as requests while
their candidate ROWS recur heavily (the same hot items are re-ranked for
every user). This module caches scores PER CANDIDATE ROW, keyed
(model, version, output-selection, row digest), so a request whose rows
are 90% hot executes only the cold 10%: the batcher consults the row
cache after collect, packs/buckets/dispatches only the cold rows, and the
completer scatters device scores (cold) and cached scores (hot) back into
each request's slice — bit-identical to a full execution, because every
cached value IS a prior execution's post-readback f32 output.

Reuses the ScoreCache machinery wholesale (RowScoreCache subclasses it):
the sharded-lock LRU store, TTL + byte/entry bounds, per-model generation
invalidation (version swaps drop row entries eagerly and kill in-flight
fills), and single-flight — now PER ROW: two co-resident batches sharing
a cold row execute it once (the first batch leads the row's flight; the
second joins as a waiter and assembles from the leader's fill). Brownout
stale-serve extends to row entries via the same `stale_s` window.

Row identity is the canonical row layout shared with dedup and the
label-join plane (cache/digest.py canonical_rows), pinned with a
structure header (per-input name/dtype/row-shape) so identical raw bytes
under a different tensor structure can never share a digest — the same
contract features_digest makes for whole requests.
"""

from __future__ import annotations

import hashlib
import logging
from concurrent.futures import Future, InvalidStateError

import numpy as np

from .digest import canonical_rows
from .score_cache import ScoreCache, _Entry, _Flight


def row_structure_header(arrays: dict[str, "np.ndarray"]) -> bytes:
    """Structure pin for per-row digests: each input's name, dtype, and
    PER-ROW shape (everything but the candidate axis), sorted by name —
    computed once per batch and folded into every row digest, so an int64
    id row can never collide with the same eight bytes read as weights."""
    parts = []
    for k in sorted(arrays):
        a = arrays[k]
        parts.append(f"{k}:{a.dtype.str}:{a.shape[1:]};")
    return "".join(parts).encode()


def digest_rows(
    blob: np.ndarray, header: bytes, rows=None
) -> list[bytes]:
    """16-byte blake2b digest per row of a canonical_rows blob (+ the
    structure header). `rows` restricts to a subset of row indices (the
    dedup-unique slots); None digests every row. ALWAYS blake2b, matching
    row_label_keys — the digest must not depend on whether the native
    host ops are built — but with the host ops present the whole batch
    hashes in ONE GIL-released native call (hostops.cc hash128_rows, the
    same RFC 7693 blake2b byte for byte) instead of a per-row python
    loop: at the armed-row-cache bucket sizes the loop was a measurable
    slice of every batch's host time (ISSUE 15 satellite)."""
    from .. import native

    if native.available():
        if rows is None:
            sel = blob
        else:
            idx = np.fromiter(rows, dtype=np.int64)
            sel = blob[idx] if idx.size else blob[:0]
        digests = native.hash128_rows(sel, header)
        return [digests[i].tobytes() for i in range(digests.shape[0])]
    if rows is None:
        rows = range(blob.shape[0])
    out = []
    for i in rows:
        h = hashlib.blake2b(digest_size=16)
        h.update(header)
        h.update(blob[i].tobytes())
        out.append(h.digest())
    return out


class RowBatchPlan:
    """One batch's row-cache consultation: per-SLOT classification (a slot
    is one distinct row entering execution planning) into

    - hits[slot]   -> cached per-row output dict (serve it),
    - waiters[slot]-> Future another in-flight batch's fill resolves,
    - lead         -> slots THIS batch must execute (their flights, when
                      coalescing, are pinned in `flights` by identity —
                      the score_cache close-by-flight-identity contract).

    stale_slots marks hits served past TTL under the brownout window
    (responses touching them must be flagged degraded, never re-filled).
    """

    __slots__ = ("cache", "model", "gen", "keys", "hits", "stale_slots",
                 "waiters", "lead", "flights")

    def __init__(self, cache: "RowScoreCache", model: str, gen: int):
        self.cache = cache
        self.model = model
        self.gen = gen
        self.keys: list[tuple] = []
        self.hits: dict[int, dict] = {}
        self.stale_slots: set[int] = set()
        self.waiters: dict[int, Future] = {}
        self.lead: list[int] = []
        # Close idempotence lives in flights.pop(): a slot's flight is
        # popped exactly once whichever of complete_rows/abort_rows runs
        # first.
        self.flights: dict[int, _Flight] = {}


class RowScoreCache(ScoreCache):
    """Per-candidate-row score cache: the ScoreCache store/LRU/TTL/
    generation/single-flight machinery over (model, version,
    output-selection, row digest) keys. Values are per-row output dicts
    (each array is one row's slice of a post-readback, post-widen host
    output — f32, sidecars already consumed), so assembly from cache is
    bit-identical to a fresh execution."""

    # Row-plane extras next to the inherited hit/miss/... counters:
    # rows_requested counts every ORIGINAL row that entered cold-row
    # extraction (duplicates included), rows_executed the rows actually
    # dispatched to the device — the headline ratio of the plane.
    _COUNTER_KEYS = ScoreCache._COUNTER_KEYS + (
        "rows_requested", "rows_executed"
    )

    def __init__(
        self,
        max_entries: int = 131072,
        max_bytes: int = 32 << 20,
        ttl_s: float = 30.0,
        coalesce: bool = True,
        shards: int = 8,
        clock=None,
    ):
        import time

        super().__init__(
            max_entries=max_entries,
            max_bytes=max_bytes,
            ttl_s=ttl_s,
            coalesce=coalesce,
            shards=shards,
            clock=clock or time.monotonic,
        )

    @staticmethod
    def row_key(model: str, version, output_keys, digest: bytes) -> tuple:
        """(model, version, output-selection, row digest) — the same key
        shape ScoreCache uses, with the request digest replaced by one
        row's canonical digest. The output-selection axis matters: a row
        cached under a score-only fetch holds only the score output and
        must never answer an all-outputs request."""
        return (model, version, output_keys, digest)

    def note_rows(self, model: str, requested: int, executed: int) -> None:
        """Batcher accounting hook: `requested` original rows entered
        cold-row extraction, `executed` were actually dispatched."""
        if requested:
            self._count(model, "rows_requested", requested)
        if executed:
            self._count(model, "rows_executed", executed)

    # ----------------------------------------------------------- batch API

    def begin_rows(
        self, model: str, version, output_keys, digests: list[bytes],
        stale_s: float = 0.0,
    ) -> RowBatchPlan:
        """Consult the cache for every row digest of one batch (one slot
        per digest, in order). Duplicate digests within the batch resolve
        through the flight machinery: the first occurrence leads, later
        ones join as waiters the leader's own completion resolves — the
        intra-batch collapse falls out of single-flight for free.

        Atomic against partial failure: an exception mid-loop aborts
        every flight already registered before re-raising, so a planning
        error can never strand another batch's waiters."""
        plan = RowBatchPlan(self, model, self._gen_of(model))
        try:
            plan.keys = [
                self.row_key(model, version, output_keys, d) for d in digests
            ]
            # Batched store reads: slots grouped by shard, each shard lock
            # taken ONCE per batch instead of once per row — at 1.5k rows
            # per batch the per-row locking was the plane's dominant host
            # cost (the counter bumps are batched the same way below).
            by_shard: dict[int, list[int]] = {}
            for slot, key in enumerate(plan.keys):
                by_shard.setdefault(self._shard_of(key), []).append(slot)
            now = self._clock()
            expired = 0
            for idx, slots in by_shard.items():
                with self._locks[idx]:
                    shard = self._shards[idx]
                    for slot in slots:
                        key = plan.keys[slot]
                        entry = shard.get(key)
                        if entry is None:
                            continue
                        if entry.gen != plan.gen:
                            del shard[key]
                            self._bytes[idx] -= entry.nbytes
                        elif now >= entry.expires_t + stale_s:
                            del shard[key]
                            self._bytes[idx] -= entry.nbytes
                            expired += 1
                        elif now >= entry.expires_t:
                            # Expired but inside the brownout stale
                            # window: served WITHOUT LRU-promote/refresh
                            # (the _get_within stale-serve contract).
                            plan.hits[slot] = entry.value
                            plan.stale_slots.add(slot)
                        else:
                            shard.move_to_end(key)
                            plan.hits[slot] = entry.value
            misses = 0
            for slot, key in enumerate(plan.keys):
                if slot in plan.hits:
                    continue
                if self.coalesce:
                    with self._flight_lock:
                        existing = self._flights.get(key)
                        if existing is not None and existing.gen == plan.gen:
                            waiter: Future = Future()
                            existing.waiters.append(waiter)
                            plan.waiters[slot] = waiter
                            continue
                        flight = _Flight(plan.gen)
                        self._flights[key] = flight
                        plan.flights[slot] = flight
                plan.lead.append(slot)
                misses += 1
            fresh_hits = len(plan.hits) - len(plan.stale_slots)
            if fresh_hits:
                self._count(model, "hits", fresh_hits)
            if plan.stale_slots:
                self._count(model, "stale_serves", len(plan.stale_slots))
            if plan.waiters:
                self._count(model, "coalesced", len(plan.waiters))
            if misses:
                self._count(model, "misses", misses)
            if expired:
                self._count(model, "expirations", expired)
        except BaseException as exc:
            self.abort_rows(plan, exc)
            raise
        return plan

    def _pop_row_waiters(self, plan: RowBatchPlan, slot: int) -> list[Future]:
        """Close one lead slot's flight by identity (the score_cache
        contract: a stale-generation leader replaced in the map must
        never pop — and resolve — the newer flight's waiters)."""
        flight = plan.flights.pop(slot, None)
        if flight is None:
            return []
        key = plan.keys[slot]
        with self._flight_lock:
            if self._flights.get(key) is flight:
                del self._flights[key]
        return flight.waiters

    def complete_rows(
        self, plan: RowBatchPlan, values: dict[int, dict],
        exc: BaseException | None = None,
    ) -> None:
        """Close a batch's lead flights from its executed rows: slots
        present in `values` fill the store (same-generation only, batched
        per shard — one lock per shard per batch, not per row) and
        resolve their waiters with the value; slots absent fail their
        waiters with `exc` (or a RuntimeError). Never raises — cache
        bookkeeping must not cost the batch its own delivery."""
        try:
            fills: list[tuple[tuple, dict]] = []
            resolve: list[tuple[list, object, BaseException | None]] = []
            for slot in list(plan.lead):
                waiters = self._pop_row_waiters(plan, slot)
                value = values.get(slot)
                if value is not None:
                    # The value is fill_from_host's private per-row copy
                    # (shared with the waiters) — stored as-is, never a
                    # second copy per row.
                    fills.append((plan.keys[slot], value))
                if waiters:
                    err = (
                        None if value is not None
                        else (exc or RuntimeError(
                            "row execution produced no value"
                        ))
                    )
                    resolve.append((waiters, value, err))
            if fills:
                self._fill_many(plan.model, fills, plan.gen)
            for waiters, value, err in resolve:
                for w in waiters:
                    if w.cancelled():
                        continue
                    try:
                        if err is None:
                            w.set_result(value)
                        else:
                            w.set_exception(err)
                    except InvalidStateError:
                        pass
        except Exception:  # noqa: BLE001 — bookkeeping must not cost a request
            logging.getLogger("dts_tpu.cache").exception(
                "row cache complete failed"
            )

    def _fill_many(
        self, model: str, items: list[tuple[tuple, dict]], gen: int
    ) -> int:
        """Batched fill: insert every (key, value) minted under `gen`
        with ONE lock acquisition per touched shard (fill()'s semantics
        otherwise — generation-refused after a swap, per-shard byte/entry
        eviction, counter accounting batched). Values must already be
        private copies."""
        if gen != self._gen_of(model):
            return 0
        expires = self._clock() + self.ttl_s
        by_shard: dict[int, list] = {}
        for key, value in items:
            by_shard.setdefault(self._shard_of(key), []).append((key, value))
        filled = 0
        evicted = 0
        for idx, batch in by_shard.items():
            with self._locks[idx]:
                shard = self._shards[idx]
                for key, value in batch:
                    nbytes = sum(v.nbytes for v in value.values())
                    if nbytes > self._shard_bytes:
                        continue
                    prev = shard.get(key)
                    if prev is not None:
                        self._bytes[idx] -= prev.nbytes
                    shard[key] = _Entry(value, expires, gen, nbytes)
                    shard.move_to_end(key)
                    self._bytes[idx] += nbytes
                    filled += 1
                while len(shard) > self._shard_entries or (
                    self._bytes[idx] > self._shard_bytes and len(shard) > 1
                ):
                    _, old = shard.popitem(last=False)
                    self._bytes[idx] -= old.nbytes
                    evicted += 1
        if filled:
            self._count(model, "fills", filled)
        if evicted:
            self._count(model, "evictions", evicted)
        return filled

    def abort_rows(self, plan: RowBatchPlan, exc: BaseException) -> None:
        """A batch that never completed its cold rows (shed while staged,
        device-stage failure, recovery capture): close every lead flight
        by failing the waiters that joined, so no foreign batch hangs on
        a fill that will never land. Idempotent after complete_rows (the
        flights are already popped)."""
        for slot in list(plan.lead):
            for w in self._pop_row_waiters(plan, slot):
                if not w.cancelled():
                    try:
                        w.set_exception(exc)
                    except InvalidStateError:
                        pass

    def snapshot(self) -> dict:
        snap = super().snapshot()
        snap["row_granular"] = True
        req = snap.get("rows_requested", 0)
        snap["rows_executed_fraction"] = (
            round(snap.get("rows_executed", 0) / req, 4) if req else 0.0
        )
        return snap
