"""Cache plane: exact-match score cache, single-flight coalescing, and
intra-batch duplicate collapse. Everything here is jax-free (numpy +
stdlib), so the client package reuses the same core for its optional
local cache."""

from .digest import canonical_rows, features_digest, rows_as_bytes
from .dedup import collapse_rows
from .score_cache import CacheHandle, CoalescedLeaderCancelled, ScoreCache
from .row_cache import RowBatchPlan, RowScoreCache

__all__ = [
    "CacheHandle",
    "CoalescedLeaderCancelled",
    "RowBatchPlan",
    "RowScoreCache",
    "ScoreCache",
    "canonical_rows",
    "collapse_rows",
    "features_digest",
    "rows_as_bytes",
]
