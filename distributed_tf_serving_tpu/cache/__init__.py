"""Cache plane: exact-match score cache, single-flight coalescing, and
intra-batch duplicate collapse. Everything here is jax-free (numpy +
stdlib), so the client package reuses the same core for its optional
local cache."""

from .digest import canonical_rows, features_digest, rows_as_bytes
from .dedup import collapse_rows
from .score_cache import CacheHandle, CoalescedLeaderCancelled, ScoreCache

__all__ = [
    "CacheHandle",
    "CoalescedLeaderCancelled",
    "ScoreCache",
    "canonical_rows",
    "collapse_rows",
    "features_digest",
    "rows_as_bytes",
]
