"""Intra-batch duplicate collapse — effective-batch shrink under skew.

The score cache (score_cache.py) kills whole-request repeats before the
queue; this module kills ROW repeats inside one combined batch after
collect: zipfian candidate traffic re-scores the same hot rows across the
requests a batch coalesces (and often inside one request's candidate
list), so a 4096-row combined batch routinely holds far fewer distinct
rows. Only the unique rows are padded/uploaded/executed — possibly in a
smaller bucket — and the batcher scatters the executed scores back to
every requester's original row order (serving/batcher.py threads the
scatter map through to the completer).

Row identity is EXACT-bytes over the canonical row layout shared with the
cache key (cache/digest.py canonical_rows): "same row" means the same
decoded feature bytes, never a hash-collision gamble, and the collapse can
never disagree with the cache about what "identical" means.
"""

from __future__ import annotations

import numpy as np

from .digest import canonical_rows


def collapse_rows(
    arrays: dict[str, "list[np.ndarray] | np.ndarray"],
) -> "tuple[dict[str, np.ndarray] | None, np.ndarray | None, dict[str, np.ndarray]]":
    """Collapse duplicate rows across a batch's concatenated inputs.

    `arrays` maps each input name to its per-request parts (list) or an
    already-concatenated array. Returns (unique_arrays, scatter, cats):
    unique_arrays holds only the distinct rows (contiguous, any stable
    order) and scatter[i] is row i's index into them — outputs executed
    over unique_arrays are restored to original order by `out[scatter]`.
    `cats` is the concatenated full batch this function had to build
    anyway; on the all-unique outcome (unique_arrays/scatter None) the
    caller pads straight from it instead of re-concatenating its parts —
    the screening cost then is one concat + the unique() sort, not a
    second copy of the batch.
    """
    cats = {
        k: (np.concatenate(v) if isinstance(v, list) and len(v) > 1
            else (v[0] if isinstance(v, list) else v))
        for k, v in arrays.items()
    }
    blob = canonical_rows(cats)
    total = blob.shape[0]
    # np.unique(axis=0) sorts rows lexicographically (C path): first_idx
    # indexes the first occurrence of each distinct row in the ORIGINAL
    # batch, inverse maps every original row onto its unique slot.
    _, first_idx, inverse = np.unique(
        blob, axis=0, return_index=True, return_inverse=True
    )
    if first_idx.shape[0] == total:
        return None, None, cats
    uniq = {k: np.ascontiguousarray(a[first_idx]) for k, a in cats.items()}
    return uniq, inverse.reshape(-1).astype(np.int64), cats
