"""Canonical-feature-bytes digests — the ONE canonicalization shared by the
score cache's exact-match key and the batcher's intra-batch duplicate
collapse (cache/dedup.py), so "these two requests are the same work" can
never mean different things on the two paths.

Canonical form: the DECODED feature tensors (dict[str, np.ndarray]), inputs
ordered by name, each row laid out as its contiguous raw bytes. Two protobuf
encodings of the same features (tensor_content vs repeated *_val fields —
both wire shapes the reference client emits, DCNClient.java:98-108) decode
to identical arrays, so they digest identically; genuinely different
requests (the compact int32/bf16 wire vs the wide int64/f32 wire) carry
different dtypes and different bytes, so they digest apart — the cache is
EXACT-match by design, never "probably the same features".

The digest primitive is the same one the DeviceInputCache keys on:
native.hash128 (one pass, GIL released) when the host ops are built,
blake2b-128 otherwise.
"""

from __future__ import annotations

import hashlib

import numpy as np


def _digest_bytes(arr: np.ndarray) -> bytes:
    """16-byte content digest of a contiguous array's raw bytes."""
    from .. import native

    if native.available():
        return native.hash128(arr)
    # uint8 view: ml_dtypes (bf16) arrays refuse the buffer protocol
    # directly, and the digest is over raw bytes anyway (same fallback as
    # serving/batcher.DeviceInputCache._key).
    return hashlib.blake2b(
        np.ascontiguousarray(arr).view(np.uint8).data, digest_size=16
    ).digest()


def rows_as_bytes(arr: np.ndarray) -> np.ndarray:
    """[n, ...] array -> [n, B] uint8 view/copy of each row's raw bytes.
    1-D arrays count as one value per row."""
    a = np.ascontiguousarray(arr)
    if a.ndim == 1:
        a = a.reshape(-1, 1)
    elif a.ndim > 2:
        a = a.reshape(a.shape[0], -1)
    return a.view(np.uint8).reshape(a.shape[0], -1)


def canonical_rows(arrays: dict[str, np.ndarray]) -> np.ndarray:
    """[n, B] uint8 matrix: row i holds candidate i's bytes across ALL
    inputs, inputs concatenated in sorted-name order. Exact row identity
    (dedup) and the request digest below both read from this one layout."""
    parts = [rows_as_bytes(arrays[k]) for k in sorted(arrays)]
    return parts[0] if len(parts) == 1 else np.concatenate(parts, axis=1)


def row_label_keys(arrays: dict[str, np.ndarray]) -> list[str]:
    """Per-candidate JOIN KEYS for the label-feedback plane
    (serving/quality.py): a 16-byte blake2b digest (hex) of each row's
    canonical bytes — the same canonical_rows layout the dedup plane
    keys row identity on, so the key a client computes over the arrays
    it SENT equals the key the server computes over the arrays it
    decoded. The digest is ALWAYS blake2b (both sides must produce
    identical hex with or without the compiled host ops); when the host
    ops are built, native.hash128_rows computes the SAME blake2b for the
    whole batch in one GIL-released call (RFC 7693 in hostops.cc,
    byte-identity regression-tested) instead of a per-row python loop."""
    rows = canonical_rows(arrays)
    from .. import native

    if native.available():
        digests = native.hash128_rows(rows)
        return [digests[i].tobytes().hex() for i in range(digests.shape[0])]
    return [
        hashlib.blake2b(rows[i].tobytes(), digest_size=16).hexdigest()
        for i in range(rows.shape[0])
    ]


def features_digest(arrays: dict[str, np.ndarray], salt: bytes = b"") -> bytes:
    """Stable 16-byte digest of a request's decoded feature tensors.

    Same identity contract as canonical_rows — exact decoded bytes per
    sorted-name input — but folded per ARRAY instead of through the
    [n, B] row matrix: the cache key never needs the row layout (only
    dedup does), and building it would cost a full copy of the request's
    bytes per cache-armed submit. Each input's name/dtype/shape rides the
    fold, so identical raw bytes under a different tensor structure (an
    int64 id re-read as eight weight bytes, a reshaped batch) can never
    share a digest.

    `salt` folds an execution-mode discriminator into the digest itself:
    a cascade stage-1 prune submit produces survivor pairs, not a score
    vector, so the same (model, version, outputs, features) identity must
    never share a digest with a full-vector run — the salt keeps the two
    result shapes apart at the key level rather than trusting every
    consumer to know about modes.
    """
    h = hashlib.blake2b(digest_size=16)
    if salt:
        h.update(salt)
    for k in sorted(arrays):
        a = arrays[k]
        h.update(f"{k}:{a.dtype.str}:{a.shape};".encode())
        h.update(_digest_bytes(a))
    return h.digest()
