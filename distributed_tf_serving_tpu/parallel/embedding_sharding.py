"""Vocab-sharded embedding lookups — the EP analog (SURVEY.md §2.4).

Two equivalent paths are provided:

1. The *annotation* path (executor.py): shard the table NamedSharding
   P("model", None), leave the model's jnp.take as-is, and let XLA's SPMD
   partitioner derive the masked-gather + psum. Idiomatic, zero model
   changes — this is what serving uses.

2. The *explicit* path here: shard_map over the mesh where each chip holds
   vocab/k contiguous rows, looks up only in-shard ids (clipped gather +
   mask), and psums partial embeddings over the model axis. This is the
   reference-visible semantics made manual — the scatter the Java client did
   per host (DCNClient.java:146-159) happens on-mesh — and it pins down the
   contract the annotation path must match (test_parallel.py asserts
   equality), while being the hook point for a Pallas lookup kernel.
"""

from __future__ import annotations

import re

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from ..utils.compat import shard_map

from .mesh import DATA_AXIS, MODEL_AXIS


# ---------------------------------------------------------------------------
# Named partition rules (the serving-mode layout contract, ISSUE 13).
#
# The generic layout walker (sharding.param_shardings) infers "vocab table"
# from path-name heuristics; the serving mode wants the layout to be an
# explicit, reviewable CONTRACT per model family — the match_partition_rules
# idiom (SNIPPETS.md): ordered (regex, PartitionSpec) pairs matched against
# the "/"-joined param path, first match wins. A rule that would place a
# mesh axis on a missing dim (spec rank > leaf rank) is a config error; an
# unmatched leaf returns None so the caller can fall back to the generic
# dense policy (replicated, or tensor-parallel splits) — the rules pin the
# memory-heavy EP decisions, the generic walker keeps handling the long
# tail of small dense params identically on both paths.


def tree_path_str(path) -> str:
    """jax key-path -> "/"-joined name ("cross/0/w") for rule matching."""
    parts = []
    for p in path:
        key = getattr(p, "key", None)
        if key is None:
            key = getattr(p, "idx", None)
        parts.append(str(key) if key is not None else str(p))
    return "/".join(parts)


# Per-family rules. Only the vocab-major tables are pinned here: they are
# the DLRM-scale memory (the 300M-qps paper's CTR models are embedding-
# dominated) and the one layout decision that MUST NOT silently change
# with a param rename. Dense MLP/cross weights fall through (None) to the
# generic policy so tensor_parallel keeps working identically.
MODEL_PARTITION_RULES: dict[str, tuple[tuple[str, P], ...]] = {
    "dcn": (("^embedding$", P(MODEL_AXIS, None)),),
    "dcn_v2": (("^embedding$", P(MODEL_AXIS, None)),),
    "dlrm": (("^embedding$", P(MODEL_AXIS, None)),),
    "two_tower": (
        ("^embedding$", P(MODEL_AXIS, None)),
        ("^temperature$", P()),  # scalar: explicit, never sharded
    ),
    "wide_deep": (
        ("^embedding$", P(MODEL_AXIS, None)),
        ("^wide$", P(MODEL_AXIS)),  # per-vocab-row scalar table (EP too)
        ("^wide_bias$", P()),
    ),
    "deepfm": (
        ("^embedding$", P(MODEL_AXIS, None)),
        ("^linear$", P(MODEL_AXIS)),
    ),
    "generic_mlp": (("^embedding$", P(MODEL_AXIS, None)),),
}


def partition_rules_for(model_kind: str) -> tuple[tuple[str, P], ...] | None:
    """The family's ordered (regex, PartitionSpec) rules, or None for an
    unknown/imported family (graph executors, custom servables) — callers
    then use the generic path-name layout unchanged."""
    return MODEL_PARTITION_RULES.get(model_kind)


def rule_matcher(rules, strict: bool = False):
    """(path, leaf) -> PartitionSpec-or-None resolver for an ordered rule
    list — the per-leaf core match_partition_rules and the generic layout
    walker (sharding.param_shardings) share.

    Scalars are never partitioned (the SNIPPETS idiom). A matched spec
    whose rank exceeds the leaf's is a layout bug — the table the rule
    was written for changed shape — and raises rather than silently
    serving a wrong layout. Unmatched leaves yield None (generic-policy
    fallback); strict=True turns them into errors for tests that want
    the rule set proven exhaustive."""
    compiled = [(re.compile(pat), spec) for pat, spec in rules]

    def resolve(path, leaf):
        shape = getattr(leaf, "shape", ())
        if len(shape) == 0 or all(d == 1 for d in shape):
            return P()  # scalars/degenerate leaves are never partitioned
        name = tree_path_str(path)
        for pat, spec in compiled:
            if pat.search(name) is not None:
                if len(spec) > len(shape):
                    raise ValueError(
                        f"partition rule {pat.pattern!r} places "
                        f"{len(spec)} dims but param {name!r} has shape "
                        f"{shape} — the rule no longer matches the model"
                    )
                return spec
        if strict:
            raise ValueError(f"no partition rule matched param {name!r}")
        return None

    return resolve


def match_partition_rules(rules, params, strict: bool = False):
    """PartitionSpec-or-None tree for `params` per the ordered rules (see
    rule_matcher for the matching semantics)."""
    return jax.tree_util.tree_map_with_path(rule_matcher(rules, strict), params)


def sharded_field_embed(
    table: jax.Array,
    feat_ids: jax.Array,
    feat_wts: jax.Array,
    mesh: Mesh,
    compute_dtype,
) -> jax.Array:
    """Weighted field lookup with the table sharded over the model axis and
    candidates sharded over the data axis.

    table     [V, D] (V divisible by mesh model-axis size)
    feat_ids  [n, F] int32, already folded into [0, V)
    feat_wts  [n, F] float
    returns   [n, F, D] in compute_dtype, candidate-sharded
    """
    vocab = table.shape[0]
    k = mesh.shape[MODEL_AXIS]
    if vocab % k != 0:
        raise ValueError(f"vocab {vocab} not divisible by model-axis size {k}")

    def local(table_shard, ids_blk, wts_blk):
        # table_shard: [V/k, D] — this chip's contiguous vocab rows.
        vshard = table_shard.shape[0]
        lo = jax.lax.axis_index(MODEL_AXIS) * vshard
        local_ids = ids_blk - lo
        in_shard = (local_ids >= 0) & (local_ids < vshard)
        # Clipped gather stays in-bounds; the mask zeroes out-of-shard rows,
        # so the psum over the model axis reassembles exact lookups.
        emb = jnp.take(table_shard, jnp.clip(local_ids, 0, vshard - 1), axis=0)
        emb = jnp.where(in_shard[..., None], emb, jnp.zeros((), emb.dtype))
        emb = jax.lax.psum(emb, MODEL_AXIS)
        return emb.astype(compute_dtype) * wts_blk[..., None].astype(compute_dtype)

    return shard_map(
        local,
        mesh=mesh,
        in_specs=(P(MODEL_AXIS, None), P(DATA_AXIS, None), P(DATA_AXIS, None)),
        out_specs=P(DATA_AXIS, None, None),
    )(table, feat_ids, feat_wts)
