"""Vocab-sharded embedding lookups — the EP analog (SURVEY.md §2.4).

Two equivalent paths are provided:

1. The *annotation* path (executor.py): shard the table NamedSharding
   P("model", None), leave the model's jnp.take as-is, and let XLA's SPMD
   partitioner derive the masked-gather + psum. Idiomatic, zero model
   changes — this is what serving uses.

2. The *explicit* path here: shard_map over the mesh where each chip holds
   vocab/k contiguous rows, looks up only in-shard ids (clipped gather +
   mask), and psums partial embeddings over the model axis. This is the
   reference-visible semantics made manual — the scatter the Java client did
   per host (DCNClient.java:146-159) happens on-mesh — and it pins down the
   contract the annotation path must match (test_parallel.py asserts
   equality), while being the hook point for a Pallas lookup kernel.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from ..utils.compat import shard_map

from .mesh import DATA_AXIS, MODEL_AXIS


def sharded_field_embed(
    table: jax.Array,
    feat_ids: jax.Array,
    feat_wts: jax.Array,
    mesh: Mesh,
    compute_dtype,
) -> jax.Array:
    """Weighted field lookup with the table sharded over the model axis and
    candidates sharded over the data axis.

    table     [V, D] (V divisible by mesh model-axis size)
    feat_ids  [n, F] int32, already folded into [0, V)
    feat_wts  [n, F] float
    returns   [n, F, D] in compute_dtype, candidate-sharded
    """
    vocab = table.shape[0]
    k = mesh.shape[MODEL_AXIS]
    if vocab % k != 0:
        raise ValueError(f"vocab {vocab} not divisible by model-axis size {k}")

    def local(table_shard, ids_blk, wts_blk):
        # table_shard: [V/k, D] — this chip's contiguous vocab rows.
        vshard = table_shard.shape[0]
        lo = jax.lax.axis_index(MODEL_AXIS) * vshard
        local_ids = ids_blk - lo
        in_shard = (local_ids >= 0) & (local_ids < vshard)
        # Clipped gather stays in-bounds; the mask zeroes out-of-shard rows,
        # so the psum over the model axis reassembles exact lookups.
        emb = jnp.take(table_shard, jnp.clip(local_ids, 0, vshard - 1), axis=0)
        emb = jnp.where(in_shard[..., None], emb, jnp.zeros((), emb.dtype))
        emb = jax.lax.psum(emb, MODEL_AXIS)
        return emb.astype(compute_dtype) * wts_blk[..., None].astype(compute_dtype)

    return shard_map(
        local,
        mesh=mesh,
        in_specs=(P(MODEL_AXIS, None), P(DATA_AXIS, None), P(DATA_AXIS, None)),
        out_specs=P(DATA_AXIS, None, None),
    )(table, feat_ids, feat_wts)
