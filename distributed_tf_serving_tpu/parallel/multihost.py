"""Multi-host (DCN-tier) execution: one logical model spanning TPU hosts.

The reference's distribution fabric tops out at independent servers behind a
client-side scatter (SURVEY.md §2.5: gRPC was the system's entire
"collective"); that topology is preserved by the fan-out client. This module
adds the tier the reference never had: a single SPMD program over a
multi-host slice, where the mesh spans every process's chips, intra-host
traffic rides ICI and cross-host collectives ride DCN via JAX's distributed
runtime (`jax.distributed.initialize`).

Serving on a multi-host mesh has a control-flow problem the training loop
doesn't: requests arrive at ONE host, but every process must enter the same
jitted computation. The answer is a leader/follower step protocol built on
device collectives, with a small broadcast control plane:

- every step starts with a fixed-shape HEADER broadcast `[op, arg]`
  (`multihost_utils.broadcast_one_to_all`), so followers always know what
  shapes the next collective carries before entering it;
- `op=SCORE, arg=bucket`: the batch arrays for that bucket follow in a
  second broadcast, every process runs the sharded forward, and the
  candidate-sharded output is gathered back to the host
  (`process_allgather` preserves shard order => the reference's host-order
  merge semantics, DCNClient.java:161-164). A LADDER of buckets is
  supported (VERDICT r2 weak #6): small requests pay small-bucket padding
  and broadcast bytes, one traced program per bucket on every process;
- `op=RELOAD, arg=version`: every process swaps `params` via the injected
  `param_loader(version)` — hot version rollout without restarting the
  slice. The jitted step takes params as an ARGUMENT, so a reload with
  unchanged shapes recompiles nothing;
- `op=SHUTDOWN`: followers exit their loop.

The gRPC frontend runs on process 0 only, with `as_run_fn()` plugged into a
DynamicBatcher configured with the same bucket ladder; followers are
headless `follow()` loops. A `VersionWatcher` on the leader hot-swaps
versions across the whole slice through `watcher_loader()`. Wire protocol
and client behavior are unchanged.

Failure semantics: a follower that dies stops heartbeating and the JAX
distributed runtime's coordinator terminates the remaining processes with
an error — fail fast and restart the job (tested in test_multihost.py);
"recovering" a lost process mid-collective-stream is not a thing SPMD
serving can do, and pretending otherwise would hang the slice silently.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import threading
from typing import Any, Callable, Sequence

import jax
import numpy as np
from jax.experimental import multihost_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import DATA_AXIS, make_mesh

log = logging.getLogger("dts_tpu.multihost")

# Header ops (first word of the fixed-shape control broadcast).
_OP_SCORE = 0
_OP_RELOAD = 1
_OP_SHUTDOWN = 2


def init_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
    heartbeat_timeout_s: int | None = None,
) -> None:
    """jax.distributed.initialize with env fallbacks (COORDINATOR_ADDRESS /
    NUM_PROCESSES / PROCESS_ID), idempotent for single-process runs.

    heartbeat_timeout_s bounds dead-process detection: when a process dies,
    the coordinator terminates the remaining ones within ~2x this value
    (measured; the default 100s is tuned for preemptible cloud jobs —
    serving deployments want it at ~10s so a dead follower fails the slice
    fast instead of wedging the leader mid-collective)."""
    if num_processes is None:
        num_processes = int(os.environ.get("NUM_PROCESSES", "1"))
    if num_processes <= 1:
        return
    kwargs = {}
    if heartbeat_timeout_s is not None:
        kwargs["heartbeat_timeout_seconds"] = heartbeat_timeout_s
    jax.distributed.initialize(
        coordinator_address=coordinator_address or os.environ["COORDINATOR_ADDRESS"],
        num_processes=num_processes,
        process_id=int(os.environ["PROCESS_ID"]) if process_id is None else process_id,
        **kwargs,
    )


def global_mesh(model_parallel: int = 1) -> Mesh:
    """Mesh over every device of every process: data-major layout so the
    candidate axis spans hosts (each host feeds its contiguous rows).
    Delegates to make_mesh — jax.devices() is already the global (all-
    process) device list under jax.distributed."""
    return make_mesh(model_parallel=model_parallel)


@dataclasses.dataclass
class MultiHostRunner:
    """Leader/follower step protocol over a multi-host mesh.

    `score_fn(params, batch) -> scores` must be identical on every process
    (same model). Batch schema comes from `batch_template` (single bucket,
    the round-2 interface) or `batch_templates` (a bucket ladder): each
    template fixes key order, trailing shapes and dtypes; leading dims are
    the padded bucket sizes. Every process must pass IDENTICAL
    shapes/dtypes into each collective, so lead() validates batches against
    the templates instead of letting a mismatch hang the slice; the header
    broadcast tells followers which bucket's shapes to expect. Static
    shapes keep all processes on one traced program per bucket.

    `param_loader(version) -> params` enables RELOAD: it must resolve the
    same version to the same params on every process (e.g. a shared
    checkpoint base path).
    """

    mesh: Mesh
    params: Any
    score_fn: Callable[[Any, dict[str, jax.Array]], jax.Array]
    batch_template: dict[str, np.ndarray] | None = None
    batch_templates: Sequence[dict[str, np.ndarray]] | None = None
    param_loader: Callable[[int], Any] | None = None
    # RELOADed params are replicated over the mesh by default: loaders
    # typically hand back host or single-device arrays (orbax restore,
    # np.load), which would clash with the mesh-wide sharding constraint.
    # A loader that already places its arrays (EP-sharded tables) sets this
    # False and owns placement itself.
    place_loaded: bool = True

    def __post_init__(self):
        mesh = self.mesh
        templates = list(self.batch_templates or [])
        if self.batch_template is not None:
            templates.append(self.batch_template)
        if not templates:
            raise ValueError("need batch_template or batch_templates")
        keys = tuple(sorted(templates[0]))
        self._keys = keys
        self._zeros: dict[int, dict[str, np.ndarray]] = {}
        for tmpl in templates:
            if tuple(sorted(tmpl)) != keys:
                raise ValueError(
                    f"all templates must share keys; got {sorted(tmpl)} vs {list(keys)}"
                )
            bucket = next(iter(tmpl.values())).shape[0]
            if any(tmpl[k].shape[0] != bucket for k in keys):
                raise ValueError("template arrays disagree on leading (bucket) dim")
            self._zeros[bucket] = {k: np.zeros_like(tmpl[k]) for k in keys}
        self.buckets: tuple[int, ...] = tuple(sorted(self._zeros))
        self.bucket = self.buckets[-1]  # largest (round-2 single-bucket attr)
        # One broadcast/collective stream: the batcher thread (lead) and a
        # version watcher (reload) must never interleave header/payload
        # broadcasts, or the slice desynchronizes into a silent hang.
        self._lock = threading.Lock()
        self.version: int | None = None
        # Constructor params are placed HERE, not lazily: host-numpy params
        # fed to the jitted step would be re-uploaded on EVERY call (there
        # is no host-array transfer cache), and construction is the one
        # protocol point every process reaches together, so the
        # cross-process device_put cannot interleave with later
        # collectives. place_loaded=False callers own placement entirely.
        self.params = self._place(self.params)

        def run(params, batch):
            batch = {
                k: jax.lax.with_sharding_constraint(
                    v, NamedSharding(mesh, P(DATA_AXIS, *(None,) * (v.ndim - 1)))
                )
                for k, v in batch.items()
            }
            return self.score_fn(params, batch)

        self._jitted = jax.jit(run)

    # ------- control plane: fixed-shape header, then bucket-shaped payload

    def _header(self, op: int, arg: int) -> tuple[int, int]:
        out = multihost_utils.broadcast_one_to_all(np.asarray([op, arg], np.int64))
        return int(out[0]), int(out[1])

    def _payload(self, bucket: int, batch: dict[str, np.ndarray] | None):
        zeros = self._zeros[bucket]
        arrays = zeros if batch is None else {k: batch[k] for k in self._keys}
        out = multihost_utils.broadcast_one_to_all(
            tuple(arrays[k] for k in self._keys)
        )
        return {k: np.asarray(v) for k, v in zip(self._keys, out)}

    def _validate(self, batch: dict[str, np.ndarray]) -> int:
        if set(batch) != set(self._keys):
            raise ValueError(
                f"batch keys {sorted(batch)} != template keys {list(self._keys)}"
            )
        bucket = next(iter(batch.values())).shape[0]
        if bucket not in self._zeros:
            raise ValueError(
                f"batch leading dim {bucket} is not a configured bucket "
                f"{self.buckets}; pad to a bucket before lead()"
            )
        for k in self._keys:
            want = self._zeros[bucket][k]
            got = batch[k]
            if got.shape != want.shape or got.dtype != want.dtype:
                raise ValueError(
                    f"batch[{k!r}] is {got.dtype}{got.shape}, template requires "
                    f"{want.dtype}{want.shape} (pad to the bucket and convert "
                    "dtypes before lead(): all processes must broadcast "
                    "identical buffers or the collective hangs)"
                )
        return bucket

    def _step(self, batch: dict[str, np.ndarray]) -> np.ndarray:
        scores = self._jitted(self.params, batch)
        # Candidate-sharded output -> full host-order array on every process
        # (shard order preserved: the reference's concat semantics).
        return np.asarray(multihost_utils.process_allgather(scores, tiled=True))

    # ----------------------------------------------------------------- API

    def lead(self, batch: dict[str, np.ndarray]) -> np.ndarray:
        """Process 0: score one padded batch across all hosts; returns the
        full score vector (caller slices off padding)."""
        bucket = self._validate(batch)
        with self._lock:
            self._header(_OP_SCORE, bucket)
            shared = self._payload(bucket, batch)
            return self._step(shared)

    def reload(self, version: int) -> None:
        """Process 0: hot-swap every process's params to `version` via the
        injected param_loader — the serving slice rolls forward without a
        restart. Unchanged param shapes => no retrace, next lead() serves
        the new version."""
        if self.param_loader is None:
            raise ValueError("reload requires a param_loader")
        # Load BEFORE broadcasting: a leader-side load failure must surface
        # before any follower has acted, or the slice would be left serving
        # v_old leader shards against v_new follower shards — silent skew.
        self._swap(version, self.param_loader(version))
        log.info("hot-swapped to version %d", version)

    def _swap(self, version: int, params) -> None:
        """Broadcast RELOAD and bind already-loaded params (the single swap
        path shared by reload() and watcher_loader). The caller passes
        HOST-loaded params: loading precedes the header broadcast (a
        leader-side load failure surfaces before any follower acts), but
        placement must FOLLOW it — device_put onto a multi-process mesh
        synchronizes across processes, so every process has to enter it at
        the same protocol point (followers place on header receipt)."""
        with self._lock:
            self._header(_OP_RELOAD, version)
            self.params = self._place(params)
            self.version = version

    def _place(self, params):
        if not self.place_loaded:
            return params
        from jax.sharding import NamedSharding, PartitionSpec

        return jax.device_put(params, NamedSharding(self.mesh, PartitionSpec()))

    def follow(self) -> None:
        """Processes 1..k-1: execute leader-broadcast steps until shutdown.

        A failing step re-raises after logging: the leader is blocked inside
        the same SPMD computation, so "recovering" into the broadcast loop
        would only desynchronize the collective stream into a silent hang.
        Exiting lets the distributed runtime's coordinator surface a real
        error on every process — fail fast, restart the job.
        """
        while True:
            op, arg = self._header(_OP_SHUTDOWN, 0)
            if op == _OP_SHUTDOWN:
                return
            try:
                if op == _OP_RELOAD:
                    if self.param_loader is None:
                        raise ValueError(
                            "leader broadcast RELOAD but this follower has no param_loader"
                        )
                    self.params = self._place(self.param_loader(arg))
                    self.version = arg
                else:
                    batch = self._payload(arg, None)
                    self._step(batch)
            except Exception:
                log.exception(
                    "follower step failed; exiting so the coordinator surfaces it"
                )
                raise

    def shutdown(self) -> None:
        """Process 0: release followers."""
        with self._lock:
            self._header(_OP_SHUTDOWN, 0)

    def as_run_fn(self, output_key: str = "prediction_node"):
        """Adapter matching DynamicBatcher's run_fn contract
        (run_fn(servable, arrays) -> {key: array}).

        Configure the batcher with the SAME ladder —
        ``DynamicBatcher(buckets=runner.buckets, run_fn=runner.as_run_fn())``
        — so each dispatch pads to the smallest bucket that fits and every
        process compiles exactly one program per rung. The batcher slices
        each request's rows back out of the returned bucket-sized scores.
        """

        def run(servable, arrays: dict[str, np.ndarray]):
            del servable  # params are runner-owned (RELOAD swaps them)
            n = next(iter(arrays.values())).shape[0]
            bucket = next((b for b in self.buckets if n <= b), None)
            if bucket is None:
                raise ValueError(
                    f"batch of {n} exceeds largest multihost bucket {self.buckets[-1]}"
                )
            zeros = self._zeros[bucket]
            padded = {}
            for k in self._keys:
                tmpl = zeros[k]
                if k not in arrays:
                    padded[k] = tmpl  # optional input (e.g. dense): zeros
                    continue
                arr = np.asarray(arrays[k])
                if arr.shape == tmpl.shape and arr.dtype == tmpl.dtype:
                    # Already bucket-shaped — the recommended setup
                    # (DynamicBatcher with buckets=runner.buckets) pads
                    # before run_fn, so this is every steady-state call;
                    # re-padding would copy MBs per dispatch for nothing.
                    padded[k] = arr
                    continue
                arr = arr.astype(tmpl.dtype, copy=False)
                buf = np.zeros_like(tmpl)
                buf[:n] = arr
                padded[k] = buf
            return {output_key: self.lead(padded)}

        return run

    def watcher_loader(self, base_loader: Callable[[int, Any], Any]):
        """Wrap a VersionWatcher loader so a version load on the leader
        hot-swaps the WHOLE slice: the wrapped loader loads the servable
        (leader-side), then broadcasts RELOAD so every follower's
        param_loader picks up the same version, and binds the new params to
        this runner. Use on process 0 only; followers sit in follow()."""

        def load(version: int, path):
            servable = base_loader(version, path)
            if self.param_loader is None:
                raise ValueError(
                    "watcher integration requires a param_loader (the "
                    "followers load versions through it)"
                )
            # The leader binds the just-loaded params DIRECTLY (no second
            # checkpoint read); the RELOAD broadcast sends followers to
            # their own param_loader for the same version.
            self._swap(version, servable.params)
            log.info("hot-swapped to version %d (watcher)", version)
            return servable

        return load
