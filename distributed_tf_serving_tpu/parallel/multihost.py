"""Multi-host (DCN-tier) execution: one logical model spanning TPU hosts.

The reference's distribution fabric tops out at independent servers behind a
client-side scatter (SURVEY.md §2.5: gRPC was the system's entire
"collective"); that topology is preserved by the fan-out client. This module
adds the tier the reference never had: a single SPMD program over a
multi-host slice, where the mesh spans every process's chips, intra-host
traffic rides ICI and cross-host collectives ride DCN via JAX's distributed
runtime (`jax.distributed.initialize`).

Serving on a multi-host mesh has a control-flow problem the training loop
doesn't: requests arrive at ONE host, but every process must enter the same
jitted computation. The standard JAX answer is a leader/follower step
protocol built on device collectives:

- `MultiHostRunner.lead(batch)` (process 0): broadcast the batch bytes to
  all processes (`multihost_utils.broadcast_one_to_all`), run the sharded
  forward, and gather the candidate-sharded output back to the host
  (`process_allgather` preserves shard order => the reference's host-order
  merge semantics, DCNClient.java:161-164).
- `MultiHostRunner.follow()` (others): block on the same broadcast, execute
  the same step, loop until the leader broadcasts shutdown.

The gRPC frontend then runs on process 0 only, with `as_run_fn()` plugged
into a single-bucket DynamicBatcher; followers are headless `follow()`
loops. Wire protocol and client behavior are unchanged.
"""

from __future__ import annotations

import dataclasses
import logging
import os
from typing import Any, Callable

import jax
import numpy as np
from jax.experimental import multihost_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import DATA_AXIS, make_mesh

log = logging.getLogger("dts_tpu.multihost")

_SHUTDOWN = -1  # broadcast control word: negative candidate count = stop


def init_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> None:
    """jax.distributed.initialize with env fallbacks (COORDINATOR_ADDRESS /
    NUM_PROCESSES / PROCESS_ID), idempotent for single-process runs."""
    if num_processes is None:
        num_processes = int(os.environ.get("NUM_PROCESSES", "1"))
    if num_processes <= 1:
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address or os.environ["COORDINATOR_ADDRESS"],
        num_processes=num_processes,
        process_id=int(os.environ["PROCESS_ID"]) if process_id is None else process_id,
    )


def global_mesh(model_parallel: int = 1) -> Mesh:
    """Mesh over every device of every process: data-major layout so the
    candidate axis spans hosts (each host feeds its contiguous rows).
    Delegates to make_mesh — jax.devices() is already the global (all-
    process) device list under jax.distributed."""
    return make_mesh(model_parallel=model_parallel)


@dataclasses.dataclass
class MultiHostRunner:
    """Leader/follower step protocol over a multi-host mesh.

    `score_fn(params, batch) -> scores` must be identical on every process
    (same model, same params placement). `batch_template` fixes the wire
    schema — key order, shapes (leading dim = the padded bucket), dtypes —
    that every broadcast carries; every process must pass IDENTICAL
    shapes/dtypes into the collective, so lead() validates batches against
    the template instead of letting a mismatch hang the slice. Static
    shapes also keep all processes on one traced program.
    """

    mesh: Mesh
    params: Any
    score_fn: Callable[[Any, dict[str, jax.Array]], jax.Array]
    batch_template: dict[str, np.ndarray]  # zero-filled exemplar batch

    def __post_init__(self):
        mesh = self.mesh
        self._keys = tuple(sorted(self.batch_template))
        self._zeros = {
            k: np.zeros_like(self.batch_template[k]) for k in self._keys
        }
        self.bucket = next(iter(self._zeros.values())).shape[0]

        def run(params, batch):
            batch = {
                k: jax.lax.with_sharding_constraint(
                    v, NamedSharding(mesh, P(DATA_AXIS, *(None,) * (v.ndim - 1)))
                )
                for k, v in batch.items()
            }
            return self.score_fn(params, batch)

        self._jitted = jax.jit(run)

    # ------- control-plane broadcast: (header, *batch arrays in key order)

    def _broadcast(self, n: int, batch: dict[str, np.ndarray] | None):
        arrays = self._zeros if batch is None else {k: batch[k] for k in self._keys}
        header = np.asarray([n], np.int64)
        out = multihost_utils.broadcast_one_to_all(
            (header, *(arrays[k] for k in self._keys))
        )
        shared = {k: np.asarray(v) for k, v in zip(self._keys, out[1:])}
        return int(out[0][0]), shared

    def _validate(self, batch: dict[str, np.ndarray]) -> None:
        if set(batch) != set(self._keys):
            raise ValueError(
                f"batch keys {sorted(batch)} != template keys {list(self._keys)}"
            )
        for k in self._keys:
            want = self._zeros[k]
            got = batch[k]
            if got.shape != want.shape or got.dtype != want.dtype:
                raise ValueError(
                    f"batch[{k!r}] is {got.dtype}{got.shape}, template requires "
                    f"{want.dtype}{want.shape} (pad to the bucket and convert "
                    "dtypes before lead(): all processes must broadcast "
                    "identical buffers or the collective hangs)"
                )

    def _step(self, batch: dict[str, np.ndarray]) -> np.ndarray:
        scores = self._jitted(self.params, batch)
        # Candidate-sharded output -> full host-order array on every process
        # (shard order preserved: the reference's concat semantics).
        return np.asarray(multihost_utils.process_allgather(scores, tiled=True))

    def lead(self, batch: dict[str, np.ndarray]) -> np.ndarray:
        """Process 0: score one padded batch across all hosts; returns the
        full score vector (caller slices off padding)."""
        self._validate(batch)
        _, shared = self._broadcast(self.bucket, batch)
        return self._step(shared)

    def follow(self) -> None:
        """Processes 1..k-1: execute leader-broadcast steps until shutdown.

        A failing step re-raises after logging: the leader is blocked inside
        the same SPMD computation, so "recovering" into the broadcast loop
        would only desynchronize the collective stream into a silent hang.
        Exiting lets the distributed runtime's coordinator surface a real
        error on every process — fail fast, restart the job.
        """
        while True:
            n, batch = self._broadcast(_SHUTDOWN, None)
            if n < 0:
                return
            try:
                self._step(batch)
            except Exception:
                log.exception(
                    "follower step failed; exiting so the coordinator surfaces it"
                )
                raise

    def shutdown(self) -> None:
        """Process 0: release followers."""
        self._broadcast(_SHUTDOWN, None)

    def as_run_fn(self, output_key: str = "prediction_node"):
        """Adapter matching DynamicBatcher's run_fn contract
        (run_fn(servable, arrays) -> {key: array}).

        The runner executes ONE static bucket (all processes share one
        traced program), so configure the batcher with a single-rung ladder
        equal to the template's leading dim — e.g.
        ``DynamicBatcher(buckets=(runner.bucket,), run_fn=runner.as_run_fn())``.
        Arrays are padded up to the bucket here; the batcher slices each
        request's rows back out of the returned full-bucket scores.
        """

        def run(servable, arrays: dict[str, np.ndarray]):
            del servable  # single-model runner; params are bound at construction
            n = next(iter(arrays.values())).shape[0]
            if n > self.bucket:
                raise ValueError(f"batch of {n} exceeds multihost bucket {self.bucket}")
            padded = {}
            for k in self._keys:
                tmpl = self._zeros[k]
                if k not in arrays:
                    padded[k] = tmpl  # optional input (e.g. dense): zeros
                    continue
                arr = np.asarray(arrays[k], dtype=tmpl.dtype)
                buf = np.zeros_like(tmpl)
                buf[:n] = arr
                padded[k] = buf
            return {output_key: self.lead(padded)}

        return run
