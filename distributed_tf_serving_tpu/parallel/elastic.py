"""Elastic mesh serving — pressure-driven data/model parallelism resizing
with hitless executable switching (ISSUE 15).

The [mesh] mode (PR 13) serves ONE static ("data", "model") split chosen at
build time, but the overload plane (PR 5) measures exactly when that choice
is wrong: under saturating load a model-parallel split wastes chips a
data-parallel split would turn into throughput, and at low load the trade
reverses for latency ("Nitsum: Serving Tiered LLM Requests with Adaptive
Tensor Parallelism", PAPERS.md). This module makes the split a RUNTIME
variable:

- **ElasticMeshExecutor** — a drop-in DynamicBatcher run_fn holding one
  hardened ShardedExecutor per configured split (e.g. {8,1}, {4,2}, {2,4}
  over the SAME devices). Every dispatch routes to the CURRENT split;
  warmup pre-compiles every split's executables (and pre-places params)
  so a switch never pays a compile on the serving path.

- **Hitless switching.** `switch_split` flips the routing pointer: new
  dispatches go to the target split immediately while batches already in
  flight on the old split drain to completion — the per-split in-flight
  accounting (issue tokens minted per batch in ``__call__``, closed by
  the batcher's completer via ``note_complete``, the PR-9 per-bucket
  in-flight accounting extended per split) IS the drain barrier: a
  further switch is refused until the previous drain closes, and the
  drain duration is recorded in the switch history ring. No request ever
  fails or waits because of a switch (the devices serialize overlapping
  old-split/new-split work per chip; both executables are warm).

- **ElasticController** — the decision loop: the overload plane's
  NOMINAL/BROWNOUT/SHED pressure state plus a queue-depth/batch-occupancy
  EWMA drive one-rung moves along the split ladder (pressure -> toward
  the data-parallel/throughput end; sustained low load -> toward the
  model-parallel/latency end), with consecutive-tick thresholds, a
  hysteresis band between the load thresholds, and a dwell floor so the
  split never flaps. No background thread: the controller ticks
  opportunistically from the executor's dispatch path and from snapshot()
  (the overload plane's precedent), so a fake clock makes every
  trajectory deterministic under test.

Everything is off by default ([elastic] enabled=false); the plane arms
only on top of [mesh] (build_stack refuses it otherwise). Surfaces: the
`elastic` block inside mesh_stats()//meshz//monitoring and the
dts_tpu_elastic_* Prometheus series.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable

# Split ordering: the ladder is sorted THROUGHPUT end first (most
# data-parallel = model_parallel ascending). "up" = toward index 0
# (throughput), "down" = toward the model-parallel/latency end.
UP, DOWN = "up", "down"


def parse_split(value) -> tuple[int, int]:
    """"4x2" / (4, 2) -> (data, model). Raises ValueError on anything
    else — a typo'd ladder must fail at config time, not at switch time."""
    if isinstance(value, (tuple, list)) and len(value) == 2:
        d, m = value
    else:
        text = str(value).strip().lower()
        d, sep, m = text.partition("x")
        if not sep:
            raise ValueError(
                f"elastic split {value!r} is not of the form 'DATAxMODEL' "
                "(e.g. '4x2')"
            )
    try:
        d, m = int(d), int(m)
    except (TypeError, ValueError) as e:
        raise ValueError(f"elastic split {value!r}: {e}") from e
    if d < 1 or m < 1:
        raise ValueError(f"elastic split {value!r}: axes must be >= 1")
    return d, m


def format_split(split: tuple[int, int]) -> str:
    return f"{split[0]}x{split[1]}"


def resolve_ladder(
    splits, n_devices: int, initial: tuple[int, int]
) -> list[tuple[int, int]]:
    """Normalize a configured ladder (or derive a default one) against the
    device count: every split must factorize exactly n_devices (the ladder
    re-factorizes the SAME chips, it never resizes the slice), the initial
    [mesh] split must be a rung (it is where serving starts), and the
    result is sorted throughput-first. An empty `splits` derives
    {n,1} / {n/2,2} / the initial split — the natural three-rung ladder of
    an 8-chip slice ({8,1}, {4,2}, {2,4})."""
    if splits:
        ladder = {parse_split(s) for s in splits}
    else:
        ladder = {(n_devices, 1), initial}
        if n_devices % 2 == 0:
            ladder.add((n_devices // 2, 2))
    ladder.add(initial)
    for d, m in sorted(ladder):
        if d * m != n_devices:
            raise ValueError(
                f"elastic split {format_split((d, m))} does not factorize "
                f"{n_devices} devices (the ladder re-shapes the same "
                "chips; data*model must equal the mesh device count)"
            )
    out = sorted(ladder, key=lambda s: (s[1], -s[0]))
    if len(out) < 2:
        raise ValueError(
            "elastic needs >= 2 distinct splits to switch between "
            f"(resolved ladder {[format_split(s) for s in out]}); add "
            "[elastic] splits or use a device count with more than one "
            "factorization"
        )
    return out


class ElasticMeshExecutor:
    """run_fn for DynamicBatcher routing each batch to the current split's
    ShardedExecutor, with per-split in-flight accounting as the switch
    drain barrier.

    Completion protocol (the batcher side lives in _run_stage/_complete):
    ``__call__`` registers the batch against the split it routed to and
    leaves an issue token in thread-local state; the batcher pops it with
    ``take_issue_token()`` right after the dispatch returns (same thread,
    synchronous) and hands it to the completer, whose finally calls
    ``note_complete(token)`` once the readback finished — the exact
    lifetime the batcher's own per-bucket in-flight accounting covers, so
    "the old split drained" means what pipeline_stats means by it.
    """

    supports_out_keys = True
    # The batcher's elastic protocol gate: take_issue_token after dispatch,
    # note_complete from the completer, warmup_call warming EVERY split.
    elastic = True

    def __init__(
        self,
        splits,
        initial,
        devices=None,
        compress_transfer: bool = True,
        tensor_parallel: bool = False,
        output_wire_dtype: str = "float32",
        history_events: int = 64,
        clock: Callable[[], float] = time.monotonic,
        executors: dict | None = None,
    ):
        parsed = [parse_split(s) for s in splits]
        if len(set(parsed)) != len(parsed):
            raise ValueError("elastic ladder holds duplicate splits")
        # Pin the throughput-first ordering HERE, where the controller's
        # rung arithmetic consumes it ("up" = toward index 0): a caller
        # passing an unsorted ladder must not get inverted switch
        # directions.
        self.splits: list[tuple[int, int]] = sorted(
            parsed, key=lambda s: (s[1], -s[0])
        )
        initial = parse_split(initial)
        if initial not in self.splits:
            raise ValueError(
                f"initial split {format_split(initial)} is not in the "
                f"ladder {[format_split(s) for s in self.splits]}"
            )
        self._clock = clock
        if executors is not None:
            # Test injection: any mapping split -> run_fn-like callable.
            self._executors = dict(executors)
        else:
            from .executor import ShardedExecutor
            from .mesh import make_mesh

            self._executors = {}
            for d, m in self.splits:
                mesh = make_mesh(d * m, model_parallel=m, devices=devices)
                self._executors[(d, m)] = ShardedExecutor(
                    mesh,
                    compress_transfer=compress_transfer,
                    tensor_parallel=tensor_parallel,
                    output_wire_dtype=output_wire_dtype,
                )
        missing = [s for s in self.splits if s not in self._executors]
        if missing:
            raise ValueError(f"no executor for splits {missing}")
        # The initial split's mesh doubles as the stack's `mesh` (loader
        # pre-placement target); each split's executor places its own
        # copy of the params lazily (warmup does it at load time), which
        # is the HBM price of compile-free switching.
        self.mesh = getattr(self._executors[initial], "mesh", None)
        self._lock = threading.Lock()
        self._tls = threading.local()
        self._current = initial
        # In-flight EPOCH: clear_for_recovery() bumps it, so a STRANDED
        # pre-recovery completer (its batch was captured and replayed)
        # whose finally fires note_complete later cannot decrement the
        # post-recovery registrations — without this, one stray close
        # could release the drain barrier while a new batch is still in
        # flight on the old split.
        self._epoch = 0
        self._inflight = {s: 0 for s in self.splits}
        self._batches = {s: 0 for s in self.splits}
        self._rows = {s: 0 for s in self.splits}
        # Switch drain barrier: the split still draining after the last
        # switch (None = no drain open). A new switch is refused while
        # open — the controller counts the hold and retries next tick.
        self._pending_from: tuple[int, int] | None = None
        self._switch_t0 = 0.0
        self.history: deque = deque(maxlen=max(int(history_events), 1))
        self.switches_up = 0
        self.switches_down = 0
        self.switches_refused_drain = 0
        self.last_drain_s: float | None = None
        # ElasticController attaches itself here; ticked once per dispatch.
        self.controller = None

    # ------------------------------------------------------------- routing

    @property
    def current_split(self) -> tuple[int, int]:
        return self._current

    @property
    def drain_pending(self) -> bool:
        return self._pending_from is not None

    def __call__(self, servable, arrays, out_keys=None):
        rows = next(iter(arrays.values())).shape[0]
        ctrl = self.controller
        if ctrl is not None:
            # Occupancy feed + opportunistic decision tick BEFORE routing,
            # so this very batch can ride a fresh switch (interval-gated).
            ctrl.note_batch(rows)
            ctrl.maybe_tick()
        with self._lock:
            split = self._current
            token = (split, self._epoch)
            self._inflight[split] += 1
            self._batches[split] += 1
            self._rows[split] += rows
        self._tls.token = token
        try:
            return self._executors[split](servable, arrays, out_keys=out_keys)
        except BaseException:
            # A dispatch that never returned outputs is not in flight:
            # close its registration here so the batcher's failure path
            # (which only completes MINTED tokens) cannot strand the
            # drain barrier.
            self._tls.token = None
            with self._lock:
                self._dec_locked(token)
            raise

    def take_issue_token(self):
        """Pop the (split, epoch) token of the JUST-dispatched batch
        (same-thread, called by the batcher right after __call__
        returns). None when no dispatch minted a token on this thread."""
        token = getattr(self._tls, "token", None)
        self._tls.token = None
        return token

    def note_complete(self, token) -> None:
        """Close one batch's in-flight registration (the completer's
        finally — readback done, or the failure path). A token from a
        PREVIOUS epoch (its batch was captured by a recovery cycle and
        the accounting reset; the stranded completer reports in late) is
        a no-op — it must not decrement a post-recovery batch's
        registration."""
        with self._lock:
            self._dec_locked(token)

    def _dec_locked(self, token) -> None:
        split, epoch = token
        if epoch != self._epoch:
            return  # pre-recovery stragglers close against a dead epoch
        n = self._inflight.get(split, 0)
        if n > 0:
            self._inflight[split] = n - 1
        if (
            self._pending_from is not None
            and self._inflight.get(self._pending_from, 0) == 0
        ):
            # The old split drained: the switch is COMPLETE. Record the
            # drain time on the history entry that opened it.
            self.last_drain_s = self._clock() - self._switch_t0
            if self.history:
                self.history[-1]["drain_s"] = round(self.last_drain_s, 6)
            self._pending_from = None

    # ----------------------------------------------------------- switching

    def switch_split(self, target, reason: str = "manual") -> bool:
        """Route new dispatches to `target` (hitless: in-flight old-split
        batches drain to completion behind the barrier). False when the
        switch cannot happen now: already current, unknown split, or the
        PREVIOUS switch's drain is still open (one drain at a time keeps
        "which split is draining" a single answer)."""
        target = parse_split(target)
        if target not in self._executors:
            raise ValueError(
                f"unknown split {format_split(target)}; ladder "
                f"{[format_split(s) for s in self.splits]}"
            )
        with self._lock:
            if target == self._current:
                return False
            if self._pending_from is not None:
                self.switches_refused_drain += 1
                return False
            old = self._current
            self._current = target
            direction = (
                UP if self.splits.index(target) < self.splits.index(old)
                else DOWN
            )
            if direction == UP:
                self.switches_up += 1
            else:
                self.switches_down += 1
            self._switch_t0 = self._clock()
            entry = {
                "t": self._switch_t0,
                "from": format_split(old),
                "to": format_split(target),
                "direction": direction,
                "reason": reason,
                "drained_behind": self._inflight.get(old, 0),
                "drain_s": None,
            }
            self.history.append(entry)
            if self._inflight.get(old, 0) > 0:
                self._pending_from = old
            else:
                self.last_drain_s = 0.0
                entry["drain_s"] = 0.0
            return True

    # ------------------------------------------------------------- warmup

    def warmup_call(self, servable, arrays, out_keys=None):
        """Run one (already host-folded) warmup batch through EVERY
        split's executor — the switch-never-compiles contract: every
        rung's executable for this (bucket, out_keys) variant exists (and
        its params are placed) before serving starts. No issue tokens:
        warmup is not in-flight work. Returns the current split's outputs
        (callers treat warmup results as discardable)."""
        out = None
        for split in self.splits:
            res = self._executors[split](servable, arrays, out_keys=out_keys)
            if split == self._current:
                out = res
        return out

    # ----------------------------------------------------------- recovery

    def clear_for_recovery(self) -> None:
        """REINIT hook (serving/recovery.py): drop every split's placed
        params + compiled entries (they reference the dead backend
        state) and reset the in-flight accounting — captured batches'
        completers are stranded and must not hold the drain barrier open
        forever. The recovery re-warm rebuilds every split's executables
        before replay (see RecoveryController._rewarm)."""
        for ex in self._executors.values():
            clear = getattr(ex, "clear_for_recovery", None)
            if clear is not None:
                clear()
        with self._lock:
            self._epoch += 1  # stranded completers close a dead epoch
            for s in self._inflight:
                self._inflight[s] = 0
            if self._pending_from is not None:
                self._pending_from = None
                self.last_drain_s = self._clock() - self._switch_t0
                if self.history:
                    self.history[-1]["drain_s"] = round(self.last_drain_s, 6)

    # ------------------------------------------------------------ snapshot

    def elastic_snapshot(self) -> dict:
        """The `elastic` surface body (inside mesh_stats()//meshz, the
        /monitoring `elastic` section, and dts_tpu_elastic_*): current
        split, ladder, per-split serve counters + live in-flight, switch
        history ring, and the controller's decision state."""
        ctrl = self.controller
        if ctrl is not None:
            ctrl.maybe_tick()  # scrapes advance the loop on idle servers
        with self._lock:
            snap = {
                "enabled": True,
                "current_split": format_split(self._current),
                "splits": [format_split(s) for s in self.splits],
                "pending_drain_from": (
                    format_split(self._pending_from)
                    if self._pending_from is not None else None
                ),
                "switches_up": self.switches_up,
                "switches_down": self.switches_down,
                "switches_refused_drain": self.switches_refused_drain,
                "last_drain_s": self.last_drain_s,
                "per_split": {
                    format_split(s): {
                        "batches": self._batches[s],
                        "rows": self._rows[s],
                        "in_flight": self._inflight[s],
                    }
                    for s in self.splits
                },
                "history": list(self.history),
            }
        if ctrl is not None:
            snap["controller"] = ctrl.snapshot()
        return snap

    def snapshot(self) -> dict:
        """mesh_stats()-shaped snapshot: the CURRENT split's geometry
        (shape/devices/layout — what the mesh dashboards read) with the
        executor serve counters AGGREGATED across every rung — the
        dts_tpu_mesh_*_total families are process-lifetime counters, and
        reading only the current rung's would jump (usually backward) on
        every switch, which Prometheus reads as a counter reset and
        rate()/increase() over-count from — plus the `elastic` block
        (which keeps the per-rung view)."""
        current = self._current
        ex = self._executors[current]
        base = ex.snapshot() if hasattr(ex, "snapshot") else {"enabled": True}
        # COUNTERS aggregate; placed_servables is a GAUGE ("servables
        # with params placed") and stays the current rung's value —
        # summing it across a warmed ladder would read N servables where
        # there is one.
        totals = {"batches": 0, "rows": 0, "pad_batches": 0,
                  "data_pad_rows": 0}
        layout: dict = {}
        for split, sub in self._executors.items():
            if split == current:
                counters = base.get("executor") or {}  # already computed
            elif hasattr(sub, "snapshot"):
                counters = sub.snapshot().get("executor") or {}
            else:
                continue
            for k in totals:
                totals[k] += int(counters.get(k, 0))
            layout.update(counters.get("layout") or {})
        if base.get("executor"):
            base["executor"] = {**base["executor"], **totals,
                                "layout": layout}
        base["elastic"] = self.elastic_snapshot()
        return base


class ElasticController:
    """The resize decision loop over one ElasticMeshExecutor.

    Signals, read per tick (interval-gated, opportunistic — dispatches
    and snapshot() drive it, no thread):

    - **pressure**: the overload plane's NOMINAL/BROWNOUT/SHED state
      (state() itself ticks that plane, and the `pressure` fault site
      pins it deterministically for tests/CI). Absent controller reads
      as NOMINAL.
    - **load EWMA**: queue fraction (queued+staged candidates /
      capacity), AMPLIFIED by the dispatched-bucket occupancy EWMA when
      the queue is non-empty — a backlog of full largest-bucket batches
      is saturation, a backlog of small ones may just be a wait-window
      artifact. An EMPTY queue always reads as its own (zero) load: a
      lone full-bucket request at a low arrival rate must not hold the
      split at the throughput end forever.

    Decision: pressure past NOMINAL or EWMA >= load_up_threshold counts
    an UP tick (toward the data-parallel/throughput end); NOMINAL and
    EWMA <= load_down_threshold counts a DOWN tick (toward the
    model-parallel/latency end); anything between resets both streaks
    (the hysteresis band). A switch fires one rung at a time after
    up_after_ticks/down_after_ticks consecutive ticks, never inside
    dwell_s of the last switch, and never while the previous switch's
    drain barrier is open.
    """

    def __init__(
        self,
        cfg,
        executor: ElasticMeshExecutor,
        overload=None,
        load_fn: Callable[[], tuple[int, int]] | None = None,
        largest_bucket: int = 0,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.cfg = cfg
        self.executor = executor
        self.overload = overload
        self._load_fn = load_fn
        self._largest_bucket = max(int(largest_bucket or 0), 0)
        self._clock = clock
        self._lock = threading.Lock()
        self._last_tick = clock()
        # Dwell measured from arming: the FIRST switch also waits a full
        # dwell, so a cold server cannot flap before its signals settle.
        self._last_switch = clock()
        self._ewma_load: float | None = None
        self._occ_ewma: float | None = None
        self._up_streak = 0
        self._down_streak = 0
        self._last_pressure = "nominal"
        self.ticks = 0
        self.holds_dwell = 0
        self.holds_drain = 0
        executor.controller = self

    # --------------------------------------------------------------- feeds

    def note_batch(self, rows: int) -> None:
        """Dispatch-side occupancy feed (called by the executor path via
        maybe_tick's caller — rows is the padded bucket size): bucket /
        largest-bucket is the saturation proxy the queue term misses
        when the pipeline drains the queue as fast as it fills."""
        if self._largest_bucket <= 0 or rows <= 0:
            return
        frac = min(rows / self._largest_bucket, 1.0)
        alpha = float(getattr(self.cfg, "load_ewma_alpha", 0.3))
        with self._lock:
            self._occ_ewma = (
                frac if self._occ_ewma is None
                else (1 - alpha) * self._occ_ewma + alpha * frac
            )

    # ---------------------------------------------------------------- tick

    def maybe_tick(self) -> None:
        now = self._clock()
        if now - self._last_tick < float(
            getattr(self.cfg, "tick_interval_s", 0.5)
        ):
            return
        with self._lock:
            if now - self._last_tick < float(
                getattr(self.cfg, "tick_interval_s", 0.5)
            ):
                return
            self._last_tick = now
            self._tick_locked(now)

    def _tick_locked(self, now: float) -> None:
        cfg = self.cfg
        self.ticks += 1
        # Queue-depth term.
        qfrac = 0.0
        if self._load_fn is not None:
            try:
                queued, capacity = self._load_fn()
                qfrac = queued / max(int(capacity), 1)
            except Exception:  # noqa: BLE001 — a signal, never a failure
                qfrac = 0.0
        # Occupancy amplifies a NON-EMPTY queue (backlog of full buckets
        # = saturation); an empty queue is idle whatever the last batch's
        # size was — otherwise one full-bucket request per second would
        # pin the split at the throughput end forever.
        load = max(qfrac, self._occ_ewma or 0.0) if qfrac > 0 else qfrac
        alpha = float(getattr(cfg, "load_ewma_alpha", 0.3))
        self._ewma_load = (
            load if self._ewma_load is None
            else (1 - alpha) * self._ewma_load + alpha * load
        )
        pressure = "nominal"
        ov = self.overload
        if ov is not None:
            try:
                pressure = ov.state()
            except Exception:  # noqa: BLE001 — a signal, never a failure
                pressure = "nominal"
        self._last_pressure = pressure
        up_thresh = float(getattr(cfg, "load_up_threshold", 0.75))
        down_thresh = float(getattr(cfg, "load_down_threshold", 0.20))
        want_up = pressure != "nominal" or self._ewma_load >= up_thresh
        want_down = pressure == "nominal" and self._ewma_load <= down_thresh
        if want_up:
            self._up_streak += 1
            self._down_streak = 0
        elif want_down:
            self._down_streak += 1
            self._up_streak = 0
        else:
            # Hysteresis band: neither signal earns a streak — the split
            # holds where it is.
            self._up_streak = 0
            self._down_streak = 0
        ex = self.executor
        ladder = ex.splits
        cur_i = ladder.index(ex.current_split)
        target = None
        direction = None
        if (
            self._up_streak >= int(getattr(cfg, "up_after_ticks", 2))
            and cur_i > 0
        ):
            target, direction = ladder[cur_i - 1], UP
        elif (
            self._down_streak >= int(getattr(cfg, "down_after_ticks", 6))
            and cur_i < len(ladder) - 1
        ):
            target, direction = ladder[cur_i + 1], DOWN
        if target is None:
            return
        if now - self._last_switch < float(getattr(cfg, "dwell_s", 5.0)):
            self.holds_dwell += 1
            return
        if ex.drain_pending:
            self.holds_drain += 1
            return
        reason = (
            f"pressure={pressure} load_ewma={self._ewma_load:.3f} "
            f"{direction} after {self._up_streak or self._down_streak} ticks"
        )
        if ex.switch_split(target, reason=reason):
            self._last_switch = now
            self._up_streak = 0
            self._down_streak = 0

    # ------------------------------------------------------------ snapshot

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "ticks": self.ticks,
                "pressure": self._last_pressure,
                "load_ewma": (
                    round(self._ewma_load, 4)
                    if self._ewma_load is not None else None
                ),
                "occupancy_ewma": (
                    round(self._occ_ewma, 4)
                    if self._occ_ewma is not None else None
                ),
                "up_streak": self._up_streak,
                "down_streak": self._down_streak,
                "holds_dwell": self.holds_dwell,
                "holds_drain": self.holds_drain,
                "dwell_s": float(getattr(self.cfg, "dwell_s", 5.0)),
                "load_up_threshold": float(
                    getattr(self.cfg, "load_up_threshold", 0.75)
                ),
                "load_down_threshold": float(
                    getattr(self.cfg, "load_down_threshold", 0.20)
                ),
            }
