"""Parameter/batch sharding rules for the CTR model zoo.

Layout policy (the scaling-book recipe: pick a mesh, annotate shardings, let
XLA insert collectives):
- vocab-major tables (embedding bags, wide/linear scalar tables): rows split
  over the model axis — the memory-heavy EP dimension for DLRM-class models.
- dense MLP/cross weights: replicated by default (small for CTR models), or
  — with tensor_parallel — split over the model axis (the §2.4 TP row):
  2-D weights column-sharded on the output-feature dim (row-sharded when
  only the input dim divides), matching biases sharded alongside. XLA's
  SPMD partitioner derives the activation all-gathers/psums the layout
  implies; dims that don't divide the axis stay replicated.
- batches: candidates split over the data axis, replicating the reference's
  per-host candidate shards (DCNClient.java:46-55) on-mesh.
"""

from __future__ import annotations

from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import DATA_AXIS, MODEL_AXIS

# Parameter-tree keys holding vocab-major tables.
VOCAB_MAJOR_KEYS = ("embedding", "wide", "linear")


def param_shardings(
    params: Any,
    mesh: Mesh,
    tensor_parallel: bool = False,
    model_kind: str | None = None,
) -> Any:
    """NamedSharding tree matching `params`: vocab tables split over the
    model axis; dense weights replicated, or model-axis split when
    tensor_parallel (divisible dims only).

    model_kind (when the family has named rules in
    embedding_sharding.MODEL_PARTITION_RULES) resolves the vocab-table
    placements through the explicit match_partition_rules contract
    instead of the path-name heuristic; unmatched leaves fall through to
    the generic dense policy below, so tensor_parallel behaves
    identically on both paths.

    A 1-D param (bias) is split over the model axis only when a sibling 2-D
    weight in the same subtree is column-split with a matching output dim —
    a column-split weight's output y = x @ W is already MODEL_AXIS-sharded
    on features, so the bias layout matches the activation it adds into. A
    row-split weight's output is replicated (post-psum), so its bias must be
    replicated too; sharding it anyway forces the partitioner to insert an
    extra all-gather per layer (round-1 advisor finding)."""
    tp = mesh.shape[MODEL_AXIS]
    vocab_keys = set(VOCAB_MAJOR_KEYS)

    pin = None
    if model_kind is not None:
        from .embedding_sharding import partition_rules_for, rule_matcher

        rules = partition_rules_for(model_kind)
        if rules is not None:
            # (path, leaf) -> pinned spec or None; the rules pin the EP
            # tables, None falls through to the generic dense policy in
            # rule() below.
            pin = rule_matcher(rules)

    def is_vocab(path) -> bool:
        return bool({getattr(p, "key", None) for p in path} & vocab_keys)

    # Output dims of column-split 2-D weights, per parent subtree: a 1-D
    # sibling of that length rides the same feature sharding.
    col_split_dims: dict[tuple, set[int]] = {}
    if tensor_parallel and tp > 1:
        def scan(path, leaf):
            if (
                getattr(leaf, "ndim", 0) == 2
                and not is_vocab(path)
                and leaf.shape[1] % tp == 0
            ):
                col_split_dims.setdefault(path[:-1], set()).add(leaf.shape[1])
            return leaf

        jax.tree_util.tree_map_with_path(scan, params)

    def rule(path, leaf):
        ndim = getattr(leaf, "ndim", 0)
        if pin is not None:
            spec = pin(path, leaf)
            if spec is not None:
                return NamedSharding(mesh, spec)
        if is_vocab(path) and ndim >= 1:
            return NamedSharding(mesh, P(MODEL_AXIS, *(None,) * (ndim - 1)))
        if tensor_parallel and tp > 1:
            shape = getattr(leaf, "shape", ())
            if ndim == 2:
                if shape[1] % tp == 0:  # column split (output features)
                    return NamedSharding(mesh, P(None, MODEL_AXIS))
                if shape[0] % tp == 0:  # row split (input features)
                    return NamedSharding(mesh, P(MODEL_AXIS, None))
            elif ndim == 1 and shape[0] in col_split_dims.get(path[:-1], ()):
                return NamedSharding(mesh, P(MODEL_AXIS))
        return NamedSharding(mesh, P())

    return jax.tree_util.tree_map_with_path(rule, params)


def batch_shardings(batch: dict, mesh: Mesh) -> dict:
    """Candidate-dim sharding for every input array."""
    return {
        k: NamedSharding(mesh, P(DATA_AXIS, *(None,) * (v.ndim - 1)))
        for k, v in batch.items()
    }


def place_params(
    params: Any,
    mesh: Mesh,
    tensor_parallel: bool = False,
    model_kind: str | None = None,
) -> Any:
    """Device-put a param tree according to param_shardings (model_kind
    routes the vocab tables through the named partition rules)."""
    return jax.device_put(
        params, param_shardings(params, mesh, tensor_parallel, model_kind)
    )
