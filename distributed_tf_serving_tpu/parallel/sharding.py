"""Parameter/batch sharding rules for the CTR model zoo.

Layout policy (the scaling-book recipe: pick a mesh, annotate shardings, let
XLA insert collectives):
- vocab-major tables (embedding bags, wide/linear scalar tables): rows split
  over the model axis — the memory-heavy EP dimension for DLRM-class models.
- everything else (MLP/cross weights — small for CTR models): replicated.
- batches: candidates split over the data axis, replicating the reference's
  per-host candidate shards (DCNClient.java:46-55) on-mesh.
"""

from __future__ import annotations

from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import DATA_AXIS, MODEL_AXIS

# Parameter-tree keys holding vocab-major tables.
VOCAB_MAJOR_KEYS = ("embedding", "wide", "linear")


def param_shardings(params: Any, mesh: Mesh) -> Any:
    """NamedSharding tree matching `params`: vocab tables split over the
    model axis, the rest replicated."""

    def rule(path, leaf):
        keys = {getattr(p, "key", None) for p in path}
        if keys & set(VOCAB_MAJOR_KEYS) and getattr(leaf, "ndim", 0) >= 1:
            return NamedSharding(mesh, P(MODEL_AXIS, *(None,) * (leaf.ndim - 1)))
        return NamedSharding(mesh, P())

    return jax.tree_util.tree_map_with_path(rule, params)


def batch_shardings(batch: dict, mesh: Mesh) -> dict:
    """Candidate-dim sharding for every input array."""
    return {
        k: NamedSharding(mesh, P(DATA_AXIS, *(None,) * (v.ndim - 1)))
        for k, v in batch.items()
    }


def place_params(params: Any, mesh: Mesh) -> Any:
    """Device-put a param tree according to param_shardings."""
    return jax.device_put(params, param_shardings(params, mesh))
