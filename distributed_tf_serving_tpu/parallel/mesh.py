"""Device mesh construction and sharding vocabulary.

The reference's distribution fabric is a client-rooted scatter/gather over
per-host gRPC channels (SURVEY.md §2.5, DCNClient.java:118-125,146-164). The
TPU-native replacement is a jax.sharding.Mesh over the slice's chips with
named axes; XLA inserts the ICI collectives implied by the sharding
annotations.

Axis conventions (the recsys analogs of tp/dp/ep from SURVEY.md §2.4):
- "data":  candidate/batch dimension — the reference's candidate sharding
           (its only real strategy) becomes a NamedSharding over this axis.
- "model": embedding vocab rows — the EP analog: DLRM/two-tower tables are
           sharded over this axis and looked up via masked local gathers +
           psum (see embedding_sharding.py).

A v5e-8 slice is the target point (BASELINE.md); tests exercise the same
code on 8 virtual CPU devices.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"


def make_mesh(
    n_devices: int | None = None,
    model_parallel: int = 1,
    devices=None,
) -> Mesh:
    """Build a ("data", "model") mesh over the first n devices.

    model_parallel chips shard embedding vocab; the rest of the factorization
    shards candidates. model_parallel=1 gives pure candidate sharding (the
    reference-equivalent layout).
    """
    devs = list(devices if devices is not None else jax.devices())
    n = n_devices or len(devs)
    if n > len(devs):
        raise ValueError(f"requested {n} devices, have {len(devs)}")
    if n % model_parallel != 0:
        raise ValueError(f"n_devices={n} not divisible by model_parallel={model_parallel}")
    grid = np.asarray(devs[:n]).reshape(n // model_parallel, model_parallel)
    return Mesh(grid, (DATA_AXIS, MODEL_AXIS))


def candidate_sharding(mesh: Mesh) -> NamedSharding:
    """Rows (candidates) split over the data axis — the on-mesh equivalent of
    partitionList's per-host contiguous shards (DCNClient.java:46-55)."""
    return NamedSharding(mesh, P(DATA_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def vocab_sharding(mesh: Mesh) -> NamedSharding:
    """Embedding tables: vocab rows split over the model axis (EP analog)."""
    return NamedSharding(mesh, P(MODEL_AXIS, None))
