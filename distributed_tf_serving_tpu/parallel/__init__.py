"""Parallel layer: device mesh, shardings, sharded executor, EP lookups."""

from .embedding_sharding import (
    MODEL_PARTITION_RULES,
    match_partition_rules,
    partition_rules_for,
    sharded_field_embed,
    tree_path_str,
)
from .elastic import ElasticController, ElasticMeshExecutor
from .executor import ShardedExecutor, shard_map_score
from .mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    candidate_sharding,
    make_mesh,
    replicated,
    vocab_sharding,
)
from .multihost import MultiHostRunner, global_mesh, init_distributed
from .sharding import batch_shardings, param_shardings, place_params

__all__ = [
    "MultiHostRunner",
    "global_mesh",
    "init_distributed",
    "DATA_AXIS",
    "MODEL_AXIS",
    "make_mesh",
    "candidate_sharding",
    "replicated",
    "vocab_sharding",
    "param_shardings",
    "batch_shardings",
    "place_params",
    "ShardedExecutor",
    "ElasticMeshExecutor",
    "ElasticController",
    "shard_map_score",
    "sharded_field_embed",
    "MODEL_PARTITION_RULES",
    "match_partition_rules",
    "partition_rules_for",
    "tree_path_str",
]
