"""Sharded serving executor: candidate scatter / score / ordered gather on a
device mesh.

Drops into DynamicBatcher via its run_fn hook, so the batching logic is
unchanged while execution spans the mesh: the reference's per-host gRPC
scatter (DCNClient.java:146-159) becomes the H2D transfer of a
candidate-sharded batch (each chip receives its contiguous rows over ICI),
and the host-order merge (DCNClient.java:161-164) becomes the ordered
device-to-host gather of the candidate-sharded outputs — contiguous shard
order is preserved by construction, so scores come back in exactly the
reference's concat order.

First-class serving mode (ISSUE 13): the executor is hardened for the
[mesh] production path —

- **Data-axis divisibility is the executor's problem, not the operator's.**
  A bucket the ladder legitimately produces (any size) is padded with zero
  rows to the next multiple of the data-axis size inside __call__ and the
  outputs sliced back before the wire compaction (so e.g. the int8 wire's
  quantization range never sees pad rows). (Historically this raised and
  forced the bucket ladder to be mesh-shaped.) Precision contract: the
  model zoo is row-independent and the pad rows never change WHICH rows
  are served, and the output-FILTERED path (what every production client
  sends — the reference client filters to its output_key) is bit-identical
  to single-chip (CI-gated, TIER1_MESH_SMOKE); an UNFILTERED all-outputs
  request at a padded shape may differ from single-chip by ~1 ULP — the
  padded shape is a different executable and XLA may fuse the
  multi-output graph differently (measured 6e-8 on CPU at one shape) —
  which is float-exact for ranking but not bitwise.
- **Output selection (out_keys) is honored** exactly like the single-chip
  jitted entries: unwanted outputs are DCE'd by XLA and never cross the
  gathered D2H link (supports_out_keys tells the batcher to pass the
  group's union through).
- **Named partition rules**: param placement routes through
  embedding_sharding.MODEL_PARTITION_RULES when the servable's model kind
  has an entry (the match_partition_rules contract), generic path-name
  layout otherwise.
- **Thread-safe entry cache + serving counters** (batches/rows/pad work),
  surfaced as the `mesh` /monitoring block and dts_tpu_mesh_* Prometheus
  series via snapshot().

Also exposes shard_map_score: the explicit shard_map formulation of the same
scatter/score/gather, used to pin the semantics in tests and as the Pallas
hook point.
"""

from __future__ import annotations

import threading
import weakref
from typing import Any

import jax
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P
from ..utils.compat import shard_map

from ..models.registry import Servable
from ..ops.transfer import (
    compact_outputs_device,
    output_wire_dtype as _wire_dtype_of,
    pack_host,
    transfer_spec,
    unpack_device,
)
from .mesh import DATA_AXIS, candidate_sharding
from .sharding import batch_shardings, place_params


class ShardedExecutor:
    """run_fn for DynamicBatcher executing over a mesh.

    Params are placed once per servable (vocab tables split over the model
    axis per the family's named partition rules, rest replicated); each
    batch is jit-executed with candidate-dim in_shardings so XLA scatters
    rows across the data axis and inserts the collectives the embedding
    sharding implies.

    output_wire_dtype mirrors the batcher's output compaction: f32 outputs
    are downcast on-device before the (gathered) D2H readback; the
    batcher's completer widens them back to f32 transparently.
    """

    # The batcher passes the group's output-selection union through
    # run_fn(servable, arrays, out_keys=...) when this is True, so the
    # mesh path gets the same XLA-DCE output filtering as the single-chip
    # jitted entries (PR-1 wire compaction composing with the mesh).
    supports_out_keys = True

    def __init__(
        self,
        mesh: Mesh,
        compress_transfer: bool = True,
        tensor_parallel: bool = False,
        output_wire_dtype: str = "float32",
    ):
        self.mesh = mesh
        self.compress_transfer = compress_transfer
        self.tensor_parallel = tensor_parallel
        self._wire_dt = _wire_dtype_of(output_wire_dtype)
        # Weak keys: an unloaded servable must not pin its placed params or
        # compiled executables (same rationale as DynamicBatcher._jitted).
        self._placed: weakref.WeakKeyDictionary[Servable, Any] = weakref.WeakKeyDictionary()
        self._jitted: weakref.WeakKeyDictionary[Servable, Any] = weakref.WeakKeyDictionary()
        # _prepare is reached from the dispatch thread, the batcher thread
        # (warmup), and measurement harnesses; one lock keeps the variant
        # build single-shot (the batcher's _jit_lock precedent).
        self._lock = threading.Lock()
        # Serving counters (the `mesh` /monitoring block): fed under the
        # lock from __call__ — one increment set per batch, no clock
        # reads on the hot path.
        self.batches = 0
        self.rows = 0  # batch rows received (the batcher's bucket sizes)
        self.data_pad_rows = 0  # zero rows added for data-axis divisibility
        self.pad_batches = 0  # batches that needed the divisibility pad
        self.rules_used: dict[str, str] = {}  # servable name -> layout source

    # ------------------------------------------------------------ internals

    def _prepare(self, servable: Servable):
        """(variant-dispatching fn, spec, placed params) for `servable`,
        built once and rebuilt when servable.params was swapped (re-serving
        after more training) so this path tracks live params like the
        batcher's default path does."""
        key = servable
        with self._lock:
            placed_for = self._placed.get(key)
            if placed_for is not None and placed_for[0] is not servable.params:
                del self._placed[key]
                self._jitted.pop(key, None)
            entry = self._jitted.get(key)
            if entry is None:
                entry = self._build_entry(servable)
                self._jitted[key] = entry
                model_kind = getattr(servable.model, "kind", "") or ""
                from .embedding_sharding import partition_rules_for

                self.rules_used[servable.name] = (
                    f"rules:{model_kind}"
                    if partition_rules_for(model_kind) is not None
                    else "generic"
                )
                self._placed[key] = (
                    servable.params,
                    place_params(
                        servable.params, self.mesh, self.tensor_parallel,
                        model_kind=model_kind or None,
                    ),
                )
            return entry, self._placed[key][1]

    def _build_entry(self, servable: Servable):
        """One callable dispatching per-(out_keys, pad) jit variants — the
        mesh analog of DynamicBatcher._build_entry: each distinct output
        selection is a separate jit closure whose dead outputs XLA DCEs
        (they never materialize in HBM or cross the gathered D2H link);
        the inner jax.jit trace cache still keys on the (padded) batch
        shape, giving one executable per (servable, padded bucket,
        out_keys).

        The data-axis divisibility pad's `pad` joins the variant key so
        the slice back to real rows is TRACED BEFORE the wire compaction:
        the int8 wire's per-tensor quantization range must be computed
        over the real rows only — pad-row scores inside the min/max would
        stretch the scale and perturb every real row's dequantized value
        (single-chip would serve differently). `pad` is bounded by the
        data-axis size, so the variant space stays small, and v[:-pad]
        slices correctly for EVERY bucket sharing that pad amount."""
        spec = transfer_spec(servable.model) if self.compress_transfer else {}
        apply = servable.model.apply
        mesh = self.mesh
        wire = self._wire_dt
        variants: dict[tuple, Any] = {}
        vlock = self._lock

        def make(out_keys, pad):
            def run(params, packed):
                batch = unpack_device(packed, spec)
                # Pin candidate-dim layout inside the computation too, so
                # the partitioner cannot re-shard rows and break merge
                # order.
                batch = {
                    k: jax.lax.with_sharding_constraint(
                        v, candidate_sharding(mesh)
                    )
                    for k, v in batch.items()
                }
                n = next(iter(batch.values())).shape[0]
                out = apply(params, batch)
                if out_keys is not None:
                    picked = {k: v for k, v in out.items() if k in out_keys}
                    out = picked or out  # never trace an empty output pytree
                if pad:
                    # Slice the divisibility pad off BEFORE compaction
                    # (candidate-major outputs only): the wire transform
                    # must never see pad rows. The shape[0]==n test is
                    # the stack-wide contract, not a heuristic: the
                    # batcher's completer slices EVERY output
                    # per-request the same way, so serving outputs are
                    # candidate-major by construction on both paths.
                    out = {
                        k: (v[:-pad]
                            if getattr(v, "ndim", 0) >= 1 and v.shape[0] == n
                            else v)
                        for k, v in out.items()
                    }
                # On-device output compaction: the gathered scores cross
                # the D2H link in the wire dtype; the batcher's completer
                # restores f32.
                return compact_outputs_device(out, wire)

            return jax.jit(run)

        def fn(params, packed, out_keys=None, pad=0):
            key = (out_keys, pad)
            jfn = variants.get(key)
            if jfn is None:
                with vlock:
                    jfn = variants.get(key)
                    if jfn is None:
                        jfn = variants[key] = make(out_keys, pad)
            return jfn(params, packed)

        return fn, spec

    # ----------------------------------------------------------------- API

    def __call__(
        self,
        servable: Servable,
        arrays: dict[str, np.ndarray],
        out_keys: tuple[str, ...] | None = None,
    ):
        (fn, spec), params = self._prepare(servable)
        rows = next(iter(arrays.values())).shape[0]
        data = self.mesh.shape[DATA_AXIS]
        pad = (-rows) % data
        if pad:
            # Candidate-dim sharding splits rows contiguously across the
            # data axis; a non-multiple batch cannot be placed. Pad with
            # zero rows to the next multiple HERE (the zoo scores rows
            # independently, so pad rows never perturb real scores) and
            # slice the candidate-major outputs back below — the bucket
            # ladder stays the operator's latency/occupancy decision, not
            # a mesh-geometry constraint (ISSUE 13 divisibility fix).
            padded = {}
            for k, v in arrays.items():
                buf = np.zeros((rows + pad,) + v.shape[1:], v.dtype)
                buf[:rows] = v
                padded[k] = buf
            arrays = padded
        with self._lock:
            self.batches += 1
            self.rows += rows
            if pad:
                self.pad_batches += 1
                self.data_pad_rows += pad
        packed = pack_host(arrays, spec) if spec else arrays
        packed = jax.device_put(packed, batch_shardings(packed, self.mesh))
        # The slice back to `rows` is traced into the entry (before the
        # wire compaction — see _build_entry), so the returned outputs
        # are already real-rows-only; sidecars are minted after it.
        return fn(params, packed, out_keys=out_keys, pad=pad)

    def clear_for_recovery(self) -> None:
        """REINIT hook ([recovery]×[mesh] compose, ISSUE 15): drop the
        placed params and compiled entries — after a device failure they
        reference the dead backend state, exactly like the single-chip
        batcher's _jitted entries the recovery plane already clears. The
        recovery re-warm rebuilds them through the queue before replay
        (the executor recovers as ONE unit; per-chip recovery of an SPMD
        executable is not a thing)."""
        with self._lock:
            self._placed = weakref.WeakKeyDictionary()
            self._jitted = weakref.WeakKeyDictionary()

    def snapshot(self) -> dict:
        """The `mesh` /monitoring block body: mesh geometry + devices +
        serving counters + the layout source per served model. Per-device
        occupancy attribution rides in from the utilization ledger at the
        impl layer (SPMD batches occupy every chip simultaneously)."""
        with self._lock:
            counters = {
                "batches": self.batches,
                "rows": self.rows,
                "pad_batches": self.pad_batches,
                "data_pad_rows": self.data_pad_rows,
                "placed_servables": len(self._placed),
                "layout": dict(self.rules_used),
            }
        return {
            "enabled": True,
            "shape": {str(k): int(v) for k, v in self.mesh.shape.items()},
            "devices": [str(d) for d in self.mesh.devices.flat],
            "tensor_parallel": self.tensor_parallel,
            "output_wire_dtype": (
                str(np.dtype(self._wire_dt)) if self._wire_dt is not None
                else "float32"
            ),
            "executor": counters,
        }


def shard_map_score(servable: Servable, mesh: Mesh):
    """Explicit scatter/score/gather: each chip scores its contiguous
    candidate block with fully-replicated params; the ordered all-gather is
    implied by the out_spec. Reference-parity formulation (per-host shard ->
    local scoring -> host-order concat)."""
    apply = servable.model.apply

    def local(params, batch):
        return apply(params, batch)["prediction_node"]

    return jax.jit(
        shard_map(
            local,
            mesh=mesh,
            in_specs=(P(), P(DATA_AXIS)),
            out_specs=P(DATA_AXIS),
        )
    )
