"""Sharded serving executor: candidate scatter / score / ordered gather on a
device mesh.

Drops into DynamicBatcher via its run_fn hook, so the batching logic is
unchanged while execution spans the mesh: the reference's per-host gRPC
scatter (DCNClient.java:146-159) becomes the H2D transfer of a
candidate-sharded batch (each chip receives its contiguous rows over ICI),
and the host-order merge (DCNClient.java:161-164) becomes the ordered
device-to-host gather of the candidate-sharded outputs — contiguous shard
order is preserved by construction, so scores come back in exactly the
reference's concat order.

Also exposes shard_map_score: the explicit shard_map formulation of the same
scatter/score/gather, used to pin the semantics in tests and as the Pallas
hook point.
"""

from __future__ import annotations

import weakref
from typing import Any

import jax
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P
from ..utils.compat import shard_map

from ..models.registry import Servable
from ..ops.transfer import (
    compact_outputs_device,
    output_wire_dtype as _wire_dtype_of,
    pack_host,
    transfer_spec,
    unpack_device,
)
from .mesh import DATA_AXIS, candidate_sharding
from .sharding import batch_shardings, param_shardings, place_params


class ShardedExecutor:
    """run_fn for DynamicBatcher executing over a mesh.

    Params are placed once per servable (vocab tables split over the model
    axis, rest replicated); each batch is jit-executed with candidate-dim
    in_shardings so XLA scatters rows across the data axis and inserts the
    collectives the embedding sharding implies.

    output_wire_dtype mirrors the batcher's output compaction: f32 outputs
    are downcast on-device before the (gathered) D2H readback; the
    batcher's completer widens them back to f32 transparently.
    """

    def __init__(
        self,
        mesh: Mesh,
        compress_transfer: bool = True,
        tensor_parallel: bool = False,
        output_wire_dtype: str = "float32",
    ):
        self.mesh = mesh
        self.compress_transfer = compress_transfer
        self.tensor_parallel = tensor_parallel
        self._wire_dt = _wire_dtype_of(output_wire_dtype)
        # Weak keys: an unloaded servable must not pin its placed params or
        # compiled executable (same rationale as DynamicBatcher._jitted).
        self._placed: weakref.WeakKeyDictionary[Servable, Any] = weakref.WeakKeyDictionary()
        self._jitted: weakref.WeakKeyDictionary[Servable, Any] = weakref.WeakKeyDictionary()

    def _prepare(self, servable: Servable):
        key = servable
        # Re-place when servable.params was swapped (e.g. re-serving after
        # more training) so this path tracks live params like the batcher's
        # default path does.
        placed_for = self._placed.get(key)
        if placed_for is not None and placed_for[0] is not servable.params:
            del self._placed[key]
            self._jitted.pop(key, None)
        if key not in self._jitted:
            spec = transfer_spec(servable.model) if self.compress_transfer else {}
            apply = servable.model.apply
            mesh = self.mesh

            wire = self._wire_dt

            def run(params, packed):
                batch = unpack_device(packed, spec)
                # Pin candidate-dim layout inside the computation too, so the
                # partitioner cannot re-shard rows and break merge order.
                batch = {
                    k: jax.lax.with_sharding_constraint(
                        v, candidate_sharding(mesh)
                    )
                    for k, v in batch.items()
                }
                # On-device output compaction: the gathered scores cross
                # the D2H link in the wire dtype; the batcher's completer
                # restores f32.
                return compact_outputs_device(apply(params, batch), wire)

            self._placed[key] = (
                servable.params,
                place_params(servable.params, mesh, self.tensor_parallel),
            )
            self._jitted[key] = (jax.jit(run), spec)
        return self._jitted[key], self._placed[key][1]

    def __call__(self, servable: Servable, arrays: dict[str, np.ndarray]):
        (fn, spec), params = self._prepare(servable)
        rows = next(iter(arrays.values())).shape[0]
        data = self.mesh.shape[DATA_AXIS]
        if rows % data:
            # Candidate-dim sharding splits rows contiguously across the
            # data axis; a non-multiple batch cannot be placed. Surface the
            # configuration fix instead of XLA's divisibility error.
            raise ValueError(
                f"batch of {rows} rows is not divisible by the mesh data "
                f"axis ({data}); configure the batcher bucket ladder with "
                f"multiples of {data} when serving over this mesh"
            )
        packed = pack_host(arrays, spec) if spec else arrays
        packed = jax.device_put(packed, batch_shardings(packed, self.mesh))
        return fn(params, packed)


def shard_map_score(servable: Servable, mesh: Mesh):
    """Explicit scatter/score/gather: each chip scores its contiguous
    candidate block with fully-replicated params; the ordered all-gather is
    implied by the out_spec. Reference-parity formulation (per-host shard ->
    local scoring -> host-order concat)."""
    apply = servable.model.apply

    def local(params, batch):
        return apply(params, batch)["prediction_node"]

    return jax.jit(
        shard_map(
            local,
            mesh=mesh,
            in_specs=(P(), P(DATA_AXIS)),
            out_specs=P(DATA_AXIS),
        )
    )
