"""distributed_tf_serving_tpu — a TPU-native distributed CTR serving framework.

A brand-new JAX/XLA/pjit/Pallas implementation of the capabilities of
neuzxy/Distributed-TF-Serving: a wire-compatible TensorFlow-Serving
`PredictionService` whose backend is an in-tree JAX runtime executing CTR
models (DCN/DCN-v2, Wide&Deep, DeepFM, two-tower, DLRM) on TPU, with
candidate-dimension sharding over the ICI mesh replacing the reference's
per-host gRPC fan-out, and a padded-bucket jit batching engine replacing
TF-Serving's server-side dynamic batching.

Layout:
  proto/     wire-compatible protobuf bindings + hand-written gRPC glue
  codec      TensorProto <-> numpy/jax array conversion
  models/    pure-JAX CTR model zoo + servable registry
  ops/       hot-path ops (Pallas kernels, embedding lookups)
  parallel/  mesh construction, shardings, collectives
  serving/   batching engine + gRPC PredictionService frontend
  client/    asyncio fan-out client + closed-loop bench harness
  train/     sharded training loop + checkpointing
  utils/     config, metrics, tracing
"""

__version__ = "0.1.0"
