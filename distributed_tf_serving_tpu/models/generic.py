"""Generic embed+MLP family — the import-boundary fallback.

The zoo covers the six CTR families the reference ecosystem actually ships
(SURVEY.md §7 endorses zoo-forward serving; the reference itself executes
arbitrary GraphDefs inside tensorflow_model_server, meta_graph.proto:31-87 /
graph.proto:14 upstream — a capability this framework deliberately scopes
to weight import onto native forwards). This family is the documented
best-effort boundary for exports whose architecture is NOT in the zoo
(VERDICT r2 item 7): any model that is structurally "embedding bag ->
dense chain -> logit" — the dominant shape of real-world CTR DNN exports —
serves through this forward, with the architecture dims inferred from the
export's own variable shapes (interop/savedmodel.py
infer_generic_architecture). Anything else gets an actionable rejection
naming the supported families.

Forward (the plain DNN classifier):
  x0    = flatten(field_embed(ids, wts))      [n, F*D]
  h     = relu MLP over mlp_dims              [n, mlp_dims[-1]]
  logit = dense(h)                            [n]
  prediction_node = sigmoid(logit)

Same serving contract as every zoo family (feat_ids/feat_wts ->
prediction_node, DCNClient.java:33-35,98-108,162); same TPU numerics
(bf16 MXU compute, f32 accumulation via mlp_apply/dense_apply).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .base import Model, ModelConfig, dense_apply, dense_init, mlp_apply, mlp_init, register_model
from .embeddings import embedding_init, field_embed


@register_model("generic")
def build_generic(config: ModelConfig) -> Model:
    d = config.num_fields * config.embed_dim

    def init(rng):
        k_emb, k_mlp, k_out = jax.random.split(rng, 3)
        return {
            "embedding": embedding_init(
                k_emb, config.vocab_size, config.embed_dim, config.pdtype
            ),
            "mlp": mlp_init(k_mlp, d, config.mlp_dims, config.pdtype),
            "out": dense_init(
                k_out, config.mlp_dims[-1] if config.mlp_dims else d, 1, config.pdtype
            ),
        }

    def apply(params, batch):
        cd = config.cdtype
        emb = field_embed(params["embedding"], batch["feat_ids"], batch["feat_wts"], cd)
        x0 = emb.reshape(emb.shape[0], d)
        h = mlp_apply(params["mlp"], x0, cd) if config.mlp_dims else x0
        logit = dense_apply(params["out"], h, cd)[:, 0]
        return {"prediction_node": jax.nn.sigmoid(logit), "logits": logit}

    return Model(config=config, init=init, apply=apply)
