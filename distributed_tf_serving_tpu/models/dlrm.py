"""DLRM (BASELINE.json config: "DLRM (embedding-bag heavy), v5e-8 ICI shard,
4k batch").

Bottom MLP over dense features, per-field sparse embedding bag, pairwise
dot-product feature interactions (the DLRM signature op), top MLP over
[bottom output ++ upper-triangle interactions].

Serving contract: accepts the standard feat_ids/feat_wts [n, F] pair plus an
optional `dense_features` float [n, num_dense] input; when absent, dense
features default to zeros so the reference's two-input request shape still
serves. The interaction matmul Z Z^T is the MXU op; it runs in compute_dtype
with f32 accumulation.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .base import Model, ModelConfig, dense_apply, dense_init, mlp_apply, mlp_init, register_model
from .embeddings import embedding_init, field_embed


@register_model("dlrm")
def build_dlrm(config: ModelConfig) -> Model:
    D = config.embed_dim
    F = config.num_fields
    if config.bottom_mlp_dims[-1] != D:
        # The bottom MLP output joins the interaction as one more "field";
        # force its width to the embedding dim like upstream DLRM.
        raise ValueError(
            f"bottom_mlp_dims[-1] ({config.bottom_mlp_dims[-1]}) must equal embed_dim ({D})"
        )
    num_feat = F + 1  # sparse fields + bottom-MLP dense "field"
    num_pairs = num_feat * (num_feat - 1) // 2
    top_in = D + num_pairs

    def init(rng):
        k_emb, k_bot, k_top, k_out = jax.random.split(rng, 4)
        return {
            "embedding": embedding_init(k_emb, config.vocab_size, D, config.pdtype),
            "bottom_mlp": mlp_init(k_bot, config.num_dense_features, config.bottom_mlp_dims, config.pdtype),
            "top_mlp": mlp_init(k_top, top_in, config.mlp_dims, config.pdtype),
            "out": dense_init(k_out, config.mlp_dims[-1], 1, config.pdtype),
        }

    def apply(params, batch):
        cd = config.cdtype
        n = batch["feat_ids"].shape[0]
        dense = batch.get("dense_features")
        if dense is None:
            dense = jnp.zeros((n, config.num_dense_features), jnp.float32)
        bot = mlp_apply(params["bottom_mlp"], dense, cd)  # [n, D]
        emb = field_embed(params["embedding"], batch["feat_ids"], batch["feat_wts"], cd)
        z = jnp.concatenate([bot[:, None, :].astype(cd), emb], axis=1)  # [n, F+1, D]
        # Pairwise dot interactions: upper triangle of Z Z^T (excl. diagonal).
        zzt = jax.lax.dot_general(
            z, z, (((2,), (2,)), ((0,), (0,))), preferred_element_type=jnp.float32
        )  # [n, F+1, F+1]
        iu, ju = jnp.triu_indices(num_feat, k=1)
        inter = zzt[:, iu, ju]  # [n, num_pairs]
        top = jnp.concatenate([bot.astype(jnp.float32), inter], axis=-1)
        logit = dense_apply(params["out"], mlp_apply(params["top_mlp"], top, cd), cd)[:, 0]
        return {"prediction_node": jax.nn.sigmoid(logit), "logits": logit}

    return Model(config=config, init=init, apply=apply)
