"""Model runtime core: functional CTR models + builder registry.

The reference delegates model execution to an external SavedModel inside
tensorflow_model_server (SURVEY.md §0); here models are in-tree pure-JAX
functions. Every model follows the serving contract the reference's client
expects (DCNClient.java:33-35,98-108,162):

  inputs : feat_ids  int64  [n, num_fields]   hashed categorical ids
           feat_wts  float  [n, num_fields]   per-feature weights
  output : prediction_node  float [n]         CTR score in [0, 1]

Models are (init, apply) pairs over pytrees — no framework classes — so they
compose directly with jit/pjit/shard_map/grad. TPU-first numerics: parameters
live in float32, matmul compute runs in a configurable dtype (bfloat16 by
default for MXU throughput) with float32 accumulation via
preferred_element_type; `compute_dtype="float32"` is the AUC-parity mode
(BASELINE.md: parity to 1e-6 vs the f32 GPU baseline).
"""

from __future__ import annotations

import dataclasses
from collections.abc import Callable
from typing import Any

import jax
import jax.numpy as jnp

Params = Any  # pytree of jax.Arrays
Batch = dict[str, jax.Array]


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    """Knob set shared by the CTR model zoo.

    Matches the reference workload point where applicable: num_fields=43
    (FIELD_NUM, DCNClient.java:25).
    """

    name: str = "DCN"
    num_fields: int = 43
    vocab_size: int = 1 << 20
    embed_dim: int = 16
    mlp_dims: tuple[int, ...] = (256, 128, 64)
    # DCN / DCN-v2
    num_cross_layers: int = 3
    cross_full_matrix: bool = False  # False => DCN-v1 rank-1 cross, True => DCN-v2
    # two-tower
    num_user_fields: int = 8
    # DLRM
    num_dense_features: int = 13
    bottom_mlp_dims: tuple[int, ...] = (64, 32, 16)
    # numerics
    compute_dtype: str = "bfloat16"  # "float32" for AUC-parity mode
    param_dtype: str = "float32"
    # Fused Pallas cross-layer kernel (DCN-v2 only). Wins when F*embed_dim is
    # 128-lane aligned (e.g. 1024): activations stay VMEM-resident across
    # layers. At unaligned widths padding eats the gain — hence opt-in.
    use_pallas_cross: bool = False

    @property
    def cdtype(self) -> jnp.dtype:
        return jnp.dtype(self.compute_dtype)

    @property
    def pdtype(self) -> jnp.dtype:
        return jnp.dtype(self.param_dtype)


@dataclasses.dataclass(frozen=True)
class Model:
    """A functional model: params = init(rng); outputs = apply(params, batch).

    wts_in_compute_dtype: True when the model consumes feat_wts exclusively
    after casting to compute_dtype (via embeddings.field_embed) — the
    precondition for the batcher's lossless bf16 weight-transfer compression.
    Models with a float32 sparse-linear term over the raw weights
    (wide_deep, deepfm) must leave it False.

    score_output: the name of the per-candidate score vector in the apply()
    output dict — the one tensor the serving path ultimately ranks on.
    The batcher's output-compaction pipeline keys on it: wire-dtype
    downcast applies to every f32 output, but top-k compaction (retrieval-
    style servables, e.g. two_tower scoring a large candidate set) returns
    only this vector's top-k (score, index) pairs over the D2H link.
    """

    config: ModelConfig
    init: Callable[[jax.Array], Params]
    apply: Callable[[Params, Batch], dict[str, jax.Array]]
    wts_in_compute_dtype: bool = True
    score_output: str = "prediction_node"
    # False for graph-executor models (interop/graph_exec.py): the imported
    # graph consumes RAW int64 ids (its own hashing/mod/lookup semantics),
    # so the batcher must not vocab-fold them on host.
    folds_ids_on_host: bool = True
    # True when the model's graph carries int64/float64 tensors that JAX's
    # default 32-bit canonicalization would silently corrupt; the batcher
    # traces AND calls such models inside jax.enable_x64().
    needs_x64: bool = False
    # Zoo family this model was built as (build_model stamps it); "" for
    # directly-constructed models (imported graphs, tests). The mesh
    # serving mode keys its named partition rules on it
    # (parallel/embedding_sharding.MODEL_PARTITION_RULES) — unknown kinds
    # fall back to the generic path-name layout.
    kind: str = ""


# ---------------------------------------------------------------------------
# Shared building blocks
# ---------------------------------------------------------------------------


def dense_init(rng: jax.Array, in_dim: int, out_dim: int, dtype) -> dict[str, jax.Array]:
    """He-style init for a dense layer."""
    wkey, _ = jax.random.split(rng)
    scale = jnp.sqrt(2.0 / in_dim).astype(dtype)
    return {
        "w": jax.random.normal(wkey, (in_dim, out_dim), dtype) * scale,
        "b": jnp.zeros((out_dim,), dtype),
    }


def dense_apply(p: dict[str, jax.Array], x: jax.Array, compute_dtype) -> jax.Array:
    """x @ w + b in compute_dtype with f32 accumulation on the MXU.

    Accepts both param forms: the float {"w", "b"} layer and the int8
    weight-only quantized {"qw", "qscale", "b"} form ops/quantize.py mints
    (per-channel symmetric). For the quantized form the matmul streams the
    int8 weights cast to compute dtype (magnitudes <= 127 are exact in
    bf16) and the per-OUTPUT-channel scale folds into the f32 accumulator
    output — algebraically identical to dequantizing the weights first,
    without materializing an [in, out] float matrix per call."""
    qw = p.get("qw")
    if qw is not None:
        y = jax.lax.dot_general(
            x.astype(compute_dtype),
            qw.astype(compute_dtype),
            (((x.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * p["qscale"].astype(jnp.float32)
        return y + p["b"].astype(jnp.float32)
    y = jax.lax.dot_general(
        x.astype(compute_dtype),
        p["w"].astype(compute_dtype),
        (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    return y + p["b"].astype(jnp.float32)


def mlp_init(rng: jax.Array, in_dim: int, dims: tuple[int, ...], dtype) -> list:
    layers = []
    for out_dim in dims:
        rng, sub = jax.random.split(rng)
        layers.append(dense_init(sub, in_dim, out_dim, dtype))
        in_dim = out_dim
    return layers


def mlp_apply(layers: list, x: jax.Array, compute_dtype, final_relu: bool = True) -> jax.Array:
    for i, p in enumerate(layers):
        x = dense_apply(p, x, compute_dtype)
        if final_relu or i + 1 < len(layers):
            x = jax.nn.relu(x)
    return x


# ---------------------------------------------------------------------------
# Builder registry
# ---------------------------------------------------------------------------

_BUILDERS: dict[str, Callable[[ModelConfig], Model]] = {}


def register_model(kind: str):
    def deco(fn: Callable[[ModelConfig], Model]):
        _BUILDERS[kind] = fn
        return fn

    return deco


def build_model(kind: str, config: ModelConfig | None = None, **overrides) -> Model:
    """Instantiate a model family by kind: dcn, dcn_v2, wide_deep, deepfm,
    two_tower, dlrm."""
    if kind not in _BUILDERS:
        raise KeyError(f"unknown model kind {kind!r}; have {sorted(_BUILDERS)}")
    if config is None:
        config = ModelConfig(**overrides)
    elif overrides:
        config = dataclasses.replace(config, **overrides)
    model = _BUILDERS[kind](config)
    if not model.kind:
        # Stamp the family so downstream layout policy (mesh partition
        # rules) can key on it without re-plumbing the kind string.
        model = dataclasses.replace(model, kind=kind)
    return model


def model_kinds() -> list[str]:
    return sorted(_BUILDERS)
