"""Two-tower retrieval model (BASELINE.json config: "Two-tower retrieval
(user/item embed), 10k candidate scoring").

Fields split positionally: the first `num_user_fields` are the user/context
tower's, the rest are the item tower's. Each tower is an MLP over its
weighted embedding bag producing an L2-normalized embedding; the score is the
scaled dot product. The serving contract stays feat_ids/feat_wts [n, F] →
prediction_node [n]: for candidate scoring the caller replicates the user
fields into each candidate row, which keeps the request shape identical to
the reference's DCN workload and lets candidate sharding apply unchanged.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .base import Model, ModelConfig, mlp_apply, mlp_init, register_model
from .embeddings import embedding_init, field_embed


@register_model("two_tower")
def build_two_tower(config: ModelConfig) -> Model:
    nu = config.num_user_fields
    ni = config.num_fields - nu
    if ni <= 0:
        raise ValueError(f"num_user_fields={nu} must be < num_fields={config.num_fields}")
    du, di = nu * config.embed_dim, ni * config.embed_dim

    def init(rng):
        k_emb, k_user, k_item = jax.random.split(rng, 3)
        return {
            "embedding": embedding_init(k_emb, config.vocab_size, config.embed_dim, config.pdtype),
            "user_mlp": mlp_init(k_user, du, config.mlp_dims, config.pdtype),
            "item_mlp": mlp_init(k_item, di, config.mlp_dims, config.pdtype),
            "temperature": jnp.asarray(10.0, config.pdtype),
        }

    def _tower(layers, emb, cd):
        x = mlp_apply(layers, emb.reshape(emb.shape[0], -1), cd, final_relu=False)
        x = x.astype(jnp.float32)
        return x / (jnp.linalg.norm(x, axis=-1, keepdims=True) + 1e-12)

    def apply(params, batch):
        cd = config.cdtype
        emb = field_embed(params["embedding"], batch["feat_ids"], batch["feat_wts"], cd)
        u = _tower(params["user_mlp"], emb[:, :nu], cd)
        v = _tower(params["item_mlp"], emb[:, nu:], cd)
        score = jnp.sum(u * v, axis=-1) * params["temperature"].astype(jnp.float32)
        return {"prediction_node": jax.nn.sigmoid(score), "logits": score}

    return Model(config=config, init=init, apply=apply)
