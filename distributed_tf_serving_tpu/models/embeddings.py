"""Embedding tables and weighted field lookups.

The reference's models (external SavedModels) consume hashed categorical ids
with per-feature weights (feat_ids/feat_wts, DCNClient.java:98-108). Here the
embedding bag is explicit: a single [vocab, dim] table, ids folded into the
vocab by modulo, gathered with jnp.take, and scaled by the feature weight.

TPU notes: the gather lowers to a dynamic-gather XLA op that is
HBM-bandwidth-bound; ids arrive [n, F] and the gather is batched over both
axes at once (one gather of n*F rows) so XLA can tile it. The vocab axis is
the sharding axis for the EP analog (SURVEY.md §2.4): under shard_map each
chip owns vocab/num_chips rows and out-of-shard ids contribute zero, summed
back with psum — see parallel/embedding_sharding.py.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def embedding_init(rng: jax.Array, vocab_size: int, embed_dim: int, dtype) -> jax.Array:
    # 1/sqrt(dim) scale keeps dot-product magnitudes O(1) for FM/two-tower.
    return jax.random.normal(rng, (vocab_size, embed_dim), dtype) / jnp.asarray(
        embed_dim**0.5, dtype
    )


def fold_ids(ids: jax.Array, vocab_size: int) -> jax.Array:
    """Fold arbitrary int64 feature ids into table rows (modulo hashing)."""
    return jnp.remainder(ids, vocab_size).astype(jnp.int32)


def sparse_linear(
    table: jax.Array,
    feat_ids: jax.Array,
    feat_wts: jax.Array,
) -> jax.Array:
    """Per-id scalar-weight sum in float32 — the Wide&Deep wide half and the
    DeepFM first-order term.

    table     [V]
    feat_ids  [n, F] int
    feat_wts  [n, F] float
    returns   [n] float32

    Runs in float32 regardless of the model's compute dtype (a scalar
    reduction, not an MXU op), which is why models using it must opt out of
    bf16 weight-transfer compression (Model.wts_in_compute_dtype=False).
    """
    rows = fold_ids(feat_ids, table.shape[0])
    return jnp.sum(
        jnp.take(table, rows, axis=0).astype(jnp.float32) * feat_wts.astype(jnp.float32),
        axis=-1,
    )


def field_embed(
    table: jax.Array,
    feat_ids: jax.Array,
    feat_wts: jax.Array,
    compute_dtype,
) -> jax.Array:
    """Weighted per-field embedding lookup.

    table     [V, D]
    feat_ids  [n, F] int
    feat_wts  [n, F] float
    returns   [n, F, D] in compute_dtype
    """
    rows = fold_ids(feat_ids, table.shape[0])
    emb = jnp.take(table, rows, axis=0)  # [n, F, D]
    return emb.astype(compute_dtype) * feat_wts[..., None].astype(compute_dtype)
