"""Wide&Deep CTR model (BASELINE.json config: "Wide&Deep CTR SavedModel").

Wide half: a per-id scalar weight table (a [V,1] embedding) summed over
fields with feature weights — the classic sparse-linear memorization path.
Deep half: MLP over the shared embedding bag. Serving contract identical to
DCN (feat_ids/feat_wts -> prediction_node).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .base import Model, ModelConfig, dense_apply, dense_init, mlp_apply, mlp_init, register_model
from .embeddings import embedding_init, field_embed, sparse_linear


@register_model("wide_deep")
def build_wide_deep(config: ModelConfig) -> Model:
    d = config.num_fields * config.embed_dim

    def init(rng):
        k_wide, k_emb, k_mlp, k_out = jax.random.split(rng, 4)
        return {
            "wide": jax.random.normal(k_wide, (config.vocab_size,), config.pdtype) * 0.01,
            "wide_bias": jnp.zeros((), config.pdtype),
            "embedding": embedding_init(k_emb, config.vocab_size, config.embed_dim, config.pdtype),
            "mlp": mlp_init(k_mlp, d, config.mlp_dims, config.pdtype),
            "out": dense_init(k_out, config.mlp_dims[-1], 1, config.pdtype),
        }

    def apply(params, batch):
        cd = config.cdtype
        ids, wts = batch["feat_ids"], batch["feat_wts"]
        # Wide: sum of per-id scalar weights, feature-weighted (f32).
        wide = sparse_linear(params["wide"], ids, wts) + params["wide_bias"].astype(jnp.float32)
        # Deep: MLP over flattened weighted embeddings.
        emb = field_embed(params["embedding"], ids, wts, cd)
        xd = mlp_apply(params["mlp"], emb.reshape(emb.shape[0], d), cd)
        logit = dense_apply(params["out"], xd, cd)[:, 0] + wide
        return {"prediction_node": jax.nn.sigmoid(logit), "logits": logit}

    # The wide half consumes raw f32 weights -> bf16 weight-transfer
    # compression would change scores; opt out.
    return Model(config=config, init=init, apply=apply, wts_in_compute_dtype=False)
