"""Pure-JAX CTR model zoo + servable registry.

Model families cover every BASELINE.json config: dcn / dcn_v2 (the
reference's served model, DCNClient.java:33), wide_deep, deepfm, two_tower,
dlrm. All share the reference serving contract feat_ids/feat_wts [n, F] ->
prediction_node [n].
"""

from .base import Batch, Model, ModelConfig, Params, build_model, model_kinds
from .registry import (
    DEFAULT_SIGNATURE,
    ModelNotFoundError,
    Servable,
    ServableRegistry,
    Signature,
    SignatureNotFoundError,
    TensorSpec,
    VersionNotFoundError,
    ctr_signatures,
)

# Import model modules for their registration side effects.
from . import dcn, deepfm, dlrm, generic, two_tower, wide_deep  # noqa: E402,F401

__all__ = [
    "Batch",
    "Model",
    "ModelConfig",
    "Params",
    "build_model",
    "model_kinds",
    "Servable",
    "ServableRegistry",
    "Signature",
    "TensorSpec",
    "ctr_signatures",
    "DEFAULT_SIGNATURE",
    "ModelNotFoundError",
    "VersionNotFoundError",
    "SignatureNotFoundError",
]
