"""DCN / DCN-v2 — the reference's flagship model family.

The reference serves an externally-exported "DCN" SavedModel with signature
"serving_default" over inputs feat_ids/feat_wts [n,43] and output
prediction_node [n] (DCNClient.java:33-35,98-108,162). This is the in-tree
TPU-native equivalent: explicit cross network + deep MLP over a shared
embedding bag.

Cross layers (per Wang et al.):
  v1 (rank-1):     x_{l+1} = x0 * (x_l . w_l) + b_l + x_l       w_l: [d]
  v2 (full-rank):  x_{l+1} = x0 * (x_l @ W_l + b_l) + x_l       W_l: [d, d]

The v2 matmul is the MXU hot op; it runs in compute_dtype (bf16 default) with
f32 accumulation. The fused-elementwise Pallas variant lives in
ops/cross_kernel.py and is numerically identical.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .base import Model, ModelConfig, dense_apply, dense_init, mlp_apply, mlp_init, register_model
from .embeddings import embedding_init, field_embed


def _cross_init(rng, num_layers: int, d: int, full_matrix: bool, dtype):
    layers = []
    for _ in range(num_layers):
        rng, sub = jax.random.split(rng)
        if full_matrix:
            w = jax.random.normal(sub, (d, d), dtype) / jnp.asarray(d**0.5, dtype)
        else:
            w = jax.random.normal(sub, (d,), dtype) / jnp.asarray(d**0.5, dtype)
        layers.append({"w": w, "b": jnp.zeros((d,), dtype)})
    return layers


def cross_apply(layers, x0: jax.Array, compute_dtype) -> jax.Array:
    """Apply the stack of cross layers; x0 is [n, d] in compute_dtype.
    Accepts both the float {"w","b"} layers and the int8 weight-only
    quantized {"qw","qscale","b"} form (ops/quantize.py): the per-channel
    scale folds into the f32 xw before the elementwise update, so the
    quantized stack differs from f32 only by the weight rounding."""
    x = x0
    for p in layers:
        b = p["b"].astype(jnp.float32)
        if "qw" in p:  # quantized DCN-v2 (v1 rank-1 layers never quantize)
            xw = jax.lax.dot_general(
                x, p["qw"].astype(compute_dtype),
                (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32,
            ) * p["qscale"].astype(jnp.float32)
            x = (x0.astype(jnp.float32) * (xw + b) + x.astype(jnp.float32)).astype(compute_dtype)
            continue
        w = p["w"].astype(compute_dtype)
        if w.ndim == 2:  # DCN-v2
            xw = jax.lax.dot_general(
                x, w, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
            )
            x = (x0.astype(jnp.float32) * (xw + b) + x.astype(jnp.float32)).astype(compute_dtype)
        else:  # DCN-v1
            xw = jnp.sum(x.astype(jnp.float32) * w.astype(jnp.float32), axis=-1, keepdims=True)
            x = (x0.astype(jnp.float32) * xw + b + x.astype(jnp.float32)).astype(compute_dtype)
    return x


def _build(config: ModelConfig) -> Model:
    d = config.num_fields * config.embed_dim

    def init(rng):
        k_emb, k_cross, k_mlp, k_out = jax.random.split(rng, 4)
        mlp = mlp_init(k_mlp, d, config.mlp_dims, config.pdtype)
        out_in = d + (config.mlp_dims[-1] if config.mlp_dims else 0)
        return {
            "embedding": embedding_init(k_emb, config.vocab_size, config.embed_dim, config.pdtype),
            "cross": _cross_init(
                k_cross, config.num_cross_layers, d, config.cross_full_matrix, config.pdtype
            ),
            "mlp": mlp,
            "out": dense_init(k_out, out_in, 1, config.pdtype),
        }

    def apply(params, batch):
        cd = config.cdtype
        emb = field_embed(params["embedding"], batch["feat_ids"], batch["feat_wts"], cd)
        x0 = emb.reshape(emb.shape[0], d)  # [n, F*D]
        use_fused = (
            config.use_pallas_cross
            and config.cross_full_matrix
            # The legacy cross-only kernel takes float stacked weights; a
            # quantized tree (ops/quantize.py {"qw"} form) rides the XLA
            # path here — the int8-operand FUSED kernel is the serving
            # batcher's per-bucket variant, not this opt-in.
            and "w" in params["cross"][0]
        )
        if use_fused:
            from ..ops.cross_kernel import fits_vmem

            # Oversized stacks (all L weight matrices are VMEM-resident in
            # the fused kernel) fall back to the per-layer XLA path.
            use_fused = fits_vmem(d, config.num_cross_layers, cd)
        if use_fused:
            import jax as _jax

            from ..ops.cross_kernel import cross_params_to_stacked, fused_cross_apply

            w, b = cross_params_to_stacked(params["cross"])
            # interpret mode keeps the kernel runnable on the CPU test mesh.
            xc = fused_cross_apply(
                x0, w, b, compute_dtype=cd, interpret=_jax.default_backend() == "cpu"
            )
        else:
            xc = cross_apply(params["cross"], x0, cd)
        xd = mlp_apply(params["mlp"], x0, cd)
        h = jnp.concatenate([xc.astype(jnp.float32), xd.astype(jnp.float32)], axis=-1)
        logit = dense_apply(params["out"], h, cd)[:, 0]
        return {"prediction_node": jax.nn.sigmoid(logit), "logits": logit}

    return Model(config=config, init=init, apply=apply)


@register_model("dcn")
def build_dcn(config: ModelConfig) -> Model:
    import dataclasses

    return _build(dataclasses.replace(config, cross_full_matrix=False))


@register_model("dcn_v2")
def build_dcn_v2(config: ModelConfig) -> Model:
    import dataclasses

    return _build(dataclasses.replace(config, cross_full_matrix=True))
