"""Servable registry: model name -> versions -> signatures.

Replicates the model-resolution semantics the reference reaches through
ModelSpec (model.proto:9-19): requests name a model, optionally pin a version
via the Int64Value wrapper (absent => latest loaded version,
model.proto:12-14), and select a signature by name (default
"serving_default", matching DCNClient.java:34). GetModelMetadata serves the
stored SignatureDefs (get_model_metadata.proto:15-30).
"""

from __future__ import annotations

import dataclasses
import functools
import threading

import jax.numpy as jnp
import numpy as np

# NOTE: the proto bindings are imported LAZILY inside the functions that
# build protobuf messages (to_tensor_info / to_signature_def /
# ctr_signatures): our vendored tensorflow.* descriptors collide with
# TensorFlow's own in the process-wide descriptor pool, and the SavedModel
# EXPORT path (interop/export.py) must import tensorflow + this models
# package in ONE process. Keeping this module proto-free at import time is
# what makes that possible.
from .base import Batch, Model, Params

# TF-Serving method names carried in SignatureDef.method_name.
PREDICT_METHOD = "tensorflow/serving/predict"
CLASSIFY_METHOD = "tensorflow/serving/classify"
REGRESS_METHOD = "tensorflow/serving/regress"

DEFAULT_SIGNATURE = "serving_default"


class ModelNotFoundError(KeyError):
    pass


class VersionNotFoundError(KeyError):
    pass


class SignatureNotFoundError(KeyError):
    pass


@dataclasses.dataclass(frozen=True)
class TensorSpec:
    name: str  # logical tensor alias (the request/response map key)
    dtype: int  # fw.DataType value
    # Per-dim None = unknown/batch dim; whole-shape None = unknown rank
    # (tensor_shape.proto unknown_rank, seen in imported SavedModels).
    shape: tuple[int | None, ...] | None

    def to_tensor_info(self):
        from ..proto import tf_meta_graph_pb2 as mg

        info = mg.TensorInfo(name=f"{self.name}:0", dtype=self.dtype)
        if self.shape is None:
            info.tensor_shape.unknown_rank = True
        else:
            for s in self.shape:
                info.tensor_shape.dim.add(size=-1 if s is None else s)
        return info


@dataclasses.dataclass(frozen=True)
class Signature:
    """One servable signature: typed I/O contract + method name."""

    inputs: tuple[TensorSpec, ...]
    outputs: tuple[TensorSpec, ...]
    method_name: str = PREDICT_METHOD

    # cached_property writes the instance __dict__ directly, which frozen
    # dataclasses permit: rebuilding these per request showed up in the
    # round-3 serving profile.
    @functools.cached_property
    def input_specs(self) -> dict[str, TensorSpec]:
        return {s.name: s for s in self.inputs}

    @functools.cached_property
    def output_names(self) -> list[str]:
        return [s.name for s in self.outputs]

    def to_signature_def(self):
        from ..proto import tf_meta_graph_pb2 as mg

        sd = mg.SignatureDef(method_name=self.method_name)
        for spec in self.inputs:
            sd.inputs[spec.name].CopyFrom(spec.to_tensor_info())
        for spec in self.outputs:
            sd.outputs[spec.name].CopyFrom(spec.to_tensor_info())
        return sd


def ctr_signatures(num_fields: int, with_dense: int | None = None) -> dict[str, Signature]:
    """The standard CTR signature set matching the reference contract
    (feat_ids int64 [n,F] + feat_wts float [n,F] -> prediction_node [n])."""
    # Hardcoded DataType values (types.proto, wire-frozen since TF 1.0:
    # DT_FLOAT=1, DT_STRING=7, DT_INT64=9) rather than the proto enum: the
    # SavedModel EXPORT path calls this from a process where TensorFlow
    # owns the descriptor pool, so this function must not import the
    # vendored bindings even lazily (tests/test_codec.py pins these values
    # against the real enum).
    DT_FLOAT, DT_STRING, DT_INT64 = 1, 7, 9
    inputs = [
        TensorSpec("feat_ids", DT_INT64, (None, num_fields)),
        TensorSpec("feat_wts", DT_FLOAT, (None, num_fields)),
    ]
    if with_dense:
        inputs.append(TensorSpec("dense_features", DT_FLOAT, (None, with_dense)))
    predict = Signature(
        inputs=tuple(inputs),
        outputs=(
            TensorSpec("prediction_node", DT_FLOAT, (None,)),
            TensorSpec("logits", DT_FLOAT, (None,)),
        ),
        method_name=PREDICT_METHOD,
    )
    classify = dataclasses.replace(
        predict,
        outputs=(
            TensorSpec("scores", DT_FLOAT, (None, 2)),
            TensorSpec("classes", DT_STRING, (None, 2)),
        ),
        method_name=CLASSIFY_METHOD,
    )
    regress = dataclasses.replace(
        predict,
        outputs=(TensorSpec("outputs", DT_FLOAT, (None,)),),
        method_name=REGRESS_METHOD,
    )
    return {DEFAULT_SIGNATURE: predict, "classify": classify, "regress": regress}


@dataclasses.dataclass(eq=False)  # identity hash: used as a weak cache key
class Servable:
    """A loaded (model, params) pair plus its signature map."""

    name: str
    version: int
    model: Model
    params: Params
    signatures: dict[str, Signature]

    def signature(self, name: str) -> Signature:
        key = name or DEFAULT_SIGNATURE
        if key not in self.signatures:
            raise SignatureNotFoundError(
                f"signature {key!r} not found in servable {self.name} v{self.version}; "
                f"have {sorted(self.signatures)}"
            )
        return self.signatures[key]

    def __call__(self, batch: Batch) -> dict[str, jnp.ndarray]:
        return self.model.apply(self.params, batch)

    def signature_def_map(self) -> dict:
        return {k: v.to_signature_def() for k, v in self.signatures.items()}


class ServableRegistry:
    """Thread-safe name -> {version -> Servable} store, with version labels.

    Mutation happens on the control plane (load/unload/set_label); the
    serving data plane only reads, so a plain lock around dict ops suffices.

    Version labels replicate tensorflow_model_server's label routing
    (model.proto field 4 upstream; assigned via ModelServerConfig
    version_labels there, via set_label / the server config here): a label
    like "stable"/"canary" names ONE loaded version per model, and requests
    may address it instead of a number — retargeting the label is the
    blue-green flip, no client change.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._servables: dict[str, dict[int, Servable]] = {}
        self._labels: dict[str, dict[str, int]] = {}

    def load(self, servable: Servable) -> None:
        with self._lock:
            self._servables.setdefault(servable.name, {})[servable.version] = servable

    def unload(self, name: str, version: int | None = None) -> None:
        with self._lock:
            if name not in self._servables:
                raise ModelNotFoundError(name)
            if version is None:
                del self._servables[name]
                self._labels.pop(name, None)
            else:
                versions = self._servables[name]
                if version not in versions:
                    raise VersionNotFoundError(f"{name} v{version}")
                del versions[version]
                labels = self._labels.get(name)
                if labels:
                    # A label must never dangle onto an unloaded version
                    # (upstream refuses to assign labels to unavailable
                    # versions for the same reason).
                    for label in [l for l, v in labels.items() if v == version]:
                        del labels[label]
                if not versions:
                    del self._servables[name]
                    self._labels.pop(name, None)

    def set_label(self, name: str, label: str, version: int) -> None:
        """Point `label` at a LOADED version (upstream rule: labels can only
        name available versions, so a typo'd rollout fails at config time,
        not at request time)."""
        if not label:
            raise ValueError("version label must be non-empty")
        with self._lock:
            self._check_labelable(name, label, version)
            self._labels.setdefault(name, {})[label] = version

    def _check_labelable(self, name: str, label: str, version: int) -> None:
        """Lock held by caller."""
        versions = self._servables.get(name)
        if not versions:
            raise ModelNotFoundError(f"model {name!r} not loaded")
        if version not in versions:
            raise VersionNotFoundError(
                f"cannot label {name!r} v{version} as {label!r}: version not "
                f"loaded; have {sorted(versions)}"
            )

    def replace_label_maps(self, maps: dict[str, dict[str, int]]) -> None:
        """REPLACE each named model's whole label map, atomically across all
        models (the reload-config semantics: the supplied map is the
        declarative state, so labels absent from it are unassigned).
        Validation and application happen under ONE lock acquisition — a
        concurrent unload can never leave a reload half-applied."""
        with self._lock:
            for name, mapping in maps.items():
                for label, version in mapping.items():
                    if not label:
                        raise ValueError("version label must be non-empty")
                    self._check_labelable(name, label, version)
            for name, mapping in maps.items():
                self._labels[name] = dict(mapping)

    def resolve(
        self,
        name: str,
        version: int | None = None,
        label: str | None = None,
    ) -> Servable:
        """ModelSpec resolution: absent version wrapper => latest
        (model.proto:12-14); version_label => the labeled version (upstream
        model.proto field 4). version XOR label is enforced by the caller
        (the proto oneof upstream)."""
        with self._lock:
            versions = self._servables.get(name)
            if not versions:
                raise ModelNotFoundError(f"model {name!r} not loaded")
            if label is not None:
                assigned = self._labels.get(name, {})
                if label not in assigned:
                    raise VersionNotFoundError(
                        f"model {name!r} has no version label {label!r}; "
                        f"have {sorted(assigned)}"
                    )
                version = assigned[label]
            if version is None:
                return versions[max(versions)]
            if version not in versions:
                raise VersionNotFoundError(
                    f"model {name!r} has no version {version}; have {sorted(versions)}"
                )
            return versions[version]

    def models(self) -> dict[str, list[int]]:
        with self._lock:
            return {k: sorted(v) for k, v in self._servables.items()}

    def labels(self, name: str) -> dict[str, int]:
        with self._lock:
            return dict(self._labels.get(name, {}))
