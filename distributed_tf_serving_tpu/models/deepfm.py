"""DeepFM CTR model (BASELINE.json config: "DeepFM CTR (Criteo-1TB features)").

First-order term: per-id scalar weights (shared with the Wide&Deep wide
half). Second-order FM term over the embedding bag uses the
O(n·F·D) identity  0.5 * ((sum_f e_f)^2 - sum_f e_f^2), which avoids the
O(F^2) pairwise products — on TPU this is two reductions over the [n,F,D]
bag, fused by XLA into the lookup. Deep half: MLP over the same bag.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .base import Model, ModelConfig, dense_apply, dense_init, mlp_apply, mlp_init, register_model
from .embeddings import embedding_init, field_embed, sparse_linear


def fm_second_order(emb: jax.Array) -> jax.Array:
    """emb [n, F, D] -> scalar FM interaction [n] (f32)."""
    e = emb.astype(jnp.float32)
    sum_sq = jnp.square(jnp.sum(e, axis=1))  # [n, D]
    sq_sum = jnp.sum(jnp.square(e), axis=1)  # [n, D]
    return 0.5 * jnp.sum(sum_sq - sq_sum, axis=-1)


@register_model("deepfm")
def build_deepfm(config: ModelConfig) -> Model:
    d = config.num_fields * config.embed_dim

    def init(rng):
        k_lin, k_emb, k_mlp, k_out = jax.random.split(rng, 4)
        return {
            "linear": jax.random.normal(k_lin, (config.vocab_size,), config.pdtype) * 0.01,
            "bias": jnp.zeros((), config.pdtype),
            "embedding": embedding_init(k_emb, config.vocab_size, config.embed_dim, config.pdtype),
            "mlp": mlp_init(k_mlp, d, config.mlp_dims, config.pdtype),
            "out": dense_init(k_out, config.mlp_dims[-1], 1, config.pdtype),
        }

    def apply(params, batch):
        cd = config.cdtype
        ids, wts = batch["feat_ids"], batch["feat_wts"]
        first = sparse_linear(params["linear"], ids, wts)
        emb = field_embed(params["embedding"], ids, wts, cd)
        second = fm_second_order(emb)
        deep = dense_apply(params["out"], mlp_apply(params["mlp"], emb.reshape(emb.shape[0], d), cd), cd)[:, 0]
        logit = first + second + deep + params["bias"].astype(jnp.float32)
        return {"prediction_node": jax.nn.sigmoid(logit), "logits": logit}

    # First-order term consumes raw f32 weights -> opt out of bf16
    # weight-transfer compression.
    return Model(config=config, init=init, apply=apply, wts_in_compute_dtype=False)
