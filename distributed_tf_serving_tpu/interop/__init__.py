"""Interop with TensorFlow SavedModel exports.

The reference's serving backend loads an externally-exported SavedModel
(SURVEY.md §0: the "DCN" model with signature `serving_default`,
DCNClient.java:33-34); users migrating from TF-Serving arrive with such a
directory. This package ingests it: signatures/metadata parse natively with
the vendored wire-compatible protos, variable values extract once via a
TensorFlow subprocess (the TensorBundle format needs TF; TF never enters
the serving process — its descriptor pool collides with ours), and the
result lands in the model zoo's native param trees / checkpoint format.
"""

__all__ = [
    "SavedModelImportError",
    "extract_variables",
    "import_savedmodel",
    "map_variables",
    "read_saved_model",
    "signatures_from_meta_graph",
]


def __getattr__(name):
    # Lazy re-exports (PEP 562): savedmodel pulls the vendored proto
    # bindings, and the EXPORT path (interop/export.py) must be importable
    # in a process that imports TensorFlow first — our tensorflow.*
    # descriptors collide with TF's in the process-wide pool, so this
    # package must not register them as an import side effect.
    if name in __all__:
        from . import savedmodel

        return getattr(savedmodel, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
