"""GraphDef executor: run arbitrary TF inference graphs natively in JAX.

`tensorflow_model_server` executes whatever graph the SavedModel carries
(reference surface: meta_graph.proto:31-87, graph.proto:14 — the repo vendors
the IDL; this module supplies the execution semantics). The zoo importer
(interop/savedmodel.py) binds weights onto a known architecture; this
executor removes that boundary for exports whose architecture is NOT in the
zoo: the exported GraphDef (main graph + FunctionDefLibrary) is interpreted
node by node into a pure-JAX callable, then jitted per padded bucket like
any zoo model — batching, versioning, and the wire protocol are unchanged.

Scope (documented, enforced):
- Inference dataflow ops (the table below: ~60 ops covering dense/embedding
  CTR-style exports: MatMul/BiasAdd/activations/Gather/Reshape/Concat/
  reductions/elementwise/StridedSlice/Select/Cast/Einsum/...).
- TF2 function calls (PartitionedCall/StatefulPartitionedCall and direct
  function-name ops) with captured variable handles, recursively.
- Variables via VarHandleOp/ReadVariableOp (TF2) or VariableV2/Identity
  (TF1, yielding the value directly — ref semantics), bound by shared_name /
  node name to extracted checkpoint values.
- Static hash tables (tf.lookup.StaticHashTable over integer keys with
  KeyValueTensorInitializer): contents are resolved STATICALLY from the
  export's initializer call chain and baked into the executable as sorted
  key/value constants; LookupTableFindV2 lowers to searchsorted + select —
  pure vectorized device code, no host callback (the common id-remap
  preprocessing in CTR exports).
- Constant-predicate conditionals (If/StatelessIf/Case over a predicate
  the graph determines at trace time — the config-gated preprocessing
  shape): the chosen branch is inlined, exactly XLA's own constant-fold
  behavior.
- NOT supported (explicit UnsupportedOpError naming the node):
  data-dependent control flow (live-predicate If, While/loops),
  TensorList/TensorArray, stateful mutation (AssignVariableOp in the
  serving path), sparse ops, string processing, mutable/file-backed/
  string-keyed tables. An export that needs them must be served by its
  original runtime.

Numerics: executed under jax.enable_x64 when the graph carries int64/f64
tensors (TF semantics are x64-native; silently downcasting hashed int64
feature ids would corrupt embedding lookups past 2^31). The Model is marked
needs_x64 so the batcher jits and calls it inside the context, and
folds_ids_on_host=False so raw ids reach the graph unmodified.
"""

from __future__ import annotations

import dataclasses
import logging

import jax
import jax.numpy as jnp
import numpy as np

from .. import codec
from ..models.base import Model, ModelConfig

log = logging.getLogger("dts_tpu.graph_exec")


class UnsupportedOpError(RuntimeError):
    """The graph uses an op outside the executor's documented scope."""


class GraphExecError(RuntimeError):
    pass


@dataclasses.dataclass(frozen=True)
class VarRef:
    """A resource handle flowing through the graph: resolves to params[key]
    at ReadVariableOp / ResourceGather sites."""

    key: str


@dataclasses.dataclass(frozen=True)
class TableRef:
    """A hash-table resource handle (HashTableV2): resolves to the statically
    extracted (sorted_keys, sorted_values) at LookupTableFindV2 sites."""

    key: str


def _attr(node, name, default=None):
    if name in node.attr:
        return node.attr[name]
    return default


def _np_dtype(dt_enum: int) -> np.dtype:
    return codec.dtype_to_numpy(dt_enum)


def _const_value(node) -> np.ndarray:
    tp = node.attr["value"].tensor
    try:
        return codec.to_ndarray(tp)
    except codec.CodecError as exc:
        # Consts may omit trailing repeated values (all-equal broadcast
        # trimming, legal on the TF side); codec validates counts strictly,
        # so repeat the last value out to the declared shape. ONLY for
        # dtypes whose repeated field we know — fabricating zeros for an
        # unhandled dtype would silently corrupt inference.
        dims = tuple(d.size for d in tp.tensor_shape.dim)
        np_dtype = _np_dtype(tp.dtype)
        field = {
            1: tp.float_val, 2: tp.double_val, 3: tp.int_val, 9: tp.int64_val,
            10: tp.bool_val,
        }.get(tp.dtype)
        vals = np.asarray(list(field) if field is not None else [], np_dtype)
        n = int(np.prod(dims)) if dims else 1
        if field is None or vals.size == 0 or vals.size > n:
            raise UnsupportedOpError(
                f"Const node {node.name!r}: cannot decode dtype "
                f"{tp.dtype} payload ({exc})"
            ) from exc
        if vals.size < n:
            vals = np.concatenate([vals, np.repeat(vals[-1], n - vals.size)])
        return vals.reshape(dims)


def _concrete(x, what: str) -> np.ndarray:
    """Require a trace-time-constant value (slice bounds, axes, shapes)."""
    try:
        return np.asarray(x)
    except Exception as exc:  # jax tracers refuse __array__
        raise UnsupportedOpError(
            f"{what} must be a graph constant (got a traced value); dynamic "
            "shapes/indices are outside the executor's scope"
        ) from exc


# --------------------------------------------------------------- op table
# Each entry: fn(node, inputs) -> tuple of outputs. `inputs` are jnp arrays,
# numpy constants, or VarRef. Single-output ops return a 1-tuple.


def _reduce(name):
    def run(node, inputs, xp):
        x, axes = inputs[0], _concrete(inputs[1], "reduction axes")
        keep = bool(_attr(node, "keep_dims").b) if _attr(node, "keep_dims") else False
        # TF: an EMPTY reduction_indices tensor is a no-op (numpy agrees
        # via axis=()); reduce-over-all is always an explicit Range.
        axes_t = tuple(int(a) for a in np.atleast_1d(axes))
        return (getattr(xp, name)(x, axis=axes_t, keepdims=keep),)

    return run


def _binop(name):
    return lambda node, inputs, xp: (getattr(xp, name)(inputs[0], inputs[1]),)


def _binfn(fn):
    """Binary op given as an explicit callable (jnp-only semantics)."""
    return lambda node, inputs, xp: (fn(inputs[0], inputs[1]),)


def _unop(name):
    return lambda node, inputs, xp: (getattr(xp, name)(inputs[0]),)


def _unfn(fn):
    """Unary op with jnp-only implementation (activations): fine staged —
    activation outputs never legally feed shape positions."""
    return lambda node, inputs, xp: (fn(inputs[0]),)


def _matmul(node, inputs, xp):
    a, b = inputs
    ta = bool(_attr(node, "transpose_a").b) if _attr(node, "transpose_a") else False
    tb = bool(_attr(node, "transpose_b").b) if _attr(node, "transpose_b") else False
    a = a.T if ta else a
    b = b.T if tb else b
    return (xp.matmul(a, b),)


def _batch_matmul(node, inputs, xp):
    a, b = inputs
    ta = bool(_attr(node, "adj_x").b) if _attr(node, "adj_x") else False
    tb = bool(_attr(node, "adj_y").b) if _attr(node, "adj_y") else False
    if ta:
        a = xp.swapaxes(a, -1, -2)
    if tb:
        b = xp.swapaxes(b, -1, -2)
    return (xp.matmul(a, b),)


def _bias_add(node, inputs, xp):
    x, b = inputs
    fmt = _attr(node, "data_format")
    if fmt is not None and fmt.s and fmt.s.decode() == "NCHW":
        shape = [1] * x.ndim
        shape[1] = b.shape[0]
        return (x + b.reshape(shape),)
    return (x + b,)


def _cast(node, inputs, xp):
    return (inputs[0].astype(_np_dtype(node.attr["DstT"].type)),)


def _reshape(node, inputs, xp):
    shape = [int(s) for s in _concrete(inputs[1], "Reshape shape")]
    return (xp.reshape(inputs[0], shape),)


def _concat_v2(node, inputs, xp):
    axis = int(_concrete(inputs[-1], "ConcatV2 axis"))
    return (xp.concatenate(inputs[:-1], axis=axis),)


def _pack(node, inputs, xp):
    axis = int(_attr(node, "axis").i) if _attr(node, "axis") else 0
    return (xp.stack(inputs, axis=axis),)


def _unpack(node, inputs, xp):
    axis = int(_attr(node, "axis").i) if _attr(node, "axis") else 0
    num = int(node.attr["num"].i)
    parts = xp.split(inputs[0], num, axis=axis)
    return tuple(xp.squeeze(p, axis=axis) for p in parts)


def _expand_dims(node, inputs, xp):
    return (xp.expand_dims(inputs[0], int(_concrete(inputs[1], "ExpandDims axis"))),)


def _squeeze(node, inputs, xp):
    dims = _attr(node, "squeeze_dims")
    axes = tuple(int(i) for i in dims.list.i) if dims and dims.list.i else None
    return (xp.squeeze(inputs[0], axis=axes),)


def _transpose(node, inputs, xp):
    perm = [int(p) for p in _concrete(inputs[1], "Transpose perm")]
    return (xp.transpose(inputs[0], perm),)


def _gather_v2(node, inputs, xp):
    params, indices = inputs[0], inputs[1]
    axis = int(_concrete(inputs[2], "GatherV2 axis")) if len(inputs) > 2 else 0
    bd = _attr(node, "batch_dims")
    batch_dims = int(bd.i) if bd else 0
    if not batch_dims:
        return (xp.take(params, indices, axis=axis),)
    if batch_dims != axis:
        raise UnsupportedOpError(
            f"node {node.name!r}: GatherV2 with batch_dims={batch_dims} != "
            f"axis={axis} not supported"
        )
    if indices.ndim == params.ndim:
        return (xp.take_along_axis(params, indices, axis=axis),)
    if indices.ndim == axis + 1 and params.ndim == axis + 2:
        # The common batched embedding select: params [..B, N, D],
        # indices [..B, K] -> [..B, K, D]; take_along_axis broadcasts the
        # trailing unit dim over D.
        out = xp.take_along_axis(params, indices[..., None], axis=axis)
        return (out,)
    raise UnsupportedOpError(
        f"node {node.name!r}: GatherV2 batch_dims={batch_dims} with "
        f"params rank {params.ndim} / indices rank {indices.ndim} not supported"
    )


def _resource_gather(node, inputs, params):
    ref, indices = inputs[0], inputs[1]
    if not isinstance(ref, VarRef):
        raise GraphExecError("ResourceGather expects a variable handle input")
    bd = _attr(node, "batch_dims")
    if bd and bd.i:
        raise UnsupportedOpError("ResourceGather with batch_dims not supported")
    return (jnp.take(params[ref.key], indices, axis=0),)


def _strided_slice(node, inputs, xp):
    x = inputs[0]
    begin = [int(v) for v in _concrete(inputs[1], "StridedSlice begin")]
    end = [int(v) for v in _concrete(inputs[2], "StridedSlice end")]
    strides = [int(v) for v in _concrete(inputs[3], "StridedSlice strides")]
    bm = int(_attr(node, "begin_mask").i) if _attr(node, "begin_mask") else 0
    em = int(_attr(node, "end_mask").i) if _attr(node, "end_mask") else 0
    ellipsis = int(_attr(node, "ellipsis_mask").i) if _attr(node, "ellipsis_mask") else 0
    new_axis = int(_attr(node, "new_axis_mask").i) if _attr(node, "new_axis_mask") else 0
    shrink = int(_attr(node, "shrink_axis_mask").i) if _attr(node, "shrink_axis_mask") else 0

    ndim = x.ndim
    nspec = len(begin)
    # Dims of x consumed by the spec = every entry that is neither a
    # new-axis insertion nor the ellipsis itself; the ellipsis expands to
    # however many full slices are left over (possibly zero).
    consumed = sum(
        1 for d in range(nspec)
        if not (new_axis & (1 << d)) and not (ellipsis & (1 << d))
    )
    idx = []
    for spec_dim in range(nspec):
        bit = 1 << spec_dim
        if ellipsis & bit:
            idx.extend([slice(None)] * (ndim - consumed))
            continue
        if new_axis & bit:
            idx.append(None)
            continue
        if shrink & bit:
            idx.append(begin[spec_dim])
            continue
        b = None if bm & bit else begin[spec_dim]
        e = None if em & bit else end[spec_dim]
        s = strides[spec_dim]
        idx.append(slice(b, e, s))
    return (x[tuple(idx)],)


def _slice(node, inputs, xp):
    x = inputs[0]
    begin = [int(v) for v in _concrete(inputs[1], "Slice begin")]
    size = [int(v) for v in _concrete(inputs[2], "Slice size")]
    idx = tuple(
        slice(b, None if s == -1 else b + s) for b, s in zip(begin, size)
    )
    return (x[idx],)


def _shape(node, inputs, xp):
    out_type = _attr(node, "out_type")
    dt = _np_dtype(out_type.type) if out_type else np.int32
    return (np.asarray(inputs[0].shape, dt),)


def _fill(node, inputs, xp):
    dims = [int(d) for d in _concrete(inputs[0], "Fill dims")]
    return (xp.full(dims, inputs[1]),)


def _range(node, inputs, xp):
    s, l, d = (_concrete(v, "Range input") for v in inputs)
    return (np.arange(int(s), int(l), int(d), dtype=np.asarray(s).dtype),)


def _softmax(node, inputs, xp):
    return (jax.nn.softmax(inputs[0], axis=-1),)


def _select(node, inputs, xp):
    return (xp.where(inputs[0], inputs[1], inputs[2]),)


def _clip(node, inputs, xp):
    return (xp.clip(inputs[0], inputs[1], inputs[2]),)


def _leaky_relu(node, inputs, xp):
    alpha = _attr(node, "alpha")
    return (jax.nn.leaky_relu(inputs[0], alpha.f if alpha else 0.2),)


def _einsum(node, inputs, xp):
    eq = node.attr["equation"].s.decode()
    return (xp.einsum(eq, *inputs),)


def _argmax(node, inputs, xp):
    axis = int(_concrete(inputs[1], "ArgMax axis")) if len(inputs) > 1 else 0
    ot = _attr(node, "output_type")
    dt = _np_dtype(ot.type) if ot else np.int64
    return (xp.argmax(inputs[0], axis=axis).astype(dt),)


def _argmin(node, inputs, xp):
    axis = int(_concrete(inputs[1], "ArgMin axis")) if len(inputs) > 1 else 0
    ot = _attr(node, "output_type")
    dt = _np_dtype(ot.type) if ot else np.int64
    return (xp.argmin(inputs[0], axis=axis).astype(dt),)


def _tile(node, inputs, xp):
    reps = [int(r) for r in _concrete(inputs[1], "Tile multiples")]
    return (xp.tile(inputs[0], reps),)


def _top_k(node, inputs, xp):
    k = int(_concrete(inputs[1], "TopKV2 k")) if len(inputs) > 1 else int(node.attr["k"].i)
    vals, idxs = jax.lax.top_k(inputs[0], k)
    return (vals, idxs.astype(np.int32))


def _one_hot(node, inputs, xp):
    depth = int(_concrete(inputs[1], "OneHot depth"))
    on, off = inputs[2], inputs[3]
    ax = _attr(node, "axis")
    axis = int(ax.i) if ax else -1
    hot = jax.nn.one_hot(inputs[0], depth, axis=axis, dtype=jnp.result_type(on))
    return (hot * on + (1 - hot) * off,)


_OPS = {
    "MatMul": _matmul,
    "BatchMatMul": _batch_matmul,
    "BatchMatMulV2": _batch_matmul,
    "BatchMatMulV3": _batch_matmul,
    "BiasAdd": _bias_add,
    "Add": _binop("add"),
    "AddV2": _binop("add"),
    "AddN": lambda node, inputs, xp: (sum(inputs[1:], inputs[0]),),
    "Sub": _binop("subtract"),
    "Mul": _binop("multiply"),
    "RealDiv": _binop("divide"),
    "Div": _binop("divide"),
    "DivNoNan": _binfn(lambda a, b: jnp.where(b == 0, 0.0, a / jnp.where(b == 0, 1.0, b))),
    "FloorDiv": _binop("floor_divide"),
    "FloorMod": _binop("mod"),
    # TF's Mod/TruncateMod are C-style truncated remainder (result takes the
    # DIVIDEND's sign); np/jnp.mod is floor-mod — silently wrong for negative
    # operands (round-3 advisor finding). fmod is the truncating one.
    "Mod": _binop("fmod"),
    "TruncateMod": _binop("fmod"),
    "Maximum": _binop("maximum"),
    "Minimum": _binop("minimum"),
    "Pow": _binop("power"),
    "SquaredDifference": _binfn(lambda a, b: jnp.square(a - b)),
    "Relu": _unfn(jax.nn.relu),
    "Relu6": _unfn(jax.nn.relu6),
    "LeakyRelu": _leaky_relu,
    "Elu": _unfn(jax.nn.elu),
    "Selu": _unfn(jax.nn.selu),
    "Gelu": _unfn(jax.nn.gelu),
    "Sigmoid": _unfn(jax.nn.sigmoid),
    "Tanh": _unop("tanh"),
    "Softplus": _unfn(jax.nn.softplus),
    "Softsign": _unfn(jax.nn.soft_sign),
    "Exp": _unop("exp"),
    "Log": _unop("log"),
    "Log1p": _unop("log1p"),
    "Sqrt": _unop("sqrt"),
    "Rsqrt": _unfn(lambda x: 1.0 / jnp.sqrt(x)),
    "Square": _unop("square"),
    "Abs": _unop("abs"),
    "Neg": _unop("negative"),
    "Sign": _unop("sign"),
    "Erf": _unfn(jax.scipy.special.erf),
    "Floor": _unop("floor"),
    "Ceil": _unop("ceil"),
    "Round": _unop("round"),
    "Softmax": _softmax,
    "LogSoftmax": lambda node, inputs, xp: (jax.nn.log_softmax(inputs[0], axis=-1),),
    "Cast": _cast,
    "Identity": lambda node, inputs, xp: (inputs[0],),
    "StopGradient": lambda node, inputs, xp: (inputs[0],),
    "PreventGradient": lambda node, inputs, xp: (inputs[0],),
    "CheckNumerics": lambda node, inputs, xp: (inputs[0],),
    "Snapshot": lambda node, inputs, xp: (inputs[0],),
    "EnsureShape": lambda node, inputs, xp: (inputs[0],),
    "IdentityN": lambda node, inputs, xp: tuple(inputs),
    "Reshape": _reshape,
    "ExpandDims": _expand_dims,
    "Squeeze": _squeeze,
    "Transpose": _transpose,
    "ConcatV2": _concat_v2,
    "Pack": _pack,
    "Unpack": _unpack,
    "StridedSlice": _strided_slice,
    "Slice": _slice,
    "Tile": _tile,
    "Fill": _fill,
    "ZerosLike": _unop("zeros_like"),
    "OnesLike": _unop("ones_like"),
    "Shape": _shape,
    "Rank": lambda node, inputs, xp: (np.asarray(inputs[0].ndim, np.int32),),
    "Size": lambda node, inputs, xp: (np.asarray(inputs[0].size, np.int32),),
    "Range": _range,
    "GatherV2": _gather_v2,
    "Gather": lambda node, inputs, xp: (xp.take(inputs[0], inputs[1], axis=0),),
    "Sum": _reduce("sum"),
    "Mean": _reduce("mean"),
    "Max": _reduce("max"),
    "Min": _reduce("min"),
    "Prod": _reduce("prod"),
    "Any": _reduce("any"),
    "All": _reduce("all"),
    "ArgMax": _argmax,
    "ArgMin": _argmin,
    "Equal": _binop("equal"),
    "NotEqual": _binop("not_equal"),
    "Greater": _binop("greater"),
    "GreaterEqual": _binop("greater_equal"),
    "Less": _binop("less"),
    "LessEqual": _binop("less_equal"),
    "LogicalAnd": _binop("logical_and"),
    "LogicalOr": _binop("logical_or"),
    "LogicalNot": _unop("logical_not"),
    "Select": _select,
    "SelectV2": _select,
    "Where": lambda node, inputs, xp: (_fail_where(),),
    "ClipByValue": _clip,
    "Einsum": _einsum,
    "TopKV2": _top_k,
    "OneHot": _one_hot,
    "L2Loss": _unfn(lambda x: 0.5 * jnp.sum(jnp.square(x))),
    "Rint": _unop("rint"),
    "Cumsum": lambda node, inputs, xp: (
        xp.cumsum(inputs[0], axis=int(_concrete(inputs[1], "Cumsum axis"))),
    ),
}

_CALL_OPS = ("PartitionedCall", "StatefulPartitionedCall")


def _fail_where():
    raise UnsupportedOpError(
        "Where (dynamic-shape output) is outside the executor's scope"
    )


class _FunctionLibrary:
    def __init__(self, graph_def):
        self.functions = {f.signature.name: f for f in graph_def.library.function}
        # table node name -> (sorted_keys, sorted_values) numpy arrays;
        # populated by _resolve_table_contents (GraphExecutor/graph_model).
        self.tables: dict[str, tuple[np.ndarray, np.ndarray]] = {}
        # Import-time variable values (numpy), for resolving conditional
        # predicates concretely: under the serving jit the live params are
        # TRACERS, but a config-gated If's predicate is decided by frozen
        # export-time values — which is also what the value will be on
        # every request (inference params never change within a servable
        # version). Populated by graph_model.
        self.const_params: dict[str, np.ndarray] = {}


def _concrete_ref(env, lib, ref: str, what: str):
    """Evaluate `ref` to a CONCRETE value even mid-trace, re-walking the
    producing chain with numpy and import-time variable values. Touching
    live data (a Placeholder, or a traced function arg with no concrete
    origin) raises UnsupportedOpError — that predicate genuinely is
    data-dependent."""
    val = env.tensor(ref)
    if not isinstance(val, jax.core.Tracer):
        return val
    parts = ref.split(":")
    head = parts[0]
    idx = int(parts[-1]) if len(parts) > 1 and parts[-1].isdigit() else 0
    node = env.nodes.get(head)
    if node is None:
        raise UnsupportedOpError(
            f"{what}: ref {ref!r} has no concrete origin in this scope"
        )
    if node.op in ("Placeholder", "PlaceholderWithDefault"):
        raise UnsupportedOpError(f"{what}: depends on live input {head!r}")
    if node.op == "ReadVariableOp":
        handle = _concrete_ref(env, lib, node.input[0], what)
        if isinstance(handle, VarRef) and handle.key in lib.const_params:
            return lib.const_params[handle.key]
        raise UnsupportedOpError(
            f"{what}: variable read has no import-time value"
        )
    fn = _OPS.get(node.op)
    if fn is None:
        raise UnsupportedOpError(
            f"{what}: cannot concretely evaluate op {node.op!r} ({head!r})"
        )
    inputs = [
        _concrete_ref(env, lib, i, what)
        for i in node.input
        if not i.startswith("^")
    ]
    try:
        return fn(node, inputs, np)[idx]
    except (UnsupportedOpError, GraphExecError):
        raise
    except Exception as exc:  # noqa: BLE001
        raise UnsupportedOpError(
            f"{what}: concrete re-evaluation of {head!r} failed: {exc}"
        ) from exc


_TABLE_INIT_OPS = ("LookupTableImportV2", "LookupTableImport",
                   "InitializeTableV2", "InitializeTable")


def _resolve_table_contents(graph_def, lib: _FunctionLibrary) -> dict:
    """Statically extract every StaticHashTable's contents from the export.

    A `tf.lookup.StaticHashTable` serializes as a HashTableV2 node plus an
    initializer call chain ending in LookupTableImportV2(table, keys, values)
    where keys/values are main-graph Consts (verified against tf 2.21
    exports: main graph holds `HashTableV2` + `StatefulPartitionedCall[
    table, Const, Const_1] -> __inference__initializer_N`). The serving
    signature never runs the init op, so contents must be resolved
    statically — which is exactly right for the TPU: the table becomes a
    sorted key/value array pair baked into the executable's constants, and
    lookups lower to searchsorted (MXU-adjacent, no host callback).

    Only compile-time-resolvable initializers are indexed; anything else
    (MutableHashTable, file-backed TextFileInitializer) simply stays out of
    the map and LookupTableFindV2 raises its ranked UnsupportedOpError.
    """
    tables: dict[str, tuple[np.ndarray, np.ndarray]] = {}
    main_nodes = {n.name: n for n in graph_def.node}

    def resolve_main(ref: str):
        """('table', name) | ('const', array) | None for a main-graph ref."""
        node = main_nodes.get(ref.partition(":")[0])
        if node is None:
            return None
        if node.op in ("HashTableV2", "HashTable"):
            return ("table", node.name)
        if node.op == "Const":
            try:
                return ("const", _const_value(node))
            except Exception:  # noqa: BLE001 — undecodable (e.g. string) const
                return None
        if node.op == "Identity":
            return resolve_main(node.input[0])
        return None

    def record(table, keys, values):
        if table is None or keys is None or values is None:
            return
        if table[0] != "table" or keys[0] != "const" or values[0] != "const":
            return
        k, v = np.asarray(keys[1]).ravel(), np.asarray(values[1]).ravel()
        if k.dtype.kind not in "iu" or v.dtype.kind not in "iufb" or k.size != v.size:
            # String/float keys or string/object VALUES: out of scope —
            # staying unresolved turns the serve-time find into the ranked
            # UnsupportedOpError instead of a raw JAX dtype crash.
            return
        order = np.argsort(k, kind="stable")
        tables[table[1]] = (k[order], v[order])

    def scan(nodes, resolve, depth):
        if depth > 4:
            return
        for node in nodes:
            if node.op in _TABLE_INIT_OPS and len(node.input) >= 3:
                record(resolve(node.input[0]), resolve(node.input[1]),
                       resolve(node.input[2]))
            elif node.op in _CALL_OPS or node.op in lib.functions:
                fname = (
                    node.attr["f"].func.name
                    if node.op in _CALL_OPS
                    else node.op
                )
                fdef = lib.functions.get(fname)
                if fdef is None:
                    continue
                data_inputs = [i for i in node.input if not i.startswith("^")]
                bindings = {
                    a.name: resolve(ref)
                    for a, ref in zip(fdef.signature.input_arg, data_inputs)
                }

                def resolve_fn(ref, _b=bindings, _f=fdef):
                    head = ref.partition(":")[0]
                    if head in _b:
                        return _b[head]
                    fnode = next(
                        (n for n in _f.node_def if n.name == head), None
                    )
                    if fnode is None:
                        return None
                    if fnode.op == "Const":
                        try:
                            return ("const", _const_value(fnode))
                        except Exception:  # noqa: BLE001
                            return None
                    if fnode.op == "Identity":
                        return resolve_fn(fnode.input[0], _b, _f)
                    return None

                scan(fdef.node_def, resolve_fn, depth + 1)

    scan(graph_def.node, resolve_main, 0)
    return tables


def _table_entry(lib, ref, node):
    if not isinstance(ref, TableRef):
        raise GraphExecError(f"{node.name}: lookup on a non-table input")
    entry = lib.tables.get(ref.key)
    if entry is None:
        raise UnsupportedOpError(
            f"{node.name}: hash table {ref.key!r} has no statically "
            "resolvable contents — mutable tables, file-backed initializers "
            "and string-keyed tables are outside the executor's scope "
            "(supported: StaticHashTable over integer keys with "
            "KeyValueTensorInitializer consts)"
        )
    return entry


def _lookup_find(node, inputs, lib, xp):
    """LookupTableFindV2 as a static sorted-array probe: searchsorted +
    equality select, which XLA lowers to pure vectorized device code (no
    host callback, table baked as executable constants)."""
    sk, sv = _table_entry(lib, inputs[0], node)
    keys, default = inputs[1], inputs[2]
    if sk.size == 0:
        return (xp.full(np.shape(keys), np.asarray(default, sv.dtype) if not
                        isinstance(default, jax.core.Tracer) else default,
                        dtype=sv.dtype),)
    idx = xp.searchsorted(sk, keys)
    idx = xp.clip(idx, 0, sk.size - 1)
    found = xp.asarray(sk)[idx] == keys
    return (xp.where(found, xp.asarray(sv)[idx], xp.asarray(default, sv.dtype)),)


class _GraphEval:
    """Evaluates the main GraphDef. Tensor refs: 'node', 'node:k', '^ctrl'."""

    def __init__(self, nodes, lib, params, feeds):
        self.nodes = nodes
        self.lib = lib
        self.params = params
        self.feeds = feeds  # placeholder node name -> value
        self.memo: dict[str, tuple] = {}

    def tensor(self, ref: str):
        if ref.startswith("^"):
            return None
        name, _, idx = ref.partition(":")
        return self.node_outputs(name)[int(idx) if idx else 0]

    def node_outputs(self, name: str) -> tuple:
        if name in self.memo:
            return self.memo[name]
        node = self.nodes.get(name)
        if node is None:
            raise GraphExecError(f"graph references unknown node {name!r}")
        out = _eval_node(node, self, self.lib, self.params)
        self.memo[name] = out
        return out


class _FuncEval:
    """Evaluates a FunctionDef body. Tensor refs: 'arg' (function input) or
    'node:out_arg_name:k' (flat index k — valid for single-tensor output
    args, which covers every op in the table)."""

    def __init__(self, fdef, args, lib, params):
        self.fdef = fdef
        self.lib = lib
        self.params = params
        self.nodes = {n.name: n for n in fdef.node_def}
        self.args = {
            a.name: v for a, v in zip(fdef.signature.input_arg, args)
        }
        self.memo: dict[str, tuple] = {}

    def tensor(self, ref: str):
        if ref.startswith("^"):
            return None
        parts = ref.split(":")
        if len(parts) == 1:
            if parts[0] in self.args:
                return self.args[parts[0]]
            # A nullary node referenced bare (Const inside a function).
            return self.node_outputs(parts[0])[0]
        if len(parts) == 2:
            # 'arg:0' style for function inputs.
            if parts[0] in self.args:
                return self.args[parts[0]]
            return self.node_outputs(parts[0])[int(parts[1])]
        name, _out_arg, idx = parts[0], parts[1], parts[2]
        return self.node_outputs(name)[int(idx)]

    def node_outputs(self, name: str) -> tuple:
        if name in self.memo:
            return self.memo[name]
        node = self.nodes.get(name)
        if node is None:
            raise GraphExecError(
                f"function {self.fdef.signature.name!r} references unknown node {name!r}"
            )
        out = _eval_node(node, self, self.lib, self.params)
        self.memo[name] = out
        return out

    def results(self) -> tuple:
        return tuple(
            self.tensor(self.fdef.ret[o.name]) for o in self.fdef.signature.output_arg
        )


def _eval_node(node, env, lib, params) -> tuple:
    op = node.op
    if op == "Placeholder" or op == "PlaceholderWithDefault":
        feeds = getattr(env, "feeds", None)
        if feeds is not None and node.name in feeds:
            return (feeds[node.name],)
        if op == "PlaceholderWithDefault":
            return (env.tensor(node.input[0]),)
        raise GraphExecError(f"placeholder {node.name!r} was not fed")
    if op == "Const":
        return (_const_value(node),)
    if op == "NoOp":
        return ()
    if op in ("VarHandleOp", "VariableV2", "VarIsInitializedOp"):
        if op == "VarIsInitializedOp":
            return (np.asarray(True),)
        shared = _attr(node, "shared_name")
        key = shared.s.decode() if shared is not None and shared.s else node.name
        if key not in params and node.name in params:
            key = node.name
        if op == "VariableV2":
            # TF1 ref-variables YIELD the tensor value wherever referenced
            # (MatMul/Gather consume the ref directly; there is no
            # ReadVariableOp in a TF1 graph) — only TF2 resource handles
            # (VarHandleOp) flow as opaque VarRefs to their read sites.
            # Round-3 advisor finding: returning VarRef here broke every
            # documented TF1 export with an opaque 0-d shape error.
            if key not in params:
                raise GraphExecError(
                    f"{node.name}: TF1 variable {key!r} not found in extracted "
                    f"checkpoint values (have {sorted(params)[:8]}...)"
                )
            return (params[key],)
        return (VarRef(key),)
    if op == "ReadVariableOp":
        ref = env.tensor(node.input[0])
        if not isinstance(ref, VarRef):
            raise GraphExecError(f"{node.name}: ReadVariableOp on a non-handle input")
        if ref.key not in params:
            raise GraphExecError(
                f"{node.name}: variable {ref.key!r} not found in extracted "
                f"checkpoint values (have {sorted(params)[:8]}...)"
            )
        return (params[ref.key],)
    if op == "ResourceGather":
        inputs = [env.tensor(i) for i in node.input if not i.startswith("^")]
        return _resource_gather(node, inputs, params)
    if op in ("HashTableV2", "HashTable"):
        return (TableRef(node.name),)
    if op in ("LookupTableFindV2", "LookupTableFind"):
        inputs = [env.tensor(i) for i in node.input if not i.startswith("^")]
        static = not any(isinstance(v, jax.core.Tracer) for v in inputs)
        return _lookup_find(node, inputs, lib, np if static else jnp)
    if op in ("LookupTableSizeV2", "LookupTableSize"):
        ref = env.tensor(node.input[0])
        sk, _sv = _table_entry(lib, ref, node)
        return (np.asarray(sk.size, np.int64),)
    if op in _TABLE_INIT_OPS:
        # Contents were resolved statically (_resolve_table_contents); the
        # init op itself is a no-op if an init path is ever walked.
        return ()
    if op in ("AssignVariableOp", "AssignAddVariableOp"):
        raise UnsupportedOpError(
            f"{node.name}: stateful variable mutation ({op}) in a serving "
            "graph is outside the executor's scope"
        )
    if op in ("If", "StatelessIf"):
        # Constant-predicate conditionals: the chosen branch is inlined at
        # trace time (exactly what XLA would do after constant folding).
        # Serving graphs gate preprocessing on captured config constants/
        # variables; under the serving jit those reads are TRACERS, so the
        # predicate is re-evaluated concretely against import-time values
        # (_concrete_ref). A predicate that genuinely depends on live
        # input stays out of scope (would need lax.cond with matched
        # branch signatures) and raises the documented error.
        try:
            cond = _concrete_ref(
                env, lib, node.input[0], f"node {node.name!r} ({op}) predicate"
            )
        except UnsupportedOpError as exc:
            raise UnsupportedOpError(
                f"node {node.name!r}: {op} with a data-dependent predicate "
                f"is outside the executor's scope ({exc})"
            ) from exc
        branch = "then_branch" if bool(np.asarray(cond)) else "else_branch"
        fname = node.attr[branch].func.name
        args = [env.tensor(i) for i in node.input[1:] if not i.startswith("^")]
        return _invoke_function(fname, node, args, lib, params, role=branch)
    if op in ("Case", "StatelessCase"):
        try:
            idx = _concrete_ref(
                env, lib, node.input[0], f"node {node.name!r} ({op}) index"
            )
        except UnsupportedOpError as exc:
            raise UnsupportedOpError(
                f"node {node.name!r}: {op} with a data-dependent branch "
                f"index is outside the executor's scope ({exc})"
            ) from exc
        branches = node.attr["branches"].list.func
        if not branches:
            raise GraphExecError(f"{node.name}: Case with no branches")
        i = int(np.asarray(idx))
        # TF semantics: ANY out-of-range index (negative included) runs
        # the LAST branch.
        if i < 0 or i >= len(branches):
            i = len(branches) - 1
        args = [env.tensor(r) for r in node.input[1:] if not r.startswith("^")]
        return _invoke_function(
            branches[i].name, node, args, lib, params, role=f"branch {i}"
        )
    if op in _CALL_OPS:
        fname = node.attr["f"].func.name
        return _call_function(fname, node, env, lib, params)
    if op in lib.functions:
        return _call_function(op, node, env, lib, params)
    fn = _OPS.get(op)
    if fn is None:
        raise UnsupportedOpError(
            f"node {node.name!r}: op {op!r} is outside the executor's scope "
            "(see graph_exec.py module docstring for the supported set)"
        )
    inputs = [env.tensor(i) for i in node.input if not i.startswith("^")]
    # Constant folding: inside a jit trace, jnp ops stage EVERYTHING (even
    # all-constant inputs become tracers), which would destroy the
    # concreteness that shape-arithmetic subgraphs (tf.shape -> Pack ->
    # Reshape) require. When no input is traced, evaluate the node with
    # numpy so its output stays a compile-time constant — exactly TF's own
    # constant-folding behavior.
    static = not any(isinstance(v, jax.core.Tracer) for v in inputs)
    try:
        return fn(node, inputs, np if static else jnp)
    except (UnsupportedOpError, GraphExecError):
        raise
    except Exception as exc:  # name the node: anonymous shape errors are undebuggable
        raise GraphExecError(
            f"node {node.name!r} (op {op}): {type(exc).__name__}: {exc}"
        ) from exc


def _invoke_function(fname, node, args, lib, params, role="function") -> tuple:
    """Arity-checked FunctionDef invocation — ONE implementation shared by
    direct calls, If branches, and Case branches, so a mismatched call
    always reports 'takes N args, got M' rather than a downstream
    unknown-node error."""
    fdef = lib.functions.get(fname)
    if fdef is None:
        raise GraphExecError(f"{node.name}: unknown {role} {fname!r}")
    want = len(fdef.signature.input_arg)
    if len(args) != want:
        raise GraphExecError(
            f"{node.name}: {role} {fname!r} takes {want} args, got {len(args)}"
        )
    return _FuncEval(fdef, args, lib, params).results()


def _call_function(fname, node, env, lib, params) -> tuple:
    args = [env.tensor(i) for i in node.input if not i.startswith("^")]
    return _invoke_function(fname, node, args, lib, params)


# ------------------------------------------------------------- public API


class GraphExecutor:
    """Callable built from a MetaGraphDef signature: feeds placeholders,
    walks the graph, returns the signature's outputs keyed by alias."""

    def __init__(self, meta_graph, signature_name: str = "serving_default"):
        if signature_name not in meta_graph.signature_def:
            raise GraphExecError(
                f"signature {signature_name!r} not in export; have "
                f"{sorted(meta_graph.signature_def)}"
            )
        sig = meta_graph.signature_def[signature_name]
        self.graph_def = meta_graph.graph_def
        self.nodes = {n.name: n for n in self.graph_def.node}
        self.lib = _FunctionLibrary(self.graph_def)
        self.lib.tables = _resolve_table_contents(self.graph_def, self.lib)
        if self.lib.tables:
            log.info(
                "resolved %d static hash table(s): %s",
                len(self.lib.tables),
                {k: v[0].size for k, v in self.lib.tables.items()},
            )
        # alias -> (node_name, output_index)
        def split(tname):
            name, _, idx = tname.partition(":")
            return name, int(idx) if idx else 0

        self.input_nodes = {a: split(i.name)[0] for a, i in sig.inputs.items()}
        self.outputs = {a: split(i.name) for a, i in sig.outputs.items()}
        self.input_dtypes = {a: i.dtype for a, i in sig.inputs.items()}

    def needs_x64(self, variables) -> bool:
        wide = (9, 2)  # DT_INT64, DT_DOUBLE
        if any(dt in wide for dt in self.input_dtypes.values()):
            return True
        if any(v.dtype in (np.int64, np.float64) for v in variables.values()):
            return True
        # Baked hash-table constants count too: a graph whose ONLY int64
        # tensors are table keys (int32 input Cast to int64 before the
        # lookup) would otherwise jit non-x64 and _lookup_find's
        # jnp.asarray(keys) would wrap >2^31 catalog ids to int32 —
        # breaking the sorted invariant searchsorted depends on, silently.
        return any(
            k.dtype in (np.int64, np.float64) or v.dtype in (np.int64, np.float64)
            for k, v in self.lib.tables.values()
        )

    def __call__(self, params: dict[str, np.ndarray], batch: dict) -> dict:
        feeds = {}
        for alias, node_name in self.input_nodes.items():
            if alias in batch:
                feeds[node_name] = batch[alias]
        ev = _GraphEval(self.nodes, self.lib, params, feeds)
        return {
            alias: ev.node_outputs(name)[idx]
            for alias, (name, idx) in self.outputs.items()
        }


def graph_model(
    meta_graph,
    variables: dict[str, np.ndarray],
    signature_name: str = "serving_default",
    name: str = "imported",
) -> tuple[Model, dict[str, np.ndarray]]:
    """Build a servable Model executing the export's own graph.

    Returns (model, params). params is the variables dict itself — the
    model's pytree is flat {variable_key: array}."""
    ex = GraphExecutor(meta_graph, signature_name)
    # Import-time values back the concrete predicate resolution for
    # config-gated conditionals (see _FunctionLibrary.const_params).
    ex.lib.const_params = {k: np.asarray(v) for k, v in variables.items()}
    sig = meta_graph.signature_def[signature_name]

    # num_fields from the first 2-D int input when present (diagnostics and
    # the Example decode path); fall back to the default.
    num_fields = 0
    for info in sig.inputs.values():
        dims = [d.size for d in info.tensor_shape.dim]
        if len(dims) == 2 and dims[1] > 0:
            num_fields = int(dims[1])
            break
    config = ModelConfig(name=name, num_fields=num_fields or 43)

    def init(rng):
        raise GraphExecError(
            "graph-executor models carry imported variables; init() is not "
            "available (no architecture to initialize)"
        )

    model = Model(
        config=config,
        init=init,
        apply=ex,
        wts_in_compute_dtype=False,
        folds_ids_on_host=False,
        needs_x64=ex.needs_x64(variables),
    )
    return model, dict(variables)
